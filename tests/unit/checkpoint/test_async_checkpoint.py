"""Async (Nebula-class) checkpoint engine (VERDICT r5 ask #8).

Reference: ``deepspeed/runtime/checkpoint_engine/nebula_checkpoint_engine.py``
— saves commit in the background while training continues; the durable
marker appears only after the commit completes, and the next save/load
takes a barrier on the in-flight commit.
"""

import os
import sys
import threading

import numpy as np
import pytest

import jax

import deepspeed_tpu
from deepspeed_tpu.runtime.checkpoint_engine import engine as ckpt_engine_mod
from deepspeed_tpu.utils import groups

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
from simple_model import make_simple_model, random_batches  # noqa: E402


def _engine(nebula=True):
    groups.initialize_mesh(force=True)
    model, params = make_simple_model(hidden_dim=16, batch_size=8)
    cfg = {"train_micro_batch_size_per_gpu": 8, "gradient_accumulation_steps": 1,
           "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
           "zero_optimization": {"stage": 2}}
    if nebula:
        cfg["nebula"] = {"enabled": True}
    eng, _, _, _ = deepspeed_tpu.initialize(model=model, model_parameters=params,
                                            config=cfg)
    return eng


def test_async_save_overlaps_training_and_is_loadable(tmp_path, monkeypatch):
    """Train steps proceed WHILE the commit is provably in flight (the
    finalizer is gated on an event the test controls); the durable marker
    appears only after the commit; the loaded state equals the state at
    save time, not the post-save steps."""
    gate = threading.Event()
    real_finish = ckpt_engine_mod.OrbaxCheckpointEngine.finish

    def gated_finish(self):
        gate.wait(timeout=60)
        real_finish(self)

    monkeypatch.setattr(ckpt_engine_mod.OrbaxCheckpointEngine, "finish", gated_finish)

    eng = _engine()
    batches = random_batches(4, 8, 16)
    for b in batches[:2]:
        float(eng.train_batch(batch=b))
    want = jax.device_get(eng.params)
    steps_at_save = eng.global_steps

    assert eng.save_checkpoint(str(tmp_path), tag="async")
    st = eng._async_ckpt
    assert st["thread"].is_alive()

    # training continues while the commit is gated open
    for b in batches[2:]:
        float(eng.train_batch(batch=b))
    assert eng.global_steps == steps_at_save + 2
    # durable-commit ordering: no latest marker / host state until the commit
    assert not os.path.exists(tmp_path / "latest")
    assert not os.path.exists(tmp_path / "async" / "host_state.pkl")
    assert st["thread"].is_alive()

    gate.set()
    eng.checkpoint_wait()
    assert st["thread"] is None  # the barrier joined and cleared it
    assert (tmp_path / "latest").read_text() == "async"
    assert os.path.exists(tmp_path / "async" / "host_state.pkl")

    # the checkpoint is the SNAPSHOT AT SAVE TIME (staged before the extra
    # steps), and load_checkpoint works on a fresh engine
    eng2 = _engine()
    path, _ = eng2.load_checkpoint(str(tmp_path), tag="async")
    assert path is not None
    assert eng2.global_steps == steps_at_save
    for a, b in zip(jax.tree.leaves(jax.device_get(eng2.params)),
                    jax.tree.leaves(want)):
        np.testing.assert_array_equal(a, b)


def test_next_save_barriers_on_inflight_commit(tmp_path, monkeypatch):
    """A second save must wait for the first commit (at most one in flight)."""
    order = []
    real_finish = ckpt_engine_mod.OrbaxCheckpointEngine.finish

    def logged_finish(self):
        order.append("finish")
        real_finish(self)

    monkeypatch.setattr(ckpt_engine_mod.OrbaxCheckpointEngine, "finish", logged_finish)

    eng = _engine()
    b = random_batches(1, 8, 16)[0]
    float(eng.train_batch(batch=b))
    eng.save_checkpoint(str(tmp_path), tag="first")
    first_thread = eng._async_ckpt["thread"]
    eng.save_checkpoint(str(tmp_path), tag="second")
    # the first commit's thread was joined before the second save dispatched
    assert not first_thread.is_alive()
    assert order and order[0] == "finish"
    eng.checkpoint_wait()
    assert (tmp_path / "latest").read_text() == "second"
    # both checkpoints are complete on disk
    assert os.path.exists(tmp_path / "first" / "host_state.pkl")
    assert os.path.exists(tmp_path / "second" / "host_state.pkl")


def test_sync_save_unaffected(tmp_path):
    """Without the nebula block the save path stays synchronous-durable."""
    eng = _engine(nebula=False)
    b = random_batches(1, 8, 16)[0]
    float(eng.train_batch(batch=b))
    eng.save_checkpoint(str(tmp_path), tag="sync")
    # durable immediately — no barrier needed
    assert (tmp_path / "latest").read_text() == "sync"
    assert getattr(eng, "_async_ckpt", None) is None


def test_failed_commit_surfaces_at_barrier(tmp_path, monkeypatch):
    """A commit that dies in the background must raise at the next barrier —
    silent loss of a checkpoint is the one unacceptable outcome."""
    def broken_finish(self):
        raise OSError("disk full (simulated)")

    monkeypatch.setattr(ckpt_engine_mod.OrbaxCheckpointEngine, "finish", broken_finish)
    eng = _engine()
    b = random_batches(1, 8, 16)[0]
    float(eng.train_batch(batch=b))
    eng.save_checkpoint(str(tmp_path), tag="doomed")  # returns; commit dies
    with pytest.raises(RuntimeError, match="async checkpoint commit failed"):
        eng.checkpoint_wait()
    # no durable marker was written for the failed save
    assert not os.path.exists(tmp_path / "latest")
    # the engine recovers: the next (sync-path barrier already taken) save works
    monkeypatch.undo()
    eng.save_checkpoint(str(tmp_path), tag="retry")
    eng.checkpoint_wait()
    assert (tmp_path / "latest").read_text() == "retry"
