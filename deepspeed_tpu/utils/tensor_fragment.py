"""safe_get/set accessors for ZeRO-partitioned state.

Reference: ``deepspeed/utils/tensor_fragment.py:101-241`` — the RLHF-era API
for reading/writing the full fp32 master value, optimizer state, or gradient
of an individual parameter regardless of how ZeRO sharded it.

TPU formulation: the reference keys off a live ``torch.nn.Parameter`` (whose
``ds_id``/``_hp_mapping`` attributes find its shards); functional parameter
trees have no param identity, so the key is the TREE PATH ("layers_0/mlp/fc1/
kernel" or a tuple of keys). Gathering is jax's job: ``jax.device_get`` of a
ZeRO-sharded global array materializes the full host value, and setting
``device_put``s the new value back through the leaf's sharding — no
per-stage cases; stages 1/2/3 and hpZ all take the same path.
"""

from typing import Any, Sequence, Union

import numpy as np

Path = Union[str, Sequence[str]]


def _keys(path: Path):
    if isinstance(path, str):
        return [k for k in path.replace(".", "/").split("/") if k]
    return list(path)


def _resolve(tree, path: Path):
    node = tree
    for k in _keys(path):
        if not isinstance(node, dict) or k not in node:
            raise KeyError(f"no leaf at path {path!r} (failed at {k!r}; "
                           f"available: {sorted(node) if isinstance(node, dict) else type(node)})")
        node = node[k]
    return node


def _set(tree, path: Path, value):
    """Copy-on-write nested set; returns the new tree."""
    keys = _keys(path)
    if not keys:
        return value

    def rec(node, i):
        if i == len(keys):
            return value
        if not isinstance(node, dict) or keys[i] not in node:
            raise KeyError(f"no leaf at path {path!r} (failed at {keys[i]!r})")
        out = dict(node)
        out[keys[i]] = rec(node[keys[i]], i + 1)
        return out

    return rec(tree, 0)


def _put_like(value, leaf):
    import jax
    import jax.numpy as jnp
    arr = jnp.asarray(np.asarray(value), leaf.dtype)
    if arr.shape != leaf.shape:
        raise ValueError(f"value shape {arr.shape} != param shape {leaf.shape}")
    sharding = getattr(leaf, "sharding", None)
    return jax.device_put(arr, sharding) if sharding is not None else arr


def safe_get_full_fp32_param(engine, path: Path):
    """Full (gathered) fp32 master value of the parameter at ``path``
    (reference :101)."""
    import jax
    return np.asarray(jax.device_get(_resolve(engine.params, path)))


def safe_set_full_fp32_param(engine, path: Path, value) -> None:
    """Replace the fp32 master at ``path``; the value is re-sharded through
    the leaf's existing placement (reference :117)."""
    leaf = _resolve(engine.params, path)
    engine.params = _set(engine.params, path, _put_like(value, leaf))


def _opt_field(engine, optim_state_key: str):
    state = engine.opt_state
    if not hasattr(state, optim_state_key):
        fields = getattr(state, "_fields", ())
        raise KeyError(f"optimizer state has no {optim_state_key!r} "
                       f"(available: {list(fields)})")
    return getattr(state, optim_state_key)


def _is_offloaded_stub(leaf) -> bool:
    from deepspeed_tpu.runtime.swap_tensor.partitioned_optimizer_swapper import _is_stub
    return _is_stub(leaf)


def safe_get_full_optimizer_state(engine, path: Path, optim_state_key: str):
    """Full value of one optimizer-state slot ('exp_avg', 'exp_avg_sq', ...)
    for the parameter at ``path`` (reference :133). An NVMe-offloaded leaf is
    read back ALONE (pending writes drained first) — materializing the whole
    state per call would defeat the tier's zero-host-RAM purpose for
    RLHF-style per-parameter loops."""
    import jax
    leaf = _resolve(_opt_field(engine, optim_state_key), path)
    if _is_offloaded_stub(leaf):
        if jax.process_count() > 1:
            # each process's swap file holds only ITS shards; assembling the
            # full value here would silently return zeros for foreign regions
            raise NotImplementedError(
                "safe_get_full_optimizer_state on an NVMe-offloaded leaf is "
                "single-process only (this process's swap file lacks other "
                "hosts' shards); load a checkpoint or disable offload first.")
        swapper = engine._offload.swapper
        swapper._drain_writes()  # the leaf's file may still be in flight
        return leaf._read_local(swapper.aio)
    return np.asarray(jax.device_get(leaf))


def safe_set_full_optimizer_state(engine, path: Path, value, optim_state_key: str) -> None:
    """Replace one optimizer-state slot for the parameter at ``path``
    (reference :150)."""
    field = _opt_field(engine, optim_state_key)
    leaf = _resolve(field, path)
    if _is_offloaded_stub(leaf):
        raise NotImplementedError(
            f"safe_set_full_optimizer_state: the {optim_state_key!r} slot at "
            f"{path!r} is NVMe-offloaded; restore it (disable offload or load "
            "a checkpoint) before writing through this API.")
    new_field = _set(field, path, _put_like(value, leaf))
    engine.opt_state = type(engine.opt_state)(
        **{k: (new_field if k == optim_state_key else getattr(engine.opt_state, k))
           for k in engine.opt_state._fields})


def safe_get_full_grad(engine, path: Path):
    """Full accumulated gradient at ``path``, or None outside the
    accumulation window (reference :168 returns None when no grad exists;
    the engine drops its buffer at the step boundary, so buffer identity IS
    the window truth)."""
    import jax
    if getattr(engine, "acc_grads", None) is None:
        return None
    return np.asarray(jax.device_get(_resolve(engine.acc_grads, path)))


# the reference's "local" variants return this rank's partition; under
# single-controller SPMD "this rank" = this PROCESS's addressable devices
def safe_get_local_fp32_param(engine, path: Path):
    """This process's partition of the fp32 master (reference :204).

    When every shard is addressable (single-host — the common case) this is
    the full value. On multi-host meshes the addressable shards are
    reassembled when they tile exactly one dim; irregular local tilings have
    no well-defined flat partition and raise with a pointer at the full
    accessor."""
    import jax
    leaf = _resolve(engine.params, path)
    shards = getattr(leaf, "addressable_shards", None)
    if not shards:
        return np.asarray(leaf)
    if getattr(leaf, "is_fully_addressable", False):
        return np.asarray(jax.device_get(leaf))
    # multi-host: dedupe replicated copies (one entry per local DEVICE —
    # replication repeats the same index), then reassemble distinct tiles
    def start(s):
        return tuple(idx.start or 0 for idx in s.index)

    distinct = list({start(s): s for s in shards}.values())
    if len(distinct) == 1:
        return np.asarray(distinct[0].data)
    sharded_dims = {d for s in distinct for d, off in enumerate(start(s)) if off != 0}
    if len(sharded_dims) > 1:
        raise NotImplementedError(
            f"safe_get_local_fp32_param: this process's shards of {path!r} "
            "tile multiple dims; use safe_get_full_fp32_param.")
    dim = sharded_dims.pop() if sharded_dims else 0
    ordered = sorted(distinct, key=lambda s: s.index[dim].start or 0)
    return np.concatenate([np.asarray(s.data) for s in ordered], axis=dim)
