"""Fused Lion.

Reference: ``deepspeed/ops/lion/fused_lion.py:17`` over ``csrc/lion``.
Lion: sign of the interpolated momentum, decoupled weight decay.
"""

from typing import NamedTuple

import jax
import jax.numpy as jnp

from deepspeed_tpu.ops.optimizer import TpuOptimizer, _tree_zeros_like


class LionState(NamedTuple):
    step: jnp.ndarray
    exp_avg: any


class FusedLion(TpuOptimizer):

    name = "lion"

    def __init__(self, lr=1e-4, betas=(0.9, 0.99), weight_decay=0.0):
        super().__init__(lr=lr, weight_decay=weight_decay)
        self.betas = betas

    def init(self, params):
        return LionState(step=jnp.zeros([], jnp.int32), exp_avg=_tree_zeros_like(params))

    def update(self, grads, state, params, lr):
        b1, b2 = self.betas
        wd = self.weight_decay

        def upd(p, g, m):
            g = g.astype(p.dtype)
            c = b1 * m + (1.0 - b1) * g
            new_p = p * (1.0 - lr * wd) - lr * jnp.sign(c)
            new_m = b2 * m + (1.0 - b2) * g
            return new_p, new_m

        p_flat, treedef = jax.tree.flatten(params)
        g_flat = treedef.flatten_up_to(grads)
        m_flat = treedef.flatten_up_to(state.exp_avg)
        out = [upd(p, g, m) for p, g, m in zip(p_flat, g_flat, m_flat)]
        return (jax.tree.unflatten(treedef, [o[0] for o in out]),
                LionState(step=state.step + 1, exp_avg=jax.tree.unflatten(treedef, [o[1] for o in out])))


DeepSpeedCPULion = FusedLion  # host-offloaded variant shares numerics (csrc/lion/cpu_lion.cpp)
