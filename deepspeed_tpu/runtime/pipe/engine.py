"""Pipeline-parallel engine.

Reference: ``deepspeed/runtime/pipe/engine.py`` (PipelineEngine:55 —
``train_batch:321`` executing TrainSchedule instruction streams via
``_exec_schedule:1357`` with P2P send/recv, ``_aggregate_total_loss:563``).

TPU-native execution: instead of a host loop dispatching P2P ops, the WHOLE
pipeline — M microbatches over P stages — is one jitted program:

- stage-stacked block parameters live sharded over the ``pipe`` mesh axis;
- a ``lax.scan`` over M + P - 1 ticks advances activations between neighbor
  stages with ``lax.ppermute`` (the reference's p2p.send/recv, but compiled:
  XLA overlaps the transfer with the next tick's compute);
- autodiff of the scan IS the backward pipeline — the reverse-order ticks with
  transposed ppermute reproduce the 1F1B dependency structure without an
  instruction interpreter, and gradient accumulation over microbatches falls
  out of the sum over ticks;
- first-batch tensor-meta exchange (reference ``_send_tensor_meta:854``) is
  unnecessary: shapes are static under jit.

The host-level instruction streams (schedule.py) remain as the semantic spec +
fallback executor; this engine is the fast path.

Model contract: a :class:`PipelineModule` whose built layers form
``[pre..., stack (homogeneous, length divisible by num_stages), post...]``.
``pre`` layers (e.g. embedding) run on the first stage, ``post`` (e.g. head)
on the last; the module's ``loss_fn(outputs, labels)`` closes the loss.
"""

from typing import Optional

import numpy as np

from deepspeed_tpu.runtime.engine import DeepSpeedEngine
from deepspeed_tpu.runtime.pipe.module import LayerSpec, PipelineModule
from deepspeed_tpu.utils import groups
from deepspeed_tpu.utils.logging import logger
from deepspeed_tpu.utils.jax_compat import shard_map as _compat_shard_map

PIPE_AXIS = groups.PIPE_AXIS


class PipelineError(Exception):
    ...


class PipelineEngine(DeepSpeedEngine):

    def __init__(self, args=None, model=None, mesh=None, config=None, config_class=None, **kwargs):
        assert isinstance(model, PipelineModule), "model must be a PipelineModule"
        import jax
        import jax.numpy as jnp

        self.pipeline_module = model
        # Pre-parse the config to learn the topology before the base engine runs.
        from deepspeed_tpu.runtime.config import DeepSpeedConfig
        cfg = config_class or DeepSpeedConfig(config, mesh=mesh)
        num_stages = model.num_stages

        if mesh is None and not groups.mesh_is_initialized():
            groups.initialize_mesh(model_parallel_size=cfg.tensor_parallel_size,
                                   pipe_parallel_size=num_stages,
                                   expert_parallel_size=cfg.expert_parallel_size,
                                   sequence_parallel_size=cfg.sequence_parallel_size)
        the_mesh = mesh if mesh is not None else groups.get_mesh()
        if the_mesh.shape.get(PIPE_AXIS, 1) != num_stages:
            raise PipelineError(f"mesh pipe axis {the_mesh.shape.get(PIPE_AXIS, 1)} != num_stages {num_stages}")

        # ---- build layers and split into pre / stack / post -----------------------
        layers = model.build_layers()
        rng = jax.random.PRNGKey(kwargs.get("rng_seed", 0) or 0)
        example = kwargs.pop("example_batch", None)
        if example is None:
            raise PipelineError("PipelineEngine requires example_batch=(inputs, labels) to "
                                "materialize layer parameters (shapes are static under XLA)")
        inputs, labels = example

        layer_params = []
        x = jnp.asarray(inputs)
        for i, layer in enumerate(layers):
            rng, sub = jax.random.split(rng)
            p = layer.init(sub, x)["params"]
            x = layer.apply({"params": p}, x)
            layer_params.append(p)
        out_struct = x

        structs = [jax.tree.structure(p) for p in layer_params]
        shapes = [tuple((l.shape, str(l.dtype)) for l in jax.tree.leaves(p)) for p in layer_params]

        def same(i, j):
            return (type(layers[i]) is type(layers[j]) and structs[i] == structs[j]
                    and shapes[i] == shapes[j])

        # longest homogeneous run = the stack
        best_lo, best_hi = 0, 1
        lo = 0
        for hi in range(1, len(layers) + 1):
            if hi == len(layers) or not same(lo, hi):
                if hi - lo > best_hi - best_lo:
                    best_lo, best_hi = lo, hi
                lo = hi
        stack_lo, stack_hi = best_lo, best_hi
        L = stack_hi - stack_lo
        if L % num_stages != 0:
            raise PipelineError(f"stack of {L} homogeneous layers not divisible by {num_stages} stages")

        self._pre_layers = layers[:stack_lo]
        self._stack_layer = layers[stack_lo]
        self._post_layers = layers[stack_hi:]
        self._num_stages = num_stages
        model.partition_layers(method="uniform")

        stacked = jax.tree.map(lambda *ls: jnp.stack(ls), *layer_params[stack_lo:stack_hi])
        params = {
            "pre": {str(i): p for i, p in enumerate(layer_params[:stack_lo])},
            "stack": stacked,
            "post": {str(i): p for i, p in enumerate(layer_params[stack_hi:])},
        }

        from jax.sharding import PartitionSpec as P
        specs = {
            "pre": jax.tree.map(lambda l: P(), params["pre"]),
            "stack": jax.tree.map(lambda l: P(PIPE_AXIS, *([None] * (l.ndim - 1))), params["stack"]),
            "post": jax.tree.map(lambda l: P(), params["post"]),
        }

        loss_closure = model.loss_fn or (lambda out, labels: out.mean())
        self._micro_batches = cfg.gradient_accumulation_steps
        pipeline_loss = self._make_pipeline_loss(loss_closure)

        kwargs.pop("model_parameters", None)
        kwargs.pop("loss_fn", None)
        kwargs.pop("param_specs", None)
        super().__init__(args=args,
                         model=None,
                         loss_fn=pipeline_loss,
                         model_parameters=params,
                         param_specs=specs,
                         mesh=the_mesh,
                         config=config,
                         config_class=config_class,
                         **kwargs)
        self._apply_gas_divisor = 1.0  # pipeline loss already averages microbatches

    # ------------------------------------------------------------------ loss --
    def _make_pipeline_loss(self, loss_closure):
        import jax
        import jax.numpy as jnp

        pre_layers = self._pre_layers
        stack_layer = self._stack_layer
        post_layers = self._post_layers
        P_stages = self._num_stages
        M = self._micro_batches

        def loss_fn(params, batch):
            inputs, labels = batch
            B = inputs.shape[0]
            assert B % M == 0, f"global batch {B} % microbatches {M} != 0"
            mb = B // M
            x_mb = inputs.reshape((M, mb) + inputs.shape[1:])
            y_mb = labels.reshape((M, mb) + labels.shape[1:])

            mesh = groups.get_mesh()
            from jax.sharding import PartitionSpec as PS

            dp_axes = tuple(ax for ax in groups.DATA_PARALLEL_AXES
                            if mesh.shape.get(ax, 1) > 1) or ("data", )
            param_specs = {
                "pre": jax.tree.map(lambda l: PS(), params["pre"]),
                "stack": jax.tree.map(lambda l: PS(PIPE_AXIS, *([None] * (l.ndim - 1))), params["stack"]),
                "post": jax.tree.map(lambda l: PS(), params["post"]),
            }
            batch_spec = PS(None, dp_axes)  # [M, mb@dp, ...]

            def pipelined(p, x_mb, y_mb):
                stage = jax.lax.axis_index(PIPE_AXIS)

                def embed(x):
                    for i, layer in enumerate(pre_layers):
                        x = layer.apply({"params": p["pre"][str(i)]}, x)
                    return x

                def head_loss(x, y):
                    for i, layer in enumerate(post_layers):
                        x = layer.apply({"params": p["post"][str(i)]}, x)
                    return loss_closure(x, y)

                def stage_fn(x):
                    def body(h, bp):
                        return stack_layer.apply({"params": bp}, h), None

                    return jax.lax.scan(body, x, p["stack"])[0]

                T = M + P_stages - 1
                act = jax.eval_shape(embed, jax.ShapeDtypeStruct(x_mb.shape[1:], x_mb.dtype))
                state = jnp.zeros(act.shape, act.dtype)
                losses = jnp.zeros((M, ), jnp.float32)

                def tick(carry, t):
                    state, losses = carry
                    recv = jax.lax.ppermute(state, PIPE_AXIS,
                                            [(i, i + 1) for i in range(P_stages - 1)])
                    t_in = jnp.clip(t, 0, M - 1)
                    x_t = jax.lax.dynamic_index_in_dim(x_mb, t_in, axis=0, keepdims=False)
                    # lax.cond on the per-shard stage id (valid under shard_map):
                    # only stage 0 pays for the embedding, only the last stage pays
                    # for the head + full-vocab loss — the module contract. Neither
                    # branch contains collectives, so per-stage divergence is safe.
                    inp = jax.lax.cond(stage == 0, lambda: embed(x_t), lambda: recv)
                    out = stage_fn(inp)
                    mb_idx = t - (P_stages - 1)
                    mb_safe = jnp.clip(mb_idx, 0, M - 1)
                    y_t = jax.lax.dynamic_index_in_dim(y_mb, mb_safe, axis=0, keepdims=False)
                    valid = (stage == P_stages - 1) & (mb_idx >= 0)
                    l_t = jax.lax.cond(valid,
                                       lambda: head_loss(out, y_t).astype(jnp.float32),
                                       lambda: jnp.float32(0.0))
                    losses = jnp.where(valid, losses.at[mb_safe].set(l_t), losses)
                    return (out, losses), None

                (state, losses), _ = jax.lax.scan(tick, (state, losses), jnp.arange(T))
                # last stage holds the loss; broadcast over pipe, average over data
                total = jax.lax.psum(jnp.where(stage == P_stages - 1, losses.mean(), 0.0), PIPE_AXIS)
                return jax.lax.pmean(total, dp_axes)

            return _compat_shard_map(pipelined,
                                 mesh=mesh,
                                 in_specs=(param_specs, batch_spec, batch_spec),
                                 out_specs=PS(),
                                 check_vma=False)(params, x_mb, y_mb)

        return loss_fn

    # ------------------------------------------------------------- train API --
    def train_batch(self, data_iter=None, batch=None):
        """Reference pipe/engine.py:321 — consumes gradient_accumulation_steps
        micro-batches and performs one optimizer step."""
        import jax
        import jax.numpy as jnp
        if self.is_gradient_accumulation_boundary() is False:
            # raise BEFORE consuming the caller's iterator — micro-batches
            # pulled past a raise would be silently lost
            raise PipelineError(
                "set_gradient_accumulation_boundary(False) cannot suppress the "
                "optimizer step: the pipeline fuses schedule+step into one program. "
                "Drive micro-steps through the base engine instead.")
        if batch is None:
            assert data_iter is not None
            micro = [next(data_iter) for _ in range(self._micro_batches)]
            batch = jax.tree.map(lambda *xs: np.concatenate([np.asarray(x) for x in xs]), *micro)

        batch = self.shard_batch(batch)
        rng = self._next_rng()
        loss, grads = self._grad_fn()(self.params, batch, rng, self.scale_state.cur_scale)
        lr = jnp.asarray(self._current_lr, jnp.float32)
        opt_in = self._offload.stage_in(self.opt_state)
        (self.params, self.opt_state, self.scale_state, norm,
         overflow) = self._apply_fn()(self.params, opt_in, grads, self.scale_state, lr)
        self.opt_state = self._offload.stage_out(self.opt_state)
        self._global_grad_norm = norm
        self._overflow_count = self._overflow_count + overflow.astype(jnp.int32)
        self.global_steps += 1
        self.global_samples += self.train_batch_size()
        self.micro_steps += self._micro_batches
        self._step_lr_scheduler(overflow)
        return loss

    def eval_batch(self, data_iter=None, batch=None, compute_loss=True, reduce_output="avg"):
        """Reference pipe/engine.py eval_batch — forward-only InferenceSchedule."""
        import jax
        if batch is None:
            assert data_iter is not None
            micro = [next(data_iter) for _ in range(self._micro_batches)]
            batch = jax.tree.map(lambda *xs: np.concatenate([np.asarray(x) for x in xs]), *micro)
        batch = self.shard_batch(batch)
        if "eval" not in self._compiled:
            self._compiled["eval"] = jax.jit(self.loss_fn)
        return self._compiled["eval"](self.params, batch)

    def forward(self, *a, **kw):
        raise PipelineError("Only train_batch() is accessible when using pipeline parallelism "
                            "(reference PipelineEngine raises the same)")

    def backward(self, *a, **kw):
        raise PipelineError("Only train_batch() is accessible when using pipeline parallelism")

    def step(self, *a, **kw):
        raise PipelineError("Only train_batch() is accessible when using pipeline parallelism")

    def is_gradient_accumulation_boundary(self):
        # train_batch fuses the whole 1F1B schedule + step into one program, so
        # every call IS a boundary — unless the user forced it off (reference
        # _force_grad_boundary, honored by set_gradient_accumulation_boundary)
        if self._gas_boundary_override is not None:
            return self._gas_boundary_override
        return True
