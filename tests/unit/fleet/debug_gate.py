"""Diagnostic replica of the flagship gate — NOT part of the suite.

Prints the full per-request/per-replica picture the real gate asserts on
(grant pattern, hedge/suppression counters, scheduler counters, demotion
EWMAs, XLA compiles inside the measured window) — the tool that found the
cold-bucket compile storms and the queue-starvation modes during PR 14.

Run: DSTPU_DEBUG_GATE=1 JAX_PLATFORMS=cpu \\
    python -m pytest tests/unit/fleet/debug_gate.py -q -s
"""
import os
import threading
import time

import numpy as np
import pytest

pytestmark = pytest.mark.skipif(
    not os.environ.get("DSTPU_DEBUG_GATE"),
    reason="diagnostic tool (set DSTPU_DEBUG_GATE=1), not part of the suite")

from deepspeed_tpu.fleet import (FaultConfig, FleetRouter, HedgeConfig,
                                 RoutingError)
from deepspeed_tpu.fleet.config import GlobalQueueConfig
from deepspeed_tpu.serving.config import OverloadConfig

from .test_overload import (GATE_ENGINE_KW, _arm_config, _fleet_config,
                            _open_loop, _prompt, _quiesce, _stall_config,
                            _warm_fleet)


def _open_loop_dbg(router, n, rate, deadline_s, seed):
    rng = np.random.default_rng(seed)
    arrivals = np.cumsum(rng.exponential(1.0 / rate, n))
    outcomes = []
    lock = threading.Lock()
    t0 = time.monotonic()

    def one(i, at):
        delay = at - (time.monotonic() - t0)
        if delay > 0:
            time.sleep(delay)
        doc = {"prompt": _prompt(8), "max_new_tokens": 4, "temperature": 0.0,
               "seed": i, "deadline_s": deadline_s,
               "priority": "interactive" if i % 2 == 0 else "batch"}
        s0 = time.monotonic()
        out = {"i": i, "priority": doc["priority"], "tokens": 0}
        try:
            routed = router.route(doc)
            for _tok in routed.tokens():
                out["tokens"] += 1
            final = dict(routed.result())
            out["state"] = final["state"]
            out["legs"] = [m["replica"] for m in final.get("legs", [])]
            out["hedged"] = routed._hedged
        except RoutingError as e:
            out["state"] = f"rejected:{e.status}"
            out["legs"] = []
            out["hedged"] = False
        except Exception as e:
            out["state"] = f"error:{type(e).__name__}: {e}"
            out["legs"] = []
            out["hedged"] = False
        out["e2e_s"] = time.monotonic() - s0
        out["done_at"] = time.monotonic() - t0
        with lock:
            outcomes.append(out)

    threads = [threading.Thread(target=one, args=(i, at), daemon=True)
               for i, at in enumerate(arrivals)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=600)
        assert not t.is_alive()
    return outcomes, time.monotonic() - t0


@pytest.mark.parametrize("overload_on", [True, False])
def test_debug_gate(make_fleet, overload_on):
    cap_mgr = make_fleet(roles=("mixed",), **GATE_ENGINE_KW)
    _warm_fleet(cap_mgr)
    cap_router = FleetRouter(cap_mgr)
    warm = cap_router.route({"prompt": _prompt(8), "max_new_tokens": 4}).result()
    assert warm["state"] == "DONE"
    e2es = []

    def closed(i):
        s0 = time.monotonic()
        final = cap_router.route({"prompt": _prompt(8), "max_new_tokens": 4,
                                  "temperature": 0.0, "seed": i}).result()
        assert final["state"] == "DONE"
        e2es.append(time.monotonic() - s0)

    for measured in (False, True):
        e2es.clear()
        t0 = time.monotonic()
        workers = [threading.Thread(target=lambda w=w: [closed(w * 8 + j)
                                                        for j in range(8)],
                                    daemon=True) for w in range(2)]
        for t in workers:
            t.start()
        for t in workers:
            t.join(timeout=600)
        wall = time.monotonic() - t0
    capacity = 16 / wall
    p50_e2e = float(np.percentile(np.asarray(e2es), 50))
    deadline_s = max(2.0, 8 * p50_e2e)
    offered = 3.0 * capacity
    horizon_s = 48 / offered + deadline_s
    base_outcomes, _ = _open_loop(cap_router, n=48, rate=offered,
                                  deadline_s=deadline_s, seed=77)
    base_ok = sum(1 for o in base_outcomes
                  if o["state"] == "DONE" and o["e2e_s"] <= deadline_s)
    capacity_goodput = base_ok / horizon_s
    print(f"\n=== capacity {capacity:.2f} req/s  p50 {p50_e2e*1e3:.0f}ms  "
          f"deadline {deadline_s:.2f}s  offered {offered:.2f} req/s "
          f"baseline {base_ok}/48 on-deadline -> {capacity_goodput:.2f} req/s "
          f"horizon {horizon_s:.2f}s overload_on={overload_on}")

    compiles = []
    import jax.monitoring as jm
    t_mark = [time.monotonic()]
    jm.register_event_duration_secs_listener(
        lambda e, d, **kw: compiles.append(
            (round(time.monotonic() - t_mark[0], 2), round(d, 3)))
        if "backend_compile" in e else None)

    stall = _stall_config("r0", stall_s=2.0, min_first=0.0)
    manager = make_fleet(roles=(), config=_arm_config(overload_on),
                         **GATE_ENGINE_KW)
    for rid in ("r0", "r1", "r2"):
        manager.add_local(role="mixed", replica_id=rid)
    _warm_fleet(manager)
    router = FleetRouter(manager)
    _open_loop(router, n=24, rate=offered, deadline_s=30.0, seed=7)
    _quiesce(manager)
    router.set_faults(FaultConfig(**stall.model_dump()))
    pre = len(compiles)
    t_mark[0] = time.monotonic()
    outcomes, arm_wall = _open_loop_dbg(router, n=48, rate=offered,
                                        deadline_s=deadline_s, seed=77)
    print(f"compiles during measurement: {len(compiles) - pre} "
          f"(at,dur)={compiles[pre:][:20]}")
    router.set_faults(None)

    on_deadline = [o for o in outcomes
                   if o["state"] == "DONE" and o["e2e_s"] <= deadline_s]
    from collections import Counter
    states = Counter(o["state"] for o in outcomes)
    late = [o for o in outcomes if o["state"] == "DONE" and o["e2e_s"] > deadline_s]
    r0 = [o for o in outcomes if "r0" in o.get("legs", [])]
    print(f"wall {arm_wall:.2f}s  goodput {len(on_deadline)/horizon_s:.2f}  "
          f"floor {0.85*capacity_goodput:.2f}")
    print(f"states: {dict(states)}")
    print(f"on_deadline={len(on_deadline)} late_done={len(late)} "
          f"hedged={sum(1 for o in outcomes if o['hedged'])} "
          f"touched_r0={len(r0)}")
    for o in sorted(outcomes, key=lambda o: -o["e2e_s"])[:12]:
        print(f"  i={o['i']:>2} {o['priority'][:5]:>5} {o['state'][:24]:<24} "
              f"e2e={o['e2e_s']:.2f} done_at={o['done_at']:.2f} "
              f"tok={o['tokens']} legs={o.get('legs')} hedged={o['hedged']}")
    print(f"router counters: {router._counters}")
    try:
        print(f"gq: {router._gq.describe() if router._gq else None}")
    except Exception:
        pass
    for r in manager.replicas():
        sched = r.scheduler
        c = {k: v for k, v in sched._counters.items() if v}
        print(f"  {r.id}: counters={c}")
        print(f"      overload={sched.stats()['overload']} "
              f"ttft={r.ttft_ewma_s} itl={r.itl_ewma_s} "
              f"samples=({r.ttft_samples},{r.itl_samples})")
    _quiesce(manager)
