"""Predicted-vs-observed perf through the real serving path (ISSUE tentpole
c): every program a workload exercises gets a populated observed/predicted
ratio, and a chaos-injected dispatch slowdown raises a drift event while an
identical control run stays quiet — the observed-vs-predicted gate.
"""

import numpy as np

from deepspeed_tpu import telemetry
from deepspeed_tpu.serving import RequestState, ServingConfig, ServingScheduler
from deepspeed_tpu.serving.config import CostConfig

MAX_STEPS = 400


def _run_until(sched, pred, max_steps=MAX_STEPS):
    for _ in range(max_steps):
        if pred():
            return
        sched.step()
    raise AssertionError(f"predicate not reached in {max_steps} steps")


def _prompt(n=9, vocab=64):
    return (np.arange(n) % vocab).tolist()


def test_ratio_populated_for_every_program_exercised(make_engine):
    """The gate's first clause: after a workload, every (program, bucket) the
    engine dispatched past its compile amnesty reports a live ratio — in the
    /v1/stats perf block AND as a perf_observed_ratio gauge."""
    telemetry.configure(telemetry.TelemetryConfig(enabled=True))
    engine = make_engine()
    sched = ServingScheduler(engine, ServingConfig(), start=False)
    try:
        # two identical waves: the second re-dispatches every (program,
        # bucket) the first compile-amnestied, so both flagship programs
        # report post-amnesty observations
        for _ in range(2):
            reqs = [sched.submit(_prompt(), max_new_tokens=6)
                    for _ in range(2)]
            _run_until(sched, lambda: all(r.finished for r in reqs))
            assert all(r.state is RequestState.DONE for r in reqs)

        perf = sched.stats()["perf"]
        assert perf["chip"]  # roofline joined against a concrete chip spec
        rows = perf["programs"]
        assert rows, "no programs observed — the dispatch observer rotted?"
        exercised = [r for r in rows if r["dispatches"] > 0]
        # prefill + repeated same-size decode: both flagship programs show up
        assert {r["program"] for r in exercised} \
            >= {"prefix_suffix_prefill", "paged_decode_step"}
        for row in exercised:
            assert row["ratio"] is not None and row["ratio"] > 0
            assert row["predicted_s"] > 0
            assert row["observed_p50_s"] is not None

        snap = telemetry.get_registry().snapshot()
        gauges = {(labels["program"], labels["bucket"]): v
                  for labels, v in snap["perf_observed_ratio"]}
        for row in exercised:
            assert gauges[(row["program"], str(row["bucket"]))] > 0
    finally:
        sched.stop(drain=False)


def _run_arm(make_engine, inject_delay_s):
    """One chaos-gate arm: freeze a baseline on a steady decode workload,
    then (chaos arm) inflate every subsequent dispatch's observed wall time
    via the engine's observer chain — the deterministic stand-in for a
    seeded perf fault — and report the drift evidence."""
    telemetry.shutdown()
    telemetry.state.registry = None
    telemetry.configure(telemetry.TelemetryConfig(enabled=True))
    engine = make_engine()
    cfg = ServingConfig(cost=CostConfig(perf_baseline_dispatches=2,
                                        perf_drift_consecutive=2,
                                        perf_drift_factor=4.0))
    sched = ServingScheduler(engine, cfg, start=False)
    try:
        # enough same-size decode ticks to pass amnesty AND freeze a baseline
        warm = sched.submit(_prompt(), max_new_tokens=8)
        _run_until(sched, lambda: warm.finished)
        assert any(r["baseline_ratio"] is not None
                   for r in sched.stats()["perf"]["programs"])
        if inject_delay_s:
            orig = engine.dispatch_observer
            engine.dispatch_observer = \
                lambda kind, n_seqs, n_tokens, seconds: \
                orig(kind, n_seqs, n_tokens, seconds + inject_delay_s)
        slow = sched.submit(_prompt(), max_new_tokens=8)
        _run_until(sched, lambda: slow.finished)
        drift_events = sum(r["drift_events"]
                           for r in sched.stats()["perf"]["programs"])
        events = [e for e in telemetry.get_registry().recent_events_snapshot()
                  if e.get("event") == "perf_drift"]
        snap = telemetry.get_registry().snapshot()
        counter = sum(v for _, v in snap.get("perf_drift_events_total", []))
        return drift_events, events, counter
    finally:
        sched.stop(drain=False)


def test_injected_slowdown_raises_drift_event_control_quiet(make_engine):
    # control first: the identical workload with no injection stays quiet
    drift, events, counter = _run_arm(make_engine, 0.0)
    assert drift == 0 and counter == 0 and not events

    # chaos arm: +250ms on every observed dispatch is far past
    # drift_factor x any sane CPU baseline for these tiny steps
    drift, events, counter = _run_arm(make_engine, 0.25)
    assert drift >= 1 and counter >= 1
    assert events, "drift fired but no perf_drift event reached the registry"
    assert events[-1]["ratio"] > events[-1]["baseline"]
    assert events[-1]["program"] in ("paged_decode_step",
                                     "prefix_suffix_prefill")
