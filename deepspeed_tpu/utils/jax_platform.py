"""Platform-selection helper shared by every subprocess entry point.

Site hooks (the axon TPU shim registers via sitecustomize) may force their
platform into ``jax.config`` at interpreter startup, OVERRIDING the
``JAX_PLATFORMS`` environment variable. Any process that must honor an
explicit platform choice (cpu-pinned autotuning experiments, the bench
smoke worker, CLI tools under test) has to re-assert it through
``jax.config.update`` before the first backend touch — otherwise a
cpu-pinned child hangs forever initializing a dead TPU tunnel.
"""

import json
import os
import subprocess
import sys

PROBE_TIMEOUT_S = 150  # backend init on a pod can legitimately take >60s


def probe_backend(timeout_s: int = PROBE_TIMEOUT_S):
    """Ask a SUBPROCESS for backend facts (a dead TPU tunnel hangs backend
    init rather than raising, so the parent must never touch it first).

    Returns (info_dict, ""), or (None, why) when the backend is unreachable.
    info: {backend, device_count, device_kind, process_count, memory_kinds}.
    """
    code = (
        "import json, jax\n"
        "d = jax.devices()\n"
        "print(json.dumps({'backend': jax.default_backend(),"
        " 'device_count': len(d),"
        " 'device_kind': d[0].device_kind if d else '-',"
        " 'process_count': jax.process_count(),"
        " 'memory_kinds': [m.kind for m in d[0].addressable_memories()] if d else []}))\n")
    try:
        r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                           text=True, timeout=timeout_s)
    except subprocess.TimeoutExpired:
        return None, f"backend probe hung >{timeout_s}s (dead TPU tunnel?)"
    if r.returncode != 0:
        return None, f"probe rc={r.returncode}: {(r.stderr or '').strip()[-200:]}"
    for line in reversed(r.stdout.strip().splitlines()):
        if line.startswith("{"):
            return json.loads(line), ""
    return None, "probe produced no info"


def honor_platform_env(default: str = "") -> None:
    """Re-assert ``JAX_PLATFORMS`` (or ``default``) over any site-hook
    override. No-op when neither is set. Must run before jax touches a
    backend."""
    plat = os.environ.get("JAX_PLATFORMS", "").strip() or default
    if plat:
        import jax
        jax.config.update("jax_platforms", plat)
