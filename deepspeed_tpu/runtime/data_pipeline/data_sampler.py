"""Curriculum-aware data sampler.

Reference: ``deepspeed/runtime/data_pipeline/data_sampling/data_sampler.py:338``
(DeepSpeedDataSampler) — difficulty-based curriculum batching: each metric has
per-sample difficulty values; at every step the sampler draws the global batch
from the pool of samples whose difficulty is within the current curriculum
threshold, dp-sharding deterministically.

TPU formulation: pure host logic producing index arrays; the engine's
dataloader consumes them. Difficulties come in as a numpy array (the
reference's offline ``data_analyzer`` index files reduce to exactly this).
"""

from typing import Optional

import numpy as np

from deepspeed_tpu.runtime.data_pipeline.curriculum_scheduler import CurriculumScheduler
from deepspeed_tpu.utils.logging import logger


class DeepSpeedDataSampler:
    """Deterministic curriculum batch sampler over sample difficulties."""

    def __init__(self, difficulties: np.ndarray, batch_size: int,
                 curriculum_scheduler: Optional[CurriculumScheduler] = None,
                 data_parallel_rank: int = 0, data_parallel_size: int = 1,
                 drop_last: bool = True, seed: int = 0):
        self.difficulties = np.asarray(difficulties)
        self.batch_size = batch_size
        assert batch_size % data_parallel_size == 0, \
            f"batch {batch_size} must divide over dp={data_parallel_size}"
        self.micro = batch_size // data_parallel_size
        self.scheduler = curriculum_scheduler
        self.dp_rank = data_parallel_rank
        self.dp_size = data_parallel_size
        self.drop_last = drop_last
        self.seed = seed
        self.global_step = 0

    def _eligible(self) -> np.ndarray:
        if self.scheduler is None:
            return np.arange(len(self.difficulties))
        limit = self.scheduler.update_difficulty(self.global_step)
        idx = np.nonzero(self.difficulties <= limit)[0]
        if idx.size < self.batch_size:
            logger.warning(f"curriculum difficulty {limit} admits only {idx.size} samples; "
                           f"falling back to the easiest {self.batch_size}")
            idx = np.argsort(self.difficulties)[:self.batch_size]
        return idx

    def next_batch(self) -> np.ndarray:
        """Global indices of THIS dp rank's micro-batch for the current step."""
        pool = self._eligible()
        rng = np.random.default_rng(self.seed + self.global_step)
        chosen = rng.choice(pool, size=self.batch_size, replace=pool.size < self.batch_size)
        self.global_step += 1
        return chosen[self.dp_rank * self.micro:(self.dp_rank + 1) * self.micro]

    def __iter__(self):
        steps = len(self.difficulties) // self.batch_size
        for _ in range(steps):
            yield self.next_batch()

    def __len__(self):
        return len(self.difficulties) // self.batch_size

    # checkpointable (reference state_dict/load_state_dict)
    def state_dict(self):
        sched = self.scheduler.get_state() if self.scheduler else None
        return {"global_step": self.global_step, "seed": self.seed, "scheduler": sched}

    def load_state_dict(self, sd):
        self.global_step = sd["global_step"]
        self.seed = sd["seed"]
        if self.scheduler is not None and sd.get("scheduler"):
            self.scheduler.set_state(sd["scheduler"])
