"""Budget ratchet files: the checked-in fence a program's HLO stats must stay
inside.

A budget is a JSON snapshot of a program's :class:`HloStats` plus per-metric
tolerances. Checks are ONE-SIDED: a metric may improve freely (fewer bytes,
fewer collectives, lower peak) but may not exceed ``value * (1 + tol)`` —
that is the ratchet. Two exact-by-default families ride along:

- the dtype audit (``f32_dot_count``/``dot_count``): an accidental f32 upcast
  on a bf16 path is a new f32 dot, tolerance 0;
- per-collective entries: payload bytes and op count per (op, group-size)
  key, and a collective key that did not exist at baseline is a violation
  outright (a NEW collective in a jitted program is always worth a human
  look).

Re-baselining is deliberate: ``bin/dstpu_perfgate rebaseline`` rewrites the
files; review the diff like any other code change.
"""

import json
import math
import os
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from deepspeed_tpu.perf.hlo_stats import HloStats

SCHEMA_VERSION = 1

# metric -> (one-sided) relative tolerance. Counts are exact; byte/flop
# totals get slack for minor XLA scheduling drift between rebuilds.
DEFAULT_TOLERANCES: Dict[str, float] = {
    "flops": 0.05,
    "bytes_accessed": 0.10,
    "peak_bytes": 0.10,
    "argument_bytes": 0.05,
    "output_bytes": 0.10,
    "collective_bytes_total": 0.05,
    "fusion_count": 0.25,
    "entry_instruction_count": 0.25,
    "stablehlo_op_count": 0.10,
    "dot_count": 0.0,
    "f32_dot_count": 0.0,
    "collective_bytes": 0.05,   # per-collective entries
    "collective_count": 0.0,
}

_SCALAR_METRICS = ("flops", "bytes_accessed", "peak_bytes", "argument_bytes",
                   "output_bytes", "collective_bytes_total", "fusion_count",
                   "entry_instruction_count", "stablehlo_op_count", "dot_count",
                   "f32_dot_count")


@dataclass
class Violation:
    program: str
    metric: str
    measured: float
    budget: float
    limit: float
    detail: str = ""

    def __str__(self) -> str:
        msg = (f"[{self.program}] {self.metric}: measured {self.measured:g} "
               f"> limit {self.limit:g} (budget {self.budget:g})")
        return msg + (f" — {self.detail}" if self.detail else "")


@dataclass
class Budget:
    program: str
    stats: dict                              # HloStats.to_dict() snapshot
    tolerances: Dict[str, float] = field(default_factory=dict)
    platform: str = "cpu"
    created: str = ""
    note: str = ""
    roofline: Optional[dict] = None          # informational v5e prediction

    def tol(self, metric: str) -> float:
        if metric in self.tolerances:
            return self.tolerances[metric]
        return DEFAULT_TOLERANCES.get(metric, 0.0)

    def to_json(self) -> dict:
        return {"schema_version": SCHEMA_VERSION, "program": self.program,
                "platform": self.platform, "created": self.created,
                "note": self.note, "tolerances": self.tolerances,
                "stats": self.stats, "roofline": self.roofline}

    @staticmethod
    def from_json(d: dict) -> "Budget":
        if d.get("schema_version") != SCHEMA_VERSION:
            raise ValueError(f"budget schema_version {d.get('schema_version')!r} != "
                             f"{SCHEMA_VERSION} — rebaseline with dstpu_perfgate")
        return Budget(program=d["program"], stats=d["stats"],
                      tolerances=d.get("tolerances", {}),
                      platform=d.get("platform", "cpu"),
                      created=d.get("created", ""), note=d.get("note", ""),
                      roofline=d.get("roofline"))


def default_budgets_dir() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)), "budgets")


def budget_path(budgets_dir: str, program: str) -> str:
    return os.path.join(budgets_dir, f"{program}.json")


def budget_from_stats(stats: HloStats, program: Optional[str] = None,
                      tolerances: Optional[Dict[str, float]] = None,
                      note: str = "", roofline: Optional[dict] = None) -> Budget:
    return Budget(program=program or stats.name, stats=stats.to_dict(),
                  tolerances=dict(tolerances or {}), platform=stats.platform,
                  created=time.strftime("%Y-%m-%d %H:%M:%S UTC", time.gmtime()),
                  note=note, roofline=roofline)


def write_budget(budgets_dir: str, budget: Budget) -> str:
    os.makedirs(budgets_dir, exist_ok=True)
    path = budget_path(budgets_dir, budget.program)
    with open(path, "w") as f:
        json.dump(budget.to_json(), f, indent=2, sort_keys=True)
        f.write("\n")
    return path


def load_budget(budgets_dir: str, program: str) -> Budget:
    path = budget_path(budgets_dir, program)
    if not os.path.exists(path):
        raise FileNotFoundError(
            f"no budget file for program {program!r} at {path} — create one "
            f"with: bin/dstpu_perfgate rebaseline --program {program}")
    with open(path) as f:
        return Budget.from_json(json.load(f))


def list_budgets(budgets_dir: str) -> List[str]:
    if not os.path.isdir(budgets_dir):
        return []
    return sorted(os.path.splitext(f)[0] for f in os.listdir(budgets_dir)
                  if f.endswith(".json"))


def check_stats(stats: HloStats, budget: Budget) -> List[Violation]:
    """All budget violations in ``stats`` (empty list = inside budget)."""
    out: List[Violation] = []
    measured = stats.to_dict()
    budgeted = budget.stats

    for metric in _SCALAR_METRICS:
        m = measured.get(metric)
        b = budgeted.get(metric)
        if m is None or b is None:
            continue
        limit = float(b) * (1.0 + budget.tol(metric))
        # integer counts: an exact-tolerance check must not trip on float
        # representation (limit == b exactly when tol is 0)
        if float(m) > limit + 1e-9:
            out.append(Violation(budget.program, metric, float(m), float(b), limit))

    b_coll = budgeted.get("collectives", {}) or {}
    for key, mc in (measured.get("collectives", {}) or {}).items():
        bc = b_coll.get(key)
        if bc is None:
            out.append(Violation(budget.program, f"collectives[{key}]",
                                 mc["count"], 0.0, 0.0,
                                 detail="collective op absent from the baseline appeared"))
            continue
        byte_limit = bc["bytes"] * (1.0 + budget.tol("collective_bytes"))
        if mc["bytes"] > byte_limit + 1e-9:
            out.append(Violation(budget.program, f"collectives[{key}].bytes",
                                 mc["bytes"], bc["bytes"], byte_limit,
                                 detail="collective payload grew"))
        count_limit = math.floor(bc["count"] * (1.0 + budget.tol("collective_count")) + 1e-9)
        if mc["count"] > count_limit:
            out.append(Violation(budget.program, f"collectives[{key}].count",
                                 mc["count"], bc["count"], count_limit,
                                 detail="more collective ops than the baseline"))
    return out
