"""Trainable transformer layer (reference ``deepspeed/ops/transformer/
transformer.py`` — DeepSpeedTransformerLayer/DeepSpeedTransformerConfig, the
BERT-style fused training block behind the reference's "fastest BERT
pretraining" kernels).

TPU formulation: one flax module whose forward XLA fuses into the same
attention + bias-gelu + bias-dropout-residual-layernorm pipelines the
reference hand-writes in CUDA (csrc/transformer/) — the MXU/fusion design
stance measured by the evoformer bench leg. The config keeps the reference's
field names; kernel-scheduling knobs that exist only because CUDA needs
manual memory choreography map to their XLA equivalents:

- ``normalize_invertible`` / ``attn_dropout_checkpoint`` / ``gelu_checkpoint``
  (drop specific activations, recompute in backward) → ``jax.checkpoint``
  over the sublayers with a dots-saveable policy when any is set;
- ``stochastic_mode`` (non-deterministic fast path) is a no-op: XLA is
  deterministic at no cost here;
- ``fp16`` → bf16 compute (the TPU half precision).

Pre-LN and Post-LN (``pre_layer_norm``) follow the reference semantics:
Post-LN matches ``transformers.BertLayer`` math exactly (the parity test
pins it); Pre-LN normalizes the sublayer inputs and adds a final residual
without norm, as the reference kernel does.
"""

import math
from dataclasses import dataclass, field
from functools import partial
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp
import flax.linen as nn


@dataclass(frozen=True)
class DeepSpeedTransformerConfig:
    """Reference transformer.py:33 field-for-field (see module docstring for
    the TPU mapping of the kernel-scheduling knobs)."""

    batch_size: int = -1          # the CUDA kernel pre-allocates; XLA doesn't need it
    hidden_size: int = 768
    intermediate_size: int = 3072
    heads: int = 12
    attn_dropout_ratio: float = 0.1
    hidden_dropout_ratio: float = 0.1
    num_hidden_layers: int = -1
    initializer_range: float = 0.02
    layer_norm_eps: float = 1e-12
    local_rank: int = -1          # device placement is the mesh's job on TPU
    seed: int = -1
    fp16: bool = False            # → bf16 compute
    pre_layer_norm: bool = True
    normalize_invertible: bool = False
    gelu_checkpoint: bool = False
    adjust_init_range: bool = True
    attn_dropout_checkpoint: bool = False
    stochastic_mode: bool = False
    return_tuple: bool = False
    training: bool = True

    @property
    def compute_dtype(self):
        return jnp.bfloat16 if self.fp16 else jnp.float32

    @property
    def wants_remat(self) -> bool:
        return (self.normalize_invertible or self.gelu_checkpoint
                or self.attn_dropout_checkpoint)


class _LayerBody(nn.Module):
    cfg: DeepSpeedTransformerConfig

    @nn.compact
    def __call__(self, x, attention_mask, deterministic):
        cfg = self.cfg
        H = cfg.heads
        D = cfg.hidden_size // H
        init = nn.initializers.normal(cfg.initializer_range)
        out_range = cfg.initializer_range
        if cfg.adjust_init_range and cfg.num_hidden_layers > 0:
            # reference: output_std = initializer_range / sqrt(2 * num_layers)
            out_range = cfg.initializer_range / math.sqrt(2.0 * cfg.num_hidden_layers)
        out_init = nn.initializers.normal(out_range)
        dense = partial(nn.Dense, dtype=cfg.compute_dtype, kernel_init=init)
        out_dense = partial(nn.Dense, dtype=cfg.compute_dtype, kernel_init=out_init)
        ln = partial(nn.LayerNorm, epsilon=cfg.layer_norm_eps, dtype=cfg.compute_dtype)
        attn_drop = nn.Dropout(cfg.attn_dropout_ratio)
        hidden_drop = nn.Dropout(cfg.hidden_dropout_ratio)

        def attention(h):
            q = dense(cfg.hidden_size, name="q_proj")(h).reshape(*h.shape[:-1], H, D)
            k = dense(cfg.hidden_size, name="k_proj")(h).reshape(*h.shape[:-1], H, D)
            v = dense(cfg.hidden_size, name="v_proj")(h).reshape(*h.shape[:-1], H, D)
            logits = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) / np.sqrt(D)
            if attention_mask is not None:
                m = jnp.asarray(attention_mask)
                # bool/int masks are KEEP-masks (1 = attend) in any rank;
                # float masks are additive (the HF extended-mask convention).
                # A binary float [B,1,1,S] mask would otherwise be silently
                # ADDED — wrong by +1 on kept logits and no masking at all.
                if m.ndim == 3:  # [B, Q, K] → [B, 1, Q, K]: right-aligned
                    m = m[:, None]  # broadcast would land batch on heads
                if m.ndim == 2:
                    logits = jnp.where(m[:, None, None, :] > 0, logits, -1e30)
                elif jnp.issubdtype(m.dtype, jnp.bool_) or jnp.issubdtype(m.dtype, jnp.integer):
                    logits = jnp.where(m > 0, logits, -1e30)
                else:
                    logits = logits + m.astype(jnp.float32)
            probs = jax.nn.softmax(logits, axis=-1).astype(h.dtype)
            probs = attn_drop(probs, deterministic=deterministic)
            out = jnp.einsum("bhqk,bkhd->bqhd", probs, v)
            out = out.reshape(*h.shape[:-1], cfg.hidden_size)
            return out_dense(cfg.hidden_size, name="attn_out")(out)

        def mlp(h):
            h = nn.gelu(dense(cfg.intermediate_size, name="intermediate")(h),
                        approximate=False)
            return out_dense(cfg.hidden_size, name="output")(h)

        if cfg.pre_layer_norm:
            x = x + hidden_drop(attention(ln(name="attn_layernorm")(x)),
                                deterministic=deterministic)
            x = x + hidden_drop(mlp(ln(name="out_layernorm")(x)),
                                deterministic=deterministic)
            return x
        # post-LN: transformers.BertLayer math (parity-tested)
        a = hidden_drop(attention(x), deterministic=deterministic)
        x = ln(name="attn_layernorm")(x + a)
        h = hidden_drop(mlp(x), deterministic=deterministic)
        return ln(name="out_layernorm")(x + h)


class DeepSpeedTransformerLayer(nn.Module):
    """``layer(hidden_states, attention_mask)`` (reference transformer.py:515
    forward). ``deterministic=None`` derives from ``config.training``."""

    config: DeepSpeedTransformerConfig

    @nn.compact
    def __call__(self, hidden_states, attention_mask=None, deterministic: Optional[bool] = None):
        cfg = self.config
        if deterministic is None:
            deterministic = not cfg.training
        body = _LayerBody
        if cfg.wants_remat:
            # the reference's activation-dropping knobs collapse onto remat:
            # save only matmul outputs, recompute the rest in backward
            body = nn.remat(
                _LayerBody, static_argnums=(3, ),
                policy=jax.checkpoint_policies.checkpoint_dots_with_no_batch_dims)
        out = body(cfg, name="layer")(hidden_states, attention_mask, deterministic)
        return (out, ) if cfg.return_tuple else out


def init_params(cfg: DeepSpeedTransformerConfig, batch_size: int = 2, seq_len: int = 16,
                rng=None):
    layer = DeepSpeedTransformerLayer(cfg)
    rng = rng if rng is not None else jax.random.PRNGKey(max(cfg.seed, 0))
    x = jnp.zeros((batch_size, seq_len, cfg.hidden_size), cfg.compute_dtype)
    variables = layer.init({"params": rng, "dropout": jax.random.fold_in(rng, 1)}, x)
    return layer, variables["params"]
