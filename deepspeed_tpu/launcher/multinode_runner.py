"""Multi-node runners: build the command that starts ``launch.py`` on every node.

Reference: ``deepspeed/launcher/multinode_runner.py:51-375`` (PDSHRunner,
OpenMPIRunner, SlurmRunner, MVAPICHRunner...). Each runner renders a command
line; ``runner.py`` execs it. Command *construction* is pure and unit-testable
without cluster access.
"""

import os
import shlex
import shutil
import sys
from abc import ABC, abstractmethod

from deepspeed_tpu.launcher.launch import encode_world_info

# env vars forwarded to remote shells (reference EXPORT_ENVS + .deepspeed_env)
EXPORT_ENVS = ("PYTHONPATH", "PATH", "LD_LIBRARY_PATH", "JAX_PLATFORMS", "XLA_FLAGS",
               "TPU_CHIPS_PER_HOST_BOUNDS", "TPU_HOST_BOUNDS", "LIBTPU_INIT_ARGS")


class MultiNodeRunner(ABC):

    def __init__(self, args, world_info: dict):
        self.args = args
        self.world_info = world_info
        self.user_arguments = list(getattr(args, "user_args", []) or [])
        self.user_script = args.user_script

    @abstractmethod
    def get_cmd(self, environment: dict, active_resources: dict):
        """Full argv to exec on this controller."""

    @property
    def name(self):
        return type(self).__name__

    def backend_exists(self) -> bool:
        return True

    def exports(self, environment):
        out = {}
        for var in EXPORT_ENVS:
            if var in environment:
                out[var] = environment[var]
        return out

    def _launch_args(self, node_rank: int):
        argv = ["--world_info", encode_world_info(self.world_info),
                "--node_rank", str(node_rank),
                "--master_addr", self.args.master_addr,
                "--master_port", str(self.args.master_port)]
        if getattr(self.args, "module", False):
            argv.append("--module")
        if getattr(self.args, "no_python", False):
            argv.append("--no_python")
        return argv + [self.user_script] + self.user_arguments


class PDSHRunner(MultiNodeRunner):
    """Reference multinode_runner.py:51 — one pdsh fan-out to all hosts;
    %n expands to the node's position in the pdsh host list."""

    def backend_exists(self):
        return shutil.which("pdsh") is not None

    def get_cmd(self, environment, active_resources):
        hosts = ",".join(active_resources.keys())
        env_flags = [f"export {k}={shlex.quote(str(v))};"
                     for k, v in self.exports(environment).items()]
        launch = [sys.executable, "-u", "-m", "deepspeed_tpu.launcher.launch",
                  "--world_info", encode_world_info(self.world_info),
                  "--node_rank", "%n",
                  "--master_addr", self.args.master_addr,
                  "--master_port", str(self.args.master_port)]
        if getattr(self.args, "module", False):
            launch.append("--module")
        if getattr(self.args, "no_python", False):
            launch.append("--no_python")
        launch += [self.user_script] + [shlex.quote(a) for a in self.user_arguments]
        return ["pdsh", "-S", "-f", "1024", "-w", hosts] + env_flags + launch


class SSHRunner(MultiNodeRunner):
    """Plain ssh loop fallback (one connection per host); get_cmd returns the
    command for a single node, per_node=True."""

    per_node = True

    def backend_exists(self):
        return shutil.which("ssh") is not None

    def get_cmd_for_node(self, environment, host, node_rank):
        env_flags = [f"export {k}={shlex.quote(str(v))};"
                     for k, v in self.exports(environment).items()]
        launch = [sys.executable, "-u", "-m", "deepspeed_tpu.launcher.launch"] \
            + self._launch_args(node_rank)
        return ["ssh", "-o", "StrictHostKeyChecking=no", host] \
            + env_flags + [shlex.quote(a) for a in launch]

    def get_cmd(self, environment, active_resources):
        return [self.get_cmd_for_node(environment, h, i)
                for i, h in enumerate(active_resources.keys())]


class SlurmRunner(MultiNodeRunner):
    """Reference multinode_runner.py SlurmRunner — srun spawns launch.py on
    every allocated node; SLURM_NODEID provides the node rank."""

    def backend_exists(self):
        return shutil.which("srun") is not None

    def get_cmd(self, environment, active_resources):
        nnodes = len(active_resources)
        srun = ["srun", "--nodes", str(nnodes), "--ntasks-per-node", "1"]
        if getattr(self.args, "slurm_comment", ""):
            srun += ["--comment", self.args.slurm_comment]
        env_flags = [f"export {k}={shlex.quote(str(v))};"
                     for k, v in self.exports(environment).items()]
        # SLURM_NODEID is expanded by a shell wrapper on each task; everything
        # else (incl. --module/--no_python and user args) goes through the same
        # _launch_args path as the other runners
        launch = [sys.executable, "-u", "-m", "deepspeed_tpu.launcher.launch",
                  "--world_info", encode_world_info(self.world_info),
                  "--node_rank", "$SLURM_NODEID",
                  "--master_addr", self.args.master_addr,
                  "--master_port", str(self.args.master_port)]
        if getattr(self.args, "module", False):
            launch.append("--module")
        if getattr(self.args, "no_python", False):
            launch.append("--no_python")
        launch += [self.user_script] + [shlex.quote(a) for a in self.user_arguments]
        return srun + ["bash", "-c", " ".join(env_flags) + " " + " ".join(launch)]


class LocalRunner(MultiNodeRunner):
    """Single-node: exec launch.py in-place (reference runner.py falls through
    to launch.py when no hostfile / one host)."""

    def get_cmd(self, environment, active_resources):
        return [sys.executable, "-u", "-m", "deepspeed_tpu.launcher.launch"] \
            + self._launch_args(node_rank=0)
