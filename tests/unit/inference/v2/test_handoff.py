"""Portable KV-block handoff payloads (ISSUE satellite): a sequence exported
from one engine continues token-identically on ANOTHER engine — the fleet
prefill→decode transport — plus framing/geometry/capacity failure modes."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deepspeed_tpu.inference.v2.config_v2 import RaggedInferenceEngineConfig
from deepspeed_tpu.inference.v2.engine_factory import build_engine
from deepspeed_tpu.inference.v2.ragged import handoff
from deepspeed_tpu.inference.v2.ragged.manager_configs import (AllocationMode,
                                                               DSStateManagerConfig,
                                                               MemoryConfig)
from deepspeed_tpu.models.llama import LlamaConfig, LlamaModel


@pytest.fixture(scope="module")
def llama_setup():
    cfg = LlamaConfig.tiny(dtype=jnp.float32)
    model = LlamaModel(cfg)
    ids = jnp.zeros((1, 8), jnp.int32)
    params = {"model": model.init(jax.random.PRNGKey(0), ids)["params"]}
    return cfg, params


@pytest.fixture
def make_engine(llama_setup):
    cfg, params = llama_setup
    engines = []

    def _make(num_blocks=32, block_size=16, **mgr_kw):
        mgr_kw.setdefault("max_context", 256)
        mgr = DSStateManagerConfig(
            memory_config=MemoryConfig(mode=AllocationMode.ALLOCATE, size=num_blocks),
            **mgr_kw)
        engine = build_engine(params, cfg,
                              RaggedInferenceEngineConfig(state_manager=mgr,
                                                          kv_block_size=block_size))
        engines.append(engine)
        return engine

    yield _make
    for engine in engines:
        engine.close()


def _greedy(logits_row) -> int:
    return int(np.argmax(np.asarray(logits_row)))


def _decode(engine, uid, first, n):
    toks = engine.decode_loop([uid], [np.asarray([first], np.int32)], n)
    return np.asarray(toks)[0].tolist()


def test_two_engine_continuation_token_identical(make_engine):
    """Prefill + a few decode steps on engine A, export, import on engine B,
    continue — the split run equals the single-engine run token for token."""
    a, b = make_engine(), make_engine()
    rng = np.random.default_rng(0)
    prompt = rng.integers(0, 64, 21).astype(np.int32)

    # reference: one engine, one decode_loop
    first = _greedy(np.asarray(a.put([1], [prompt]))[0])
    ref = _decode(a, 1, first, 6)

    # split run: same prefill on A under another uid, 3 steps, hand off to B
    first2 = _greedy(np.asarray(a.put([7], [prompt]))[0])
    assert first2 == first
    head = _decode(a, 7, first, 3)
    assert head == ref[:3]
    tokens = prompt.tolist() + [first] + head
    payload = a.export_sequence(7, tokens=tokens, extra={"next_token": head[-1]})
    assert isinstance(payload, bytes)
    a.flush(7)  # the recipient owns the state now

    uid, header = b.import_sequence(payload)
    assert uid == 7
    assert header["tokens"] == tokens
    assert header["extra"]["next_token"] == head[-1]
    tail = _decode(b, 7, head[-1], 3)
    assert head + tail == ref, "handoff must not change the sampled tokens"
    b.flush(7)


def test_import_under_new_uid_and_uid_collision(make_engine):
    a, b = make_engine(), make_engine()
    prompt = np.arange(9, dtype=np.int32)
    a.put([3], [prompt])
    payload = a.export_sequence(3, tokens=prompt.tolist())

    uid, _ = b.import_sequence(payload, uid=11)
    assert uid == 11
    # donor's uid is free on B, so the default lands too
    uid2, _ = b.import_sequence(payload)
    assert uid2 == 3
    with pytest.raises(ValueError, match="already tracked"):
        b.import_sequence(payload, uid=11)


def test_export_restores_offloaded_sequence(make_engine):
    a, b = make_engine(), make_engine()
    prompt = np.arange(17, dtype=np.int32)
    a.put([5], [prompt])
    a.offload_sequence(5)
    assert a.is_offloaded(5)
    payload = a.export_sequence(5, tokens=prompt.tolist())
    header, kv = handoff.unpack(payload)
    assert header["seen_tokens"] == 17 and kv is not None
    b.import_sequence(payload)
    assert b._state_manager.get_sequence(5).seen_tokens == 17


def test_framing_rejects_corruption(make_engine):
    a = make_engine()
    a.put([2], [np.arange(8, dtype=np.int32)])
    payload = a.export_sequence(2, tokens=list(range(8)))

    with pytest.raises(ValueError, match="bad magic"):
        handoff.unpack(b"NOTMAGIC" + payload[8:])
    with pytest.raises(ValueError, match="truncated"):
        handoff.unpack(payload[:-3])
    with pytest.raises(ValueError, match="must be bytes"):
        handoff.unpack({"not": "bytes"})
    # version check
    hdr = handoff.unpack(payload)[0]
    assert hdr["version"] == handoff.VERSION
    # a single flipped byte in the raw-KV region keeps every length/framing
    # check happy — only the kv_crc32 catches it (corruption-in-transit must
    # be a loud reject, never silently wrong attention on the recipient)
    assert isinstance(hdr["kv_crc32"], int)
    flipped = bytearray(payload)
    flipped[-1] ^= 0xFF
    with pytest.raises(ValueError, match="checksum mismatch"):
        handoff.unpack(bytes(flipped))


def test_seen_tokens_must_be_covered_by_shipped_kv(make_engine):
    """A crafted header claiming more committed tokens than the payload's KV
    blocks can hold is rejected at the framing layer — it must never reach a
    scheduler batch where it would attend over unallocated blocks."""
    import json
    import struct

    a = make_engine()
    a.put([8], [np.arange(20, dtype=np.int32)])
    payload = a.export_sequence(8, tokens=list(range(20)))
    header, _ = handoff.unpack(payload)
    (hdr_len, ) = struct.unpack_from("<I", payload, len(handoff.MAGIC))
    raw = payload[len(handoff.MAGIC) + 4 + hdr_len:]

    def reframe(hdr_doc):
        hdr = json.dumps(hdr_doc).encode()
        return handoff.MAGIC + struct.pack("<I", len(hdr)) + hdr + raw

    bad = dict(header)
    bad["seen_tokens"] = (header["kv"]["shape"][2] * header["cache"]["block_size"]) + 1
    with pytest.raises(ValueError, match="KV coverage"):
        handoff.unpack(reframe(bad))

    # committed tokens with no KV shipped at all is just as inconsistent
    no_kv = dict(header)
    no_kv["kv"] = None
    with pytest.raises(ValueError, match="KV coverage"):
        handoff.unpack(handoff.MAGIC
                       + struct.pack("<I", len(json.dumps(no_kv).encode()))
                       + json.dumps(no_kv).encode())


def test_geometry_mismatch_is_permanent(make_engine):
    a = make_engine(block_size=16)
    b = make_engine(block_size=8)
    a.put([4], [np.arange(10, dtype=np.int32)])
    payload = a.export_sequence(4, tokens=list(range(10)))
    header, _ = handoff.unpack(payload)
    err = handoff.compatibility_error(b._state_manager, header)
    assert err is not None and "does not match" in err
    with pytest.raises(ValueError, match="does not match"):
        b.import_sequence(payload)


def test_oversized_payload_is_permanent_small_pool_is_not(make_engine):
    a = make_engine(num_blocks=32)
    tiny = make_engine(num_blocks=2)
    prompt = np.arange(60, dtype=np.int32)  # 4 blocks of 16
    a.put([6], [prompt])
    payload = a.export_sequence(6, tokens=prompt.tolist())
    header, _ = handoff.unpack(payload)
    # 4 blocks can never fit a 2-block pool: permanent, reported before import
    assert "whole pool" in (handoff.compatibility_error(tiny._state_manager, header) or "")

    # a pool that is big enough but currently full raises the allocator's
    # capacity error and consumes nothing (evict-and-retry contract)
    b = make_engine(num_blocks=8, max_ragged_sequence_count=4)
    b.put([1], [np.arange(90, dtype=np.int32)])  # 6 of 8 blocks
    free_before = b.free_blocks
    with pytest.raises(Exception):
        b.import_sequence(payload)
    assert b.free_blocks == free_before
    assert b._state_manager.get_sequence(6) is None


def test_export_unknown_or_in_flight_uid(make_engine):
    a = make_engine()
    with pytest.raises(ValueError, match="unknown uid"):
        a.export_sequence(99, tokens=[])


def test_kv_dtype_is_part_of_the_cache_signature(make_engine):
    """Review regression: importing into a different-dtype cache would
    silently cast the KV and break token-identical continuation — the dtype
    rides the signature and mismatches are permanent."""
    a, b = make_engine(), make_engine()
    a.put([12], [np.arange(9, dtype=np.int32)])
    payload = a.export_sequence(12, tokens=list(range(9)))
    header, _ = handoff.unpack(payload)
    assert header["cache"]["dtype"] == "float32"  # these engines run fp32 KV
    tampered = dict(header, cache=dict(header["cache"], dtype="bfloat16"))
    err = handoff.compatibility_error(b._state_manager, tampered)
    assert err is not None and "does not match" in err
