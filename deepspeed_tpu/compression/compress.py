"""Config-driven model compression.

Reference: ``deepspeed/compression/compress.py`` (``init_compression:100``
walks the model swapping layers for compressed variants per config patterns;
``redundancy_clean:148`` materializes structured pruning). TPU formulation:
the "model" is a parameter pytree — compression is a tree transform keyed by
the same config schema (``weight_quantization`` / ``sparse_pruning`` /
``row_pruning`` / ``head_pruning`` blocks with ``modules`` glob patterns).
"""

import fnmatch
from typing import Dict

import numpy as np

import jax
import jax.numpy as jnp

from deepspeed_tpu.compression.basic_layer import (apply_head_mask, fake_quantize,
                                                  head_prune_mask, row_prune_mask)
from deepspeed_tpu.utils.logging import logger


def get_compression_config(param_dict: dict) -> dict:
    return param_dict.get("compression_training", {})


def _path_str(path):
    return ".".join(str(getattr(p, "key", getattr(p, "name", p))) for p in path)


def _matches(name: str, patterns) -> bool:
    return any(fnmatch.fnmatch(name, f"*{p}*") if "*" not in p else fnmatch.fnmatch(name, p)
               for p in patterns)


def _block(cfg: dict, key: str):
    """shared_parameters + the first enabled group's modules/params."""
    blk = cfg.get(key, {})
    shared = blk.get("shared_parameters", {})
    if not shared.get("enabled", False):
        return None
    groups = blk.get("different_groups", {})
    out = []
    for g in groups.values():
        params = g.get("params", {})
        out.append((g.get("modules", ["*"]), params))
    return {"shared": shared, "groups": out}


def init_compression(params, deepspeed_config: dict, mpu=None):
    """Apply the configured compression transforms to a parameter pytree
    (reference init_compression:100 — layer swap becomes a leaf transform).
    Returns the new pytree; fake-quant keeps shapes/dtypes."""
    cfg = get_compression_config(deepspeed_config if isinstance(deepspeed_config, dict)
                                 else {})
    wq = _block(cfg, "weight_quantization")
    rp = _block(cfg, "row_pruning")
    hp = _block(cfg, "head_pruning")
    sp = _block(cfg, "sparse_pruning")

    def transform(path, leaf):
        if getattr(leaf, "ndim", 0) < 2:
            return leaf
        name = _path_str(path)
        out = leaf
        if wq is not None:
            for patterns, p in wq["groups"]:
                if _matches(name, patterns):
                    bits = p.get("start_bits", p.get("target_bits", 8))
                    out = fake_quantize(out, bits=int(bits),
                                        symmetric=p.get("quantization_type", "symmetric")
                                        == "symmetric")
                    break
        if sp is not None:
            for patterns, p in sp["groups"]:
                if _matches(name, patterns):
                    ratio = float(p.get("dense_ratio", 0.5))
                    k = int(np.ceil((1 - ratio) * out.size))
                    if k > 0:
                        flat = jnp.abs(out).reshape(-1)
                        thresh = jnp.sort(flat)[k - 1]
                        out = out * (jnp.abs(out) > thresh).astype(out.dtype)
                    break
        if rp is not None:
            for patterns, p in rp["groups"]:
                if _matches(name, patterns):
                    mask = row_prune_mask(out, float(p.get("row_sparsity", 0.5)), axis=0)
                    out = out * mask[:, None].astype(out.dtype)
                    break
        if hp is not None:
            for patterns, p in hp["groups"]:
                if _matches(name, patterns):
                    heads = int(p.get("num_heads", 1))
                    mask = head_prune_mask(out, float(p.get("head_sparsity", 0.5)), heads)
                    out = apply_head_mask(out, mask, heads)
                    break
        return out

    new = jax.tree_util.tree_map_with_path(transform, params)
    logger.info("init_compression: applied "
                + ", ".join(k for k, v in (("weight_quantization", wq), ("row_pruning", rp),
                                           ("head_pruning", hp), ("sparse_pruning", sp))
                            if v is not None))
    return new


def student_initialization(student_params, teacher_params, deepspeed_config: dict):
    """Layer-reduction distillation init (reference compress.py:192):
    re-initialize the student's layers from selected TEACHER layers per the
    ``layer_reduction`` block:

        {"layer_reduction": {"enabled": true, "module_name_prefix": "layers",
                             "teacher_layer": [1, 3], "other_module_name": [...]}}

    TPU formulation: a pytree edit. Layer i of the student takes teacher layer
    ``teacher_layer[i]`` (tree keys ``{prefix}_{n}`` — the flax naming the
    in-repo models use, vs the reference's dotted ``prefix.n``);
    ``other_module_name`` entries copy whole subtrees verbatim. Returns the
    new student tree; shapes must already agree (same hidden size)."""
    cfg = get_compression_config(deepspeed_config if isinstance(deepspeed_config, dict) else {})
    lr = cfg.get("layer_reduction", {})
    if not lr.get("enabled", False):
        return student_params
    prefix = lr.get("module_name_prefix", "layers")
    if "teacher_layer" not in lr:
        raise KeyError("layer_reduction: 'teacher_layer' (the teacher layer ids the "
                       "student re-initializes from) is required when enabled")
    teacher_layer = lr["teacher_layer"]
    other = lr.get("other_module_name", [])

    def walk(tree, dotted, who):
        node = tree
        for p in dotted.split("."):
            try:
                node = node[p]
            except KeyError as e:
                raise KeyError(f"layer_reduction: {dotted!r} not found in the "
                               f"{who} tree (missing {p!r})") from e
        return node

    out = jax.tree.map(lambda x: x, student_params)  # shallow-copy dicts
    *layer_parents, layer_base = prefix.split(".")
    layers_parent = ".".join(layer_parents)
    for s_idx, t_idx in enumerate(teacher_layer):
        s_key, t_key = f"{layer_base}_{s_idx}", f"{layer_base}_{t_idx}"
        try:
            s_parent = walk(out, layers_parent, "student") if layers_parent else out
            t_parent = walk(teacher_params, layers_parent, "teacher") if layers_parent \
                else teacher_params
            t_layer = t_parent[t_key]
            s_parent[s_key]  # student must have the slot
        except KeyError as e:
            raise KeyError(f"layer_reduction: missing {s_key!r} in student or "
                           f"{t_key!r} in teacher (prefix {prefix!r})") from e
        s_parent[s_key] = t_layer
    for name in other:
        *parents, leafname = name.split(".")
        parent = ".".join(parents)
        node_t = walk(teacher_params, parent, "teacher") if parent else teacher_params
        node_s = walk(out, parent, "student") if parent else out
        if leafname not in node_t or leafname not in node_s:
            raise KeyError(f"layer_reduction: other_module_name {name!r} not present "
                           f"in both trees")
        node_s[leafname] = node_t[leafname]
    logger.info(f"layer_reduction: student layers <- teacher {teacher_layer}, "
                f"copied modules {other}")
    return out


def redundancy_clean(params, deepspeed_config: dict, mpu=None):
    """Materialize structured pruning: physically drop zeroed rows (reference
    redundancy_clean:148 shrinks the swapped layers). Only row pruning changes
    shapes; masked-but-kept transforms are already materialized in the tree."""
    cfg = get_compression_config(deepspeed_config if isinstance(deepspeed_config, dict)
                                 else {})
    rp = _block(cfg, "row_pruning")
    if rp is None:
        return params

    def transform(path, leaf):
        if getattr(leaf, "ndim", 0) != 2:
            return leaf
        name = _path_str(path)
        for patterns, p in rp["groups"]:
            if _matches(name, patterns):
                keep = np.asarray(jnp.any(jnp.asarray(leaf) != 0, axis=1))
                return jnp.asarray(leaf)[keep]
        return leaf

    return jax.tree_util.tree_map_with_path(transform, params)
