"""Offline data-efficiency tier (reference data_analyzer.py:417 +
indexed_dataset.py:617): build a memory-mapped corpus, index it offline,
train with a difficulty-from-index curriculum."""

import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.runtime.data_pipeline.curriculum_scheduler import CurriculumScheduler
from deepspeed_tpu.runtime.data_pipeline.data_sampler import DeepSpeedDataSampler
from deepspeed_tpu.runtime.data_pipeline.data_sampling import (DataAnalyzer,
                                                               MMapIndexedDataset,
                                                               MMapIndexedDatasetBuilder)
from deepspeed_tpu.utils import groups


def _build_corpus(prefix, n=64, seed=0):
    rng = np.random.default_rng(seed)
    b = MMapIndexedDatasetBuilder(prefix, dtype=np.int32)
    samples = []
    for i in range(n):
        s = rng.integers(0, 1000, size=rng.integers(4, 40))
        samples.append(s.astype(np.int32))
        b.add_item(s)
    b.finalize()
    return samples


def test_indexed_dataset_roundtrip(tmp_path):
    prefix = str(tmp_path / "corpus")
    samples = _build_corpus(prefix)
    ds = MMapIndexedDataset(prefix)
    assert len(ds) == len(samples)
    for i in (0, 7, 63, -1):
        np.testing.assert_array_equal(np.asarray(ds[i]), samples[i])
    assert ds.num_tokens(3) == samples[3].size
    assert MMapIndexedDataset.exists(prefix)
    # zero-copy: reading all samples must not materialize the corpus
    got = ds[10:13]
    assert all(isinstance(g, np.memmap) or g.base is not None for g in got)


def test_indexed_dataset_merge(tmp_path):
    a = str(tmp_path / "a")
    b = str(tmp_path / "b")
    sa = _build_corpus(a, n=5, seed=1)
    sb = _build_corpus(b, n=3, seed=2)
    m = MMapIndexedDatasetBuilder(str(tmp_path / "m"), dtype=np.int32)
    m.merge_file(a)
    m.merge_file(b)
    m.finalize()
    ds = MMapIndexedDataset(str(tmp_path / "m"))
    assert len(ds) == 8
    np.testing.assert_array_equal(np.asarray(ds[6]), sb[1])


def test_analyzer_multiworker_matches_direct(tmp_path):
    """2-worker × 2-thread map/reduce produces the same sample_to_metric as a
    direct computation, in sample order."""
    prefix = str(tmp_path / "corpus")
    samples = _build_corpus(prefix)
    ds = MMapIndexedDataset(prefix)
    an = DataAnalyzer(ds, metric_names=["seqlen", "vocabsum"],
                      metric_functions=[len, lambda s: int(np.sum(s) % 97)],
                      save_path=str(tmp_path / "idx"), num_workers=2, num_threads=2)
    out = an.run_map_reduce()
    want = np.asarray([len(s) for s in samples])
    np.testing.assert_array_equal(out["seqlen"], want)
    np.testing.assert_array_equal(
        DataAnalyzer.load_difficulties(str(tmp_path / "idx"), "seqlen"), want)
    # metric_to_sample inverts sample_to_metric
    import numpy.lib.npyio
    m2s = np.load(str(tmp_path / "idx") + "/seqlen_metric_to_sample.npz")
    for v in m2s.files:
        assert all(want[i] == int(v) for i in m2s[v])
    pct = DataAnalyzer.get_metric_value_percentiles(str(tmp_path / "idx"), "seqlen")
    assert pct[0] == want.min() and pct[100] == want.max()


def test_analyzer_accumulate_metric(tmp_path):
    prefix = str(tmp_path / "corpus")
    samples = _build_corpus(prefix, n=16)
    ds = MMapIndexedDataset(prefix)
    an = DataAnalyzer(ds, metric_names=["hist"],
                      metric_functions=[lambda s: np.bincount(np.asarray(s) % 8, minlength=8)],
                      metric_types=["accumulate_value_over_samples"],
                      save_path=str(tmp_path / "idx"), num_workers=1, num_threads=3)
    out = an.run_map_reduce()
    want = np.sum([np.bincount(s % 8, minlength=8) for s in samples], axis=0)
    np.testing.assert_array_equal(out["hist"], want)


def test_curriculum_follows_offline_index(tmp_path):
    """Train-time batch composition follows the OFFLINE index: while the
    curriculum threshold is below max difficulty, every drawn sample's indexed
    difficulty is within the threshold."""
    prefix = str(tmp_path / "corpus")
    _build_corpus(prefix)
    ds = MMapIndexedDataset(prefix)
    an = DataAnalyzer(ds, metric_names=["seqlen"], metric_functions=[len],
                      save_path=str(tmp_path / "idx"))
    an.run_map_reduce()
    diffs = DataAnalyzer.load_difficulties(str(tmp_path / "idx"), "seqlen")

    sched = CurriculumScheduler({"curriculum_type": "seqlen", "min_difficulty": 8,
                                 "max_difficulty": 40, "schedule_type": "fixed_linear",
                                 "schedule_config": {"total_curriculum_step": 10,
                                                     "difficulty_step": 1}})
    sampler = DeepSpeedDataSampler(diffs, batch_size=4, curriculum_scheduler=sched)
    for step, idx in zip(range(8), sampler):
        limit = sched.update_difficulty(step)
        assert np.all(diffs[idx] <= limit), (step, limit, diffs[idx])
