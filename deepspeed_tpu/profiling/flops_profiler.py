"""Flops profiler.

Reference: ``deepspeed/profiling/flops_profiler/profiler.py:28`` (FlopsProfiler —
monkey-patches torch functional ops + module hooks to count MACs/params/latency
per module tree; ``get_model_profile():?`` convenience API).

TPU-native implementation — no patching required, the information is already
first-class:

- per-module tree: flax's interceptor-based module table (``nn.summary``)
  yields forward flops, VJP (fwd+bwd) flops and parameter counts per submodule;
- compiled totals: ``jax.jit(...).lower(...).compile().cost_analysis()`` —
  what XLA actually schedules after fusion (the reference can only estimate
  this, a profiler on top of a compiler can read it);
- latency: wall-clock over the jitted forward (compile excluded).

MACs are reported as flops/2 (the reference counts one MAC per
multiply-accumulate; XLA/flax count both the multiply and the add).
"""

import time
from typing import Any, Optional

from deepspeed_tpu.utils.logging import logger


def _num(x, precision=2):
    if x is None:
        return "-"
    for unit, div in (("T", 1e12), ("G", 1e9), ("M", 1e6), ("K", 1e3)):
        if abs(x) >= div:
            return f"{x / div:.{precision}f} {unit}"
    return str(round(x, precision))


def number_to_string(x, precision=2):
    return _num(x, precision)


def flops_to_string(flops, precision=2):
    return _num(flops, precision) + ("FLOPS" if flops is not None else "")


def macs_to_string(macs, precision=2):
    return _num(macs, precision) + ("MACs" if macs is not None else "")


def params_to_string(params, precision=2):
    return _num(params, precision)


def duration_to_string(duration, precision=2):
    if duration is None:
        return "-"
    if duration > 1:
        return f"{duration:.{precision}f} s"
    if duration * 1000 > 1:
        return f"{duration * 1000:.{precision}f} ms"
    return f"{duration * 1e6:.{precision}f} us"


class FlopsProfiler:
    """Reference-parity surface over the jaxpr/flax cost model.

    ``model`` is a flax module; inputs are supplied to ``start_profile`` (the
    reference captures them from the profiled training step's forward)."""

    def __init__(self, model, ds_engine=None, recompute_fwd_factor: float = 0.0):
        self.model = model
        self.ds_engine = ds_engine
        self.recompute_fwd_factor = recompute_fwd_factor
        self._rows = None
        self._duration = None
        self._compiled_flops = None
        self._compiled_bytes = None
        self._started = False

    # ------------------------------------------------------------------ profile --
    def start_profile(self, ignore_list=None, *model_args, **model_kwargs):
        """Build the per-module table; measure latency when args are given."""
        import jax
        from flax.linen import summary as nn_summary

        self._started = True
        if not model_args and not model_kwargs:
            return  # reference defers counting to the profiled forward

        tab = nn_summary._get_module_table(self.model, depth=None, show_repeated=False,
                                           compute_flops=True, compute_vjp_flops=True)
        # flop counting is shape-based but flax reads it off a lowered module's
        # cost_analysis, which some PJRT plugins (TPU) don't provide pre-compile
        # — count against the CPU backend, it's the same jaxpr
        try:
            cpu = jax.local_devices(backend="cpu")[0]
            with jax.default_device(cpu):
                self._rows = tab(jax.random.PRNGKey(0), *model_args, **model_kwargs)
        except Exception:
            self._rows = tab(jax.random.PRNGKey(0), *model_args, **model_kwargs)

        params = None
        try:
            params = self.model.init(jax.random.PRNGKey(0), *model_args, **model_kwargs)
        except Exception:
            pass
        if params is not None:
            fn = jax.jit(lambda v, *a: self.model.apply(v, *a))
            try:
                compiled = fn.lower(params, *model_args).compile()
                cost = compiled.cost_analysis() or {}
                self._compiled_flops = cost.get("flops")
                self._compiled_bytes = cost.get("bytes accessed")
                out = fn(params, *model_args)
                jax.block_until_ready(out)
                t0 = time.perf_counter()
                for _ in range(3):
                    out = fn(params, *model_args)
                jax.block_until_ready(out)
                self._duration = (time.perf_counter() - t0) / 3
            except Exception as e:  # latency/cost are best-effort extras
                logger.warning(f"flops profiler: compiled analysis unavailable ({e})")

    def stop_profile(self):
        pass  # symmetric with the reference API; counting is not hook-based here

    def end_profile(self):
        self._started = False
        self._rows = None

    def reset_profile(self):
        self._rows = None
        self._duration = None

    # ------------------------------------------------------------------- totals --
    def _root_row(self):
        assert self._rows is not None, "start_profile(args...) first"
        return next(r for r in self._rows if r.path == ())

    def get_total_flops(self, as_string=False):
        f = float(self._root_row().flops)
        f = f * (1.0 + self.recompute_fwd_factor)
        return flops_to_string(f) if as_string else f

    def get_total_macs(self, as_string=False):
        m = self.get_total_flops() / 2
        return macs_to_string(m) if as_string else m

    def get_total_params(self, as_string=False):
        import jax
        p = sum(sum(x.size for x in jax.tree.leaves(v))
                for r in self._rows for v in [r.counted_variables.get("params", {})])
        return params_to_string(p) if as_string else p

    def get_total_duration(self, as_string=False):
        return duration_to_string(self._duration) if as_string else self._duration

    # ------------------------------------------------------------------- report --
    def print_model_profile(self, profile_step=1, module_depth=-1, top_modules=1,
                            detailed=True, output_file=None):
        import jax

        lines = []
        w = lines.append
        w("\n-------------------------- DeepSpeed-TPU Flops Profiler --------------------------")
        w(f"Profile Summary at step {profile_step}:")
        w("Notations:\ndata parallel size (dp_size), model parallel size(mp_size),\n"
          "number of parameters (params), number of multiply-accumulate operations(MACs),\n"
          "number of floating-point operations (flops), floating-point operations per second (FLOPS),\n"
          "fwd latency (forward propagation latency)\n")
        total_flops = self.get_total_flops()
        total_params = self.get_total_params()
        dur = self.get_total_duration()
        w(f"params per device:                                            {params_to_string(total_params)}")
        w(f"fwd MACs per device:                                          {macs_to_string(total_flops / 2)}")
        w(f"fwd flops per device:                                         {flops_to_string(total_flops)}")
        if self._compiled_flops is not None:
            w(f"fwd flops (XLA compiled, post-fusion):                        {flops_to_string(self._compiled_flops)}")
        if self._compiled_bytes is not None:
            w(f"fwd HBM bytes accessed (XLA):                                 {number_to_string(self._compiled_bytes)}B")
        if dur:
            w(f"fwd latency:                                                  {duration_to_string(dur)}")
            w(f"fwd FLOPS per device = fwd flops per device / fwd latency:    {flops_to_string(total_flops / dur)}")
        w("")

        if detailed and self._rows is not None:
            w("----------------------------- Aggregated Profile per Depth -----------------------------")
            by_depth = {}
            for r in self._rows:
                d = len(r.path)
                if module_depth >= 0 and d > module_depth:
                    continue
                by_depth.setdefault(d, []).append(r)
            for d in sorted(by_depth):
                rows = sorted(by_depth[d], key=lambda r: -(r.flops or 0))
                w(f"depth {d}:")
                shown = rows if d == 0 else rows[:max(top_modules, 1)]
                for r in shown:
                    name = "/".join(r.path) if r.path else type(self.model).__name__
                    nparams = sum(x.size for x in jax.tree.leaves(r.module_variables.get("params", {})))
                    w(f"    {name:<40} params: {params_to_string(nparams):>10}  "
                      f"fwd flops: {flops_to_string(float(r.flops or 0)):>12}  "
                      f"fwd+bwd flops: {flops_to_string(float(r.vjp_flops or 0)):>12}")
        w("------------------------------------------------------------------------------")

        text = "\n".join(lines)
        if output_file:
            with open(output_file, "w") as f:
                f.write(text)
        else:
            print(text)
        return text


def get_model_profile(model, input_shape=None, args=(), kwargs=None, print_profile=True,
                      detailed=True, module_depth=-1, top_modules=1, warm_up=1,
                      as_string=True, output_file=None, ignore_modules=None):
    """Reference get_model_profile: returns (flops, macs, params) of one forward.

    ``input_shape`` builds a float32 zeros input (reference semantics); or pass
    ``args``/``kwargs`` explicitly."""
    import jax.numpy as jnp

    kwargs = kwargs or {}
    if input_shape is not None:
        assert not args, "pass input_shape or args, not both"
        args = (jnp.zeros(input_shape, jnp.float32), )
    prof = FlopsProfiler(model)
    prof.start_profile(None, *args, **kwargs)
    flops = prof.get_total_flops()
    macs = prof.get_total_macs()
    params = prof.get_total_params()
    if print_profile:
        prof.print_model_profile(module_depth=module_depth, top_modules=top_modules,
                                 detailed=detailed, output_file=output_file)
    prof.end_profile()
    if as_string:
        return flops_to_string(flops), macs_to_string(macs), params_to_string(params)
    return flops, macs, params
