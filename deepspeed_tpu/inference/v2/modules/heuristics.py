"""Module-implementation heuristics.

Reference: ``deepspeed/inference/v2/modules/heuristics.py:36-165``
(``instantiate_attn/linear/moe/...`` — pick a concrete kernel implementation
from the registry given the model+engine config). The TPU build has two real
attention implementations to arbitrate between; everything else is one
XLA-fused implementation, so the heuristic surface is exactly this choice.
"""

from deepspeed_tpu.utils.logging import logger


def attention_implementation(model, engine_config, bucket_tokens: int) -> str:
    """Pick the attention implementation for a (model, bucket) pair.

    Returns "pallas_paged" (ops/pallas/paged_attention.py — the reference's
    blocked_flash role) or "xla_gather" (dense per-batch gather). Policy:

    - an explicit ``use_paged_kernel`` config wins;
    - the kernel needs a TPU backend, a decode-dominated bucket (its grid is
      sequential per token — long prefills amortize better through one dense
      gather), full-causal masking (the sliding-window walk is not implemented
      in-kernel), and VMEM room for its double-buffered K/V chunks.
    """
    flag = getattr(engine_config, "use_paged_kernel", None)
    if getattr(model, "attention_window", 0):
        # sliding window is only masked on the dense path — correctness beats
        # an explicit kernel request
        if flag:
            logger.warning("use_paged_kernel=True ignored: the Pallas kernel has no "
                           "sliding-window mask; using the XLA gather path")
        return "xla_gather"
    if flag is not None:
        return "pallas_paged" if flag else "xla_gather"
    import jax
    if jax.default_backend() != "tpu":
        return "xla_gather"
    if bucket_tokens > 32:
        return "xla_gather"  # prefill-heavy bucket
    from deepspeed_tpu.ops.pallas.paged_attention import CHUNK
    bs = engine_config.kv_block_size
    scratch_bytes = 2 * 2 * CHUNK * model.num_kv_heads * bs * model.head_dim * 2
    if scratch_bytes > 8 * 1024 * 1024:  # leave headroom in ~16MB VMEM
        logger.warning(f"paged kernel K/V scratch {scratch_bytes >> 20}MB exceeds VMEM "
                       f"budget (kv_block_size={bs}); using the XLA gather path")
        return "xla_gather"
    return "pallas_paged"
