"""Unified telemetry: metrics registry + span recorder + HTTP exporter.

One process-wide layer that every subsystem feeds (training engine step
metrics, per-collective latency/bytes, inference batch/token occupancy) and
that an operator can scrape (``/metrics``), tail (JSONL event stream) or load
into a trace viewer (Chrome-trace export).

Hot-path contract: when telemetry is disabled (the default) instrumented call
sites perform exactly one boolean check (``telemetry.state.active``) and
nothing else — no registry lookups, no allocations. The registry counts its
own API calls so tests can enforce this.

Usage::

    from deepspeed_tpu import telemetry
    session = telemetry.configure(TelemetryConfig(enabled=True, ...))
    telemetry.get_registry().counter('my_total').inc()  # catalog new names!
    session.close()
"""

import threading

from deepspeed_tpu.telemetry import compile_watch as compile_watch
from deepspeed_tpu.telemetry.collector import TraceCollector
from deepspeed_tpu.telemetry.config import (FlightRecorderConfig, SLOConfig,
                                            SLOObjectiveConfig, TelemetryConfig,
                                            TelemetryHTTPConfig, TimeSeriesConfig)
from deepspeed_tpu.telemetry.exporter import (TelemetryHTTPServer, scrape_metrics,
                                              start_http_server)
from deepspeed_tpu.telemetry.flight_recorder import FlightRecorder
from deepspeed_tpu.telemetry.slo import SLOEngine
from deepspeed_tpu.telemetry.timeseries import TimeSeriesStore
from deepspeed_tpu.telemetry.registry import (Counter, Gauge, Histogram, MetricsRegistry,
                                              parse_prometheus_text)
from deepspeed_tpu.telemetry.spans import (Span, SpanRecorder, TracingTimers,
                                           current_trace, new_span_id, new_trace_id,
                                           now_us, trace_context)
from deepspeed_tpu.utils.logging import logger

__all__ = [
    "TelemetryConfig", "TelemetryHTTPConfig", "FlightRecorderConfig", "MetricsRegistry",
    "TimeSeriesConfig", "SLOConfig", "SLOObjectiveConfig", "TimeSeriesStore",
    "SLOEngine", "TraceCollector",
    "Counter", "Gauge", "Histogram", "SpanRecorder", "Span", "TracingTimers",
    "TelemetryHTTPServer", "TelemetrySession", "FlightRecorder", "configure",
    "shutdown", "get_registry", "get_span_recorder", "get_flight_recorder",
    "get_timeseries", "get_slo_engine",
    "is_active", "record_comm_op", "wrap_timers", "start_http_server", "scrape_metrics",
    "parse_prometheus_text", "state", "now_us", "new_trace_id", "new_span_id",
    "trace_context", "current_trace", "compile_watch",
]

# comm-op latencies live well under the default buckets' top decades; bytes
# need their own scale
_COMM_BYTES_BUCKETS = (1024.0, 16384.0, 131072.0, 1048576.0, 8388608.0,
                       67108864.0, 536870912.0, 4294967296.0)


class _TelemetryState:
    """The one boolean the hot paths check, plus the live sinks behind it."""

    def __init__(self):
        self.active = False
        self.registry = None
        self.spans = None
        self.session = None
        self.flight_recorder = None
        self.timeseries = None
        self.slo = None
        self._lock = threading.RLock()
        self._comm_metrics = {}


state = _TelemetryState()


def get_registry():
    """The process-wide registry (created on first use; exists independently
    of whether telemetry is active so tests can count calls while disabled)."""
    with state._lock:
        if state.registry is None:
            state.registry = MetricsRegistry()
        return state.registry


def get_span_recorder():
    return state.spans


def get_flight_recorder():
    """The active :class:`FlightRecorder` (None unless configured)."""
    return state.flight_recorder


def get_timeseries():
    """The active :class:`TimeSeriesStore` (None unless configured)."""
    return state.timeseries


def get_slo_engine():
    """The active :class:`SLOEngine` (None unless configured)."""
    return state.slo


def is_active():
    return state.active


def _process_index():
    try:
        import jax
        return jax.process_index()
    except Exception:
        return 0


class TelemetrySession:

    def __init__(self, config: TelemetryConfig):
        self.config = config
        self.registry = get_registry()
        self.spans = SpanRecorder(max_spans=config.max_spans)
        self.spans.drop_counter = self.registry.counter(
            "spans_dropped_total",
            "Spans dropped from the ring buffer past max_spans")
        self.server = None
        self._closed = False
        # metrics/spans record on every rank (cheap, local); the export
        # surfaces — file sinks and the HTTP port — are process-0-only by
        # default, like the monitor backends, so multi-process runs don't
        # interleave one JSONL file or collide on a fixed port.
        self.exporting = config.all_ranks or _process_index() == 0
        if config.jsonl_path and self.exporting:
            self.registry.open_jsonl(config.jsonl_path)
        if config.http.enabled and self.exporting:
            self.server = start_http_server(self.registry, spans=self.spans,
                                            host=config.http.host, port=config.http.port)
        self.compile_watch = (compile_watch.install(self.registry, spans=self.spans)
                              if config.compile_watch else None)
        self.flight_recorder = None
        if config.flight_recorder.enabled:
            if config.flight_recorder.watchdog_enabled and self.compile_watch is None:
                # without wrapped-call occupancy the watchdog cannot tell a
                # long XLA compile from a wedged loop and will false-positive
                logger.warning(
                    "telemetry: flight-recorder watchdog is on but compile_watch "
                    "is off — a loop blocked in a long XLA compile gets no stall "
                    f"amnesty; raise watchdog_stall_s "
                    f"(={config.flight_recorder.watchdog_stall_s}s) past your "
                    "longest compile or re-enable compile_watch")
            self.flight_recorder = FlightRecorder(config.flight_recorder,
                                                  self.registry,
                                                  spans=self.spans).install()
        self.timeseries = None
        self.slo = None
        if config.timeseries.enabled or config.slo.enabled:
            # the SLO engine reads windowed deltas from the store, so
            # enabling SLOs implies the sampler even without timeseries
            ts_cfg = config.timeseries
            self.timeseries = TimeSeriesStore(
                self.registry, interval_s=ts_cfg.interval_s,
                retention_points=ts_cfg.retention_points,
                families=ts_cfg.families or None)
            if config.slo.enabled:
                self.slo = SLOEngine(config.slo, self.timeseries, self.registry)
            self.timeseries.start()
        state.spans = self.spans
        state.flight_recorder = self.flight_recorder
        state.timeseries = self.timeseries
        state.slo = self.slo
        state.session = self
        state.active = True

    @property
    def metrics_url(self):
        return self.server.url + "/metrics" if self.server else None

    def flush(self):
        """Write the Chrome trace (if configured). JSONL is flushed per event."""
        if self.config.trace_path and self.exporting:
            self.spans.export_chrome_trace(self.config.trace_path)
            logger.info(f"telemetry: wrote Chrome trace to {self.config.trace_path} "
                        f"({len(self.spans)} spans; open in chrome://tracing or Perfetto)")

    def close(self):
        """Idempotent; a session displaced by a newer configure() was already
        closed and must not touch the (shared) registry's current sinks."""
        if self._closed:
            return
        self._closed = True
        self.flush()
        if self.timeseries is not None:
            self.timeseries.stop()
        if self.server is not None:
            self.server.stop()
            self.server = None
        if self.flight_recorder is not None:
            self.flight_recorder.close()
        if self.compile_watch is not None:
            compile_watch.uninstall(self.compile_watch)
            self.compile_watch = None
        if state.session is self:
            self.registry.close_jsonl()
            state.active = False
            state.session = None
            state.spans = None
            state.timeseries = None
            state.slo = None
            if state.flight_recorder is self.flight_recorder:
                state.flight_recorder = None
            with state._lock:
                state._comm_metrics.clear()
        self.flight_recorder = None


def configure(config) -> TelemetrySession:
    """Activate telemetry from a :class:`TelemetryConfig` (or a raw dict).
    Reconfiguring closes the previous session's sinks; the registry (and its
    accumulated metrics) persists across sessions."""
    if isinstance(config, dict):
        config = TelemetryConfig(**config)
    if state.session is not None:
        state.session.close()
    return TelemetrySession(config)


def shutdown():
    if state.session is not None:
        state.session.close()


def wrap_timers(timers):
    """Wrap a timers object so start/stop pairs emit spans (engine fwd/bwd/step)."""
    return TracingTimers(timers, state.spans) if state.spans is not None else timers


def record_comm_op(op_name, latency_s, size_bytes):
    """One collective's telemetry: latency/bytes histograms, op counter and a
    span. Called from ``comm.timed_op`` only when ``state.active``."""
    with state._lock:
        metrics = state._comm_metrics.get(op_name)
        if metrics is None:
            registry = get_registry()
            labels = {"op": op_name}
            metrics = (
                registry.histogram("comm_op_latency_seconds",
                                   "Per-collective wall latency", labels=labels),
                registry.histogram("comm_op_bytes", "Per-collective message size",
                                   labels=labels, buckets=_COMM_BYTES_BUCKETS),
                registry.counter("comm_ops_total", "Collectives executed", labels=labels),
            )
            state._comm_metrics[op_name] = metrics
    lat_hist, bytes_hist, counter = metrics
    lat_hist.observe(latency_s)
    bytes_hist.observe(size_bytes)
    counter.inc()
    spans = state.spans  # snapshot: a concurrent close() may null the field
    if spans is not None:
        end = now_us()
        dur = int(latency_s * 1e6)
        spans.record(op_name, cat="comm", ts_us=end - dur, dur_us=dur,
                     args={"bytes": int(size_bytes)})
