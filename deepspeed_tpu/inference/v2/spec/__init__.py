"""Speculative decoding: drafters for the ragged decode path.

The drafter proposes cheap draft tokens per sequence per decode step; the
engine's verify step prices every proposed position in ONE ragged forward
and the scheduler accepts under the spec-off sampling rule — >1 token per
decode dispatch, exact spec-off equivalence always. Two drafter families:

- :class:`PromptLookupDrafter` — model-free n-gram lookup (drafter.py); a
  LINEAR draft verified by ``engine_v2.verify``; wins on repetitive text,
  degrades to k=0 elsewhere;
- :class:`LearnedDrafter` over a :class:`MedusaDraftHead` (learned.py) —
  tiny trained heads reading the target's hidden state; proposes a
  :class:`TokenTree` (tree.py) of candidate branches verified in one ragged
  forward by ``engine_v2.verify_tree`` under the tree-attention mask; wins
  on arbitrary text after self-distillation (distill.py).
"""

from deepspeed_tpu.inference.v2.spec.drafter import PromptLookupDrafter
from deepspeed_tpu.inference.v2.spec.learned import LearnedDrafter, MedusaDraftHead
from deepspeed_tpu.inference.v2.spec.tree import TokenTree

__all__ = ["LearnedDrafter", "MedusaDraftHead", "PromptLookupDrafter", "TokenTree"]
