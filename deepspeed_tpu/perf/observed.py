"""Predicted-vs-observed perf ledger: live dispatch timings joined against
the roofline model.

The PR-13 perf gates predict step-time *lower bounds* from static HLO cost
analysis, but nothing ever checked those predictions against live dispatch
wall times — a serving-path perf regression that stays inside the budget
ratchets is invisible.  :class:`PerfObservedLedger` closes that loop:

- the serving scheduler installs an engine ``dispatch_observer``; every jitted
  call (``put`` / ``decode_loop`` / ``verify`` / ``verify_tree``) reports its
  (kind, sequences, tokens, wall seconds);
- each dispatch maps to the flagship program that models it and a padded
  token bucket, lands in a ``perf_observed_dispatch_seconds{program,bucket}``
  histogram, and updates ``perf_observed_ratio{program,bucket}`` =
  observed / roofline-predicted step seconds;
- the FIRST sight of a (program, bucket) is **compile amnesty**: the wall time
  is dominated by the XLA compile, so it is excluded from the histogram and
  baseline and returned to the caller, which bills it to the requests in the
  batch as ``amnesty_seconds`` instead of device time;
- drift: absolute ratios are meaningless off-TPU (CPU observed vs
  TPU-predicted is orders of magnitude), so each (program, bucket) freezes a
  baseline ratio from its first ``baseline_dispatches`` post-amnesty
  observations; a run of ``drift_consecutive`` dispatches whose ratio exceeds
  ``drift_factor`` x baseline raises a drift event
  (``perf_drift_events_total{program}`` + a ``perf_drift`` registry event),
  which the time-series store samples and the SLO engine can alarm on.

Like the cost ledger, this object only exists while a telemetry session is
active; with telemetry off the engine's observer slot stays None and the
dispatch path pays a single attribute load.
"""

from deepspeed_tpu.perf.chip_specs import DEFAULT_CHIP, get_chip_spec

# engine dispatch kind -> the flagship program whose roofline models it; a
# `put` whose feeds are all single tokens IS a paged decode step
_KIND_PROGRAM = {
    "decode_loop": "paged_decode_step",
    "verify": "spec_verify_step",
    "verify_tree": "spec_tree_verify",
}


def _bucket(tokens: int) -> int:
    """Padded token bucket: next power of two (the engine pads ragged batches
    to bucketed shapes, so wall times cluster by bucket, not exact size)."""
    b = 1
    while b < tokens:
        b <<= 1
    return b


class _KeyState:
    __slots__ = ("hist", "ratio_gauge", "dispatches", "amnestied",
                 "baseline", "_baseline_sum", "_baseline_n",
                 "over_run", "drift_events", "last_ratio", "predicted_s")

    def __init__(self, hist, ratio_gauge, predicted_s):
        self.hist = hist
        self.ratio_gauge = ratio_gauge
        self.predicted_s = predicted_s
        self.dispatches = 0
        self.amnestied = False
        self.baseline = None
        self._baseline_sum = 0.0
        self._baseline_n = 0
        self.over_run = 0
        self.drift_events = 0
        self.last_ratio = None


class PerfObservedLedger:

    def __init__(self, registry, pricebook, chip: str = DEFAULT_CHIP,
                 drift_factor: float = 4.0, drift_consecutive: int = 3,
                 baseline_dispatches: int = 8):
        self._registry = registry
        self._pricebook = pricebook
        self._chip = get_chip_spec(chip or DEFAULT_CHIP)
        self._drift_factor = float(drift_factor)
        self._drift_consecutive = max(1, int(drift_consecutive))
        self._baseline_dispatches = max(1, int(baseline_dispatches))
        self._keys = {}           # (program, bucket) -> _KeyState
        self._predictions = {}    # program -> explicit step_s override
        self._drift_counters = {}  # program -> counter

    # ------------------------------------------------------------ predictions --
    def load_predictions(self, step_s_by_program: dict) -> None:
        """Install explicit per-program predicted step seconds (e.g. from a
        perf-gate budgets file); they override the analytic roofline price for
        every bucket of that program."""
        self._predictions.update({str(k): float(v)
                                  for k, v in step_s_by_program.items()})

    def _predicted_s(self, program: str, bucket: int) -> float:
        explicit = self._predictions.get(program)
        if explicit is not None:
            return explicit
        # analytic roofline over the price book's per-token facts: the step
        # can be no faster than the busiest resource
        compute_s = self._pricebook.flops(bucket) / self._chip.peak_bf16_flops
        memory_s = self._pricebook.bytes(bucket) / self._chip.hbm_bytes_per_s
        return max(compute_s, memory_s, 1e-12)

    # -------------------------------------------------------------- observing --
    @staticmethod
    def program_for(kind: str, n_seqs: int, n_tokens: int) -> str:
        mapped = _KIND_PROGRAM.get(kind)
        if mapped is not None:
            return mapped
        # `put`: multi-token feeds are prefill chunks, all-single-token feeds
        # are one decode step
        return "prefix_suffix_prefill" if n_tokens > n_seqs else "paged_decode_step"

    def observe(self, kind: str, n_seqs: int, n_tokens: int, seconds: float) -> float:
        """Record one dispatch; returns the compile-amnesty seconds (the whole
        wall time on first sight of a (program, bucket), else 0.0)."""
        program = self.program_for(kind, n_seqs, n_tokens)
        bucket = _bucket(max(1, n_tokens))
        key = (program, bucket)
        st = self._keys.get(key)
        if st is None:
            labels = {"program": program, "bucket": str(bucket)}
            st = self._keys[key] = _KeyState(
                self._registry.histogram(
                    "perf_observed_dispatch_seconds",
                    "wall seconds around the engine's jitted dispatches, by program/bucket",
                    labels=labels),
                self._registry.gauge(
                    "perf_observed_ratio",
                    "observed dispatch seconds over roofline-predicted step seconds",
                    labels=labels),
                self._predicted_s(program, bucket))
        if not st.amnestied:
            # first sight of this (program, bucket): the compile dominates
            st.amnestied = True
            return seconds
        ratio = seconds / st.predicted_s
        st.dispatches += 1
        st.last_ratio = ratio
        st.hist.observe(seconds)
        st.ratio_gauge.set(ratio)
        if st.baseline is None:
            st._baseline_sum += ratio
            st._baseline_n += 1
            if st._baseline_n >= self._baseline_dispatches:
                st.baseline = st._baseline_sum / st._baseline_n
            return 0.0
        if ratio > self._drift_factor * st.baseline:
            st.over_run += 1
            if st.over_run >= self._drift_consecutive:
                st.over_run = 0
                self._drift(program, bucket, st, ratio)
        else:
            st.over_run = 0
        return 0.0

    def _drift(self, program: str, bucket: int, st: _KeyState, ratio: float) -> None:
        st.drift_events += 1
        counter = self._drift_counters.get(program)
        if counter is None:
            counter = self._drift_counters[program] = self._registry.counter(
                "perf_drift_events_total",
                "sustained observed-vs-predicted dispatch-time drift episodes",
                labels={"program": program})
        counter.inc()
        self._registry.event("perf_drift", program=program, bucket=bucket,
                             ratio=round(ratio, 3),
                             baseline=round(st.baseline, 3),
                             factor=self._drift_factor,
                             predicted_s=st.predicted_s)

    # ---------------------------------------------------------------- reading --
    def doc(self) -> dict:
        """The /v1/stats ``perf`` block: the live predicted-vs-observed join."""
        rows = []
        for (program, bucket), st in sorted(self._keys.items()):
            rows.append({
                "program": program,
                "bucket": bucket,
                "dispatches": st.dispatches,
                "predicted_s": st.predicted_s,
                "observed_p50_s": st.hist.quantile(0.5),
                "ratio": st.last_ratio,
                "baseline_ratio": st.baseline,
                "drift_events": st.drift_events,
            })
        return {"chip": self._chip.name,
                "drift_factor": self._drift_factor,
                "programs": rows}
