import jax
import jax.numpy as jnp
import pytest

from deepspeed_tpu import telemetry
from deepspeed_tpu.inference.v2.config_v2 import RaggedInferenceEngineConfig
from deepspeed_tpu.inference.v2.engine_factory import build_engine
from deepspeed_tpu.inference.v2.ragged.manager_configs import (AllocationMode,
                                                               DSStateManagerConfig,
                                                               MemoryConfig)
from deepspeed_tpu.models.llama import LlamaConfig, LlamaModel


@pytest.fixture(autouse=True)
def fresh_telemetry():
    """Telemetry state is process-global: serving tests must neither inherit a
    leaked session nor leave one behind (same contract as tests/unit/telemetry)."""
    telemetry.shutdown()
    telemetry.state.registry = None
    yield
    telemetry.shutdown()
    telemetry.state.registry = None


@pytest.fixture(scope="package")
def llama_setup():
    # package scope: one model init for the whole serving suite, not one per
    # test file — the params are read-only inputs to every engine build
    cfg = LlamaConfig.tiny(dtype=jnp.float32)
    model = LlamaModel(cfg)
    ids = jnp.zeros((1, 8), jnp.int32)
    params = {"model": model.init(jax.random.PRNGKey(0), ids)["params"]}
    return cfg, model, params


@pytest.fixture
def make_engine(llama_setup):
    """Engine factory with a small, test-controllable KV pool; every engine
    built through it is closed at teardown (scheduler detach + tracer clear)."""
    cfg, _, params = llama_setup
    engines = []

    def _make(num_blocks=64, block_size=16, **mgr_kw):
        mgr_kw.setdefault("max_context", 512)
        mgr = DSStateManagerConfig(
            memory_config=MemoryConfig(mode=AllocationMode.ALLOCATE, size=num_blocks),
            **mgr_kw)
        engine = build_engine(params, cfg,
                              RaggedInferenceEngineConfig(state_manager=mgr,
                                                          kv_block_size=block_size))
        engines.append(engine)
        return engine

    yield _make
    for engine in engines:
        engine.close()
