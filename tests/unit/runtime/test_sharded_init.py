"""Sharded-at-birth parameter init (the zero.Init analog).

Reference: ``deepspeed/runtime/zero/partition_parameters.py:786`` (zero.Init) —
parameters are partitioned at construction so the full model never
materializes per-rank. Here: ``engine(example_batch=...)`` jit-inits straight
into the ZeRO shardings; the test instruments the module to prove init only
ever ran under trace (no eager host materialization) and that stage-3 leaves
come out sharded."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import flax.linen as nn

import deepspeed_tpu
from deepspeed_tpu.utils import groups

HIDDEN = 32
CALLS = {"eager": 0, "traced": 0}


class Probe(nn.Module):
    """Records whether __call__ executes eagerly or under trace."""

    @nn.compact
    def __call__(self, batch):
        x, y = batch
        if isinstance(jnp.asarray(0.0) + 0.0, jax.core.Tracer) or isinstance(x, jax.core.Tracer):
            CALLS["traced"] += 1
        else:
            CALLS["eager"] += 1
        h = nn.Dense(HIDDEN)(x)
        h = nn.relu(h)
        out = nn.Dense(HIDDEN)(h)
        return jnp.mean((out - y) ** 2)


def _cfg(stage):
    return {
        "train_micro_batch_size_per_gpu": 2,
        "optimizer": {"type": "AdamW", "params": {"lr": 0.01}},
        "zero_optimization": {"stage": stage, "stage3_param_persistence_threshold": 0},
    }


def test_params_born_sharded_stage3():
    groups.initialize_mesh(force=True)
    CALLS["eager"] = CALLS["traced"] = 0
    rng = np.random.default_rng(0)
    batch = (rng.normal(size=(16, HIDDEN)).astype(np.float32),
             rng.normal(size=(16, HIDDEN)).astype(np.float32))
    eng, _, _, _ = deepspeed_tpu.initialize(model=Probe(), config=_cfg(3), example_batch=batch)

    # init executed, but never eagerly: the full tree was never on the host
    assert CALLS["eager"] == 0, "zero.Init analog must not materialize params eagerly"
    assert CALLS["traced"] >= 1

    # stage-3: divisible leaves actually sharded over the zero axes
    sharded = [l for l in jax.tree.leaves(eng.params)
               if l.ndim > 0 and not l.sharding.is_fully_replicated]
    assert sharded, "stage 3 must shard parameters"

    # and the engine still trains
    l0 = float(eng.train_batch(batch=batch))
    l1 = float(eng.train_batch(batch=batch))
    assert l1 < l0


def test_born_sharded_matches_host_init():
    """Same rng seed → identical params whether born sharded or passed in."""
    groups.initialize_mesh(force=True)
    rng = np.random.default_rng(1)
    batch = (rng.normal(size=(16, HIDDEN)).astype(np.float32),
             rng.normal(size=(16, HIDDEN)).astype(np.float32))
    model = Probe()
    eng, _, _, _ = deepspeed_tpu.initialize(model=model, config=_cfg(3), example_batch=batch,
                                            rng_seed=7)

    key = jax.random.split(jax.random.PRNGKey(7))[1]
    host_params = model.init(key, batch)["params"]
    groups.initialize_mesh(force=True)
    ref, _, _, _ = deepspeed_tpu.initialize(model=model, model_parameters=host_params,
                                            config=_cfg(3))
    for a, b in zip(jax.tree.leaves(jax.device_get(eng.params)),
                    jax.tree.leaves(jax.device_get(ref.params))):
        np.testing.assert_array_equal(a, b)
