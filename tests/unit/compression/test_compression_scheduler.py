"""Progressive compression scheduling (reference compression/scheduler.py —
the engine steps technique schedules; transforms fire at their offsets)."""

import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.utils import groups

from ..simple_model import make_simple_model, random_batches

HIDDEN = 16


def _cfg(extra_compression, gas=1):
    return {
        "train_micro_batch_size_per_gpu": 16 // gas,
        "gradient_accumulation_steps": gas,
        "optimizer": {"type": "AdamW", "params": {"lr": 0.001, "weight_decay": 0.0}},
        "compression_training": extra_compression,
    }


def _wq(offset, frequency=0, **shared_extra):
    return {"weight_quantization": {
        "shared_parameters": {"enabled": True, "schedule_offset": offset,
                              "frequency": frequency, **shared_extra},
        "different_groups": {"g": {"params": {"target_bits": 4}, "modules": ["*"]}},
    }}


def _n_distinct(engine):
    import jax
    leaves = [np.asarray(l) for l in jax.tree.leaves(jax.device_get(engine.params))
              if np.asarray(l).ndim == 2]
    return max(len(np.unique(l)) for l in leaves)


def test_quantization_fires_at_offset():
    """Parameters stay full precision until schedule_offset, then snap to the
    4-bit grid — staged compression visible in the parameter statistics."""
    groups.initialize_mesh(force=True)
    model, params0 = make_simple_model(hidden_dim=HIDDEN, batch_size=16)
    eng, _, _, _ = deepspeed_tpu.initialize(model=model, model_parameters=params0,
                                            config=_cfg(_wq(offset=3)))
    assert eng.compression_scheduler is not None
    batches = random_batches(6, 16, HIDDEN)
    for i, b in enumerate(batches):
        eng.train_batch(batch=b)
        distinct = _n_distinct(eng)
        if eng.global_steps < 3:
            assert distinct > 64, (eng.global_steps, distinct)
        elif eng.global_steps == 3:
            # 4-bit symmetric fake-quant: <= 16 levels per channel row, far
            # fewer distinct values than the fp32 matrix had
            assert distinct <= 16 * HIDDEN, distinct


def test_quantization_reapplies_on_frequency():
    groups.initialize_mesh(force=True)
    model, params0 = make_simple_model(hidden_dim=HIDDEN, batch_size=16)
    eng, _, _, _ = deepspeed_tpu.initialize(model=model, model_parameters=params0,
                                            config=_cfg(_wq(offset=1, frequency=2)))
    applied = []
    orig = eng.apply_compression_transform

    def spy(sub_cfg):
        applied.append(eng.global_steps)
        orig(sub_cfg)

    eng.apply_compression_transform = spy
    for b in random_batches(6, 16, HIDDEN):
        eng.train_batch(batch=b)
    assert applied == [1, 3, 5], applied


def test_loss_curve_shows_staged_compression():
    """The quantization event at the offset perturbs the loss trajectory
    relative to an uncompressed run — before the offset the two runs are
    IDENTICAL (scheduling really is staged, not at-init)."""
    groups.initialize_mesh(force=True)
    model, params0 = make_simple_model(hidden_dim=HIDDEN, batch_size=16)
    batches = random_batches(8, 16, HIDDEN)

    def run(compression):
        groups.initialize_mesh(force=True)
        cfg = _cfg(compression) if compression else \
            {k: v for k, v in _cfg({}).items() if k != "compression_training"}
        eng, _, _, _ = deepspeed_tpu.initialize(model=model, model_parameters=params0,
                                                config=cfg)
        return [float(eng.train_batch(batch=b)) for b in batches]

    plain = run(None)
    comp = run(_wq(offset=4))
    np.testing.assert_allclose(comp[:4], plain[:4], rtol=1e-6)
    assert any(abs(a - b) > 1e-7 for a, b in zip(comp[5:], plain[5:])), \
        "quantization at step 4 must perturb later losses"


def test_eigenvalue_gate_defers_activation():
    """eigenvalue_gated quantization waits for curvature below the threshold;
    with an impossible threshold it never fires, with a huge one it fires at
    the offset."""
    groups.initialize_mesh(force=True)
    model, params0 = make_simple_model(hidden_dim=HIDDEN, batch_size=16)
    batches = random_batches(4, 16, HIDDEN)

    def run(threshold):
        groups.initialize_mesh(force=True)
        eng, _, _, _ = deepspeed_tpu.initialize(
            model=model, model_parameters=params0,
            config=_cfg(_wq(offset=1, eigenvalue_gated=True,
                            eigenvalue_threshold=threshold)))
        fired = []
        orig = eng.apply_compression_transform
        eng.apply_compression_transform = lambda c: (fired.append(eng.global_steps), orig(c))
        for b in batches:
            eng.train_batch(batch=b)
        return fired

    assert run(threshold=1e-30) == []          # never smooth enough
    assert run(threshold=1e30) == [1]          # gate trivially open at offset


def test_scheduler_state_roundtrip():
    from deepspeed_tpu.compression.scheduler import CompressionScheduler

    cfg = {"compression_training": _wq(offset=2, frequency=3)}
    a = CompressionScheduler(cfg)
    a.techniques["weight_quantization"]["active"] = True
    a.techniques["weight_quantization"]["last_applied"] = 5
    a.training_steps = 6
    b = CompressionScheduler(cfg)
    b.load_state_dict(a.state_dict())
    assert b.training_steps == 6
    assert b.techniques["weight_quantization"]["active"]
    assert b.techniques["weight_quantization"]["last_applied"] == 5


def test_student_initialization_layer_reduction():
    """Layer-reduction distillation init (reference compress.py:192): student
    layer i takes teacher layer teacher_layer[i]; listed modules copy whole."""
    import jax
    import jax.numpy as jnp
    from deepspeed_tpu.compression import student_initialization
    from deepspeed_tpu.models.llama import LlamaConfig, init_params

    t_cfg = LlamaConfig.tiny(num_hidden_layers=4, dtype=jnp.float32)
    s_cfg = LlamaConfig.tiny(num_hidden_layers=2, dtype=jnp.float32)
    _, teacher = init_params(t_cfg, rng=jax.random.PRNGKey(0))
    s_model, student = init_params(s_cfg, rng=jax.random.PRNGKey(1))

    cfg = {"compression_training": {"layer_reduction": {
        "enabled": True, "module_name_prefix": "model.layers",
        "teacher_layer": [1, 3],
        "other_module_name": ["model.embed_tokens", "model.norm", "model.lm_head"]}}}
    out = student_initialization(student, teacher, cfg)

    for s_i, t_i in ((0, 1), (1, 3)):
        a = jax.tree.leaves(out["model"][f"layers_{s_i}"])
        b = jax.tree.leaves(teacher["model"][f"layers_{t_i}"])
        assert all(np.array_equal(x, y) for x, y in zip(a, b))
    assert np.array_equal(out["model"]["embed_tokens"]["embedding"],
                          teacher["model"]["embed_tokens"]["embedding"])
    # untouched student leaves stay the student's (nothing silently replaced)
    ids = np.zeros((1, 8), np.int32)
    s_model.apply({"params": out}, (ids, ids))  # still a valid 2-layer model

    # disabled block is the identity
    same = student_initialization(student, teacher, {})
    assert all(np.array_equal(a, b) for a, b in
               zip(jax.tree.leaves(same), jax.tree.leaves(student)))

    with pytest.raises(KeyError, match="layer_reduction"):
        student_initialization(student, teacher, {"compression_training": {
            "layer_reduction": {"enabled": True, "module_name_prefix": "model.layers",
                                "teacher_layer": [0, 1, 2]}}})


def test_xtc_binary_ternary_quantization():
    """XTC tier (reference compression/utils.py Binary/TernaryQuantizer):
    1-bit snaps to ±(mean magnitude) per channel; 2-bit to {-a, 0, +a} with a
    0.7·mean|w| threshold."""
    from deepspeed_tpu.compression import fake_quantize

    rng = np.random.default_rng(0)
    w = rng.normal(size=(32, 8)).astype(np.float32)

    b = np.asarray(fake_quantize(w, bits=1))
    for c in range(8):
        col = b[:, c]
        assert len(np.unique(np.abs(col))) == 1          # one magnitude
        np.testing.assert_allclose(np.unique(np.abs(col))[0],
                                   np.abs(w[:, c]).mean(), rtol=1e-5)
        assert np.array_equal(np.sign(col), np.sign(w[:, c]))

    wz = w.copy()
    wz[0, :] = 0.0  # pruned weights must STAY zero under binarization
    bz = np.asarray(fake_quantize(wz, bits=1))
    assert not np.any(bz[0, :])

    t = np.asarray(fake_quantize(w, bits=2))
    for c in range(8):
        vals = np.unique(t[:, c])
        assert len(vals) <= 3 and (0.0 in vals)          # {-a, 0, +a}
        thresh = 0.7 * np.abs(w[:, c]).mean()
        np.testing.assert_array_equal(t[:, c] == 0, np.abs(w[:, c]) <= thresh)
