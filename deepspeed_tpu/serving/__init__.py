"""Serving layer: persistent request-lifecycle subsystem over InferenceEngineV2.

Reference: DeepSpeed-FastGen/MII's persistent deployment (Holmes et al. 2024)
— continuous admission, Dynamic SplitFuse chunked-prefill/decode interleaving
(iteration-level scheduling per Orca, Yu et al. OSDI'22), per-request token
streaming, deadlines, and backpressure.

Usage::

    from deepspeed_tpu.serving import ServingConfig, ServingScheduler, ServingServer

    scheduler = ServingScheduler(engine, ServingConfig(decode_chunk=4))
    req = scheduler.submit(prompt_tokens, max_new_tokens=64, deadline_s=2.0)
    for token in req.stream:          # streams as the scheduler generates
        ...
    server = ServingServer(scheduler).start()   # POST /v1/generate (SSE), GET /v1/stats
    server.stop()                               # graceful drain
"""

from deepspeed_tpu.serving.config import (KVTierConfig, OverloadConfig,
                                          PrefixCacheConfig, ServingConfig,
                                          SpeculativeConfig)
from deepspeed_tpu.serving.metrics import ServingMetrics
from deepspeed_tpu.serving.overload import (PRIORITIES, BrownoutController,
                                            RateEstimator)
from deepspeed_tpu.serving.request import (Request, RequestState, TERMINAL_STATES,
                                           TokenStream)
from deepspeed_tpu.serving.scheduler import (AdmissionRejected, QueueFullError,
                                             SchedulerStopped, ServingScheduler)
from deepspeed_tpu.serving.server import ServingServer

__all__ = [
    "KVTierConfig", "OverloadConfig", "PrefixCacheConfig", "SpeculativeConfig", "PRIORITIES",
    "BrownoutController", "RateEstimator",
    "ServingConfig", "ServingMetrics", "Request", "RequestState", "TERMINAL_STATES",
    "TokenStream", "ServingScheduler", "AdmissionRejected", "QueueFullError",
    "SchedulerStopped", "ServingServer",
]
