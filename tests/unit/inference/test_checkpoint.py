"""HF-checkpoint loader round-trip (ADVICE r2: the lm_head path was untested and
mapped outside the "model" subtree, crashing every build_hf_engine-loaded model).

Strategy: export a tiny training tree to HF tensor naming (the inverse of
``inference/checkpoint.py``'s mapping), write a safetensors shard + config.json,
reload with ``load_hf_checkpoint`` and demand the trees match leaf-for-leaf —
then run the loaded tree through ``build_hf_engine`` and compare logits against
an engine built directly on the original params."""

import json
import os

import numpy as np

import jax.numpy as jnp

from deepspeed_tpu.inference.checkpoint import load_hf_checkpoint
from deepspeed_tpu.models.llama import LlamaConfig, init_params as llama_init
from deepspeed_tpu.models.mixtral import MixtralConfig, init_params as mixtral_init
from deepspeed_tpu.utils import groups


def _hf_config_dict(cfg, model_type):
    d = dict(model_type=model_type,
             architectures=[{"llama": "LlamaForCausalLM", "mistral": "MistralForCausalLM",
                             "mixtral": "MixtralForCausalLM"}[model_type]],
             vocab_size=cfg.vocab_size, hidden_size=cfg.hidden_size,
             intermediate_size=cfg.intermediate_size,
             num_hidden_layers=cfg.num_hidden_layers,
             num_attention_heads=cfg.num_attention_heads,
             num_key_value_heads=cfg.num_key_value_heads,
             max_position_embeddings=cfg.max_position_embeddings,
             rms_norm_eps=cfg.rms_norm_eps, rope_theta=cfg.rope_theta,
             torch_dtype="float32")
    if model_type == "mixtral":
        d["num_local_experts"] = cfg.num_local_experts
        d["num_experts_per_tok"] = cfg.num_experts_per_tok
    return d


def _export_hf(params, cfg, path, model_type):
    """Write the training tree as an HF-named safetensors checkpoint."""
    from safetensors.numpy import save_file

    def _c(x):  # safetensors writes the raw buffer: views must be materialized
        return np.ascontiguousarray(x)
    root = params["model"] if "model" in params else params
    out = {}
    out["model.embed_tokens.weight"] = _c(np.asarray(root["embed_tokens"]["embedding"]))
    out["model.norm.weight"] = _c(np.asarray(root["norm"]["weight"]))
    out["lm_head.weight"] = _c(np.asarray(root["lm_head"]["kernel"]).T)
    for li in range(cfg.num_hidden_layers):
        lp = root[f"layers_{li}"]
        pre = f"model.layers.{li}"
        out[f"{pre}.input_layernorm.weight"] = _c(np.asarray(lp["input_layernorm"]["weight"]))
        out[f"{pre}.post_attention_layernorm.weight"] = _c(np.asarray(lp["post_attention_layernorm"]["weight"]))
        for w in ("q_proj", "k_proj", "v_proj", "o_proj"):
            out[f"{pre}.self_attn.{w}.weight"] = _c(np.asarray(lp["self_attn"][w]["kernel"]).T)
        if "mlp" in lp:
            for w in ("gate_proj", "up_proj", "down_proj"):
                out[f"{pre}.mlp.{w}.weight"] = _c(np.asarray(lp["mlp"][w]["kernel"]).T)
        if "block_sparse_moe" in lp:
            moe = lp["block_sparse_moe"]
            out[f"{pre}.block_sparse_moe.gate.weight"] = _c(np.asarray(moe["gate"]).T)
            wi = np.asarray(moe["ExpertFFN_0"]["wi"])  # [E, M, 2F] (gate|up)
            wo = np.asarray(moe["ExpertFFN_0"]["wo"])  # [E, F, M]
            F = wo.shape[1]
            for e in range(wi.shape[0]):
                out[f"{pre}.block_sparse_moe.experts.{e}.w1.weight"] = _c(wi[e, :, :F].T)
                out[f"{pre}.block_sparse_moe.experts.{e}.w3.weight"] = _c(wi[e, :, F:].T)
                out[f"{pre}.block_sparse_moe.experts.{e}.w2.weight"] = _c(wo[e].T)
    save_file(out, os.path.join(path, "model.safetensors"))
    with open(os.path.join(path, "config.json"), "w") as f:
        json.dump(_hf_config_dict(cfg, model_type), f)


def _assert_trees_equal(a, b, path=""):
    if path == "":  # both layouts are legal (llama nests under "model", mixtral
        a = a.get("model", a)  # is flat); _root() normalizes them at runtime
        b = b.get("model", b)
    assert set(a) == set(b), f"{path}: {set(a)} != {set(b)}"
    for k in a:
        if isinstance(a[k], dict):
            _assert_trees_equal(a[k], b[k], f"{path}/{k}")
        else:
            np.testing.assert_allclose(np.asarray(a[k]), np.asarray(b[k]), rtol=0, atol=0,
                                       err_msg=f"{path}/{k}")


def test_llama_roundtrip(tmp_path):
    cfg = LlamaConfig.tiny(dtype=jnp.float32)
    _, params = llama_init(cfg)
    _export_hf(params, cfg, str(tmp_path), "llama")
    loaded, loaded_cfg = load_hf_checkpoint(str(tmp_path))
    assert loaded_cfg.num_hidden_layers == cfg.num_hidden_layers
    _assert_trees_equal(params, loaded)


def test_mixtral_roundtrip(tmp_path):
    cfg = MixtralConfig.tiny(dtype=jnp.float32)
    _, params = mixtral_init(cfg)
    _export_hf(params, cfg, str(tmp_path), "mixtral")
    loaded, loaded_cfg = load_hf_checkpoint(str(tmp_path))
    assert loaded_cfg.num_local_experts == cfg.num_local_experts
    _assert_trees_equal(params, loaded)


def test_tied_embeddings(tmp_path):
    """tie_word_embeddings checkpoints ship no lm_head.weight; the loader must
    derive the unembed kernel from the embedding (code-review r3 finding #1)."""
    cfg = LlamaConfig.tiny(dtype=jnp.float32)
    _, params = llama_init(cfg)
    _export_hf(params, cfg, str(tmp_path), "llama")
    # rewrite the shard without lm_head, as a tied checkpoint would be
    from safetensors.numpy import load_file, save_file
    shard = os.path.join(str(tmp_path), "model.safetensors")
    tensors = load_file(shard)
    del tensors["lm_head.weight"]
    save_file(tensors, shard)

    loaded, _ = load_hf_checkpoint(str(tmp_path))
    got = np.asarray(loaded["model"]["lm_head"]["kernel"])
    want = np.asarray(params["model"]["embed_tokens"]["embedding"]).T
    np.testing.assert_array_equal(got, want)


def test_build_hf_engine_logits(tmp_path):
    """End-to-end: the loader's tree must drive the v2 engine (this is the path
    that crashed with KeyError 'lm_head' before the fix)."""
    from deepspeed_tpu.inference.v2.engine_factory import build_engine, build_hf_engine

    groups.initialize_mesh(force=True)
    cfg = LlamaConfig.tiny(dtype=jnp.float32)
    _, params = llama_init(cfg)
    _export_hf(params, cfg, str(tmp_path), "llama")

    from deepspeed_tpu.inference.v2.config_v2 import RaggedInferenceEngineConfig
    from deepspeed_tpu.inference.v2.ragged.manager_configs import (AllocationMode,
                                                                   DSStateManagerConfig,
                                                                   MemoryConfig)
    mgr = DSStateManagerConfig(memory_config=MemoryConfig(mode=AllocationMode.ALLOCATE, size=64),
                               max_context=512)
    ecfg = RaggedInferenceEngineConfig(state_manager=mgr, kv_block_size=16)

    toks = np.random.default_rng(0).integers(0, cfg.vocab_size, 12)
    ref = np.asarray(build_engine(params, cfg, ecfg).put([0], [toks]))
    out = np.asarray(build_hf_engine(str(tmp_path), ecfg).put([0], [toks]))
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)
