"""Inference engine (v1-equivalent).

Reference: ``deepspeed/inference/engine.py:39`` (InferenceEngine: TP group creation,
injection policy, CUDA-graph capture, forward/generate). The TPU formulation:

- TP group = the ``model`` mesh axis; parameters are placed by ``param_specs``
  (AutoTP's role of picking row/col sharding) and XLA inserts the per-layer
  collectives the reference's ``inference_all_reduce`` calls perform.
- CUDA-graph capture/replay == jit compile/execute; ``enable_cuda_graph`` is
  honored trivially.
- Kernel injection == the Pallas op tier, used by the model implementations.
"""

from typing import Any, Callable, Optional

from deepspeed_tpu.inference.config import DeepSpeedInferenceConfig
from deepspeed_tpu.utils import groups
from deepspeed_tpu.utils.logging import logger


class InferenceEngine:

    def __init__(self, model, config: DeepSpeedInferenceConfig, params=None, param_specs=None):
        import jax

        self._config = config
        self.module = model

        tp = config.tensor_parallel.tp_size
        if not groups.mesh_is_initialized():
            groups.initialize_mesh(model_parallel_size=tp)
        self.mesh = groups.get_mesh()

        # resolve (apply_fn, params)
        if params is None and isinstance(model, dict):
            params = model.get("params")
            model = model.get("module")
            self.module = model
        if hasattr(model, "apply"):
            self._apply = lambda p, *a, **kw: model.apply({"params": p}, *a, **kw)
        elif callable(model):
            self._apply = model
        else:
            raise ValueError(f"Cannot build an inference engine from {type(model)}")

        self.params = None
        if params is not None:
            dtype = config.jnp_dtype
            from deepspeed_tpu.runtime.utils import cast_tree
            from deepspeed_tpu.runtime.zero.policy import ZeroShardingPolicy
            # zero stage 0 here: inference params sharded only by TP specs
            policy = ZeroShardingPolicy(stage=0, mesh=self.mesh)
            shardings = policy.param_shardings(params, param_specs)
            self.params = jax.device_put(cast_tree(params, dtype), shardings)

        self._jit_forward = jax.jit(self._apply)

    def forward(self, *inputs, **kwargs):
        """Reference engine.py:584 — jit-compiled forward (graph replay analog)."""
        if self.params is not None:
            return self._jit_forward(self.params, *inputs, **kwargs)
        return self._jit_forward(*inputs, **kwargs)

    __call__ = forward

    def generate(self, *inputs, input_ids=None, max_new_tokens: Optional[int] = None,
                 do_sample: Optional[bool] = None, temperature: Optional[float] = None,
                 eos_token_id: Optional[int] = None, rng=None, **kwargs):
        """Reference engine.py:613 (``_generate`` → module.generate or the
        sampling loop). A module-provided ``generate`` wins; otherwise this is
        the v1 autoregressive loop for causal-LM modules whose forward returns
        logits [B, S, V]:

        One jitted ``lax.fori_loop`` over a padded [B, S0+max_new_tokens]
        buffer — static shapes, a single compile per (S0, max_new_tokens)
        bucket. No KV cache: each step re-runs the prefix (the v2 ragged
        engine with the paged Pallas kernel is the production decode path;
        this matches reference v1's no-cache fallback semantics).
        """
        if hasattr(self.module, "generate"):
            # verbatim pass-through of positionals; only knobs the caller
            # EXPLICITLY set are forwarded (None = unset, so the module's own
            # defaults win), filtered by the module's signature
            import inspect
            mg = self.module.generate
            named = {k: v for k, v in dict(max_new_tokens=max_new_tokens,
                                           do_sample=do_sample, temperature=temperature,
                                           eos_token_id=eos_token_id, rng=rng).items()
                     if v is not None}
            try:
                sig = inspect.signature(mg)
                if not any(p.kind == p.VAR_KEYWORD for p in sig.parameters.values()):
                    named = {k: v for k, v in named.items() if k in sig.parameters}
            except (TypeError, ValueError):
                pass
            pos = inputs if input_ids is None else (input_ids, ) + inputs
            return mg(*pos, **named, **kwargs)
        if input_ids is None:
            if len(inputs) != 1:
                raise ValueError("the built-in sampling loop takes exactly one "
                                 "input_ids array")
            input_ids = inputs[0]
        max_new_tokens = 32 if max_new_tokens is None else int(max_new_tokens)
        do_sample = bool(do_sample)
        temperature = 1.0 if temperature is None else float(temperature)

        import jax
        import jax.numpy as jnp

        input_ids = jnp.asarray(input_ids)
        if input_ids.ndim == 1:
            input_ids = input_ids[None]
        B, S0 = input_ids.shape
        total = S0 + int(max_new_tokens)
        rng = rng if rng is not None else jax.random.PRNGKey(0)
        eos = -1 if eos_token_id is None else int(eos_token_id)

        # the loop needs a causal-LM-shaped module: ids [B, S] -> logits [B, S, V]
        try:
            probe = jax.eval_shape(
                (lambda p, i: self._apply(p, i)) if self.params is not None else
                (lambda p, i: self._apply(i)), self.params, input_ids)
            probe = probe[0] if isinstance(probe, tuple) else probe
            if len(probe.shape) != 3 or probe.shape[:2] != (B, S0):
                raise TypeError(f"forward returns {probe.shape}, not [B, S, vocab]")
        except Exception as e:
            raise NotImplementedError(
                f"generate() needs a causal-LM module (ids [B,S] -> logits [B,S,V]); "
                f"this module does not qualify ({e}); provide module.generate or use "
                f"the v2 ragged engine") from e

        key = ("gen", B, S0, total, bool(do_sample), float(temperature), eos)
        if not hasattr(self, "_gen_cache"):
            self._gen_cache = {}
        if key not in self._gen_cache:
            apply, params_given = self._apply, self.params is not None
            temp = float(temperature)

            def logits_at(params, ids, pos):
                out = apply(params, ids) if params_given else apply(ids)
                logits = out[0] if isinstance(out, tuple) else out
                return jax.lax.dynamic_slice_in_dim(logits, pos, 1, axis=1)[:, 0]

            def run(params, ids0, r):
                def step(i, carry):
                    ids, done, r = carry
                    logits = logits_at(params, ids, i - 1)
                    r, sub = jax.random.split(r)
                    if do_sample:
                        nxt = jax.random.categorical(sub, logits / max(temp, 1e-6), axis=-1)
                    else:
                        nxt = jnp.argmax(logits, axis=-1)
                    nxt = jnp.where(done, 0, nxt).astype(ids.dtype)
                    ids = jax.lax.dynamic_update_slice_in_dim(ids, nxt[:, None], i, axis=1)
                    done = done | (nxt == eos)
                    return ids, done, r

                pad = jnp.zeros((B, total - S0), ids0.dtype)
                ids = jnp.concatenate([ids0, pad], axis=1)
                done = jnp.zeros((B, ), bool)
                ids, done, _ = jax.lax.fori_loop(S0, total, step, (ids, done, r))
                return ids

            self._gen_cache[key] = jax.jit(run)
        return self._gen_cache[key](self.params, input_ids, rng)

    def profile_model_time(self, use_cuda_events=True):
        logger.warning("model profiling on TPU: use jax.profiler traces")
