"""Replica abstractions: local + HTTP upstream behind one dispatch interface."""

import numpy as np
import pytest

from deepspeed_tpu.fleet import (LocalReplica, ReplicaState, ReplicaUnavailable)
from deepspeed_tpu.serving import ServingConfig, ServingScheduler, ServingServer


def _prompt(n=9, vocab=64):
    return (np.arange(n) % vocab).tolist()


class TestLocalReplica:

    def test_dispatch_streams_and_resolves(self, make_engine):
        replica = LocalReplica(make_engine(), role="mixed")
        try:
            leg = replica.dispatch({"prompt": _prompt(), "max_new_tokens": 4})
            tokens = list(leg)
            doc = leg.result(timeout=60)
            assert doc["state"] == "DONE" and doc["tokens"] == tokens
            assert len(tokens) == 4 or doc["finish_reason"] == "eos"
        finally:
            replica.drain(timeout=0.0)

    def test_probe_shape_and_load(self, make_engine):
        replica = LocalReplica(make_engine(num_blocks=32), role="prefill")
        try:
            doc = replica.probe()
            assert doc["healthy"] and not doc["draining"]
            assert doc["queue_depth"] == 0 and doc["active"] == 0
            assert doc["kv_free_frac"] == 1.0
            assert replica.load == 0
        finally:
            replica.drain(timeout=0.0)

    def test_handoff_payload_rides_result(self, make_engine):
        replica = LocalReplica(make_engine(), role="prefill")
        try:
            leg = replica.dispatch({"prompt": _prompt(), "max_new_tokens": 1,
                                    "handoff": True})
            doc = leg.result(timeout=60)
            assert doc["finish_reason"] == "length"
            assert isinstance(doc["handoff"], bytes)  # raw bytes in-process
        finally:
            replica.drain(timeout=0.0)

    def test_drained_replica_refuses_dispatch(self, make_engine):
        replica = LocalReplica(make_engine())
        replica.drain(timeout=0.0)
        assert replica.state is ReplicaState.DOWN and not replica.available
        with pytest.raises(ReplicaUnavailable):
            replica.dispatch({"prompt": _prompt()})

    def test_backpressure_maps_to_unavailable(self, make_engine, monkeypatch):
        """QueueFullError -> 429, SchedulerStopped -> 503: the router's two
        failover signals, distinguished so the client's terminal status is
        right when every replica refuses."""
        from deepspeed_tpu.serving import QueueFullError, SchedulerStopped
        replica = LocalReplica(make_engine())
        try:
            monkeypatch.setattr(replica.scheduler, "submit",
                                lambda *a, **k: (_ for _ in ()).throw(
                                    QueueFullError("queue full")))
            with pytest.raises(ReplicaUnavailable) as err:
                replica.dispatch({"prompt": _prompt()})
            assert err.value.status == 429
            monkeypatch.setattr(replica.scheduler, "submit",
                                lambda *a, **k: (_ for _ in ()).throw(
                                    SchedulerStopped("stopping")))
            with pytest.raises(ReplicaUnavailable) as err:
                replica.dispatch({"prompt": _prompt()})
            assert err.value.status == 503
        finally:
            replica.drain(timeout=0.0)


class TestHttpReplica:

    @pytest.fixture
    def upstream(self, make_engine):
        srv = ServingServer(ServingScheduler(make_engine(), ServingConfig())).start()
        yield srv
        srv.stop(drain=False)

    def test_probe_reads_health_and_stats(self, upstream, make_fleet):
        manager = make_fleet(roles=())
        replica = manager.add_upstream(upstream.url, role="decode")
        doc = replica.probe()
        assert doc["healthy"] and not doc["draining"]
        assert doc["kv_free_frac"] == 1.0  # capacity_blocks rides /v1/stats now

    def test_dispatch_streams_over_the_wire(self, upstream, make_fleet):
        manager = make_fleet(roles=())
        replica = manager.add_upstream(upstream.url)
        leg = replica.dispatch({"prompt": _prompt(), "max_new_tokens": 3})
        tokens = list(leg)
        doc = leg.result(timeout=60)
        assert doc["state"] == "DONE" and doc["tokens"] == tokens

    def test_unreachable_upstream_is_unavailable(self, make_fleet):
        manager = make_fleet(roles=())
        replica = manager.add_upstream("http://127.0.0.1:9")  # discard port
        assert replica.probe()["healthy"] is False
        with pytest.raises(ReplicaUnavailable):
            replica.dispatch({"prompt": _prompt()})

    def test_drain_leaves_rotation_without_stopping_upstream(self, upstream, make_fleet):
        manager = make_fleet(roles=())
        replica = manager.add_upstream(upstream.url)
        manager.drain(replica.id, remove=False)
        # DOWN (not a forever-DRAINING zombie counted as live capacity) ...
        assert replica.state is ReplicaState.DOWN and not replica.available
        # ... but the external process is not ours to stop: it still answers
        assert upstream.scheduler.queue_depth == 0
