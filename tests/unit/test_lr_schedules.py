"""LR schedule tests (reference: tests/unit/runtime/test_lr_schedulers.py)."""

import math

import pytest

from deepspeed_tpu.runtime.lr_schedules import (LRRangeTest, OneCycle, WarmupCosineLR, WarmupDecayLR, WarmupLR,
                                                get_lr_schedule_class)


def test_warmup_lr_reaches_max():
    s = WarmupLR(warmup_min_lr=0.0, warmup_max_lr=0.1, warmup_num_steps=10, warmup_type="linear")
    lrs = [s.step()[0] for _ in range(20)]
    assert lrs[-1] == pytest.approx(0.1)
    assert lrs[0] < lrs[5] < lrs[9]


def test_warmup_log_monotone():
    s = WarmupLR(warmup_min_lr=0.0, warmup_max_lr=1.0, warmup_num_steps=100, warmup_type="log")
    lrs = [s.step()[0] for _ in range(100)]
    assert all(b >= a for a, b in zip(lrs, lrs[1:]))
    assert lrs[-1] == pytest.approx(1.0)


def test_warmup_decay_hits_zero():
    s = WarmupDecayLR(total_num_steps=100, warmup_max_lr=0.1, warmup_num_steps=10)
    for _ in range(101):
        lr = s.step()[0]
    assert lr == pytest.approx(0.0, abs=1e-6)


def test_warmup_decay_validates():
    with pytest.raises(ValueError):
        WarmupDecayLR(total_num_steps=5, warmup_num_steps=10)


def test_one_cycle_shape():
    s = OneCycle(cycle_min_lr=0.01, cycle_max_lr=0.1, cycle_first_step_size=10)
    lrs = [s.step()[0] for _ in range(30)]
    assert max(lrs) == pytest.approx(0.1, rel=0.2)
    assert lrs[0] == pytest.approx(0.01, rel=0.1)
    # decays after the cycle
    assert lrs[-1] <= 0.01 + 1e-9


def test_lr_range_test_staircase():
    s = LRRangeTest(lr_range_test_min_lr=0.01, lr_range_test_step_size=5, lr_range_test_step_rate=1.0,
                    lr_range_test_staircase=True)
    lrs = [s.step()[0] for _ in range(10)]
    assert lrs[0] == lrs[4] == pytest.approx(0.01)
    assert lrs[5] == pytest.approx(0.02)


def test_warmup_cosine():
    s = WarmupCosineLR(total_num_steps=100, warmup_num_steps=10, cos_min_ratio=0.0)
    lrs = [s.step()[0] for _ in range(101)]
    assert lrs[10] == pytest.approx(1.0, rel=0.01)
    assert lrs[-1] == pytest.approx(0.0, abs=0.01)


def test_registry():
    assert get_lr_schedule_class("WarmupLR") is WarmupLR
    with pytest.raises(ValueError):
        get_lr_schedule_class("NoSuch")


def test_state_dict_roundtrip():
    s = WarmupLR(warmup_max_lr=0.1, warmup_num_steps=10)
    for _ in range(5):
        s.step()
    sd = s.state_dict()
    s2 = WarmupLR(warmup_max_lr=0.1, warmup_num_steps=10)
    s2.load_state_dict(sd)
    assert s2.last_batch_iteration == s.last_batch_iteration


def test_cli_config_helpers():
    """parse_arguments/get_config_from_args/get_lr_from_config (reference
    lr_schedules.py:124,208)."""
    import argparse
    from deepspeed_tpu.runtime.lr_schedules import (add_tuning_arguments, get_config_from_args,
                                                    get_lr_from_config)

    parser = add_tuning_arguments(argparse.ArgumentParser())
    args, rest = parser.parse_known_args(
        ["--lr_schedule", "WarmupLR", "--warmup_max_lr", "0.01", "--unrelated", "1"])
    assert rest == ["--unrelated", "1"]
    cfg, err = get_config_from_args(args)
    assert err is None and cfg["type"] == "WarmupLR"
    assert cfg["params"]["warmup_max_lr"] == 0.01
    lr, why = get_lr_from_config(cfg)
    assert lr == 0.01 and "warmup" in why

    bad, err = get_config_from_args(argparse.Namespace(lr_schedule="NopeLR"))
    assert bad is None and "not supported" in err
