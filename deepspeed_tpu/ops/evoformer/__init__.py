from deepspeed_tpu.ops.evoformer.attention import DS4Sci_EvoformerAttention, evoformer_attention

__all__ = ["DS4Sci_EvoformerAttention", "evoformer_attention"]
