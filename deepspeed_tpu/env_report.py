"""``ds_report`` analog: environment / compatibility report.

Reference: ``deepspeed/env_report.py:182`` — prints the op-compat matrix,
torch/cuda versions and install paths. The TPU report covers what matters
here: JAX backend + devices, default mesh axes, library versions, and which
native/pallas subsystems are usable on this backend.
"""

import importlib
import sys


def _version(mod):
    try:
        return importlib.import_module(mod).__version__
    except Exception:
        return "not installed"


GREEN_OK = "\033[92m[OKAY]\033[0m"
RED_NO = "\033[93m[NO]\033[0m"


def metrics_report(url):
    """``dstpu_report --metrics-url <url>``: scrape a running engine's
    telemetry endpoint and pretty-print it (plus the /healthz verdict)."""
    import json
    import urllib.request

    from deepspeed_tpu.telemetry import scrape_metrics

    base = url if url.startswith(("http://", "https://")) else "http://" + url
    base = base.rstrip("/")
    for suffix in ("/metrics", "/healthz", "/trace"):
        if base.endswith(suffix):
            base = base[:-len(suffix)]
            break
    try:
        with urllib.request.urlopen(base + "/healthz", timeout=5) as resp:
            health = json.loads(resp.read().decode()).get("status", "?")
            health_line = f"{GREEN_OK} ({health}, HTTP {resp.status})"
    except Exception as e:
        health_line = f"{RED_NO} ({e})"
    print("-" * 60)
    print(f"telemetry endpoint ..... {base}")
    print(f"healthz ................ {health_line}")
    print("-" * 60)
    try:
        families = scrape_metrics(base)
    except Exception as e:
        print(f"scrape failed: {e}")
        return 1
    for name in sorted(families):
        fam = families[name]
        header = f"{name} [{fam['type']}]"
        if fam["help"]:
            header += f" — {fam['help']}"
        print(header)
        for sample_name, labels, value in fam["samples"]:
            if sample_name.endswith("_bucket"):
                continue  # count/sum summarize; buckets are for the scraper
            print(f"  {sample_name + _fmt_labels(labels):<44} {value:g}")
        print()
    return 0


def _fmt_labels(labels):
    return "{" + ",".join(f"{k}={v}" for k, v in sorted(labels.items())) + "}" if labels else ""


def _load_trace_events(path):
    """Normalize either export format into one event-dict list: a Chrome trace
    (``traceEvents`` with ts/dur us) or a flight-recorder dump (``spans`` with
    ts_us/dur_us)."""
    import json

    with open(path) as f:
        doc = json.load(f)
    if "traceEvents" in doc:
        return [{"name": e["name"], "cat": e.get("cat", ""), "ts": e["ts"],
                 "dur": e.get("dur", 0), "args": e.get("args", {})}
                for e in doc["traceEvents"] if e.get("ph") == "X"]
    if "spans" in doc:  # flight-recorder dump
        return [{"name": s["name"], "cat": s.get("cat", ""), "ts": s["ts_us"],
                 "dur": s.get("dur_us", 0),
                 "args": {**s.get("args", {}),
                          **({"trace_id": s["trace_id"], "span_id": s.get("span_id"),
                              "parent_id": s.get("parent_id")}
                             if s.get("trace_id") is not None else {})}}
                for s in doc["spans"]]
    raise ValueError(f"{path}: neither a Chrome trace (traceEvents) nor a "
                     f"flight-recorder dump (spans)")


def trace_report(path):
    """``dstpu_report --trace <file>``: per-request timelines (queued/prefill/
    decode durations, recompiles encountered) from an exported Chrome trace or
    a flight-recorder dump."""
    try:
        events = _load_trace_events(path)
    except (OSError, ValueError, KeyError) as e:
        print(f"trace report failed: {e}")
        return 1

    ms = 1e-3  # event times are microseconds
    compiles = [e for e in events if e["name"] == "xla_compile"]
    by_trace = {}
    for e in events:
        trace_id = e["args"].get("trace_id")
        if trace_id is not None:
            by_trace.setdefault(trace_id, []).append(e)

    print("-" * 78)
    print(f"trace ................... {path}")
    print(f"events .................. {len(events)} "
          f"({len(by_trace)} request traces, {len(compiles)} XLA compiles)")
    print("-" * 78)
    if not by_trace:
        print("no request traces found (serve with telemetry enabled; the "
              "X-DSTPU-Trace-Id response header names each request's trace)")
        return 0

    def total(evs, name):
        return sum(e["dur"] for e in evs if e["name"] == name)

    # roots sorted by arrival so the report reads as an admission log
    roots = sorted((evs for evs in by_trace.values()),
                   key=lambda evs: min(e["ts"] for e in evs))
    for evs in roots:
        root = next((e for e in evs if e["name"] == "request"), None)
        head = root or min(evs, key=lambda e: e["ts"])
        args = head["args"]
        t0, t1 = head["ts"], head["ts"] + head["dur"]
        n_recompiles = sum(1 for c in compiles if t0 <= c["ts"] + c["dur"] and c["ts"] <= t1)
        decode_evs = [e for e in evs if e["name"] in ("decode", "decode_loop")]
        decode_toks = sum(e["args"].get("tokens", 0) for e in decode_evs)
        print(f"request uid={args.get('uid')} trace={args.get('trace_id')} "
              f"[{args.get('state', '?')}"
              f"{', ' + str(args.get('finish_reason')) if args.get('finish_reason') else ''}]")
        print(f"  prompt/generated ..... {args.get('prompt_tokens', '?')}t / "
              f"{args.get('generated', '?')}t")
        print(f"  total ................ {head['dur'] * ms:10.3f} ms")
        print(f"  queued ............... {total(evs, 'queued') * ms:10.3f} ms")
        n_prefill = sum(1 for e in evs if e["name"] == "prefill")
        print(f"  prefill .............. {total(evs, 'prefill') * ms:10.3f} ms "
              f"({n_prefill} chunks)")
        decode_total = total(evs, "decode") + total(evs, "decode_loop")
        print(f"  decode ............... {decode_total * ms:10.3f} ms "
              f"({len(decode_evs)} iterations, {decode_toks} tokens)")
        print(f"  recompiles overlapped  {n_recompiles}")
        print()
    return 0


def checkpoint_report(save_dir, keep_last_k=None):
    """``dstpu_report --checkpoint <dir>``: verify every tag's manifest CRCs
    and list good/torn/corrupt/reference status, plus which tags keep-last-K
    retention would keep (K from ``--keep-last-k``, else the newest manifest's
    recorded ``keep_last_k``). Returns 0 when every tag is good."""
    import os

    from deepspeed_tpu.runtime.checkpoint_engine.engine import (
        LATEST_FILE, PREEMPT_MARKER, list_tags, retention_plan,
        verify_checkpoint)

    save_dir = os.path.abspath(save_dir)
    tags = list_tags(save_dir)
    pointed = None
    latest_file = os.path.join(save_dir, LATEST_FILE)
    if os.path.isfile(latest_file):
        with open(latest_file) as f:
            pointed = f.read().strip()

    if keep_last_k is None:
        for entry in tags:  # newest first; the freshest save's config wins
            if entry["manifest"] is not None:
                keep_last_k = entry["manifest"].get("keep_last_k", 0)
                break
    keep, drop = retention_plan(save_dir, keep_last_k or 0)
    survivors = {e["tag"] for e in keep}

    print("-" * 78)
    print(f"checkpoint dir ......... {save_dir}")
    print(f"tags ................... {len(tags)} "
          f"(latest → {pointed or 'none'}, keep_last_k={keep_last_k or 0})")
    if os.path.isfile(os.path.join(save_dir, PREEMPT_MARKER)):
        import json
        with open(os.path.join(save_dir, PREEMPT_MARKER)) as f:
            marker = json.load(f)
        print(f"preemption marker ...... tag {marker.get('tag')} at step "
              f"{marker.get('global_steps')} "
              f"({marker.get('used_s')}s of {marker.get('grace_s')}s grace)")
    print("-" * 78)
    if not tags:
        print("no checkpoint tags found")
        return 1
    all_good = True
    for entry in tags:
        status, detail = verify_checkpoint(entry["path"])
        all_good &= status == "good"
        manifest = entry["manifest"] or {}
        step = manifest.get("global_steps", "?")
        n_files = len(manifest.get("files", {}))
        n_arrays = len(manifest.get("arrays") or {})
        flags = []
        if entry["tag"] == pointed:
            flags.append("latest")
        flags.append("kept" if entry["tag"] in survivors else "prunable")
        verdict = {"good": GREEN_OK, }.get(status, RED_NO)
        print(f"{entry['tag']:<28} {verdict} {status:<9} step={step:<8} "
              f"files={n_files:<4} arrays={n_arrays:<4} [{', '.join(flags)}]")
        if status != "good":
            print(f"{'':<28}   ↳ {detail}")
    print("-" * 78)
    print(f"verdict ................ "
          f"{GREEN_OK + ' all tags verified' if all_good else RED_NO + ' bad tags present (load falls back to the newest good one)'}")
    return 0 if all_good else 1


def gang_report(gang_dir):
    """``dstpu_report --gang <dir>``: render the elastic agent's gang state —
    per-rank liveness (heartbeat age/step/phase, pid, exit code), crash/hang
    history, current vs valid world sizes and the last shrink event. Returns
    0 when the gang is running/done with no recorded failures, 1 otherwise."""
    import os
    import time

    from deepspeed_tpu.elasticity.gang import read_gang_state, read_heartbeats

    gang_dir = os.path.abspath(gang_dir)
    state = read_gang_state(gang_dir)
    beats = read_heartbeats(gang_dir)
    print("-" * 78)
    print(f"gang dir ............... {gang_dir}")
    if state is None and not beats:
        print("no gang state or heartbeats found (is this a DSTPU_GANG_DIR?)")
        return 2
    state = state or {}
    age = time.time() - state["updated_unix"] if "updated_unix" in state else None
    print(f"phase .................. {state.get('phase', '?')}"
          f"{f'  (state written {age:.1f}s ago)' if age is not None else ''}")
    print(f"world .................. {state.get('world', '?')} of initial "
          f"{state.get('initial_world', '?')} "
          f"(valid: {state.get('valid_worlds', '?')})")
    print(f"restarts ............... {state.get('restart_count', '?')}"
          f"/{state.get('max_restarts', '?')}  crashes in window: "
          f"{state.get('crashes_in_window', '?')}/{state.get('max_crashes', '?')} "
          f"(window {state.get('crash_window_s', '?')}s)")
    hang = state.get("hang_timeout_s")
    print(f"hang watchdog .......... "
          f"{f'{hang}s heartbeat staleness' if hang else 'off'}")
    shrink = state.get("last_shrink")
    if shrink:
        print(f"last shrink ............ world {shrink.get('from')} → "
              f"{shrink.get('to')} after {shrink.get('crashes')} crash(es) "
              f"(life {shrink.get('life')})")
    print("-" * 78)
    ranks = state.get("ranks") or {str(r): {"heartbeat": hb}
                                   for r, hb in beats.items()}
    failures = 0
    for rank in sorted(ranks, key=int):
        doc = ranks[rank] or {}
        hb = beats.get(int(rank)) or doc.get("heartbeat")
        alive = doc.get("alive")
        rc = doc.get("exit_code")
        if alive:
            live = GREEN_OK + " alive"
        elif alive is None:
            live = "?  unknown"
        elif rc == 143:
            # the agent's preemption contract: 143 = TrainingPreempted with
            # the final checkpoint committed — a clean drain, not a failure
            live = GREEN_OK + " exit=143 (preempted)"
        else:
            live = (GREEN_OK if rc == 0 else RED_NO) + f" exit={rc}"
        if rc not in (None, 0, 143):
            failures += 1
        if hb:
            beat = (f"beat {hb.get('age_s', 0):.1f}s ago  "
                    f"step={hb.get('step')}  phase={hb.get('phase')}")
        else:
            beat = "no heartbeat this life"
        print(f"rank {rank:<4} {live:<18} {beat}")
    events = state.get("events") or []
    if events:
        print("-" * 78)
        for ev in events[-10:]:
            print(f"life {ev.get('life'):<3} world={ev.get('world'):<3} "
                  f"{ev.get('kind'):<8} {ev.get('detail') or ''}")
    print("-" * 78)
    bad = failures or any(ev.get("kind") in ("crash", "hang") for ev in events) \
        or state.get("phase") == "failed"
    print(f"verdict ................ "
          f"{RED_NO + ' failures recorded' if bad else GREEN_OK + ' gang healthy'}")
    return 1 if bad else 0


_SPARK_CHARS = "▁▂▃▄▅▆▇█"


def _sparkline(values, width=40):
    """Render a value list as a fixed-height unicode sparkline (newest-last,
    truncated to ``width`` points, scaled to the visible min..max)."""
    vals = [v for v in values if v is not None]
    if not vals:
        return ""
    if len(vals) > width:
        vals = vals[-width:]
    lo, hi = min(vals), max(vals)
    if hi <= lo:
        return _SPARK_CHARS[0] * len(vals)
    top = len(_SPARK_CHARS) - 1
    return "".join(_SPARK_CHARS[min(top, int((v - lo) / (hi - lo) * len(_SPARK_CHARS)))]
                   for v in vals)


def _load_timeseries_doc(src):
    """A ``--timeseries`` operand is either a saved JSON file or a live
    router/engine address (``/v1/fleet/timeseries`` is fetched)."""
    import json
    import os
    import urllib.request

    if os.path.isfile(src):
        with open(src) as f:
            return json.load(f)
    base = src if src.startswith(("http://", "https://")) else "http://" + src
    base = base.rstrip("/")
    if not base.endswith("/v1/fleet/timeseries"):
        base += "/v1/fleet/timeseries"
    with urllib.request.urlopen(base, timeout=5) as resp:
        return json.loads(resp.read().decode())


def _render_timeseries_snapshot(label, snap):
    series = (snap or {}).get("series") or {}
    interval = snap.get("interval_s", 0) or 0
    retention = snap.get("retention_points", 0) or 0
    print(f"{label}  interval={interval:g}s  retention={retention} pts "
          f"(~{interval * retention:g}s)  window={snap.get('window_s', '?')}s  "
          f"ticks={snap.get('ticks', '?')}")
    if not series:
        print("  (no series sampled yet)")
        return

    def fmt_ms(v):
        return f"{v * 1e3:.1f}ms" if v is not None else "—"

    def fmt_rate(v):
        return f"{v:.2f}/s" if v is not None else "—"

    for name in sorted(series):
        fam = series[name]
        pts = fam.get("points") or []
        if fam.get("kind") == "histogram":
            # cumulative counts -> per-interval deltas for the sparkline
            counts = [p[1] for p in pts]
            deltas = [max(0, b - a) for a, b in zip(counts, counts[1:])]
            spark = _sparkline(deltas or counts)
            tail = (f"p50={fmt_ms(fam.get('p50'))} p95={fmt_ms(fam.get('p95'))} "
                    f"p99={fmt_ms(fam.get('p99'))} rate={fmt_rate(fam.get('rate'))}")
        elif fam.get("kind") == "counter":
            values = [p[1] for p in pts]
            deltas = [max(0.0, b - a) for a, b in zip(values, values[1:])]
            spark = _sparkline(deltas or values)
            last = values[-1] if values else None
            tail = (f"total={last:g} " if last is not None else "") \
                + f"rate={fmt_rate(fam.get('rate'))}"
        else:  # gauge
            values = [p[1] for p in pts]
            spark = _sparkline(values)
            tail = f"last={values[-1]:g}" if values else ""
        print(f"  {name:<34} {spark:<40} {tail}")


def timeseries_report(src):
    """``dstpu_report --timeseries <file | host:port>``: sparkline tables from
    a ``/v1/fleet/timeseries`` export (router + per-replica sections), a bare
    store snapshot, or a ``/v1/stats`` doc carrying a ``timeseries`` block."""
    try:
        doc = _load_timeseries_doc(src)
    except Exception as e:
        print(f"cannot load time series from {src}: {e}")
        return 2
    if isinstance(doc, dict) and "series" in doc:
        sections = [("snapshot", doc)]
    elif isinstance(doc, dict) and ("router" in doc or "replicas" in doc):
        sections = []
        if doc.get("router"):
            sections.append(("router", doc["router"]))
        for rid, snap in sorted((doc.get("replicas") or {}).items()):
            if snap:
                sections.append((f"replica {rid}", snap))
    elif isinstance(doc, dict) and isinstance(doc.get("timeseries"), dict):
        sections = [("engine", doc["timeseries"])]
    else:
        print(f"{src}: not a time-series doc (expected 'series', "
              f"'router'/'replicas', or a stats doc with 'timeseries')")
        return 2
    print("-" * 78)
    print(f"time series ............ {src}")
    print("-" * 78)
    if not sections:
        print("no time-series data (enable telemetry.timeseries on the "
              "replicas and the router)")
        return 0
    for label, snap in sections:
        _render_timeseries_snapshot(label, snap)
        print()
    return 0


def _load_kv_doc(src):
    """A ``--kv`` operand is either a saved JSON stats doc or a live
    address: ``/v1/fleet/stats`` is tried first (router form), then
    ``/v1/stats`` (single-replica form)."""
    import json
    import os
    import urllib.request

    if os.path.isfile(src):
        with open(src) as f:
            return json.load(f)
    base = src if src.startswith(("http://", "https://")) else "http://" + src
    base = base.rstrip("/")
    if base.endswith("/v1/fleet/stats") or base.endswith("/v1/stats"):
        urls = [base]
    else:
        urls = [base + "/v1/fleet/stats", base + "/v1/stats"]
    last = None
    for url in urls:
        try:
            with urllib.request.urlopen(url, timeout=5) as resp:
                return json.loads(resp.read().decode())
        except Exception as e:  # try the next form; re-raise the last
            last = e
    raise last


def _render_kv_tiers(tiers):
    dev_used = tiers.get("device_blocks_used", 0)
    dev_total = tiers.get("device_blocks_total", 0)
    budget = tiers.get("host_bytes_budget")
    print("tier occupancy:")
    print(f"  device ............... {dev_used}/{dev_total} blocks")
    print(f"  host ................. {tiers.get('host_entries', 0)} entries, "
          f"{tiers.get('host_blocks', 0)} blocks, "
          f"{tiers.get('host_bytes', 0)} bytes"
          + (f" (budget {budget})" if budget else " (budget unbounded)"))
    print(f"  disk ................. {tiers.get('disk_entries', 0)} entries, "
          f"{tiers.get('disk_blocks', 0)} blocks, "
          f"{tiers.get('disk_bytes', 0)} bytes")
    print("ladder counters:")
    print(f"  pressure demotions ... {tiers.get('pressure_demotions', 0)} "
          f"(demote-before-shed passes, device blocks)")
    print(f"  host->disk commits ... {tiers.get('demotions', 0)}")
    print(f"  demote races ......... {tiers.get('demote_races', 0)} "
          f"(reader won mid-spill; reclaimed to host)")
    print(f"  writeback ............ {tiers.get('writeback_pending', 0)} "
          f"pending, {tiers.get('writeback_joins', 0)} joined reads")
    print(f"  reads ................ host {tiers.get('reads_host', 0)} / "
          f"disk {tiers.get('reads_disk', 0)}")
    if "trie_demotions" in tiers:
        print(f"  prefix trie .......... {tiers.get('trie_offloaded_nodes', 0)} "
              f"offloaded nodes, {tiers.get('trie_demotions', 0)} demotions, "
              f"{tiers.get('trie_promotions', 0)} promotions")


def _render_park(park):
    print(f"park store ............. {park.get('sessions', 0)} sessions, "
          f"{park.get('bytes', 0)} bytes (caps: "
          f"{park.get('max_sessions', '?')} sessions / "
          f"{park.get('max_bytes', '?')} bytes, ttl {park.get('ttl_s', '?')}s)")
    print(f"  parks ................ {park.get('parks', 0)}")
    print(f"  rehydrate hits ....... {park.get('rehydrate_hits', 0)}")
    print(f"  rehydrate misses ..... {park.get('rehydrate_misses', 0)} "
          f"(expired or diverged)")
    print(f"  corrupt rejects ...... {park.get('corrupt_rejects', 0)}")
    print(f"  evictions ............ {park.get('evictions', 0)}")
    inventory = park.get("inventory") or []
    if inventory:
        print("parked sessions:")
        print(f"  {'session':<24} {'tokens':>7} {'bytes':>10} "
              f"{'tier':<7} {'parked_by':<12} {'age_s':>8}")
        for row in inventory:
            print(f"  {str(row.get('session', '?')):<24} "
                  f"{row.get('tokens', 0):>7} {row.get('bytes', 0):>10} "
                  f"{str(row.get('tier_source') or '-'):<7} "
                  f"{str(row.get('parked_by') or '-'):<12} "
                  f"{row.get('age_s', 0):>8}")


def kv_report(src):
    """``dstpu_report --kv <stats.json | host:port>``: render the tiered KV
    memory surface — per-tier occupancy and the demotion/promotion counters
    from a serving ``/v1/stats`` doc (its ``kv_tiers`` block), and the
    router's parked-session inventory from a ``/v1/fleet/stats`` doc."""
    try:
        doc = _load_kv_doc(src)
    except Exception as e:
        print(f"cannot load KV stats from {src}: {e}")
        return 2
    if not isinstance(doc, dict):
        print(f"{src}: not a stats doc")
        return 2
    print("-" * 78)
    print(f"tiered KV memory ....... {src}")
    print("-" * 78)
    rendered = False
    if "kv_tiers" in doc:
        rendered = True
        tiers = doc.get("kv_tiers")
        if isinstance(tiers, dict):
            _render_kv_tiers(tiers)
        else:
            print("kv tiers ............... disabled "
                  "(KVTierConfig.enabled=false)")
    router = doc.get("router")
    if isinstance(router, dict):
        rendered = True
        park = router.get("park")
        if isinstance(park, dict):
            _render_park(park)
        else:
            print("park store ............. disabled "
                  "(ParkConfig.enabled=false)")
    if not rendered:
        print(f"{src}: no kv_tiers or router.park block (is this a /v1/stats "
              f"or /v1/fleet/stats doc?)")
        return 2
    return 0


def overload_report(path):
    """``dstpu_report --overload <loadgen-json>``: render the goodput-vs-
    offered-load table from ``bin/dstpu_loadgen --overload --json`` and flag
    the knee point — the first ramp step whose goodput drops below 90% of
    the measured single-replica capacity. Returns 0 when the doc parses and
    has at least one step (a knee is expected on a real overload ramp, not a
    failure)."""
    import json
    import os

    path = os.path.abspath(path)
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        print(f"cannot read overload report {path}: {e}")
        return 2
    steps = doc.get("steps") or []
    capacity = doc.get("capacity_req_s")
    if not steps or not capacity:
        print(f"{path} has no ramp steps / capacity "
              f"(is this a loadgen --overload --json file?)")
        return 2
    knee_floor = 0.9 * capacity
    # only saturated steps can knee: below capacity, goodput is bounded by
    # the OFFERED rate, not by overload collapse — a 0.5x step can never
    # reach 90% of capacity and must not be flagged
    knee = next((s for s in steps
                 if s.get("offered_req_s", 0.0) >= knee_floor
                 and s.get("goodput_req_s", 0.0) < knee_floor), None)
    print("-" * 78)
    print(f"overload ramp .......... {path}")
    print(f"capacity ............... {capacity:.2f} req/s "
          f"(deadline {doc.get('deadline_s', 0):.2f}s, interactive_frac "
          f"{doc.get('interactive_frac', '?')}, "
          f"{doc.get('requests_per_step', '?')} requests/step)")
    print(f"knee floor ............. {knee_floor:.2f} req/s (90% of capacity)")
    has_slo = any(isinstance(s.get("slo"), dict) for s in steps)
    if has_slo:
        spec = doc.get("slo_spec") or {}
        print(f"slo .................... {spec.get('metric', 'ttft')} <= "
              f"{spec.get('target_s', '?')}s for {spec.get('target_ratio', '?')} "
              f"of requests (burn alert at {spec.get('burn_threshold', '?')}x)")
    print("-" * 78)
    print(f"{'offered':>8} {'req/s':>8} {'goodput':>8} {'ok':>5} "
          f"{'on-ddl':>6} {'shed':>5} {'degr':>5} {'hedged':>6} "
          f"{'ttft_i_p99':>11} {'ttft_b_p99':>11}"
          + (f" {'burn':>7}" if has_slo else ""))

    def _p99_ms(step, cls):
        p99 = ((step.get("ttft") or {}).get(cls) or {}).get("p99_s")
        return f"{p99 * 1e3:>9.1f}ms" if p99 is not None else f"{'—':>11}"

    def _burn(step):
        slo = step.get("slo") or {}
        burn = slo.get("burn_rate")
        if burn is None:
            return f" {'—':>7}"
        return f" {burn:>6.2f}{'!' if slo.get('breached') else ' '}"

    for step in steps:
        marker = "  <- knee" if step is knee else ""
        print(f"{step.get('offered_x', 0):>7.1f}x "
              f"{step.get('offered_req_s', 0):>8.2f} "
              f"{step.get('goodput_req_s', 0):>8.2f} {step.get('ok', 0):>5} "
              f"{step.get('on_deadline', 0):>6} {step.get('shed', 0):>5} "
              f"{step.get('degraded', 0):>5} {step.get('hedged', 0):>6} "
              f"{_p99_ms(step, 'interactive')} {_p99_ms(step, 'batch')}"
              + (_burn(step) if has_slo else "")
              + marker)
    print("-" * 78)
    if has_slo:
        first = doc.get("slo_first_breach_step")
        if first is None:
            print(f"slo verdict ............ {GREEN_OK} no step breached the "
                  f"SLO burn threshold")
        else:
            breach = steps[first] if 0 <= first < len(steps) else {}
            print(f"slo verdict ............ first breach at step {first} "
                  f"({breach.get('offered_x', '?')}x offered, burn "
                  f"{(breach.get('slo') or {}).get('burn_rate', float('nan')):.2f})")
    if knee is None:
        print(f"verdict ................ {GREEN_OK} goodput held >= 90% of "
              f"capacity through {steps[-1].get('offered_x', 0):.1f}x offered "
              f"load (no knee)")
    else:
        print(f"verdict ................ knee at "
              f"{knee.get('offered_x', 0):.1f}x offered load: goodput "
              f"{knee.get('goodput_req_s', 0):.2f} req/s < "
              f"{knee_floor:.2f} req/s floor")
    return 0


def spec_report(path):
    """``dstpu_report --spec <loadgen-json>``: render the per-drafter
    speculative-decoding comparison table from ``bin/dstpu_loadgen
    --spec-demo --json`` — acceptance rate, tokens per decode dispatch, and
    ITL percentiles for each drafter family the run observed (prompt_lookup
    vs learned, or both under auto arbitration / --drafter pins). Returns 0
    when the doc parses and carries at least one drafter row."""
    import json
    import os

    path = os.path.abspath(path)
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        print(f"cannot read speculative report {path}: {e}")
        return 2
    drafters = doc.get("drafters") or {}
    overall = doc.get("overall") or {}
    if not drafters:
        print(f"{path} has no per-drafter rows "
              f"(is this a loadgen --spec-demo --json file against a "
              f"speculation-enabled server?)")
        return 2
    wl = doc.get("workload") or {}
    print("-" * 78)
    print(f"speculative decoding ... {path}")
    demo = wl.get("spec_demo")
    if demo:
        print(f"workload ............... --spec-demo "
              f"{demo[0]}:{demo[1] if len(demo) > 1 else 1} "
              f"({wl.get('ok', '?')}/{wl.get('requests', '?')} ok"
              + (f", pinned --drafter {wl['drafter_pin']}"
                 if wl.get("drafter_pin") else "")
              + ")")
    drafted = overall.get("drafted", 0)
    print(f"overall ................ accept_rate="
          f"{overall.get('accepted', 0) / max(1, drafted):.2f} "
          f"({overall.get('accepted', 0)}/{drafted} drafts) "
          f"tokens_per_step={overall.get('tokens_per_step', 0):.2f}")
    print("-" * 78)
    print(f"{'drafter':<14} {'reqs':>5} {'accepted':>9} {'drafted':>8} "
          f"{'accept':>7} {'tok/step':>9} {'itl_p50':>9} {'itl_p99':>9}")

    def _ms(agg, pct):
        v = (agg.get("itl") or {}).get(pct, (agg.get("itl") or {}).get(str(pct)))
        return f"{v * 1e3:>7.1f}ms" if isinstance(v, (int, float)) \
            and v == v else f"{'—':>9}"

    best = max(drafters, key=lambda n: drafters[n].get("tokens_per_step", 0))
    for name in sorted(drafters):
        agg = drafters[name]
        marker = "  <- best" if name == best and len(drafters) > 1 else ""
        print(f"{name:<14} {agg.get('requests', 0):>5} "
              f"{agg.get('accepted', 0):>9} {agg.get('drafted', 0):>8} "
              f"{agg.get('accept_rate', 0):>7.2f} "
              f"{agg.get('tokens_per_step', 0):>9.2f} "
              f"{_ms(agg, 50)} {_ms(agg, 99)}" + marker)
    print("-" * 78)
    print(f"verdict ................ {GREEN_OK} best tokens/step: {best} "
          f"({drafters[best].get('tokens_per_step', 0):.2f})")
    return 0


def _load_usage_doc(src):
    """A ``--usage`` operand is either a saved JSON file (a ``/v1/usage`` /
    ``/v1/fleet/usage`` / ``/v1/stats`` doc, or a ``bin/dstpu_loadgen
    --tenants --json`` file) or a live address: ``/v1/usage`` is tried first
    (single replica; the ``perf`` join rides along from ``/v1/stats``), then
    the router's ``/v1/fleet/usage``."""
    import json
    import os
    import urllib.request

    if os.path.isfile(src):
        with open(src) as f:
            return json.load(f)
    base = src if src.startswith(("http://", "https://")) else "http://" + src
    base = base.rstrip("/")
    if base.endswith(("/v1/usage", "/v1/fleet/usage", "/v1/stats")):
        urls = [base]
    else:
        urls = [base + "/v1/usage", base + "/v1/fleet/usage"]
    last = None
    for url in urls:
        try:
            with urllib.request.urlopen(url, timeout=5) as resp:
                doc = json.loads(resp.read().decode())
        except Exception as e:
            last = e
            continue
        if url.endswith("/v1/usage") and "perf" not in doc:
            stats_url = url[: -len("/v1/usage")] + "/v1/stats"
            try:
                with urllib.request.urlopen(stats_url, timeout=5) as resp:
                    doc["perf"] = json.loads(resp.read().decode()).get("perf")
            except Exception:
                pass
        return doc
    raise last if last is not None else RuntimeError("no usage doc")


def _render_ledger_tenants(tenants):
    """The cost-ledger tenant table (``/v1/usage`` / ``/v1/fleet/usage``
    shape: nested token/kv/wire accumulators per tenant)."""
    print(f"{'tenant':<14} {'reqs':>5} {'billed_tok':>10} {'device_s':>9} "
          f"{'kv_blk_s':>9} {'wire_B':>10} {'saved_tok':>9}")
    for name in sorted(tenants, key=lambda n: -(tenants[n].get("tokens") or
                                                {}).get("billed", 0)):
        row = tenants[name]
        tokens = row.get("tokens") or {}
        saved = row.get("saved_tokens") or {}
        print(f"{name:<14} {row.get('requests', 0):>5} "
              f"{tokens.get('billed', 0):>10} "
              f"{row.get('device_seconds', 0.0):>9.3f} "
              f"{sum((row.get('kv_block_seconds') or {}).values()):>9.2f} "
              f"{sum((row.get('wire_bytes') or {}).values()):>10} "
              f"{sum(saved.values()):>9}")


def _render_loadgen_tenants(tenants):
    """The client-side tenant table (``bin/dstpu_loadgen --tenants --json``
    shape: offered/ok/shed counts, goodput, TTFT percentiles)."""
    print(f"{'tenant':<14} {'reqs':>5} {'ok':>5} {'shed':>5} "
          f"{'goodput':>9} {'ttft_p50':>10} {'ttft_p99':>10}")

    def _ms(row, pct):
        v = (row.get("ttft_ms") or {}).get(pct)
        return f"{v:>8.1f}ms" if isinstance(v, (int, float)) else f"{'—':>10}"

    for name in sorted(tenants, key=lambda n: -tenants[n].get("requests", 0)):
        row = tenants[name]
        print(f"{name:<14} {row.get('requests', 0):>5} {row.get('ok', 0):>5} "
              f"{row.get('shed', 0):>5} "
              f"{row.get('goodput_req_s', 0.0):>7.2f}/s "
              f"{_ms(row, 'p50')} {_ms(row, 'p99')}")


def _render_perf_join(perf):
    """The predicted-vs-observed table: one row per (program, bucket) the
    engine dispatched, joined live against the roofline prediction. A ratio
    near 1 means the analytic model holds; sustained drift raised the
    ``perf_drift_events_total`` rows shown in the last column."""
    rows = (perf or {}).get("programs") or []
    if not rows:
        print("predicted-vs-observed .. no dispatches observed yet")
        return
    print(f"predicted-vs-observed .. chip={perf.get('chip', '?')} "
          f"drift_factor={perf.get('drift_factor', '?')}")
    print(f"{'program':<24} {'bucket':>8} {'disp':>6} {'pred':>10} "
          f"{'obs_p50':>10} {'ratio':>7} {'drift':>6}")
    def _ms(v):
        return (f"{v * 1e3:>8.2f}ms" if isinstance(v, (int, float)) and v == v
                else f"{'—':>10}")

    for row in sorted(rows, key=lambda r: (r.get("program", ""),
                                           r.get("bucket", 0))):
        ratio = row.get("ratio")
        print(f"{row.get('program', '?'):<24} {row.get('bucket', 0):>8} "
              f"{row.get('dispatches', 0):>6} "
              f"{_ms(row.get('predicted_s'))} {_ms(row.get('observed_p50_s'))} "
              + (f"{ratio:>7.2f}" if isinstance(ratio, (int, float))
                 else f"{'—':>7}")
              + f" {row.get('drift_events', 0):>6}")


def usage_report(src):
    """``dstpu_report --usage <file | host:port>``: tenant cost-attribution
    tables plus the predicted-vs-observed perf join. The operand is a live
    replica/router address, a saved ``/v1/usage`` / ``/v1/fleet/usage`` /
    ``/v1/stats`` doc, or a ``bin/dstpu_loadgen --tenants --json`` file."""
    try:
        doc = _load_usage_doc(src)
    except Exception as e:
        print(f"cannot load usage doc from {src}: {e}")
        return 2
    if not isinstance(doc, dict):
        print(f"{src}: not a usage doc")
        return 2
    perf = doc.get("perf")
    if isinstance(doc.get("usage"), dict):  # a /v1/stats doc
        doc = doc["usage"]
    print("-" * 78)
    print(f"cost attribution ....... {src}")
    print("-" * 78)
    if doc.get("enabled") is False:
        print("cost ledger disabled (run the server with telemetry active "
              "and ServingConfig.cost.enabled)")
        return 0
    totals = doc.get("totals")
    if isinstance(totals, dict):
        tokens = totals.get("tokens") or {}
        print(f"totals ................. requests={totals.get('requests', 0)} "
              f"billed_tokens={tokens.get('billed', 0)} "
              f"device_s={totals.get('device_seconds', 0.0):.3f} "
              f"dispatches={totals.get('dispatches', 0)}")
    tenants = doc.get("tenants") or {}
    if not tenants:
        print("no tenant rows yet")
    elif any("goodput_req_s" in row for row in tenants.values()):
        _render_loadgen_tenants(tenants)
    else:
        _render_ledger_tenants(tenants)
    if isinstance(doc.get("fair_share"), dict):
        fs = doc["fair_share"]
        print(f"fair share ............. sheds={fs.get('sheds', 0)} "
              f"tenants={len(fs.get('tenants') or ())}")
    if perf is not None:
        print("-" * 78)
        _render_perf_join(perf)
    print("-" * 78)
    return 0


def main(argv=None):
    argv = list(sys.argv[1:]) if argv is None else list(argv)
    if "--spec" in argv:
        idx = argv.index("--spec")
        if idx + 1 >= len(argv):
            print("usage: dstpu_report --spec <loadgen-spec-demo.json>")
            return 2
        return spec_report(argv[idx + 1])
    if "--overload" in argv:
        idx = argv.index("--overload")
        if idx + 1 >= len(argv):
            print("usage: dstpu_report --overload <loadgen-overload.json>")
            return 2
        return overload_report(argv[idx + 1])
    if "--gang" in argv:
        idx = argv.index("--gang")
        if idx + 1 >= len(argv):
            print("usage: dstpu_report --gang <dir>")
            return 2
        return gang_report(argv[idx + 1])
    if "--checkpoint" in argv:
        idx = argv.index("--checkpoint")
        if idx + 1 >= len(argv):
            print("usage: dstpu_report --checkpoint <dir> [--keep-last-k K]")
            return 2
        keep = None
        if "--keep-last-k" in argv:
            kidx = argv.index("--keep-last-k")
            if kidx + 1 >= len(argv):
                print("usage: dstpu_report --checkpoint <dir> [--keep-last-k K]")
                return 2
            keep = int(argv[kidx + 1])
        return checkpoint_report(argv[idx + 1], keep_last_k=keep)
    if "--perf" in argv:
        idx = argv.index("--perf")
        if idx + 1 >= len(argv):
            print("usage: dstpu_report --perf <budgets-dir | gate-report.json>")
            return 2
        from deepspeed_tpu.perf.reporting import perf_report
        return perf_report(argv[idx + 1])
    if "--metrics-url" in argv:
        idx = argv.index("--metrics-url")
        if idx + 1 >= len(argv):
            print("usage: dstpu_report --metrics-url <host:port | http://...>")
            return 2
        return metrics_report(argv[idx + 1])
    if "--trace" in argv:
        idx = argv.index("--trace")
        if idx + 1 >= len(argv):
            print("usage: dstpu_report --trace <chrome-trace.json | flight-dump.json>")
            return 2
        return trace_report(argv[idx + 1])
    if "--timeseries" in argv:
        idx = argv.index("--timeseries")
        if idx + 1 >= len(argv):
            print("usage: dstpu_report --timeseries <timeseries.json | host:port>")
            return 2
        return timeseries_report(argv[idx + 1])
    if "--usage" in argv:
        idx = argv.index("--usage")
        if idx + 1 >= len(argv):
            print("usage: dstpu_report --usage <usage.json | host:port>")
            return 2
        return usage_report(argv[idx + 1])
    if "--kv" in argv:
        idx = argv.index("--kv")
        if idx + 1 >= len(argv):
            print("usage: dstpu_report --kv <stats.json | host:port>")
            return 2
        return kv_report(argv[idx + 1])
    import deepspeed_tpu
    print("-" * 60)
    print("DeepSpeed-TPU C++/JAX environment report")
    print("-" * 60)
    print(f"deepspeed_tpu version ... {deepspeed_tpu.__version__}")
    print(f"python ................. {sys.version.split()[0]}")
    for mod in ("jax", "jaxlib", "flax", "optax", "orbax.checkpoint", "numpy"):
        print(f"{mod:<22} ... {_version(mod)}")
    print("-" * 60)
    # a dead TPU tunnel HANGS backend init rather than raising — the device
    # facts come from ONE timed subprocess (shared probe; the parent never
    # touches the backend, so the report can't freeze and doesn't pay
    # backend init twice)
    from deepspeed_tpu.utils.jax_platform import probe_backend
    info, why = probe_backend()
    if info is None:
        print(f"backend ................ UNREACHABLE ({why})")
    else:
        mems = info["memory_kinds"]
        print(f"backend ................ {info['backend']}")
        print(f"devices ................ {info['device_count']}: {info['device_kind']}")
        print(f"process count .......... {info['process_count']}")
        print(f"memory kinds ........... {mems}")
        print(f"host offload ........... "
              f"{GREEN_OK if 'pinned_host' in mems else RED_NO}")
    print("-" * 60)
    # native-op compat matrix (reference env_report.py op_report / ds_report)
    from deepspeed_tpu.ops.op_builder import ALL_OPS
    for name, cls in ALL_OPS.items():
        b = cls()
        ok = b.is_compatible()
        print(f"native op {name:<12} ... {GREEN_OK if ok else RED_NO}"
              f"{'' if ok else '  (' + str(b.error_log) + ')'}")
    print("-" * 60)
    from deepspeed_tpu.utils import groups
    print(f"mesh axes .............. {groups.MESH_AXES}")
    if groups.mesh_is_initialized():
        print(f"mesh ................... {dict(groups.get_mesh().shape)}")
    else:
        print("mesh ................... not initialized (created at engine init)")
    print("-" * 60)
    return 0


if __name__ == "__main__":
    sys.exit(main())
