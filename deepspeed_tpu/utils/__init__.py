from deepspeed_tpu.utils import groups
from deepspeed_tpu.utils.logging import log_dist, logger
from deepspeed_tpu.utils.timer import NoopTimer, SynchronizedWallClockTimer, ThroughputTimer
from deepspeed_tpu.utils.init_on_device import OnDevice
from deepspeed_tpu.utils.tensor_fragment import (safe_get_full_fp32_param,
                                                 safe_get_full_grad,
                                                 safe_get_full_optimizer_state,
                                                 safe_get_local_fp32_param,
                                                 safe_set_full_fp32_param,
                                                 safe_set_full_optimizer_state)
