"""Fair-share admission (ISSUE tentpole b): per-tenant rate EWMAs, the
deficit-weighted over-share verdict with hysteresis, the brownout-gated
admission 429 and queue-shed paths, and the flood gate — a one-tenant flood
cannot starve a well-behaved tenant's interactive deadline goodput.

Policy math (FairSharePolicy, validate_tenant) is tested engine-free;
scheduler behavior drives ``step()`` manually (``start=False``) like
test_overload.py. The flood gate runs real engine work on a warmed engine
with deadlines derived from a measured baseline, so it is rate-calibrated
rather than wall-clock-guessed.
"""

import time

import numpy as np
import pytest

from deepspeed_tpu.serving import (AdmissionRejected, RequestState,
                                   ServingConfig, ServingScheduler)
from deepspeed_tpu.serving.config import OverloadConfig
from deepspeed_tpu.serving.overload import FairSharePolicy, validate_tenant

MAX_STEPS = 400


def _run_until(sched, pred, max_steps=MAX_STEPS):
    for _ in range(max_steps):
        if pred():
            return
        sched.step()
    raise AssertionError(f"predicate not reached in {max_steps} steps")


def _prompt(n=9, vocab=64):
    return (np.arange(n) % vocab).tolist()


def _force_stage(sched, minimum=1):
    """Deterministically drive the brownout controller past ``minimum``, then
    pin it there: per-tick pressure sampling must not decay the stage while a
    test exercises the pressure-gated fair-share paths."""
    for _ in range(30):
        sched._brownout.update(1.0)
    assert sched._brownout.stage >= minimum
    sched._brownout.update = lambda pressure: sched._brownout.stage


# ---------------------------------------------------------------------------
# policy primitives (engine-free)
# ---------------------------------------------------------------------------
def test_validate_tenant_normalizes_and_rejects():
    assert validate_tenant(None) is None
    assert validate_tenant("") is None
    assert validate_tenant("   ") is None  # whitespace-only = unlabeled
    assert validate_tenant("  acme \t") == "acme"
    with pytest.raises(ValueError, match="longer"):
        validate_tenant("x" * 65)
    for bad in ("a\nb", "a\rb", "a\x00b"):
        with pytest.raises(ValueError, match="control"):
            validate_tenant(bad)


def test_lone_tenant_owns_share_one_and_is_never_over():
    """The policy is inert until there is someone to be unfair to."""
    fs = FairSharePolicy(alpha=1.0)
    fs.observe("only", 10_000, now=0.0)
    fs.observe("only", 10_000, now=1.0)
    assert fs.configured_share("only") == 1.0
    assert fs.measured_share("only") == 1.0
    assert not fs.over_share("only")  # measured <= 1.0 < over_factor * 1.0


def test_observe_ignores_zero_tokens_and_non_advancing_clock():
    fs = FairSharePolicy(alpha=1.0)
    fs.observe("a", 100, now=0.0)  # anchor only: no interval yet
    assert fs.measured_share("a") == 0.0
    fs.observe("a", 100, now=1.0)
    rate = fs.doc()["tenants"]["a"]["rate_tokens_per_s"]
    assert rate == pytest.approx(100.0)
    fs.observe("a", 0, now=2.0)    # zero tokens: dropped entirely
    fs.observe("a", 50, now=0.5)   # behind the last observation: dt <= 0
    assert fs.doc()["tenants"]["a"]["rate_tokens_per_s"] == pytest.approx(rate)


def test_over_share_enters_and_clears_with_hysteresis():
    fs = FairSharePolicy(alpha=1.0, over_factor=1.25, hysteresis=0.25)
    for t in ("hog", "meek"):
        fs.observe(t, 1, now=0.0)  # anchors
    # equal default shares (0.5 each); hog takes ~99% of the measured rate
    fs.observe("hog", 9_900, now=1.0)
    fs.observe("meek", 100, now=1.0)
    assert not fs.over_share("meek")
    assert fs.over_share("hog")  # 0.99 > 1.25 * 0.5
    # hysteresis holds the flag in the dead band: 0.55 is under the 0.625
    # enter threshold but above the (1.25 - 0.25) * 0.5 = 0.5 clear threshold
    fs.observe("hog", 5_500, now=2.0)
    fs.observe("meek", 4_500, now=2.0)
    assert fs.measured_share("hog") == pytest.approx(0.55)
    assert fs.over_share("hog")
    # a fresh policy at the same measured split would NOT flag — the flag is
    # state, not a pure function of the rates
    fresh = FairSharePolicy(alpha=1.0, over_factor=1.25, hysteresis=0.25)
    for t, tok in (("hog", 5_500), ("meek", 4_500)):
        fresh.observe(t, 1, now=0.0)
        fresh.observe(t, tok, now=1.0)
    assert not fresh.over_share("hog")
    # falling below the clear threshold releases the original flag
    fs.observe("hog", 1_000, now=3.0)
    fs.observe("meek", 9_000, now=3.0)
    assert not fs.over_share("hog")


def test_explicit_shares_weight_the_entitlement():
    fs = FairSharePolicy(shares={"gold": 3.0, "bronze": 1.0}, alpha=1.0)
    for t in ("gold", "bronze"):
        fs.observe(t, 1, now=0.0)
        fs.observe(t, 5_000, now=1.0)  # equal measured rates
    assert fs.configured_share("gold") == pytest.approx(0.75)
    assert fs.configured_share("bronze") == pytest.approx(0.25)
    # at a 50/50 measured split, bronze is past 1.25 x 0.25, gold is under
    assert fs.deficit("bronze") == pytest.approx(0.25)
    assert fs.deficit("gold") == pytest.approx(-0.25)
    assert fs.over_share("bronze") and not fs.over_share("gold")
    # a tenant the map does not list gets weight 1.0, never zero entitlement
    fs.note("walkin")
    assert fs.configured_share("walkin") == pytest.approx(1.0 / 5.0)


def test_doc_shape():
    fs = FairSharePolicy(alpha=1.0, over_factor=1.5, hysteresis=0.1)
    fs.note("a")
    doc = fs.doc()
    assert doc["over_factor"] == 1.5 and doc["sheds"] == 0
    row = doc["tenants"]["a"]
    assert row["rate_tokens_per_s"] is None
    assert row["configured_share"] == 1.0 and not row["over_share"]


def test_over_factor_must_exceed_one():
    with pytest.raises(ValueError, match="over_factor"):
        FairSharePolicy(over_factor=1.0)


# ---------------------------------------------------------------------------
# scheduler gates (manual stepping)
# ---------------------------------------------------------------------------
def _fs_config(queue_capacity=64, **overload_kw):
    overload_kw.setdefault("fair_share_enabled", True)
    overload_kw.setdefault("fair_share_alpha", 1.0)
    return ServingConfig(queue_capacity=queue_capacity,
                         overload=OverloadConfig(**overload_kw))


def _make_over_share(sched, hog="hog", meek="meek"):
    """Synthetically establish hog as over-share: feed the policy's EWMAs
    directly (the deterministic stand-in for hog's executed batches)."""
    fs = sched._fair_share
    fs.note(meek)
    fs.observe(hog, 1, now=0.0)
    fs.observe(hog, 10_000, now=1.0)
    assert fs.over_share(hog)


def test_admission_429_for_over_share_tenant_under_pressure(make_engine):
    engine = make_engine()
    sched = ServingScheduler(engine, _fs_config(), start=False)
    try:
        _make_over_share(sched)
        # stage 0: no pressure, the gate is inert even for an over-share tenant
        r0 = sched.submit(_prompt(), max_new_tokens=2, tenant="hog")
        _run_until(sched, lambda: r0.state is RequestState.DONE)
        _force_stage(sched, minimum=1)
        with pytest.raises(AdmissionRejected) as exc:
            sched.submit(_prompt(), max_new_tokens=2, tenant="hog")
        assert exc.value.retry_after_s is not None
        assert exc.value.retry_after_s >= \
            sched._config.overload.retry_after_floor_s
        assert sched.stats()["counters"]["fair_share_shed"] == 1
        # the well-behaved tenant is admitted and completes under the same
        # pressure — that is the entire point of the policy
        good = sched.submit(_prompt(7), max_new_tokens=2, tenant="meek")
        _run_until(sched, lambda: good.state is RequestState.DONE)
        # the shed shows in the usage doc's fair-share posture
        fair = sched.usage()["fair_share"]
        assert fair["sheds"] == 1
        assert fair["tenants"]["hog"]["over_share"]
    finally:
        sched.stop(drain=False)


def test_unlabeled_requests_bill_to_the_default_tenant(make_engine):
    engine = make_engine()
    sched = ServingScheduler(engine, _fs_config(), start=False)
    try:
        req = sched.submit(_prompt(), max_new_tokens=2)
        assert req.tenant == sched._config.cost.default_tenant == "default"
        _run_until(sched, lambda: req.state is RequestState.DONE)
        assert "default" in sched.usage()["fair_share"]["tenants"]
    finally:
        sched.stop(drain=False)


def test_fair_share_disabled_is_the_control_arm(make_engine):
    engine = make_engine()
    sched = ServingScheduler(engine, ServingConfig(), start=False)
    try:
        assert sched._fair_share is None  # default off
        _force_stage(sched, minimum=1)
        # no fair-share gate: any tenant is admitted under pressure
        req = sched.submit(_prompt(), max_new_tokens=2, tenant="hog")
        assert req.shed_reason is None
        _run_until(sched, lambda: req.state is RequestState.DONE)
        assert "fair_share" not in sched.usage()
    finally:
        sched.stop(drain=False)


def test_queue_shed_takes_over_share_tenants_first(make_engine):
    engine = make_engine(max_tracked_sequences=1)
    # admission control off so requests QUEUE; the stage->shed path (not the
    # submit() gate) must be what rejects them — stage is still 0 at submit
    cfg = _fs_config(admission_control=False)
    sched = ServingScheduler(engine, cfg, start=False)
    try:
        hog1 = sched.submit(_prompt(), max_new_tokens=4, tenant="hog")
        hog2 = sched.submit(_prompt(5), max_new_tokens=4, tenant="hog")
        meek = sched.submit(_prompt(7), max_new_tokens=4, tenant="meek")
        _make_over_share(sched)
        _force_stage(sched, minimum=1)
        sched._shed_queued(now=time.monotonic())
        for r in (hog1, hog2):
            assert r.state is RequestState.FAILED
            assert "fair-share" in r.shed_reason
            assert r.retry_after_s is not None and r.retry_after_s > 0
            assert r.tokens == [] and r._fed == 0  # zero engine work consumed
        assert meek.shed_reason is None
        assert sched.stats()["counters"]["fair_share_shed"] == 2
        assert sched._fair_share.sheds == 2
        _run_until(sched, lambda: meek.state is RequestState.DONE)
    finally:
        sched.stop(drain=False)


def test_fair_share_shed_is_work_conserving(make_engine):
    """Shedding only happens when an under-share tenant is waiting behind the
    over-share work: with the queue holding ONLY the flagged tenant's
    requests, dropping them frees capacity for nobody — nothing is shed, and
    the work completes once pressure-independent admission reaches it."""
    engine = make_engine(max_tracked_sequences=1)
    sched = ServingScheduler(engine, _fs_config(admission_control=False),
                             start=False)
    try:
        hog1 = sched.submit(_prompt(), max_new_tokens=2, tenant="hog")
        hog2 = sched.submit(_prompt(5), max_new_tokens=2, tenant="hog")
        _make_over_share(sched)
        _force_stage(sched, minimum=1)
        sched._shed_queued(now=time.monotonic())
        assert hog1.shed_reason is None and hog2.shed_reason is None
        assert sched.stats()["counters"]["fair_share_shed"] == 0
        _run_until(sched, lambda: hog1.state is RequestState.DONE
                   and hog2.state is RequestState.DONE)
    finally:
        sched.stop(drain=False)


# ---------------------------------------------------------------------------
# the flood gate: real engine work, rate-calibrated deadlines
# ---------------------------------------------------------------------------
N_GOOD = 3
GOOD_TOKENS = 6
FLOOD_TOKENS = 32          # per flood request; the COUNT adapts to the rate
PROMPT_TOKENS = 9


def _flood_config(fair_share_on):
    # FIFO admission models the realistic arrival order (same priority
    # class); admission control off so the flood actually queues — the
    # policy under test is fair-share, not deadline feasibility
    return _fs_config(queue_capacity=256,
                      fair_share_enabled=fair_share_on,
                      priority_ordering=False,
                      admission_control=False)


def _warm_engine(sched):
    """Pay every XLA compile (prefill bucket + decode batch 1 and 2) before
    any clock starts: compile time must bias neither the measured baseline
    nor a deadline."""
    warm = [sched.submit(_prompt(), max_new_tokens=2, tenant="warmup")
            for _ in range(2)]
    _run_until(sched, lambda: all(r.state is RequestState.DONE for r in warm))


def _measure_baseline(make_engine):
    """The well-behaved tenant alone on a warmed engine: the good workload's
    wall time AND the sustained flood-shaped token rate — everything else in
    the gate is calibrated off these. Two measurements because they differ by
    an order of magnitude: the good run is tiny (scheduler-overhead-bound),
    while the flood drains at the engine's sustained batch-decode rate."""
    engine = make_engine(max_tracked_sequences=2)
    sched = ServingScheduler(engine, _flood_config(True), start=False)
    try:
        _warm_engine(sched)
        # two identical passes, timing only the second: the first flushes any
        # batch-shape compile _warm_engine missed (e.g. the lone-sequence
        # decode tail), which would otherwise inflate the measured wall ~10x
        # and mis-size every deadline derived from it
        for _ in range(2):
            t0 = time.monotonic()
            good = [sched.submit(_prompt(), max_new_tokens=GOOD_TOKENS,
                                 tenant="good") for _ in range(N_GOOD)]
            _run_until(sched, lambda: all(r.finished for r in good))
            wall_good = time.monotonic() - t0
        assert all(r.state is RequestState.DONE for r in good)
        # sustained rate over >= 4 flood-sized requests (a long enough window
        # that per-dispatch jitter and burst effects average out)
        t0 = time.monotonic()
        cal = [sched.submit(_prompt(), max_new_tokens=FLOOD_TOKENS,
                            tenant="good") for _ in range(4)]
        _run_until(sched, lambda: all(r.finished for r in cal),
                   max_steps=4000)
        rate = 4 * (PROMPT_TOKENS + FLOOD_TOKENS) / (time.monotonic() - t0)
        return max(wall_good, 1e-3), rate
    finally:
        sched.stop(drain=False)


def _run_flood_arm(make_engine, fair_share_on, deadline_s, flood_n):
    """Deadline goodput is judged by the TEST's clock, not in-scheduler
    deadlines: the good requests carry none, so neither the deadline-
    feasibility walk nor the timeout path can touch them — what separates
    the arms is fair-share alone."""
    engine = make_engine(max_tracked_sequences=2)
    sched = ServingScheduler(engine, _flood_config(fair_share_on), start=False)
    try:
        _warm_engine(sched)
        _force_stage(sched, minimum=1)  # sustained pressure for the whole arm
        flood = []
        for _ in range(flood_n):
            try:
                flood.append(sched.submit(_prompt(), tenant="flood",
                                          max_new_tokens=FLOOD_TOKENS))
            except AdmissionRejected as exc:
                # a 429 at submit is a valid fair-share outcome — but never
                # without the backoff contract
                assert exc.retry_after_s is not None and exc.retry_after_s > 0
        good = [sched.submit(_prompt(), max_new_tokens=GOOD_TOKENS,
                             tenant="good") for _ in range(N_GOOD)]
        cutoff = time.monotonic() + deadline_s
        while time.monotonic() < cutoff \
                and not all(r.finished for r in good):
            sched.step()
        goodput = sum(1 for r in good if r.state is RequestState.DONE)
        # the Retry-After contract holds on EVERY fair-share shed
        for r in flood:
            if r.shed_reason is not None:
                assert "fair-share" in r.shed_reason
                assert r.retry_after_s is not None and r.retry_after_s > 0
        sheds = sum(1 for r in flood if r.shed_reason is not None)
        return goodput, sheds
    finally:
        sched.stop(drain=False)


def test_flood_cannot_starve_well_behaved_tenant(make_engine):
    """The acceptance gate: tenant ``flood`` dumps ~2.5 deadlines' worth of
    work ahead of tenant ``good``'s interactive requests. With fair-share on,
    good keeps >= 90% of its no-flood deadline goodput (the flood is shed);
    the off control collapses to zero — the difference IS the policy."""
    wall_good, rate = _measure_baseline(make_engine)
    flood_work = PROMPT_TOKENS + FLOOD_TOKENS
    # the deadline covers (with ~8x slack) the un-sheddable in-flight flood
    # (2 tracked sequences) plus the good workload itself — generous because
    # the fair arm also pays a per-tick shed walk over the whole queued
    # flood, and suite-load CPU noise halves the calibrated rate; the flood
    # COUNT then scales to ~2.5 deadlines of drain time so the FIFO control
    # arm cannot finish it before the cutoff however fast the machine is
    deadline_s = max(2.5, 4.0 * wall_good,
                     8.0 * (2 * flood_work + N_GOOD * 15) / rate)
    flood_n = int(min(400, max(24, 2.5 * deadline_s * rate / flood_work)))

    goodput_fair, sheds = _run_flood_arm(
        make_engine, True, deadline_s, flood_n)
    goodput_ctrl, _ = _run_flood_arm(
        make_engine, False, deadline_s, flood_n)

    baseline_goodput = N_GOOD  # the baseline run completed every request
    assert goodput_fair >= 0.9 * baseline_goodput, (
        f"fair-share arm: {goodput_fair}/{baseline_goodput} good-tenant "
        f"requests made the {deadline_s:.2f}s deadline under a "
        f"{flood_n}-request flood")
    assert sheds > 0, "the flood was never shed — the gate proved nothing"
    assert goodput_ctrl < 0.5 * baseline_goodput, (
        f"control arm (fair-share off) did not collapse "
        f"({goodput_ctrl}/{baseline_goodput}): the flood sizing is too small "
        f"to starve anyone, so the fair-share arm passes vacuously")
