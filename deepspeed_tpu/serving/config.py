"""Serving config block.

Reference role: DeepSpeed-MII's deployment/``RaggedInferenceEngineConfig``
knobs for the persistent server (queue sizing, response behavior under load);
validated pydantic-style like the other config blocks (``config_v2.py``,
``telemetry/config.py``).
"""

from typing import Dict, Literal, Optional, Tuple

from pydantic import Field, field_validator, model_validator

from deepspeed_tpu.runtime.config_utils import DeepSpeedConfigModel

DEFAULT_MAX_RESUME_BODY_BYTES = 2 << 30
"""One authority for the ``/v1/resume`` body bound — shared by
``ServingConfig``, ``FleetConfig`` and ``serving/server.py`` so the router
and a replica can never disagree on whether the same payload is admissible."""


class PrefixCacheConfig(DeepSpeedConfigModel):
    """Automatic prefix caching (radix-tree KV reuse with copy-on-write block
    sharing — ``inference/v2/ragged/prefix_cache.py``). Off by default: the
    trie pins finished sequences' prefix blocks, so a cache-enabled scheduler
    intentionally does NOT return the KV pool to empty between requests."""

    enabled: bool = False
    """Look up every admitted prompt's longest cached prefix and publish
    completed sequences' full blocks back to the trie."""

    max_blocks: Optional[int] = Field(None, ge=1)
    """Cap on device blocks the trie may pin; None = bounded only by the pool
    (the KV-pressure path evicts unreferenced trie leaves LRU-first before
    touching live sequences)."""

    min_prefix_blocks: int = Field(1, ge=1)
    """Smallest cached-prefix match (in blocks) worth applying to a request;
    shorter matches prefill cold."""

    digest_catalog_limit: int = Field(64, ge=0)
    """How many trie-node digests (truncated hex, recency-first) the replica
    publishes in its probe doc for the fleet's cache-aware routing; 0 turns
    publication off (the replica then only receives hash-routed traffic)."""


class SpeculativeConfig(DeepSpeedConfigModel):
    """Speculative decoding (``inference/v2/spec/``): a drafter proposes
    continuation tokens per sequence per decode step at batch-build time and
    the engine verifies every proposed position in ONE ragged forward.
    Output is token-identical to non-speculative decoding at the same seed —
    greedy and sampled — the only effect is fewer decode dispatches. Off by
    default.

    Two drafter families, selected by ``drafter``: ``prompt_lookup`` mines
    n-gram repeats (linear ``1+k`` feeds through ``engine.verify``; wins on
    repetitive text, k adapts to 0 elsewhere) and ``learned`` reads the
    target's hidden state through trained Medusa-style heads and proposes a
    token TREE verified under the tree-attention mask
    (``engine.verify_tree``; wins on arbitrary text after self-distillation
    — ``bin/dstpu_spec_train``). ``auto`` arbitrates per request on measured
    per-drafter acceptance EWMAs, probing the loser periodically."""

    def __init__(self, strict=False, **data):
        # the base model drops "auto"-valued kwargs so defaults apply (the
        # reference's use-the-default marker) — but "auto" is a REAL drafter
        # mode here; route it around the filter and through validation
        drafter = data.pop("drafter", None)
        super().__init__(strict=strict, **data)
        if drafter is not None:
            self.drafter = drafter

    enabled: bool = False
    """Draft at batch-build time and run multi-token verify feeds through
    the decode path."""

    drafter: Literal["prompt_lookup", "learned", "auto"] = "prompt_lookup"
    """Drafter selection. ``prompt_lookup`` keeps the linear verify path;
    ``learned``/``auto`` route speculative decode through token-tree verify
    (a prompt-lookup draft then rides as a chain tree — bitwise the linear
    program's output)."""

    max_draft_tokens: int = Field(4, ge=1)
    """Upper bound on draft tokens per sequence per step (k). The effective k
    adapts per request from a measured acceptance EWMA and reaches 0 on
    adversarial (pattern-free) text. For the learned drafter this caps tree
    DEPTH (bounded additionally by ``num_draft_heads``)."""

    num_draft_heads: int = Field(3, ge=1, le=8)
    """Medusa heads a freshly-initialized learned drafter carries (head ``h``
    predicts the token ``h + 2`` positions past the hidden state); ignored
    when ``draft_head_path`` loads trained heads with their own count."""

    tree_width: int = Field(2, ge=1)
    """Candidate tokens per head the learned drafter may branch over when
    growing the draft tree (best-first by joint log-probability)."""

    tree_node_budget: int = Field(8, ge=2)
    """Cap on nodes per draft tree (root included). Tree nodes are fed
    tokens: they compete under the ragged token budget and
    ``draft_token_budget`` exactly like linear draft tokens."""

    draft_head_path: Optional[str] = None
    """Trained draft-head ``.npz`` (``bin/dstpu_spec_train`` output) for the
    learned drafter; None = fresh deterministic heads (acceptance adapts k
    to 0 until they are trained, so this is safe but slow)."""

    min_ngram: int = Field(1, ge=1)
    max_ngram: int = Field(3, ge=1)
    """Self-lookup n-gram window: the drafter matches the longest history
    suffix between these bounds against earlier occurrences."""

    accept_alpha: float = Field(0.5, gt=0, le=1)
    """EWMA smoothing for the per-request acceptance rate that drives the
    adaptive k (higher = faster back-off AND faster recovery)."""

    probe_interval: int = Field(16, ge=1)
    """At k=0 (acceptance collapsed), propose a single probe draft every this
    many decode steps so acceptance can recover when the text turns
    repetitive again."""

    draft_token_budget: Optional[int] = Field(None, ge=1)
    """Cap on draft tokens per batch (they compete with prefill chunks under
    the ragged token budget); None = bounded only by that budget. Brownout
    stage >= 2 zeroes the budget regardless."""

    @model_validator(mode="after")
    def _ngram_ordered(self):
        if self.max_ngram < self.min_ngram:
            raise ValueError("max_ngram must be >= min_ngram")
        return self


class KVTierConfig(DeepSpeedConfigModel):
    """Tiered KV memory (``inference/v2/ragged/tiering.py`` +
    ``serving/kv_tiers.py``): device blocks → host memory → disk spill files.
    Off by default — when enabled, KV pressure *demotes* cached-but-idle
    state down the ladder (prefix-trie nodes first, then offloaded sessions
    host→disk) before anything is evicted or shed, and brownout gains a
    demote stage ahead of shedding."""

    enabled: bool = False
    """Run the tiered ladder: configure the engine's tiered store with the
    budget/spill policy below and demote under pressure."""

    host_bytes: Optional[int] = Field(None, ge=0)
    """Host-tier budget in bytes: when host-resident offloaded KV exceeds it
    (and ``spill_dir`` is set), the coldest entries demote to disk on the
    async writer. None = unbounded host tier."""

    spill_dir: Optional[str] = None
    """Disk-tier directory for spill files; None = the host tier is the
    floor (nothing demotes to disk)."""

    demote_batch: int = Field(4, ge=1)
    """Device blocks demoted per pressure tick (brownout's demote-before-shed
    stage and the scheduler's demote-first eviction)."""


class OverloadConfig(DeepSpeedConfigModel):
    """Overload control (``serving/overload.py``): priority admission,
    deadline-aware shedding and staged brownout degradation. Enabled by
    default but quiescent under normal load — admission control only acts on
    requests that carry a deadline, and the brownout stages only engage when
    the smoothed pressure signal clears the thresholds."""

    enabled: bool = True
    """Master switch. False = the pre-overload-control scheduler: FIFO queue
    order, no admission estimate, no shedding, no brownout (the uniform-FIFO
    control arm the overload gates compare against)."""

    priority_ordering: bool = True
    """Admit queued requests in (priority, deadline, arrival) order instead
    of FIFO; within a class, earliest deadline first."""

    admission_control: bool = True
    """Estimate queue wait from the measured token rate at ``submit()`` and
    reject a request whose deadline is provably unmeetable (HTTP 429 +
    ``Retry-After``) instead of admitting it to fail mid-queue — rejecting at
    admission is cheap, failing after prefill wastes engine work."""

    admission_margin: float = Field(1.0, gt=0)
    """Feasibility proof margin: a request is rejected when the estimated
    completion time exceeds ``deadline * margin``. Values above 1 are more
    lenient (reject later); below 1 more aggressive."""

    min_rate_samples: int = Field(4, ge=1)
    """Executed batches the rate estimator needs before admission control or
    shedding trusts it; a cold estimator admits everything."""

    rate_alpha: float = Field(0.25, gt=0, le=1)
    """EWMA smoothing factor for the measured token rate."""

    shed_enabled: bool = True
    """Under sustained pressure (brownout stage >= 1), shed queued requests
    whose deadline is provably unmeetable — lowest priority / latest deadline
    first — before they waste a prefill."""

    brownout_stage_thresholds: Tuple[float, float, float] = (0.65, 0.85, 0.95)
    """Smoothed-pressure entry thresholds for brownout stages 1..3 (stage 1:
    clamp batch ``max_new_tokens``; stage 2: + disable speculative decode
    chunking; stage 3: + reject batch class at submission)."""

    brownout_hysteresis: float = Field(0.1, ge=0)
    """A stage entered at threshold ``t`` is only left when the smoothed
    pressure falls below ``t - hysteresis`` (no service-mode flapping)."""

    pressure_alpha: float = Field(0.3, gt=0, le=1)
    """EWMA smoothing factor for the pressure signal
    (``max(queue_fraction, kv_occupancy)``, sampled every scheduler tick)."""

    brownout_clamp_max_new_tokens: int = Field(16, ge=1)
    """Stage >= 1 generation cap for batch-class requests (flagged
    ``degraded_mode`` in the response)."""

    retry_after_floor_s: float = Field(0.5, gt=0)
    retry_after_cap_s: float = Field(30.0, gt=0)
    """Bounds on the ``Retry-After`` estimate derived from the measured queue
    drain rate (429/503 responses)."""

    slo_pressure: bool = False
    """Feed the SLO engine's breach signal (fast-window burn normalized by
    its alert threshold, in [0, 1]) into the brownout pressure sample as a
    floor — a burning error budget browns the replica out even while queue
    depth and KV occupancy look healthy. Requires an active telemetry
    session with ``telemetry.slo`` configured; off by default."""

    fair_share_enabled: bool = False
    """Tenant fair-share stage in the admission path (opt-in): while the
    brownout controller reports pressure (stage >= 1), a tenant whose
    measured share of the token rate exceeds ``fair_share_over_factor`` x its
    configured share is shed first — new submissions 429 with ``Retry-After``
    and its queued requests are shed ahead of deadline-based shedding
    (deficit-weighted). Requires ``enabled``; the ``enabled=false`` control
    arm is untouched."""

    fair_share_shares: Optional[Dict[str, float]] = None
    """Per-tenant share weights (normalized over tenants seen); None = equal
    split across every tenant that has submitted. Tenants missing from the
    map get weight 1.0."""

    fair_share_alpha: float = Field(0.2, gt=0, le=1)
    """EWMA smoothing for per-tenant measured token rates."""

    fair_share_over_factor: float = Field(1.25, gt=1)
    """A tenant is over-share when measured share > factor x configured
    share."""

    fair_share_hysteresis: float = Field(0.25, ge=0)
    """The over-share verdict clears only below
    ``(over_factor - hysteresis) x configured share`` (no admit/shed
    flapping at the boundary)."""

    @model_validator(mode="after")
    def _ordered_thresholds(self):
        if list(self.brownout_stage_thresholds) != sorted(self.brownout_stage_thresholds):
            raise ValueError("brownout_stage_thresholds must be ascending")
        return self


class CostConfig(DeepSpeedConfigModel):
    """Cost-attribution plane (``telemetry/ledger.py`` + ``perf/observed.py``):
    per-request metering, bounded per-tenant rollups (``/v1/usage``), and the
    predicted-vs-observed perf ledger. The plane only materializes while a
    telemetry session is active — with telemetry off every hot-path site is a
    single None check and the registry sees zero api_calls."""

    enabled: bool = True
    """Meter requests when telemetry is active. False = no ledger even with
    telemetry on (spans/metrics still record)."""

    default_tenant: str = "default"
    """Tenant billed for requests that carry no identity (no ``tenant`` JSON
    field, no ``X-DSTPU-Tenant`` header)."""

    max_tenants: int = Field(64, ge=1)
    """Bound on distinct tenants in the usage rollup; later tenants fold
    into ``<other>`` (sums still reconcile against the aggregate)."""

    tenant_metric_top_k: int = Field(8, ge=1)
    """Bound on per-tenant metric label sets (``serving_tenant_*``); tenants
    past the cap share the ``<other>`` label."""

    perf_chip: str = "v5e"
    """Chip spec the observed-vs-predicted join prices rooflines against
    (``perf/chip_specs.py``); drift detection is baseline-relative, so an
    off-target chip only shifts the absolute ratio, not the alarm."""

    perf_drift_factor: float = Field(4.0, gt=1)
    """Observed/predicted ratio above ``factor x baseline`` counts toward a
    drift episode."""

    perf_drift_consecutive: int = Field(3, ge=1)
    """Consecutive over-factor dispatches that raise one drift event."""

    perf_baseline_dispatches: int = Field(8, ge=1)
    """Post-amnesty dispatches averaged into each (program, bucket)'s
    baseline ratio before drift detection arms."""


class ServingConfig(DeepSpeedConfigModel):
    """Knobs for the request scheduler + HTTP front-end."""

    queue_capacity: int = Field(128, ge=1)
    """Maximum QUEUED (admitted-but-unscheduled) requests; beyond it the
    backpressure policy applies."""

    backpressure: Literal["reject", "block"] = "reject"
    """Queue-full behavior: ``reject`` fails ``submit()`` immediately (HTTP
    429); ``block`` stalls the submitting thread until space frees (the
    closed-loop client pattern)."""

    default_max_new_tokens: int = Field(64, ge=1)
    """Per-request cap when the request doesn't specify one."""

    default_deadline_s: Optional[float] = Field(None, gt=0)
    """Deadline applied to requests that don't carry their own; None = no
    deadline (requests are bounded by max_new_tokens only)."""

    drain_timeout_s: float = Field(30.0, ge=0)
    """Graceful-shutdown budget: how long ``stop(drain=True)`` lets in-flight
    requests finish before cancelling the remainder."""

    scheduler_tick_s: float = Field(0.001, gt=0)
    """Idle sleep between scheduler iterations when there is no work; busy
    iterations run back-to-back."""

    decode_chunk: int = Field(1, ge=1)
    """Decode steps per device dispatch on the decode-only fast path
    (``engine.decode_loop``); >1 trades up-to-(K-1)-token speculative
    over-generation for one host round-trip per K tokens."""

    max_prefill_chunk: Optional[int] = Field(None, ge=1)
    """Cap on prompt tokens admitted per batch per request (Dynamic SplitFuse
    chunk size); None = bounded only by the engine's ragged token budget."""

    heartbeat_interval_s: float = Field(0.05, ge=0)
    """How often an *idle* scheduler runs ``engine.empty_run()`` so EP
    replicas stay in collective lock-step. 0 = every idle tick."""

    heartbeat_enabled: Optional[bool] = None
    """None = auto (heartbeat only when the engine has expert parallelism
    enabled); True/False force it."""

    sse_keepalive_s: float = Field(10.0, gt=0)
    """SSE comment-line cadence while a stream has no token to send (queue
    wait, long prefill): keeps the socket demonstrably alive so a fleet
    router's bounded read budget (``FleetConfig.read_timeout_s``) measures
    replica *death*, never mere load."""

    host: str = "127.0.0.1"
    port: int = Field(0, ge=0, le=65535)
    """Bind address for ``ServingServer``; port 0 = ephemeral (the bound
    address is on ``server.address`` after ``start()``)."""

    prefix_cache: PrefixCacheConfig = PrefixCacheConfig()
    """Automatic prefix caching over the paged KV cache (radix-tree reuse +
    copy-on-write sharing); see :class:`PrefixCacheConfig`."""

    speculative: SpeculativeConfig = SpeculativeConfig()
    """Speculative decoding (model-free self-drafting + batch-wide verify);
    see :class:`SpeculativeConfig`."""

    overload: OverloadConfig = OverloadConfig()
    """Overload control: priority admission, deadline-aware shedding, staged
    brownout degradation; see :class:`OverloadConfig`."""

    kv_tiers: KVTierConfig = KVTierConfig()
    """Tiered KV memory (device→host→disk demotion under pressure); see
    :class:`KVTierConfig`."""

    cost: CostConfig = CostConfig()
    """Cost-attribution plane: per-request/per-tenant metering ledger and the
    predicted-vs-observed perf ledger; see :class:`CostConfig`."""

    max_resume_body_bytes: int = Field(DEFAULT_MAX_RESUME_BODY_BYTES, gt=0)
    """Upper bound on a ``POST /v1/resume`` body (the base64 KV-handoff
    payload; real-model KV runs to hundreds of MB and base64 adds 4/3). The
    body is fully buffered per handler thread, so operators whose resume
    endpoint is reachable beyond fleet-internal traffic should lower this to
    their largest expected payload."""

    @field_validator("default_deadline_s")
    @classmethod
    def _deadline_finite(cls, v):
        if v is not None and not (v > 0 and v == v):  # rejects NaN too
            raise ValueError("default_deadline_s must be a positive number")
        return v
