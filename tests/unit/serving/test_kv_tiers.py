"""Tiered KV memory, serving side (ISSUE 18): park/rehydrate CPU gates —
bitwise-identical continuation through demote→park→rehydrate-on-a-different-
replica (greedy AND sampled), zero prefill chunks for the cached turns,
demote-before-shed under brownout pressure, and the demote-first eviction
ladder with promotion-on-hit."""

import time

import numpy as np
import pytest

from deepspeed_tpu.serving import (KVTierConfig, OverloadConfig,
                                   PrefixCacheConfig, RequestState,
                                   ServingConfig, ServingScheduler)

MAX_STEPS = 400


def _run_until(sched, pred, max_steps=MAX_STEPS):
    for _ in range(max_steps):
        if pred():
            return
        sched.step()
    raise AssertionError(f"predicate not reached in {max_steps} steps")


def _prompt(n=9, vocab=64, base=0):
    return [(base + i) % vocab for i in range(n)]


def _tiered_config(tmp_path, **kw):
    return ServingConfig(
        kv_tiers=KVTierConfig(enabled=True, spill_dir=str(tmp_path)), **kw)


# ------------------------------------------------------- park & rehydrate --
@pytest.mark.parametrize("temperature", [0.0, 0.8],
                         ids=["greedy", "sampled"])
def test_park_rehydrate_bitwise_across_replicas(make_engine, tmp_path,
                                                temperature):
    """The flagship gate: turn 1 parks on replica A (after its KV rode the
    demotion ladder host→disk mid-session), turn 2 rehydrates the parked
    frame on replica B and must be BITWISE identical to an uninterrupted
    cold run of the full two-turn prompt at the same seed — greedy and
    sampled — while the cached turns schedule zero prefill chunks."""
    sched_a = ServingScheduler(make_engine(), _tiered_config(tmp_path / "a"),
                               start=False)
    p1 = _prompt(9)
    req1 = sched_a.submit(p1, max_new_tokens=6, temperature=temperature,
                          seed=3, park=True)
    # mid-session pressure: ride the full ladder device→host→disk, then let
    # decode restore transparently and finish
    _run_until(sched_a, lambda: len(req1.tokens) >= 2)
    sm_a = sched_a._engine._state_manager
    sched_a._engine.offload_sequence(req1.uid)
    assert sm_a.sequence_tier(req1.uid) == "host"
    assert sm_a.demote_sequence(req1.uid, wait=True)
    assert sm_a.sequence_tier(req1.uid) == "disk"
    _run_until(sched_a, lambda: req1.finished)
    assert req1.state is RequestState.DONE
    assert req1.park_payload is not None
    assert sched_a._counters["parks"] == 1
    parked = p1 + [int(t) for t in req1.tokens]

    # the returning turn strictly extends the parked history
    p2 = parked + _prompt(5, base=40)

    # replica B: rehydrate — count every prefill token actually fed
    eng_b = make_engine()
    sched_b = ServingScheduler(eng_b, _tiered_config(tmp_path / "b"),
                               start=False)
    fed_b = []
    real_put = eng_b.put

    def counting_put(uids, tokens, *a, **kw):
        fed_b.extend(int(np.asarray(t).size) for t in tokens)
        return real_put(uids, tokens, *a, **kw)

    eng_b.put = counting_put
    req2 = sched_b.submit_resume(req1.park_payload, prompt=p2,
                                 max_new_tokens=6, temperature=temperature,
                                 seed=9)
    _run_until(sched_b, lambda: req2.finished)
    assert req2.state is RequestState.DONE
    assert sched_b._counters["rehydrates"] == 1
    # the parked turns came from the frame's KV, not a re-prefill: only the
    # boundary token + the new turn's suffix are ever fed (plus one token per
    # decode step); no single feed is larger than the un-parked suffix
    seen = len(parked) - 1
    assert req2.cached_tokens == seen
    assert max(fed_b) <= len(p2) - seen

    # replica C: the uninterrupted control at the same seed
    sched_c = ServingScheduler(make_engine(), _tiered_config(tmp_path / "c"),
                               start=False)
    req3 = sched_c.submit(p2, max_new_tokens=6, temperature=temperature,
                          seed=9)
    _run_until(sched_c, lambda: req3.finished)
    assert req2.result() == req3.result()
    for s in (sched_a, sched_b, sched_c):
        s.stop(drain=False)


def test_park_on_eos_finish(make_engine, tmp_path):
    """An eos finish parks too (unlike a handoff): the next turn continues
    from the full history via the rehydrate prompt, no next_token needed."""
    # learn what greedy decode emits, then replay with that token as eos
    sched = ServingScheduler(make_engine(), _tiered_config(tmp_path / "x"),
                             start=False)
    probe = sched.submit(_prompt(8), max_new_tokens=2)
    _run_until(sched, lambda: probe.finished)
    eos = int(probe.tokens[1])
    sched.stop(drain=False)

    sched2 = ServingScheduler(make_engine(), _tiered_config(tmp_path / "y"),
                              start=False)
    req2 = sched2.submit(_prompt(8), max_new_tokens=40, park=True,
                         eos_token_id=eos)
    _run_until(sched2, lambda: req2.finished)
    assert req2.finish_reason == "eos"
    assert req2.park_payload is not None
    from deepspeed_tpu.inference.v2.ragged import handoff
    header, _ = handoff.unpack(req2.park_payload)
    assert header["version"] == handoff.PARK_VERSION
    assert header["extra"]["tier"]["v"] == handoff.TIER_FIELD_VERSION
    assert header["extra"]["tier"]["source"] == "device"
    assert "next_token" not in header["extra"]  # eos: not plain-resumable
    # the eos token is in the parked history (the rehydrate prompt builds on
    # the full visible conversation) but was never fed: seen = len - 1
    assert header["tokens"][-1] == eos
    assert header["seen_tokens"] == len(header["tokens"]) - 1
    sched2.stop(drain=False)


def test_rehydrate_prompt_must_extend_parked_history(make_engine, tmp_path):
    sched = ServingScheduler(make_engine(), _tiered_config(tmp_path),
                             start=False)
    p1 = _prompt(9)
    req = sched.submit(p1, max_new_tokens=4, park=True)
    _run_until(sched, lambda: req.finished)
    payload = req.park_payload
    parked = p1 + [int(t) for t in req.tokens]
    # same length (no new turn), a diverged prefix, and a shorter prompt all
    # fail loudly before any queue or engine work
    for bad in (parked,
                [t + 1 for t in parked] + [1, 2],
                parked[:-1]):
        with pytest.raises(ValueError, match="strictly extend"):
            sched.submit_resume(payload, prompt=bad)
    sched.stop(drain=False)


def test_unparked_resume_without_next_token_still_rejected(make_engine,
                                                           tmp_path):
    """The PR-16 contract survives: a plain resume (no rehydrate prompt) of
    an eos-finished export still needs next_token."""
    sched = ServingScheduler(make_engine(), _tiered_config(tmp_path),
                             start=False)
    req = sched.submit(_prompt(9), max_new_tokens=4, park=True)
    _run_until(sched, lambda: req.finished)
    pl = req.park_payload
    # strip next_token by re-parking an eos finish is covered above; here a
    # length finish DOES carry next_token, so a plain resume works
    req2 = sched.submit_resume(pl, max_new_tokens=2)
    _run_until(sched, lambda: req2.finished)
    assert req2.state is RequestState.DONE
    sched.stop(drain=False)


# ------------------------------------------------ pressure: demote ladder --
def _fill_trie(sched, n=4, toks=3):
    """Finish a few distinct requests so the prefix trie pins device blocks."""
    reqs = [sched.submit(_prompt(17, base=7 * i), max_new_tokens=toks)
            for i in range(n)]
    _run_until(sched, lambda: all(r.finished for r in reqs))
    return reqs


def test_evict_one_demotes_before_evicting(make_engine, tmp_path):
    """The eviction ladder's new first rung: KV pressure demotes a trie node
    (keeps its KV, host tier) before any leaf is discarded, and a later
    prompt hit promotes it back — served from cache, not recomputed."""
    cfg = _tiered_config(
        tmp_path, prefix_cache=PrefixCacheConfig(enabled=True),
        # isolate the eviction ladder: without this the brownout tick's
        # proactive demote stage relieves the pressure first
        overload=OverloadConfig(enabled=False))
    sched = ServingScheduler(make_engine(num_blocks=8), cfg, start=False)
    _fill_trie(sched, n=3)
    trie = sched._prefix_cache
    assert trie.n_blocks > 0
    evictions_before = sched._counters["prefix_evictions"]
    # a fat request forces pressure: the ladder must demote first
    big = sched.submit(_prompt(100, base=31), max_new_tokens=2)
    _run_until(sched, lambda: big.finished)
    assert big.state is RequestState.DONE
    assert sched._counters["tier_demotions"] > 0
    assert trie.tier_demotions > 0
    # demotion ran AHEAD of discarding: blocks moved down the ladder before
    # (possibly instead of) any leaf eviction
    assert sched._counters["tier_demotions"] >= \
        sched._counters["prefix_evictions"] - evictions_before or \
        sched._counters["prefix_evictions"] == evictions_before

    # demote everything idle, then re-run a cached prompt: acquire promotes
    # the demoted path back to device and serves the prompt from cache
    trie.demote(100)
    assert trie.offloaded_nodes > 0
    again = sched.submit(_prompt(17), max_new_tokens=2)
    _run_until(sched, lambda: again.finished)
    assert trie.tier_promotions > 0
    assert again.cached_tokens > 0
    assert sched.stats()["kv_tiers"]["enabled"] is True
    sched.stop(drain=False)


def _brownout_config(tmp_path, tiered):
    kv = (KVTierConfig(enabled=True, spill_dir=str(tmp_path), demote_batch=1)
          if tiered else KVTierConfig())
    return ServingConfig(
        kv_tiers=kv,
        prefix_cache=PrefixCacheConfig(enabled=True),
        queue_capacity=4,
        overload=OverloadConfig(
            brownout_stage_thresholds=(0.05, 0.85, 0.95),
            pressure_alpha=1.0, min_rate_samples=1,
            admission_control=False))


def _pressure_with_doomed_queue(sched):
    """Warm the rate estimator, pin trie blocks, queue deadline-doomed work
    and push the brownout to stage >= 1 — the setup in which a shed-enabled
    scheduler WOULD shed (the control arm proves it does)."""
    _fill_trie(sched, n=3)
    assert sched._prefix_cache.n_blocks > 0
    doomed = [sched.submit(_prompt(12, base=50 + i), max_new_tokens=64,
                           deadline_s=0.01) for i in range(3)]
    time.sleep(0.02)  # every queued deadline is now provably blown
    return doomed


def test_brownout_demotes_before_shedding(make_engine, tmp_path):
    """The brownout gate: while the demote ladder still has somewhere to put
    idle KV, pressure ticks demote instead of shedding — the shed counter
    stays ZERO while demotions occur. The identical setup WITHOUT tiering
    sheds immediately (the control arm proving the doomed queue is real)."""
    control = ServingScheduler(make_engine(num_blocks=16),
                               _brownout_config(tmp_path / "c", tiered=False),
                               start=False)
    _pressure_with_doomed_queue(control)
    control._overload_tick(time.monotonic())
    assert control._counters["shed_queue"] > 0  # the old behavior: shed
    control.stop(drain=False)

    sched = ServingScheduler(make_engine(num_blocks=16),
                             _brownout_config(tmp_path / "t", tiered=True),
                             start=False)
    doomed = _pressure_with_doomed_queue(sched)
    for _ in range(2):
        sched._overload_tick(time.monotonic())
    assert sched._counters["brownout_demotions"] > 0
    # the gate: no queued request was shed on any demoting tick
    assert sched._counters["shed_queue"] == 0
    assert all(not r.finished for r in doomed)
    for r in doomed:
        r.cancel()
    sched.stop(drain=False)


def test_tier_gauges_and_stats_block(make_engine, tmp_path):
    """/v1/stats carries the kv_tiers block; disabled schedulers carry None
    (the zero-cost-when-disabled contract)."""
    sched = ServingScheduler(make_engine(), _tiered_config(tmp_path),
                             start=False)
    doc = sched.stats()["kv_tiers"]
    assert doc["enabled"] is True
    assert doc["device_blocks_total"] > 0
    assert {"host_blocks", "disk_blocks", "demotions",
            "pressure_demotions"} <= set(doc)
    sched.stop(drain=False)

    plain = ServingScheduler(make_engine(), ServingConfig(), start=False)
    assert plain.stats()["kv_tiers"] is None
    plain.stop(drain=False)
