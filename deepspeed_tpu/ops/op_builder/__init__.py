"""Native-op builder registry (reference ``op_builder/__init__.py`` +
``all_ops.py``: the dict ``ds_report`` walks to print build compatibility).

Pallas/XLA compute ops need no build step; only runtime-tier native code
registers here.
"""

from deepspeed_tpu.ops.op_builder.async_io import AsyncIOBuilder
from deepspeed_tpu.ops.op_builder.builder import OpBuilder

ALL_OPS = {
    AsyncIOBuilder.NAME: AsyncIOBuilder,
}


def get_op_builder(name: str) -> OpBuilder:
    return ALL_OPS[name]()
