"""Learning-rate schedules.

Reference: ``deepspeed/runtime/lr_schedules.py`` (LRRangeTest:267, OneCycle:370,
WarmupLR:634, WarmupDecayLR:723, WarmupCosineLR:774). Each schedule is implemented
as a pure ``step -> lr`` function (jit-friendly, usable as an optax schedule) wrapped
in a stateful object with the reference's ``step()/get_lr()/state_dict()`` API.
"""

import math
from typing import List, Union

LR_SCHEDULE = "lr_schedule"
LR_RANGE_TEST = "LRRangeTest"
ONE_CYCLE = "OneCycle"
WARMUP_LR = "WarmupLR"
WARMUP_DECAY_LR = "WarmupDecayLR"
WARMUP_COSINE_LR = "WarmupCosineLR"
VALID_LR_SCHEDULES = [LR_RANGE_TEST, ONE_CYCLE, WARMUP_LR, WARMUP_DECAY_LR, WARMUP_COSINE_LR]

WARMUP_LOG_RATE = "log"
WARMUP_LINEAR_RATE = "linear"


class _LRSchedulerBase:
    """Stateful wrapper exposing the torch-style scheduler API over a pure fn."""

    def __init__(self, optimizer=None, last_batch_iteration=-1):
        self.optimizer = optimizer
        self.last_batch_iteration = last_batch_iteration

    def _lr_at(self, step: int) -> List[float]:
        raise NotImplementedError

    def get_lr(self) -> List[float]:
        return self._lr_at(max(0, self.last_batch_iteration))

    def get_last_lr(self) -> List[float]:
        assert getattr(self, "_last_lr", None) is not None, "need to call step() first"
        return self._last_lr

    def step(self, last_batch_iteration=None):
        if last_batch_iteration is None:
            last_batch_iteration = self.last_batch_iteration + 1
        self.last_batch_iteration = last_batch_iteration
        lrs = self.get_lr()
        if self.optimizer is not None and hasattr(self.optimizer, "set_lr"):
            self.optimizer.set_lr(lrs[0])
        self._last_lr = lrs
        return lrs

    def state_dict(self):
        return {"last_batch_iteration": self.last_batch_iteration}

    def load_state_dict(self, sd):
        self.last_batch_iteration = sd["last_batch_iteration"]

    def as_schedule_fn(self):
        """Return a pure ``step -> lr`` callable (optax-compatible)."""

        def fn(step):
            return self._lr_at(step)[0]

        return fn


class LRRangeTest(_LRSchedulerBase):
    """Reference lr_schedules.py:267 — LR range test (Smith 2017)."""

    def __init__(self,
                 optimizer=None,
                 lr_range_test_min_lr: Union[float, List[float]] = 1e-3,
                 lr_range_test_step_size: int = 2000,
                 lr_range_test_step_rate: float = 1.0,
                 lr_range_test_staircase: bool = False,
                 last_batch_iteration: int = -1):
        super().__init__(optimizer, last_batch_iteration)
        if lr_range_test_step_size <= 0:
            raise ValueError(f"Step size must be positive, got {lr_range_test_step_size}")
        self.min_lr = lr_range_test_min_lr if isinstance(lr_range_test_min_lr, list) else [lr_range_test_min_lr]
        self.step_size = lr_range_test_step_size
        self.step_rate = lr_range_test_step_rate
        self.staircase = lr_range_test_staircase

    def _lr_at(self, step):
        if self.staircase:
            interval = float(step // self.step_size)
        else:
            interval = step / self.step_size
        scale = 1.0 + self.step_rate * interval
        return [lr * scale for lr in self.min_lr]


class OneCycle(_LRSchedulerBase):
    """Reference lr_schedules.py:370 — 1-cycle LR (+ optional momentum cycle)."""

    def __init__(self,
                 optimizer=None,
                 cycle_min_lr: float = 0.001,
                 cycle_max_lr: float = 0.01,
                 decay_lr_rate: float = 0.0,
                 cycle_first_step_size: int = 2000,
                 cycle_second_step_size: int = None,
                 cycle_first_stair_count: int = 0,
                 cycle_second_stair_count: int = None,
                 decay_step_size: int = 0,
                 cycle_momentum: bool = True,
                 cycle_min_mom: float = 0.8,
                 cycle_max_mom: float = 0.9,
                 decay_mom_rate: float = 0.0,
                 last_batch_iteration: int = -1):
        super().__init__(optimizer, last_batch_iteration)
        self.cycle_min_lr = cycle_min_lr
        self.cycle_max_lr = cycle_max_lr
        self.decay_lr_rate = decay_lr_rate
        self.first_step_size = cycle_first_step_size
        self.second_step_size = cycle_second_step_size if cycle_second_step_size is not None else cycle_first_step_size
        self.decay_step_size = decay_step_size
        self.total_cycle_size = self.first_step_size + self.second_step_size
        self.cycle_momentum = cycle_momentum
        self.cycle_min_mom = cycle_min_mom
        self.cycle_max_mom = cycle_max_mom
        self.decay_mom_rate = decay_mom_rate

    def _lr_at(self, step):
        if step < self.total_cycle_size:
            if step < self.first_step_size:
                frac = step / self.first_step_size
                lr = self.cycle_min_lr + (self.cycle_max_lr - self.cycle_min_lr) * frac
            else:
                frac = (step - self.first_step_size) / self.second_step_size
                lr = self.cycle_max_lr - (self.cycle_max_lr - self.cycle_min_lr) * frac
            return [lr]
        # decay phase
        decay_steps = step - self.total_cycle_size + 1
        if self.decay_step_size > 0:
            intervals = decay_steps / self.decay_step_size
        else:
            intervals = decay_steps
        lr = self.cycle_min_lr / (1.0 + self.decay_lr_rate * intervals)
        return [lr]

    def get_mom(self):
        step = max(0, self.last_batch_iteration)
        if not self.cycle_momentum:
            return None
        if step < self.total_cycle_size:
            if step < self.first_step_size:
                frac = step / self.first_step_size
                mom = self.cycle_max_mom - (self.cycle_max_mom - self.cycle_min_mom) * frac
            else:
                frac = (step - self.first_step_size) / self.second_step_size
                mom = self.cycle_min_mom + (self.cycle_max_mom - self.cycle_min_mom) * frac
            return [mom]
        decay_steps = step - self.total_cycle_size + 1
        if self.decay_step_size > 0:
            intervals = decay_steps / self.decay_step_size
        else:
            intervals = decay_steps
        return [self.cycle_max_mom * (1.0 + self.decay_mom_rate * intervals)]


class WarmupLR(_LRSchedulerBase):
    """Reference lr_schedules.py:634 — warmup to base lr then hold."""

    def __init__(self,
                 optimizer=None,
                 warmup_min_lr: float = 0.0,
                 warmup_max_lr: float = 0.001,
                 warmup_num_steps: int = 1000,
                 warmup_type: str = WARMUP_LOG_RATE,
                 last_batch_iteration: int = -1):
        super().__init__(optimizer, last_batch_iteration)
        self.min_lrs = [warmup_min_lr] if not isinstance(warmup_min_lr, list) else warmup_min_lr
        self.max_lrs = [warmup_max_lr] if not isinstance(warmup_max_lr, list) else warmup_max_lr
        self.delta_lrs = [big - small for big, small in zip(self.max_lrs, self.min_lrs)]
        self.warmup_num_steps = max(2, warmup_num_steps)
        if warmup_type not in (WARMUP_LOG_RATE, WARMUP_LINEAR_RATE):
            raise ValueError(f"warmup_type {warmup_type} not supported")
        self.warmup_type = warmup_type
        self.inverse_log_warm_up = 1.0 / math.log(self.warmup_num_steps)

    def _get_gamma(self, step):
        if step < self.warmup_num_steps:
            if self.warmup_type == WARMUP_LOG_RATE:
                return self.inverse_log_warm_up * math.log(step + 1)
            return min(1.0, step / self.warmup_num_steps)
        return 1.0

    def _lr_at(self, step):
        gamma = self._get_gamma(step)
        return [min_lr + gamma * delta for min_lr, delta in zip(self.min_lrs, self.delta_lrs)]


class WarmupDecayLR(WarmupLR):
    """Reference lr_schedules.py:723 — warmup then linear decay to 0."""

    def __init__(self,
                 optimizer=None,
                 total_num_steps: int = 10000,
                 warmup_min_lr: float = 0.0,
                 warmup_max_lr: float = 0.001,
                 warmup_num_steps: int = 1000,
                 warmup_type: str = WARMUP_LOG_RATE,
                 last_batch_iteration: int = -1):
        self.total_num_steps = total_num_steps
        super().__init__(optimizer, warmup_min_lr, warmup_max_lr, warmup_num_steps, warmup_type,
                         last_batch_iteration)
        if self.total_num_steps < self.warmup_num_steps:
            raise ValueError(f"total_num_steps {total_num_steps} is less than warmup_num_steps {warmup_num_steps}")

    def _get_gamma(self, step):
        if step < self.warmup_num_steps:
            return super()._get_gamma(step)
        return max(
            0.0,
            float(self.total_num_steps - step) / float(max(1.0, self.total_num_steps - self.warmup_num_steps)))


class WarmupCosineLR(_LRSchedulerBase):
    """Reference lr_schedules.py:774 — linear warmup then cosine decay."""

    def __init__(self,
                 optimizer=None,
                 total_num_steps: int = 10000,
                 warmup_min_ratio: float = 0.0,
                 warmup_num_steps: int = 1000,
                 cos_min_ratio: float = 0.0001,
                 last_batch_iteration: int = -1):
        super().__init__(optimizer, last_batch_iteration)
        self.total_num_steps = total_num_steps
        self.warmup_min_ratio = warmup_min_ratio
        self.warmup_num_steps = max(2, warmup_num_steps)
        self.cos_min_ratio = cos_min_ratio
        self.base_lr = 1.0  # ratios multiply the optimizer's base lr
        if optimizer is not None and hasattr(optimizer, "get_lr"):
            self.base_lr = optimizer.get_lr()

    def _get_ratio(self, step):
        if step < self.warmup_num_steps:
            frac = step / self.warmup_num_steps
            return self.warmup_min_ratio + (1.0 - self.warmup_min_ratio) * frac
        frac = (step - self.warmup_num_steps) / max(1, self.total_num_steps - self.warmup_num_steps)
        frac = min(1.0, frac)
        cos = 0.5 * (1.0 + math.cos(math.pi * frac))
        return self.cos_min_ratio + (1.0 - self.cos_min_ratio) * cos

    def _lr_at(self, step):
        return [self.base_lr * self._get_ratio(step)]


_SCHEDULES = {
    LR_RANGE_TEST: LRRangeTest,
    ONE_CYCLE: OneCycle,
    WARMUP_LR: WarmupLR,
    WARMUP_DECAY_LR: WarmupDecayLR,
    WARMUP_COSINE_LR: WarmupCosineLR,
}


def get_lr_schedule_class(name: str):
    if name not in _SCHEDULES:
        raise ValueError(f"{name} is not a valid LR schedule; valid: {VALID_LR_SCHEDULES}")
    return _SCHEDULES[name]


def add_tuning_arguments(parser):
    """Reference lr_schedules.py argparse integration (subset)."""
    group = parser.add_argument_group("Convergence Tuning")
    group.add_argument("--lr_schedule", type=str, default=None)
    group.add_argument("--lr_range_test_min_lr", type=float, default=0.001)
    group.add_argument("--lr_range_test_step_size", type=int, default=1000)
    group.add_argument("--lr_range_test_step_rate", type=float, default=1.0)
    group.add_argument("--lr_range_test_staircase", type=bool, default=False)
    group.add_argument("--cycle_min_lr", type=float, default=0.001)
    group.add_argument("--cycle_max_lr", type=float, default=0.01)
    group.add_argument("--cycle_first_step_size", type=int, default=2000)
    group.add_argument("--decay_lr_rate", type=float, default=0.0)
    group.add_argument("--warmup_min_lr", type=float, default=0)
    group.add_argument("--warmup_max_lr", type=float, default=0.001)
    group.add_argument("--warmup_num_steps", type=int, default=1000)
    group.add_argument("--warmup_type", type=str, default="log")
    return parser


def parse_arguments():
    """Reference lr_schedules.py:124 — (known LR args, the rest)."""
    import argparse
    parser = add_tuning_arguments(argparse.ArgumentParser())
    return parser.parse_known_args()


def get_config_from_args(args):
    """Reference lr_schedules.py:208 — a scheduler config block from argparse
    flags; returns (config, error_string)."""
    if not getattr(args, "lr_schedule", None):
        return None, "--lr_schedule not specified on command line"
    if args.lr_schedule not in VALID_LR_SCHEDULES:
        return None, f"{args.lr_schedule} is not supported LR schedule"
    # only flags the chosen scheduler actually accepts (each class has its own
    # parameter vocabulary — WarmupCosineLR takes ratios, not warmup_*_lr)
    import inspect
    accepted = set(inspect.signature(_SCHEDULES[args.lr_schedule].__init__).parameters)
    params = {k: v for k, v in vars(args).items()
              if k in accepted and v is not None and k != "lr_schedule"}
    return {"type": args.lr_schedule, "params": params}, None


def get_lr_from_config(config):
    """Reference lr_schedules.py — the schedule's peak/base LR; returns
    (lr, explanation)."""
    if "type" not in config:
        return None, "LR schedule type not defined in config"
    params = config.get("params", {})
    stype = config["type"]
    if stype not in VALID_LR_SCHEDULES:
        return None, f"{stype} is not a valid LR schedule"
    if stype == "LRRangeTest":
        return params.get("lr_range_test_min_lr", 0.001), "LR range test minimum"
    if stype == "OneCycle":
        return params.get("cycle_max_lr", 0.001), "OneCycle maximum"
    return params.get("warmup_max_lr", 0.001), "warmup maximum"
