"""The stdlib HTTP exporter: /metrics, /healthz, /trace."""

import json
import urllib.error
import urllib.request

import pytest

from deepspeed_tpu.telemetry import (MetricsRegistry, SpanRecorder, parse_prometheus_text,
                                     scrape_metrics, start_http_server)


@pytest.fixture
def server():
    reg = MetricsRegistry()
    reg.counter("hits_total", "hits").inc(3)
    reg.gauge("free", "free").set(12)
    spans = SpanRecorder()
    spans.record("phase", cat="test", ts_us=1, dur_us=2)
    srv = start_http_server(reg, spans=spans, host="127.0.0.1", port=0)
    yield srv
    srv.stop()


def _get(url):
    with urllib.request.urlopen(url, timeout=5) as resp:
        return resp.status, resp.read().decode()


def test_metrics_endpoint(server):
    status, body = _get(server.url + "/metrics")
    assert status == 200
    fams = parse_prometheus_text(body)
    assert fams["hits_total"]["samples"][0][2] == 3.0
    assert fams["free"]["samples"][0][2] == 12.0


def test_healthz_endpoint(server):
    status, body = _get(server.url + "/healthz")
    assert status == 200
    assert json.loads(body) == {"status": "ok"}


def test_trace_endpoint_serves_chrome_trace(server):
    status, body = _get(server.url + "/trace")
    assert status == 200
    trace = json.loads(body)
    assert trace["traceEvents"][0]["name"] == "phase"


def test_unknown_route_404(server):
    with pytest.raises(urllib.error.HTTPError) as exc:
        _get(server.url + "/nope")
    assert exc.value.code == 404


def test_scrape_metrics_helper(server):
    host, port = server.address
    # bare host:port → /metrics appended; http://... /metrics passthrough
    for url in (f"{host}:{port}", server.url + "/metrics"):
        fams = scrape_metrics(url)
        assert fams["hits_total"]["samples"][0][2] == 3.0
