"""Prefix-cache mechanism units: reference-counted BlockedAllocator, the
radix/trie index (``ragged/prefix_cache.py``), and copy-on-write block forks
(``kv_cache.fork_blocks``) — the layers below the serving integration
(tests/unit/serving/test_prefix_cache.py)."""

import numpy as np
import pytest

from deepspeed_tpu.inference.v2.ragged.blocked_allocator import BlockedAllocator
from deepspeed_tpu.inference.v2.ragged.kv_cache import BlockedKVCache
from deepspeed_tpu.inference.v2.ragged.manager_configs import (AllocationMode,
                                                               KVCacheConfig,
                                                               MemoryConfig)
from deepspeed_tpu.inference.v2.ragged.prefix_cache import PrefixCache

BS = 4  # tiny block size: tests spell out block boundaries


# ---------------------------------------------------------------- allocator --
class TestRefcountedAllocator:

    def test_allocate_free_roundtrip_unshared(self):
        a = BlockedAllocator(8)
        blocks = a.allocate(3)
        assert a.free_blocks == 5
        assert all(a.ref_count(b) == 1 for b in blocks)
        a.free(blocks)
        assert a.free_blocks == 8

    def test_shared_block_survives_first_free(self):
        a = BlockedAllocator(4)
        (b, ) = a.allocate(1)
        a.incref([b])
        assert a.ref_count(b) == 2
        a.free([b])
        assert a.free_blocks == 3  # still held by the second reference
        a.free([b])
        assert a.free_blocks == 4

    def test_double_free_raises(self):
        a = BlockedAllocator(4)
        (b, ) = a.allocate(1)
        a.free([b])
        with pytest.raises(ValueError, match="double free"):
            a.free([b])
        assert a.free_blocks == 4  # the failed free corrupted nothing

    def test_incref_of_free_block_raises(self):
        a = BlockedAllocator(4)
        with pytest.raises(ValueError, match="not allocated"):
            a.incref([0])

    def test_freed_shared_block_is_not_reissued_while_referenced(self):
        a = BlockedAllocator(2)
        (b, ) = a.allocate(1)
        a.incref([b])
        a.free([b])
        other = a.allocate(1)  # must NOT hand back b
        assert int(other[0]) != int(b)


# -------------------------------------------------------------------- trie --
@pytest.fixture
def kv():
    cfg = KVCacheConfig(block_size=BS, cache_shape=(1, 1, 8), cache_dtype="float32")
    return BlockedKVCache(cfg, MemoryConfig(mode=AllocationMode.ALLOCATE, size=32))


def _alloc_seq(kv, tokens):
    """Simulate a finished sequence: one block per BS tokens, all committed."""
    n = (len(tokens) + BS - 1) // BS
    return kv.reserve(n)


class TestRadixIndex:

    def test_publish_then_match_longest_prefix(self, kv):
        pc = PrefixCache(kv)
        toks = np.arange(10)  # 2 full blocks + a 2-token tail
        blocks = _alloc_seq(kv, toks)
        assert pc.publish(toks, blocks, committed_tokens=10) == 2
        kv.free(blocks)  # "sequence flushed"; trie refs keep the 2 full blocks

        hit = pc.acquire(np.arange(9))
        assert hit.tokens == 2 * BS
        assert hit.blocks == [int(blocks[0]), int(blocks[1])]
        pc.release(hit.blocks)

    def test_divergent_block_does_not_match(self, kv):
        pc = PrefixCache(kv)
        toks = np.arange(8)
        blocks = _alloc_seq(kv, toks)
        pc.publish(toks, blocks, committed_tokens=8)
        kv.free(blocks)
        other = np.concatenate([np.arange(4), [99, 99, 99, 99]])
        hit = pc.acquire(other)
        assert hit.tokens == BS and len(hit.blocks) == 1  # first block only
        pc.release(hit.blocks)
        # chained hashing: same tokens in block 1 under a DIFFERENT block 0
        # must not match block 1's node
        shifted = np.concatenate([[99] * 4, np.arange(4, 8)])
        assert pc.acquire(shifted).tokens == 0

    def test_min_prefix_blocks_gates_short_hits(self, kv):
        pc = PrefixCache(kv, min_prefix_blocks=2)
        toks = np.arange(4)
        blocks = _alloc_seq(kv, toks)
        pc.publish(toks, blocks, committed_tokens=4)
        kv.free(blocks)
        assert pc.acquire(toks).tokens == 0  # 1-block match < min
        assert pc.stats()["hits"] == 0

    def test_committed_cap_excludes_overrun_blocks(self, kv):
        pc = PrefixCache(kv)
        toks = np.arange(8)
        blocks = _alloc_seq(kv, toks)
        # only 5 positions hold kept-token KV: block 1 must not be indexed
        assert pc.publish(toks, blocks, committed_tokens=5) == 1
        assert pc.n_blocks == 1

    def test_eviction_skips_chains_shared_by_live_sequences(self, kv):
        pc = PrefixCache(kv)
        a = np.arange(8)           # blocks A0, A1
        b = np.arange(100, 108)    # blocks B0, B1
        ba, bb = _alloc_seq(kv, a), _alloc_seq(kv, b)
        pc.publish(a, ba, 8)
        pc.publish(b, bb, 8)
        kv.free(ba)
        kv.free(bb)
        hit = pc.acquire(a)       # a live sequence shares A's chain
        assert pc.evict(10) == 2  # only B's chain is evictable (leaf-first)
        assert pc.n_blocks == 2
        assert pc.acquire(b).tokens == 0  # B gone, A intact
        pc.release(hit.blocks)
        assert pc.evict(10) == 2  # A's chain now unwinds too
        assert kv.free_blocks == kv.num_blocks

    def test_eviction_is_lru_ordered(self, kv):
        pc = PrefixCache(kv)
        a, b = np.arange(4), np.arange(100, 104)
        ba = _alloc_seq(kv, a)
        pc.publish(a, ba, 4)
        kv.free(ba)
        bb = _alloc_seq(kv, b)
        pc.publish(b, bb, 4)
        kv.free(bb)
        pc.release(pc.acquire(a).blocks)  # touch A: B becomes LRU
        assert pc.evict(1) == 1
        assert pc.acquire(b).tokens == 0  # the LRU chain (B) was the victim
        hit = pc.acquire(a)
        assert hit.tokens == 4
        pc.release(hit.blocks)

    def test_shared_leaves_are_not_evictable(self, kv):
        pc = PrefixCache(kv)
        toks = np.arange(8)
        blocks = _alloc_seq(kv, toks)
        pc.publish(toks, blocks, 8)
        kv.free(blocks)
        hit = pc.acquire(toks)  # a "live sequence" shares both blocks
        assert pc.evict(4) == 0  # nothing evictable: freeing reclaims nothing
        pc.release(hit.blocks)
        assert pc.evict(4) == 2  # now the whole chain unwinds leaf-first
        assert kv.free_blocks == kv.num_blocks

    def test_max_blocks_cap_evicts_lru_to_publish(self, kv):
        pc = PrefixCache(kv, max_blocks=2)
        a = np.arange(8)
        ba = _alloc_seq(kv, a)
        pc.publish(a, ba, 8)
        kv.free(ba)
        b = np.arange(100, 108)
        bb = _alloc_seq(kv, b)
        assert pc.publish(b, bb, 8) == 2  # evicted A's chain to make room
        kv.free(bb)
        assert pc.n_blocks == 2
        assert pc.acquire(a).tokens == 0
        hit = pc.acquire(b)
        assert hit.tokens == 8
        pc.release(hit.blocks)

    def test_publish_at_cap_never_evicts_its_own_walk_path(self, kv):
        """A capped trie asked to extend a matched chain must not evict the
        node the walk is standing on (the only evictable leaf): it stops
        indexing instead of attaching children to a detached parent."""
        pc = PrefixCache(kv, max_blocks=1)
        a = np.arange(4)
        ba = _alloc_seq(kv, a)
        pc.publish(a, ba, 4)
        kv.free(ba)
        extended = np.arange(8)  # block 0 matches the cached chain
        bb = _alloc_seq(kv, extended)
        assert pc.publish(extended, bb, 8) == 0  # no room that isn't the spine
        kv.free(bb)
        assert pc.n_blocks == 1
        hit = pc.acquire(extended)
        assert hit.tokens == BS  # the original chain is intact and reachable
        pc.release(hit.blocks)

    def test_clear_releases_only_trie_refs(self, kv):
        pc = PrefixCache(kv)
        toks = np.arange(8)
        blocks = _alloc_seq(kv, toks)
        pc.publish(toks, blocks, 8)
        hit = pc.acquire(toks)  # simulated live sequence
        kv.free(blocks)         # publisher flushed
        pc.clear()
        assert pc.n_blocks == 0
        assert kv.free_blocks == kv.num_blocks - 2  # the live sharer holds on
        kv.free(hit.blocks)
        assert kv.free_blocks == kv.num_blocks


# --------------------------------------------------------------------- cow --
def test_fork_blocks_copies_content_and_isolates_writes(kv):
    import jax.numpy as jnp

    (src, ) = kv.reserve(1)
    cache = kv.cache.at[:, :, src].set(7.0)
    kv.set_cache(cache)
    (dst, ) = kv.fork_blocks([src])
    assert dst != src
    assert kv.ref_count(dst) == 1
    np.testing.assert_array_equal(np.asarray(kv.cache[:, :, dst]),
                                  np.asarray(kv.cache[:, :, src]))
    # a write through the fork leaves the source untouched
    kv.set_cache(kv.cache.at[:, :, dst].set(9.0))
    assert float(jnp.max(jnp.abs(kv.cache[:, :, src] - 7.0))) == 0.0


def test_fork_blocks_pool_exhausted_consumes_nothing(kv):
    blocks = kv.reserve(kv.num_blocks)
    with pytest.raises(ValueError):
        kv.fork_blocks([int(blocks[0])])
    kv.free(blocks)
    assert kv.free_blocks == kv.num_blocks
