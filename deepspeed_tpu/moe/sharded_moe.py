"""MoE gating + dispatch math.

Reference: ``deepspeed/moe/sharded_moe.py`` (TopKGate:348, top1gating:184 with
capacity + random token selection, top2gating:282, MOELayer:425, _AllToAll:95).

TPU-native formulation: gating is pure jnp (einsum dispatch/combine masks exactly as
the reference computes them), and expert parallelism is expressed with
``with_sharding_constraint`` over the ``expert`` mesh axis — GSPMD inserts the two
variable all-to-alls the reference issues explicitly (dispatch and return), and
overlaps them with the expert GEMMs.
"""

import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from deepspeed_tpu.utils import groups

def multiplicative_jitter(x, rng, epsilon=1e-2):
    """Reference sharded_moe.py multiplicative_jitter: noise in [1-eps, 1+eps]."""
    if epsilon == 0:
        return x
    u = jax.random.uniform(rng, x.shape, dtype=x.dtype, minval=1.0 - epsilon, maxval=1.0 + epsilon)
    return x * u


def gumbel_rsample(shape, rng, dtype=jnp.float32):
    return jax.random.gumbel(rng, shape, dtype=dtype)


def _capacity(num_tokens: int, num_experts: int, capacity_factor: float, min_capacity: int) -> int:
    """Reference sharded_moe.py _capacity: ceil(tokens/experts * factor), floored
    at min_capacity. Static on TPU (shapes must be compile-time constants)."""
    capacity = math.ceil((num_tokens / num_experts) * capacity_factor)
    return max(capacity, min_capacity)


def _one_hot(indices, num_classes, dtype=jnp.float32):
    return jax.nn.one_hot(indices, num_classes, dtype=dtype)


def top1gating(logits: jnp.ndarray,
               capacity_factor: float = 1.0,
               min_capacity: int = 4,
               used_token: Optional[jnp.ndarray] = None,
               noisy_gate_policy: Optional[str] = None,
               rng: Optional[jnp.ndarray] = None,
               drop_tokens: bool = True,
               use_rts: bool = True):
    """Top-1 gating (reference top1gating:184).

    Returns (l_aux, combine_weights [S,E,C], dispatch_mask [S,E,C], exp_counts).
    """
    S, E = logits.shape
    capacity = _capacity(S, E, capacity_factor, min_capacity)
    if not drop_tokens:
        # grow capacity to fit every token (reference drop_tokens=False path does a
        # max over exp_counts; static shapes force worst-case S here)
        capacity = S

    if noisy_gate_policy == "RSample":
        assert rng is not None, "RSample noisy gating needs an rng"
        logits_w_noise = logits + gumbel_rsample(logits.shape, rng, dtype=logits.dtype)
    else:
        logits_w_noise = logits

    gates = jax.nn.softmax(logits, axis=1)
    indices1_s = jnp.argmax(logits_w_noise if noisy_gate_policy == "RSample" else gates, axis=1)
    mask1 = _one_hot(indices1_s, E)
    if used_token is not None:
        mask1 = mask1 * used_token[:, None]

    exp_counts = jnp.sum(mask1, axis=0)

    # aux loss (reference: me*ce*E)
    me = jnp.mean(gates, axis=0)
    ce = jnp.mean(mask1, axis=0)
    l_aux = jnp.sum(me * ce) * E

    # random token selection: prioritize tokens by uniform score within expert;
    # with no rng (eval / inference) fall back to positional priority — the
    # reference uses torch's implicit generator, which has no analog here
    if use_rts and rng is not None:
        mask1_rand = mask1 * jax.random.uniform(jax.random.fold_in(rng, 1), mask1.shape, dtype=mask1.dtype)
    else:
        mask1_rand = mask1

    # position of each token within its expert's queue, ordered by priority
    top_idx = jnp.argsort(-mask1_rand, axis=0)  # [S, E] token order per expert
    rank_in_expert = jnp.argsort(top_idx, axis=0)  # inverse perm: priority rank
    locations1 = jnp.where(mask1 > 0, rank_in_expert.astype(jnp.float32), 0.0)
    keep = (rank_in_expert < capacity).astype(mask1.dtype)
    mask1 = mask1 * keep

    locations1_s = jnp.sum(locations1 * mask1, axis=1).astype(jnp.int32)

    gates1_s = jnp.sum(gates * mask1, axis=1)  # gate value if kept else 0
    locations1_sc = _one_hot(locations1_s, capacity)
    combine_weights = gates1_s[:, None, None] * mask1[:, :, None] * locations1_sc[:, None, :]
    dispatch_mask = (combine_weights > 0).astype(logits.dtype)
    return l_aux, combine_weights, dispatch_mask, exp_counts


def top2gating(logits: jnp.ndarray,
               capacity_factor: float = 1.0,
               min_capacity: int = 4,
               rng: Optional[jnp.ndarray] = None):
    """Top-2 gating (reference top2gating:282, GShard algorithm)."""
    S, E = logits.shape
    capacity = _capacity(S, E, 2 * capacity_factor, min_capacity)

    gates = jax.nn.softmax(logits, axis=1)
    indices1_s = jnp.argmax(gates, axis=1)
    mask1 = _one_hot(indices1_s, E)

    logits_w_noise = logits + (gumbel_rsample(logits.shape, rng, dtype=logits.dtype) if rng is not None else 0.0)
    logits_except1 = jnp.where(mask1.astype(bool), -jnp.inf, logits_w_noise)
    indices2_s = jnp.argmax(logits_except1, axis=1)
    mask2 = _one_hot(indices2_s, E)

    locations1 = jnp.cumsum(mask1, axis=0) - 1
    locations2 = jnp.cumsum(mask2, axis=0) - 1 + jnp.sum(mask1, axis=0, keepdims=True)

    exp_counts = jnp.sum(mask1 + mask2, axis=0)

    me = jnp.mean(gates, axis=0)
    ce = jnp.mean(mask1, axis=0)
    l_aux = jnp.mean(me * ce) * E * E

    mask1 = mask1 * (locations1 < capacity)
    mask2 = mask2 * (locations2 < capacity)

    locations1_s = jnp.sum(locations1 * mask1, axis=1).astype(jnp.int32)
    locations2_s = jnp.sum(locations2 * mask2, axis=1).astype(jnp.int32)

    # normalize gate values of the two selected experts
    gates1_s = jnp.sum(gates * mask1, axis=1)
    gates2_s = jnp.sum(gates * mask2, axis=1)
    denom = gates1_s + gates2_s
    denom = jnp.where(denom < jnp.finfo(denom.dtype).eps, 1.0, denom)
    gates1_s = gates1_s / denom
    gates2_s = gates2_s / denom

    combine1 = gates1_s[:, None, None] * mask1[:, :, None] * _one_hot(locations1_s, capacity)[:, None, :]
    combine2 = gates2_s[:, None, None] * mask2[:, :, None] * _one_hot(locations2_s, capacity)[:, None, :]
    combine_weights = combine1 + combine2
    dispatch_mask = (combine_weights > 0).astype(logits.dtype)
    return l_aux, combine_weights, dispatch_mask, exp_counts


class TopKGate:
    """Reference TopKGate:348 — functional form: call with (wg, x, rng)."""

    def __init__(self,
                 model_dim: int,
                 num_experts: int,
                 k: int = 1,
                 capacity_factor: float = 1.0,
                 eval_capacity_factor: float = 1.0,
                 min_capacity: int = 8,
                 noisy_gate_policy: Optional[str] = None,
                 drop_tokens: bool = True,
                 use_rts: bool = True,
                 top2_2nd_expert_sampling: bool = True):
        if k not in (1, 2):
            raise ValueError("Only top-1 and top-2 gatings are supported.")
        self.model_dim = model_dim
        self.num_experts = num_experts
        self.k = k
        self.capacity_factor = capacity_factor
        self.eval_capacity_factor = eval_capacity_factor
        self.min_capacity = min_capacity
        self.noisy_gate_policy = noisy_gate_policy
        self.drop_tokens = drop_tokens
        self.use_rts = use_rts
        self.top2_2nd_expert_sampling = top2_2nd_expert_sampling

    def __call__(self, wg: jnp.ndarray, x: jnp.ndarray, rng=None, used_token=None, training=True):
        x_fp32 = x.astype(jnp.float32)
        if self.noisy_gate_policy == "Jitter" and rng is not None and training:
            x_fp32 = multiplicative_jitter(x_fp32, rng)
        logits = x_fp32 @ wg.astype(jnp.float32)
        cf = self.capacity_factor if training else self.eval_capacity_factor
        if self.k == 1:
            return top1gating(logits, cf, self.min_capacity, used_token,
                              self.noisy_gate_policy if training else None, rng,
                              self.drop_tokens, self.use_rts)
        return top2gating(logits, cf, self.min_capacity,
                          rng if (training and self.top2_2nd_expert_sampling) else None)


def moe_dispatch_combine(x: jnp.ndarray,
                         combine_weights: jnp.ndarray,
                         dispatch_mask: jnp.ndarray,
                         expert_fn,
                         expert_axis: str = groups.EXPERT_AXIS,
                         mesh=None):
    """Dispatch → expert compute → combine (reference MOELayer.forward:477-554).

    x: [S, M]; combine/dispatch: [S, E, C]. ``expert_fn(inputs[E, C, M]) -> [E, C, M]``
    applies the per-expert FFN (vmapped over the expert dim, whose parameters are
    sharded over the expert axis). The sharding constraints around expert_fn force
    the [E, C, M] buffers onto the expert axis — GSPMD materializes the dispatch
    and return all-to-alls of the reference's _AllToAll autograd fn.
    """
    from deepspeed_tpu.sequence.layer import _constrain

    def expert_sharded(t):
        return _constrain(t, (expert_axis, ) + (None, ) * (t.ndim - 1), mesh)

    dispatched = jnp.einsum("sec,sm->ecm", dispatch_mask, x)
    dispatched = expert_sharded(dispatched)
    expert_out = expert_fn(dispatched)
    expert_out = expert_sharded(expert_out)
    combined = jnp.einsum("sec,ecm->sm", combine_weights.astype(x.dtype), expert_out.astype(x.dtype))
    return combined
