"""GPT-2 causal LM (milestone config #2: GPT-2-125M SFT, BASELINE.md).

Reference exercises GPT-2 through HF injection policies
(``deepspeed/module_inject/containers/gpt2.py``); here it is a native flax model.
"""

from dataclasses import dataclass
from functools import partial

import flax.linen as nn
import jax
import jax.numpy as jnp

from deepspeed_tpu.models.llama import causal_attention, cross_entropy_loss
from deepspeed_tpu.utils import groups


@dataclass(frozen=True)
class GPT2Config:
    vocab_size: int = 50257
    n_positions: int = 1024
    n_embd: int = 768
    n_layer: int = 12
    n_head: int = 12
    layer_norm_epsilon: float = 1e-5
    dtype: jnp.dtype = jnp.bfloat16
    remat: bool = False

    @staticmethod
    def gpt2_125m(**kw):
        return GPT2Config(**kw)

    @staticmethod
    def tiny(**kw):
        return GPT2Config(vocab_size=256, n_positions=64, n_embd=64, n_layer=2, n_head=4, **kw)


class GPT2Block(nn.Module):
    cfg: GPT2Config

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        H = cfg.n_head
        D = cfg.n_embd // H
        B, S, _ = x.shape
        dense = partial(nn.Dense, dtype=cfg.dtype)

        h = nn.LayerNorm(epsilon=cfg.layer_norm_epsilon, dtype=cfg.dtype, name="ln_1")(x)
        qkv = dense(3 * cfg.n_embd, name="c_attn")(h)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(B, S, H, D)
        k = k.reshape(B, S, H, D)
        v = v.reshape(B, S, H, D)
        attn = causal_attention(q, k, v, scale=1.0 / (D**0.5)).reshape(B, S, cfg.n_embd)
        x = x + dense(cfg.n_embd, name="c_proj")(attn)

        h = nn.LayerNorm(epsilon=cfg.layer_norm_epsilon, dtype=cfg.dtype, name="ln_2")(x)
        h = dense(4 * cfg.n_embd, name="c_fc")(h)
        h = nn.gelu(h)
        x = x + dense(cfg.n_embd, name="mlp_c_proj")(h)
        return x


def _gpt2_logits(cfg: GPT2Config, input_ids):
    """Shared trunk (called inside @nn.compact): every submodule is explicitly
    named, so GPT2LMHeadModel and GPT2Model expose the SAME parameter tree —
    one converted checkpoint serves training and inference."""
    S = input_ids.shape[1]
    wte = nn.Embed(cfg.vocab_size, cfg.n_embd, dtype=cfg.dtype, name="wte")
    x = wte(input_ids)
    pos = nn.Embed(cfg.n_positions, cfg.n_embd, dtype=cfg.dtype, name="wpe")(jnp.arange(S)[None])
    x = x + pos
    block = nn.remat(GPT2Block) if cfg.remat else GPT2Block
    for i in range(cfg.n_layer):
        x = block(cfg, name=f"h_{i}")(x)
    x = nn.LayerNorm(epsilon=cfg.layer_norm_epsilon, dtype=cfg.dtype, name="ln_f")(x)
    return wte.attend(x.astype(jnp.float32))  # tied embeddings


class GPT2LMHeadModel(nn.Module):
    cfg: GPT2Config

    @nn.compact
    def __call__(self, batch):
        input_ids, labels = batch
        return cross_entropy_loss(_gpt2_logits(self.cfg, input_ids), labels)


class GPT2Model(nn.Module):
    """Logits-returning module over the shared trunk."""
    cfg: GPT2Config

    @nn.compact
    def __call__(self, input_ids):
        return _gpt2_logits(self.cfg, input_ids)


def init_params(cfg: GPT2Config, rng=None, batch_size=1, seq_len=16):
    model = GPT2LMHeadModel(cfg)
    rng = rng if rng is not None else jax.random.PRNGKey(0)
    ids = jnp.zeros((batch_size, seq_len), jnp.int32)
    return model, model.init(rng, (ids, ids))["params"]
