"""Pipeline-parallel module description.

Reference: ``deepspeed/runtime/pipe/module.py`` (LayerSpec:30, TiedLayerSpec,
PipelineModule:86 with ``_partition_layers:370`` supporting uniform and
parameter-balanced partitioning).

TPU execution model: a PipelineModule describes the model as a flat sequence of
layer callables. The engine stacks the *homogeneous* middle layers into a single
leading-dim parameter bank sharded over the ``pipe`` mesh axis; each stage scans
its local slice (pipe/engine.py). Partitioning methods (uniform / by parameters)
decide the stage boundaries exactly as in the reference.
"""

from dataclasses import dataclass, field
from typing import Any, Callable, List, Optional

import numpy as np

from deepspeed_tpu.utils.logging import logger


class LayerSpec:
    """Reference module.py:30 — a lazily-built layer: class + ctor args."""

    def __init__(self, typename, *module_args, **module_kwargs):
        self.typename = typename
        self.module_args = module_args
        self.module_kwargs = module_kwargs
        if not issubclass(typename, object):
            raise RuntimeError("LayerSpec only supports classes")

    def build(self, log=False):
        if log:
            logger.info(f"Building {repr(self)}")
        return self.typename(*self.module_args, **self.module_kwargs)

    def __repr__(self):
        from deepspeed_tpu.runtime.utils import call_to_str
        return call_to_str(self.typename.__name__, *self.module_args, **self.module_kwargs)


class TiedLayerSpec(LayerSpec):
    """Reference module.py TiedLayerSpec — layers sharing parameters (e.g. embed
    and unembed). Under SPMD the tie is a shared param subtree, and the 'tied
    weight allreduce' of the reference (module.py:423) is implicit in autodiff."""

    def __init__(self, key, typename, *module_args, forward_fn=None, tied_weight_attr="weight", **module_kwargs):
        super().__init__(typename, *module_args, **module_kwargs)
        self.key = key
        self.forward_fn = forward_fn
        self.tied_weight_attr = tied_weight_attr


def partition_uniform(num_items, num_parts):
    """Reference ds_utils.partition_uniform: even split, remainder to the front."""
    parts = [0] * (num_parts + 1)
    chunk = num_items // num_parts
    rem = num_items % num_parts
    offset = 0
    for p in range(num_parts):
        parts[p] = offset
        offset += chunk + (1 if p < rem else 0)
    parts[num_parts] = num_items
    return parts


def partition_balanced(weights, num_parts):
    """Reference ds_utils.partition_balanced — minimize the max part weight
    (binary search over the bottleneck + greedy check)."""
    weights = list(weights)
    n = len(weights)
    prefix = np.concatenate([[0], np.cumsum(weights)])

    def feasible(cap):
        parts = [0]
        cur = 0
        for i, w in enumerate(weights):
            if w > cap:
                return None
            if cur + w > cap:
                parts.append(i)
                cur = 0
            cur += w
        parts.append(n)
        return parts if len(parts) <= num_parts + 1 else None

    lo, hi = max(weights) if weights else 0, float(prefix[-1])
    best = None
    for _ in range(64):
        mid = (lo + hi) / 2
        p = feasible(mid)
        if p is not None:
            best = p
            hi = mid
        else:
            lo = mid
    if best is None:
        best = [0, n]
    # pad to exactly num_parts boundaries
    while len(best) < num_parts + 1:
        best.insert(-1, best[-1])
    return best


class PipelineModule:
    """Reference module.py:86. Holds the layer list, builds stage partitions.

    Args follow the reference: ``layers`` (list of LayerSpec or callables),
    ``num_stages`` or ``topology``, ``partition_method`` in
    {'uniform', 'parameters', 'type:regex'}, ``loss_fn``, ``activation_checkpoint_interval``.
    """

    def __init__(self,
                 layers,
                 num_stages=None,
                 topology=None,
                 loss_fn=None,
                 seed_layers=False,
                 base_seed=1234,
                 partition_method="parameters",
                 activation_checkpoint_interval=0,
                 checkpointable_layers=None):
        self._layer_specs = list(layers)
        self.loss_fn = loss_fn
        self.seed_layers = seed_layers
        self.base_seed = base_seed
        self.partition_method = partition_method
        self.activation_checkpoint_interval = activation_checkpoint_interval
        self.checkpointable_layers = checkpointable_layers

        if num_stages is None and topology is None:
            raise RuntimeError("must provide num_stages or topology")
        if topology is not None:
            self.num_stages = topology.get_dim("pipe")
            self._topo = topology
        else:
            self.num_stages = num_stages
            self._topo = None

        self.parts = None  # stage boundaries, computed by partition_layers

    def __len__(self):
        return len(self._layer_specs)

    def build_layers(self):
        """Materialize every LayerSpec into a module/callable."""
        out = []
        for spec in self._layer_specs:
            out.append(spec.build() if isinstance(spec, LayerSpec) else spec)
        return out

    def _count_layer_params(self, params_per_layer=None):
        if params_per_layer is not None:
            return params_per_layer
        counts = []
        for spec in self._layer_specs:
            if isinstance(spec, LayerSpec):
                # estimate from ctor args (flax modules are lazy); fall back to 1
                counts.append(1)
            else:
                counts.append(1)
        return counts

    def partition_layers(self, method=None, params_per_layer=None):
        """Reference _partition_layers:370 — compute self.parts stage boundaries."""
        method = (method or self.partition_method).lower()
        n = len(self._layer_specs)
        if method == "uniform":
            self.parts = partition_uniform(n, self.num_stages)
        elif method == "parameters":
            weights = params_per_layer or self._count_layer_params()
            self.parts = partition_balanced(weights, self.num_stages)
        elif method.startswith("type:"):
            import re
            pat = method.split(":", 1)[1]
            weights = [1 if re.search(pat, type(s).__name__ if not isinstance(s, LayerSpec) else
                                      s.typename.__name__, re.IGNORECASE) else 0 for s in self._layer_specs]
            self.parts = partition_balanced(weights, self.num_stages)
        else:
            raise NotImplementedError(f"Partitioning method {method} not implemented")
        return self.parts

    def stage_layers(self, stage_id):
        if self.parts is None:
            self.partition_layers()
        return self._layer_specs[self.parts[stage_id]:self.parts[stage_id + 1]]

    def topology(self):
        return self._topo
