"""Front-end fleet router: one HTTP endpoint over N serving replicas.

Same wire format as ``serving/server.py`` (``POST /v1/generate`` with
optional SSE streaming, ``POST /v1/resume``, ``GET /v1/stats``,
``GET /healthz``) plus ``GET /v1/fleet/stats`` (per-replica dispatch counts,
roles, breaker states, supervisor slots, probes) and — when fault injection
is armed with ``allow_remote`` — ``POST /v1/fleet/chaos`` (re-seed/disable
the chaos harness; what ``bin/dstpu_loadgen --chaos`` drives). A client
cannot tell the router from a single replica, which is the point: "millions
of users" is N replicas behind this process.

Dispatch policy per request leg:

- **session affinity**: a session key (the ``X-DSTPU-Session`` header or the
  JSON ``session`` field) rendezvous-hashes over the healthy pool — stable
  under replica loss: keys only move off a replica that left.
- **least-loaded**: without a key, the replica with the fewest
  queued+in-flight requests wins (probes cached ``probe_ttl_s``, driven by
  the ``/healthz`` + ``/v1/stats`` surfaces for HTTP upstreams).
- **circuit breaking**: every replica's breaker (``fleet/breaker.py``) gates
  candidacy — an OPEN replica is skipped without a probe or a socket; a
  HALF_OPEN one admits bounded trial dispatches. Breakers are fed by probe
  failures, dispatch refusals (never 429 backpressure) and mid-leg deaths.
- **failover**: an unavailable replica is excluded and the next candidate
  tried, up to ``max_attempts``, with bounded-jitter backoff between
  attempts (the shared ``backoff_delay`` policy).
- **graceful degradation**: when a disaggregated fleet has one role pool
  entirely dark (drained, quarantined, or breaker-open), requests are served
  monolithically on the surviving pool — counted in
  ``fleet_degraded_requests_total`` and flagged ``degraded`` in the final
  doc, never silent, never a blanket 502.

Prefill/decode disaggregation: when both a ``prefill`` and a ``decode`` pool
exist, a generate request runs as two legs — prefill + first token on a
prefill-role replica (``handoff=True``), then the portable KV payload
(``ragged/handoff.py``) continues on a decode-role replica via
``/v1/resume``. A decode replica dying mid-leg is retried **once** on a peer
with the still-buffered payload: the resume is token-identical, so the
already-streamed token prefix is skipped and the client sees one seamless
stream. The router parents both replica request spans under its own span, so
the Perfetto track reads router → prefill replica → decode replica as one
trace.
"""

import base64
import hashlib
import json
import os
import random
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Iterator, List, Optional, Set

from deepspeed_tpu import telemetry
from deepspeed_tpu.fleet.breaker import backoff_delay
from deepspeed_tpu.fleet.config import FleetConfig
from deepspeed_tpu.fleet.faults import (FaultConfig, FaultInjector,
                                        config_from_env)
from deepspeed_tpu.fleet.manager import ReplicaManager
from deepspeed_tpu.fleet.metrics import FleetMetrics
from deepspeed_tpu.fleet.replica import (Leg, Replica, ReplicaDied,
                                         ReplicaUnavailable)
from deepspeed_tpu.serving.server import TRACE_HEADER, parse_request_body
from deepspeed_tpu.telemetry import new_span_id, new_trace_id, now_us
from deepspeed_tpu.utils.logging import logger

# request fields forwarded verbatim to a replica leg (everything else —
# stream, session, handoff — is router-interpreted, never blind-forwarded)
_LEG_FIELDS = ("max_new_tokens", "temperature", "eos_token_id", "deadline_s",
               "seed")


class RoutingError(RuntimeError):
    """No replica could take the request (all candidates excluded or
    unavailable); ``status`` is the HTTP code the client sees (503, or 429
    when the last refusal was backpressure)."""

    def __init__(self, message: str, status: int = 503):
        super().__init__(message)
        self.status = status


def _rendezvous_score(session_key: str, replica_id: str) -> int:
    digest = hashlib.md5(f"{session_key}\x00{replica_id}".encode()).digest()
    return int.from_bytes(digest[:8], "big")


class RoutedRequest:
    """One client request in flight through the router.

    The first leg is dispatched in the constructor, so admission problems
    (everything down, fleet-wide backpressure) raise :class:`RoutingError`
    before any response bytes are written; iterate ``tokens()`` for the live
    cross-leg stream, then ``result()`` for the merged final doc.
    """

    def __init__(self, router: "FleetRouter", doc: dict, resume: bool,
                 session_key: Optional[str], trace_id: Optional[str]):
        self._router = router
        self._doc = doc
        self._resume = resume
        self._session_key = session_key
        self.trace_id = trace_id
        self._root_span_id = new_span_id() if trace_id is not None else None
        self._t0_us = now_us()
        self._t0_s = time.monotonic()
        self._final: Optional[dict] = None
        self._current_leg: Optional[Leg] = None
        self._current_replica: Optional[Replica] = None
        self._legs_meta: List[dict] = []
        self._cancelled = False
        self._degraded = False

        mgr = router._manager
        prefill_pool = self._dispatchable("prefill")
        decode_pool = self._dispatchable("decode")
        # disaggregated *topology*: both roles exist in the registry, whatever
        # their current health — the degradation accounting baseline
        registered_roles = {r.role for r in mgr.replicas()}
        disagg_topology = {"prefill", "decode"} <= registered_roles
        mnt = doc.get("max_new_tokens")
        # `is None`, not falsy-or: an explicit 0 must flow through to the
        # replica's own 'max_new_tokens must be >= 1' 400, exactly as it
        # would on a single server — not become a default-budget completion
        self._n = int(router._config.default_max_new_tokens if mnt is None else mnt)
        self._client_handoff = bool(doc.get("handoff"))
        self._disagg = (not resume and bool(prefill_pool) and bool(decode_pool)
                        and self._n > 1)
        if self._disagg:
            self._leg1 = self._dispatch(
                self._leg_doc(prompt=doc["prompt"], max_new_tokens=1,
                              handoff=True),
                resume=False, pool=prefill_pool, what="prefill")
        elif resume:
            pool = decode_pool or self._dispatchable()
            if not decode_pool and "decode" in registered_roles:
                # same contract as the generate path: serving a resume off
                # the dark decode pool is degradation — counted, not silent
                self._mark_degraded("decode pool unavailable; resuming on "
                                    "the surviving pool")
            self._leg1 = self._dispatch(
                self._leg_doc(payload=doc["payload"],
                              handoff=self._client_handoff),
                resume=True, pool=pool, what="resume")
        else:
            # whole-request serving: the mixed pool when one exists, else any
            # dispatchable replica. A disaggregated fleet with one side
            # entirely dark lands here — graceful degradation, counted
            pool = self._dispatchable("mixed") or self._dispatchable()
            if disagg_topology and self._n > 1:
                self._mark_degraded(
                    f"{'decode' if prefill_pool else 'prefill'} pool "
                    f"unavailable; serving monolithically")
            self._leg1 = self._dispatch(
                self._leg_doc(prompt=doc["prompt"],
                              handoff=self._client_handoff),
                resume=False, pool=pool, what="generate")
        self._iter = self._run()

    def tokens(self) -> Iterator[int]:
        return self._iter

    def result(self) -> dict:
        for _ in self._iter:  # drain whatever the caller didn't consume
            pass
        assert self._final is not None
        return self._final

    def cancel(self) -> None:
        """Client went away: cancel the active leg so its KV frees upstream."""
        self._cancelled = True
        leg = self._current_leg
        if leg is not None:
            leg.cancel()

    # ---------------------------------------------------------------- pools --
    def _dispatchable(self, role: Optional[str] = None) -> List[Replica]:
        """The pool the router may dispatch to right now: in-rotation AND not
        behind an open breaker (an OPEN replica costs nothing here — no probe,
        no socket)."""
        return [r for r in self._router._manager.replicas(role=role,
                                                          available_only=True)
                if r.breaker is None or r.breaker.allow()]

    def _mark_degraded(self, reason: str) -> None:
        if self._degraded:
            return
        self._degraded = True
        router = self._router
        with router._counter_lock:
            router._counters["degraded"] += 1
        if router._metrics:
            router._metrics.degraded.inc()
        logger.warning(f"fleet: degraded serving: {reason}")

    # ---------------------------------------------------------------- legs --
    def _dispatch(self, doc: dict, resume: bool, pool: List[Replica],
                  what: str, exclude: Optional[Set[str]] = None,
                  internal_payload: bool = False) -> Leg:
        """Failover dispatch over ``pool``: an unavailable replica (429/503/
        unreachable) is excluded — and its breaker fed — and the next
        candidate tried after a bounded-jitter backoff; the chosen replica's
        request root parents under a per-hop router span. ``internal_payload``
        marks a router-packed resume body: a replica rejecting it (ValueError)
        smells like transit corruption, so the next attempt re-sends the
        pristine buffered copy instead of failing the request."""
        router = self._router
        cfg = router._config
        faults = router._faults
        exclude = set(exclude or ())
        last: Optional[Exception] = None
        last_status = 503
        for attempt in range(min(cfg.max_attempts, max(1, len(pool)))):
            if attempt and cfg.retry_backoff_base_s > 0:
                time.sleep(backoff_delay(attempt - 1, cfg.retry_backoff_base_s,
                                         cfg.retry_backoff_cap_s,
                                         cfg.retry_jitter_frac, random.random()))
            candidates = router._healthy(pool, exclude)
            if not candidates:
                break
            replica = router._pick(candidates, self._session_key)
            breaker = replica.breaker
            if breaker is not None and not breaker.try_acquire():
                exclude.add(replica.id)  # HALF_OPEN trial slots exhausted
                continue
            hop_span = new_span_id() if self.trace_id is not None else None
            t0 = now_us()
            with router._counter_lock:  # handler threads race on attribution
                replica.dispatches += 1
            body = doc
            try:
                if faults is not None:
                    body = self._inject_dispatch_faults(faults, replica, doc,
                                                        resume and internal_payload)
                leg = replica.dispatch(body, resume=resume,
                                       trace_id=self.trace_id,
                                       parent_span_id=hop_span)
            except ReplicaUnavailable as e:
                with router._counter_lock:
                    replica.failures += 1
                if breaker is not None:
                    if e.status == 429:
                        breaker.release()  # backpressure is load, not breakage
                    else:
                        breaker.record_failure()
                exclude.add(replica.id)
                last, last_status = e, e.status
                if router._metrics:
                    router._metrics.retries.inc()
                logger.info(f"fleet: {what} leg failed over from {replica.id}: {e}")
                continue
            except (ValueError, TypeError) as e:
                if breaker is not None:
                    breaker.release()  # the payload was refused, not the replica
                if resume and internal_payload:
                    last, last_status = e, 502
                    if router._metrics:
                        router._metrics.retries.inc()
                    logger.warning(f"fleet: {what} leg payload refused by "
                                   f"{replica.id} (suspected transit corruption; "
                                   f"retrying pristine): {e}")
                    continue
                raise
            if breaker is not None:
                breaker.record_success()
            spans = telemetry.get_span_recorder()
            if spans is not None and self.trace_id is not None:
                # the hop span is recorded up-front (instant event): its id
                # must exist in the trace for the replica's request root —
                # recorded at the replica's own finalize — to parent under
                spans.record(f"dispatch:{what}", cat="fleet", ts_us=t0,
                             trace_id=self.trace_id, span_id=hop_span,
                             parent_id=self._root_span_id,
                             args={"replica": replica.id, "role": replica.role,
                                   "excluded": sorted(exclude)})
            self._current_leg = leg
            self._current_replica = replica
            self._last_replica_id = replica.id
            return leg
        if router._metrics:
            router._metrics.failures.inc()
        status = last.status if isinstance(last, ReplicaUnavailable) else last_status
        if status < 100:  # transport-class failures carry status=0 as the
            status = 503  # breaker signal; a client must see a real HTTP code
        raise RoutingError(
            f"no replica available for {what} leg "
            f"({len(pool)} in pool, {len(exclude)} excluded): {last}", status)

    def _inject_dispatch_faults(self, faults: FaultInjector, replica: Replica,
                                doc: dict, corruptible: bool) -> dict:
        """Consult every dispatch-time injection point for this attempt;
        returns the (possibly corrupted-copy) body to send. Raising here
        flows through the same except-arms a real transport failure would."""
        router = self._router
        n = faults.fire("dispatch_delay", replica.id)
        if n is not None:
            router._count_fault()
            time.sleep(faults.delay_s(n, replica.id))
        if faults.fire("replica_kill", replica.id) is not None \
                and hasattr(replica, "kill"):
            router._count_fault()
            replica.kill("injected replica_kill")  # dispatch below will refuse
        if faults.fire("connect_reset", replica.id) is not None:
            router._count_fault()
            raise ReplicaUnavailable(
                f"replica {replica.id}: injected connection reset", status=0)
        if faults.fire("http_5xx", replica.id) is not None:
            router._count_fault()
            raise ReplicaUnavailable(
                f"replica {replica.id}: injected HTTP 503", status=503)
        if corruptible:
            n = faults.fire("handoff_corrupt", replica.id)
            if n is not None:
                router._count_fault()
                # corrupt THIS attempt's copy only: the retry re-sends the
                # pristine buffered payload (corruption-in-transit semantics)
                return {**doc, "payload": faults.corrupt(doc["payload"], n,
                                                         replica.id)}
        return doc

    def _stream(self, leg: Leg, replica_id: str) -> Iterator[int]:
        """Leg token iterator with the mid-stream truncation injection point
        armed (one decision per leg)."""
        faults = self._router._faults
        cut = None
        if faults is not None:
            n = faults.fire("stream_truncate", replica_id)
            if n is not None:
                self._router._count_fault()
                cut = faults.truncate_after(n, replica_id)
        for i, tok in enumerate(leg):
            if cut is not None and i >= cut:
                leg.cancel()
                raise ReplicaDied(f"replica {replica_id}: injected mid-stream "
                                  f"truncation after {cut} tokens")
            yield tok

    def _fail_current_replica(self) -> None:
        """A leg died under an admitted request: a breaker-grade failure for
        the replica that held it."""
        replica = self._current_replica
        if replica is not None and replica.breaker is not None:
            replica.breaker.record_failure(trial=False)

    def _leg_doc(self, **overrides) -> dict:
        doc = {k: self._doc[k] for k in _LEG_FIELDS if self._doc.get(k) is not None}
        doc.update(overrides)
        return doc

    def _leg_meta(self, kind: str, final: dict) -> None:
        self._legs_meta.append({"replica": self._last_replica_id, "kind": kind,
                                "uid": final.get("uid"),
                                "n_tokens": final.get("n_tokens")})

    # --------------------------------------------------------------- route --
    def _run(self) -> Iterator[int]:
        router = self._router
        if not self._disagg:
            try:
                for tok in self._stream(self._leg1, self._last_replica_id):
                    yield tok
                final = dict(self._leg1.result())
            except ReplicaDied:
                # single-leg death: nothing buffered to resume from — the
                # breaker learns, the client gets 502 / a terminal SSE error
                self._fail_current_replica()
                raise
            self._leg_meta("resume" if self._resume else "serve", final)
            if not self._client_handoff:
                final.pop("handoff", None)
        else:
            # --- leg 1 result: prefill + first token
            try:
                final1 = self._leg1.result()
            except ReplicaDied:
                self._fail_current_replica()
                raise
            for tok in final1["tokens"]:
                yield tok
            self._leg_meta("prefill", final1)
            payload = final1.get("handoff")
            continuable = (final1.get("state") == "DONE"
                           and final1.get("finish_reason") == "length"
                           and payload is not None and not self._cancelled)
            if not continuable:
                if (payload is None and not self._cancelled and self._n > 1
                        and final1.get("state") == "DONE"
                        and final1.get("finish_reason") == "length"):
                    # the donor stopped at the handoff point but exported no
                    # payload (export failed replica-side): returning leg 1
                    # verbatim would silently truncate the request to one
                    # token dressed up as a clean completion
                    raise RoutingError(
                        f"prefill replica produced no handoff payload for "
                        f"uid {final1.get('uid')}", status=502)
                # eos on the first token, cancel, or a failed prefill: the
                # first leg's outcome IS the request's outcome
                final = dict(final1)
                final.pop("handoff", None)  # internal transport, not client data
            else:
                # --- leg 2: decode continuation on the decode pool. The
                # payload stays buffered until the leg completes: a decode
                # replica dying mid-leg gets ONE re-dispatch to a peer —
                # resume is token-identical, so the already-streamed prefix
                # is skipped and the client stream stays seamless.
                if router._metrics:
                    router._metrics.handoffs.inc()
                    router._metrics.handoff_bytes.observe(len(payload))
                exclude: Set[str] = set()
                sent2 = 0
                final2 = None
                for attempt in range(2):
                    leg2 = self._dispatch_decode(payload, exclude)
                    try:
                        to_skip, skipped = sent2, 0
                        for tok in self._stream(leg2, self._last_replica_id):
                            if skipped < to_skip:
                                skipped += 1
                                continue
                            yield tok
                            sent2 += 1
                        final2 = dict(leg2.result())
                        break
                    except ReplicaDied as e:
                        self._fail_current_replica()
                        exclude.add(self._last_replica_id)
                        if attempt == 1 or self._cancelled:
                            raise
                        if router._metrics:
                            router._metrics.retries.inc()
                        logger.warning(
                            f"fleet: decode leg died on {self._last_replica_id} "
                            f"after {sent2} streamed tokens; re-dispatching the "
                            f"buffered handoff once: {e}")
                self._leg_meta("decode", final2)
                tokens = list(final1["tokens"]) + list(final2["tokens"])
                final = {
                    "uid": final2.get("uid"),
                    "tokens": tokens,
                    "n_tokens": len(tokens),
                    # the prefix-cache hit happened on the prefill leg: surface
                    # it like the monolithic path does (loadgen --shared-prefix
                    # splits hit/miss TTFT on this field)
                    "cached_tokens": final1.get("cached_tokens", 0),
                    "state": final2.get("state"),
                    "finish_reason": final2.get("finish_reason"),
                    "error": final2.get("error"),
                    "ttft_s": final1.get("ttft_s"),
                    "e2e_s": time.monotonic() - self._t0_s,
                }
                if "handoff" in final2:  # the CLIENT asked for a payload
                    final["handoff"] = final2["handoff"]

        final["trace_id"] = self.trace_id
        final["legs"] = self._legs_meta
        if self._degraded:
            final["degraded"] = True
        spans = telemetry.get_span_recorder()
        if spans is not None and self.trace_id is not None:
            spans.record("route", cat="fleet", ts_us=self._t0_us,
                         dur_us=now_us() - self._t0_us,
                         trace_id=self.trace_id, span_id=self._root_span_id,
                         args={"disaggregated": self._disagg,
                               "degraded": self._degraded,
                               "state": final.get("state"),
                               "legs": [m["replica"] for m in self._legs_meta]})
        self._final = final

    def _dispatch_decode(self, payload: bytes, exclude: Set[str]) -> Leg:
        """Dispatch the decode continuation: the decode pool first; when that
        pool is entirely dark, degrade to resuming on any surviving replica
        (prefill/mixed engines share the KV geometry) rather than 502ing a
        request whose prefill work is already paid for."""
        router = self._router
        remaining = None
        if self._doc.get("deadline_s") is not None:
            remaining = max(0.001, float(self._doc["deadline_s"])
                            - (time.monotonic() - self._t0_s))
        doc = self._leg_doc(payload=payload, max_new_tokens=self._n - 1,
                            handoff=self._client_handoff, deadline_s=remaining)
        decode_pool = [r for r in self._dispatchable("decode")
                       if r.id not in exclude]
        try:
            return self._dispatch(doc, resume=True, pool=decode_pool,
                                  what="decode", exclude=exclude,
                                  internal_payload=True)
        except RoutingError:
            fallback = [r for r in self._dispatchable()
                        if r.role != "decode" and r.id not in exclude]
            if not fallback:
                raise
            self._mark_degraded("decode pool unavailable mid-request; "
                                "resuming on the surviving pool")
            return self._dispatch(doc, resume=True, pool=fallback,
                                  what="decode-degraded", exclude=exclude,
                                  internal_payload=True)


class FleetRouter:
    """The fleet front-end: routing core + stdlib HTTP listener."""

    def __init__(self, manager: ReplicaManager, config: Optional[FleetConfig] = None):
        self._manager = manager
        self._config = config or manager.config
        self._metrics = FleetMetrics.maybe_create()
        self._counters = {"requests": 0, "degraded": 0}
        self._counter_lock = threading.Lock()
        self._server = None
        self._thread = None
        self._draining = threading.Event()
        # fault injection: config first, the DSTPU_FAULTS env var (JSON
        # FaultConfig body) second — None on the (default, production) path,
        # so every hook is one is-None check
        env_faults = config_from_env(os.environ.get("DSTPU_FAULTS"))
        self._faults: Optional[FaultInjector] = None
        if self._config.faults.enabled:
            self._faults = FaultInjector(self._config.faults)
        elif env_faults is not None and env_faults.enabled:
            self._faults = FaultInjector(env_faults)
        # remote chaos control is decided ONCE at construction — and
        # independently of arming: DSTPU_FAULTS='{"allow_remote": true}'
        # exposes the endpoint with zero faults firing, so a loadgen --chaos
        # run's baseline half really is fault-free
        self._chaos_remote = bool(
            self._config.faults.allow_remote
            or (env_faults is not None and env_faults.allow_remote))
        if self._faults is not None:
            logger.warning(f"fleet: FAULT INJECTION ARMED "
                           f"(seed={self._faults.config.seed})")

    @property
    def manager(self) -> ReplicaManager:
        return self._manager

    # ------------------------------------------------------------- dispatch --
    def _healthy(self, pool: List[Replica], exclude) -> List[Replica]:
        ttl = self._config.probe_ttl_s
        out = []
        for replica in pool:
            if replica.id in exclude or not replica.available:
                continue
            if replica.breaker is not None and not replica.breaker.allow():
                # open breaker: skipped without a probe — no socket, no
                # handler thread pinned on a black-holed upstream
                if self._metrics:
                    self._metrics.breaker_short_circuits.inc()
                continue
            probe = replica.probe(max_age_s=ttl)
            if probe.get("healthy") and not probe.get("draining"):
                out.append(replica)
        return out

    def _pick(self, candidates: List[Replica], session_key: Optional[str]) -> Replica:
        """Affinity (rendezvous hash) when a session key rides the request,
        least-loaded otherwise; candidates are already healthy-filtered."""
        if session_key:
            return max(candidates,
                       key=lambda r: _rendezvous_score(session_key, r.id))
        return min(candidates, key=lambda r: (r.load, r.id))

    def _count_fault(self) -> None:
        if self._metrics:
            self._metrics.faults_injected.inc()

    def set_faults(self, config: Optional[FaultConfig]) -> None:
        """Arm/re-seed/disable the fault injector at runtime (the
        ``/v1/fleet/chaos`` handler and the chaos tests)."""
        self._faults = (FaultInjector(config)
                        if config is not None and config.enabled else None)
        if self._faults is not None:
            logger.warning(f"fleet: FAULT INJECTION ARMED "
                           f"(seed={config.seed})")
        else:
            logger.info("fleet: fault injection disarmed")

    def route(self, doc: dict, resume: bool = False,
              session_key: Optional[str] = None,
              trace_id: Optional[str] = None) -> RoutedRequest:
        """Admit one client request; the first leg is dispatched before this
        returns (admission failures raise :class:`RoutingError`).
        ``trace_id`` adopts an upstream trace (minted otherwise when
        telemetry is active); the router span parents both replica legs."""
        if self._draining.is_set():
            raise RoutingError("router is draining", status=503)
        with self._counter_lock:
            self._counters["requests"] += 1
        if self._metrics:
            self._metrics.requests.inc()
        # no fleet-wide probe sweep here: _healthy probes the candidate pool
        # (TTL-cached) during dispatch; a dead upstream elsewhere in the fleet
        # must not add its probe timeout to THIS request's latency. The
        # fleet-wide gauges are pushed by stats()/the autoscaler tick instead.
        if trace_id is None and telemetry.get_span_recorder() is not None:
            trace_id = new_trace_id()
        return RoutedRequest(self, doc, resume, session_key, trace_id)

    def drain(self, timeout: Optional[float] = None) -> None:
        """Fleet-wide graceful drain: stop admitting (503), then drain every
        replica bounded by ``drain_timeout_s`` each."""
        self._draining.set()
        self._manager.drain_all(timeout=timeout)

    # ---------------------------------------------------------------- stats --
    def fleet_stats(self) -> dict:
        doc = self._manager.stats()
        with self._counter_lock:
            doc["router"] = dict(self._counters)
        doc["router"]["draining"] = self._draining.is_set()
        faults = self._faults
        if faults is not None:
            doc["faults"] = faults.report()
        return doc

    def stats(self) -> dict:
        """Aggregate ``/v1/stats`` (single-replica wire shape, fleet-wide
        numbers) so loadgen-style clients work unchanged through the router."""
        probes = self._manager.sweep_probes()
        live = [p for p in probes if p.get("healthy")]
        with self._counter_lock:
            counters = dict(self._counters)
        return {
            "queue_depth": sum(p["queue_depth"] for p in live),
            "active": {"total": sum(p["active"] for p in live)},
            "replicas": len(probes),
            "draining": self._draining.is_set(),
            "counters": counters,
        }

    # ----------------------------------------------------------------- HTTP --
    @property
    def address(self):
        return self._server.server_address if self._server else None

    @property
    def url(self) -> str:
        host, port = self.address
        return f"http://{host}:{port}"

    def start(self) -> "FleetRouter":
        router, config, draining = self, self._config, self._draining

        class Handler(BaseHTTPRequestHandler):

            def _send_json(self, code, doc, trace_id=None):
                data = json.dumps(doc).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                if trace_id is not None:
                    self.send_header(TRACE_HEADER, trace_id)
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                path = self.path.split("?", 1)[0].rstrip("/") or "/"
                if path == "/v1/fleet/stats":
                    self._send_json(200, router.fleet_stats())
                elif path == "/v1/stats":
                    self._send_json(200, router.stats())
                elif path == "/healthz":
                    self._send_json(200, {"status": "draining" if draining.is_set()
                                          else "ok"})
                else:
                    self._send_json(404, {"error": f"no route {path}"})

            def _handle_chaos(self):
                """POST /v1/fleet/chaos: arm/re-seed/disable fault injection
                over HTTP — only when a config/env explicitly allowed remote
                chaos control (403 otherwise; production routers never expose
                a kill switch by accident)."""
                if not router._chaos_remote:
                    self._send_json(403, {"error": "remote chaos control is "
                                          "not enabled on this router"})
                    return
                try:
                    length = int(self.headers.get("Content-Length", 0))
                    if not 0 < length <= 1 << 16:
                        raise ValueError(f"body length {length} out of bounds")
                    fault_config = FaultConfig(**json.loads(self.rfile.read(length)))
                except Exception as e:
                    self._send_json(400, {"error": str(e)})
                    return
                router.set_faults(fault_config)
                self._send_json(200, {"enabled": fault_config.enabled,
                                      "seed": fault_config.seed})

            def do_POST(self):
                path = self.path.split("?", 1)[0].rstrip("/")
                if path == "/v1/fleet/chaos":
                    self._handle_chaos()
                    return
                if path not in ("/v1/generate", "/v1/resume"):
                    self._send_json(404, {"error": f"no route {path}"})
                    return
                if draining.is_set():
                    self._send_json(503, {"error": "router is draining"})
                    return
                resume = path == "/v1/resume"
                try:
                    # the single wire-format authority, shared with
                    # serving/server.py: a client cannot tell the router
                    # from one replica
                    doc = parse_request_body(
                        self, resume=resume,
                        max_bytes=config.max_resume_body_bytes if resume else None)
                except (KeyError, ValueError, TypeError) as e:
                    self._send_json(400, {"error": str(e)})
                    return
                session_key = (self.headers.get(config.affinity_header)
                               or doc.get("session") or None)
                upstream_trace = self.headers.get(TRACE_HEADER) or None
                try:
                    routed = router.route(doc, resume=resume,
                                          session_key=session_key,
                                          trace_id=upstream_trace)
                except RoutingError as e:
                    self._send_json(e.status, {"error": str(e)})
                    return
                except (ValueError, TypeError) as e:
                    self._send_json(400, {"error": str(e)})
                    return
                try:
                    if doc.get("stream"):
                        self._stream_sse(routed)
                    else:
                        final = dict(routed.result())
                        self._encode_handoff(final)
                        self._send_json(200, final, trace_id=routed.trace_id)
                except RoutingError as e:
                    # mid-route failure (e.g. the decode pool vanished after
                    # the prefill leg): non-stream mode can still say why
                    routed.cancel()
                    self._send_json(e.status, {"error": str(e)})
                except (ValueError, TypeError) as e:
                    routed.cancel()
                    self._send_json(400, {"error": str(e)})
                except RuntimeError as e:
                    # a replica died mid-leg (ReplicaDied, or an upstream SSE
                    # malformation): answer 502, free the surviving leg's KV
                    routed.cancel()
                    self._send_json(502, {"error": str(e)})

            @staticmethod
            def _encode_handoff(doc):
                if isinstance(doc.get("handoff"), (bytes, bytearray)):
                    doc["handoff"] = base64.b64encode(doc["handoff"]).decode()

            def _stream_sse(self, routed):
                self.send_response(200)
                self.send_header("Content-Type", "text/event-stream")
                self.send_header("Cache-Control", "no-cache")
                if routed.trace_id is not None:
                    self.send_header(TRACE_HEADER, routed.trace_id)
                self.end_headers()
                try:
                    for i, tok in enumerate(routed.tokens()):
                        self.wfile.write(
                            f"data: {json.dumps({'token': tok, 'index': i})}\n\n".encode())
                        self.wfile.flush()
                    final = dict(routed.result())
                    self._encode_handoff(final)
                    self.wfile.write(
                        f"data: {json.dumps({'done': True, **final})}\n\n".encode())
                    self.wfile.flush()
                except (BrokenPipeError, ConnectionResetError):
                    routed.cancel()  # client went away: free KV upstream
                except (RoutingError, RuntimeError, ValueError, TypeError) as e:
                    # mid-stream routing failure, a replica dying mid-leg, or a
                    # malformed upstream event: the SSE headers are already on
                    # the wire, so the ONLY valid reaction is a terminal error
                    # event — never a second HTTP status line.
                    # Free the surviving leg's KV, best-effort error event
                    routed.cancel()
                    try:
                        self.wfile.write(
                            f"data: {json.dumps({'done': True, 'state': 'FAILED', 'error': str(e)})}\n\n".encode())
                        self.wfile.flush()
                    except (BrokenPipeError, ConnectionResetError):
                        pass

            def log_message(self, fmt, *args):
                ...  # routing must not spam the serving log

        self._server = ThreadingHTTPServer((self._config.host, self._config.port),
                                           Handler)
        self._server.daemon_threads = True
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        name="dstpu-fleet-router", daemon=True)
        self._thread.start()
        logger.info(f"fleet router: /v1/generate /v1/resume /v1/stats "
                    f"/v1/fleet/stats /healthz on {self.url}")
        return self

    def stop(self, drain: bool = True, timeout: Optional[float] = None) -> None:
        """Graceful fleet shutdown: 503 new requests, drain every replica,
        close the listener. Idempotent."""
        self.drain(timeout=(timeout if timeout is not None
                            else self._config.drain_timeout_s) if drain else 0.0)
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
            self._thread = None

    def __enter__(self):
        return self.start() if self._server is None else self

    def __exit__(self, *exc):
        self.stop(drain=False)
