"""Curriculum learning difficulty scheduler.

Reference: ``deepspeed/runtime/data_pipeline/curriculum_scheduler.py`` — maps
the global step to a difficulty value (typically the sequence length) through
``fixed_linear`` / ``fixed_root`` / ``fixed_discrete`` / ``custom`` schedules.
Pure host logic; the engine truncates batches to the current difficulty (a
TPU-friendly knob when ``difficulty_step`` keeps the bucket count small —
every distinct difficulty is one compiled program).
"""

import math

from deepspeed_tpu.utils.logging import logger

FIXED_LINEAR = "fixed_linear"
FIXED_ROOT = "fixed_root"
FIXED_DISCRETE = "fixed_discrete"
CUSTOM = "custom"


class CurriculumScheduler:

    def __init__(self, config: dict):
        for key in ("min_difficulty", "max_difficulty", "schedule_type"):
            assert key in config, f"curriculum learning requires config {key!r}"
        self.state = {
            "min_difficulty": config["min_difficulty"],
            "max_difficulty": config["max_difficulty"],
            "current_difficulty": config["min_difficulty"],
            "schedule_type": config["schedule_type"],
        }
        self.first_step = True
        schedule = config.get("schedule_config", {})
        stype = config["schedule_type"]
        if stype == FIXED_DISCRETE:
            assert len(schedule.get("difficulty", [])) > 0
            assert len(schedule.get("max_step", [])) == len(schedule["difficulty"]) - 1, \
                "fixed_discrete needs len(max_step) == len(difficulty) - 1"
        elif stype in (FIXED_LINEAR, FIXED_ROOT):
            assert schedule.get("total_curriculum_step", 0) > 0
            assert schedule.get("difficulty_step", 0) > 0
            if stype == FIXED_ROOT:
                assert schedule.get("root_degree", 0) > 0
            if schedule["difficulty_step"] % 8 != 0:
                logger.warning("difficulty_step not multiple of 8: sequence lengths may "
                               "be tile-unfriendly on TPU (reference warns for fp16 too)")
        elif stype == CUSTOM:
            self.custom_get_difficulty = None
        else:
            raise RuntimeError(f"unsupported schedule type {stype}")
        self.state["schedule"] = schedule

    # -- reference API --------------------------------------------------------
    def get_current_difficulty(self):
        return self.state["current_difficulty"]

    def set_current_difficulty(self, difficulty):
        self.state["current_difficulty"] = difficulty

    def set_custom_get_difficulty(self, fn):
        self.custom_get_difficulty = fn

    def get_state(self):
        return self.state

    def set_state(self, state):
        self.state = state

    def __fixed_discrete(self, global_steps):
        sched = self.state["schedule"]
        for limit, diff in zip(sched["max_step"], sched["difficulty"]):
            if global_steps <= limit:
                return diff
        return sched["difficulty"][-1]

    def __fixed_root(self, global_steps, degree):
        sched = self.state["schedule"]
        frac = min(1.0, (global_steps / sched["total_curriculum_step"])**(1.0 / degree))
        diff = self.state["min_difficulty"] + frac * (self.state["max_difficulty"] -
                                                      self.state["min_difficulty"])
        diff -= diff % sched["difficulty_step"]
        return int(min(self.state["max_difficulty"], max(self.state["min_difficulty"], diff)))

    def get_difficulty(self, global_steps: int) -> int:
        stype = self.state["schedule_type"]
        if stype == FIXED_DISCRETE:
            return self.__fixed_discrete(global_steps)
        if stype == FIXED_LINEAR:
            return self.__fixed_root(global_steps, 1)
        if stype == FIXED_ROOT:
            return self.__fixed_root(global_steps, self.state["schedule"]["root_degree"])
        assert self.custom_get_difficulty is not None, "custom schedule needs a callable"
        return self.custom_get_difficulty(global_steps)

    def update_difficulty(self, global_steps: int) -> int:
        if self.state["current_difficulty"] < self.state["max_difficulty"]:
            self.state["current_difficulty"] = self.get_difficulty(global_steps)
        return self.state["current_difficulty"]
