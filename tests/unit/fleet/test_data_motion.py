"""Fleet data motion, router side (ISSUE 16): the cache-aware routing gate
(hit rate vs the hash control, hit vs miss TTFT), peer prefix fetch with the
``peer_fetch_corrupt`` chaos point, work stealing end-to-end (queued regrant,
``steal_race`` exactly-once), the zero-copy wire-byte gate (binary vs base64),
and the loadgen ``--shared-prefix`` / ``--routing`` A/B surface."""

import os
import subprocess
import sys
import time

import numpy as np
import pytest

from deepspeed_tpu.fleet import (FaultConfig, FleetConfig, FleetRouter,
                                 LocalReplica)
from deepspeed_tpu.fleet.config import CacheRouteConfig, StealConfig
from deepspeed_tpu.fleet.router import _rendezvous_score
from deepspeed_tpu.inference.v2.ragged.handoff import unpack
from deepspeed_tpu.serving import PrefixCacheConfig, ServingConfig

BLOCK = 16


def _reference_greedy(llama_setup, prompt, n):
    import jax.numpy as jnp
    _, model, params = llama_setup
    toks, out = list(prompt), []
    for _ in range(n):
        logits = np.asarray(model.apply({"params": params["model"]},
                                        jnp.asarray(toks, jnp.int32)[None])[0])
        out.append(int(np.argmax(logits[-1])))
        toks.append(out[-1])
    return out


def _pin_key(target_id, other_id):
    """A session key whose rendezvous winner is ``target_id`` — deterministic
    placement for the fallback (non-cache) arm."""
    for i in range(1000):
        k = f"pin{i}"
        if _rendezvous_score(k, target_id) > _rendezvous_score(k, other_id):
            return k
    raise AssertionError("rendezvous never favored the target")


def _cache_fleet(make_fleet, **cache_kw):
    return make_fleet(
        roles=("mixed", "mixed"),
        serving_config=ServingConfig(
            prefix_cache=PrefixCacheConfig(enabled=True)),
        config=FleetConfig(probe_ttl_s=0.0, drain_timeout_s=10.0,
                           cache_route=CacheRouteConfig(**cache_kw)))


def _settle(manager, timeout_s=60.0):
    """Wait until no replica tracks a sequence (the zero-leak sweep; the
    prefix trie may legitimately pin blocks, tracked sequences may not stay)."""
    deadline = time.monotonic() + timeout_s
    for replica in manager.replicas():
        while time.monotonic() < deadline:
            sched = replica.scheduler
            if (sched.n_active == 0 and sched.queue_depth == 0
                    and replica.engine._state_manager.n_tracked_sequences == 0):
                break
            time.sleep(0.02)
        assert replica.engine._state_manager.n_tracked_sequences == 0, replica.id


# ---------------------------------------------------------------------------
# the CPU routing gate: cache-aware vs hash control on a shared-prefix load
# ---------------------------------------------------------------------------
def _shared_prefix_prompts(vocab, groups=2, per_group=12,
                           prefix_blocks=4, suffix=8, seed=1234):
    rng = np.random.default_rng(seed)
    prefixes = [rng.integers(0, vocab, prefix_blocks * BLOCK).tolist()
                for _ in range(groups)]
    return [prefixes[i % groups]
            + rng.integers(0, vocab, suffix).tolist()
            for i in range(groups * per_group)]


def _run_arm(make_fleet, routing, prompts):
    manager = _cache_fleet(make_fleet, peer_fetch=False)  # isolate ROUTING:
    # with peer fetch on, the hash control would import the prefix anyway and
    # the A/B would measure the fetch path, not the placement policy
    router = FleetRouter(manager)
    finals = []
    for i, prompt in enumerate(prompts):
        routed = router.route({"prompt": prompt, "max_new_tokens": 1,
                               "routing": routing}, session_key=f"s{i}")
        finals.append(dict(routed.result()))
    hits = lookups = 0
    for replica in manager.replicas():
        s = replica.scheduler._prefix_cache.stats()
        hits += s["hits"]
        lookups += s["lookups"]
    _settle(manager)
    return router, finals, hits / lookups


def test_cache_routing_gate_hit_rate_and_ttft(make_fleet, llama_setup):
    """The acceptance gate: on a 2-replica fleet and a shared-prefix workload,
    cache-aware routing concentrates each prefix chain on its holder — fleet
    hit rate >= the single-replica baseline (~88%) and strictly above the
    hash-routing control at the identical seed — and cached requests see a
    smaller TTFT than cold ones (p50 vs p50)."""
    cfg = llama_setup[0]
    prompts = _shared_prefix_prompts(cfg.vocab_size)

    cache_router, finals, cache_rate = _run_arm(make_fleet, "cache", prompts)
    hash_router, _, hash_rate = _run_arm(make_fleet, "hash", prompts)

    assert cache_rate >= 0.88, f"cache-aware hit rate {cache_rate:.3f}"
    assert cache_rate > hash_rate, (cache_rate, hash_rate)

    # placement telemetry: every request was judged once; only the group
    # firsts (nobody held the chain yet) fell back to rendezvous
    groups = 2
    assert cache_router._counters["cache_route_hits"] == len(prompts) - groups
    assert cache_router._counters["cache_route_misses"] == groups
    assert hash_router._counters["cache_route_hits"] == 0  # A/B control arm

    hit_ttft = [f["ttft_s"] for f in finals if f["cached_tokens"] > 0]
    miss_ttft = [f["ttft_s"] for f in finals if f["cached_tokens"] == 0]
    assert len(miss_ttft) == groups and len(hit_ttft) == len(prompts) - groups
    assert np.median(hit_ttft) < np.median(miss_ttft), \
        f"hit p50 {np.median(hit_ttft):.4f}s vs miss p50 {np.median(miss_ttft):.4f}s"


def test_unknown_routing_mode_is_client_error(make_fleet):
    manager = _cache_fleet(make_fleet, peer_fetch=False)
    router = FleetRouter(manager)
    with pytest.raises(ValueError, match="unknown routing mode"):
        router.route({"prompt": [1, 2, 3], "routing": "psychic"})


# ---------------------------------------------------------------------------
# peer prefix fetch: import instead of recompute; chaos corrupt -> recompute
# ---------------------------------------------------------------------------
def _warm_one_replica(router, manager, prefix, vocab):
    """Serve one prefixed request; returns (holder, other) replicas."""
    rng = np.random.default_rng(7)
    routed = router.route({"prompt": prefix + rng.integers(0, vocab, 6).tolist(),
                           "max_new_tokens": 1})
    routed.result()
    holder_id = routed._legs_meta[0]["replica"]
    replicas = {r.id: r for r in manager.replicas()}
    holder = replicas.pop(holder_id)
    return holder, next(iter(replicas.values()))


def test_peer_prefix_fetch_imports_blocks_token_identical(make_fleet, llama_setup):
    """A request forced onto the replica that does NOT hold its prefix pulls
    the KV blocks from the peer over the handoff frame instead of recomputing
    — served cached, greedy-identical to the model's ground truth."""
    cfg = llama_setup[0]
    manager = _cache_fleet(make_fleet, peer_fetch=True)
    router = FleetRouter(manager)
    rng = np.random.default_rng(21)
    prefix = rng.integers(0, cfg.vocab_size, 3 * BLOCK).tolist()
    holder, cold = _warm_one_replica(router, manager, prefix, cfg.vocab_size)

    prompt = prefix + rng.integers(0, cfg.vocab_size, 6).tolist()
    routed = router.route({"prompt": prompt, "max_new_tokens": 3,
                           "routing": "hash"},  # dodge the cache pick: the
                          # point is the replica-side fetch, not placement
                          session_key=_pin_key(cold.id, holder.id))
    final = dict(routed.result())
    assert routed._legs_meta[0]["replica"] == cold.id
    assert final["cached_tokens"] == 3 * BLOCK  # the imported chain applied
    assert final["tokens"] == _reference_greedy(llama_setup, prompt, 3)

    counters = cold.scheduler.stats()["counters"]
    assert counters["peer_fetch_hits"] == 1
    assert counters["peer_fetch_blocks"] == 3
    assert counters["peer_fetch_rejects"] == 0
    assert holder.kv_wire_bytes["local"] > 0  # the donor's export was counted
    _settle(manager)


def test_peer_fetch_corrupt_rejects_loudly_and_recomputes(make_fleet, llama_setup):
    """The ``peer_fetch_corrupt`` chaos point: a flipped/truncated frame is a
    CRC/framing reject — counted, logged — and the request degrades to a cold
    prefill that still streams the correct tokens."""
    cfg = llama_setup[0]
    manager = _cache_fleet(make_fleet, peer_fetch=True)
    router = FleetRouter(manager)
    router.set_faults(FaultConfig(enabled=True, seed=5, peer_fetch_corrupt_p=1.0))
    rng = np.random.default_rng(22)
    prefix = rng.integers(0, cfg.vocab_size, 3 * BLOCK).tolist()
    holder, cold = _warm_one_replica(router, manager, prefix, cfg.vocab_size)

    prompt = prefix + rng.integers(0, cfg.vocab_size, 6).tolist()
    routed = router.route({"prompt": prompt, "max_new_tokens": 3,
                           "routing": "hash"},
                          session_key=_pin_key(cold.id, holder.id))
    final = dict(routed.result())
    assert final["tokens"] == _reference_greedy(llama_setup, prompt, 3)
    assert final["cached_tokens"] == 0  # corrupt import -> recompute, not trust

    counters = cold.scheduler.stats()["counters"]
    assert counters["peer_fetch_rejects"] == 1
    assert counters["peer_fetch_hits"] == 0
    _settle(manager)


@pytest.mark.parametrize("temperature", [0.0, 0.8])
def test_chaos_soak_data_motion_token_identical(make_fleet, llama_setup,
                                                temperature):
    """Seeded chaos soak over the data-motion paths: with both new fault
    points armed at p=0.5, a shared-prefix workload (greedy and
    seeded-sampled) streams exactly the tokens the fault-free pass produced,
    and nothing leaks — corruption degrades to recompute, never to silence."""
    cfg = llama_setup[0]
    manager = _cache_fleet(make_fleet, peer_fetch=True)
    router = FleetRouter(manager)
    rng = np.random.default_rng(31)
    prefixes = [rng.integers(0, cfg.vocab_size, 3 * BLOCK).tolist()
                for _ in range(2)]
    prompts = [prefixes[i % 2] + rng.integers(0, cfg.vocab_size, 5).tolist()
               for i in range(8)]

    def run(prompt):
        routed = router.route({"prompt": prompt, "max_new_tokens": 4,
                               "temperature": temperature, "seed": 1234})
        final = dict(routed.result())
        assert final["state"] == "DONE"
        return final["tokens"]

    truth = [run(p) for p in prompts]  # fault-free pass (also warms the tries)
    router.set_faults(FaultConfig(enabled=True, seed=11,
                                  peer_fetch_corrupt_p=0.5, steal_race_p=0.5))
    for prompt, expected in zip(prompts, truth):
        assert run(prompt) == expected
    router.set_faults(None)
    _settle(manager)


# ---------------------------------------------------------------------------
# work stealing end-to-end
# ---------------------------------------------------------------------------
def _steal_fleet(make_fleet, **steal_kw):
    """Two single-slot replicas (``max_tracked_sequences=1``): a decoding
    blocker makes the victim verifiably hot while the target request queues."""
    steal_kw.setdefault("enabled", True)
    steal_kw.setdefault("wait_budget_s", 0.1)
    steal_kw.setdefault("load_ratio", 1.5)
    manager = make_fleet(roles=(),
                         config=FleetConfig(probe_ttl_s=0.0,
                                            drain_timeout_s=10.0,
                                            steal=StealConfig(**steal_kw)),
                         max_tracked_sequences=1)
    manager.add_local(role="mixed", replica_id="r0")
    manager.add_local(role="mixed", replica_id="r1")
    return manager


def _warm_and_truth(manager, prompt, n=4):
    truth = None
    for replica in manager.replicas():
        tokens = replica.scheduler.submit(prompt, max_new_tokens=n,
                                          seed=0).result(timeout=300)
        truth = tokens if truth is None else truth
        assert tokens == truth
    return truth


def test_steal_queued_regrants_to_cold_replica_token_identical(make_fleet):
    """The flagship steal contract: a request queued behind a busy victim is
    stolen after the wait budget and re-granted to the cold replica; the
    stream is token-identical to the unstolen run and nothing leaks."""
    manager = _steal_fleet(make_fleet)
    r0, r1 = manager.replicas()
    prompt = (np.arange(9) % 64).tolist()
    truth = _warm_and_truth(manager, prompt)

    blocker = r0.scheduler.submit((np.arange(7) % 64).tolist(),
                                  max_new_tokens=300)
    router = FleetRouter(manager)
    routed = router.route({"prompt": prompt, "max_new_tokens": 4, "seed": 0},
                          session_key=_pin_key("r0", "r1"))
    final = dict(routed.result())

    assert final["state"] == "DONE" and final["tokens"] == truth
    assert final.get("stolen") is True
    kinds = [leg["kind"] for leg in final["legs"]]
    assert kinds == ["steal-victim", "steal"]
    assert final["legs"][0]["replica"] == "r0"
    assert final["legs"][1]["replica"] == "r1"
    assert router._counters["steal_attempts"] == 1
    assert router._counters["steals"] == 1
    assert r0.scheduler.stats()["counters"]["steals"] == 1

    blocker.result(timeout=300)  # the victim's own work was never disturbed
    _settle(manager)


def test_steal_race_completes_exactly_once(make_fleet):
    """The ``steal_race`` chaos point: the victim finishes while the steal
    decision is in flight — the router keeps the original leg and the client
    sees exactly one complete, token-identical stream."""
    manager = _steal_fleet(make_fleet)
    r0, r1 = manager.replicas()
    prompt = (np.arange(9) % 64).tolist()
    truth = _warm_and_truth(manager, prompt)

    blockers = [r0.scheduler.submit((np.arange(7) % 64).tolist(),
                                    max_new_tokens=200) for _ in range(2)]
    router = FleetRouter(manager)
    router.set_faults(FaultConfig(enabled=True, seed=0, steal_race_p=1.0))
    routed = router.route({"prompt": prompt, "max_new_tokens": 4, "seed": 0},
                          session_key=_pin_key("r0", "r1"))
    final = dict(routed.result())

    assert final["state"] == "DONE" and final["tokens"] == truth
    assert not final.get("stolen")
    assert [leg["kind"] for leg in final["legs"]] == ["serve"]
    assert final["legs"][0]["replica"] == "r0"  # stayed on the victim
    assert router._counters["steal_attempts"] == 1
    assert router._counters["steals"] == 0  # the race was lost, not retried
    assert r0.scheduler.stats()["counters"]["steals"] == 0
    for blocker in blockers:
        blocker.result(timeout=300)
    _settle(manager)


# ---------------------------------------------------------------------------
# the zero-copy wire gate: binary <= 1.05x raw KV, base64 control >= 4/3x
# ---------------------------------------------------------------------------
def test_zero_copy_wire_bytes_gate(make_fleet, make_engine, llama_setup):
    """A binary-transport resume of an N-byte KV payload moves ~N wire bytes
    (frame overhead under 5%); the base64 compatibility arm pays the >= 4/3
    encode tax on the same payload class — both read off the per-transport
    byte accounting that feeds ``fleet_kv_transport_*_bytes_total``."""
    from deepspeed_tpu.serving import ServingScheduler, ServingServer
    cfg = llama_setup[0]
    upstream = ServingServer(ServingScheduler(make_engine(),
                                              ServingConfig())).start()
    donor = LocalReplica(make_engine(), role="prefill")
    try:
        manager = make_fleet(roles=())
        replica = manager.add_upstream(upstream.url, role="decode")
        assert replica.binary_transport  # kv_transport="binary" is the default

        def handoff_payload(seed):
            prompt = (np.arange(64) + seed) % cfg.vocab_size
            leg = donor.dispatch({"prompt": prompt.tolist(),
                                  "max_new_tokens": 1, "handoff": True})
            doc = leg.result(timeout=300)
            return prompt.tolist(), doc["tokens"], doc["handoff"]

        # binary arm
        prompt, first, payload = handoff_payload(0)
        n_kv = unpack(payload)[1].nbytes
        leg = replica.dispatch({"payload": payload, "max_new_tokens": 3},
                               resume=True)
        resumed = leg.result(timeout=300)
        assert first + resumed["tokens"] == _reference_greedy(
            llama_setup, prompt, 4)  # the wire moved the exact KV
        wire = replica.kv_wire_bytes["binary"]
        assert wire == len(payload)
        assert wire <= 1.05 * n_kv, f"binary moved {wire} for {n_kv} KV bytes"

        # base64 control arm (the compatibility fallback)
        prompt2, first2, payload2 = handoff_payload(1)
        n_kv2 = unpack(payload2)[1].nbytes
        replica.binary_transport = False  # as after an upstream 400
        leg = replica.dispatch({"payload": payload2, "max_new_tokens": 3},
                               resume=True)
        resumed2 = leg.result(timeout=300)
        assert first2 + resumed2["tokens"] == _reference_greedy(
            llama_setup, prompt2, 4)
        b64 = replica.kv_wire_bytes["base64"]
        assert b64 >= (4 / 3) * n_kv2, f"base64 moved {b64} for {n_kv2} KV bytes"

        # the fleet-wide rollup the loadgen report reads
        rollup = manager.stats()["kv_wire_bytes"]
        assert rollup["binary"] == wire and rollup["base64"] == b64
    finally:
        donor.drain(timeout=0.0)
        upstream.stop(drain=False)


# ---------------------------------------------------------------------------
# loadgen A/B surface
# ---------------------------------------------------------------------------
def test_loadgen_shared_prefix_routing_ab(make_fleet, llama_setup):
    """The CLI satellite: ``--shared-prefix`` + ``--routing cache`` prints the
    digest-match dispatch fraction and per-replica hit-rate attribution."""
    cfg = llama_setup[0]
    manager = _cache_fleet(make_fleet, peer_fetch=False)
    router = FleetRouter(manager).start()
    try:
        bin_dir = os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))), "bin")
        r = subprocess.run(
            [sys.executable, os.path.join(bin_dir, "dstpu_loadgen"),
             "--target", router.url, "--requests", "8", "--concurrency", "1",
             "--shared-prefix", "48:2", "--prompt-len", "56",
             "--max-new-tokens", "2", "--routing", "cache",
             "--vocab-size", str(cfg.vocab_size)],
            capture_output=True, text=True, timeout=300)
        assert r.returncode == 0, r.stderr[-800:]
        assert "ok=8 err=0" in r.stdout
        assert "# prefix cache: hits=" in r.stdout        # client-side summary
        assert "cache routing: digest-matched" in r.stdout  # router counters
        assert "prefix cache: hits=" in r.stdout          # per-replica probe
    finally:
        router.stop(drain=False)
