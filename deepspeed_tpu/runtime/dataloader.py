"""Data loading.

Reference: ``deepspeed/runtime/dataloader.py`` (DeepSpeedDataLoader, RepeatingLoader).
Under single-controller SPMD the loader yields *global* batches of host numpy arrays;
``engine.shard_batch`` places them over the data/seq mesh axes (the role the
per-rank DistributedSampler plays in the reference).

``PrefetchingLoader`` adds the reference's pinned-memory prefetch worker: a
background thread runs collation + curriculum + the H2D ``device_put``
(``engine.stage_train_batch``) ``depth`` batches ahead, so the host staging
never sits on the device critical path.
"""

import queue
import threading
import numpy as np


class StagedBatch:
    """A device-resident, micro-stacked batch ready for ``train_batch``."""

    __slots__ = ("tree", )

    def __init__(self, tree):
        self.tree = tree


class FusedHostBatch:
    """A full global batch still on host — prefetched but intentionally
    unstaged (curriculum truncation must happen at consume time)."""

    __slots__ = ("tree", )

    def __init__(self, tree):
        self.tree = tree


class DeepSpeedDataLoader:

    def __init__(self, dataset, batch_size, shuffle=False, seed=0, collate_fn=None, drop_last=True):
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.seed = seed
        self.collate_fn = collate_fn or _default_collate
        self.drop_last = drop_last
        self._epoch = 0

    def __len__(self):
        n = len(self.dataset)
        return n // self.batch_size if self.drop_last else (n + self.batch_size - 1) // self.batch_size

    def set_epoch(self, epoch):
        self._epoch = epoch

    def __iter__(self):
        n = len(self.dataset)
        idx = np.arange(n)
        if self.shuffle:
            rng = np.random.default_rng(self.seed + self._epoch)
            rng.shuffle(idx)
        for start in range(0, n - (self.batch_size - 1 if self.drop_last else 0), self.batch_size):
            sel = idx[start:start + self.batch_size]
            yield self.collate_fn([self.dataset[int(i)] for i in sel])


class RepeatingLoader:
    """Reference dataloader.py RepeatingLoader: wrap an iterator to restart it."""

    def __init__(self, loader):
        self.loader = loader
        self.data_iter = iter(self.loader)

    def __iter__(self):
        return self

    def __next__(self):
        try:
            batch = next(self.data_iter)
        except StopIteration:
            if hasattr(self.loader, "set_epoch"):
                self.loader.set_epoch(getattr(self.loader, "_epoch", 0) + 1)
            self.data_iter = iter(self.loader)
            batch = next(self.data_iter)
        return batch


class _PrefetchEpoch:
    """One epoch's iterator: owns ITS queue, stop event, and worker thread — a
    straggler surviving close() can never feed a later epoch's queue, and
    ``for`` re-calling ``iter()`` on this object is a no-op (no restart)."""

    def __init__(self, loader, engine, depth):
        self._q = queue.Queue(maxsize=depth)
        self._stop = threading.Event()
        # curriculum difficulty is a function of the step the batch will be
        # CONSUMED at; staging it `depth` steps early would truncate to a stale
        # seqlen, so with curriculum on we prefetch host batches only and let
        # train_batch stage at consume time
        stage = engine.curriculum_scheduler is None
        self._thread = threading.Thread(
            target=self._worker, args=(loader, engine, stage, self._q, self._stop),
            daemon=True)
        self._thread.start()

    @staticmethod
    def _worker(loader, engine, stage, q, stop):
        def put(item) -> bool:
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        try:
            for batch in loader:
                if stop.is_set():
                    return
                item = engine.stage_train_batch(batch=batch) if stage \
                    else FusedHostBatch(batch)
                if not put(item):
                    return
            put(_END)
        except BaseException as e:  # surface loader errors at the consumer
            put(_Err(e))

    def __iter__(self):
        return self

    def __next__(self):
        if self._q is None:
            raise StopIteration
        item = self._q.get()
        if item is _END:
            self._thread.join()
            self._q = None
            raise StopIteration
        if isinstance(item, _Err):
            self._q = None
            raise item.exc
        return item

    def close(self):
        """Stop the worker and drop in-flight batches (safe mid-epoch)."""
        if self._thread is not None and self._thread.is_alive():
            self._stop.set()
            try:  # drop whatever was queued
                while True:
                    self._q.get_nowait()
            except queue.Empty:
                pass
            self._thread.join(timeout=5)
        self._q = None


class PrefetchingLoader:
    """Iterate ``loader`` with staging moved to a background thread.

    ``loader`` must yield *global fused batches* (shape [gas*micro_global, ...]
    per leaf — what ``engine.train_batch(batch=...)`` accepts). Each ``iter()``
    starts a fresh epoch yielding :class:`StagedBatch` objects (or
    :class:`FusedHostBatch` under curriculum) that ``train_batch`` consumes.
    ``depth`` bounds in-flight batches (double-buffering at 2).
    """

    def __init__(self, loader, engine, depth: int = 2):
        self.loader = loader
        self.engine = engine
        self.depth = max(1, depth)
        self._epoch = None

    def __len__(self):
        return len(self.loader)

    def __iter__(self):
        self.close()
        self._epoch = _PrefetchEpoch(self.loader, self.engine, self.depth)
        return self._epoch

    def close(self):
        if self._epoch is not None:
            self._epoch.close()
            self._epoch = None


_END = object()


class _Err:
    def __init__(self, exc):
        self.exc = exc


def _default_collate(samples):
    first = samples[0]
    if isinstance(first, (tuple, list)):
        return type(first)(_default_collate([s[i] for s in samples]) for i in range(len(first)))
    if isinstance(first, dict):
        return {k: _default_collate([s[k] for s in samples]) for k in first}
    return np.stack([np.asarray(s) for s in samples])
