"""Test harness.

TPU translation of the reference's ``tests/unit/common.py`` strategy (SURVEY.md §4):
instead of forking N torch.multiprocessing workers per test, we run single-controller
SPMD over a *virtual 8-device CPU mesh* (xla_force_host_platform_device_count), so
every distributed code path — ZeRO sharding, MoE all_to_all, Ulysses, pipeline
ppermute — executes real XLA collectives without TPU hardware.

This must run before JAX initializes a backend, hence the top-of-conftest env
mutation (the axon TPU plugin registers itself in sitecustomize; forcing the cpu
platform here overrides it for tests).
"""

import os

os.environ["XLA_FLAGS"] = os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
# env (not just jax.config) so test-SPAWNED subprocesses — launcher e2e,
# autotuning experiments — inherit the cpu platform instead of hanging on a
# dead/absent TPU tunnel
os.environ["JAX_PLATFORMS"] = "cpu"

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np
import pytest

from deepspeed_tpu.utils import groups


@pytest.fixture(autouse=True)
def reset_mesh():
    """Fresh topology per test (the reference tears down process groups per test)."""
    groups.destroy_mesh()
    yield
    groups.destroy_mesh()


@pytest.fixture
def mesh8():
    return groups.initialize_mesh(force=True)


def pytest_configure(config):
    config.addinivalue_line("markers", "world_size(n): mesh size used by the test")
    config.addinivalue_line("markers", "tpu_only: requires real TPU hardware")
    config.addinivalue_line("markers", "nightly: slow end-to-end convergence test")
