"""Compressed collectives: 1-bit error-feedback allreduce and qgZ-style
quantized reduce-scatter.

Reference: ``deepspeed/runtime/comm/nccl.py:51`` (compressed_allreduce — sign
compression with worker+server error feedback, chunked all-to-all then
allgather) and ``deepspeed/runtime/comm/coalesced_collectives.py:31``
(all_to_all_quant_reduce — ZeRO++ qgZ int8 hierarchical gradient reduction,
backed by ``csrc/quantization`` swizzled-quant kernels).

TPU formulation: the same wire math expressed over a mesh axis inside
``shard_map`` — XLA lowers the exchanges to the identical
all-to-all/reduce-scatter/all-gather pattern on ICI/DCN, with the quantized
payloads as int8 arrays (1 byte/element on the wire instead of 4). The sign
compression keeps both error-feedback states exactly as the reference does:
``worker_error`` is full-size per rank, ``server_error`` is chunk-size.
"""

from typing import Tuple

import numpy as np

from deepspeed_tpu.utils import groups
from deepspeed_tpu.utils.jax_compat import shard_map as _compat_shard_map


def _sign_compress(x):
    """1-bit compression: per-tensor L1 scale + sign (reference
    NcclBackend.compressed_allreduce worker phase)."""
    import jax.numpy as jnp
    scale = jnp.mean(jnp.abs(x))
    sign = jnp.sign(x).astype(jnp.int8)  # torch semantics: sign(0) == 0
    return scale, sign


def compressed_allreduce_local(x, worker_error, server_error, axis_name: str, n_ranks: int):
    """The per-rank body (call inside shard_map/jit with ``axis_name`` bound).

    x: this rank's full-size tensor [N] (N divisible by n_ranks);
    worker_error: [N]; server_error: [N // n_ranks].
    Returns (averaged tensor [N], new_worker_error, new_server_error)."""
    import jax
    import jax.numpy as jnp

    N = x.shape[0]
    chunk = N // n_ranks

    # worker compression
    compensated = x + worker_error
    w_scale, w_sign = _sign_compress(compensated)
    new_worker_error = compensated - w_scale * w_sign.astype(x.dtype)

    # exchange: every rank receives all ranks' signs for ITS chunk — the
    # reference's chunked all_to_all; int8 on the wire
    my_signs = jax.lax.all_to_all(w_sign.reshape(n_ranks, chunk), axis_name, 0, 0,
                                  tiled=True)  # [n_ranks, chunk] int8, rows = sources
    scales = jax.lax.all_gather(w_scale, axis_name)  # [n_ranks] f32
    server_avg = jnp.einsum("r,rc->c", scales, my_signs.astype(x.dtype)) / n_ranks

    # server compression of the owned chunk
    comp_server = server_avg + server_error
    s_scale, s_sign = _sign_compress(comp_server)
    new_server_error = comp_server - s_scale * s_sign.astype(x.dtype)

    # allgather the compressed server chunks back to everyone
    all_signs = jax.lax.all_gather(s_sign, axis_name)       # [n_ranks, chunk] int8
    all_scales = jax.lax.all_gather(s_scale, axis_name)     # [n_ranks]
    out = (all_scales[:, None] * all_signs.astype(x.dtype)).reshape(N)
    return out, new_worker_error, new_server_error


def compressed_allreduce(tensor, worker_error, server_error, axis_name=None, mesh=None):
    """Host-level entry: runs the 1-bit allreduce over a mesh axis via
    shard_map; inputs are replicated full-size arrays (the engine's grads)."""
    import jax
    from jax.sharding import PartitionSpec as P

    axis_name = axis_name or groups.DATA_AXIS
    mesh = mesh if mesh is not None else groups.get_mesh()
    n = int(mesh.shape.get(axis_name, 1))
    if n <= 1:
        return tensor, worker_error, server_error

    fn = _compat_shard_map(
        lambda x, we, se: compressed_allreduce_local(x[0], we[0], se[0], axis_name, n),
        mesh=mesh,
        in_specs=(P(axis_name), P(axis_name), P(axis_name)),
        # worker/server error feedback is PER-RANK state: keep it sharded over
        # the axis (reference: each rank persists its own worker_error buffer)
        out_specs=(P(), P(axis_name), P(axis_name)),
        check_vma=False)
    # feed each rank its own (replicated) copy: stack over the axis
    import jax.numpy as jnp
    xs = jnp.broadcast_to(tensor, (n, ) + tensor.shape)
    wes = worker_error.reshape((n, -1)) if worker_error.ndim == 1 and \
        worker_error.shape[0] == n * tensor.shape[0] else jnp.broadcast_to(
            worker_error, (n, ) + worker_error.shape)
    ses = server_error.reshape((n, -1))
    out, we, se = fn(xs, wes, ses)
    # flat stacked layouts ([n*N] / [N]) so the next call's reshape round-trips
    return out, we.reshape(-1), se.reshape(-1)


def quantized_reduce_scatter_local(x, axis_name: str, n_ranks: int, block: int = 512):
    """qgZ-analog body (inside shard_map): blockwise-int8 quantize the local
    gradient, all-to-all the int8 payload + f32 block scales, dequantize and
    sum locally → this rank's reduced chunk. 4x wire compression vs f32
    reduce-scatter (reference all_to_all_quant_reduce,
    coalesced_collectives.py:31)."""
    import jax
    import jax.numpy as jnp

    N = x.shape[0]
    chunk = N // n_ranks
    # pad each rank's chunk up to whole blocks so any N divisible by n_ranks
    # works (the padding quantizes to exact zeros and is sliced off)
    nb = -(-chunk // block)
    pad = nb * block - chunk

    v = x.reshape(n_ranks, chunk)
    if pad:
        v = jnp.pad(v, ((0, 0), (0, pad)))
    v = v.reshape(n_ranks, nb, block)
    scale = jnp.max(jnp.abs(v), axis=-1, keepdims=True) / 127.0
    scale = jnp.maximum(scale, 1e-12)
    q = jnp.clip(jnp.round(v / scale), -127, 127).astype(jnp.int8)

    q_recv = jax.lax.all_to_all(q, axis_name, 0, 0, tiled=True)          # int8 wire
    s_recv = jax.lax.all_to_all(scale, axis_name, 0, 0, tiled=True)      # f32 scales
    deq = q_recv.astype(jnp.float32) * s_recv
    return jnp.sum(deq, axis=0).reshape(nb * block)[:chunk]


def quantized_reduce_scatter(tensor, axis_name=None, mesh=None, block: int = 512):
    """Host-level qgZ-style reduce-scatter: dim0 of ``tensor`` = per-rank
    contiguous input copies (the comm API's layout); returns dim0 = per-rank
    reduced chunks."""
    import jax
    from jax.sharding import PartitionSpec as P

    axis_name = axis_name or groups.DATA_AXIS
    mesh = mesh if mesh is not None else groups.get_mesh()
    n = int(mesh.shape.get(axis_name, 1))
    if n <= 1:
        return tensor
    if tensor.shape[-1] % n != 0:
        raise ValueError(f"reduce-scatter length {tensor.shape[-1]} must be divisible "
                         f"by the axis size {n} (pad the flat gradient first)")

    fn = _compat_shard_map(
        lambda x: quantized_reduce_scatter_local(x[0], axis_name, n, block),
        mesh=mesh, in_specs=P(axis_name), out_specs=P(axis_name), check_vma=False)
    return fn(tensor)
