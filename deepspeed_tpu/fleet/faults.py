"""Deterministic fault injection for the fleet layer (the chaos harness).

Every recovery path the fault-tolerance subsystem claims — failover, circuit
breaking, decode-leg re-dispatch, supervisor restarts — must be *testable* on
the tier-1 CPU mesh, reproducibly, not by anecdotal kill-a-process demos. The
:class:`FaultInjector` makes failures a pure function of ``(seed, point, n)``:
the *n*-th event at an injection point fires iff a hash of the seed, the point
key and *n* falls under that point's probability. No wall clock, no shared RNG
stream — thread interleaving changes which *request* hits a scheduled fault,
never the schedule itself, so the identical seed reproduces the identical
fault schedule (:meth:`would_fire` recomputes it from scratch).

Injection points (all consulted by ``fleet/router.py``; each scoped
*per-replica* where a replica identity exists, so e.g. consecutive 5xx bursts
land on one replica and exercise its circuit breaker):

- ``dispatch_delay`` — sleep before dispatching a leg (slow network / GC pause);
- ``connect_reset`` — the dispatch connection dies before admission;
- ``http_5xx`` — the replica answers 503 at admission;
- ``stream_truncate`` — the SSE leg dies mid-stream after K tokens;
- ``handoff_corrupt`` — the prefill→decode payload is corrupted/truncated in
  transit (for ONE dispatch attempt; the router's buffered copy stays pristine);
- ``replica_kill`` — the chosen replica is killed outright (the supervisor's
  restart path);
- ``decode_stall`` — a seeded per-token delay on one replica's token stream
  (``decode_stall_replica`` scopes it): the slow-but-alive replica the
  circuit breaker never sees, what hedged dispatch exists to beat;
- ``overload_burst`` — a synthetic admission burst: the router's global queue
  gains ``overload_burst_requests`` phantom entries held for
  ``overload_burst_hold_s``, deterministically exercising queue-depth
  pressure, Retry-After growth and shedding.

Disabled is the default and costs one ``None`` check at every hook; the
injector only exists when ``FleetConfig.faults.enabled`` (or the
``DSTPU_FAULTS`` env var, a JSON ``FaultConfig`` body) says so.
"""

import hashlib
import threading
from collections import deque
from typing import Dict, List, Optional, Tuple

from pydantic import Field

from deepspeed_tpu.runtime.config_utils import DeepSpeedConfigModel

# every injection point the router consults; would_fire rejects unknown ones
# so a typo'd hook cannot silently never fire
POINTS = ("dispatch_delay", "connect_reset", "http_5xx", "stream_truncate",
          "handoff_corrupt", "replica_kill", "decode_stall", "overload_burst",
          "peer_fetch_corrupt", "steal_race", "park_store_corrupt",
          "demote_race")

_EVENT_LOG_CAP = 512  # per injector, for the recovery report


class FaultConfig(DeepSpeedConfigModel):
    """Chaos-harness knobs. All probabilities are per *event* at the point
    (per dispatch attempt, per stream, per payload hop, ...)."""

    enabled: bool = False
    """Master switch; False = no injector is constructed at all."""

    allow_remote: bool = False
    """Expose ``POST /v1/fleet/chaos`` on the router so a loadgen run can arm
    / re-seed the injector over HTTP (``bin/dstpu_loadgen --chaos``). Keep
    False anywhere untrusted clients can reach the router."""

    seed: int = 0
    """The schedule seed: identical seed = identical fault schedule."""

    dispatch_delay_p: float = Field(0.0, ge=0, le=1)
    dispatch_delay_max_s: float = Field(0.05, ge=0)
    """Injected dispatch latency is uniform in (0, max], hash-derived."""

    connect_reset_p: float = Field(0.0, ge=0, le=1)
    http_5xx_p: float = Field(0.0, ge=0, le=1)
    http_5xx_burst: int = Field(1, ge=1)
    """When a 5xx fires, the next ``burst-1`` events at the same (point,
    replica) fire too — consecutive failures are what trips a breaker."""

    stream_truncate_p: float = Field(0.0, ge=0, le=1)
    stream_truncate_max_tokens: int = Field(4, ge=0)
    """A truncated stream dies after a hash-derived 0..max token prefix."""

    handoff_corrupt_p: float = Field(0.0, ge=0, le=1)
    replica_kill_p: float = Field(0.0, ge=0, le=1)

    peer_fetch_corrupt_p: float = Field(0.0, ge=0, le=1)
    """Per-peer-prefix-fetch probability of corrupting the fetched KV frame
    in transit (byte flip in the CRC-covered region / truncation): the
    importer must reject loudly and recompute cold, never publish a
    corrupted block into its trie."""

    steal_race_p: float = Field(0.0, ge=0, le=1)
    """Per-steal probability that the victim finishes the request while the
    steal decision is in flight: the router must keep the original leg and
    complete exactly once (no duplicate tokens, no lost request)."""

    park_store_corrupt_p: float = Field(0.0, ge=0, le=1)
    """Per-rehydrate-dispatch probability of corrupting the parked frame
    sent to the target replica (the store's copy stays pristine): the replica
    must reject loudly on CRC/framing and the router must fall back to a cold
    full-prompt run, never continue from half-corrupt KV."""

    demote_race_p: float = Field(0.0, ge=0, le=1)
    """Per-demotion probability of injecting a concurrent read into the
    tier writer's spill-to-commit window (``TieredKVStore.race_hook``): the
    reader must reclaim the entry to host, the writer must discard its
    orphan file, and the race must be counted — never a read of a
    half-written spill."""

    decode_stall_p: float = Field(0.0, ge=0, le=1)
    """Per-token probability of an injected stall on the leg's token stream
    (the slow-but-alive replica: latency stretches, the breaker — which keys
    on failures — never trips)."""

    decode_stall_s: float = Field(0.05, ge=0)
    """Stall ceiling: each firing sleeps a hash-derived uniform
    (0, decode_stall_s]."""

    decode_stall_replica: Optional[str] = None
    """Scope the stall to ONE replica id (the hedging scenario: exactly one
    slow member stretches fleet p99); None = every replica is subject."""

    overload_burst_p: float = Field(0.0, ge=0, le=1)
    """Per-admitted-request probability of injecting a synthetic burst into
    the router's global queue."""

    overload_burst_requests: int = Field(8, ge=1)
    """Phantom queue entries per burst (batch priority, never granted)."""

    overload_burst_hold_s: float = Field(0.25, ge=0)
    """How long the phantom entries occupy the queue before expiring."""


def _u64(seed: int, key: str, n: int, salt: str = "") -> int:
    digest = hashlib.sha256(f"{seed}:{key}:{n}:{salt}".encode()).digest()
    return int.from_bytes(digest[:8], "big")


def _uniform(seed: int, key: str, n: int, salt: str = "") -> float:
    """Deterministic uniform [0, 1) for the n-th event at ``key``."""
    return _u64(seed, key, n, salt) / 2.0 ** 64


class FaultInjector:
    """Seed-driven fault schedule over named injection points.

    One counter per ``(point, scope)`` key (scope = replica id where one
    exists); :meth:`fire` consumes the next index for the key and answers
    whether that event faults. All mutation is under one lock — the counters
    are the only state, so the hot disabled path in the router is just the
    ``injector is None`` check at each hook.
    """

    def __init__(self, config: FaultConfig):
        self.config = config
        self._lock = threading.Lock()
        self._counters: Dict[str, int] = {}
        self._fired: Dict[str, int] = {}          # point -> total fired
        self._events: deque = deque(maxlen=_EVENT_LOG_CAP)

    # ---------------------------------------------------------------- schedule --
    def _p(self, point: str) -> float:
        return getattr(self.config, f"{point}_p")

    def _burst(self, point: str) -> int:
        return self.config.http_5xx_burst if point == "http_5xx" else 1

    @staticmethod
    def _key(point: str, scope: Optional[str]) -> str:
        return f"{point}@{scope}" if scope else point

    def would_fire(self, point: str, n: int, scope: Optional[str] = None) -> bool:
        """Pure schedule query: does the n-th event at ``(point, scope)``
        fault? Recomputed from the seed alone — the reproducibility oracle the
        chaos tests diff a live run against."""
        if point not in POINTS:
            raise ValueError(f"unknown injection point {point!r} (know {POINTS})")
        p, burst = self._p(point), self._burst(point)
        if p <= 0.0:
            return False
        if _uniform(self.config.seed, self._key(point, scope), n) < p:
            return True
        # inside a burst started by an earlier firing index?
        for back in range(1, burst):
            if n - back >= 0 and _uniform(self.config.seed,
                                          self._key(point, scope), n - back) < p:
                return True
        return False

    def schedule(self, point: str, count: int,
                 scope: Optional[str] = None) -> List[int]:
        """The firing indices among the first ``count`` events — the whole
        deterministic schedule for a key, for reports and tests."""
        return [n for n in range(count) if self.would_fire(point, n, scope)]

    # -------------------------------------------------------------------- fire --
    def fire(self, point: str, scope: Optional[str] = None) -> Optional[int]:
        """Consume the next event index at ``(point, scope)``; returns the
        index when that event faults, None otherwise."""
        key = self._key(point, scope)
        with self._lock:
            n = self._counters.get(key, 0)
            self._counters[key] = n + 1
            # the live decision IS the pure oracle — fire() adds only the
            # per-key event counter, so a replayed schedule cannot diverge
            # from a recorded run
            if self.would_fire(point, n, scope):
                self._fired[point] = self._fired.get(point, 0) + 1
                self._events.append({"point": point, "scope": scope, "n": n})
                return n
        return None

    # ----------------------------------------------------- fault-shape helpers --
    def delay_s(self, n: int, scope: Optional[str] = None) -> float:
        """Injected dispatch delay for firing index ``n``: uniform
        (0, dispatch_delay_max_s], hash-derived so the same index always
        delays the same amount."""
        u = _uniform(self.config.seed, self._key("dispatch_delay", scope), n, "len")
        return self.config.dispatch_delay_max_s * max(u, 1e-3)

    def stalls_replica(self, replica_id: Optional[str]) -> bool:
        """Is this replica's stream subject to ``decode_stall`` at all? One
        cheap check before the per-token ``fire`` consult — a scoped stall
        must not consume schedule indices on unscoped replicas (the oracle
        and the live run count the same events)."""
        return (self.config.decode_stall_p > 0
                and self.config.decode_stall_replica in (None, replica_id))

    def stall_s(self, n: int, scope: Optional[str] = None) -> float:
        """Injected per-token stall for firing index ``n``: uniform
        (0, decode_stall_s], hash-derived like :meth:`delay_s`."""
        u = _uniform(self.config.seed, self._key("decode_stall", scope), n, "len")
        return self.config.decode_stall_s * max(u, 1e-3)

    def truncate_after(self, n: int, scope: Optional[str] = None) -> int:
        """How many tokens a truncated stream yields before dying."""
        u = _uniform(self.config.seed, self._key("stream_truncate", scope), n, "len")
        return int(u * (self.config.stream_truncate_max_tokens + 1))

    def corrupt(self, payload: bytes, n: int, scope: Optional[str] = None,
                point: str = "handoff_corrupt") -> bytes:
        """A corrupted copy of ``payload`` for firing index ``n``: either a
        short (truncated) payload — the framing/length validation path — or
        one with a byte flipped inside the raw-KV region, which only the
        payload's ``kv_crc32`` can catch. Both shapes must be a loud
        ``ValueError`` at unpack, never silently wrong attention. The same
        shape serves ``handoff_corrupt`` (prefill→decode hop) and
        ``peer_fetch_corrupt`` (cross-replica prefix fetch) via ``point``."""
        u = _uniform(self.config.seed, self._key(point, scope), n, "mode")
        if not payload:
            return payload
        if u < 0.5:  # short payload: framing/length validation path
            return payload[:max(1, int(len(payload) * u))]
        bad = bytearray(payload)
        # flip past the JSON header (MAGIC + u32 length prefix + header):
        # a flip inside the header could keep the JSON valid and mutate a
        # token id silently — the KV region is checksummed, so a flip there
        # is guaranteed loud
        from deepspeed_tpu.inference.v2.ragged.handoff import MAGIC
        kv_off = 0
        frame = len(MAGIC) + 4
        if len(bad) > frame and bad[:len(MAGIC)] == MAGIC:
            import struct
            kv_off = min(len(bad) - 1,
                         frame + struct.unpack_from("<I", bad, len(MAGIC))[0])
        pos = kv_off + _u64(self.config.seed, self._key(point, scope),
                            n, "pos") % max(1, len(bad) - kv_off)
        bad[min(pos, len(bad) - 1)] ^= 0xFF
        return bytes(bad)

    # ------------------------------------------------------------------ report --
    def report(self) -> dict:
        """Recovery-report body: per-point fired totals, per-key event counts
        and the recent firing log (bounded)."""
        with self._lock:
            return {
                "seed": self.config.seed,
                "fired": dict(self._fired),
                "events_seen": dict(self._counters),
                "recent": list(self._events),
            }


def config_from_env(env_value: Optional[str]) -> Optional[FaultConfig]:
    """Parse the ``DSTPU_FAULTS`` env var (a JSON ``FaultConfig`` body, e.g.
    ``{"enabled": true, "seed": 7, "replica_kill_p": 0.02}`` — or just
    ``{"allow_remote": true}`` to expose the chaos endpoint without arming
    anything at start, the ``dstpu_loadgen --chaos`` flow). None when unset.
    Malformed JSON raises — a chaos run with a typo'd config must not
    silently run clean."""
    if not env_value:
        return None
    import json
    return FaultConfig(**json.loads(env_value))


def injector_from_env(env_value: Optional[str]) -> Optional[FaultInjector]:
    """An armed injector from ``DSTPU_FAULTS``; None when unset/disabled."""
    config = config_from_env(env_value)
    return FaultInjector(config) if config is not None and config.enabled else None
