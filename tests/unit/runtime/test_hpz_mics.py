"""ZeRO++ hpZ (secondary tensor partition) and MiCS (shard-within-group,
replicate-across-groups).

Reference: ``deepspeed/runtime/zero/config.py`` (zero_hpz_partition_size),
``parameter_offload.py``/stage3 secondary-partition path, and
``deepspeed/runtime/zero/mics.py`` (MiCS_Optimizer:171 — params sharded inside
a shard group, allgathers intra-group, grad sync across replica groups).

TPU formulation: the data dimension splits into (data, hpz); hpZ shards stage-3
parameters over only the inner ``hpz`` axis (intra-node allgathers) while
optimizer state and gradients stay sharded over the full ZeRO group; MiCS
restricts everything to the subgroup, and XLA's psum over the replicated
``data`` axis is the cross-group gradient sync.
"""

import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.utils import groups

from ..simple_model import make_simple_model, random_batches

HIDDEN = 16


def _cfg(stage=3, hpz=None, mics=None):
    z = {"stage": stage, "stage3_param_persistence_threshold": 0}
    if hpz:
        z["zero_hpz_partition_size"] = hpz
    if mics:
        z["mics_shard_size"] = mics
    return {
        "train_micro_batch_size_per_gpu": 2,
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": "AdamW", "params": {"lr": 0.01, "weight_decay": 0.0}},
        "zero_optimization": z,
    }


def _train(engine, batches):
    for b in batches:
        loss = engine.forward(b)
        engine.backward(loss)
        engine.step()


def _axes_of(sharding):
    out = set()
    for entry in sharding.spec:
        if entry is None:
            continue
        for ax in (entry if isinstance(entry, tuple) else (entry, )):
            out.add(ax)
    return out


def test_hpz_param_placement_and_parity():
    """hpz=2 on 8 devices: params sharded over ONLY the 2-wide hpz axis
    (intra-node allgather), moments over the full (data, hpz) group; numerics
    match plain ZeRO-3."""
    import jax

    model, params0 = make_simple_model(hidden_dim=HIDDEN, batch_size=16)
    batches = random_batches(4, 16, HIDDEN)

    groups.destroy_mesh()
    ref, _, _, _ = deepspeed_tpu.initialize(model=model, model_parameters=params0,
                                            config=_cfg(stage=3))
    _train(ref, batches)

    groups.destroy_mesh()
    eng, _, _, _ = deepspeed_tpu.initialize(model=model, model_parameters=params0,
                                            config=_cfg(stage=3, hpz=2))
    assert eng.mesh.shape[groups.HPZ_AXIS] == 2 and eng.mesh.shape[groups.DATA_AXIS] == 4

    sharded_params = [l for l in jax.tree.leaves(eng._param_shardings) if _axes_of(l)]
    assert sharded_params, "stage 3 must shard some parameters"
    for s in sharded_params:
        assert _axes_of(s) <= {groups.HPZ_AXIS}, \
            f"hpZ params must shard over the secondary group only, got {s.spec}"
    opt_axes = set().union(*[_axes_of(l) for l in jax.tree.leaves(eng._opt_shardings)])
    assert groups.DATA_AXIS in opt_axes, "optimizer state keeps the full ZeRO partition"

    _train(eng, batches)
    for a, b in zip(jax.tree.leaves(jax.device_get(eng.params)),
                    jax.tree.leaves(jax.device_get(ref.params))):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-5)


def test_mics_placement_and_parity():
    """mics_shard_size=2: params AND optimizer state live in the 2-wide shard
    group (replicated across the 4 replica groups); numerics match ZeRO-3."""
    import jax

    model, params0 = make_simple_model(hidden_dim=HIDDEN, batch_size=16)
    batches = random_batches(4, 16, HIDDEN)

    groups.destroy_mesh()
    ref, _, _, _ = deepspeed_tpu.initialize(model=model, model_parameters=params0,
                                            config=_cfg(stage=3))
    _train(ref, batches)

    groups.destroy_mesh()
    eng, _, _, _ = deepspeed_tpu.initialize(model=model, model_parameters=params0,
                                            config=_cfg(stage=3, mics=2))
    for tree in (eng._param_shardings, eng._opt_shardings, eng._grad_shardings):
        for s in jax.tree.leaves(tree):
            assert groups.DATA_AXIS not in _axes_of(s), \
                f"MiCS state must not shard across replica groups, got {s.spec}"
    assert any(groups.HPZ_AXIS in _axes_of(s) for s in jax.tree.leaves(eng._param_shardings))

    _train(eng, batches)
    for a, b in zip(jax.tree.leaves(jax.device_get(eng.params)),
                    jax.tree.leaves(jax.device_get(ref.params))):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-5)


def test_hpz_requires_divisible_split():
    model, params0 = make_simple_model(hidden_dim=HIDDEN, batch_size=16)
    groups.destroy_mesh()
    with pytest.raises(groups.TopologyError):
        deepspeed_tpu.initialize(model=model, model_parameters=params0,
                                 config=_cfg(stage=3, hpz=3))
