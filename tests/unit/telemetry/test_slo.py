"""SLOEngine: burn-rate math, multi-window breach episodes, the flight-dump
trigger, and the config-gated consumer signals."""

import glob
import os

import pytest

from deepspeed_tpu import telemetry
from deepspeed_tpu.telemetry import MetricsRegistry, SLOConfig, TelemetryConfig
from deepspeed_tpu.telemetry.slo import SLOEngine
from deepspeed_tpu.telemetry.timeseries import TimeSeriesStore

TTFT_BUCKETS = (0.1, 0.5, 1.0)


def _engine(reg, **objective):
    spec = {"name": "ttft", "metric": "ttft", "target_s": 0.1,
            "target_ratio": 0.9, "fast_window_s": 10.0, "slow_window_s": 30.0,
            "burn_threshold": 2.0}
    spec.update(objective)
    store = TimeSeriesStore(reg, interval_s=1.0)
    config = SLOConfig(enabled=True, objectives=[spec])
    return SLOEngine(config, store, reg), store


def test_burn_rate_is_bad_fraction_over_budget():
    reg = MetricsRegistry()
    h = reg.histogram("serving_ttft_seconds", "ttft", buckets=TTFT_BUCKETS)
    engine, store = _engine(reg)
    store.tick(now=0.0)
    # 10 observations, 2 bad (above 0.1s): bad_frac 0.2, allowed 0.1 → burn 2
    for _ in range(8):
        h.observe(0.05)
    for _ in range(2):
        h.observe(0.9)
    store.tick(now=1.0)  # on_tick drives evaluate()
    status = store is engine.store and engine.status()
    obj = status["objectives"][0]
    assert obj["fast_burn"] == pytest.approx(2.0, rel=0.01)
    assert obj["slow_burn"] == pytest.approx(2.0, rel=0.01)
    # the burn gauges are registered per objective/window and sampled
    fast = reg.gauge("slo_burn_rate", labels={"slo": "ttft", "window": "fast"})
    assert fast.value == pytest.approx(2.0, rel=0.01)


def test_no_traffic_burns_nothing():
    reg = MetricsRegistry()
    reg.histogram("serving_ttft_seconds", "ttft", buckets=TTFT_BUCKETS)
    engine, store = _engine(reg)
    store.tick(now=0.0)
    store.tick(now=1.0)
    status = engine.status()
    assert status["objectives"][0]["fast_burn"] == 0.0
    assert not status["in_breach"]
    assert engine.breach_signal() == 0.0


def test_breach_requires_both_windows_and_counts_episodes_once():
    reg = MetricsRegistry()
    h = reg.histogram("serving_ttft_seconds", "ttft", buckets=TTFT_BUCKETS)
    engine, store = _engine(reg)
    store.tick(now=0.0)
    for _ in range(20):
        h.observe(0.9)  # all bad: burn 10x
    store.tick(now=1.0)
    assert engine.in_breach()
    breaches = reg.counter("slo_breaches_total")
    assert breaches.value == 1
    # still breaching on the next tick: same episode, no new count
    for _ in range(20):
        h.observe(0.9)
    store.tick(now=2.0)
    assert breaches.value == 1
    # the fast window drains (no new observations) → episode closes even
    # though the slow window still remembers the burn
    store.tick(now=12.0)
    store.tick(now=12.5)
    assert not engine.in_breach()
    status = engine.status()["objectives"][0]
    assert status["fast_burn"] == 0.0 and status["slow_burn"] > 2.0
    # a fresh burn opens a NEW episode
    for _ in range(20):
        h.observe(0.9)
    store.tick(now=13.0)
    assert engine.in_breach()
    assert breaches.value == 2
    assert status["breaches"] == 1  # snapshot from before the second episode
    assert engine.status()["objectives"][0]["breaches"] == 2


def test_error_rate_and_goodput_objectives():
    reg = MetricsRegistry()
    done = reg.counter("serving_completions_total", "done")
    failed = reg.counter("serving_failures_total", "failed")
    shed = reg.counter("serving_shed_admission_total", "shed")
    engine, store = _engine(reg, name="errors", metric="error_rate",
                            target_ratio=0.95)
    store.tick(now=0.0)
    done.inc(8)
    failed.inc(2)
    shed.inc(10)
    store.tick(now=1.0)
    # error_rate ignores sheds: 2 bad / 10 terminal = 0.2 over 0.05 → 4x
    obj = engine.status()["objectives"][0]
    assert obj["fast_burn"] == pytest.approx(4.0)

    reg2 = MetricsRegistry()
    done2 = reg2.counter("serving_completions_total", "done")
    shed2 = reg2.counter("serving_shed_admission_total", "shed")
    engine2, store2 = _engine(reg2, name="goodput", metric="goodput",
                              target_ratio=0.5)
    store2.tick(now=0.0)
    done2.inc(5)
    shed2.inc(15)
    store2.tick(now=1.0)
    # goodput counts sheds: 15 bad / 20 outcomes = 0.75 over 0.5 → 1.5x
    obj2 = engine2.status()["objectives"][0]
    assert obj2["fast_burn"] == pytest.approx(1.5)


def test_breach_signal_is_normalized_and_clamped():
    reg = MetricsRegistry()
    h = reg.histogram("serving_ttft_seconds", "ttft", buckets=TTFT_BUCKETS)
    engine, store = _engine(reg)
    store.tick(now=0.0)
    for _ in range(10):
        h.observe(0.9)
    store.tick(now=1.0)
    assert engine.breach_signal() == 1.0  # 10x burn over a 2x threshold, clamped
    no_objectives = SLOEngine(SLOConfig(enabled=True), store, reg)
    assert no_objectives.breach_signal() == 0.0


def test_breach_fires_one_flight_dump_per_episode(tmp_path, fresh_telemetry):
    session = telemetry.configure(TelemetryConfig(
        enabled=True,
        flight_recorder={"enabled": True, "dir": str(tmp_path),
                         "watchdog_enabled": False},
        timeseries={"interval_s": 60.0},
        slo={"enabled": True,
             "objectives": [{"name": "ttft", "metric": "ttft",
                             "target_s": 0.1, "target_ratio": 0.9,
                             "fast_window_s": 10.0, "slow_window_s": 30.0,
                             "burn_threshold": 2.0}]}))
    try:
        reg = telemetry.get_registry()
        store = telemetry.get_timeseries()
        assert store is not None  # SLO implies the store even without timeseries
        h = reg.histogram("serving_ttft_seconds", "ttft", buckets=TTFT_BUCKETS)
        store.tick(now=0.0)
        for _ in range(20):
            h.observe(0.9)
        store.tick(now=1.0)   # breach opens → one dump
        store.tick(now=2.0)   # same episode → no second dump
        dumps = glob.glob(os.path.join(str(tmp_path), "*slo_breach*.json"))
        assert len(dumps) == 1
        # the stats/fleet surface reads the same engine
        assert telemetry.get_slo_engine().status()["in_breach"]
    finally:
        session.close()
    assert telemetry.get_slo_engine() is None
