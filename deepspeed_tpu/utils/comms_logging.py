"""Per-op communication statistics.

Reference: ``deepspeed/utils/comms_logging.py:67`` (CommsLogger) — per-op message-size
histograms with count/latency/algbw/busbw and straggler detection via
``dist.log_summary``.
"""

import math
from collections import defaultdict

from deepspeed_tpu.utils.logging import logger


def get_caller_func(frame=3):
    import sys
    return sys._getframe(frame).f_code.co_name


def convert_size(size_bytes):
    if size_bytes == 0:
        return "0B"
    size_name = ("B", "KB", "MB", "GB", "TB", "PB")
    i = int(math.floor(math.log(size_bytes, 1024)))
    p = math.pow(1024, i)
    s = round(size_bytes / p, 2)
    return f"{s} {size_name[i]}"


def calc_bw_log(comm_op, size, duration, n):
    """Algorithm/bus bandwidth for a collective (reference comms_logging.py:32)."""
    if duration <= 0:
        return 0, 0, 0
    tput = size / duration
    if comm_op in ("all_to_all_single", ):
        busbw = tput * ((n - 1) / n) if n > 0 else tput
    elif comm_op in ("all_gather_into_tensor", "reduce_scatter_tensor", "allgather_fn", "reduce_scatter_fn"):
        busbw = tput * ((n - 1) / n) if n > 0 else tput
    elif comm_op in ("all_reduce", "inference_all_reduce"):
        busbw = tput * (2 * (n - 1) / n) if n > 0 else tput
    else:
        busbw = tput
    return tput / 1e9, busbw / 1e9, duration * 1e3


class CommsLogger:

    def __init__(self):
        self.comms_dict = defaultdict(lambda: defaultdict(lambda: [0, [], [], []]))
        self.verbose = False
        self.debug = False
        self.prof_ops = []
        self.prof_all = True
        self.enabled = False

    def configure(self, deepspeed_config=None, enabled=None, prof_all=None, prof_ops=None, verbose=None, debug=None):
        if deepspeed_config is not None:
            cl = getattr(deepspeed_config, "comms_config", None)
            if cl is not None:
                self.enabled = cl.enabled
                self.prof_all = cl.prof_all
                self.prof_ops = cl.prof_ops
                self.verbose = cl.verbose
                self.debug = cl.debug
        if enabled is not None:
            self.enabled = enabled
        if prof_all is not None:
            self.prof_all = prof_all
        if prof_ops is not None:
            self.prof_ops = prof_ops
        if verbose is not None:
            self.verbose = verbose
        if debug is not None:
            self.debug = debug

    def append(self, raw_name, record_name, latency, msg_size, n=1):
        if self.prof_ops and raw_name not in self.prof_ops and not self.prof_all:
            return
        entry = self.comms_dict[record_name][msg_size]
        algbw, busbw, lat_ms = calc_bw_log(raw_name, msg_size, latency, n)
        entry[0] += 1
        entry[1].append(lat_ms)
        entry[2].append(algbw)
        entry[3].append(busbw)
        if self.verbose:
            logger.info(f"comm op: {record_name} | time (ms): {lat_ms:.2f} | "
                        f"msg size: {convert_size(msg_size)} | algbw (Gbps): {algbw*8:.2f} | "
                        f"busbw (Gbps): {busbw*8:.2f}")

    def log_all(self, print_log=True, show_straggler=False):
        from numpy import mean
        lines = [f"{'Comm. Op':<20}{'Message Size':<20}{'Count':<10}"
                 f"{'Total Latency(ms)':<20}{'Avg Latency(ms)':<20}{'tput_avg (Gbps)':<20}{'busbw_avg (Gbps)':<20}"]
        for record_name in self.comms_dict.keys():
            lines.append(record_name)
            for msg_size, vals in sorted(self.comms_dict[record_name].items()):
                count, latencies, algbws, busbws = vals
                lines.append(f"{'':<20}{convert_size(msg_size):<20}{count:<10}"
                             f"{sum(latencies):<20.2f}{mean(latencies):<20.2f}"
                             f"{mean(algbws)*8:<20.2f}{mean(busbws)*8:<20.2f}")
        if show_straggler:
            lines.append("")
            lines.extend(self._straggler_summary())
        out = "\n".join(lines)
        if print_log:
            logger.info("\n" + out)
        return out

    def _straggler_summary(self):
        """Per-op straggler effect (reference: ``dist.log_summary``'s straggler
        mode). Multi-process, latencies are gathered across ranks and the
        straggler is the slowest rank's mean vs the fleet mean; single-process
        it degrades to max-vs-mean across this process's records.

        COLLECTIVE when multi-process (exactly like the reference's
        ``log_summary``): every process must call it — do NOT guard the call
        with ``if rank == 0`` or the allgather deadlocks; gate the *printing*
        instead (``log_all(print_log=(rank == 0), ...)``)."""
        from numpy import mean
        lines = [f"{'Straggler summary':<20}",
                 f"{'Comm. Op':<20}{'Count':<10}{'Mean Lat(ms)':<16}"
                 f"{'Max Lat(ms)':<16}{'Straggler(ms)':<16}"]
        cross = self._cross_process_stats()
        for record_name, sizes in self.comms_dict.items():
            lats = [lat for vals in sizes.values() for lat in vals[1]]
            if not lats:
                continue
            local_mean, local_max = float(mean(lats)), float(max(lats))
            if cross is not None and record_name in cross:
                g_mean, g_max = cross[record_name]
                straggler = g_max - g_mean
                local_mean, local_max = g_mean, g_max
            else:
                straggler = local_max - local_mean
            lines.append(f"{record_name:<20}{len(lats):<10}{local_mean:<16.2f}"
                         f"{local_max:<16.2f}{straggler:<16.2f}")
        return lines

    def _cross_process_stats(self):
        """{op: (fleet mean-of-rank-means, slowest rank mean)} in ms when
        ``deepspeed_tpu.comm`` is initialized multi-process, else None. Every
        rank records the same op set under SPMD, so the allgather is aligned."""
        try:
            import jax
            from deepspeed_tpu import comm as dist
            if not dist.is_initialized() or jax.process_count() <= 1:
                return None
            import numpy as np
            from jax.experimental import multihost_utils
            ops = sorted(self.comms_dict.keys())
            if not ops:
                return None
            means = np.array([
                np.mean([lat for vals in self.comms_dict[op].values() for lat in vals[1]] or [0.0])
                for op in ops
            ], np.float32)
            gathered = np.asarray(multihost_utils.process_allgather(means))  # [P, n_ops]
            return {op: (float(gathered[:, i].mean()), float(gathered[:, i].max()))
                    for i, op in enumerate(ops)}
        except Exception:
            return None
