"""``dstpu`` CLI — the ``deepspeed`` launcher equivalent.

Reference: ``deepspeed/launcher/runner.py:388`` (main), ``fetch_hostfile:200``,
include/exclude filtering (``parse_resource_filter``), runner selection. Usage:

    dstpu --hostfile /job/hostfile train.py --deepspeed_config ds.json
    dstpu --num_nodes 1 --num_chips 4 train.py ...

Single-node launches exec the per-node spawner directly; multi-node launches
render a pdsh/ssh/srun command. Spawned processes receive
``DSTPU_COORDINATOR/NUM_PROCESSES/PROCESS_ID`` which
``deepspeed_tpu.comm.init_distributed`` feeds to ``jax.distributed.initialize``
(the JAX coordination-service rendezvous replacing torch.distributed's).
"""

import argparse
import os
import subprocess
import sys
from collections import OrderedDict

from deepspeed_tpu.utils.logging import logger

DLTS_HOSTFILE = "/job/hostfile"


def parse_args(argv=None):
    parser = argparse.ArgumentParser(
        description="dstpu launcher (reference: deepspeed/launcher/runner.py)")
    parser.add_argument("-H", "--hostfile", type=str, default=DLTS_HOSTFILE,
                        help="hostfile with lines '<hostname> slots=<n>'")
    parser.add_argument("-i", "--include", type=str, default="",
                        help="e.g. 'host1@host2:0,2' — restrict hosts/slots")
    parser.add_argument("-e", "--exclude", type=str, default="",
                        help="e.g. 'host1:1@host2' — drop hosts/slots")
    parser.add_argument("--num_nodes", type=int, default=-1)
    parser.add_argument("--num_chips", "--num_gpus", dest="num_chips", type=int, default=-1)
    parser.add_argument("--master_addr", type=str, default="")
    parser.add_argument("--master_port", type=int, default=29500)
    parser.add_argument("--launcher", type=str, default="pdsh",
                        choices=("pdsh", "ssh", "slurm", "local"))
    parser.add_argument("--module", action="store_true")
    parser.add_argument("--no_python", action="store_true")
    parser.add_argument("--force_multi", action="store_true")
    parser.add_argument("--slurm_comment", type=str, default="")
    parser.add_argument("user_script", type=str)
    parser.add_argument("user_args", nargs=argparse.REMAINDER)
    return parser.parse_args(argv)


def fetch_hostfile(path):
    """'<hostname> slots=<n>' per line → OrderedDict host→slots (reference
    runner.py:200). Returns None when the file doesn't exist (single-node)."""
    if not os.path.isfile(path):
        return None
    pool = OrderedDict()
    with open(path) as f:
        for line in f:
            line = line.split("#")[0].strip()
            if not line:
                continue
            try:
                host, slots = line.split()
                n = int(slots.split("=")[1])
            except (ValueError, IndexError) as e:
                raise ValueError(f"hostfile line not '<host> slots=<n>': {line!r}") from e
            if host in pool:
                raise ValueError(f"host {host} repeated in hostfile")
            pool[host] = n
    if not pool:
        raise ValueError(f"hostfile {path} is empty")
    return pool


def _parse_filter(s):
    """'host1@host2:0,2' → {host1: None (all), host2: [0, 2]}"""
    out = OrderedDict()
    for part in filter(None, s.split("@")):
        if ":" in part:
            host, slots = part.split(":")
            out[host.strip()] = sorted(int(x) for x in slots.split(","))
        else:
            out[part.strip()] = None
    return out


def parse_resource_filter(pool, include_str="", exclude_str=""):
    """Apply include/exclude to host→slots, producing host→[slot ids]
    (reference runner.py parse_resource_filter — include and exclude are
    mutually exclusive there too)."""
    if include_str and exclude_str:
        raise ValueError("--include and --exclude are mutually exclusive")
    full = OrderedDict((h, list(range(n))) for h, n in pool.items())
    if include_str:
        inc = _parse_filter(include_str)
        out = OrderedDict()
        for host, slots in inc.items():
            if host not in full:
                raise ValueError(f"include host {host} not in hostfile")
            picked = full[host] if slots is None else slots
            bad = set(picked) - set(full[host])
            if bad:
                raise ValueError(f"include slots {sorted(bad)} not available on {host}")
            out[host] = sorted(picked)
        return out
    if exclude_str:
        exc = _parse_filter(exclude_str)
        out = OrderedDict()
        for host, slots in full.items():
            if host in exc:
                if exc[host] is None:
                    continue
                keep = [s for s in slots if s not in exc[host]]
                if keep:
                    out[host] = keep
            else:
                out[host] = slots
        if not out:
            raise ValueError("exclude filter removed every host")
        return out
    return full


def _world_info(active: "OrderedDict[str, list]"):
    """host→[slot ids] → host→[global ranks], rank-ordered by host then slot."""
    world, rank = OrderedDict(), 0
    for host, slots in active.items():
        world[host] = list(range(rank, rank + len(slots)))
        rank += len(slots)
    return world


def main(argv=None):
    args = parse_args(argv)
    # strip a leading '--' that argparse.REMAINDER keeps
    if args.user_args and args.user_args[0] == "--":
        args.user_args = args.user_args[1:]

    pool = fetch_hostfile(args.hostfile)
    if pool is None:
        n = args.num_chips if args.num_chips > 0 else _local_chip_count()
        pool = OrderedDict([("localhost", n)])
    active = parse_resource_filter(pool, args.include, args.exclude)
    if args.num_nodes > 0:
        active = OrderedDict(list(active.items())[:args.num_nodes])
    if args.num_chips > 0:
        active = OrderedDict((h, s[:args.num_chips]) for h, s in active.items())
    world = _world_info(active)

    multi_node = args.force_multi or len(world) > 1
    if not args.master_addr:
        args.master_addr = next(iter(world)) if multi_node else "127.0.0.1"

    from deepspeed_tpu.launcher.multinode_runner import (LocalRunner, PDSHRunner, SlurmRunner,
                                                         SSHRunner)
    env = os.environ.copy()
    if not multi_node:
        runner = LocalRunner(args, world)
        cmd = runner.get_cmd(env, active)
        logger.info(f"dstpu local launch: {' '.join(cmd)}")
        return subprocess.call(cmd, env=env)

    runner_cls = {"pdsh": PDSHRunner, "ssh": SSHRunner, "slurm": SlurmRunner}[args.launcher]
    runner = runner_cls(args, world)
    if not runner.backend_exists():
        raise RuntimeError(f"launcher backend {args.launcher!r} not found on PATH")
    if getattr(runner, "per_node", False):
        procs = [subprocess.Popen(c, env=env) for c in runner.get_cmd(env, active)]
        rc = 0
        for p in procs:
            p.wait()
            rc = rc or p.returncode
        return rc
    cmd = runner.get_cmd(env, active)
    logger.info(f"dstpu {runner.name}: {' '.join(cmd)}")
    return subprocess.call(cmd, env=env)


def _local_chip_count():
    try:
        import jax
        return max(1, len(jax.devices()))
    except Exception:
        return 1


if __name__ == "__main__":
    sys.exit(main())
