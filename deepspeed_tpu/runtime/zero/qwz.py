"""qwZ — ZeRO++ quantized weight all-gather.

Reference: ``deepspeed/runtime/zero/partition_parameters.py:1152``
(``all_gather_coalesced`` with ``quantization`` — each rank quantizes its
shard to int8 + scales, all-gathers the int8 payload, dequantizes after) and
``CUDAQuantizer`` at ``partition_parameters.py:731`` over
``csrc/quantization/quantize.cu``.

TPU formulation: under ZeRO-3 the forward/backward parameter all-gathers are
inserted by the SPMD partitioner at each weight's consumer. qwZ interposes on
the master→compute cast: the (still sharded) fp32 shard is quantized to int8
with per-row scales along the ZeRO-sharded dimension — an elementwise op, so
no pre-gather communication — and a sharding constraint then *forces the
all-gather on the int8 payload* (1 byte/element on the ICI wire instead of 2)
before the dequantize+cast runs replicated. XLA fuses dequant into each
weight's consumer. Gradients take the straight-through path (``custom_vjp``
identity): the quantization error perturbs the forward like the reference's,
while the backward reduce-scatter stays exact.
"""

import functools

import numpy as np

from deepspeed_tpu.utils import groups


def qwz_supported(stage: int) -> bool:
    return stage >= 3


def _sharded_dim(spec, zero_axes):
    """The dim of ``spec`` carrying any ZeRO axis, or None (replicated /
    TP-only leaves have nothing to gather cheaply)."""
    zset = set(zero_axes)
    for d, entry in enumerate(tuple(spec)):
        if entry is None:
            continue
        axes = entry if isinstance(entry, tuple) else (entry, )
        if any(ax in zset for ax in axes):
            return d
    return None


def _gathered_spec(spec, zero_axes):
    """``spec`` with the ZeRO axes removed (TP/EP placement survives)."""
    from jax.sharding import PartitionSpec as P
    zset = set(zero_axes)
    out = []
    for entry in tuple(spec):
        if entry is None:
            out.append(None)
            continue
        axes = tuple(ax for ax in (entry if isinstance(entry, tuple) else (entry, ))
                     if ax not in zset)
        out.append(axes if len(axes) > 1 else (axes[0] if axes else None))
    return P(*out)


def _make_quantized_gather(dim, spec, gathered_spec, gather_axes, mesh, compute_dtype):
    """fp32 shard -> compute-dtype full weight, moving int8 over the wire.

    The all-gather is an *explicit* ``jax.lax.all_gather`` on the s8 payload
    inside ``shard_map`` — a mere sharding constraint lets the partitioner
    hoist the int8→fp convert ahead of the gather and put fp32 on the wire
    (observed; the same reason qgZ routes through shard_map).

    Straight-through: the vjp is identity (grad flows to the master shard as
    if the cast were exact) — the partitioner still emits the exact
    reduce-scatter for the gradient.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    axis_name = gather_axes if len(gather_axes) > 1 else gather_axes[0]
    # the scale is size-1 on every dim but ``dim``: only that entry survives
    scale_spec = P(*[entry if i == dim else None for i, entry in enumerate(tuple(spec))])
    scale_gathered = P(*[entry if i == dim else None
                         for i, entry in enumerate(tuple(gathered_spec))])

    def gather_block(q_blk, s_blk):
        q_full = jax.lax.all_gather(q_blk, axis_name, axis=dim, tiled=True)
        s_full = jax.lax.all_gather(s_blk, axis_name, axis=dim, tiled=True)
        return q_full, s_full

    gather_sm = jax.shard_map(gather_block, mesh=mesh, in_specs=(spec, scale_spec),
                              out_specs=(gathered_spec, scale_gathered),
                              check_vma=False)

    @jax.custom_vjp
    def qgather(w):
        # per-row symmetric int8 along the ZeRO-sharded dim: the scale reduces
        # every OTHER dim, so it is elementwise w.r.t. the sharding — no
        # communication before the gather
        red = tuple(i for i in range(w.ndim) if i != dim)
        scale = jnp.max(jnp.abs(w), axis=red, keepdims=True) / 127.0
        scale = jnp.maximum(scale, 1e-12)
        q = jnp.clip(jnp.round(w / scale), -127, 127).astype(jnp.int8)
        q, scale = gather_sm(q, scale)
        return (q.astype(jnp.float32) * scale).astype(compute_dtype)

    def fwd(w):
        # 0-d residual carries the master dtype (a bare dtype is not a pytree leaf)
        return qgather(w), jnp.zeros((), w.dtype)

    def bwd(res, g):
        # restore the master dtype: the incoming cotangent arrives in
        # compute dtype (bf16), and the optimizer accumulates in fp32
        return (g.astype(res.dtype), )

    qgather.defvjp(fwd, bwd)
    return qgather


def make_qwz_cast(param_shardings, mesh, compute_dtype, zero_axes=None,
                  threshold: int = 2048):
    """Build the qwZ master→compute cast for the engine's parameter tree.

    Leaves that are floating, ndim>=2, >= ``threshold`` elements AND actually
    ZeRO-sharded take the quantized gather; everything else (norm scales,
    biases, small or replicated params) casts exactly.
    """
    import jax
    import jax.numpy as jnp

    zero_axes = tuple(zero_axes) if zero_axes is not None else groups.get_zero_partition_axes()
    zero_axes = tuple(ax for ax in zero_axes if mesh.shape.get(ax, 1) > 1)

    def leaf_cast_factory(sharding):
        spec = getattr(sharding, "spec", None)
        dim = _sharded_dim(spec, zero_axes) if spec is not None else None
        if dim is None:
            return None
        entry = tuple(spec)[dim]
        gather_axes = tuple(ax for ax in (entry if isinstance(entry, tuple) else (entry, ))
                            if ax in set(zero_axes))
        return _make_quantized_gather(dim, spec, _gathered_spec(spec, zero_axes),
                                      gather_axes, mesh, compute_dtype)

    def cast(params):
        def one(w, sharding):
            if not hasattr(w, "dtype") or not jnp.issubdtype(w.dtype, jnp.floating):
                return w  # match cast_tree: non-floating leaves pass through
            if w.ndim < 2 or int(np.prod(w.shape)) < threshold:
                return w.astype(compute_dtype)
            fn = leaf_cast_factory(sharding)
            if fn is None:
                return w.astype(compute_dtype)
            return fn(w)

        return jax.tree.map(one, params, param_shardings)

    return cast
