"""Flight recorder: dump contents, SIGUSR1/API/HTTP triggers, and the
heartbeat watchdog (stall detection + serving_stalled metric)."""

import json
import os
import signal
import time
import urllib.error
import urllib.request

import pytest

from deepspeed_tpu import telemetry
from deepspeed_tpu.telemetry.flight_recorder import SERVING_SCHEDULER_CHANNEL


def _session(tmp_path, **fr_kw):
    fr = {"enabled": True, "dir": str(tmp_path / "flight"),
          "watchdog_enabled": False, "signal_enabled": False}
    fr.update(fr_kw)
    return telemetry.configure(telemetry.TelemetryConfig(
        enabled=True, flight_recorder=fr))


def test_dump_contains_spans_events_metrics_and_state(tmp_path):
    session = _session(tmp_path)
    reg = telemetry.get_registry()
    reg.counter("serving_completions_total", "done").inc(3)
    reg.event("train_step", step=7, loss=0.5)
    session.spans.record("put", cat="inference", ts_us=1, dur_us=2,
                         trace_id="abc123", span_id=9)
    recorder = telemetry.get_flight_recorder()
    recorder.register_provider("custom", lambda: {"answer": 42})
    recorder.register_provider("broken", lambda: 1 / 0)

    path = recorder.dump("api")
    with open(path) as f:
        doc = json.load(f)  # must be parseable JSON
    assert doc["meta"]["trigger"] == "api" and doc["meta"]["pid"] == os.getpid()
    span = next(s for s in doc["spans"] if s["name"] == "put")
    assert span["trace_id"] == "abc123" and span["span_id"] == 9
    assert any(e["event"] == "train_step" and e["step"] == 7 for e in doc["events"])
    assert doc["metrics"]["serving_completions_total"][0][1] == 3
    assert doc["state"]["custom"] == {"answer": 42}
    assert "provider raised" in doc["state"]["broken"]["error"]
    # the dump itself is metered
    assert reg.snapshot()["flight_recorder_dumps_total"] == [({"trigger": "api"}, 1.0)]


def test_sigusr1_triggers_a_dump_and_close_restores_handler(tmp_path):
    prev = signal.getsignal(signal.SIGUSR1)
    session = _session(tmp_path, signal_enabled=True)
    os.kill(os.getpid(), signal.SIGUSR1)
    # the handler hands the dump to a worker thread (inline dumping could
    # deadlock on the recorder lock) — poll briefly
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline and not os.path.exists(tmp_path / "flight"):
        time.sleep(0.01)
    dumps = os.listdir(tmp_path / "flight")
    assert len(dumps) == 1 and "sigusr1" in dumps[0]
    session.close()
    assert signal.getsignal(signal.SIGUSR1) == prev


def test_displaced_recorder_close_keeps_newer_handler(tmp_path):
    """Closing an older recorder must not stomp a newer recorder's live
    SIGUSR1 handler with its own (possibly SIG_DFL) predecessor — that would
    turn the runbook's `kill -USR1` dump into process termination."""
    from deepspeed_tpu.telemetry.config import FlightRecorderConfig
    from deepspeed_tpu.telemetry.flight_recorder import FlightRecorder
    from deepspeed_tpu.telemetry.registry import MetricsRegistry

    prev = signal.getsignal(signal.SIGUSR1)
    a = FlightRecorder(FlightRecorderConfig(
        enabled=True, dir=str(tmp_path / "a"), signal_enabled=True,
        watchdog_enabled=False), MetricsRegistry()).install()
    b = FlightRecorder(FlightRecorderConfig(
        enabled=True, dir=str(tmp_path / "b"), signal_enabled=True,
        watchdog_enabled=False), MetricsRegistry()).install()
    try:
        a.close()  # out of order: B's handler is live and must stay
        assert signal.getsignal(signal.SIGUSR1) == b._on_signal
    finally:
        b.close()
        signal.signal(signal.SIGUSR1, prev)


def test_http_flight_route_dumps(tmp_path):
    session = telemetry.configure(telemetry.TelemetryConfig(
        enabled=True, http={"enabled": True},
        flight_recorder={"enabled": True, "dir": str(tmp_path / "flight"),
                         "watchdog_enabled": False, "signal_enabled": False}))
    with urllib.request.urlopen(session.server.url + "/flight", timeout=5) as resp:
        doc = json.loads(resp.read())
    assert os.path.exists(doc["path"])
    assert doc["dump"]["meta"]["trigger"] == "http"


def test_flight_route_404_without_recorder():
    session = telemetry.configure(telemetry.TelemetryConfig(
        enabled=True, http={"enabled": True}))
    with pytest.raises(urllib.error.HTTPError) as err:
        urllib.request.urlopen(session.server.url + "/flight", timeout=5)
    assert err.value.code == 404


def test_watchdog_detects_a_stalled_heartbeat(tmp_path):
    session = _session(tmp_path, watchdog_enabled=True,
                       watchdog_stall_s=0.1, watchdog_poll_s=0.02)
    recorder = telemetry.get_flight_recorder()
    recorder.register_provider(SERVING_SCHEDULER_CHANNEL,
                               lambda: {"queue_depth": 5})
    recorder.watch_heartbeat(SERVING_SCHEDULER_CHANNEL)
    # beat for a while: no dump while the loop makes progress
    for _ in range(5):
        recorder.heartbeat(SERVING_SCHEDULER_CHANNEL)
        time.sleep(0.02)
    assert not os.path.exists(tmp_path / "flight")
    # ...then stop beating: exactly ONE dump per stall episode + the metric
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline and not os.path.exists(tmp_path / "flight"):
        time.sleep(0.02)
    time.sleep(0.1)  # would double-dump here if episodes weren't latched
    dumps = os.listdir(tmp_path / "flight")
    assert len(dumps) == 1 and "watchdog" in dumps[0]
    with open(tmp_path / "flight" / dumps[0]) as f:
        doc = json.load(f)
    assert doc["state"][SERVING_SCHEDULER_CHANNEL] == {"queue_depth": 5}
    assert doc["heartbeats_age_s"][SERVING_SCHEDULER_CHANNEL] > 0.1
    snap = telemetry.get_registry().snapshot()
    assert snap["serving_stalled_total"] == [({}, 1.0)]
    # a resumed heartbeat re-arms the episode
    recorder.heartbeat(SERVING_SCHEDULER_CHANNEL)
    time.sleep(0.1)
    recorder.unwatch_heartbeat(SERVING_SCHEDULER_CHANNEL)
    session.close()


def test_watchdog_grants_compile_grace_to_busy_loops(tmp_path):
    """A loop blocked inside a watched jit call (a long first-bucket XLA
    compile) is busy, not wedged: no stall until the hard budget expires."""
    import threading

    from deepspeed_tpu.telemetry import compile_watch

    session = _session(tmp_path, watchdog_enabled=True,
                       watchdog_stall_s=0.05, watchdog_poll_s=0.01,
                       watchdog_hard_stall_s=0.6)
    recorder = telemetry.get_flight_recorder()
    recorder.watch_heartbeat("c")

    watch = compile_watch.get()
    release = time.monotonic() + 0.3

    def slow(x):  # holds the wrapped call open well past the soft stall
        while time.monotonic() < release:
            time.sleep(0.01)
        return x

    wrapped = watch.wrap("test", "slow", slow)
    thread = threading.Thread(target=wrapped, args=(1, ))
    thread.start()
    time.sleep(0.2)  # soft stall long exceeded, but the call is in flight
    assert not os.path.exists(tmp_path / "flight")
    thread.join()
    # call over, heartbeat still stale: the stall now fires
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline and not os.path.exists(tmp_path / "flight"):
        time.sleep(0.02)
    assert os.path.exists(tmp_path / "flight")
    session.close()


def test_unwatched_channel_never_fires(tmp_path):
    session = _session(tmp_path, watchdog_enabled=True,
                       watchdog_stall_s=0.05, watchdog_poll_s=0.01)
    recorder = telemetry.get_flight_recorder()
    recorder.watch_heartbeat("c")
    recorder.unwatch_heartbeat("c")
    time.sleep(0.15)
    assert not os.path.exists(tmp_path / "flight")
    session.close()
