"""Sequence tracking.

Reference: ``deepspeed/inference/v2/ragged/sequence_descriptor.py``
(DSSequenceDescriptor — per-sequence KV block table, seen/in-flight token counts).
"""

from typing import List, Optional

import numpy as np


class DSSequenceDescriptor:

    def __init__(self, tracking_id: int, max_blocks_per_seq: int = 256):
        self.tracking_id = tracking_id
        self._seen_tokens = 0
        self._in_flight_tokens = 0
        self._max_blocks = max_blocks_per_seq
        self._kv_blocks: List[int] = []
        # which tier of the KV ladder holds this sequence's cache — one of
        # ragged.tiering.TIERS. "device" while the block table is live; the
        # state manager flips it to the store-reported tier across an
        # offload (ragged_manager.offload_sequence / restore_sequence)
        self.kv_tier: str = "device"

    @property
    def seen_tokens(self) -> int:
        return self._seen_tokens

    @property
    def in_flight_tokens(self) -> int:
        return self._in_flight_tokens

    @property
    def cur_allocated_blocks(self) -> int:
        return len(self._kv_blocks)

    @property
    def max_blocks(self) -> int:
        return self._max_blocks

    @property
    def kv_blocks(self) -> np.ndarray:
        return np.asarray(self._kv_blocks, dtype=np.int64)

    def kv_cache_ids(self, on_device: bool = False) -> np.ndarray:
        return self.kv_blocks

    def extend_kv_cache(self, new_blocks) -> None:
        new_blocks = np.atleast_1d(np.asarray(new_blocks)).tolist()
        if len(self._kv_blocks) + len(new_blocks) > self._max_blocks:
            raise ValueError(f"Sequence {self.tracking_id} exceeds max blocks {self._max_blocks}")
        self._kv_blocks.extend(int(b) for b in new_blocks)

    def replace_kv_blocks(self, new_blocks) -> None:
        """Swap the whole block table for fresh ids (KV offload→restore hands
        back different device blocks; token order is preserved)."""
        new_blocks = np.atleast_1d(np.asarray(new_blocks)).tolist()
        if len(new_blocks) != len(self._kv_blocks):
            raise ValueError(f"restore returned {len(new_blocks)} blocks for a "
                             f"{len(self._kv_blocks)}-block sequence")
        self._kv_blocks = [int(b) for b in new_blocks]

    def pre_forward(self, num_tokens: int) -> None:
        """Reference: mark tokens as in-flight before the forward."""
        self._in_flight_tokens = num_tokens

    def post_forward(self) -> None:
        """Reference: commit in-flight tokens to seen after the forward."""
        self._seen_tokens += self._in_flight_tokens
        self._in_flight_tokens = 0

    def rollback(self, n_tokens: int) -> None:
        """Forget the last ``n_tokens`` committed tokens (write-then-truncate):
        their KV stays in place and is overwritten when the correct tokens are
        fed at those positions — the speculative-verify rejection path. The
        blocks stay allocated; only the committed count moves."""
        n_tokens = int(n_tokens)
        if self._in_flight_tokens:
            raise RuntimeError(f"sequence {self.tracking_id}: rollback with "
                               f"{self._in_flight_tokens} in-flight tokens")
        if n_tokens < 0 or n_tokens > self._seen_tokens:
            raise ValueError(f"rollback({n_tokens}) with {self._seen_tokens} "
                             f"committed tokens")
        self._seen_tokens -= n_tokens


class PlaceholderSequenceDescriptor(DSSequenceDescriptor):
    """Ephemeral stand-in used by ``engine.query``/``can_schedule`` for uids the
    engine does not know yet (reference sequence_descriptor.py Placeholder...)."""

    def __init__(self, tracking_id: int = -1, max_blocks_per_seq: int = 2**30):
        super().__init__(tracking_id, max_blocks_per_seq=max_blocks_per_seq)
