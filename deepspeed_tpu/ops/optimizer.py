"""Optimizer base protocol.

TPU-native replacement for the reference's fused CUDA optimizers
(``csrc/adam/multi_tensor_adam.cu``, ``csrc/lamb``, ``csrc/lion``; Python wrappers in
``deepspeed/ops/{adam,lamb,lion,adagrad}``). Each optimizer is a *pure functional*
transform — ``init(params) -> state`` and ``update(grads, state, params, lr) ->
(new_params, new_state)`` — applied inside the engine's jitted step, where XLA fuses
the whole elementwise update chain into a single pass over HBM (the role the
multi-tensor-apply CUDA kernels play in the reference).

``lr`` is a traced scalar so LR schedules never trigger recompilation.
"""

from typing import Any, NamedTuple, Tuple

import jax
import jax.numpy as jnp

from deepspeed_tpu.runtime import DeepSpeedOptimizer


class TpuOptimizer(DeepSpeedOptimizer):
    """Functional optimizer protocol; subclasses implement init/update.

    Subclasses ``DeepSpeedOptimizer`` so reference-style
    ``isinstance(engine.optimizer, deepspeed.DeepSpeedOptimizer)`` checks
    hold; when the engine runs ZeRO it additionally mixes ``ZeROOptimizer``
    into the instance (engine.py) so the sharded case is distinguishable the
    way the reference's wrapped optimizers are."""

    name = "base"

    def __init__(self, lr=1e-3, weight_decay=0.0):
        self.lr = lr
        self.weight_decay = weight_decay

    # -- functional API (used inside jit) ------------------------------------------
    def init(self, params):
        raise NotImplementedError

    def update(self, grads, state, params, lr):
        raise NotImplementedError

    # -- convenience imperative API (reference-parity surface) ---------------------
    def get_lr(self):
        return self.lr

    def set_lr(self, lr):
        self.lr = lr

    # param_groups shim so reference-style LR schedulers can drive us
    @property
    def param_groups(self):
        return [{"lr": self.lr, "weight_decay": self.weight_decay}]


def _tree_zeros_like(params, dtype=None):
    return jax.tree.map(lambda p: jnp.zeros_like(p, dtype=dtype or p.dtype), params)


def apply_weight_decay(update, param, weight_decay, lr, decoupled: bool):
    """AdamW-style decoupled decay adds wd*p to the step; L2 adds wd*p to the grad
    (handled by callers before moments for the non-decoupled mode)."""
    if weight_decay == 0.0:
        return update
    if decoupled:
        return update + weight_decay * param
    return update
