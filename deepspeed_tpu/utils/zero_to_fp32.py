"""Offline checkpoint → consolidated fp32 state-dict converter (CLI).

Reference: ``deepspeed/utils/zero_to_fp32.py`` (592 LoC — stitches per-rank
``zero_pp_rank_*`` flat partitions back into full fp32 tensors) and
``deepspeed/checkpoint/ds_to_universal.py:286``. The TPU checkpoint is ONE
logical sharded array store (orbax/tensorstore), so consolidation is a plain
offline restore — no engine, no mesh, no shard stitching — followed by a
flat-named export:

    python -m deepspeed_tpu.utils.zero_to_fp32 <checkpoint_dir> <output_file> [--tag TAG]

Output: an ``.npz`` with one entry per parameter, keys joined with ``.``
(``model.layers_0.self_attn.q_proj.kernel``), everything cast to fp32 —
loadable with ``numpy.load`` anywhere, no JAX required at load time.
"""

import argparse
import os
import sys

import numpy as np


def get_fp32_state_dict_from_zero_checkpoint(checkpoint_dir, tag=None):
    """Reference zero_to_fp32.py API: returns {flat_name: np.float32 array}."""
    from deepspeed_tpu.runtime.checkpoint_engine.engine import LATEST_FILE, OrbaxCheckpointEngine

    if tag is None:
        latest = os.path.join(checkpoint_dir, LATEST_FILE)
        if not os.path.isfile(latest):
            raise FileNotFoundError(f"no tag given and no {latest}")
        with open(latest) as f:
            tag = f.read().strip()
    state_path = os.path.join(os.path.abspath(checkpoint_dir), str(tag), "state")
    if not os.path.isdir(state_path):
        raise FileNotFoundError(f"checkpoint state not found at {state_path}")

    restored = OrbaxCheckpointEngine().load(state_path)
    params = restored["params"]

    out = {}

    def walk(node, prefix):
        if isinstance(node, dict):
            for k, v in node.items():
                walk(v, prefix + (str(k), ))
        else:
            out[".".join(prefix)] = np.asarray(node, dtype=np.float32)

    walk(params, ())
    return out


def convert_zero_checkpoint_to_fp32_state_dict(checkpoint_dir, output_file, tag=None):
    sd = get_fp32_state_dict_from_zero_checkpoint(checkpoint_dir, tag)
    os.makedirs(os.path.dirname(os.path.abspath(output_file)), exist_ok=True)
    np.savez(output_file, **sd)
    total = sum(v.size for v in sd.values())
    print(f"wrote {len(sd)} tensors ({total:,} fp32 params) to {output_file}")
    return output_file


def main(argv=None):
    parser = argparse.ArgumentParser(
        description="consolidate a deepspeed_tpu checkpoint into a flat fp32 .npz "
                    "(reference: deepspeed/utils/zero_to_fp32.py)")
    parser.add_argument("checkpoint_dir")
    parser.add_argument("output_file")
    parser.add_argument("--tag", default=None, help="checkpoint tag (default: 'latest' file)")
    args = parser.parse_args(argv)
    convert_zero_checkpoint_to_fp32_state_dict(args.checkpoint_dir, args.output_file, args.tag)
    return 0


if __name__ == "__main__":
    sys.exit(main())
