"""Tracer summarization edge cases, ring-buffer bounds and span export."""

import json

from deepspeed_tpu.inference.v2 import tracer as tracer_mod
from deepspeed_tpu.inference.v2.tracer import RECORD_NAMES, Tracer
from deepspeed_tpu.telemetry import SpanRecorder


class _Seq:

    def __init__(self, seen, in_flight):
        self.seen_tokens = seen
        self.in_flight_tokens = in_flight


def test_summarize_empty_run_batch():
    tr = Tracer()
    tr.init_batch(is_empty_run=True, num_layers=2)
    (summary, ) = list(tr.batch_summaries())
    assert summary.is_empty_run is True
    assert summary.embed == 0 and summary.unembed == 0
    assert summary.record_exec_times == [[0] * len(RECORD_NAMES)] * 2


def test_summarize_missing_embed_unembed_markers():
    tr = Tracer()
    tr.init_batch(is_empty_run=False, num_layers=2)
    tr.add_sequence(_Seq(4, 1))
    # no embed/unembed phases recorded at all: layer phases must not be
    # misattributed to them
    for _ in range(2):
        tr.add_trace("attn", 10)
        tr.add_trace("ffn", 20)
    (summary, ) = list(tr.batch_summaries())
    assert summary.embed == 0 and summary.unembed == 0
    attn = RECORD_NAMES.index("attn")
    ffn = RECORD_NAMES.index("ffn")
    assert [row[attn] for row in summary.record_exec_times] == [10, 10]
    assert [row[ffn] for row in summary.record_exec_times] == [20, 20]


def test_summarize_with_markers():
    tr = Tracer()
    tr.init_batch(is_empty_run=False, num_layers=1)
    tr.add_trace("embed", 5)
    tr.add_trace("attn", 10)
    tr.add_trace("ffn", 20)
    tr.add_trace("unembed", 7)
    (summary, ) = list(tr.batch_summaries())
    assert summary.embed == 5 and summary.unembed == 7
    assert summary.record_exec_times[0][RECORD_NAMES.index("attn")] == 10


def test_summarize_layer_count_mismatch_does_not_crash():
    tr = Tracer()
    # claims 3 layers but records phases for 2: summaries stay well-formed
    tr.init_batch(is_empty_run=False, num_layers=3)
    tr.add_trace("attn", 10)
    tr.add_trace("attn", 11)
    (summary, ) = list(tr.batch_summaries())
    assert summary.num_layers == 3
    assert len(summary.record_exec_times) == 3
    assert all(len(row) == len(RECORD_NAMES) for row in summary.record_exec_times)


def test_ring_buffer_bounds_memory():
    tr = Tracer(max_batches=4)
    for _ in range(10):
        tr.init_batch(is_empty_run=False, num_layers=1)
        tr.add_trace("attn", 1)
    assert tr.pending_batches == 4
    assert [s.batch_id for s in tr.batch_summaries()] == [6, 7, 8, 9]


def test_drain_summaries_frees_consumed_traces():
    tr = Tracer(max_batches=8)
    for _ in range(3):
        tr.init_batch(is_empty_run=False, num_layers=1)
        tr.add_trace("attn", 1)
    drained = tr.drain_summaries()
    assert [s.batch_id for s in drained] == [0, 1, 2]
    assert tr.pending_batches == 0
    assert tr.drain_summaries() == []
    # the drained current batch must not resurrect through add_trace
    tr.add_trace("attn", 1)
    assert tr.pending_batches == 0
    # and tracing continues cleanly afterwards
    tr.init_batch(is_empty_run=False, num_layers=1)
    tr.add_trace("attn", 2)
    assert [s.batch_id for s in tr.drain_summaries()] == [3]


def test_record_context_manager_emits_spans(tmp_path):
    rec = SpanRecorder()
    tr = Tracer(span_recorder=rec)
    tracer_mod.set_tracer(tr)
    try:
        tr.init_batch(is_empty_run=False, num_layers=1)
        with tracer_mod.record("attn"):
            pass
        with tracer_mod.record("ffn"):
            pass
    finally:
        tracer_mod.set_tracer(None)

    path = rec.export_chrome_trace(str(tmp_path / "trace.json"))
    with open(path) as f:
        trace = json.load(f)  # valid JSON
    evs = trace["traceEvents"]
    assert [e["name"] for e in evs] == ["attn", "ffn"]
    assert all(e["ph"] == "X" and e["cat"] == "inference" for e in evs)
    assert [e["ts"] for e in evs] == sorted(e["ts"] for e in evs)
    assert all(e["args"]["batch_id"] == 0 for e in evs)
    # the tracer's own trace list recorded the same phases
    (summary, ) = list(tr.batch_summaries())
    assert summary.record_exec_times[0][RECORD_NAMES.index("attn")] >= 0


def test_record_noop_without_tracer():
    tracer_mod.set_tracer(None)
    with tracer_mod.record("attn"):
        pass  # must not raise
