"""Gate sensitivity: the gate is proven to catch what it claims to catch.

Each test INJECTS one regression class into a program and asserts the
budget check trips the matching assertion:

1. drop remat            -> live-buffer peak exceeds the budget;
2. force an f32 upcast   -> the dtype audit's exact f32-dot count trips;
3. de-fuse a matmul      -> fusion / entry-kernel counts trip;
4. double a collective payload -> the per-collective byte budget trips.

1–2 regress the REAL flagship ZeRO-3 program against its checked-in budget;
3–4 use a minimal synthetic program with an in-test baseline so the injected
delta is exactly one structural change."""

import jax
import jax.numpy as jnp
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from deepspeed_tpu.perf import gate
from deepspeed_tpu.perf.budgets import budget_from_stats, check_stats
from deepspeed_tpu.perf.hlo_stats import stats_from_callable, stats_from_lowered
from deepspeed_tpu.perf.programs import build_train_engine, train_batch_example

pytestmark = pytest.mark.perfgate


def _train_stats(remat=True, dtype=None):
    engine, cfg = build_train_engine(remat=remat, dtype=dtype)
    lowered = engine.lower_train_batch(batch=train_batch_example(cfg))
    return stats_from_lowered(lowered, name="zero3_train_batch")


def test_dropping_remat_trips_peak_bytes_budget():
    stats = _train_stats(remat=False)
    tripped = [v.metric for v in gate.check_program("zero3_train_batch", stats)]
    assert "peak_bytes" in tripped, f"tripped only: {tripped}"


def test_f32_upcast_trips_dtype_audit():
    stats = _train_stats(dtype=jnp.float32)
    violations = gate.check_program("zero3_train_batch", stats)
    tripped = [v.metric for v in violations]
    assert "f32_dot_count" in tripped, f"tripped only: {tripped}"
    f32v = next(v for v in violations if v.metric == "f32_dot_count")
    assert f32v.budget == 0 and f32v.measured > 0


def test_defusing_a_matmul_trips_kernel_count_budget():
    x = jnp.ones((128, 128), jnp.bfloat16)
    w = jnp.ones((128, 128), jnp.bfloat16)

    def fused(x, w):
        return jnp.sin((x @ w).astype(jnp.float32) * 2.0 + 1.0).sum()

    def defused(x, w):
        y = (x @ w).astype(jnp.float32)
        y = jax.lax.optimization_barrier(y)  # the injected fusion break
        y = jax.lax.optimization_barrier(y * 2.0)
        return jnp.sin(jax.lax.optimization_barrier(y + 1.0)).sum()

    budget = budget_from_stats(stats_from_callable(fused, x, w, name="mm_fused"))
    bad = stats_from_callable(defused, x, w, name="mm_fused")
    tripped = [v.metric for v in check_stats(bad, budget)]
    # the CPU backend optimizes through the barriers, so the catch is the
    # jax-level program-size ratchet (backends that keep the split would
    # additionally trip the fusion/entry-kernel counters)
    assert {"stablehlo_op_count", "entry_instruction_count",
            "fusion_count"} & set(tripped), f"tripped only: {tripped}"


def test_doubling_collective_payload_trips_byte_budget(mesh8):
    def make(cols):
        x = jax.device_put(jnp.ones((256, cols), jnp.float32),
                           NamedSharding(mesh8, P("data", None)))
        fn = jax.jit(lambda a: a.sum(axis=0),
                     out_shardings=NamedSharding(mesh8, P()))
        return stats_from_callable(fn, x, name="grad_reduce")

    baseline = make(8)
    assert baseline.collective_bytes_total > 0, "no collective to budget"
    budget = budget_from_stats(baseline)
    doubled = make(16)  # the reduced payload doubles: f32[8] -> f32[16]
    violations = check_stats(doubled, budget)
    tripped = [v.metric for v in violations]
    assert any(m.endswith(".bytes") or m == "collective_bytes_total"
               for m in tripped), f"tripped only: {tripped}"


def test_widening_the_draft_tree_trips_tree_verify_flops_budget():
    """The spec_tree_verify budget is pinned to the smallest decode bucket:
    a tree that outgrows it (wider/deeper than the node budget the baseline
    shipped with) pads into the next token bucket, and the extra attention +
    unembed work must trip the flops ratchet — the gate proves the budgeted
    'tree costs one forward' claim is falsifiable, not vacuous."""
    from deepspeed_tpu.perf.programs import build_v2_engine

    engine, _ = build_v2_engine()
    wide = stats_from_lowered(engine.lower_tree_verify(bucket=(16, 8, 4),
                                                       greedy=True),
                              name="spec_tree_verify")
    tripped = [v.metric for v in gate.check_program("spec_tree_verify", wide)]
    assert "flops" in tripped, f"tripped only: {tripped}"
