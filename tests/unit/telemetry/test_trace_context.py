"""Distributed-tracing span extensions: trace/span/parent ids, the ambient
trace context, and the per-trace Perfetto track export."""

import json

from deepspeed_tpu.telemetry import (SpanRecorder, current_trace, new_span_id,
                                     new_trace_id, trace_context)


def test_trace_and_span_ids_are_unique():
    trace_ids = {new_trace_id() for _ in range(100)}
    span_ids = {new_span_id() for _ in range(100)}
    assert len(trace_ids) == 100 and len(span_ids) == 100
    assert all(len(t) == 16 for t in trace_ids)


def test_record_with_explicit_ids_and_parent_chain():
    rec = SpanRecorder()
    trace = new_trace_id()
    root = new_span_id()
    rec.record("request", ts_us=0, dur_us=100, trace_id=trace, span_id=root)
    child = rec.record("queued", ts_us=0, dur_us=10, trace_id=trace, parent_id=root)
    assert child.span_id is not None and child.span_id != root
    assert child.parent_id == root and child.trace_id == trace


def test_ambient_context_inherited_by_record():
    rec = SpanRecorder()
    assert current_trace() is None
    trace, root = new_trace_id(), new_span_id()
    with trace_context(trace, root):
        assert current_trace() == (trace, root)
        span = rec.record("inner", ts_us=5, dur_us=1)
    assert current_trace() is None
    assert span.trace_id == trace and span.parent_id == root
    # outside the context nothing is inherited
    bare = rec.record("outside", ts_us=6, dur_us=1)
    assert bare.trace_id is None and bare.span_id is None


def test_span_context_manager_nests_parents():
    rec = SpanRecorder()
    trace, root = new_trace_id(), new_span_id()
    with trace_context(trace, root):
        with rec.span("outer", cat="t"):
            with rec.span("inner", cat="t"):
                pass
    spans = {s["name"]: s for s in rec.tail(10)}
    assert spans["outer"]["parent_id"] == root
    assert spans["inner"]["parent_id"] == spans["outer"]["span_id"]
    assert spans["inner"]["trace_id"] == spans["outer"]["trace_id"] == trace


def test_chrome_trace_gives_each_trace_its_own_track(tmp_path):
    rec = SpanRecorder()
    t_a, t_b = new_trace_id(), new_trace_id()
    rec.record("request", ts_us=0, dur_us=100, trace_id=t_a, span_id=1)
    rec.record("request", ts_us=10, dur_us=100, trace_id=t_b, span_id=2)
    rec.record("decode", ts_us=20, dur_us=5, trace_id=t_a, parent_id=1)
    rec.record("untraced", ts_us=30, dur_us=5)

    path = rec.export_chrome_trace(str(tmp_path / "trace.json"))
    with open(path) as f:
        trace = json.load(f)
    evs = trace["traceEvents"]
    meta = [e for e in evs if e.get("ph") == "M"]
    xs = [e for e in evs if e["ph"] == "X"]
    # one named track per trace id; same-trace spans share a tid
    assert {m["args"]["name"] for m in meta} == {f"request {t_a}", f"request {t_b}"}
    tids = {e["name"]: e["tid"] for e in xs}
    by_trace = {e["args"]["trace_id"]: e["tid"] for e in xs if "args" in e
                and "trace_id" in e.get("args", {})}
    assert by_trace[t_a] != by_trace[t_b]
    assert tids["decode"] == by_trace[t_a]
    assert tids["untraced"] == 0
    # ids ride in args so tooling can rebuild the parent chain
    decode = next(e for e in xs if e["name"] == "decode")
    assert decode["args"]["parent_id"] == 1 and decode["args"]["trace_id"] == t_a
    # X events still sorted by ts (viewer contract)
    assert [e["ts"] for e in xs] == sorted(e["ts"] for e in xs)


def test_untraced_export_has_no_metadata_events():
    rec = SpanRecorder()
    rec.record("plain", ts_us=0, dur_us=1)
    evs = rec.chrome_trace()["traceEvents"]
    assert all(e["ph"] == "X" for e in evs)
