"""Training fault tolerance (ISSUE 11): crash-consistent checkpoints,
preemption-safe exit, anomaly sentinel, training chaos injector.

The two acceptance gates live here and in test_examples.py:

- a deliberately corrupted NEWEST checkpoint makes ``load_checkpoint`` fall
  back to the previous good tag LOUDLY (telemetry-counted), never silently;
- a save/load-interrupted run reaches step-exact, bitwise-identical final
  params versus an uninterrupted run (the kill-under-supervisor formulation
  is the subprocess gate in test_examples.py).
"""

import glob
import json
import os
import signal

import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu import telemetry
from deepspeed_tpu.runtime.checkpoint_engine.engine import (
    MANIFEST_FILE, CheckpointCorruptionError, ReferenceCheckpointError,
    list_tags, read_manifest, retention_plan, verify_checkpoint)
from deepspeed_tpu.runtime.engine import TrainingPreempted
from deepspeed_tpu.runtime.faults import (TrainFaultConfig, TrainFaultInjector,
                                          injector_from_env)
from deepspeed_tpu.utils import groups

from ..simple_model import make_simple_model

HIDDEN = 16


@pytest.fixture(autouse=True)
def fresh_telemetry_and_signals():
    """Telemetry is process-global and the preemption test rebinds SIGTERM:
    leave both exactly as found."""
    telemetry.shutdown()
    telemetry.state.registry = None
    old_term = signal.getsignal(signal.SIGTERM)
    yield
    signal.signal(signal.SIGTERM, old_term)
    telemetry.shutdown()
    telemetry.state.registry = None


def _config(extra=None):
    cfg = {
        "train_micro_batch_size_per_gpu": 8,
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": "AdamW", "params": {"lr": 0.01, "weight_decay": 0.0}},
        "zero_optimization": {"stage": 2},
    }
    if extra:
        cfg.update(extra)
    return cfg


def _engine(extra=None):
    groups.initialize_mesh(force=True)
    model, params0 = make_simple_model(hidden_dim=HIDDEN, batch_size=8)
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=params0, config=_config(extra))
    return engine


def _batch(step):
    rng = np.random.default_rng(100 + step)
    x = rng.normal(size=(8, HIDDEN)).astype(np.float32)
    return x, (x[:, 0] - 0.5 * x[:, 1]).astype(np.float32)


def _train_and_save(engine, save_dir, steps, start=0):
    for s in range(start, steps):
        engine.train_batch(batch=_batch(s))
        engine.save_checkpoint(str(save_dir))


def _corrupt_largest_state_file(tag_dir):
    files = [f for f in glob.glob(os.path.join(tag_dir, "state", "**"),
                                  recursive=True) if os.path.isfile(f)]
    target = max(files, key=os.path.getsize)
    with open(target, "r+b") as f:
        f.seek(0)
        byte = f.read(1)
        f.seek(0)
        f.write(bytes([byte[0] ^ 0xFF]))
    return target


def _counter_value(name):
    reg = telemetry.get_registry()
    return reg.counter(name).value


# ------------------------------------------------------------- manifest core --
def test_manifest_seals_the_commit(tmp_path):
    e = _engine()
    _train_and_save(e, tmp_path, 1)
    tag_dir = os.path.join(str(tmp_path), "global_step1")
    manifest = read_manifest(tag_dir)
    assert manifest is not None and manifest["format"] == 1
    assert manifest["global_steps"] == 1
    assert manifest["rng"] is not None          # step-exact resume state
    assert manifest["world"]["device_count"] >= 1
    assert manifest["files"], "file seals missing"
    assert manifest["arrays"], "per-array CRC32s missing"
    assert any("params" in k for k in manifest["arrays"])
    assert verify_checkpoint(tag_dir) == ("good", f"{len(manifest['files'])} files verified")


def test_corrupted_and_torn_tags_fall_back_loudly(tmp_path):
    """THE acceptance gate: corrupt the newest tag (CRC mismatch) AND tear
    the middle one (manifest removed) → load lands on the oldest GOOD tag,
    telemetry-counted, never silently. An empty dir beforehand is a fresh
    start, not an error."""
    telemetry.configure(telemetry.TelemetryConfig(enabled=True))
    e = _engine()
    path, client = e.load_checkpoint(str(tmp_path))  # nothing committed yet
    assert path is None and client is None

    _train_and_save(e, tmp_path, 3)
    _corrupt_largest_state_file(os.path.join(str(tmp_path), "global_step3"))
    os.unlink(os.path.join(str(tmp_path), "global_step2", MANIFEST_FILE))
    assert verify_checkpoint(os.path.join(str(tmp_path), "global_step3"))[0] == "corrupt"
    assert verify_checkpoint(os.path.join(str(tmp_path), "global_step2"))[0] == "torn"

    groups.destroy_mesh()
    e2 = _engine()
    path, _ = e2.load_checkpoint(str(tmp_path))
    assert path is not None and path.endswith("global_step1")
    assert e2.global_steps == 1
    assert _counter_value("checkpoint_verify_failures_total") >= 2
    assert _counter_value("checkpoint_load_fallbacks_total") >= 2


def test_bad_tags_raise_instead_of_silent_none(tmp_path):
    """An explicit corrupt tag raises; with every tag bad, tag=None raises
    too — never a silent (None, None) over real-but-unusable state."""
    e = _engine()
    _train_and_save(e, tmp_path, 1)
    _corrupt_largest_state_file(os.path.join(str(tmp_path), "global_step1"))
    groups.destroy_mesh()
    e2 = _engine()
    with pytest.raises(CheckpointCorruptionError, match="CORRUPT"):
        e2.load_checkpoint(str(tmp_path), tag="global_step1")
    with pytest.raises(CheckpointCorruptionError, match="no verified-good"):
        e2.load_checkpoint(str(tmp_path))


def test_verify_arrays_on_load_catches_sub_file_corruption(tmp_path):
    """Per-array CRC re-check on the restored tree (defense below the file
    layer): tamper with the manifest's array seal → the restore refuses."""
    e = _engine(extra={"checkpoint": {"verify_arrays_on_load": True}})
    _train_and_save(e, tmp_path, 1)
    tag_dir = os.path.join(str(tmp_path), "global_step1")
    manifest = read_manifest(tag_dir)
    key = next(k for k in manifest["arrays"] if "params" in k)
    # tamper with one array seal only; the file seals stay truthful, so the
    # FILE layer passes and only the array layer can catch it
    manifest["arrays"][key]["crc32"] ^= 0xFF
    with open(os.path.join(tag_dir, MANIFEST_FILE), "w") as f:
        json.dump(manifest, f)

    groups.destroy_mesh()
    e2 = _engine(extra={"checkpoint": {"verify_arrays_on_load": True}})
    with pytest.raises(CheckpointCorruptionError, match="per-array"):
        e2.load_checkpoint(str(tmp_path), tag="global_step1")


# ---------------------------------------------------------------- retention --
def test_retention_keeps_last_k(tmp_path):
    e = _engine(extra={"checkpoint": {"keep_last_k": 2}})
    _train_and_save(e, tmp_path, 4)
    tags = {t["tag"] for t in list_tags(str(tmp_path))}
    assert tags == {"global_step3", "global_step4"}


def test_retention_never_deletes_last_good(tmp_path):
    e = _engine()
    _train_and_save(e, tmp_path, 3)
    # newest two torn (e.g. chaos-truncated): the only good one is oldest
    for tag in ("global_step2", "global_step3"):
        os.unlink(os.path.join(str(tmp_path), tag, MANIFEST_FILE))
    keep, drop = retention_plan(str(tmp_path), keep_last_k=1)
    kept = {e["tag"] for e in keep}
    assert "global_step1" in kept, "the last good tag must survive retention"
    assert "global_step3" in kept  # the newest stays in-window
    assert {e["tag"] for e in drop} == {"global_step2"}


# ------------------------------------------------- reference-format rejection --
def test_reference_torch_checkpoint_rejected_loudly(tmp_path):
    """ROADMAP item 5 (reject half): zero_pp_rank_*/mp_rank_* shards name the
    migration path instead of dying inside orbax."""
    ref = tmp_path / "global_step100"
    ref.mkdir()
    (ref / "zero_pp_rank_0_mp_rank_00_optim_states.pt").write_bytes(b"torch")
    (ref / "mp_rank_00_model_states.pt").write_bytes(b"torch")
    (tmp_path / "latest").write_text("global_step100")

    e = _engine()
    with pytest.raises(ReferenceCheckpointError, match="ds_to_universal"):
        e.load_checkpoint(str(tmp_path))
    # an explicit tag is rejected the same way
    with pytest.raises(ReferenceCheckpointError, match="ds_to_universal"):
        e.load_checkpoint(str(tmp_path), tag="global_step100")


# --------------------------------------------------------- step-exact resume --
def test_save_load_resume_is_step_exact(tmp_path):
    """Interrupted-at-step-2 + resumed reaches BITWISE the params/rng an
    uninterrupted run reaches (the in-process half of the chaos-equivalence
    gate; the kill-under-supervisor half lives in test_examples.py)."""
    import jax
    e1 = _engine()
    _train_and_save(e1, tmp_path, 2)
    rng_at_save = np.asarray(e1._rng)
    for s in range(2, 5):
        e1.train_batch(batch=_batch(s))
    want = jax.device_get(e1.params)

    groups.destroy_mesh()
    e2 = _engine()
    path, _ = e2.load_checkpoint(str(tmp_path))
    assert path is not None and e2.global_steps == 2
    assert np.array_equal(np.asarray(e2._rng), rng_at_save), \
        "the per-step rng stream must resume exactly"
    for s in range(2, 5):
        e2.train_batch(batch=_batch(s))
    got = jax.device_get(e2.params)
    for a, b in zip(jax.tree.leaves(got), jax.tree.leaves(want)):
        assert np.array_equal(a, b), "resumed run diverged from uninterrupted"


# ------------------------------------------------------- async save draining --
def test_async_save_commits_on_destroy(tmp_path):
    """Satellite: an in-flight async (nebula) save must not be torn by engine
    close/interpreter teardown — destroy() drains the commit."""
    e = _engine(extra={"nebula": {"enabled": True}})
    e.train_batch(batch=_batch(0))
    e.save_checkpoint(str(tmp_path))
    e.destroy()  # joins the commit thread + closes the async checkpointer
    tag_dir = os.path.join(str(tmp_path), "global_step1")
    status, detail = verify_checkpoint(tag_dir)
    assert status == "good", f"async save torn by destroy: {detail}"
    assert getattr(e, "_async_ckpt")["ckptr"] is None


def test_async_manifest_seals_dispatch_time_state(tmp_path, monkeypatch):
    """The manifest an async commit writes must describe the DISPATCH-time
    snapshot, not whatever steps the training thread took while the commit
    was in flight."""
    import threading

    from deepspeed_tpu.runtime.checkpoint_engine import engine as ck_mod
    gate = threading.Event()
    real_finish = ck_mod.OrbaxCheckpointEngine.finish

    def gated_finish(self):
        gate.wait(timeout=60)
        real_finish(self)

    monkeypatch.setattr(ck_mod.OrbaxCheckpointEngine, "finish", gated_finish)
    e = _engine(extra={"nebula": {"enabled": True}})
    e.train_batch(batch=_batch(0))
    e.save_checkpoint(str(tmp_path))   # snapshot at step 1, commit gated open
    e.train_batch(batch=_batch(1))     # training continues to step 2
    gate.set()
    e.checkpoint_wait()
    manifest = read_manifest(os.path.join(str(tmp_path), "global_step1"))
    assert manifest["global_steps"] == 1, \
        "manifest must seal the dispatch-time step, not the commit-time one"
    e.destroy()


def test_dangling_latest_with_no_tags_is_a_fresh_start(tmp_path):
    """An operator who wiped the tag dirs but left `latest` behind gets a
    fresh start, not a supervisor crash loop."""
    (tmp_path / "latest").write_text("global_step9")
    e = _engine()
    path, client = e.load_checkpoint(str(tmp_path))
    assert path is None and client is None


def test_crash_during_first_ever_save_is_a_fresh_start(tmp_path):
    """SIGKILL mid-way through the very FIRST save leaves a torn partial tag
    and no `latest`/manifest anywhere: nothing was ever committed, so resume
    is a fresh start — not a raise that quarantines the supervisor."""
    partial = tmp_path / "global_step1" / "state"
    partial.mkdir(parents=True)
    (partial / "partial_write").write_bytes(b"torn")
    e = _engine()
    path, client = e.load_checkpoint(str(tmp_path))
    assert path is None and client is None


# ------------------------------------------------------------ preemption path --
def test_preemption_sigterm_final_checkpoint_and_marker(tmp_path):
    """SIGTERM → the in-flight step finishes, a final synchronous checkpoint
    commits, PREEMPTED.json lands, and the process exits 143 — then a fresh
    engine resumes from the preempt tag."""
    telemetry.configure(telemetry.TelemetryConfig(enabled=True))
    e = _engine()
    e.install_preemption_handler(save_dir=str(tmp_path))
    e.train_batch(batch=_batch(0))
    os.kill(os.getpid(), signal.SIGTERM)  # the preemption notice
    with pytest.raises(TrainingPreempted) as exc:
        e.train_batch(batch=_batch(1))
    assert exc.value.code == 143
    assert exc.value.tag == f"preempt_step{exc.value.step}"

    marker = json.load(open(os.path.join(str(tmp_path), "PREEMPTED.json")))
    assert marker["tag"] == exc.value.tag
    tag_dir = os.path.join(str(tmp_path), marker["tag"])
    assert verify_checkpoint(tag_dir)[0] == "good"
    assert _counter_value("train_preemptions_total") == 1

    groups.destroy_mesh()
    e2 = _engine()
    path, _ = e2.load_checkpoint(str(tmp_path))
    assert path is not None and path.endswith(marker["tag"])
    assert e2.global_steps == marker["global_steps"]


# ------------------------------------------------------------ anomaly sentinel --
def test_sentinel_skips_nonfinite_steps_and_rolls_back(tmp_path):
    """NaN grads: (1) skip-step — params untouched, counted as skipped — in a
    NON-fp16 mode; (2) M consecutive anomalies → rollback to last good."""
    import jax
    telemetry.configure(telemetry.TelemetryConfig(enabled=True))
    e = _engine(extra={"anomaly_sentinel": {"enabled": True, "max_consecutive": 2,
                                            "warmup_steps": 0},
                       "bf16": {"enabled": True}})
    e.train_batch(batch=_batch(0))
    e.save_checkpoint(str(tmp_path))
    good_params = jax.device_get(e.params)

    x, y = _batch(1)
    bad = (np.full_like(x, np.nan), y)
    e.train_batch(batch=bad)  # anomaly 1: skipped, no rollback yet
    assert e.skipped_steps == 1, "non-finite step must be skip-stepped"
    for a, b in zip(jax.tree.leaves(jax.device_get(e.params)),
                    jax.tree.leaves(good_params)):
        assert np.array_equal(a, b), "skip-step must leave params untouched"
    assert _counter_value("train_anomalies_total") == 1

    e.train_batch(batch=bad)  # anomaly 2: rollback to the step-1 checkpoint
    assert _counter_value("train_rollbacks_total") == 1
    assert e.global_steps == 1, "rollback must land on the checkpointed step"
    for a, b in zip(jax.tree.leaves(jax.device_get(e.params)),
                    jax.tree.leaves(good_params)):
        assert np.array_equal(a, b)
    # healthy training continues after the rollback
    loss = e.train_batch(batch=_batch(1))
    assert np.isfinite(float(loss))


def test_sentinel_spike_rollback_targets_pre_divergence_tag(tmp_path):
    """A SPIKE (finite loss) still applies its update — and a loop that
    checkpoints every step then saves the diverged weights. Rollback must
    land on the newest tag at-or-before the last HEALTHY step, not the
    newest tag outright."""
    import jax
    e = _engine(extra={"anomaly_sentinel": {"enabled": True, "max_consecutive": 2,
                                            "warmup_steps": 0, "spike_factor": 5.0}})
    e.train_batch(batch=_batch(0))       # step 1: healthy
    e.save_checkpoint(str(tmp_path))     # pre-divergence tag global_step1
    good_params = jax.device_get(e.params)

    x, y = _batch(1)
    spike = (x * 100.0, y)               # finite but enormous loss
    e.train_batch(batch=spike)           # step 2: anomaly 1, update APPLIED
    e.save_checkpoint(str(tmp_path))     # the DIVERGED state gets checkpointed
    assert e._sentinel.anomalies == 1
    e.train_batch(batch=spike)           # step 3: anomaly 2 → rollback
    assert e._sentinel.rollbacks == 1
    assert e.global_steps == 1, \
        "rollback must target the pre-divergence tag, not the newest save"
    for a, b in zip(jax.tree.leaves(jax.device_get(e.params)),
                    jax.tree.leaves(good_params)):
        assert np.array_equal(a, b)

    # with NO tag at-or-before the healthy horizon left, rollback must
    # REFUSE (loading the newest would restore the diverged state)
    import shutil
    shutil.rmtree(os.path.join(str(tmp_path), "global_step1"))
    e.train_batch(batch=spike)
    e.train_batch(batch=spike)  # anomalies 3+4 → rollback verdict again
    assert e._sentinel.rollbacks == 2
    assert e.global_steps == 3, "no pre-divergence tag: must not load anything"


# ---------------------------------------------------------- chaos injector --
def test_train_fault_injector_is_deterministic():
    cfg = TrainFaultConfig(enabled=True, seed=7, nan_inject_p=0.3,
                           kill_at_steps=(5, ))
    a, b = TrainFaultInjector(cfg), TrainFaultInjector(cfg)
    assert a.schedule("nan_inject", 200) == b.schedule("nan_inject", 200)
    assert a.schedule("nan_inject", 200), "p=0.3 over 200 events must fire"
    assert a.would_fire("kill_at_step", 5) and not a.would_fire("kill_at_step", 4)
    # live fire == the pure oracle
    fired = [n for n in range(50) if a.fire("checkpoint_corrupt") is not None]
    assert fired == a.schedule("checkpoint_corrupt", 50)
    with pytest.raises(ValueError, match="unknown injection point"):
        a.would_fire("nope", 0)


def test_injector_kill_points_are_first_life_only(monkeypatch):
    cfg = TrainFaultConfig(enabled=True, kill_at_steps=(3, ))
    inj = TrainFaultInjector(cfg)
    monkeypatch.setenv("DSTPU_RESTART_COUNT", "1")
    assert inj.fire_step("kill_at_step", 3) is None, \
        "a restarted life must not replay the kill"
    monkeypatch.setenv("DSTPU_RESTART_COUNT", "0")
    assert inj.fire_step("kill_at_step", 3) == 3
    assert inj.fire_step("kill_at_step", 3) is None  # once per step


def test_injector_env_arming(monkeypatch):
    assert injector_from_env(None) is None
    assert injector_from_env(json.dumps({"enabled": False})) is None
    inj = injector_from_env(json.dumps({"enabled": True, "seed": 3,
                                        "sigterm_at_steps": [2]}))
    assert inj is not None and inj.would_fire("sigterm_at_step", 2)
    with pytest.raises(Exception):
        injector_from_env("{not json")


def test_injector_corrupts_sealed_checkpoint(tmp_path):
    """The corrupt helper flips a byte the manifest CRC must catch."""
    e = _engine()
    _train_and_save(e, tmp_path, 1)
    tag_dir = os.path.join(str(tmp_path), "global_step1")
    inj = TrainFaultInjector(TrainFaultConfig(enabled=True, seed=1))
    rel = inj.corrupt_checkpoint(tag_dir, 0)
    assert rel is not None
    status, detail = verify_checkpoint(tag_dir)
    assert status == "corrupt" and "crc32 mismatch" in detail
    # truncate removes the manifest: the torn-commit shape
    assert inj.truncate_checkpoint(tag_dir) is True
    assert verify_checkpoint(tag_dir)[0] == "torn"
    assert inj.truncate_checkpoint(tag_dir) is False  # nothing left to tear


def test_nan_inject_through_the_engine_env(tmp_path, monkeypatch):
    """End-to-end chaos: DSTPU_TRAIN_FAULTS nan_at_steps poisons the batch,
    the sentinel's finite gate skip-steps it."""
    monkeypatch.setenv("DSTPU_TRAIN_FAULTS",
                       json.dumps({"enabled": True, "nan_at_steps": [1]}))
    e = _engine(extra={"anomaly_sentinel": {"enabled": True,
                                            "max_consecutive": 10}})
    e.train_batch(batch=_batch(0))
    assert e.skipped_steps == 0
    e.train_batch(batch=_batch(1))  # global step 1: poisoned
    assert e.skipped_steps == 1
    assert e._sentinel.anomalies == 1
    e.train_batch(batch=_batch(2))
    assert e.skipped_steps == 1


# ------------------------------------------------------------ report tooling --
def test_checkpoint_report_lists_statuses_and_survivors(tmp_path, capsys):
    """Satellite: ``dstpu_report --checkpoint`` verdicts + retention view."""
    from deepspeed_tpu.env_report import checkpoint_report
    e = _engine(extra={"checkpoint": {"keep_last_k": 3}})
    _train_and_save(e, tmp_path, 3)

    # every tag good → rc 0
    assert checkpoint_report(str(tmp_path)) == 0
    assert "all tags verified" in capsys.readouterr().out

    _corrupt_largest_state_file(os.path.join(str(tmp_path), "global_step3"))
    os.unlink(os.path.join(str(tmp_path), "global_step2", MANIFEST_FILE))
    rc = checkpoint_report(str(tmp_path))
    out = capsys.readouterr().out
    assert rc == 1
    assert "corrupt" in out and "torn" in out and "good" in out
    assert "crc32 mismatch" in out
    assert "latest" in out and "kept" in out
    assert "keep_last_k=3" in out
