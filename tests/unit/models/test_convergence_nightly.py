"""End-to-end convergence (SURVEY.md §4 nightly tier — the reference's
tests/model suite role: not just 'runs', but 'learns')."""

import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.utils import groups


@pytest.mark.nightly
@pytest.mark.parametrize("zero_stage", [2, 3])
def test_tiny_llama_memorizes(zero_stage):
    """A tiny llama under the fused train_batch path must drive loss far below
    its initial value on a fixed batch (memorization) — exercising the full
    stack: sharded init, ZeRO placement, remat-free forward, fused
    scan-accumulate-step, LR schedule."""
    import jax
    import jax.numpy as jnp
    from deepspeed_tpu.models.llama import LlamaConfig, LlamaForCausalLM, init_params

    groups.initialize_mesh(force=True)
    cfg = LlamaConfig.tiny(vocab_size=128, hidden_size=64, intermediate_size=128,
                           num_hidden_layers=2, num_attention_heads=4,
                           num_key_value_heads=4, max_position_embeddings=64)
    model = LlamaForCausalLM(cfg)
    rng = np.random.default_rng(0)
    ids = rng.integers(0, cfg.vocab_size, size=(16, 33), dtype=np.int64)
    batch = (ids[:, :-1].astype(np.int32), ids[:, 1:].astype(np.int32))
    params = model.init(jax.random.PRNGKey(0), batch)["params"]

    eng, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=params,
        config={"train_micro_batch_size_per_gpu": 1, "gradient_accumulation_steps": 2,
                "optimizer": {"type": "AdamW", "params": {"lr": 3e-3}},
                "scheduler": {"type": "WarmupLR",
                              "params": {"warmup_min_lr": 0, "warmup_max_lr": 3e-3,
                                         "warmup_num_steps": 5}},
                "zero_optimization": {"stage": zero_stage,
                                      "stage3_param_persistence_threshold": 0}})
    losses = [float(eng.train_batch(batch=batch)) for _ in range(30)]
    assert np.isfinite(losses).all()
    assert losses[-1] < 0.5 * losses[0], (losses[0], losses[-1])
    assert losses[-1] < 2.0, f"memorization should push CE well below ln(128): {losses[-1]}"
