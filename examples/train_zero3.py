"""Quickstart: ZeRO-3 training with bf16 compute and qwZ weight gathers.

Run (virtual 8-device CPU mesh):
    XLA_FLAGS=--xla_force_host_platform_device_count=8 python examples/train_zero3.py
On a TPU host, drop the flag — the real chips form the mesh.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.realpath(__file__))))

if "--cpu" in sys.argv or os.environ.get("JAX_PLATFORMS", "") == "cpu" \
        or "host_platform_device_count" in os.environ.get("XLA_FLAGS", ""):
    import jax
    jax.config.update("jax_platforms", "cpu")

import numpy as np
import jax
import jax.numpy as jnp
import flax.linen as nn

import deepspeed_tpu


class MLP(nn.Module):
    """A module whose apply(params, batch) returns the scalar loss."""

    @nn.compact
    def __call__(self, batch):
        x, y = batch
        h = nn.tanh(nn.Dense(256)(x))
        h = nn.tanh(nn.Dense(256)(h))
        return jnp.mean((nn.Dense(1)(h).squeeze(-1) - y) ** 2)


def batch_for_step(step, batch=32, dim=64):
    """Deterministic pure-function-of-step data: a resumed run replays the
    exact batches an uninterrupted run would see (the chaos-equivalence
    contract — real loaders checkpoint their cursor via client_state)."""
    rng = np.random.default_rng(1000 + step)
    x = rng.normal(size=(batch, dim)).astype(np.float32)
    y = (x[:, 0] * 0.5 - x[:, 1]).astype(np.float32)
    return x, y


def main_fault_tolerant():
    """DSTPU_CKPT_DIR mode: crash-consistent checkpoint per step, resume from
    the latest good tag, preemption-safe SIGTERM exit — and, under
    DSTPU_KILL_AT_STEP=N, a chaos SIGKILL after step N (first life only; the
    supervisor's DSTPU_RESTART_COUNT suppresses the replay). Run it under
    ``bin/dstpu_train`` and the killed-and-resumed run reaches a final
    loss/params numerically identical to an uninterrupted one."""
    import json

    ckdir = os.environ["DSTPU_CKPT_DIR"]
    total_steps = int(os.environ.get("DSTPU_TOTAL_STEPS", "8"))
    kill_at = os.environ.get("DSTPU_KILL_AT_STEP")
    if kill_at and "DSTPU_TRAIN_FAULTS" not in os.environ:
        os.environ["DSTPU_TRAIN_FAULTS"] = json.dumps(
            {"enabled": True, "kill_at_steps": [int(kill_at)]})

    model = MLP()
    params = model.init(jax.random.PRNGKey(0),
                        (jnp.asarray(batch_for_step(0)[0]),
                         jnp.asarray(batch_for_step(0)[1])))["params"]
    config = {
        "train_micro_batch_size_per_gpu": 32,
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 2},
        "checkpoint": {"keep_last_k": 3, "verify_arrays_on_load": True},
    }
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=params, config=config)
    engine.install_preemption_handler(save_dir=ckdir)
    path, _ = engine.load_checkpoint(ckdir)  # (None, None) on a fresh dir
    life = os.environ.get("DSTPU_RESTART_COUNT", "0")
    print(f"life {life}: {'resumed from ' + path if path else 'fresh start'} "
          f"at step {engine.global_steps}", flush=True)

    loss = None
    while engine.global_steps < total_steps:
        loss = engine.train_batch(batch=batch_for_step(engine.global_steps))
        engine.save_checkpoint(ckdir)

    if loss is None:  # resumed life found training already complete
        print(f"final step {engine.global_steps} (already complete)")
    else:
        print(f"final step {engine.global_steps} loss {float(loss):.10f}")
    out = os.environ.get("DSTPU_FINAL_PARAMS")
    if out:
        flat = jax.tree_util.tree_flatten_with_path(jax.device_get(engine.params))[0]
        np.savez(out, **{jax.tree_util.keystr(k): np.asarray(v) for k, v in flat})
    engine.destroy()
    print("OK")


def main():
    model = MLP()
    rng = np.random.default_rng(0)
    x = rng.normal(size=(32, 64)).astype(np.float32)
    y = (x[:, 0] * 0.5 - x[:, 1]).astype(np.float32)
    params = model.init(jax.random.PRNGKey(0), (jnp.asarray(x), jnp.asarray(y)))["params"]

    config = {
        "train_micro_batch_size_per_gpu": 32,
        "gradient_accumulation_steps": 2,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 3,
                              "zero_quantized_weights": True,   # qwZ: s8 gathers
                              "stage3_param_persistence_threshold": 0},
    }
    # DSTPU_TELEMETRY_DIR=<dir>: unified telemetry — JSONL metrics stream +
    # Chrome trace (open telemetry.trace.json in chrome://tracing / Perfetto)
    tel_dir = os.environ.get("DSTPU_TELEMETRY_DIR")
    if tel_dir:
        config["telemetry"] = {"enabled": True,
                               "jsonl_path": os.path.join(tel_dir, "telemetry.jsonl"),
                               "trace_path": os.path.join(tel_dir, "telemetry.trace.json")}

    engine, optimizer, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=params, config=config)
    assert isinstance(optimizer, deepspeed_tpu.ZeROOptimizer)

    for step in range(20):
        loss = engine.train_batch(batch=(np.tile(x, (2, 1)), np.tile(y, 2)))
        if step % 5 == 0:
            print(f"step {step:3d}  loss {float(loss):.4f}  lr {engine.get_lr()[0]:.2e}")

    if tel_dir:
        # micro-loop steps so the trace carries fwd/bwd/step spans, plus one
        # profiled eager collective for a comm span + latency/bytes histograms
        for _ in range(2):
            loss = engine.forward((x, y))
            engine.backward(loss)
            engine.step()
        deepspeed_tpu.comm.all_reduce(np.ones((8, 32), np.float32))

    # checkpoint + RLHF-style surgery on the sharded master
    import tempfile
    ckdir = tempfile.mkdtemp()
    engine.save_checkpoint(ckdir, tag="demo")
    from deepspeed_tpu.utils import safe_get_full_fp32_param
    w = safe_get_full_fp32_param(engine, "Dense_0/kernel")
    print(f"checkpoint saved; Dense_0/kernel gathered shape {w.shape}")
    engine.destroy()  # flushes the telemetry trace/JSONL when enabled
    print("OK")


if __name__ == "__main__":
    if os.environ.get("DSTPU_CKPT_DIR"):
        main_fault_tolerant()
    else:
        main()
