"""Benchmark harness (driver contract: print ONE JSON line, exit 0 ALWAYS).

Structure (crash/hang-proof — VERDICT r4 weak #1):
- The top-level process is an ORCHESTRATOR that never imports jax. A dead
  axon tunnel does not merely raise — it can HANG ``jax.devices()`` forever —
  so the backend probe and the measurement body both run in subprocesses
  with timeouts.
- The measurement body (``--worker <backend> <result.json>``) checkpoints its
  results to ``result.json`` after every leg; if the worker dies or hangs
  mid-leg, the orchestrator still harvests the completed legs and reports
  them with ``"partial": true``.
- If the TPU is unreachable the orchestrator emits a structured
  ``{"skipped": "tpu_unavailable", ...}`` line with CPU smoke numbers and
  exits 0 — the driver must never record a stack trace as the round's perf
  artifact.

Measurement targets (single chip, v5e):
- Headline: 530M-param Llama training step, ZeRO-3 semantics, bf16 + fp32
  master, B=8 GAS=8 S=1024, remat=dots — ``vs_baseline`` = MFU / 0.45 (the
  BASELINE.json north star is ZeRO-3 Llama SFT at >=45% MFU).
- Long-seq flash leg: S=4096 Pallas flash fwd+bwd vs dense.
- Inference: prefill + on-device decode_loop, Pallas paged kernel vs XLA
  gather (two-point differenced; the tunnel has ~100ms dispatch RTT and
  memoizes identical dispatches, so per-call timing of repeated identical
  programs is garbage — chain data, difference two N's, barrier via a host
  float() fetch).
- Block-sparse attention at 8k seq; evoformer at AF2 MSA shapes.

``bench.py --microbench`` runs ONLY the on-device kernel suite (paged-
attention decode, int4 unpack, block-sparse, evoformer) — two-point
differenced like the decode loop, structured-skip safe — so kernel numbers
accrue automatically whenever a chip is reachable, without paying for the
full training bench.

FLOPs model: 6*(N - N_embed) dense (fwd+bwd) + 12*L*S*H attention per token
(PaLM-appendix MFU convention, causal not discounted; embedding lookup
excluded).
"""

import json
import os
import subprocess
import sys
import tempfile
import time

PROBE_TIMEOUT_S = 150          # dead tunnel: jax.devices() hangs, not raises
TPU_WORKER_TIMEOUT_S = 55 * 60  # full TPU bench historically ~25-35 min
CPU_WORKER_TIMEOUT_S = 15 * 60


# --------------------------------------------------------------------------
# orchestrator (no jax imports at this level)
# --------------------------------------------------------------------------

def _probe_tpu():
    """Ask a subprocess whether the TPU backend answers. Returns (ok, why)."""
    if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
        return False, "JAX_PLATFORMS=cpu in environment"
    code = "import jax; jax.devices(); print(jax.default_backend())"
    try:
        r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                           text=True, timeout=PROBE_TIMEOUT_S)
    except subprocess.TimeoutExpired:
        return False, f"backend probe hung >{PROBE_TIMEOUT_S}s (tunnel dead?)"
    if r.returncode != 0:
        return False, f"backend probe rc={r.returncode}: {(r.stderr or '').strip()[-300:]}"
    backend = r.stdout.strip().splitlines()[-1] if r.stdout.strip() else ""
    if backend != "tpu":
        return False, f"default backend is {backend!r}, not tpu"
    return True, ""


def _run_worker(backend, timeout, microbench=False):
    """Run the measurement body in a subprocess; harvest its checkpoint file.

    Returns (result_dict, rc, err_tail). rc -1 = timeout. The checkpoint file
    is written after every completed leg, so a mid-leg death still yields the
    finished legs.
    """
    fd, path = tempfile.mkstemp(suffix=".json", prefix="bench_")
    os.close(fd)
    env = dict(os.environ)
    if backend == "cpu":
        env["JAX_PLATFORMS"] = "cpu"
    rc, err = 0, ""
    argv = [sys.executable, os.path.abspath(__file__), "--worker", backend, path]
    if microbench:
        argv.append("--microbench")
    try:
        proc = subprocess.run(argv, capture_output=True, text=True, timeout=timeout,
                              env=env)
        rc = proc.returncode
        err = (proc.stderr or "").strip()[-400:]
    except subprocess.TimeoutExpired:
        rc, err = -1, f"worker timed out after {timeout}s"
    except Exception as e:  # noqa: BLE001 — never let the orchestrator die
        rc, err = -2, repr(e)[:400]
    result = {}
    try:
        with open(path) as f:
            result = json.load(f)
    except Exception:
        pass
    try:
        os.unlink(path)
    except OSError:
        pass
    return result, rc, err


def _emit(payload):
    print(json.dumps(payload))
    sys.stdout.flush()


def main_microbench():
    """``bench.py --microbench``: on-device kernel microbenches only (paged
    decode, int4 unpack, block-sparse, evoformer). Same driver contract —
    one JSON line, exit 0, structured skip when no chip answers. Interpret-
    mode kernels on CPU are not measurements, so there is no CPU smoke leg."""
    tpu_ok, why = _probe_tpu()
    if not tpu_ok:
        _emit({"metric": "paged_decode_kernel_step_ms", "value": 0.0, "unit": "ms",
               "vs_baseline": 0.0, "skipped": "tpu_unavailable", "skip_reason": why,
               "extra": {"mode": "microbench"}})
        return
    res, rc, err = _run_worker("tpu", TPU_WORKER_TIMEOUT_S, microbench=True)
    extra = res.get("extra", {})
    paged = extra.get("paged_decode", {})
    out = {
        "metric": "paged_decode_kernel_step_ms",
        "value": float(paged.get("kernel_step_ms", 0.0)),
        "unit": "ms",
        "vs_baseline": 0.0,
        "extra": extra,
    }
    if "kernel_step_ms" not in paged:
        # the headline leg errored (or never ran): 0.0 must read as missing,
        # never as a real measurement
        out["partial"] = True
        out["partial_reason"] = (f"paged_decode leg produced no kernel_step_ms "
                                 f"({paged.get('error', 'leg absent')}); worker rc={rc}: {err}")
    elif not res.get("done"):
        out["partial"] = True
        out["partial_reason"] = f"worker rc={rc}: {err}"
    _emit(out)


def main():
    tpu_ok, why = _probe_tpu()

    if tpu_ok:
        res, rc, err = _run_worker("tpu", TPU_WORKER_TIMEOUT_S)
        if res.get("tokens_per_sec"):
            extra = res.get("extra", {})
            extra.update({k: v for k, v in res.items()
                          if k not in ("tokens_per_sec", "mfu", "extra", "done")})
            out = {
                "metric": "llama_train_tokens_per_sec_per_chip",
                "value": round(res["tokens_per_sec"], 1),
                "unit": "tokens/s",
                "vs_baseline": round(res["mfu"] / 0.45, 4),
                "extra": extra,
            }
            if not res.get("done"):
                out["partial"] = True
                out["partial_reason"] = f"worker rc={rc}: {err}"
            _emit(out)
            return
        why = f"tpu worker produced no headline number (rc={rc}): {err}"

    # TPU unreachable or its worker died before the headline leg: structured
    # skip + CPU smoke numbers so the artifact is still machine-readable.
    res, rc, err = _run_worker("cpu", CPU_WORKER_TIMEOUT_S)
    out = {
        "metric": "llama_train_tokens_per_sec_per_chip",
        "value": round(res.get("tokens_per_sec", 0.0), 1),
        "unit": "tokens/s",
        "vs_baseline": 0.0,
        "skipped": "tpu_unavailable",
        "skip_reason": why,
        "extra": {"cpu_smoke": res} if res else {"cpu_smoke_error": f"rc={rc}: {err}"},
    }
    _emit(out)


# --------------------------------------------------------------------------
# worker (imports jax; checkpoints to the result file after every leg)
# --------------------------------------------------------------------------

def _peak_flops():
    """bf16 peak per chip."""
    import jax
    kind = jax.devices()[0].device_kind.lower()
    if "v5 lite" in kind or "v5e" in kind:
        return 197e12
    if "v5p" in kind or "v5" in kind:
        return 459e12
    if "v4" in kind:
        return 275e12
    if "v6" in kind:
        return 918e12
    return 197e12  # conservative default


def _llama_530m(llama, jnp, S, **kw):
    """The 530M bench model (largest Llama-class fitting one 16 GB chip with
    fp32 master + Adam moments)."""
    return llama.LlamaConfig(vocab_size=32000, hidden_size=2048, intermediate_size=5376,
                             num_hidden_layers=8, num_attention_heads=16,
                             num_key_value_heads=16, max_position_embeddings=S,
                             dtype=jnp.bfloat16, **kw)


def _flops_per_token(cfg, n_params, S):
    """PaLM-appendix MFU convention: 6*(N - N_embed) dense fwd+bwd +
    12*L*S*H attention per token (causal not discounted; embed lookup free)."""
    return 6.0 * (n_params - cfg.vocab_size * cfg.hidden_size) \
        + 12.0 * cfg.num_hidden_layers * S * cfg.hidden_size


def _bench_attn_compare(llama, groups, jnp, peak, B, S, GAS):
    """Dense vs Pallas-flash training comparison at one (B, S, GAS) shape —
    two-point differenced per leg; flash_speedup is the ratio. Reused by the
    S=4096 long-seq leg AND the S=1024 headline-shape leg (the headline
    itself now trains with flash; this keeps the dense path selectable and
    measured for the same shape)."""
    import jax
    import numpy as np
    import deepspeed_tpu

    out = {}
    for flash in (False, True):
        groups.initialize_mesh(force=True)
        cfg = _llama_530m(llama, jnp, S, remat=True, remat_policy="dots",
                          use_flash_attention=flash)
        model, params = llama.init_params(cfg, batch_size=B, seq_len=S)
        n_params = sum(int(np.prod(p.shape)) for p in jax.tree.leaves(params))
        eng, _, _, _ = deepspeed_tpu.initialize(
            model=model, model_parameters=params,
            config={"train_micro_batch_size_per_gpu": B, "gradient_accumulation_steps": GAS,
                    "optimizer": {"type": "AdamW", "params": {"lr": 1e-4}},
                    "zero_optimization": {"stage": 3}, "bf16": {"enabled": True}})
        rng = np.random.default_rng(0)
        ids = rng.integers(0, cfg.vocab_size, size=(B * GAS, S + 1), dtype=np.int64)
        batch = (ids[:, :-1].astype(np.int32), ids[:, 1:].astype(np.int32))
        for _ in range(2):
            float(eng.train_batch(batch=batch))
        t0 = time.perf_counter()
        loss = None
        for _ in range(4):
            loss = eng.train_batch(batch=batch)
        float(loss)
        dt = (time.perf_counter() - t0) / 4
        tps = B * GAS * S / dt
        out["flash" if flash else "dense"] = {
            "tokens_per_sec": round(tps, 1),
            "mfu": round(tps * _flops_per_token(cfg, n_params, S) / peak, 4)}
        del eng, params
    out["flash_speedup"] = round(out["flash"]["tokens_per_sec"] /
                                 max(out["dense"]["tokens_per_sec"], 1e-9), 2)
    out["seq"] = S
    return out


def _bench_long_seq(llama, groups, jnp, peak):
    """Long-sequence training leg (VERDICT r3 #10): S=4096, Pallas flash
    attention vs dense — flash must win (dense OOMs outright at 8k on 16 GB)."""
    return _bench_attn_compare(llama, groups, jnp, peak, B=1, S=4096, GAS=4)


def _bench_headline_attention(llama, groups, jnp, peak):
    """Flash vs dense at the HEADLINE shape (S=1024) — the differenced
    justification for the headline leg running on the flash kernel (ROADMAP
    item 1's oldest unpaid debt). GAS shrunk from the headline's 8 to keep
    the comparison leg short; per-token step time is GAS-independent."""
    return _bench_attn_compare(llama, groups, jnp, peak, B=8, S=1024, GAS=2)


def _bench_inference(llama, groups, jnp):
    """Inference legs (VERDICT r4 #1): prefill tokens/s + decode tokens/s at
    long context, Pallas paged-attention kernel vs the XLA gather path.

    Methodology (the r3 numbers were tunnel artifacts in BOTH directions —
    fixed ~100ms dispatch RTT inflating per-put loops, and RPC elision
    deflating them below the HBM roofline):
    - prefill: warm puts differenced ((t(2 puts) - t(1 put)) / CTX) so the
      per-dispatch RTT cancels;
    - decode: the engine's on-device ``decode_loop`` (one dispatch runs N
      greedy steps as a lax.scan), two-point differenced between N1 and N2
      steps — device-bound, elision-proof (metadata advances every call).
    """
    import jax
    import numpy as np
    from deepspeed_tpu.inference.v2.config_v2 import RaggedInferenceEngineConfig
    from deepspeed_tpu.inference.v2.engine_factory import build_engine
    from deepspeed_tpu.inference.v2.ragged.manager_configs import (AllocationMode,
                                                                   DSStateManagerConfig,
                                                                   MemoryConfig)

    groups.initialize_mesh(force=True)
    MAXCTX, CTX = 4096, 3500
    N1, N2 = 16, 112
    cfg = _llama_530m(llama, jnp, MAXCTX)
    _, params = llama.init_params(cfg, seq_len=16)
    rng = np.random.default_rng(0)
    prompt = rng.integers(0, cfg.vocab_size, CTX)

    out = {"context": CTX, "decode_method": f"on-device decode_loop, (t({N2})-t({N1}))/{N2 - N1}"}
    # paged leg = auto mode (the deployment config): XLA-gather prefill +
    # Pallas-kernel decode buckets; forcing the kernel for a 3.5k prefill
    # would serialize 3.5k per-token programs nobody would ship
    for kernel, key in ((False, "xla_gather"), (None, "paged_kernel")):
        mgr = DSStateManagerConfig(memory_config=MemoryConfig(mode=AllocationMode.ALLOCATE,
                                                              size=2048),
                                   max_context=MAXCTX, max_ragged_batch_size=4096,
                                   max_ragged_sequence_count=8)
        eng = build_engine(params, cfg,
                           RaggedInferenceEngineConfig(state_manager=mgr, kv_block_size=16,
                                                       use_paged_kernel=kernel))
        t0 = time.perf_counter()
        pre = eng.put([0], [prompt])
        jax.block_until_ready(pre)
        prefill_compile_sec = time.perf_counter() - t0  # cold: includes compile

        # warm prefill, RTT-differenced: time 1 blocked put, then 2 puts with a
        # SINGLE sync (the dispatches pipeline; the cache chains them on
        # device) — the difference is one put's device time, RTT cancelled
        t0 = time.perf_counter()
        jax.block_until_ready(eng.put([1], [prompt]))
        t_one = time.perf_counter() - t0
        t0 = time.perf_counter()
        eng.put([2], [prompt])
        jax.block_until_ready(eng.put([3], [prompt]))
        t_two = time.perf_counter() - t0
        prefill_tps = CTX / max(t_two - t_one, 1e-9)
        if t_two <= t_one:  # timing noise — fall back to the single-put number
            prefill_tps = CTX / t_one

        # decode: device-side loop on uid 0 (context CTX and growing)
        first = np.asarray([int(np.argmax(np.asarray(pre)[0]))], np.int32)
        t0 = time.perf_counter()
        toks = eng.decode_loop([0], [first], N1)   # compiles the N1 program
        nxt = toks[:, -1]
        t_c1 = time.perf_counter() - t0
        t0 = time.perf_counter()
        toks = eng.decode_loop([0], [nxt], N2)     # compiles the N2 program
        nxt = toks[:, -1]
        decode_compile_sec = t_c1 + time.perf_counter() - t0
        t0 = time.perf_counter()
        toks = eng.decode_loop([0], [nxt], N1)
        nxt = toks[:, -1]
        t_n1 = time.perf_counter() - t0
        t0 = time.perf_counter()
        toks = eng.decode_loop([0], [nxt], N2)
        t_n2 = time.perf_counter() - t0
        if t_n2 > t_n1:
            decode_tps = (N2 - N1) / (t_n2 - t_n1)
            step_ms = 1e3 * (t_n2 - t_n1) / (N2 - N1)
        else:  # timing noise — fall back to the (RTT-inclusive) whole-call rate
            decode_tps = N2 / t_n2
            step_ms = 1e3 * t_n2 / N2
        out[key] = {"prefill_tokens_per_sec": round(prefill_tps, 1),
                    "decode_tokens_per_sec": round(decode_tps, 1),
                    "decode_step_ms": round(step_ms, 3),
                    "prefill_compile_sec": round(prefill_compile_sec, 1),
                    "decode_compile_sec": round(decode_compile_sec, 1)}
        del eng
    out["kernel_decode_speedup"] = round(
        out["paged_kernel"]["decode_tokens_per_sec"] /
        max(out["xla_gather"]["decode_tokens_per_sec"], 1e-9), 2)
    return out


def _bench_prefix_cache(llama, groups, jnp):
    """Automatic prefix-cache leg: cold vs warm TTFT on a shared-prefix batch
    (the shared-system-prompt workload). Both phases pay the identical fixed
    per-request cost — scheduler dispatch, the single-step forward producing
    the first token, sampling — so differencing warm from cold (the two-point
    trick at request granularity) isolates exactly the prefill the cache
    eliminated. Warmup requests absorb compiles before either phase is timed.
    """
    import numpy as np
    from deepspeed_tpu.inference.v2.config_v2 import RaggedInferenceEngineConfig
    from deepspeed_tpu.inference.v2.engine_factory import build_engine
    from deepspeed_tpu.inference.v2.ragged.manager_configs import (AllocationMode,
                                                                   DSStateManagerConfig,
                                                                   MemoryConfig)
    from deepspeed_tpu.serving import PrefixCacheConfig, ServingConfig, ServingScheduler

    groups.initialize_mesh(force=True)
    MAXCTX, PREFIX, SUFFIX, K = 4096, 3456, 64, 4
    cfg = _llama_530m(llama, jnp, MAXCTX)
    _, params = llama.init_params(cfg, seq_len=16)
    rng = np.random.default_rng(0)
    shared = rng.integers(0, cfg.vocab_size, PREFIX)

    mgr = DSStateManagerConfig(memory_config=MemoryConfig(mode=AllocationMode.ALLOCATE,
                                                          size=4096),
                               max_context=MAXCTX, max_ragged_batch_size=4096,
                               max_ragged_sequence_count=8)
    eng = build_engine(params, cfg,
                       RaggedInferenceEngineConfig(state_manager=mgr, kv_block_size=16))
    sched = ServingScheduler(eng, ServingConfig(
        prefix_cache=PrefixCacheConfig(enabled=True)))

    def ttft(prefix):
        prompt = np.concatenate([prefix,
                                 rng.integers(0, cfg.vocab_size, SUFFIX)])
        req = sched.submit(prompt.tolist(), max_new_tokens=2)
        req.result(timeout=600)
        return req.ttft_s, req.cached_tokens

    try:
        # warmup: compile every bucket both phases touch AND publish the
        # shared prefix (the first shared-prefix request is the publisher)
        ttft(rng.integers(0, cfg.vocab_size, PREFIX))
        ttft(shared)
        cold = [ttft(rng.integers(0, cfg.vocab_size, PREFIX))[0] for _ in range(K)]
        warm_pairs = [ttft(shared) for _ in range(K)]
        warm = [t for t, _ in warm_pairs]
        cached = [c for _, c in warm_pairs]
    finally:
        sched.stop(drain=False)
        del eng
    cold_ms = 1e3 * float(np.median(cold))
    warm_ms = 1e3 * float(np.median(warm))
    return {"prefix_tokens": PREFIX, "suffix_tokens": SUFFIX, "requests_per_phase": K,
            "cold_ttft_ms": round(cold_ms, 2), "warm_ttft_ms": round(warm_ms, 2),
            "ttft_saved_ms": round(cold_ms - warm_ms, 2),
            "ttft_speedup": round(cold_ms / max(warm_ms, 1e-9), 2),
            "cached_tokens_per_hit": int(np.median(cached))}


def _bench_speculative_decode(llama, groups, jnp):
    """Speculative-decoding leg: a repeated (templated-workload shape) prompt
    decoded spec-on vs spec-off through the serving scheduler. Two-point
    differenced like the decode-loop leg: each arm times a warm N1-token and
    a warm N2-token request, so (t2 - t1)/(N2 - N1) isolates the marginal
    per-token cost (ITL) and cancels the shared fixed cost — dispatch, the
    prefix-hit admission, the single prefill step. Warmup requests absorb
    compiles (including every verify-feed bucket) before either arm is
    timed. Reports accepted-tokens-per-step, acceptance rate, and the ITL
    delta/speedup. The third arm runs ``drafter="auto"`` (tree verify, a
    fresh learned head racing prompt-lookup): on this templated workload
    arbitration should settle on prompt-lookup — the reported
    ``winning_drafter`` shows auto finds the right drafter instead of
    taxing the win the trie already delivers."""
    import numpy as np
    from deepspeed_tpu.inference.v2.config_v2 import RaggedInferenceEngineConfig
    from deepspeed_tpu.inference.v2.engine_factory import build_engine
    from deepspeed_tpu.inference.v2.ragged.manager_configs import (AllocationMode,
                                                                   DSStateManagerConfig,
                                                                   MemoryConfig)
    from deepspeed_tpu.serving import (PrefixCacheConfig, ServingConfig,
                                       ServingScheduler, SpeculativeConfig)

    groups.initialize_mesh(force=True)
    MAXCTX, PROMPT, N1, N2, K = 2048, 512, 16, 112, 4
    cfg = _llama_530m(llama, jnp, MAXCTX)
    _, params = llama.init_params(cfg, seq_len=16)
    prompt = np.random.default_rng(0).integers(0, cfg.vocab_size, PROMPT).tolist()

    out = {"prompt_tokens": PROMPT, "n1": N1, "n2": N2, "max_draft_tokens": K}
    arms = (("spec_off", dict(enabled=False)),
            ("spec_on", dict(enabled=True, max_draft_tokens=K)),
            ("spec_auto", dict(enabled=True, drafter="auto",
                               max_draft_tokens=K)))
    for key, spec_kw in arms:
        mgr = DSStateManagerConfig(memory_config=MemoryConfig(mode=AllocationMode.ALLOCATE,
                                                              size=512),
                                   max_context=MAXCTX, max_ragged_batch_size=2048,
                                   max_ragged_sequence_count=8)
        eng = build_engine(params, cfg,
                           RaggedInferenceEngineConfig(state_manager=mgr,
                                                       kv_block_size=16))
        sched = ServingScheduler(eng, ServingConfig(
            prefix_cache=PrefixCacheConfig(enabled=True),
            speculative=SpeculativeConfig(**spec_kw)))

        def gen(n):
            req = sched.submit(prompt, max_new_tokens=n)
            req.result(timeout=600)
            return req

        try:
            gen(N2)            # publisher: full history lands in the trie
            gen(N1)
            gen(N2)            # warm the exact timed shapes and programs
            t0 = time.perf_counter()
            gen(N1)
            t_n1 = time.perf_counter() - t0
            t0 = time.perf_counter()
            r2 = gen(N2)
            t_n2 = time.perf_counter() - t0
            winner = None
            if key == "spec_auto":
                doc = sched.stats()["speculative"]
                ew = {n: d["ewma"] for n, d in doc["drafters"].items()
                      if d["ewma"] is not None}
                winner = max(ew, key=ew.get) if ew else None
        finally:
            sched.stop(drain=False)
            del eng
        itl_ms = (1e3 * (t_n2 - t_n1) / (N2 - N1) if t_n2 > t_n1
                  else 1e3 * t_n2 / N2)  # timing noise: whole-call fallback
        dispatches = max(1, r2.decode_steps) + 1  # + the prefill-hit dispatch
        out[key] = {"itl_ms": round(itl_ms, 3),
                    "decode_steps": r2.decode_steps,
                    "tokens_per_step": round(N2 / dispatches, 2),
                    "accept_rate": (round(r2.spec_accepted / r2.spec_drafted, 3)
                                    if r2.spec_drafted else None)}
        if key == "spec_auto":
            out[key]["winning_drafter"] = winner
    out["accepted_tokens_per_step"] = out["spec_on"]["tokens_per_step"]
    out["itl_saved_ms"] = round(out["spec_off"]["itl_ms"]
                                - out["spec_on"]["itl_ms"], 3)
    out["itl_speedup"] = round(out["spec_off"]["itl_ms"]
                               / max(out["spec_on"]["itl_ms"], 1e-9), 2)
    return out


def _bench_int4_weights(llama, groups, jnp):
    """ZeRO-Inference weight-quantization leg (VERDICT r5 ask #5): decode
    throughput with bf16 vs int8 vs int4 weights — weight-only quantization
    pays off when decode is weight-bandwidth-bound."""
    import numpy as np
    from deepspeed_tpu.inference.v2.config_v2 import (QuantizationConfig,
                                                      RaggedInferenceEngineConfig)
    from deepspeed_tpu.inference.v2.engine_factory import build_engine
    from deepspeed_tpu.inference.v2.ragged.manager_configs import (AllocationMode,
                                                                   DSStateManagerConfig,
                                                                   MemoryConfig)

    groups.initialize_mesh(force=True)
    MAXCTX, CTX = 2048, 512
    N1, N2 = 16, 112
    cfg = _llama_530m(llama, jnp, MAXCTX)
    _, params = llama.init_params(cfg, seq_len=16)
    rng = np.random.default_rng(0)
    prompt = rng.integers(0, cfg.vocab_size, CTX)

    out = {"context": CTX}
    for bits, key in ((None, "bf16"), (8, "int8"), (4, "int4")):
        mgr = DSStateManagerConfig(memory_config=MemoryConfig(mode=AllocationMode.ALLOCATE,
                                                              size=512),
                                   max_context=MAXCTX, max_ragged_batch_size=2048,
                                   max_ragged_sequence_count=8)
        eng = build_engine(params, cfg,
                           RaggedInferenceEngineConfig(
                               state_manager=mgr, kv_block_size=16,
                               quantization=QuantizationConfig(enabled=bits is not None,
                                                               bits=bits or 8)))
        pre = eng.put([0], [prompt])
        first = np.asarray([int(np.argmax(np.asarray(pre)[0]))], np.int32)
        nxt = eng.decode_loop([0], [first], N1)[:, -1]   # compile N1
        nxt = eng.decode_loop([0], [nxt], N2)[:, -1]     # compile N2
        t0 = time.perf_counter()
        nxt = eng.decode_loop([0], [nxt], N1)[:, -1]
        t_n1 = time.perf_counter() - t0
        t0 = time.perf_counter()
        toks = eng.decode_loop([0], [nxt], N2)
        t_n2 = time.perf_counter() - t0
        if t_n2 > t_n1:
            tps = (N2 - N1) / (t_n2 - t_n1)
        else:
            tps = N2 / t_n2
        out[key] = {"decode_tokens_per_sec": round(tps, 1)}
        del eng
    out["int4_vs_bf16"] = round(out["int4"]["decode_tokens_per_sec"] /
                                max(out["bf16"]["decode_tokens_per_sec"], 1e-9), 2)
    return out


def _bench_sparse_attention(jnp):
    """Block-sparse attention leg (VERDICT r4 #4): 8k sequence — where dense
    S² scores OOM on 16 GB — BigBird layouts at two densities; fwd+bwd time
    must scale with layout density. Timing: chained on-device scans, two-point
    differenced, with a host value fetch as the barrier."""
    import jax
    from deepspeed_tpu.ops.pallas.block_sparse_attention import block_sparse_attention
    from deepspeed_tpu.ops.sparse_attention.sparsity_config import BigBirdSparsityConfig

    B, H, S, D, LB = 1, 16, 8192, 128, 64
    N1, N2 = 2, 10
    mk = lambda s: jax.jit(lambda k: jax.random.normal(k, (B, H, S, D), jnp.bfloat16))(
        jax.random.PRNGKey(s))
    q, k, v = mk(1), mk(2), mk(3)

    def leg(nrand, nwin):
        layout = BigBirdSparsityConfig(num_heads=H, block=LB, num_random_blocks=nrand,
                                       num_sliding_window_blocks=nwin,
                                       num_global_blocks=1).make_layout(S)

        def loss(q, k, v):
            return (block_sparse_attention(q, k, v, layout, LB).astype(jnp.float32) ** 2).mean()

        def make(n):
            @jax.jit
            def scan_fn(q, k, v):
                def body(x, _):
                    l, gq = jax.value_and_grad(loss)(x, k, v)
                    return x + gq.astype(x.dtype) * 1e-4, l
                return jax.lax.scan(body, q, None, length=n)
            return scan_fn

        f1, f2 = make(N1), make(N2)
        x, ls = f1(q, k, v)
        float(ls[-1])
        x, ls = f2(x, k, v)
        float(ls[-1])

        def t(f):
            nonlocal x
            best = 1e9
            for _ in range(3):
                t0 = time.perf_counter()
                x, ls = f(x, k, v)
                float(ls[-1])  # host fetch = true barrier
                best = min(best, time.perf_counter() - t0)
            return best

        ms = (t(f2) - t(f1)) / (N2 - N1) * 1e3
        return {"density": round(float(layout.mean()), 4), "fwd_bwd_ms": round(ms, 2)}

    lo = leg(1, 3)
    hi = leg(4, 9)
    return {"seq": S, "layout": "bigbird", "low": lo, "high": hi,
            "time_ratio": round(hi["fwd_bwd_ms"] / max(lo["fwd_bwd_ms"], 1e-9), 2),
            "density_ratio": round(hi["density"] / lo["density"], 2)}


def _bench_evoformer(jnp, peak):
    """Evoformer attention at MSA-realistic shapes (VERDICT r4 #10): fwd+bwd
    time and achieved FLOP/s for the XLA-fused einsum formulation, with and
    without remat — the measured justification for not hand-writing the
    reference's 15k-LoC CUTLASS tier. Two-point differenced scans, host-fetch
    barrier."""
    import jax
    from deepspeed_tpu.ops.evoformer import DS4Sci_EvoformerAttention

    B, N, S, H, D = 1, 128, 256, 4, 32  # MSA row-attention shape (AF2)
    key = jax.random.PRNGKey(0)
    mk = lambda i, shape: jax.random.normal(jax.random.fold_in(key, i), shape, jnp.bfloat16)
    q = mk(0, (B, N, S, H, D))
    k = mk(1, (B, N, S, H, D))
    v = mk(2, (B, N, S, H, D))
    b1 = mk(3, (B, N, 1, 1, S))
    b2 = mk(4, (B, 1, H, S, S))

    def one(remat):
        attn = DS4Sci_EvoformerAttention
        if remat:
            attn = jax.checkpoint(lambda *a: DS4Sci_EvoformerAttention(a[0], a[1], a[2],
                                                                       biases=[a[3], a[4]]))
            loss0 = lambda q: (attn(q, k, v, b1, b2).astype(jnp.float32) ** 2).mean()
        else:
            loss0 = lambda q: (attn(q, k, v, biases=[b1, b2]).astype(jnp.float32) ** 2).mean()

        def make(n):
            @jax.jit
            def f(q):
                def body(x, _):
                    l, g = jax.value_and_grad(loss0)(x)
                    return x + g.astype(x.dtype) * 1e-4, l
                return jax.lax.scan(body, q, None, length=n)
            return f

        f1, f2 = make(2), make(10)
        x, ls = f1(q)
        float(ls[-1])
        x, ls = f2(x)
        float(ls[-1])

        def t(f, x):
            best = 1e9
            for _ in range(3):
                t0 = time.perf_counter()
                x, ls = f(x)
                float(ls[-1])
                best = min(best, time.perf_counter() - t0)
            return best, x

        ta, x = t(f1, x)
        tb, x = t(f2, x)
        return (tb - ta) / 8

    plain = one(False)
    remat = one(True)
    # fwd 4*N*H*S^2*D mults-adds *2, bwd ~2.5x
    flops = 2 * 4 * B * N * H * S * S * D * 3.5
    return {"shape": f"B{B} N{N} S{S} H{H} D{D}", "fwd_bwd_ms": round(plain * 1e3, 2),
            "achieved_tflops": round(flops / plain / 1e12, 1),
            "peak_fraction": round(flops / plain / peak, 3),
            "remat_fwd_bwd_ms": round(remat * 1e3, 2),
            "remat_time_ratio": round(remat / max(plain, 1e-12), 2)}


def _microbench_paged_decode(jnp, T=8, H=16, KVH=16, D=128, bs=16, S=8, MB=64,
                             N1=4, N2=20):
    """Kernel-level paged-attention decode microbench: the Pallas fused
    KV-insert + blocked-attention kernel vs nothing else — one decode batch
    (8 sequences x 1 token, 1k context each at the default shape) applied in
    a chained on-device scan, two-point differenced with a host-fetch
    barrier (the decode-loop methodology at kernel granularity). Shapes are
    overridable so the CPU interpret-mode smoke test stays cheap."""
    import jax
    from deepspeed_tpu.ops.pallas.paged_attention import paged_attention_update

    NB = S * MB + 1                    # +1: the drop-mode scatter target
    key = jax.random.PRNGKey(0)
    mk = lambda i, shape: jax.random.normal(jax.random.fold_in(key, i), shape, jnp.bfloat16)
    q = mk(0, (T, H, D))
    k_new = mk(1, (T, KVH, D))
    v_new = mk(2, (T, KVH, D))
    cache = mk(3, (1, 2, NB, KVH, bs, D))
    table = jnp.arange(S * MB, dtype=jnp.int32).reshape(S, MB)
    token_seq = jnp.arange(T, dtype=jnp.int32)
    token_pos = jnp.full((T, ), MB * bs - 1, jnp.int32)
    token_valid = jnp.ones((T, ), bool)

    def make(n):
        @jax.jit
        def f(q, cache):
            def body(carry, _):
                qq, cache = carry
                out, cache = paged_attention_update(qq, k_new, v_new, cache, 0, table,
                                                    token_seq, token_pos, token_valid)
                # chain through q so the scan cannot be elided or reordered
                return (q + out * jnp.bfloat16(1e-3), cache), out[0, 0, 0]
            (_, cache), outs = jax.lax.scan(body, (q, cache), None, length=n)
            return cache, outs[-1]
        return f

    f1, f2 = make(N1), make(N2)
    cache, o = f1(q, cache)
    float(o)
    cache, o = f2(q, cache)
    float(o)

    def t(f, cache):
        best = 1e9
        for _ in range(3):
            t0 = time.perf_counter()
            cache, o = f(q, cache)
            float(o)  # host fetch = true barrier
            best = min(best, time.perf_counter() - t0)
        return best, cache

    ta, cache = t(f1, cache)
    tb, cache = t(f2, cache)
    ms = (tb - ta) / (N2 - N1) * 1e3
    return {"seqs": S, "context": MB * bs, "heads": H, "head_dim": D,
            "kernel_step_ms": round(ms, 4),
            "tokens_per_sec": round(T / max(ms / 1e3, 1e-9), 1)}


def _microbench_int4_unpack(jnp, K=4096, N=4096, N1=8, N2=40):
    """Int4 unpack on the decode critical path: x[1,K] @ W[K,N] with W held
    bf16 vs packed-int4 (dequantized inside the jit, as the engine does) —
    the weight-bandwidth story isolated from the rest of the model. Chained
    scans, two-point differenced."""
    import jax
    from deepspeed_tpu.inference.v2.quantization import (_quantize_leaf_int4,
                                                         dequantize_tree)
    key = jax.random.PRNGKey(0)
    w = jax.random.normal(key, (K, N), jnp.bfloat16)
    x0 = jax.random.normal(jax.random.fold_in(key, 1), (1, K), jnp.bfloat16)
    packed = jax.jit(_quantize_leaf_int4)(w)

    def make(n, weights):
        @jax.jit
        def f(x):
            def body(x, _):
                y = x @ dequantize_tree(weights)   # [1, N] (N == K chains back)
                # renormalize so the chain neither explodes nor denorms
                x = (y / (jnp.abs(y).max() + 1e-6)).astype(jnp.bfloat16)
                return x, y[0, 0]
            x, ys = jax.lax.scan(body, x, None, length=n)
            return x, ys[-1]
        return f

    out = {"K": K, "N": N}
    for name, weights in (("bf16", w), ("int4", packed)):
        f1, f2 = make(N1, weights), make(N2, weights)
        x, y = f1(x0)
        float(y)
        x, y = f2(x)
        float(y)

        def t(f, x):
            best = 1e9
            for _ in range(3):
                t0 = time.perf_counter()
                x, y = f(x)
                float(y)
                best = min(best, time.perf_counter() - t0)
            return best, x

        ta, x = t(f1, x)
        tb, x = t(f2, x)
        out[name] = {"matmul_us": round((tb - ta) / (N2 - N1) * 1e6, 2)}
    out["int4_speedup"] = round(out["bf16"]["matmul_us"] /
                                max(out["int4"]["matmul_us"], 1e-9), 2)
    return out


def _microbench_legs(jnp, peak):
    """The --microbench kernel suite: two-point differenced on-device kernel
    timings (paged decode, int4 unpack, block-sparse, evoformer) that accrue
    automatically whenever a chip is reachable."""
    return (
        ("paged_decode", lambda: _microbench_paged_decode(jnp)),
        ("int4_unpack", lambda: _microbench_int4_unpack(jnp)),
        ("sparse_attention", lambda: _bench_sparse_attention(jnp)),
        ("evoformer", lambda: _bench_evoformer(jnp, peak)),
    )


def _worker(backend, result_path, microbench=False):
    """Measurement body. Writes the accumulating result dict to result_path
    after every leg so a mid-leg crash/hang still leaves evidence.
    ``microbench`` skips the training/engine legs and runs only the
    kernel-level suite (``bench.py --microbench``)."""
    if backend == "cpu":
        # site hooks (the axon TPU shim) override JAX_PLATFORMS at startup;
        # re-assert cpu before any backend touch or the smoke worker hangs
        # on a dead tunnel
        from deepspeed_tpu.utils.jax_platform import honor_platform_env
        honor_platform_env(default="cpu")
    import jax
    import jax.numpy as jnp
    import numpy as np

    import deepspeed_tpu
    from deepspeed_tpu.models import llama
    from deepspeed_tpu.utils import groups

    acc = {}

    def save():
        # atomic: a timeout kill mid-write must not truncate the finished legs
        tmp = result_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(acc, f)
        os.replace(tmp, result_path)

    on_tpu = jax.default_backend() == "tpu"

    if microbench:
        acc["extra"] = {"mode": "microbench", "backend": jax.default_backend(),
                        "device": str(jax.devices()[0])}
        for name, fn in _microbench_legs(jnp, _peak_flops()):
            try:
                acc["extra"][name] = fn()
            except Exception as e:  # noqa: BLE001 — a leg must not kill the bench
                acc["extra"][name] = {"error": str(e)[:200]}
            save()
        acc["done"] = True
        save()
        return
    if on_tpu:
        B, S, GAS, STAGE = 8, 1024, 8, 3
        # the headline leg trains on the Pallas flash kernel (ROADMAP item 1);
        # DSTPU_BENCH_ATTENTION=dense selects the dense path for A/B runs, and
        # the headline_attention leg measures both at this shape regardless
        attention = os.environ.get("DSTPU_BENCH_ATTENTION", "flash")
        cfg = llama.LlamaConfig(vocab_size=32000, hidden_size=2048, intermediate_size=5376,
                                num_hidden_layers=8, num_attention_heads=16, num_key_value_heads=16,
                                max_position_embeddings=S, remat=True, remat_policy="dots",
                                dtype=jnp.bfloat16,
                                use_flash_attention=(attention != "dense"))
        steps, warmup = 12, 3
    else:  # smoke-test shape for CPU runs
        B, S, GAS, STAGE = 2, 128, 1, 3
        cfg = llama.LlamaConfig.tiny()
        steps, warmup = 8, 1

    model, params = llama.init_params(cfg, batch_size=B, seq_len=S)
    n_params = sum(int(np.prod(p.shape)) for p in jax.tree.leaves(params))

    groups.initialize_mesh(force=True)
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=params,
        config={
            "train_micro_batch_size_per_gpu": B,
            "gradient_accumulation_steps": GAS,
            "optimizer": {"type": "AdamW", "params": {"lr": 1e-4}},
            "zero_optimization": {"stage": STAGE},
            "bf16": {"enabled": True},
        })

    # Pre-generate host batches (the input pipeline must not sit inside the
    # measured loop; train_batch's device_put overlaps the previous step's
    # compute because dispatch is async).
    rng = np.random.default_rng(0)
    batches = []
    for _ in range(8):
        ids = rng.integers(0, cfg.vocab_size, size=(B * GAS, S + 1), dtype=np.int64)
        batches.append((ids[:, :-1].astype(np.int32), ids[:, 1:].astype(np.int32)))

    for i in range(warmup):
        float(engine.train_batch(batch=batches[i % len(batches)]))  # host fetch = true barrier

    # Two-point measurement: total(N) = N*step + RTT. The steps chain through the
    # donated params, so ONE final scalar fetch forces the whole chain; differencing
    # two N's cancels the (tunnel) round-trip latency and async-dispatch skew.
    def run(n):
        t0 = time.perf_counter()
        loss = None
        for i in range(n):
            loss = engine.train_batch(batch=batches[i % len(batches)])
        float(loss)
        return time.perf_counter() - t0, loss

    n1 = max(2, steps // 4)
    t1, _ = run(n1)
    t2, loss = run(steps)
    step_time = (t2 - t1) / (steps - n1)
    if step_time <= 0:  # timing noise (fast local backends) — fall back to plain avg
        step_time = t2 / steps
    tokens_per_sec = B * GAS * S / step_time
    mfu = tokens_per_sec * _flops_per_token(cfg, n_params, S) / _peak_flops()

    acc.update({
        "tokens_per_sec": tokens_per_sec,
        "mfu": round(mfu, 4),
        "extra": {
            "mfu": round(mfu, 4),
            "n_params": n_params,
            "batch": B,
            "gas": GAS,
            "seq": S,
            "zero_stage": STAGE,
            "attention": cfg.use_flash_attention and "flash" or "dense",
            "backend": jax.default_backend(),
            "device": str(jax.devices()[0]),
            "loss_final": float(loss),
        },
    })
    save()

    if on_tpu:
        # free the training engine's HBM before the other legs
        del engine, params
        legs = (
            ("headline_attention", lambda: _bench_headline_attention(llama, groups, jnp,
                                                                     _peak_flops())),
            ("long_seq_train", lambda: _bench_long_seq(llama, groups, jnp, _peak_flops())),
            ("microbench_paged_decode", lambda: _microbench_paged_decode(jnp)),
            ("microbench_int4_unpack", lambda: _microbench_int4_unpack(jnp)),
            ("inference", lambda: _bench_inference(llama, groups, jnp)),
            ("prefix_cache", lambda: _bench_prefix_cache(llama, groups, jnp)),
            ("speculative_decode", lambda: _bench_speculative_decode(llama, groups, jnp)),
            ("int4_weights", lambda: _bench_int4_weights(llama, groups, jnp)),
            ("sparse_attention", lambda: _bench_sparse_attention(jnp)),
            ("evoformer", lambda: _bench_evoformer(jnp, _peak_flops())),
        )
        for name, fn in legs:
            try:
                acc["extra"][name] = fn()
            except Exception as e:  # noqa: BLE001 — a leg must not kill the bench
                acc["extra"][name] = {"error": str(e)[:200]}
            save()

    acc["done"] = True
    save()


if __name__ == "__main__":
    if len(sys.argv) >= 4 and sys.argv[1] == "--worker":
        _worker(sys.argv[2], sys.argv[3], microbench="--microbench" in sys.argv[4:])
    else:
        try:
            if "--microbench" in sys.argv[1:]:
                main_microbench()
            else:
                main()
        except Exception as e:  # noqa: BLE001 — the driver contract is rc=0 + one JSON line
            _emit({"metric": "llama_train_tokens_per_sec_per_chip", "value": 0.0,
                   "unit": "tokens/s", "vs_baseline": 0.0,
                   "skipped": "bench_orchestrator_error", "skip_reason": repr(e)[:400]})
        sys.exit(0)
