"""Overload control, fleet side (ISSUE tentpole c): the router global queue
(priority/deadline pull dispatch, ROADMAP 3c), hedged dispatch with
first-writer-wins cancellation, slow-replica demotion, the two new chaos
points (``decode_stall``, ``overload_burst``), the Retry-After contract
through the router — plus the seeded overload soak and the flagship CPU gate
(both slow-marked).
"""

import threading
import time

import numpy as np
import pytest

from deepspeed_tpu.fleet import (FaultConfig, FaultInjector, FleetConfig,
                                 FleetRouter, GlobalQueue, GlobalQueueFull,
                                 HedgeConfig, QueueWaitExpired, RoutingError)
from deepspeed_tpu.fleet.config import GlobalQueueConfig
from deepspeed_tpu.serving.config import OverloadConfig, ServingConfig


def _prompt(n=9, vocab=64):
    return (np.arange(n) % vocab).tolist()


def _fleet_config(**kw):
    kw.setdefault("probe_ttl_s", 0.0)
    kw.setdefault("retry_backoff_base_s", 0.0)
    return FleetConfig(**kw)


class _Stub:
    """A replica as the global queue sees one: an id and a load."""

    def __init__(self, rid, load=0):
        self.id = rid
        self.load = load


def _pick(candidates, session_key, **_kw):
    return min(candidates, key=lambda r: (r.load, r.id))


# ---------------------------------------------------------------------------
# the global queue (no engine)
# ---------------------------------------------------------------------------
def test_global_queue_grants_in_priority_then_deadline_order():
    gq = GlobalQueue(max_inflight=1, capacity=16, pick=_pick)
    r0 = _Stub("r0")
    pool = lambda: [r0]
    granted = gq.acquire(pool)          # free slot: granted inline
    assert granted is r0 and gq.slots_in_use("r0") == 1

    order = []

    def waiter(name, priority, deadline_s):
        gq.acquire(pool, priority=priority, deadline_s=deadline_s,
                   timeout_s=30.0)
        order.append(name)

    # submission order deliberately worst-first; grant order must be
    # (priority, deadline) — interactive beats batch, earlier deadline wins
    threads = [threading.Thread(target=waiter, args=args, daemon=True)
               for args in (("batch-late", "batch", 60.0),
                            ("batch-early", "batch", 20.0),
                            ("interactive", "interactive", 60.0))]
    for t in threads:
        t.start()
        time.sleep(0.02)  # deterministic enqueue order (seq tiebreak)
    deadline = time.monotonic() + 5
    while gq.depth < 3 and time.monotonic() < deadline:
        time.sleep(0.005)
    assert gq.depth == 3

    for expect in range(1, 4):
        gq.release("r0")  # frees the slot; the pump grants the best entry
        deadline = time.monotonic() + 5
        while len(order) < expect and time.monotonic() < deadline:
            time.sleep(0.005)
        assert len(order) == expect, f"grant {expect} never happened"
    for t in threads:
        t.join(timeout=5)
    assert order == ["interactive", "batch-early", "batch-late"]
    assert gq.describe()["grants"] == 4


def test_global_queue_at_capacity_raises_with_retry_after():
    gq = GlobalQueue(max_inflight=1, capacity=2, pick=_pick,
                     retry_after_floor_s=0.5)
    assert gq.inject_phantoms(5, hold_s=30.0) == 2  # capacity-bounded
    with pytest.raises(GlobalQueueFull) as exc:
        gq.acquire(lambda: [_Stub("r0")], timeout_s=1.0)
    assert exc.value.retry_after_s >= 0.5
    assert gq.describe()["phantoms_injected"] == 2


def test_global_queue_wait_expiry_sheds_before_any_dispatch():
    gq = GlobalQueue(max_inflight=1, capacity=8, pick=_pick)
    r0 = _Stub("r0")
    gq.acquire(lambda: [r0])  # the only slot is taken
    t0 = time.monotonic()
    with pytest.raises(QueueWaitExpired) as exc:
        gq.acquire(lambda: [r0], deadline_s=0.15, timeout_s=30.0)
    assert 0.1 < time.monotonic() - t0 < 5.0  # expired at the deadline
    assert exc.value.retry_after_s > 0
    assert gq.describe()["expired"] == 1
    assert gq.depth == 0  # the expired entry left the queue
    assert gq.slots_in_use("r0") == 1  # the holder's slot is untouched


def test_global_queue_phantoms_expire_through_normal_accounting():
    gq = GlobalQueue(max_inflight=2, capacity=8, pick=_pick)
    assert gq.inject_phantoms(2, hold_s=0.05) == 2
    assert gq.depth == 2
    time.sleep(0.1)
    # any pump sweeps expired phantoms; a real acquire still grants through
    assert gq.acquire(lambda: [_Stub("r0")]) is not None
    doc = gq.describe()
    assert doc["depth"] == 0 and doc["expired"] == 2
    assert doc["grants"] == 1  # phantoms are never granted


# ---------------------------------------------------------------------------
# the two new chaos points
# ---------------------------------------------------------------------------
def test_new_fault_points_schedules_deterministic_and_scoped():
    cfg = FaultConfig(enabled=True, seed=11, decode_stall_p=0.3,
                      decode_stall_s=0.4, overload_burst_p=0.2)
    a, b = FaultInjector(cfg), FaultInjector(cfg)
    for point, scope in (("decode_stall", "r0"), ("overload_burst", None)):
        live = [n for n in (a.fire(point, scope) for _ in range(200))
                if n is not None]
        assert live == a.schedule(point, 200, scope)  # live == pure oracle
        assert live == b.schedule(point, 200, scope)  # fresh instance agrees
        assert live, f"nothing fired at {point} in 200 events — p rotted?"
    # stall shape: hash-derived, bounded by decode_stall_s, never zero
    for n in range(20):
        assert 0 < a.stall_s(n, "r0") <= 0.4

    # replica scoping: a scoped stall leaves other replicas untouched (and
    # consumes no schedule indices there)
    scoped = FaultInjector(FaultConfig(enabled=True, seed=11,
                                       decode_stall_p=1.0,
                                       decode_stall_replica="r0"))
    assert scoped.stalls_replica("r0") and not scoped.stalls_replica("r1")
    unscoped = FaultInjector(FaultConfig(enabled=True, seed=11,
                                         decode_stall_p=1.0))
    assert unscoped.stalls_replica("r0") and unscoped.stalls_replica("r1")


def test_overload_burst_injects_phantoms_on_route(make_fleet):
    manager = make_fleet(roles=("mixed",))
    router = FleetRouter(manager)
    router.set_faults(FaultConfig(enabled=True, seed=3, overload_burst_p=1.0,
                                  overload_burst_requests=4,
                                  overload_burst_hold_s=0.05))
    final = router.route({"prompt": _prompt(), "max_new_tokens": 2}).result()
    assert final["state"] == "DONE"  # phantoms pressure, never block real work
    doc = router._gq.describe()
    assert doc["phantoms_injected"] == 4


# ---------------------------------------------------------------------------
# slow-replica demotion + hedge budget
# ---------------------------------------------------------------------------
def test_slow_replica_demoted_to_last_resort(make_fleet):
    manager = make_fleet(roles=())
    for rid in ("a0", "b1", "b2"):  # the slow one sorts FIRST by id: only
        manager.add_local(role="mixed", replica_id=rid)  # demotion avoids it
    router = FleetRouter(manager)
    reps = {r.id: r for r in manager.replicas()}
    for rid, ttft in (("a0", 0.5), ("b1", 0.01), ("b2", 0.012)):
        for _ in range(10):
            reps[rid].record_ttft(ttft)
    demoted = router._demoted_ids(list(reps.values()))
    assert demoted == {"a0"}
    # least-loaded tie: without demotion "a0" would win the id tiebreak
    assert router._pick(list(reps.values()), None).id == "b1"
    # a lone informed replica has no peer to be slower than: no demotion
    assert router._demoted_ids([reps["a0"]]) == set()
    # session affinity overrides demotion (sticky sessions stay sticky)
    sticky = router._pick(list(reps.values()), "session-1")
    assert sticky.id in reps


def test_hedge_budget_fixed_then_p95_derived(make_fleet):
    manager = make_fleet(roles=("mixed",))
    fixed = FleetRouter(manager, config=_fleet_config(
        hedge=HedgeConfig(enabled=True, ttft_budget_s=0.33)))
    assert fixed._hedge_budget_s() == 0.33

    derived = FleetRouter(manager, config=_fleet_config(
        hedge=HedgeConfig(enabled=True, min_samples=8, default_budget_s=1.0,
                          budget_factor=2.0, min_budget_s=0.05)))
    assert derived._hedge_budget_s() == 1.0  # cold: the default budget
    for s in [0.1] * 19 + [0.5]:
        derived._ttft_samples.append(s)
    derived._budget_cache = (0.0, None)  # bust the 100ms staleness cache
    # p95 of the samples is ~0.12..0.5 x factor 2; strictly above the floor
    assert derived._hedge_budget_s() == pytest.approx(
        2.0 * float(np.percentile(np.asarray(list(derived._ttft_samples)), 95)))

    # a lightly-loaded fleet's tiny p95 must not arm a hair-trigger: the
    # min_budget_s floor binds
    floored = FleetRouter(manager, config=_fleet_config(
        hedge=HedgeConfig(enabled=True, min_samples=8, budget_factor=2.0)))
    for _ in range(20):
        floored._ttft_samples.append(0.01)
    floored._budget_cache = (0.0, None)
    assert floored._hedge_budget_s() == floored._config.hedge.min_budget_s

    off = FleetRouter(manager, config=_fleet_config())
    assert off._hedge_budget_s() is None  # hedging is opt-in


# ---------------------------------------------------------------------------
# retry-after through the router + fleet overload plumbing
# ---------------------------------------------------------------------------
def test_replica_overload_rejection_propagates_retry_after(make_fleet):
    manager = make_fleet(
        roles=("mixed",),
        config=_fleet_config(overload=OverloadConfig(admission_margin=0.5)))
    replica = manager.replicas()[0]
    # the fleet overload block is authoritative for fleet-built replicas
    assert replica.scheduler._config.overload.admission_margin == 0.5
    # warm the replica's rate estimator to a known slow rate so its
    # admission gate provably rejects
    for i in range(6):
        replica.scheduler._rate.observe(10, now=float(i))
    router = FleetRouter(manager)
    with pytest.raises(RoutingError) as exc:
        router.route({"prompt": _prompt(), "max_new_tokens": 400,
                      "deadline_s": 0.05}).result()
    assert exc.value.status == 429
    assert exc.value.retry_after_s is not None and exc.value.retry_after_s > 0


def test_router_rejects_unknown_priority_class(make_fleet):
    manager = make_fleet(roles=("mixed",))
    router = FleetRouter(manager)
    with pytest.raises(ValueError, match="unknown priority"):
        router.route({"prompt": _prompt(), "max_new_tokens": 2,
                      "priority": "gold"})


# ---------------------------------------------------------------------------
# hedged dispatch: first-writer-wins, token-identical, KV freed (flagship c)
# ---------------------------------------------------------------------------
def _stall_config(replica_id, stall_s=2.0, min_first=1.0):
    """A decode_stall FaultConfig whose FIRST stall on ``replica_id`` is
    provably >= ``min_first`` seconds — chosen by walking seeds through the
    pure schedule (fault shape is a hash of the seed, so this is
    deterministic, not luck)."""
    for seed in range(200):
        cfg = FaultConfig(enabled=True, seed=seed, decode_stall_p=1.0,
                          decode_stall_s=stall_s,
                          decode_stall_replica=replica_id)
        if FaultInjector(cfg).stall_s(0, replica_id) >= min_first:
            return cfg
    raise AssertionError("no seed with a big first stall in 200 tries")


def _quiesce(manager, num_blocks=64, timeout_s=60.0):
    """Wait until every replica engine is empty again; the KV-balance sweep
    (hedge losers included — their cancel frees on the owner's next tick)."""
    deadline = time.monotonic() + timeout_s
    for replica in manager.replicas():
        while time.monotonic() < deadline:
            sched = replica.scheduler
            if (sched.n_active == 0 and sched.queue_depth == 0
                    and replica.engine._state_manager.n_tracked_sequences == 0
                    and replica.engine.free_blocks == num_blocks):
                break
            time.sleep(0.02)
        assert replica.engine.free_blocks == num_blocks, \
            f"{replica.id} leaked {num_blocks - replica.engine.free_blocks} blocks"
        assert replica.engine._state_manager.n_tracked_sequences == 0, replica.id


def test_hedge_first_writer_wins_token_identical_loser_kv_freed(make_fleet):
    """The flagship hedge contract: a stalled primary is hedged after the
    TTFT budget, the hedge leg wins, the stream is token-identical to the
    unhedged stream, and the loser's KV is verifiably freed (exact pool
    balance on BOTH replicas)."""
    manager = make_fleet(roles=(), config=_fleet_config(
        hedge=HedgeConfig(enabled=True, ttft_budget_s=0.15)))
    manager.add_local(role="mixed", replica_id="r0")  # least-loaded first pick
    manager.add_local(role="mixed", replica_id="r1")
    prompt = _prompt(11)

    # warm both engines (compile) and capture the unhedged ground truth
    truth = None
    for replica in manager.replicas():
        req = replica.scheduler.submit(prompt, max_new_tokens=4)
        tokens = req.result(timeout=300)
        truth = tokens if truth is None else truth
        assert tokens == truth  # same params: replicas agree
    _quiesce(manager)

    router = FleetRouter(manager)
    router.set_faults(_stall_config("r0"))
    routed = router.route({"prompt": prompt, "max_new_tokens": 4,
                           "temperature": 0.0, "seed": 0})
    streamed = list(routed.tokens())
    final = dict(routed.result())
    assert streamed == truth and final["tokens"] == truth  # token-identical
    assert final["state"] == "DONE"
    assert routed._hedged
    assert router._counters["hedged"] == 1
    assert router._counters["hedge_wins"] == 1  # the fast replica won
    assert final["legs"][-1]["kind"] == "hedge"
    router.set_faults(None)
    _quiesce(manager)  # the loser's cancel freed its KV: exact pool balance


def test_hedge_ineligible_paths_never_hedge(make_fleet):
    """Batch-class requests (interactive_only) and fleets with hedging
    disabled dispatch exactly one leg even when slow."""
    manager = make_fleet(roles=(), config=_fleet_config(
        hedge=HedgeConfig(enabled=True, ttft_budget_s=0.05,
                          interactive_only=True)))
    manager.add_local(role="mixed", replica_id="r0")
    manager.add_local(role="mixed", replica_id="r1")
    router = FleetRouter(manager)
    final = router.route({"prompt": _prompt(), "max_new_tokens": 2,
                          "priority": "batch"}).result()
    assert final["state"] == "DONE"
    assert router._counters["hedged"] == 0


# ---------------------------------------------------------------------------
# seeded overload soak (slow): leaks, shed-consumed-nothing, hedging wins
# ---------------------------------------------------------------------------
def _run_workload(manager, router, n_requests, seed, deadline_s,
                  concurrency=6, max_new_tokens=3):
    """Concurrent seeded workload; returns per-request outcome dicts."""
    rng = np.random.default_rng(seed)
    plans = [{"prompt": rng.integers(0, 64, int(rng.integers(4, 16))).tolist(),
              "priority": "interactive" if i % 2 == 0 else "batch",
              "seed": i}
             for i, _ in enumerate(range(n_requests))]
    outcomes = []
    lock = threading.Lock()

    def one(plan):
        doc = {"prompt": plan["prompt"], "max_new_tokens": max_new_tokens,
               "temperature": 0.0, "seed": plan["seed"],
               "priority": plan["priority"], "deadline_s": deadline_s}
        t0 = time.monotonic()
        out = {"priority": plan["priority"], "ttft_s": None, "tokens": 0}
        try:
            routed = router.route(doc)
            for i, _tok in enumerate(routed.tokens()):
                if i == 0:
                    out["ttft_s"] = time.monotonic() - t0
                out["tokens"] += 1
            final = dict(routed.result())
            out["state"] = final["state"]
            out["retry_after_s"] = final.get("retry_after_s")
        except RoutingError as e:
            out["state"] = f"rejected:{e.status}"
            out["retry_after_s"] = e.retry_after_s
        except Exception as e:  # pragma: no cover - a soak must stay terminal
            out["state"] = f"error:{type(e).__name__}"
            out["retry_after_s"] = None
        out["e2e_s"] = time.monotonic() - t0
        with lock:
            outcomes.append(out)

    threads = [threading.Thread(target=one, args=(p,), daemon=True)
               for p in plans]
    for batch in range(0, n_requests, concurrency):
        group = threads[batch:batch + concurrency]
        for t in group:
            t.start()
        for t in group:
            t.join(timeout=300)
            assert not t.is_alive(), "overload request wedged — not terminal"
    return outcomes


def _interactive_p99_ttft(outcomes):
    vals = [o["ttft_s"] for o in outcomes
            if o["priority"] == "interactive" and o["ttft_s"] is not None]
    assert vals, "no interactive request produced a first token"
    return float(np.percentile(np.asarray(vals), 99))


@pytest.mark.slow
def test_seeded_overload_soak_no_leaks_shed_cheap_hedging_beats_tail(make_fleet):
    """The overload soak (ISSUE satellite): under a seeded decode_stall on
    one replica, (i) nothing leaks KV or sequences — including every
    hedge-loser cancellation, (ii) every shed / deadline-failed request
    consumed zero decode steps, (iii) interactive p99 TTFT is lower with
    hedging ON than OFF at the identical seed."""
    stall = _stall_config("r0", stall_s=1.5, min_first=0.0)
    n_requests, seed, deadline_s = 36, 1234, 30.0
    results = {}
    for hedge_on in (True, False):
        # pinned engine geometry + full bucket warmup (see GATE_ENGINE_KW):
        # the p99-TTFT comparison below is exactly what a cold XLA compile
        # mid-run pollutes, and compiles are per-engine so BOTH arms must
        # warm their own
        manager = make_fleet(roles=(), config=_fleet_config(
            hedge=HedgeConfig(enabled=hedge_on, ttft_budget_s=0.2)),
            **GATE_ENGINE_KW)
        for rid in ("r0", "r1", "r2"):
            manager.add_local(role="mixed", replica_id=rid)
        _warm_fleet(manager)
        router = FleetRouter(manager)
        router.set_faults(FaultConfig(**stall.model_dump()))
        outcomes = _run_workload(manager, router, n_requests, seed, deadline_s)
        router.set_faults(None)

        assert len(outcomes) == n_requests  # every request terminal
        done = [o for o in outcomes if o["state"] == "DONE"]
        assert len(done) >= n_requests // 2, f"overload drowned: {len(done)}"
        # (ii) anything shed or deadline-failed consumed ZERO decode steps
        for o in outcomes:
            if o["state"] != "DONE":
                assert o["tokens"] == 0, \
                    f"shed/failed request streamed {o['tokens']} tokens: {o}"
        # (i) zero KV / sequence leak, hedge losers included
        _quiesce(manager)
        results[hedge_on] = outcomes

    # (iii) hedging beats the stalled replica's tail at the identical seed
    hedged_p99 = _interactive_p99_ttft(results[True])
    unhedged_p99 = _interactive_p99_ttft(results[False])
    assert hedged_p99 < unhedged_p99, \
        f"hedging did not cut p99 TTFT: on={hedged_p99:.3f}s off={unhedged_p99:.3f}s"

    # identical seed => identical stall schedule (the property the run rode on)
    fresh = FaultInjector(FaultConfig(**stall.model_dump()))
    again = FaultInjector(FaultConfig(**stall.model_dump()))
    assert fresh.schedule("decode_stall", 300, "r0") == \
        again.schedule("decode_stall", 300, "r0")


# ---------------------------------------------------------------------------
# flagship CPU gate (slow): goodput under 3x overload + interactive contract
# ---------------------------------------------------------------------------
def _arm_config(overload_on):
    # margin 0.5: admit only when the estimate fits HALF the deadline — the
    # rate estimator is measured under lighter load than the burst, so the
    # headroom is what keeps admitted work finishing inside its deadline.
    # The hedge budget is p95-DERIVED (not fixed): under uniform load the
    # budget tracks the fleet's own tail so hedges stay rare, and only the
    # stalled replica's legs blow past it — a fixed budget below the loaded
    # TTFT would hedge everything and burn half the capacity.
    overload = OverloadConfig(enabled=overload_on, admission_margin=0.5)
    return _fleet_config(
        overload=overload,
        # probe_ttl 0.25: every queue pump health-checks its candidates —
        # fresh probes at pump frequency contend on the scheduler locks the
        # engines need (1-CPU tier-1 reality; production default is also
        # TTL'd)
        probe_ttl_s=0.25,
        # max_inflight 6: the burst must PARK at the router (priority/
        # deadline grant order, cheap shed on queue-wait expiry) instead of
        # fanning out into deep replica queues that drain blindly
        global_queue=GlobalQueueConfig(enabled=overload_on,
                                       max_inflight_per_replica=6),
        # interactive_only=False: the stalled replica cannot tell classes
        # apart — a batch leg crawling on it stretches the measurement wall
        # for everyone, so the overload arm hedges every class (the
        # interactive preference still holds at queue order and brownout).
        # min_samples 3: demotion evidence must form off the handful of
        # legs the stalled replica is granted before it is sidelined.
        # max_hedge_frac 0.5: on this host EVERY replica's latency smears
        # under burst contention, so demotion evidence (slow vs the peer
        # median) forms late — the gate leans on the speculative bucket to
        # rescue the stalled replica's early victims instead; hedge legs
        # are 4-token replays, so even the worst case is cheap
        hedge=HedgeConfig(enabled=overload_on, ttft_budget_s=None,
                          min_samples=3, default_budget_s=2.0,
                          budget_factor=1.5, max_hedge_frac=0.5,
                          interactive_only=False))


GATE_ENGINE_KW = dict(max_tracked_sequences=8, max_ragged_batch_size=16)
"""Engine geometry for every gate fleet (capacity AND both arms): at most 8
tracked sequences (the S bucket never leaves 8) and a 16-token ragged budget
(the T bucket never leaves {8, 16}). The ragged engine compiles one XLA
program per padded (T, S, MB) bucket PER ENGINE and compiles serialize
process-wide — on the 1-CPU tier-1 host a single cold bucket hit
mid-measurement stalls every engine for over a second and reads as fake
overload, so the gate bounds the bucket space and warms all of it."""


def _warm_fleet(manager, concurrency=8):
    """Compile every batch bucket the gate's burst can touch, per replica
    (see :func:`_gate_serving_config`): a simultaneous 8-deep burst (S=8
    decode bucket, lone-prefill T=8) and a staggered round (prefill packed
    with in-flight decode rows: T=16). Compiles land here, outside every
    measured window."""
    for replica in manager.replicas():
        for stagger_s in (0.0, 0.012):
            threads = [threading.Thread(
                target=lambda s=s: replica.scheduler.submit(
                    _prompt(8), max_new_tokens=4, temperature=0.0,
                    seed=s).result(timeout=300),
                daemon=True) for s in range(concurrency)]
            for t in threads:
                t.start()
                if stagger_s:
                    time.sleep(stagger_s)
            for t in threads:
                t.join(timeout=300)
                assert not t.is_alive(), "warmup request wedged"
    _quiesce(manager)


def _open_loop(router, n, rate, deadline_s, seed):
    """Open-loop Poisson arrivals at ``rate`` req/s; returns outcomes."""
    rng = np.random.default_rng(seed)
    arrivals = np.cumsum(rng.exponential(1.0 / rate, n))
    outcomes = []
    lock = threading.Lock()
    t0 = time.monotonic()

    def one(i, at):
        delay = at - (time.monotonic() - t0)
        if delay > 0:
            time.sleep(delay)
        doc = {"prompt": _prompt(8), "max_new_tokens": 4, "temperature": 0.0,
               "seed": i, "deadline_s": deadline_s,
               "priority": "interactive" if i % 2 == 0 else "batch"}
        s0 = time.monotonic()
        out = {"priority": doc["priority"], "tokens": 0}
        try:
            routed = router.route(doc)
            for _tok in routed.tokens():
                out["tokens"] += 1
            final = dict(routed.result())
            out["state"] = final["state"]
            out["retry_after_s"] = final.get("retry_after_s")
        except RoutingError as e:
            out["state"] = f"rejected:{e.status}"
            out["retry_after_s"] = e.retry_after_s
        out["e2e_s"] = time.monotonic() - s0
        with lock:
            outcomes.append(out)

    threads = [threading.Thread(target=one, args=(i, at), daemon=True)
               for i, at in enumerate(arrivals)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=600)
        assert not t.is_alive(), "gate request wedged"
    return outcomes, time.monotonic() - t0


@pytest.mark.slow
def test_flagship_overload_gate_goodput_and_interactive_contract(make_fleet):
    """The acceptance gate: under a seeded 3x-capacity overload with one
    decode_stall replica, (a) goodput (on-deadline completions/s over the
    workload horizon) stays >= 85% of measured single-replica capacity —
    the SAME workload's goodput through one fault-free replica — while the
    uniform-FIFO control arm drops below it, and (b) every interactive
    request either completes on-deadline or is rejected at admission with
    Retry-After — none fails mid-decode.

    The closed-loop measure sets the offered rate (3x) and the deadline;
    the goodput floor is measured in open-loop units so both sides of the
    comparison share arrival schedule, deadline and horizon. The stalled
    replica's engine drains instantly (the injected stall delays the token
    RELAY, not the engine), so blind least-loaded push sees it as the
    perpetually-emptiest replica and keeps feeding it — the overload arm
    must instead route around it (demotion + queue grants) and rescue the
    already-granted victims (hedges)."""
    # ---- measured single-replica capacity (closed loop, warm) ----
    cap_mgr = make_fleet(roles=("mixed",), **GATE_ENGINE_KW)
    _warm_fleet(cap_mgr)
    cap_router = FleetRouter(cap_mgr)
    warm = cap_router.route({"prompt": _prompt(8), "max_new_tokens": 4}).result()
    assert warm["state"] == "DONE"
    e2es = []

    def closed(i):
        s0 = time.monotonic()
        final = cap_router.route({"prompt": _prompt(8), "max_new_tokens": 4,
                                  "temperature": 0.0, "seed": i}).result()
        assert final["state"] == "DONE"
        e2es.append(time.monotonic() - s0)

    # two passes: the first is the last warm stage (any program only this
    # exact closed-loop mix triggers compiles there), the second measures
    for measured in (False, True):
        e2es.clear()
        t0 = time.monotonic()
        workers = [threading.Thread(target=lambda w=w: [closed(w * 8 + j)
                                                        for j in range(8)],
                                    daemon=True) for w in range(2)]
        for t in workers:
            t.start()
        for t in workers:
            t.join(timeout=600)
        wall = time.monotonic() - t0
    capacity = 16 / wall
    p50_e2e = float(np.percentile(np.asarray(e2es), 50))
    deadline_s = max(2.0, 8 * p50_e2e)
    offered = 3.0 * capacity

    # ---- single-replica capacity in GOODPUT units: the identical open-loop
    # workload (same seed => same arrival schedule, same deadline) through
    # the one fault-free replica. Goodput is on-deadline completions over
    # the fixed workload horizon (arrival span + deadline) — the same
    # denominator for the baseline and both arms, so the comparison is
    # robust to wall-clock tail noise on the shared-CPU tier-1 host and
    # reduces to on-deadline completion COUNTS under identical load.
    horizon_s = 48 / offered + deadline_s
    base_outcomes, _ = _open_loop(cap_router, n=48, rate=offered,
                                  deadline_s=deadline_s, seed=77)
    capacity_goodput = sum(
        1 for o in base_outcomes
        if o["state"] == "DONE" and o["e2e_s"] <= deadline_s) / horizon_s
    assert capacity_goodput > 0, "single replica completed nothing on-deadline"

    # ---- the two arms under the identical seeded 3x overload ----
    # stall 2.0s/token: a leg that stays on r0 provably blows the deadline
    # (4 tokens x ~1s expected stall vs a ~2s deadline), so the FIFO
    # control arm — which keeps pushing to the always-empty-looking r0 —
    # loses every request it lands there, while the overload arm's
    # demotion + hedging must route around it or rescue
    stall = _stall_config("r0", stall_s=2.0, min_first=0.0)
    goodput = {}
    arms = {}
    for overload_on in (True, False):
        manager = make_fleet(roles=(), config=_arm_config(overload_on),
                             **GATE_ENGINE_KW)
        for rid in ("r0", "r1", "r2"):
            manager.add_local(role="mixed", replica_id=rid)
        _warm_fleet(manager)
        router = FleetRouter(manager)
        # final warm stage: the EXACT measured workload, fault-free — any
        # program only this arrival/admission mix triggers compiles here,
        # outside the measured window (and the rate estimators, TTFT sample
        # window and admission clocks start the measured run warm)
        _open_loop(router, n=24, rate=offered, deadline_s=30.0, seed=7)
        _quiesce(manager)
        router.set_faults(FaultConfig(**stall.model_dump()))
        outcomes, arm_wall = _open_loop(router, n=48, rate=offered,
                                        deadline_s=deadline_s, seed=77)
        router.set_faults(None)
        on_deadline = [o for o in outcomes
                       if o["state"] == "DONE" and o["e2e_s"] <= deadline_s]
        goodput[overload_on] = len(on_deadline) / horizon_s
        arms[overload_on] = outcomes
        _quiesce(manager)

    floor = 0.85 * capacity_goodput
    assert goodput[True] >= floor, \
        (f"overload arm goodput {goodput[True]:.2f} req/s < 85% of "
         f"single-replica capacity {capacity_goodput:.2f} req/s "
         f"(horizon {horizon_s:.2f}s)")
    assert goodput[False] < floor, \
        (f"uniform-FIFO control held {goodput[False]:.2f} req/s >= "
         f"{floor:.2f}: the stalled replica did not hurt blind push")
    assert goodput[True] > goodput[False]

    # (b) the interactive contract, overload arm: on-deadline completion OR
    # an admission rejection carrying Retry-After — never a mid-decode death
    for o in arms[True]:
        if o["priority"] != "interactive":
            continue
        if o["state"] == "DONE":
            assert o["e2e_s"] <= deadline_s, f"late completion: {o}"
        else:
            assert o["tokens"] == 0, f"mid-decode failure: {o}"
            assert o["retry_after_s"] is not None, \
                f"rejection without Retry-After: {o}"
