"""NVMe optimizer-state swapping (ZeRO-Infinity's disk tier).

Reference: ``deepspeed/runtime/swap_tensor/partitioned_optimizer_swapper.py:29``
(PartitionedOptimizerSwapper over an aio handle + swap buffers) and
``optimizer_utils.py`` (OptimizerSwapper bookkeeping). The reference swaps each
rank's flat fp32 partitions between GPU and NVMe around the CPU-Adam step.

TPU formulation: optimizer state is a pytree of ZeRO-sharded jax.Arrays. At
rest, every leaf's *addressable shards* live in a per-process file under
``nvme_path`` (each process writes only its partitions — the reference's
per-rank swap files); between steps the engine holds only
:class:`NvmeSwappedLeaf` stubs (shape/dtype/shard table — no HBM, no host
RAM). ``swap_in`` streams shards disk→host→device with a bounded number of
in-flight host buffers (``buffer_count``, the reference's swap-buffer pool) on
the native aio thread pool; ``swap_out`` streams device→host→disk the same
way. Every transfer's byte count is validated, and writes are fsync'd by the
native engine, so a checkpoint taken from stubs is readable immediately.
"""

import os
from dataclasses import dataclass
from typing import Any, List, Optional, Tuple

import numpy as np

from deepspeed_tpu.utils.logging import logger


@dataclass(frozen=True)
class _ShardEntry:
    index: Tuple  # tuple of slices into the global array
    offset: int   # byte offset inside the leaf's per-process file
    shape: Tuple[int, ...]


@dataclass(frozen=True)
class NvmeSwappedLeaf:
    """Stub standing in for a swapped-out optimizer-state leaf."""
    path: str
    shape: Tuple[int, ...]  # global shape
    dtype: Any              # numpy dtype
    shards: Tuple[_ShardEntry, ...]

    def _submit_reads(self, aio):
        """Start all shard preads; returns the pending list for
        :meth:`_complete_reads` — split so the swapper can overlap reads of
        MANY leaves (the pipelined swap-in)."""
        pending = []
        for sh in self.shards:
            buf = np.empty(sh.shape, self.dtype)
            rid = aio.async_pread(buf, self.path, offset=sh.offset)
            pending.append((rid, sh, buf))
        return pending

    def _complete_reads(self, aio, pending) -> np.ndarray:
        """Wait the preads and assemble the global-shaped host buffer (regions
        owned by other processes stay zero — never consumed there)."""
        out = np.zeros(self.shape, self.dtype)
        for rid, sh, buf in pending:
            got = aio.wait(rid)
            if got != buf.nbytes:
                raise IOError(f"short read from {self.path}: shard at offset {sh.offset} "
                              f"returned {got} of {buf.nbytes} bytes (stale or foreign "
                              f"swap file?)")
            idx = sh.index if out.ndim else ()
            out[idx] = np.reshape(buf, np.shape(out[idx]))
        return out

    def _read_local(self, aio) -> np.ndarray:
        return self._complete_reads(aio, self._submit_reads(aio))


def _is_stub(x) -> bool:
    return isinstance(x, NvmeSwappedLeaf)


def _addressable_shards(leaf):
    """[(index, np.ndarray)] of this process's pieces; plain arrays are one
    whole-array shard."""
    shards = getattr(leaf, "addressable_shards", None)
    if shards is None:
        data = np.ascontiguousarray(np.asarray(leaf))
        return [(tuple(slice(None) for _ in data.shape), data)]
    out = []
    seen = set()  # replicated-over-some-axes leaves repeat indices: write once
    for s in sorted(shards, key=lambda s: s.device.id):
        key = tuple((sl.start, sl.stop, sl.step) if isinstance(sl, slice) else sl
                    for sl in s.index)
        if key in seen:
            continue
        seen.add(key)
        out.append((s.index, np.ascontiguousarray(np.asarray(s.data))))
    return out


class PartitionedOptimizerSwapper:
    """Streams an optimizer-state pytree between device HBM and NVMe files."""

    def __init__(self, nvme_path: str, aio_config=None, buffer_count: int = 4):
        from deepspeed_tpu.ops.aio import AsyncIOHandle
        os.makedirs(nvme_path, exist_ok=True)
        self.swap_dir = nvme_path
        block_size = getattr(aio_config, "block_size", 1 << 20)
        queue_depth = getattr(aio_config, "queue_depth", 8)
        threads = getattr(aio_config, "thread_count", 2)
        self.buffer_count = max(1, buffer_count)
        self.aio = AsyncIOHandle(block_size=block_size, queue_depth=queue_depth,
                                 thread_count=threads)
        self._pending_writes = []  # (request_id, buffer) of the last swap_out

    # ----------------------------------------------------------------- helpers --
    def _leaf_path(self, index: int) -> str:
        import jax
        return os.path.join(self.swap_dir, f"state_{index}_proc{jax.process_index()}.bin")

    # ---------------------------------------------------------------- swap out --
    def swap_out(self, opt_state, shardings=None) -> Any:
        """Device → disk. Returns the stub tree the engine holds between steps.

        Each process writes only its *addressable shards* (multi-host safe —
        VERDICT-class fix for the full-gather device_get), packed back-to-back
        in its per-leaf file. Writes overlap on the aio pool; leaves that are
        already stubs (idempotent re-swap) pass through.
        """
        import jax
        # earlier writes to the SAME paths must finish first (e.g. init
        # stage_out immediately followed by a restore's swap_out)
        self._drain_writes()
        leaves, treedef = jax.tree.flatten(opt_state)
        stubs = []
        for i, leaf in enumerate(leaves):
            if _is_stub(leaf):
                stubs.append(leaf)
                continue
            path = self._leaf_path(i)
            offset = 0
            entries = []
            global_shape = tuple(getattr(leaf, "shape", np.asarray(leaf).shape))
            dtype = None
            for index, data in _addressable_shards(leaf):
                rid = self.aio.async_pwrite(data, path, offset=offset)
                self._pending_writes.append((rid, data))
                entries.append(_ShardEntry(index=index, offset=offset,
                                           shape=tuple(data.shape)))
                offset += data.nbytes
                dtype = data.dtype
                if len(self._pending_writes) >= self.buffer_count:
                    self._drain_writes()
            stubs.append(NvmeSwappedLeaf(path=path, shape=global_shape, dtype=dtype,
                                         shards=tuple(entries)))
        return jax.tree.unflatten(treedef, stubs)

    def _drain_writes(self):
        for rid, buf in self._pending_writes:
            got = self.aio.wait(rid)
            if got != buf.nbytes:
                raise IOError(f"short write: {got} of {buf.nbytes} bytes reached disk")
        self._pending_writes.clear()

    # ----------------------------------------------------------------- swap in --
    def swap_in(self, stub_tree, shardings) -> Any:
        """Disk → device, placed per ``shardings``. Each process reads back its
        own shard regions and ``device_put`` materializes only the addressable
        pieces, so the path is identical single- and multi-host. Bounded
        in-flight leaves (``buffer_count`` — the reference's pipelined swap-in,
        partitioned_optimizer_swapper.py:239)."""
        import jax
        self._drain_writes()  # read-after-write ordering
        leaves, treedef = jax.tree.flatten(stub_tree)
        shard_leaves = jax.tree.leaves(shardings) if shardings is not None else [None] * len(leaves)
        if len(shard_leaves) != len(leaves):
            shard_leaves = [None] * len(leaves)

        inflight = []  # (position, stub, pending preads) — reads of up to
        out = [None] * len(leaves)  # buffer_count LEAVES overlap on the pool

        def complete_one():
            i, stub, pending = inflight.pop(0)
            host = stub._complete_reads(self.aio, pending)
            s = shard_leaves[i]
            out[i] = jax.device_put(host, s) if s is not None else jax.numpy.asarray(host)

        for i, leaf in enumerate(leaves):
            if not _is_stub(leaf):
                out[i] = leaf
                continue
            inflight.append((i, leaf, leaf._submit_reads(self.aio)))
            if len(inflight) >= self.buffer_count:
                complete_one()
        while inflight:
            complete_one()
        return jax.tree.unflatten(treedef, out)

    # ------------------------------------------------------------- checkpoints --
    def materialize_host(self, stub_tree) -> Any:
        """Disk → host numpy (no device involvement) — the single-process
        checkpoint save path. Multi-process checkpointing goes through
        ``swap_in`` (sharded jax.Arrays) instead; see NvmeOffloadPlan."""
        import jax
        self._drain_writes()
        leaves, treedef = jax.tree.flatten(stub_tree)
        out = [leaf._read_local(self.aio) if _is_stub(leaf) else leaf for leaf in leaves]
        return jax.tree.unflatten(treedef, out)

    def close(self):
        self._drain_writes()
        self.aio.close()
