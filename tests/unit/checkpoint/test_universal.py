"""Universal-checkpoint depth (VERDICT r3 #7).

Reference: ``deepspeed/checkpoint/ds_to_universal.py:286`` (extract → merge →
reshape into any topology), MoE expert-sharded save (``engine.py:3153``),
``deepspeed/utils/zero_to_fp32.py`` offline consolidation, and tag validation
(``engine.py:3035``). The TPU checkpoint is one sharded array store, so the
universal reshape is "restore under the new mesh" — these tests prove it for
the hard case: an EP-sharded MoE saved under one topology and restored under
a completely different one."""

import subprocess
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.models.mixtral import MixtralConfig, MixtralForCausalLM, init_params, \
    mixtral_param_specs
from deepspeed_tpu.utils import groups


def _cfg(stage=2):
    return {
        "train_micro_batch_size_per_gpu": 2,
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": stage},
    }


def _batch(cfg, rng, bs=8, seq=16):
    ids = rng.integers(0, cfg.vocab_size, size=(bs, seq)).astype(np.int32)
    return (ids, ids.copy())


def _make_engine(mcfg, params):
    model = MixtralForCausalLM(mcfg)
    eng, _, _, _ = deepspeed_tpu.initialize(model=model, model_parameters=params,
                                            config=_cfg(),
                                            param_specs=mixtral_param_specs(params))
    return eng


def test_moe_cross_mesh_reshard(tmp_path):
    """Save a ZeRO-2 Mixtral on a (data=4, expert=2) mesh; restore on
    (data=2, seq=2, model=2). Expert banks move from EP shards to TP-sharded
    replicas; every leaf must survive bit-for-bit and training must continue."""
    mcfg = MixtralConfig.tiny(dtype=jnp.float32)
    _, params0 = init_params(mcfg)
    rng = np.random.default_rng(0)

    groups.initialize_mesh(expert_parallel_size=2, force=True)  # data=4, expert=2
    eng = _make_engine(mcfg, params0)
    for _ in range(3):
        eng.train_batch(batch=_batch(mcfg, rng))
    eng.save_checkpoint(str(tmp_path), tag="cross")
    want_params = jax.device_get(eng.params)
    want_opt = jax.device_get(eng.opt_state)
    steps = eng.global_steps

    groups.initialize_mesh(sequence_parallel_size=2, model_parallel_size=2, force=True)
    eng2 = _make_engine(mcfg, params0)
    eng2.load_checkpoint(str(tmp_path), tag="cross")
    assert eng2.global_steps == steps
    for a, b in zip(jax.tree.leaves(jax.device_get(eng2.params)), jax.tree.leaves(want_params)):
        np.testing.assert_array_equal(a, b)
    for a, b in zip(jax.tree.leaves(jax.device_get(eng2.opt_state)), jax.tree.leaves(want_opt)):
        np.testing.assert_array_equal(a, b)

    # the restored engine trains under the NEW topology
    l0 = float(eng2.train_batch(batch=_batch(mcfg, rng)))
    assert np.isfinite(l0)


def test_zero_to_fp32_cli(tmp_path):
    """Offline consolidation CLI: checkpoint dir → flat fp32 npz, no engine."""
    from ..simple_model import make_simple_model, random_batches

    groups.initialize_mesh(force=True)
    model, params0 = make_simple_model(hidden_dim=16, batch_size=16)
    eng, _, _, _ = deepspeed_tpu.initialize(model=model, model_parameters=params0,
                                            config=_cfg(stage=3))
    for b in random_batches(2, 16, 16):
        eng.train_batch(batch=b)
    eng.save_checkpoint(str(tmp_path))  # default tag + latest file

    import os
    out = tmp_path / "consolidated.npz"
    env = {**os.environ, "JAX_PLATFORMS": "cpu", "PYTHONPATH": "/root/repo"}
    rc = subprocess.call([sys.executable, "-m", "deepspeed_tpu.utils.zero_to_fp32",
                          str(tmp_path), str(out)], env=env)
    assert rc == 0
    sd = np.load(str(out))
    want = jax.device_get(eng.params)
    import jax.tree_util as jtu
    flat = {".".join(str(getattr(k, "key", k)) for k in path): v
            for path, v in jtu.tree_flatten_with_path(want)[0]}
    assert set(sd.files) == set(flat)
    for name in sd.files:
        assert sd[name].dtype == np.float32
        np.testing.assert_array_equal(sd[name], np.asarray(flat[name], np.float32))


def test_tag_validation():
    """Consistent tags pass; the check runs a real min/max all-reduce."""
    from ..simple_model import make_simple_model

    groups.initialize_mesh(force=True)
    model, params0 = make_simple_model(hidden_dim=16, batch_size=16)
    eng, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=params0,
        config={**_cfg(), "checkpoint": {"tag_validation": "Fail"}})
    eng._checkpoint_tag_validation("tag1")  # must not raise

    # simulate cross-host disagreement: rank 0 "broadcasts" a different hash
    # (instance-level patch so the class staticmethod is untouched)
    eng._broadcast_rank0_value = lambda v: int(v) + 1
    try:
        with pytest.raises(RuntimeError, match="not consistent"):
            eng._checkpoint_tag_validation("tag2")
    finally:
        del eng._broadcast_rank0_value
