"""1-bit Adam.

Reference: ``deepspeed/runtime/fp16/onebit/adam.py`` (OnebitAdam, 306 LoC) —
exact Adam during the warmup ("freeze") phase; afterwards the variance is
frozen and only the momentum moves over the wire, sign-compressed with
error-feedback (``runtime/comm/nccl.py:51`` compressed_allreduce).

TPU formulation: the optimizer is a pure functional update whose post-freeze
momentum passes through the same sign-compress + error-feedback math
(``runtime/comm/compressed.py``); when gradients/momenta are sharded over the
data axis, the exchange the compression feeds is the 1-byte/element
all-to-all+allgather instead of a 4-byte allreduce — the reference's 32x
wire-volume claim. Numerics (compression error carried in persistent state)
are identical either way and are what the tests pin.
"""

from typing import NamedTuple

import jax
import jax.numpy as jnp

from deepspeed_tpu.ops.optimizer import TpuOptimizer, _tree_zeros_like


class OnebitAdamState(NamedTuple):
    step: jnp.ndarray
    exp_avg: any
    exp_avg_sq: any
    worker_error: any  # error-feedback state (reference's worker_error)


class OnebitAdam(TpuOptimizer):

    name = "onebitadam"

    def __init__(self, lr=1e-3, betas=(0.9, 0.999), eps=1e-8, weight_decay=0.0,
                 freeze_step=100, cuda_aware=False, comm_backend_name="xla"):
        super().__init__(lr=lr, weight_decay=weight_decay)
        self.betas = betas
        self.eps = eps
        self.freeze_step = int(freeze_step)

    def init(self, params):
        return OnebitAdamState(step=jnp.zeros([], jnp.int32),
                               exp_avg=_tree_zeros_like(params),
                               exp_avg_sq=_tree_zeros_like(params),
                               worker_error=_tree_zeros_like(params))

    def update(self, grads, state, params, lr):
        b1, b2 = self.betas
        step = state.step + 1
        stepf = step.astype(jnp.float32)
        bc1 = 1.0 - b1**stepf
        bc2 = 1.0 - b2**stepf
        frozen = step > self.freeze_step
        wd = self.weight_decay

        def upd(p, g, m, v, err):
            g = g.astype(p.dtype)
            m_new = b1 * m + (1.0 - b1) * g
            # variance is FROZEN after the warmup phase (reference adam.py:
            # exp_avg_sq stops updating at freeze_step)
            v_new = jnp.where(frozen, v, b2 * v + (1.0 - b2) * (g * g))
            # post-freeze: the momentum travels sign-compressed with error
            # feedback; pre-freeze it is exact (and error stays zero)
            compensated = m_new + err
            scale = jnp.mean(jnp.abs(compensated))
            # torch semantics: sign(0) == 0 — zero-momentum elements (whose
            # variance is also ~0) must not receive full-scale updates
            compressed = scale * jnp.sign(compensated).astype(p.dtype)
            m_used = jnp.where(frozen, compressed, m_new)
            err_new = jnp.where(frozen, compensated - compressed, err)

            update = (m_used / bc1) / (jnp.sqrt(v_new / bc2) + self.eps)
            if wd != 0.0:
                update = update + wd * p
            return p - lr * update, m_used, v_new, err_new

        p_flat, treedef = jax.tree.flatten(params)
        g_flat = treedef.flatten_up_to(grads)
        m_flat = treedef.flatten_up_to(state.exp_avg)
        v_flat = treedef.flatten_up_to(state.exp_avg_sq)
        e_flat = treedef.flatten_up_to(state.worker_error)
        out = [upd(p, g, m, v, e) for p, g, m, v, e in
               zip(p_flat, g_flat, m_flat, v_flat, e_flat)]
        return (jax.tree.unflatten(treedef, [o[0] for o in out]),
                OnebitAdamState(step=step,
                                exp_avg=jax.tree.unflatten(treedef, [o[1] for o in out]),
                                exp_avg_sq=jax.tree.unflatten(treedef, [o[2] for o in out]),
                                worker_error=jax.tree.unflatten(treedef, [o[3] for o in out])))
