"""Training-job supervision: restart a crashed training process with bounded
backoff, resume it from the latest good checkpoint, quarantine crash loops.

The training-side sibling of the serving fleet's ``ReplicaSupervisor``
(``fleet/supervisor.py``), sharing the same vocabulary deliberately: the one
``fleet/breaker.backoff_delay`` formula spaces restarts (exponential, capped,
bounded jitter — deterministic in ``seed`` so chaos runs replay the same
schedule), and the same crash-window budget (``max_crashes`` crashes inside
``crash_window_s``) turns a persistent crasher into a QUARANTINE (the
supervisor gives up loudly with the child's exit code) instead of burning the
cluster on respawns forever.

Contract with the child (what ``bin/dstpu_train`` wraps):

- the child is the resume authority: on start it calls
  ``engine.load_checkpoint(ckpt_dir)`` — empty dir = fresh start, newest
  verified-good tag otherwise (torn/corrupt tags are skipped loudly by the
  checkpoint engine), so "restart" IS "resume";
- ``DSTPU_RESTART_COUNT`` is exported (0 on the first life) — the training
  chaos injector keys its one-shot kill/sigterm points on it, and training
  scripts can use it to vary logging;
- ``DSTPU_CKPT_DIR`` is exported when the supervisor was given one;
- exit code 0 = done; exit code 143 (``TrainingPreempted.EXIT_CODE``) = the
  child's preemption handler wrote its final checkpoint — the supervisor
  exits with 143 rather than restarting (``restart_on_preempt`` overrides,
  for environments where capacity returns under the same process);
- any other exit = crash → backoff → restart.

SIGTERM/SIGINT to the supervisor forwards SIGTERM to the child (triggering
its preemption handler), waits ``grace_s`` for the final checkpoint to
commit, then SIGKILLs and exits with the child's code — the supervisor never
restarts after an operator/preemptor stop.
"""

import os
import random
import signal
import subprocess
import threading
import time
from collections import deque
from typing import Dict, List, Optional

from deepspeed_tpu.fleet.breaker import backoff_delay
from deepspeed_tpu.utils.logging import logger

PREEMPT_EXIT_CODE = 143  # TrainingPreempted.EXIT_CODE without importing jax


def _metrics():
    from deepspeed_tpu import telemetry
    if not telemetry.is_active():
        return None
    return telemetry.get_registry().counter(
        "train_restarts_total",
        "Training process restarts by the supervisor after a crash")


class TrainSupervisor:
    """Supervise ONE training command with restart-on-crash + resume."""

    def __init__(self, cmd: List[str], env: Optional[Dict[str, str]] = None,
                 ckpt_dir: Optional[str] = None,
                 max_crashes: int = 3, crash_window_s: float = 300.0,
                 backoff_base_s: float = 0.5, backoff_cap_s: float = 30.0,
                 backoff_multiplier: float = 2.0, jitter_frac: float = 0.1,
                 seed: int = 0, grace_s: float = 30.0,
                 restart_on_preempt: bool = False,
                 preempt_exit_code: int = PREEMPT_EXIT_CODE,
                 monitor_interval_s: float = 0.05):
        self.cmd = list(cmd)
        self.env = dict(env if env is not None else os.environ)
        self.ckpt_dir = ckpt_dir
        self.max_crashes = int(max_crashes)
        self.crash_window_s = float(crash_window_s)
        self.backoff_base_s = float(backoff_base_s)
        self.backoff_cap_s = float(backoff_cap_s)
        self.backoff_multiplier = float(backoff_multiplier)
        self.jitter_frac = float(jitter_frac)
        self.grace_s = float(grace_s)
        self.restart_on_preempt = bool(restart_on_preempt)
        self.preempt_exit_code = int(preempt_exit_code)
        self.monitor_interval_s = float(monitor_interval_s)
        self.restarts = 0
        self.crashes: deque = deque()  # monotonic timestamps, window-pruned
        self.quarantined = False
        self._rng = random.Random(f"{seed}:train_supervisor")
        self._term_evt = threading.Event()
        self._term_sig: Optional[int] = None
        self._proc: Optional[subprocess.Popen] = None

    # ------------------------------------------------------------- signals --
    def request_stop(self, signum: int = signal.SIGTERM) -> None:
        """Operator/preemptor stop (also the signal handler's body): forward
        SIGTERM to the child so its preemption handler runs; ``run`` then
        waits ``grace_s`` and exits without restarting."""
        self._term_sig = signum
        self._term_evt.set()

    def _install_handlers(self) -> None:
        def on_sig(signum, frame):
            self.request_stop(signum)
        try:
            signal.signal(signal.SIGTERM, on_sig)
            signal.signal(signal.SIGINT, on_sig)
        except ValueError:
            # not the main thread (tests drive request_stop directly)
            pass

    # ----------------------------------------------------------------- run --
    def _spawn(self) -> subprocess.Popen:
        env = dict(self.env)
        env["DSTPU_RESTART_COUNT"] = str(self.restarts)
        env["DSTPU_SUPERVISED"] = "1"
        if self.ckpt_dir:
            env.setdefault("DSTPU_CKPT_DIR", self.ckpt_dir)
        return subprocess.Popen(self.cmd, env=env)

    def _wait_child(self, proc: subprocess.Popen) -> int:
        """Poll the child; on a stop request forward SIGTERM, give the
        preemption handler ``grace_s`` to commit its final checkpoint, then
        SIGKILL. Returns the child's exit code."""
        forwarded_at = None
        while True:
            rc = proc.poll()
            if rc is not None:
                return rc
            if self._term_evt.is_set():
                now = time.monotonic()
                if forwarded_at is None:
                    forwarded_at = now
                    logger.warning(f"train supervisor: stop requested "
                                   f"(signal {self._term_sig}); forwarding "
                                   f"SIGTERM, grace {self.grace_s:.0f}s")
                    proc.send_signal(signal.SIGTERM)
                elif now - forwarded_at > self.grace_s:
                    logger.error("train supervisor: grace budget exhausted; "
                                 "killing the child")
                    proc.kill()
                    return proc.wait()
            time.sleep(self.monitor_interval_s)

    @staticmethod
    def _exit_code(rc: int) -> int:
        """Popen reports signal deaths as negative; map to the shell's
        128+signum convention so run()'s return value is a real exit code
        (sys.exit(-9) would otherwise read as status 247)."""
        return 128 - rc if rc < 0 else rc

    def run(self) -> int:
        self._install_handlers()
        while True:
            life = self.restarts
            logger.info(f"train supervisor: launching (life {life}, "
                        f"cmd={self.cmd[0]}...)")
            self._proc = proc = self._spawn()
            rc = self._exit_code(self._wait_child(proc))
            if self._term_evt.is_set():
                logger.warning(f"train supervisor: stopped after operator/"
                               f"preemption signal (child rc={rc})")
                return rc
            if rc == 0:
                logger.info("train supervisor: training finished cleanly")
                return 0
            if rc == self.preempt_exit_code and not self.restart_on_preempt:
                logger.warning(f"train supervisor: child exited preempted "
                               f"(rc={rc}, final checkpoint committed); not "
                               f"restarting (restart_on_preempt=False)")
                return rc
            now = time.monotonic()
            self.crashes.append(now)
            while self.crashes and now - self.crashes[0] > self.crash_window_s:
                self.crashes.popleft()
            if len(self.crashes) >= self.max_crashes:
                # crash loop: quarantine — give up loudly with the child's rc
                self.quarantined = True
                logger.error(f"train supervisor: QUARANTINED after "
                             f"{len(self.crashes)} crashes in "
                             f"{self.crash_window_s:.0f}s (last rc={rc}); "
                             f"not restarting")
                return rc
            self.restarts += 1
            m = _metrics()
            if m is not None:
                m.inc()
            delay = backoff_delay(len(self.crashes) - 1, self.backoff_base_s,
                                  self.backoff_cap_s, self.jitter_frac,
                                  self._rng.random(),
                                  multiplier=self.backoff_multiplier)
            logger.warning(f"train supervisor: child crashed (rc={rc}); "
                           f"restart #{self.restarts} in {delay:.2f}s "
                           f"(resume from latest good checkpoint)")
            # interruptible sleep: a stop request during backoff exits
            if self._term_evt.wait(delay):
                logger.warning("train supervisor: stopped during backoff")
                return rc

    def describe(self) -> dict:
        return {"restarts": self.restarts,
                "crashes_in_window": len(self.crashes),
                "quarantined": self.quarantined,
                "ckpt_dir": self.ckpt_dir}
