"""``ds_report`` analog: environment / compatibility report.

Reference: ``deepspeed/env_report.py:182`` — prints the op-compat matrix,
torch/cuda versions and install paths. The TPU report covers what matters
here: JAX backend + devices, default mesh axes, library versions, and which
native/pallas subsystems are usable on this backend.
"""

import importlib
import sys


def _version(mod):
    try:
        return importlib.import_module(mod).__version__
    except Exception:
        return "not installed"


GREEN_OK = "\033[92m[OKAY]\033[0m"
RED_NO = "\033[93m[NO]\033[0m"


def main(argv=None):
    import deepspeed_tpu
    print("-" * 60)
    print("DeepSpeed-TPU C++/JAX environment report")
    print("-" * 60)
    print(f"deepspeed_tpu version ... {deepspeed_tpu.__version__}")
    print(f"python ................. {sys.version.split()[0]}")
    for mod in ("jax", "jaxlib", "flax", "optax", "orbax.checkpoint", "numpy"):
        print(f"{mod:<22} ... {_version(mod)}")
    print("-" * 60)
    # a dead TPU tunnel HANGS backend init rather than raising — the device
    # facts come from ONE timed subprocess (shared probe; the parent never
    # touches the backend, so the report can't freeze and doesn't pay
    # backend init twice)
    from deepspeed_tpu.utils.jax_platform import probe_backend
    info, why = probe_backend()
    if info is None:
        print(f"backend ................ UNREACHABLE ({why})")
    else:
        mems = info["memory_kinds"]
        print(f"backend ................ {info['backend']}")
        print(f"devices ................ {info['device_count']}: {info['device_kind']}")
        print(f"process count .......... {info['process_count']}")
        print(f"memory kinds ........... {mems}")
        print(f"host offload ........... "
              f"{GREEN_OK if 'pinned_host' in mems else RED_NO}")
    print("-" * 60)
    # native-op compat matrix (reference env_report.py op_report / ds_report)
    from deepspeed_tpu.ops.op_builder import ALL_OPS
    for name, cls in ALL_OPS.items():
        b = cls()
        ok = b.is_compatible()
        print(f"native op {name:<12} ... {GREEN_OK if ok else RED_NO}"
              f"{'' if ok else '  (' + str(b.error_log) + ')'}")
    print("-" * 60)
    from deepspeed_tpu.utils import groups
    print(f"mesh axes .............. {groups.MESH_AXES}")
    if groups.mesh_is_initialized():
        print(f"mesh ................... {dict(groups.get_mesh().shape)}")
    else:
        print("mesh ................... not initialized (created at engine init)")
    print("-" * 60)
    return 0


if __name__ == "__main__":
    sys.exit(main())
