"""MetricsRegistry and SpanRecorder under concurrent writers: no lost
increments, no ring corruption, stable Prometheus rendering (ISSUE
satellite — the registry is shared by the scheduler thread, HTTP handler
threads and the jax.monitoring listener)."""

import threading

from deepspeed_tpu.telemetry import (MetricsRegistry, SpanRecorder,
                                     parse_prometheus_text)

N_THREADS = 8
N_OPS = 500


def _run_threads(target):
    barrier = threading.Barrier(N_THREADS)  # maximize interleaving

    def wrapped(i):
        barrier.wait()
        target(i)

    threads = [threading.Thread(target=wrapped, args=(i, )) for i in range(N_THREADS)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()


def test_registry_concurrent_writers_lose_nothing():
    reg = MetricsRegistry()
    counter = reg.counter("hits_total", "hits")
    gauge = reg.gauge("level", "level")
    hist = reg.histogram("lat_seconds", "lat", buckets=(0.01, 0.1, 1.0))

    def work(i):
        labeled = reg.counter("per_thread_total", labels={"t": str(i)})
        for k in range(N_OPS):
            counter.inc()
            gauge.set(k)
            hist.observe(0.05)
            labeled.inc()
            reg.event("tick", thread=i, k=k)

    _run_threads(work)
    assert counter.value == N_THREADS * N_OPS
    assert hist.count == N_THREADS * N_OPS
    assert hist.bucket_counts[1] == N_THREADS * N_OPS  # all in the 0.1 bucket
    snap = reg.snapshot()
    per_thread = dict((labels["t"], v) for labels, v in snap["per_thread_total"])
    assert per_thread == {str(i): float(N_OPS) for i in range(N_THREADS)}
    # every api call was counted (the zero-cost guarantee's probe must not race)
    assert reg.api_calls == N_THREADS * N_OPS * 5
    assert len(reg.recent_events) == reg.recent_events.maxlen


def test_concurrent_writers_with_concurrent_scrapes():
    reg = MetricsRegistry()
    counter = reg.counter("ops_total", "ops")
    stop = threading.Event()
    renders = []

    def scraper():
        while not stop.is_set():
            renders.append(reg.render_prometheus())

    scrape_thread = threading.Thread(target=scraper)
    scrape_thread.start()
    try:
        _run_threads(lambda i: [counter.inc() for _ in range(N_OPS)])
    finally:
        stop.set()
        scrape_thread.join()
    renders.append(reg.render_prometheus())
    # every intermediate render parses, and values only move forward
    last = -1.0
    for text in renders:
        fams = parse_prometheus_text(text)
        (_, _, value), = fams["ops_total"]["samples"]
        assert value >= last
        last = value
    assert last == N_THREADS * N_OPS


def test_span_ring_concurrent_recording_stays_bounded():
    rec = SpanRecorder(max_spans=256)

    def work(i):
        for k in range(N_OPS):
            rec.record(f"s{i}", cat="stress", ts_us=k, dur_us=1,
                       trace_id=f"trace{i}", parent_id=i)

    _run_threads(work)
    assert len(rec) == 256
    assert rec.dropped == N_THREADS * N_OPS - 256
    trace = rec.chrome_trace()
    xs = [e for e in trace["traceEvents"] if e["ph"] == "X"]
    assert len(xs) == 256
    assert [e["ts"] for e in xs] == sorted(e["ts"] for e in xs)
    # every surviving span kept its trace identity intact
    for e in xs:
        tid_owner = e["name"][1:]
        assert e["args"]["trace_id"] == f"trace{tid_owner}"
        assert e["args"]["parent_id"] == int(tid_owner)
        assert isinstance(e["args"]["span_id"], int)
