"""Ragged inference engine config.

Reference: ``deepspeed/inference/v2/config_v2.py`` (RaggedInferenceEngineConfig:29,
DeepSpeedTPConfig:12, the fork's DeepSpeedEPConfig:18 with ``replica_num``, and the
``simulated_gating``/``trace_enabled`` fork flags).
"""

from typing import Optional

from pydantic import Field

from deepspeed_tpu.inference.v2.ragged.manager_configs import DSStateManagerConfig
from deepspeed_tpu.runtime.config_utils import DeepSpeedConfigModel
from deepspeed_tpu.telemetry.config import TelemetryConfig


class DeepSpeedTPConfig(DeepSpeedConfigModel):
    """Tensor-parallel settings: model params sharded over the ``model`` mesh axis."""

    tp_size: int = 1


class DeepSpeedEPConfig(DeepSpeedConfigModel):
    """Expert-parallel settings (fork addition). Each replica serves
    ``num_experts // replica_num`` experts; the dispatch/return all-to-alls run
    over the ``expert`` mesh axis."""

    enabled: bool = False
    replica_num: int = 1
    capacity_factor: float = 2.0
    """Fixed-capacity slack for the XLA (shape-static) all-to-all; the reference's
    variable-size a2a needs no capacity but pays a host-side size exchange."""


class QuantizationConfig(DeepSpeedConfigModel):
    """ZeRO-Inference weight quantization (reference README.md:17 news item +
    deepspeed/inference/quantization): int8 at-rest weights, dequantized
    inside the jitted forward so the convert fuses into each consumer."""

    enabled: bool = False
    bits: int = 8
    min_size: int = 4096
    """Leaves smaller than this (norms, biases) stay full precision."""


class RaggedInferenceEngineConfig(DeepSpeedConfigModel):
    """Top-level FastGen engine config."""

    tensor_parallel: DeepSpeedTPConfig = Field(default_factory=DeepSpeedTPConfig, alias="tp")
    quantization: QuantizationConfig = Field(default_factory=QuantizationConfig,
                                             alias="weight_quantization")
    expert_parallel: DeepSpeedEPConfig = Field(default_factory=DeepSpeedEPConfig, alias="ep")
    state_manager: DSStateManagerConfig = Field(default_factory=DSStateManagerConfig, alias="manager")

    kv_block_size: int = 64
    # Pallas blocked-attention kernel (reference blocked_flash role):
    # True/False force it; None = auto (TPU decode buckets)
    use_paged_kernel: Optional[bool] = None

    simulated_gating: bool = False
    simulated_gating_temperature: float = 1.0
    trace_enabled: bool = False
    max_trace_batches: int = 1024
    """Tracer ring-buffer capacity (batches); beyond it the oldest unconsumed
    trace is dropped — drain via ``engine.tracer.drain_summaries()``."""

    telemetry: TelemetryConfig = TelemetryConfig()
    """Unified telemetry: batch/token/KV gauges, per-phase spans, and the
    ``/metrics`` + ``/healthz`` endpoint when ``telemetry.http.enabled``."""
