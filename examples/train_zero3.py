"""Quickstart: ZeRO-3 training with bf16 compute and qwZ weight gathers.

Run (virtual 8-device CPU mesh):
    XLA_FLAGS=--xla_force_host_platform_device_count=8 python examples/train_zero3.py
On a TPU host, drop the flag — the real chips form the mesh.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.realpath(__file__))))

if "--cpu" in sys.argv or os.environ.get("JAX_PLATFORMS", "") == "cpu" \
        or "host_platform_device_count" in os.environ.get("XLA_FLAGS", ""):
    import jax
    jax.config.update("jax_platforms", "cpu")

import numpy as np
import jax
import jax.numpy as jnp
import flax.linen as nn

import deepspeed_tpu


class MLP(nn.Module):
    """A module whose apply(params, batch) returns the scalar loss."""

    @nn.compact
    def __call__(self, batch):
        x, y = batch
        h = nn.tanh(nn.Dense(256)(x))
        h = nn.tanh(nn.Dense(256)(h))
        return jnp.mean((nn.Dense(1)(h).squeeze(-1) - y) ** 2)


def main():
    model = MLP()
    rng = np.random.default_rng(0)
    x = rng.normal(size=(32, 64)).astype(np.float32)
    y = (x[:, 0] * 0.5 - x[:, 1]).astype(np.float32)
    params = model.init(jax.random.PRNGKey(0), (jnp.asarray(x), jnp.asarray(y)))["params"]

    config = {
        "train_micro_batch_size_per_gpu": 32,
        "gradient_accumulation_steps": 2,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 3,
                              "zero_quantized_weights": True,   # qwZ: s8 gathers
                              "stage3_param_persistence_threshold": 0},
    }
    # DSTPU_TELEMETRY_DIR=<dir>: unified telemetry — JSONL metrics stream +
    # Chrome trace (open telemetry.trace.json in chrome://tracing / Perfetto)
    tel_dir = os.environ.get("DSTPU_TELEMETRY_DIR")
    if tel_dir:
        config["telemetry"] = {"enabled": True,
                               "jsonl_path": os.path.join(tel_dir, "telemetry.jsonl"),
                               "trace_path": os.path.join(tel_dir, "telemetry.trace.json")}

    engine, optimizer, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=params, config=config)
    assert isinstance(optimizer, deepspeed_tpu.ZeROOptimizer)

    for step in range(20):
        loss = engine.train_batch(batch=(np.tile(x, (2, 1)), np.tile(y, 2)))
        if step % 5 == 0:
            print(f"step {step:3d}  loss {float(loss):.4f}  lr {engine.get_lr()[0]:.2e}")

    if tel_dir:
        # micro-loop steps so the trace carries fwd/bwd/step spans, plus one
        # profiled eager collective for a comm span + latency/bytes histograms
        for _ in range(2):
            loss = engine.forward((x, y))
            engine.backward(loss)
            engine.step()
        deepspeed_tpu.comm.all_reduce(np.ones((8, 32), np.float32))

    # checkpoint + RLHF-style surgery on the sharded master
    import tempfile
    ckdir = tempfile.mkdtemp()
    engine.save_checkpoint(ckdir, tag="demo")
    from deepspeed_tpu.utils import safe_get_full_fp32_param
    w = safe_get_full_fp32_param(engine, "Dense_0/kernel")
    print(f"checkpoint saved; Dense_0/kernel gathered shape {w.shape}")
    engine.destroy()  # flushes the telemetry trace/JSONL when enabled
    print("OK")


if __name__ == "__main__":
    main()
