"""Token-tree verification units: the TokenTree container, the tree-attention
mask (ancestor-only visibility — a chain tree is bitwise the linear verify),
ragged multi-sequence tree packing, the device-argmax greedy verify path, and
the accepted-path KV compaction (re-pack + rollback with exact pool balance).

The serving-layer integration (learned drafter, auto arbitration, bitwise
spec-on/off identity through the scheduler) lives in
tests/unit/serving/test_speculative.py and test_spec_learned.py.
"""

import numpy as np
import pytest

from deepspeed_tpu.inference.v2.spec import TokenTree


# --------------------------------------------------------------- container --
def test_token_tree_chain_and_validation():
    t = TokenTree.chain([5, 6, 7])
    assert t.size == 3 and t.is_chain and t.max_depth == 2
    assert t.parents.tolist() == [-1, 0, 1]
    assert t.depths.tolist() == [0, 1, 2]

    # branching: root -> {a, b}, a -> c
    t = TokenTree([1, 2, 3, 4], [-1, 0, 0, 1])
    assert not t.is_chain and t.max_depth == 2
    assert t.children(0) == [1, 2] and t.children(1) == [3]
    assert t.child_with_token(0, 3) == 2
    assert t.child_with_token(0, 9) is None

    with pytest.raises(ValueError, match="root"):
        TokenTree([1, 2], [0, 0])
    with pytest.raises(ValueError, match="topological"):
        TokenTree([1, 2, 3], [-1, 2, 0])
    with pytest.raises(ValueError):
        TokenTree([], [])
    with pytest.raises(ValueError, match="depths"):
        TokenTree([1, 2], [-1, 0], depths=[0, 2])


# ----------------------------------------------------------------- fixture --
@pytest.fixture(scope="module")
def tree_engine_setup():
    import jax
    import jax.numpy as jnp

    from deepspeed_tpu.inference.v2.config_v2 import RaggedInferenceEngineConfig
    from deepspeed_tpu.inference.v2.engine_factory import build_engine
    from deepspeed_tpu.inference.v2.ragged.manager_configs import (AllocationMode,
                                                                   DSStateManagerConfig,
                                                                   MemoryConfig)
    from deepspeed_tpu.models.llama import LlamaConfig, LlamaModel

    cfg = LlamaConfig.tiny(dtype=jnp.float32)
    model = LlamaModel(cfg)
    params = {"model": model.init(jax.random.PRNGKey(0),
                                  jnp.zeros((1, 8), jnp.int32))["params"]}

    def make(blocks=64):
        mgr = DSStateManagerConfig(
            memory_config=MemoryConfig(mode=AllocationMode.ALLOCATE, size=blocks),
            max_context=512)
        return build_engine(params, cfg,
                            RaggedInferenceEngineConfig(state_manager=mgr,
                                                        kv_block_size=16))
    return cfg, make


def _prefill_argmax(engine, prompt):
    logits = engine.put([0], [prompt])
    return int(np.argmax(np.asarray(logits)[0]))


# --------------------------------------------------- chain tree == linear --
def test_chain_tree_verify_matches_linear_verify_bitwise(tree_engine_setup):
    """A chain tree through verify_tree produces the SAME per-position logits
    as the linear verify feed — the tree-attention mask degenerates to
    causal, logical positions equal slot positions, and the program's
    arithmetic matches the linear verify's."""
    cfg, make = tree_engine_setup
    prompt = np.random.default_rng(0).integers(0, cfg.vocab_size, 24)

    lin = make()
    t1 = _prefill_argmax(lin, prompt)
    feed = np.asarray([t1, 3, 9, 4], np.int32)
    lin_rows = lin.verify([0], [feed])[0]

    tre = make()
    assert _prefill_argmax(tre, prompt) == t1
    out = tre.verify_tree([0], [TokenTree.chain(feed)])[0]
    assert out["rows"].shape == (4, cfg.vocab_size)
    assert out["hidden"].shape[0] == 4
    np.testing.assert_array_equal(out["rows"], lin_rows)
    assert tre._state_manager.get_sequence(0).seen_tokens == prompt.size + 4


def test_tree_greedy_ids_match_logits_argmax(tree_engine_setup):
    cfg, make = tree_engine_setup
    prompt = np.random.default_rng(1).integers(0, cfg.vocab_size, 16)
    tree = TokenTree([0, 1, 2, 3, 4], [-1, 0, 0, 1, 2])

    e1 = make()
    t1 = _prefill_argmax(e1, prompt)
    tree.tokens[0] = t1
    rows = e1.verify_tree([0], [tree])[0]["rows"]

    e2 = make()
    assert _prefill_argmax(e2, prompt) == t1
    out = e2.verify_tree([0], [tree], greedy=True)[0]
    assert out["rows"] is None
    assert out["ids"].dtype == np.int32 and out["ids"].shape == (5,)
    np.testing.assert_array_equal(out["ids"], np.argmax(rows, axis=-1))


# --------------------------------------------------- ancestor-only masking --
def test_sibling_branches_are_mutually_invisible(tree_engine_setup):
    """Each branch of a tree scores exactly as if it were fed ALONE as a
    chain: node logits depend on the ancestor path only, never on sibling
    branches sharing the ragged feed."""
    cfg, make = tree_engine_setup
    prompt = np.random.default_rng(2).integers(0, cfg.vocab_size, 20)

    # root -> {a-branch: 7 -> 11, b-branch: 3 -> 5}
    eng = make()
    t1 = _prefill_argmax(eng, prompt)
    tree = TokenTree([t1, 7, 11, 3, 5], [-1, 0, 1, 0, 3])
    rows = eng.verify_tree([0], [tree])[0]["rows"]

    for chain_nodes in ([0, 1, 2], [0, 3, 4]):
        ref = make()
        assert _prefill_argmax(ref, prompt) == t1
        chain = TokenTree.chain(tree.tokens[chain_nodes])
        ref_rows = ref.verify_tree([0], [chain])[0]["rows"]
        np.testing.assert_array_equal(rows[chain_nodes], ref_rows)


def test_ragged_multi_sequence_tree_packing(tree_engine_setup):
    """One dispatch carries a wide tree, a narrow tree, and a chain across
    three sequences; every sequence scores as if verified alone."""
    cfg, make = tree_engine_setup
    rng = np.random.default_rng(3)
    prompts = [rng.integers(0, cfg.vocab_size, n) for n in (20, 12, 9)]

    eng = make()
    logits = np.asarray(eng.put([0, 1, 2], prompts))
    nxt = [int(np.argmax(logits[i])) for i in range(3)]
    trees = [TokenTree([nxt[0], 7, 11, 3, 5], [-1, 0, 1, 0, 3]),
             TokenTree([nxt[1], 2, 4], [-1, 0, 0]),
             TokenTree.chain([nxt[2], 8])]
    outs = eng.verify_tree([0, 1, 2], trees)

    for i, (prompt, tree) in enumerate(zip(prompts, trees)):
        solo = make()
        lg = solo.put([0], [prompt])
        assert int(np.argmax(np.asarray(lg)[0])) == nxt[i]
        ref = solo.verify_tree([0], [tree])[0]
        np.testing.assert_array_equal(outs[i]["rows"], ref["rows"])
        np.testing.assert_array_equal(outs[i]["hidden"], ref["hidden"])
        assert eng._state_manager.get_sequence(i).seen_tokens == \
            prompt.size + tree.size


# ------------------------------------------------------------- compaction --
def test_compact_accepted_repacks_branch_and_decode_continues_exactly(tree_engine_setup):
    """Accept the SECOND branch of a tree (nodes at non-contiguous slots):
    compact_accepted must gather the accepted KV to contiguous slots and
    truncate the rest, so subsequent decode is bitwise identical to a run
    that fed the accepted tokens linearly."""
    cfg, make = tree_engine_setup
    prompt = np.random.default_rng(4).integers(0, cfg.vocab_size, 24)

    # reference: feed [t1, a, b] linearly, then greedy-decode 4 tokens
    ref = make()
    t1 = _prefill_argmax(ref, prompt)
    a, b = 3, 5
    ref_rows = ref.verify([0], [np.asarray([t1, a, b], np.int32)])[0]
    nxt = int(np.argmax(ref_rows[-1]))
    ref_out = [nxt]
    for _ in range(3):
        lg = ref.put([0], [[ref_out[-1]]])
        ref_out.append(int(np.argmax(np.asarray(lg)[0])))

    # tree run: the accepted path 0 -> 3 -> 4 sits AFTER a rejected branch
    eng = make()
    assert _prefill_argmax(eng, prompt) == t1
    tree = TokenTree([t1, 7, 11, a, b], [-1, 0, 1, 0, 3])
    out = eng.verify_tree([0], [tree])[0]
    np.testing.assert_array_equal(out["rows"][[0, 3, 4]], ref_rows)
    rejected = eng.compact_accepted(0, tree.size, [3, 4])
    assert rejected == 2
    seq = eng._state_manager.get_sequence(0)
    assert seq.seen_tokens == prompt.size + 3  # t1, a, b committed
    tree_out = [int(np.argmax(out["rows"][4]))]
    for _ in range(3):
        lg = eng.put([0], [[tree_out[-1]]])
        tree_out.append(int(np.argmax(np.asarray(lg)[0])))
    assert tree_out == ref_out


def test_compact_accepted_chain_path_skips_device_copy(tree_engine_setup):
    """A chain-shaped acceptance (path[j] == j+1) needs no KV movement: no
    compact program is compiled, only the rollback runs."""
    cfg, make = tree_engine_setup
    prompt = np.random.default_rng(5).integers(0, cfg.vocab_size, 16)
    eng = make()
    t1 = _prefill_argmax(eng, prompt)
    tree = TokenTree([t1, 1, 2, 3], [-1, 0, 1, 2])
    eng.verify_tree([0], [tree])
    before = [k for k in eng.model._lowerable if k[0] == "compact"]
    assert eng.compact_accepted(0, tree.size, [1, 2]) == 1
    after = [k for k in eng.model._lowerable
             if isinstance(k, tuple) and k[0] == "compact"]
    assert before == after  # contiguous path: pure rollback
    assert eng._state_manager.get_sequence(0).seen_tokens == prompt.size + 3


def test_compact_accepted_validates_path(tree_engine_setup):
    cfg, make = tree_engine_setup
    eng = make()
    prompt = np.random.default_rng(6).integers(0, cfg.vocab_size, 8)
    t1 = _prefill_argmax(eng, prompt)
    eng.verify_tree([0], [TokenTree([t1, 1, 2], [-1, 0, 0])])
    with pytest.raises(ValueError, match="ascending"):
        eng.compact_accepted(0, 3, [2, 1])
    with pytest.raises(ValueError, match="ascending"):
        eng.compact_accepted(0, 3, [0])  # root is not part of the path
    with pytest.raises(ValueError, match="unknown uid"):
        eng.compact_accepted(404, 3, [])
    assert eng.compact_accepted(0, 3, []) == 2  # nothing accepted


def test_tree_rollback_soak_pool_balance(tree_engine_setup):
    """PR-10-style soak: interleaved tree verifies, compactions and flushes
    over several sequences never leak KV blocks — the pool balances exactly
    once every sequence is flushed."""
    cfg, make = tree_engine_setup
    eng = make()
    kv = eng._state_manager.kv_cache
    total = kv.num_blocks
    rng = np.random.default_rng(7)
    for round_ in range(6):
        uids = [10 + round_ * 3 + i for i in range(3)]
        prompts = [rng.integers(0, cfg.vocab_size, int(rng.integers(5, 40)))
                   for _ in uids]
        logits = np.asarray(eng.put(uids, prompts))
        trees = []
        for i in range(len(uids)):
            t1 = int(np.argmax(logits[i]))
            trees.append(TokenTree([t1, 7, 11, 3, 5], [-1, 0, 1, 0, 3]))
        eng.verify_tree(uids, trees)
        for i, uid in enumerate(uids):
            n_accept = int(rng.integers(0, 3))
            path = [[], [3], [3, 4]][n_accept]
            eng.compact_accepted(uid, trees[i].size, path)
            seq = eng._state_manager.get_sequence(uid)
            assert seq.seen_tokens == prompts[i].size + 1 + n_accept
        for uid in uids:
            eng.flush(uid)
        assert eng.free_blocks == total
    assert eng._state_manager.n_tracked_sequences == 0


def test_ragged_wrapper_rejects_malformed_tree_metadata(tree_engine_setup):
    from deepspeed_tpu.inference.v2.ragged.manager_configs import \
        DSStateManagerConfig
    from deepspeed_tpu.inference.v2.ragged.ragged_wrapper import \
        RaggedBatchWrapper
    from deepspeed_tpu.inference.v2.ragged.sequence_descriptor import \
        DSSequenceDescriptor
    w = RaggedBatchWrapper(DSStateManagerConfig())
    seq = DSSequenceDescriptor(0)
    with pytest.raises(ValueError, match="align"):
        w.insert_sequence(seq, [1, 2, 3], tree=([-1, 0], [0, 1]))
    with pytest.raises(ValueError, match="root"):
        w.insert_sequence(seq, [1, 2], tree=([0, 0], [1, 1]))
    with pytest.raises(ValueError, match="topological"):
        w.insert_sequence(seq, [1, 2, 3], tree=([-1, 2, 0], [0, 1, 1]))
    # a valid tree packs tree_meta into the device batch
    w.insert_sequence(seq, [1, 2, 3], tree=([-1, 0, 0], [0, 1, 1]))
    batch = w.finalize()
    assert batch["tree_meta"].shape[0] == 2
    assert batch["tree_meta"][0, :3].tolist() == [-1, 0, 0]
    assert batch["tree_meta"][1, :3].tolist() == [0, 1, 1]
