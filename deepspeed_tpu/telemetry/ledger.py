"""Per-request / per-tenant cost-attribution ledger (the cost plane).

Three pieces, all scheduler-owned and telemetry-gated:

- :class:`PriceBook` — deterministic analytic pricing: flops / HBM bytes per
  token derived once from the model config (falls back to fixed constants when
  no config is reachable).  Pricing happens at *read* time over integer token
  counts, so per-tenant sums reconcile exactly against the aggregate — the
  conservation gate's invariant.
- :class:`RequestCost` — the per-request accumulator carried on
  ``Request.cost``: tokens billed per phase, device-seconds amortized over
  batch occupants, compile-amnesty seconds, KV block-seconds per tier, wire
  bytes per channel, and cache savings (prefix tokens served, spec tokens
  accepted).
- :class:`CostLedger` — the charging API plus an engine-level aggregate of the
  same fields (incremented at the same sites, so nothing can be double-billed
  or unattributed) and a bounded per-tenant rollup (:class:`TenantRollup`,
  overflow tenants fold into ``<other>`` so conservation still holds).

Zero-cost-when-disabled: ``CostLedger.maybe_create`` returns None unless a
telemetry session is active; every scheduler hot-path site is one
``if ledger is not None`` check.  The accumulators themselves are plain
Python — only the mirrored ``serving_cost_*`` / ``serving_tenant_*`` metric
families touch the registry.
"""

from typing import Optional

DEFAULT_TENANT = "default"
OTHER_TENANT = "<other>"

# phases the scheduler bills (the engine dispatch kinds, scheduler-side view)
PHASES = ("prefill", "decode", "verify", "tree_verify")

# fallbacks when no model config is reachable: arbitrary but fixed, so pricing
# stays deterministic across runs of the same build
_FALLBACK_FLOPS_PER_TOKEN = 2.0e6
_FALLBACK_BYTES_PER_TOKEN = 1.0e6


class PriceBook:
    """Deterministic (phase, tokens) -> (flops, bytes) pricing.

    The analytic model is the standard dense-transformer count: forward flops
    per token ~= 2 * params, and decode HBM traffic per token ~= the full
    parameter + KV read (approximated as ``param_bytes``).  The point is not
    chip-accurate accounting — the PR-13 perf gates own that — but a *fixed,
    documented* price per token so tenant bills are comparable and the
    conservation gate can check exact reconciliation on integer token counts.
    """

    def __init__(self, flops_per_token: float = _FALLBACK_FLOPS_PER_TOKEN,
                 bytes_per_token: float = _FALLBACK_BYTES_PER_TOKEN,
                 source: str = "fallback"):
        self.flops_per_token = float(flops_per_token)
        self.bytes_per_token = float(bytes_per_token)
        self.source = source

    @classmethod
    def from_model_config(cls, cfg) -> "PriceBook":
        """Analytic pricing from a model config exposing the usual dense
        fields; any missing attribute falls back to the fixed constants."""
        try:
            h = int(cfg.hidden_size)
            layers = int(cfg.num_layers)
            vocab = int(cfg.vocab_size)
            inter = int(getattr(cfg, "intermediate_size", 4 * h))
            # attention (4 h^2) + gated MLP (3 h*inter) per layer, plus the
            # embedding/unembedding matrix
            params = layers * (4 * h * h + 3 * h * inter) + vocab * h
            bytes_per_param = 2.0  # bf16 weights are the serving default
            return cls(flops_per_token=2.0 * params,
                       bytes_per_token=bytes_per_param * params,
                       source="analytic")
        except (AttributeError, TypeError, ValueError):
            return cls()

    def flops(self, tokens: int) -> float:
        return self.flops_per_token * tokens

    def bytes(self, tokens: int) -> float:
        return self.bytes_per_token * tokens

    def to_dict(self) -> dict:
        return {"flops_per_token": self.flops_per_token,
                "bytes_per_token": self.bytes_per_token,
                "source": self.source}


class _CostBase:
    """Shared accumulator fields for the per-request cost and the aggregate /
    per-tenant totals — same fields, charged at the same sites."""

    __slots__ = ("tokens", "drafted_tokens", "accepted_tokens",
                 "saved_prefix_tokens", "saved_spec_tokens",
                 "device_seconds", "amnesty_seconds", "dispatches",
                 "kv_block_seconds", "wire_bytes")

    def __init__(self):
        self.tokens = {p: 0 for p in PHASES}
        self.drafted_tokens = 0
        self.accepted_tokens = 0
        self.saved_prefix_tokens = 0
        self.saved_spec_tokens = 0
        self.device_seconds = 0.0
        self.amnesty_seconds = 0.0
        self.dispatches = 0
        self.kv_block_seconds = {}   # tier -> float seconds
        self.wire_bytes = {}         # channel -> int bytes

    @property
    def billed_tokens(self) -> int:
        return sum(self.tokens.values())

    def doc(self, pricebook: Optional[PriceBook] = None) -> dict:
        billed = self.billed_tokens
        out = {
            "tokens": dict(self.tokens, billed=billed),
            "speculative": {"drafted": self.drafted_tokens,
                            "accepted": self.accepted_tokens},
            "saved_tokens": {"prefix": self.saved_prefix_tokens,
                             "spec": self.saved_spec_tokens},
            "device_seconds": round(self.device_seconds, 6),
            "amnesty_seconds": round(self.amnesty_seconds, 6),
            "dispatches": self.dispatches,
            "kv_block_seconds": {t: round(s, 6)
                                 for t, s in sorted(self.kv_block_seconds.items())},
            "wire_bytes": dict(sorted(self.wire_bytes.items())),
        }
        if pricebook is not None:
            out["flops"] = pricebook.flops(billed)
            out["hbm_bytes"] = pricebook.bytes(billed)
        return out


class RequestCost(_CostBase):
    """The accumulator carried on ``Request.cost`` (None with telemetry off).

    ``_kv_anchor`` implements piecewise-constant KV block-second accrual: the
    ledger closes the open segment and re-anchors on every block-count / tier
    transition it is told about, so occupancy between events is billed at the
    last known (blocks, tier)."""

    __slots__ = ("pricebook", "_kv_anchor")

    def __init__(self, pricebook: PriceBook):
        super().__init__()
        self.pricebook = pricebook
        self._kv_anchor = None  # (ts_s, blocks, tier)

    def to_dict(self) -> dict:
        return self.doc(self.pricebook)

    def compact_row(self) -> dict:
        """Cost-to-date for /v1/stats request rows and flight-recorder rows."""
        return {"billed_tokens": self.billed_tokens,
                "device_ms": round(self.device_seconds * 1e3, 3),
                "kv_block_s": round(sum(self.kv_block_seconds.values()), 3),
                "wire_bytes": sum(self.wire_bytes.values())}


class _Totals(_CostBase):
    __slots__ = ("requests",)

    def __init__(self):
        super().__init__()
        self.requests = 0

    def fold(self, cost: _CostBase):
        for p, n in cost.tokens.items():
            self.tokens[p] = self.tokens.get(p, 0) + n
        self.drafted_tokens += cost.drafted_tokens
        self.accepted_tokens += cost.accepted_tokens
        self.saved_prefix_tokens += cost.saved_prefix_tokens
        self.saved_spec_tokens += cost.saved_spec_tokens
        self.device_seconds += cost.device_seconds
        self.amnesty_seconds += cost.amnesty_seconds
        self.dispatches += cost.dispatches
        for t, s in cost.kv_block_seconds.items():
            self.kv_block_seconds[t] = self.kv_block_seconds.get(t, 0.0) + s
        for c, b in cost.wire_bytes.items():
            self.wire_bytes[c] = self.wire_bytes.get(c, 0) + b
        self.requests += 1


class TenantRollup:
    """Bounded tenant -> totals store.  Once ``max_tenants`` distinct tenants
    exist, later tenants fold into ``<other>`` — bounded memory, and the sum
    over rows still reconciles against the aggregate."""

    def __init__(self, max_tenants: int = 64):
        self.max_tenants = max(1, int(max_tenants))
        self._tenants = {}  # tenant -> _Totals

    def bucket_for(self, tenant: str) -> str:
        if tenant in self._tenants or len(self._tenants) < self.max_tenants:
            return tenant
        return OTHER_TENANT

    def fold(self, tenant: str, cost: _CostBase) -> str:
        bucket = self.bucket_for(tenant)
        totals = self._tenants.get(bucket)
        if totals is None:
            totals = self._tenants[bucket] = _Totals()
        totals.fold(cost)
        return bucket

    def items(self):
        return self._tenants.items()

    def doc(self, pricebook: Optional[PriceBook] = None) -> dict:
        return {tenant: dict(totals.doc(pricebook), requests=totals.requests)
                for tenant, totals in sorted(self._tenants.items())}


class CostLedger:
    """The charging API.  Created by the serving scheduler when (and only
    when) a telemetry session is active; every call site in the scheduler is
    behind one ``if self._ledger is not None`` check, so disabled telemetry
    pays nothing and the registry sees zero api_calls."""

    def __init__(self, registry, pricebook: Optional[PriceBook] = None,
                 max_tenants: int = 64, tenant_metric_top_k: int = 8,
                 default_tenant: str = DEFAULT_TENANT):
        self.pricebook = pricebook or PriceBook()
        self.default_tenant = default_tenant
        self.totals = _Totals()
        self.tenants = TenantRollup(max_tenants=max_tenants)
        self._tenant_metric_top_k = max(1, int(tenant_metric_top_k))
        self._registry = registry
        self._m_billed = {
            p: registry.counter(
                "serving_cost_billed_tokens_total",
                "tokens billed by the cost ledger, by engine phase",
                labels={"phase": p})
            for p in PHASES}
        self._m_device_s = registry.counter(
            "serving_cost_device_seconds_total",
            "dispatch wall-seconds attributed to requests (amortized over batch occupants)")
        self._m_amnesty_s = registry.counter(
            "serving_cost_amnesty_seconds_total",
            "dispatch wall-seconds forgiven as compile amnesty (first sight of a (program, bucket))")
        self._m_kv = {}    # tier -> counter
        self._m_wire = {}  # channel -> counter
        self._m_saved = {
            src: registry.counter(
                "serving_cost_saved_tokens_total",
                "tokens the request did NOT pay for (prefix-cache hits, accepted spec drafts)",
                labels={"source": src})
            for src in ("prefix", "spec")}
        self._tenant_m = {}  # tenant -> (tokens_counter, requests_counter)

    # ------------------------------------------------------------- lifecycle --
    def begin(self, req) -> None:
        req.cost = RequestCost(self.pricebook)

    def finalize(self, req, now_s: float) -> None:
        """Close the open KV segment and fold the request into its tenant's
        rollup (bounded; overflow tenants land in ``<other>``)."""
        cost = req.cost
        if cost is None:
            return
        self._close_kv(cost, now_s)
        tenant = req.tenant or self.default_tenant
        bucket = self.tenants.fold(tenant, cost)
        self.totals.requests += 1
        tokens_c, requests_c = self._tenant_metrics(bucket)
        tokens_c.inc(cost.billed_tokens)
        requests_c.inc()

    # -------------------------------------------------------------- charging --
    def charge_dispatch(self, members, seconds: float, amnesty_s: float = 0.0) -> None:
        """Attribute one engine dispatch to its batch members.

        ``members`` is ``[(cost, phase, tokens), ...]`` — the executed plan's
        view.  Wall time (and any compile-amnesty forgiveness) is amortized by
        each member's share of the dispatch's fed tokens."""
        total = sum(t for _, _, t in members)
        if total <= 0:
            return
        billed_by_phase = {}
        for cost, phase, tokens in members:
            cost.tokens[phase] = cost.tokens.get(phase, 0) + tokens
            self.totals.tokens[phase] = self.totals.tokens.get(phase, 0) + tokens
            share = tokens / total
            cost.device_seconds += seconds * share
            cost.amnesty_seconds += amnesty_s * share
            cost.dispatches += 1
            billed_by_phase[phase] = billed_by_phase.get(phase, 0) + tokens
        self.totals.device_seconds += seconds
        self.totals.amnesty_seconds += amnesty_s
        self.totals.dispatches += 1
        for phase, tokens in billed_by_phase.items():
            self._m_billed[phase].inc(tokens)
        self._m_device_s.inc(seconds)
        if amnesty_s:
            self._m_amnesty_s.inc(amnesty_s)

    def charge_spec(self, cost: RequestCost, drafted: int, accepted: int) -> None:
        cost.drafted_tokens += drafted
        cost.accepted_tokens += accepted
        cost.saved_spec_tokens += accepted
        self.totals.drafted_tokens += drafted
        self.totals.accepted_tokens += accepted
        self.totals.saved_spec_tokens += accepted
        if accepted:
            self._m_saved["spec"].inc(accepted)

    def charge_prefix(self, cost: RequestCost, tokens: int) -> None:
        cost.saved_prefix_tokens += tokens
        self.totals.saved_prefix_tokens += tokens
        if tokens:
            self._m_saved["prefix"].inc(tokens)

    def charge_wire(self, cost: RequestCost, channel: str, nbytes: int) -> None:
        cost.wire_bytes[channel] = cost.wire_bytes.get(channel, 0) + nbytes
        self.totals.wire_bytes[channel] = self.totals.wire_bytes.get(channel, 0) + nbytes
        counter = self._m_wire.get(channel)
        if counter is None:
            counter = self._m_wire[channel] = self._registry.counter(
                "serving_cost_wire_bytes_total",
                "KV payload bytes billed to requests, by motion channel",
                labels={"channel": channel})
        counter.inc(nbytes)

    def touch_kv(self, cost: RequestCost, blocks: int, tier: str, now_s: float) -> None:
        """Close the open occupancy segment and re-anchor at (blocks, tier)."""
        self._close_kv(cost, now_s)
        if blocks > 0:
            cost._kv_anchor = (now_s, int(blocks), tier)

    def _close_kv(self, cost: RequestCost, now_s: float) -> None:
        anchor = cost._kv_anchor
        if anchor is None:
            return
        ts, blocks, tier = anchor
        cost._kv_anchor = None
        dt = max(0.0, now_s - ts)
        if dt <= 0.0 or blocks <= 0:
            return
        amount = blocks * dt
        cost.kv_block_seconds[tier] = cost.kv_block_seconds.get(tier, 0.0) + amount
        self.totals.kv_block_seconds[tier] = \
            self.totals.kv_block_seconds.get(tier, 0.0) + amount
        counter = self._m_kv.get(tier)
        if counter is None:
            counter = self._m_kv[tier] = self._registry.counter(
                "serving_cost_kv_block_seconds_total",
                "KV block-seconds billed to requests, by residency tier",
                labels={"tier": tier})
        counter.inc(amount)

    # -------------------------------------------------------------- reading --
    def _tenant_metrics(self, tenant: str):
        m = self._tenant_m.get(tenant)
        if m is None:
            if len(self._tenant_m) >= self._tenant_metric_top_k and tenant != OTHER_TENANT:
                tenant = OTHER_TENANT
                m = self._tenant_m.get(tenant)
            if m is None:
                m = self._tenant_m[tenant] = (
                    self._registry.counter(
                        "serving_tenant_tokens_total",
                        "tokens billed per tenant (top-K tenants; overflow under <other>)",
                        labels={"tenant": tenant}),
                    self._registry.counter(
                        "serving_tenant_requests_total",
                        "finished requests per tenant (top-K tenants; overflow under <other>)",
                        labels={"tenant": tenant}))
        return m

    def usage_doc(self) -> dict:
        return {"enabled": True,
                "default_tenant": self.default_tenant,
                "pricing": self.pricebook.to_dict(),
                "totals": dict(self.totals.doc(self.pricebook),
                               requests=self.totals.requests),
                "tenants": self.tenants.doc(self.pricebook)}
