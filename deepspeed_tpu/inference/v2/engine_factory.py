"""Engine construction + generation driver.

Reference: ``deepspeed/inference/v2/engine_factory.py`` (build_hf_engine:66 picks an
InferenceV2Policy by HF ``model_type``). Here model classes consume the training
pytree directly, so the "policy" is a config-type → model-class dispatch.

The decode loop (``generate``) is the serving-side driver the reference leaves to
MII: continuous-batching greedy/temperature sampling over ``engine.put()``.
"""

from typing import Dict, List, Optional, Sequence

import numpy as np

from deepspeed_tpu.inference.v2.config_v2 import RaggedInferenceEngineConfig
from deepspeed_tpu.inference.v2.engine_v2 import InferenceEngineV2


def build_engine(params, model_config, engine_config: Optional[RaggedInferenceEngineConfig] = None):
    """Build an InferenceEngineV2 for a training param tree + model config;
    the model class resolves through the policy registry (reference
    engine_factory.py:66-120 model_type dispatch)."""
    from deepspeed_tpu.inference.v2.model_implementations.registry import model_cls_for

    if engine_config is None:
        engine_config = RaggedInferenceEngineConfig()
    model = model_cls_for(model_config)(params, model_config, engine_config)
    return InferenceEngineV2(model, engine_config)


def build_engine_from_ds_checkpoint(path: str,
                                    engine_config: Optional[RaggedInferenceEngineConfig] = None):
    """Rebuild an engine from an ``InferenceEngineV2.serialize`` directory
    (reference engine_factory.py:29) — the inference-checkpoint round-trip.
    The config is JSON (never pickle: a checkpoint directory must not be an
    arbitrary-code-execution vector) and its class is restricted to this
    package's model configs."""
    import importlib
    import json
    import os

    import jax.numpy as jnp

    with open(os.path.join(path, "ds_model_config.json")) as f:
        cfg_doc = json.load(f)
    mod_name, _, cls_name = cfg_doc["config_class"].rpartition(".")
    if not mod_name.startswith("deepspeed_tpu."):
        raise ValueError(f"refusing to import config class from {mod_name!r} "
                         "(only deepspeed_tpu model configs are loadable)")
    cfg_cls = getattr(importlib.import_module(mod_name), cls_name)

    def dec(v):
        if isinstance(v, dict) and "__dtype__" in v:
            # restore the jnp SCALAR TYPE (jnp.float32), not np.dtype: they
            # compare equal but models may branch on the exact object
            return getattr(jnp, v["__dtype__"], jnp.dtype(v["__dtype__"]))
        return v

    model_config = cfg_cls(**{k: dec(v) for k, v in cfg_doc["fields"].items()})
    with open(os.path.join(path, "metadata_rank0.json")) as f:
        meta = json.load(f)
    params: Dict = {}
    with np.load(os.path.join(path, "params_rank0.npz")) as z:
        for i, m in enumerate(meta):
            arr = z[f"p{i}"]
            if str(arr.dtype) != m["dtype"]:  # stored as a uint view (bf16)
                arr = jnp.asarray(arr).view(jnp.dtype(m["dtype"]))
            node = params
            keys = m["path"].split("/")
            for k in keys[:-1]:
                node = node.setdefault(k, {})
            node[keys[-1]] = jnp.asarray(arr).reshape(m["shape"])
    return build_engine(params, model_config, engine_config)


def build_hf_engine(path: str, engine_config: Optional[RaggedInferenceEngineConfig] = None):
    """Load an HF checkpoint directory and build an engine (reference
    engine_factory.py:66); a directory written by ``engine.serialize`` routes
    to the DS-checkpoint loader (reference :84 ds_model_config detection)."""
    import os

    if os.path.exists(os.path.join(path, "ds_model_config.json")):
        return build_engine_from_ds_checkpoint(path, engine_config)
    if os.path.exists(os.path.join(path, "ds_model_config.pkl")):
        raise ValueError(
            f"{path} is a LEGACY pickle-format DS checkpoint; the format was "
            "retired (pickle in a checkpoint is an arbitrary-code-execution "
            "vector). Re-serialize the engine with the current code to get "
            "the JSON-config format.")
    from deepspeed_tpu.inference.checkpoint import load_hf_checkpoint

    params, model_config = load_hf_checkpoint(path)
    return build_engine(params, model_config, engine_config)


def generate(engine: InferenceEngineV2,
             prompts: Sequence[Sequence[int]],
             max_new_tokens: int = 16,
             temperature: float = 0.0,
             eos_token_id: Optional[int] = None,
             seed: int = 0,
             decode_chunk: int = 1) -> List[List[int]]:
    """Continuous-batching decode: prefill all prompts (token budget permitting),
    then decode step-by-step; finished sequences are flushed and their KV blocks
    recycled. Greedy when ``temperature == 0``.

    ``decode_chunk`` > 1 runs decode in chunks of K steps through the engine's
    on-device ``decode_loop`` (one dispatch per chunk instead of one per
    token); eos is checked between chunks, so a finished sequence over-
    generates up to K-1 discarded tokens before its KV blocks recycle — the
    standard chunked-serving tradeoff of host-RTT against speculative compute.
    NOTE: with ``temperature > 0`` the chunked path samples on device from a
    jax PRNG stream, so sampled outputs differ from ``decode_chunk=1`` (host
    numpy stream) for the same seed; greedy output is identical either way.
    """
    rng = np.random.default_rng(seed)
    uids = list(range(len(prompts)))
    outputs: Dict[int, List[int]] = {u: [] for u in uids}
    pending = {u: np.asarray(p, np.int32) for u, p in zip(uids, prompts)}
    live: Dict[int, np.ndarray] = {}  # uid -> next token to feed
    done: set = set()

    def sample(row: np.ndarray) -> int:
        if temperature <= 0.0:
            return int(np.argmax(row))
        z = row.astype(np.float64) / temperature
        z -= z.max()
        p = np.exp(z)
        p /= p.sum()
        return int(rng.choice(row.shape[0], p=p))

    from deepspeed_tpu.inference.v2.scheduling_utils import SchedulingError, SchedulingResult

    def admits(uids_l, lens_l):
        """Full admission check — sequence count and KV blocks, not just the
        token budget (ADVICE r2: token-only budgeting made put() raise instead
        of deferring)."""
        return engine.can_schedule(uids_l, lens_l) == SchedulingResult.Success

    while len(done) < len(uids):
        batch_uids, batch_tokens = [], []

        def try_admit(u, toks):
            cand_u = batch_uids + [u]
            cand_t = [t.size for t in batch_tokens] + [len(toks)]
            if not admits(cand_u, cand_t):
                return False
            batch_uids.append(u)
            batch_tokens.append(np.asarray(toks, np.int32))
            return True

        # admit pending prefills first (SplitFuse-style: chunk to fit the budget)
        budget = engine._config.state_manager.max_ragged_batch_size
        for u in list(pending):
            used = sum(t.size for t in batch_tokens)
            room = budget - used
            if room < 1:
                break
            chunk, rest = pending[u][:room], pending[u][room:]
            while chunk.size and not try_admit(u, chunk):
                chunk = chunk[:chunk.size // 2]  # back off under KV pressure
                rest = pending[u][chunk.size:]
            if not chunk.size:
                continue  # deferred to a later iteration
            if rest.size:
                pending[u] = rest
            else:
                del pending[u]
                live[u] = None  # logits from this put() seed decode
        for u, tok in live.items():
            if tok is not None and u not in batch_uids:
                try_admit(u, [tok])  # deferred when unschedulable, not crashed
        if not batch_uids:
            if pending or any(t is not None for t in live.values()):
                raise RuntimeError(
                    f"generate(): no sequence schedulable ({len(pending)} pending, "
                    f"{engine.free_blocks} free KV blocks) — raise the engine's "
                    f"KV/sequence budgets or lower concurrency")
            break
        def finish_or_continue(u, nxt):
            outputs[u].append(nxt)
            if (eos_token_id is not None and nxt == eos_token_id) or len(outputs[u]) >= max_new_tokens:
                done.add(u)
                live.pop(u, None)
                engine.flush(u)
            else:
                live[u] = nxt

        decoding_only = (decode_chunk > 1 and not pending
                         and all(t.size == 1 for t in batch_tokens))
        if decoding_only:
            # chunked device loop: always K steps per dispatch — one compiled
            # program per bucket; the stop/discard pass below drops any tokens
            # past eos or max_new_tokens (the documented up-to-K-1 overshoot)
            try:
                import jax as _jax
                toks = engine.decode_loop(
                    batch_uids, batch_tokens, decode_chunk,
                    temperature=float(temperature),
                    rng=_jax.random.PRNGKey(seed + sum(len(o) for o in outputs.values()))
                    if temperature > 0 else None)
            except SchedulingError:
                toks = None  # KV too tight for K steps — single-step fallback
            if toks is not None:
                for i, u in enumerate(batch_uids):
                    stop = False
                    for t in toks[i]:
                        if stop:
                            break  # discard over-generated tokens past eos
                        finish_or_continue(u, int(t))
                        stop = u in done
                continue
        logits = np.asarray(engine.put(batch_uids, batch_tokens))
        for i, u in enumerate(batch_uids):
            if u in pending:  # mid-prefill: ignore logits until prompt is consumed
                continue
            finish_or_continue(u, sample(logits[i]))
    return [outputs[u] for u in uids]
