"""Wall-clock and throughput timers.

TPU-native analog of the reference's ``deepspeed/utils/timer.py``
(SynchronizedWallClockTimer:43, ThroughputTimer:198). Instead of CUDA events we
synchronize by blocking on JAX async dispatch (``jax.block_until_ready`` /
``jax.effects_barrier``) before reading the host clock — the same role CUDA event
synchronization plays in the reference.
"""

import time

from deepspeed_tpu.utils.logging import log_dist

FORWARD_MICRO_TIMER = "fwd_microstep"
FORWARD_GLOBAL_TIMER = "fwd"
BACKWARD_MICRO_TIMER = "bwd_microstep"
BACKWARD_GLOBAL_TIMER = "bwd"
BACKWARD_INNER_MICRO_TIMER = "bwd_inner_microstep"
BACKWARD_INNER_GLOBAL_TIMER = "bwd_inner"
BACKWARD_REDUCE_MICRO_TIMER = "bwd_allreduce_microstep"
BACKWARD_REDUCE_GLOBAL_TIMER = "bwd_allreduce"
STEP_MICRO_TIMER = "step_microstep"
STEP_GLOBAL_TIMER = "step"

TRAIN_BATCH_TIMER = "train_batch"


def _device_synchronize():
    try:
        import jax
        jax.effects_barrier()
    except Exception:
        pass


class SynchronizedWallClockTimer:
    """Named timers that synchronize the accelerator before reading the clock."""

    class Timer:

        def __init__(self, name):
            self.name_ = name
            self.started_ = False
            self.start_time = 0.0
            self.elapsed_ = 0.0
            self.elapsed_records = []

        def start(self):
            assert not self.started_, f"{self.name_} timer has already been started"
            _device_synchronize()
            self.start_time = time.time()
            self.started_ = True

        def stop(self, reset=False, record=True):
            assert self.started_, f"{self.name_} timer is not started"
            _device_synchronize()
            elapsed = time.time() - self.start_time
            if reset:
                self.elapsed_ = elapsed
            else:
                self.elapsed_ += elapsed
            if record:
                self.elapsed_records.append(elapsed)
            self.started_ = False

        def reset(self):
            self.started_ = False
            self.elapsed_ = 0.0

        def elapsed(self, reset=True):
            started = self.started_
            if started:
                self.stop(record=False)
            elapsed = self.elapsed_
            if reset:
                self.reset()
            if started:
                self.start()
            return elapsed

        def mean(self):
            if not self.elapsed_records:
                return 0.0
            return sum(self.elapsed_records) / len(self.elapsed_records)

    def __init__(self):
        self.timers = {}

    def get_timers(self):
        return self.timers

    def __call__(self, name):
        if name not in self.timers:
            self.timers[name] = self.Timer(name)
        return self.timers[name]

    @staticmethod
    def memory_usage():
        try:
            import jax
            stats = jax.local_devices()[0].memory_stats() or {}
            in_use = stats.get("bytes_in_use", 0) / (1024**3)
            peak = stats.get("peak_bytes_in_use", 0) / (1024**3)
            return f"Mem in-use {round(in_use, 2)} GB \t peak {round(peak, 2)} GB"
        except Exception:
            return "Mem stats unavailable"

    def log(self, names, normalizer=1.0, reset=True, memory_breakdown=False, ranks=None):
        assert normalizer > 0.0
        string = "time (ms)"
        for name in names:
            if name in self.timers:
                elapsed_time = self.timers[name].elapsed(reset=reset) * 1000.0 / normalizer
                string += f" | {name}: {elapsed_time:.2f}"
        log_dist(string, ranks=ranks or [0])


class NoopTimer:

    class Timer:

        def start(self):
            ...

        def reset(self):
            ...

        def stop(self, **kwargs):
            ...

        def elapsed(self, **kwargs):
            return 0

        def mean(self):
            return 0

    def __init__(self):
        self.timer = self.Timer()

    def __call__(self, name):
        return self.timer

    def get_timers(self):
        return {}

    def log(self, names, normalizer=1.0, reset=True, memory_breakdown=False, ranks=None):
        ...


class ThroughputTimer:
    """Samples/sec timer (reference: utils/timer.py:198)."""

    def __init__(self, config, batch_size, start_step=2, steps_per_output=None, monitor_memory=False, logging_fn=None):
        self.config = config
        self.start_time = 0
        self.end_time = 0
        self.started = False
        self.batch_size = max(1, batch_size)
        self.start_step = start_step
        self.epoch_count = 0
        self.micro_step_count = 0
        self.global_step_count = 0
        self.total_elapsed_time = 0
        self.step_elapsed_time = 0
        self.steps_per_output = steps_per_output
        self.monitor_memory = monitor_memory
        self.logging = logging_fn or log_dist

    def update_epoch_count(self):
        self.epoch_count += 1
        self.micro_step_count = 0

    def start(self):
        if not self.config.enabled:
            return
        _device_synchronize()
        self.start_time = time.time()
        self.started = True

    def stop(self, global_step=False, report_speed=True):
        if not self.config.enabled or not self.started:
            return
        self.started = False
        self.micro_step_count += 1
        if global_step:
            self.global_step_count += 1
        _device_synchronize()
        self.end_time = time.time()
        duration = self.end_time - self.start_time
        self.step_elapsed_time += duration
        # exclude warmup (jit compile) steps before start_step from the running
        # average, reference ThroughputTimer semantics (utils/timer.py:198)
        if global_step and self.global_step_count >= self.start_step:
            self.total_elapsed_time += self.step_elapsed_time

        if global_step and report_speed and self.global_step_count >= self.start_step:
            if self.steps_per_output and self.global_step_count % self.steps_per_output == 0:
                msg = (f"epoch={self.epoch_count}/micro_step={self.micro_step_count}/"
                       f"global_step={self.global_step_count}, "
                       f"RunningAvgSamplesPerSec={self.avg_samples_per_sec():.3f}, "
                       f"CurrSamplesPerSec={self.batch_size / self.step_elapsed_time:.3f}")
                if self.monitor_memory:
                    # reference ThroughputTimer monitor_memory: device memory
                    # appended on report steps
                    msg += f", {SynchronizedWallClockTimer.memory_usage()}"
                self.logging(msg)
        if global_step:
            self.step_elapsed_time = 0

    def avg_samples_per_sec(self):
        counted = self.global_step_count - self.start_step + 1
        if counted > 0 and self.total_elapsed_time > 0:
            return self.batch_size * counted / self.total_elapsed_time
        return float("-inf")
