"""Base ragged transformer model implementation.

Reference: ``deepspeed/inference/v2/model_implementations/inference_transformer_base.py``
(DSTransformerModelBase:49 — attn/mlp/moe module composition, KV cache config and
sizing, ``get_kv_requirements``/``maybe_allocate_kv``/``kv_cache_config``) and
``inference_policy_base.py:104``.

TPU execution model: ``forward(ragged_batch)`` runs ONE jitted program per batch
*bucket* (padded token/sequence/block counts — see ragged_wrapper.py). The program
consumes the paged KV cache array functionally (donated in, returned out) and the
padded metadata arrays; scatter updates into the cache use XLA drop-mode so padding
never corrupts live blocks. Per-layer compute is supplied by subclasses via
``layer_forward``; embed/unembed live here, as does the logits gather (only each
sequence's final token is unembedded — reference ``logits_gather.cu`` semantics).
"""

from functools import partial
from typing import Optional, Tuple

import numpy as np

from deepspeed_tpu.inference.v2.ragged.manager_configs import KVCacheConfig
from deepspeed_tpu.inference.v2.ragged.sequence_descriptor import DSSequenceDescriptor
from deepspeed_tpu.inference.v2.tracer import get_tracer, record
from deepspeed_tpu.telemetry import compile_watch


class DSTransformerModelBase:
    """Subclasses define: num_layers, num_kv_heads, head_dim, vocab_size,
    ``embed(params, ids)``, ``layer_forward(params, li, x, attn_fn, batch)`` and
    ``unembed(params, x)``."""

    def __init__(self, params, config, engine_config, state_manager=None):
        wq = getattr(engine_config, "quantization", None)
        if wq is not None and wq.enabled:
            # ZeRO-Inference weight quantization: int8 at rest, dequantized
            # inside the jitted forward (inference/v2/quantization.py)
            if engine_config.tensor_parallel.tp_size > 1:
                raise NotImplementedError(
                    "weight_quantization with TP>1: AutoTP classifies by leaf "
                    "paths, which quantized subtrees change — quantize per-shard "
                    "after placement instead (not yet wired)")
            from deepspeed_tpu.inference.v2.quantization import quantize_tree
            params = quantize_tree(params, min_size=wq.min_size, bits=wq.bits)
        self._params = params
        self._config = config
        self._engine_config = engine_config
        self._state_manager = None
        self._compiled = {}
        self._lowerable = {}  # same keys, UNwrapped jit fns (perf-gate hook)
        if state_manager is not None:
            self.set_state_manager(state_manager)

    # ------------------------------------------------------------ properties --
    @property
    def config(self):
        return self._config

    @property
    def num_layers(self) -> int:
        raise NotImplementedError

    @property
    def num_kv_heads(self) -> int:
        raise NotImplementedError

    @property
    def num_heads(self) -> int:
        raise NotImplementedError

    @property
    def head_dim(self) -> int:
        raise NotImplementedError

    @property
    def vocab_size(self) -> int:
        raise NotImplementedError

    @property
    def max_context(self) -> int:
        return self._engine_config.state_manager.max_context

    # ------------------------------------------------------------- kv sizing --
    def kv_cache_config(self) -> KVCacheConfig:
        import jax.numpy as jnp
        sm = self._engine_config.state_manager
        model_dtype = getattr(self._config, "dtype", jnp.bfloat16)
        # normalize through np.dtype: keying on the jnp scalar OBJECTS would
        # silently default an equivalent representation (np.float32,
        # np.dtype('float32')) to a bf16 cache under an fp32 model
        cache_dtype = np.dtype(model_dtype).name
        if cache_dtype not in ("bfloat16", "float16", "float32"):
            cache_dtype = "bfloat16"
        return KVCacheConfig(block_size=self._engine_config.kv_block_size,
                             cache_shape=(self.num_layers, self.num_kv_heads, self.head_dim),
                             cache_dtype=cache_dtype,
                             max_blocks_per_allocation_group=(sm.max_context + self._engine_config.kv_block_size - 1)
                             // self._engine_config.kv_block_size)

    def set_state_manager(self, state_manager):
        self._state_manager = state_manager

    @property
    def state_manager(self):
        return self._state_manager

    def get_kv_requirements(self, seq_desc: DSSequenceDescriptor, max_new_tokens: int,
                            max_new_blocks: int) -> Tuple[int, int]:
        """How many of ``max_new_tokens`` can run given ``max_new_blocks`` free
        blocks, and how many blocks that takes (reference
        inference_transformer_base.py get_kv_requirements)."""
        bs = self._state_manager.kv_block_size
        # the per-sequence table cap (max_context) bounds schedulable tokens
        # too: admission must reject here, not crash in extend_kv_cache after
        # blocks were already pulled from the pool
        seq_cap = seq_desc.max_blocks - seq_desc.cur_allocated_blocks
        max_new_blocks = min(max_new_blocks, seq_cap)
        total = seq_desc.seen_tokens + max_new_tokens
        blocks_needed = (total + bs - 1) // bs - seq_desc.cur_allocated_blocks
        if blocks_needed <= max_new_blocks:
            return max_new_tokens, max(0, blocks_needed)
        # clip tokens to what the block budget allows
        capacity = (seq_desc.cur_allocated_blocks + max_new_blocks) * bs - seq_desc.seen_tokens
        return max(0, capacity), max_new_blocks

    def get_remaining_block_capacity(self, seq_desc: DSSequenceDescriptor) -> int:
        bs = self._state_manager.kv_block_size
        return seq_desc.cur_allocated_blocks * bs - seq_desc.seen_tokens

    def maybe_allocate_kv(self, seq_desc: DSSequenceDescriptor, n_new_tokens: int) -> None:
        sched, n_blocks = self.get_kv_requirements(seq_desc, n_new_tokens,
                                                   self._state_manager.free_blocks)
        if sched < n_new_tokens:
            # the do_checks=True path rejects this earlier with a
            # SchedulingError; an unchecked put must fail LOUDLY — silently
            # under-allocating would scatter KV through out-of-range block-
            # table entries and corrupt other sequences
            raise ValueError(
                f"sequence {seq_desc.tracking_id}: {n_new_tokens} new tokens need more "
                f"KV blocks than the free pool / per-sequence max_context allows "
                f"(schedulable: {sched})")
        if n_blocks > 0:
            seq_desc.extend_kv_cache(self._state_manager.allocate_blocks(n_blocks))

    def maybe_free_kv(self, seq_desc: DSSequenceDescriptor) -> None:
        """Hook for cache shrinking; paged blocks are retained until flush."""

    # ---------------------------------------------------------------- forward --
    def prepare_batch(self, ragged_batch) -> None:
        """Amortized pre-forward work (reference engine_v2.py prepare_batch)."""

    def forward(self, ragged_batch):
        """Run the ragged forward; returns logits [n_seqs, vocab] (one row per
        sequence — its final token), and updates the paged KV cache in place."""
        import jax

        batch = ragged_batch.device_batch if hasattr(ragged_batch, "device_batch") else ragged_batch
        bucket = (batch["tok_meta"].shape[1], batch["seq_meta"].shape[0],
                  batch["seq_meta"].shape[1] - 4)
        fn = self._get_compiled(bucket)
        cache = self._state_manager.kv_cache.cache
        tracer = get_tracer()
        n = int(batch["n_seqs"])
        dev = {"tok_meta": batch["tok_meta"], "seq_meta": batch["seq_meta"]}
        if tracer is not None:
            logits, new_cache = self._traced_forward(dev, cache, n)
        else:
            logits, new_cache = fn(self._params, cache, dev)
        self._state_manager.kv_cache.set_cache(new_cache)
        return logits[:n] if n else logits[:0]

    def empty_run(self) -> None:
        """Participate in collectives with zero live tokens (fork engine_v2.py:308).
        Uses the smallest bucket with every validity mask false."""
        from deepspeed_tpu.inference.v2.ragged.ragged_wrapper import RaggedBatchWrapper
        wrapper = RaggedBatchWrapper(self._engine_config.state_manager,
                                     block_size=self._engine_config.kv_block_size)
        batch = wrapper.finalize()  # zero live sequences/tokens
        dev = {"tok_meta": batch["tok_meta"], "seq_meta": batch["seq_meta"]}
        tracer = get_tracer()
        if tracer is not None:
            self._traced_forward(dev, self._state_manager.kv_cache.cache, 0)
            return
        fn = self._get_compiled((batch["tok_meta"].shape[1], batch["seq_meta"].shape[0],
                                 batch["seq_meta"].shape[1] - 4))
        _, new_cache = fn(self._params, self._state_manager.kv_cache.cache, dev)
        self._state_manager.kv_cache.set_cache(new_cache)

    def _get_compiled(self, bucket):
        import jax
        if bucket not in self._compiled:
            fn = jax.jit(self._forward_impl, donate_argnums=(1, ))
            self._lowerable[bucket] = fn
            cw = compile_watch.get()
            if cw is not None:
                # attribute the bucket's XLA compile (and any later internal
                # recompile) to this site in the compile_* metrics and trace
                fn = cw.wrap("inference_forward", bucket, fn)
            self._compiled[bucket] = fn
        return self._compiled[bucket]

    # -------------------------------------------------------- lowering hooks --
    @staticmethod
    def _lowerable_kind(key) -> str:
        """Program-kind classification of a ``_compiled``/``_lowerable`` jit
        cache key: ``(T, S, MB)`` int tuples are forward programs,
        ``(bucket, n_steps, sampled)`` are decode loops, and every 2-tuple
        with a string head is named after that head (``verify``,
        ``verify_greedy``, ``tree_verify``, ``tree_verify_greedy``,
        ``compact``)."""
        if isinstance(key, tuple) and len(key) == 3 and isinstance(key[0], tuple):
            return "decode_loop"
        if isinstance(key, tuple) and len(key) == 2 and isinstance(key[0], str):
            return key[0]
        return "forward"

    def lowerable_callables(self):
        """Raw ``jax.jit`` callables (they support ``.lower()``) grouped by
        program kind and keyed exactly like ``_compiled``: forward programs by
        ``(T, S, MB)`` bucket, decode programs by ``(bucket, n_steps,
        sampled)``, the speculative verify family by ``("verify"|
        "verify_greedy"|"tree_verify"|"tree_verify_greedy", bucket)`` and the
        accepted-path KV re-pack by ``("compact", n_pairs)``. The official
        hook for HLO-level analysis (deepspeed_tpu/perf/) — the entries in
        ``_compiled`` may be compile-watch wrappers, which cannot lower."""
        out = {"forward": {}, "decode_loop": {}, "verify": {}}
        for k, v in self._lowerable.items():
            out.setdefault(self._lowerable_kind(k), {})[k] = v
        return out

    def _synthetic_batch(self, bucket=None):
        """Shape/dtype-faithful device-batch arrays for ``bucket`` (default:
        the smallest bucket the ragged wrapper produces) — lowering needs
        avals, not live data. Built directly (the wrapper's own pad helpers
        give the bucket shape): ``RaggedBatchWrapper.finalize`` would report
        the bucket to the compile watch, and an analysis-only lowering must
        not pollute the bucket-churn recompile telemetry."""
        if bucket is None:
            from deepspeed_tpu.inference.v2.ragged.ragged_wrapper import (_pad_to,
                                                                          _pow2_pad,
                                                                          to_padded)
            bucket = (to_padded(1), _pad_to(1, 8), _pow2_pad(1, 4))
        T, S, MB = bucket
        return {"tok_meta": np.zeros((4, T), np.int32),
                "seq_meta": np.full((S, 4 + MB), -1, np.int32)}

    def lower_forward(self, bucket=None):
        """Lower the ragged forward at ``bucket`` (``(T, S, MB)``; default
        smallest) against the live params + paged KV cache and return the
        ``jax.stages.Lowered``. Never executes; the program is the same
        ``_forward_impl`` jit :meth:`forward` runs for that bucket."""
        import jax
        dev = self._synthetic_batch(bucket)
        key = (dev["tok_meta"].shape[1], dev["seq_meta"].shape[0],
               dev["seq_meta"].shape[1] - 4)
        # reuse the engine's own jit entry when the bucket has run already
        fn = self._lowerable.get(key) or jax.jit(self._forward_impl, donate_argnums=(1, ))
        return fn.lower(self._params, self._state_manager.kv_cache.cache, dev)

    def lower_decode_loop(self, n_steps: int, bucket=None, temperature: float = 0.0):
        """Lower the ``n_steps`` on-device decode program (same
        ``_decode_loop_impl`` jit as :meth:`decode_loop`)."""
        import jax
        import jax.numpy as jnp
        dev = self._synthetic_batch(bucket)
        key = ((dev["tok_meta"].shape[1], dev["seq_meta"].shape[0],
                dev["seq_meta"].shape[1] - 4), int(n_steps), temperature > 0)
        fn = self._lowerable.get(key) or jax.jit(
            partial(self._decode_loop_impl, n_steps=int(n_steps),
                    sampled=temperature > 0),
            donate_argnums=(1, ))
        return fn.lower(self._params, self._state_manager.kv_cache.cache, dev,
                        jnp.float32(temperature), jax.random.PRNGKey(0))

    def lower_verify_step(self, bucket=None):
        """Lower the speculative verify program at ``bucket`` (default
        smallest) — the same ``_verify_impl`` jit :meth:`forward_verify`
        runs. Never executes."""
        import jax
        dev = self._synthetic_batch(bucket)
        key = ("verify", (dev["tok_meta"].shape[1], dev["seq_meta"].shape[0],
                          dev["seq_meta"].shape[1] - 4))
        fn = self._lowerable.get(key) or jax.jit(self._verify_impl,
                                                 donate_argnums=(1, ))
        return fn.lower(self._params, self._state_manager.kv_cache.cache, dev)

    def lower_tree_verify(self, bucket=None, greedy: bool = False):
        """Lower the token-tree verify program at ``bucket`` (default
        smallest) — the same ``_tree_verify_impl`` jit
        :meth:`forward_verify_tree` runs. The synthetic ``tree_meta`` is a
        chain (lowering consumes avals only; the mask program is identical
        for every tree shape at a bucket). Never executes."""
        import jax
        dev = self._synthetic_batch(bucket)
        T = dev["tok_meta"].shape[1]
        dev["tree_meta"] = np.stack([np.arange(-1, T - 1, dtype=np.int32),
                                     np.arange(T, dtype=np.int32)])
        key = ("tree_verify_greedy" if greedy else "tree_verify",
               (T, dev["seq_meta"].shape[0], dev["seq_meta"].shape[1] - 4))
        fn = self._lowerable.get(key) or jax.jit(
            partial(self._tree_verify_impl, greedy=greedy), donate_argnums=(1, ))
        return fn.lower(self._params, self._state_manager.kv_cache.cache, dev)

    # ------------------------------------------------------------ decode loop --
    def decode_loop(self, ragged_batch, n_steps: int, temperature: float = 0.0,
                    rng=None):
        """Decode ``n_steps`` tokens per sequence in ONE device program —
        greedy argmax at ``temperature`` 0, categorical sampling otherwise
        (``rng`` folded per step; REQUIRED when sampling — a silent fixed
        default would make "sampling" deterministic across calls).

        The host-loop decode (one ``put`` per generated token) pays a full
        host→device dispatch round-trip per token — through a tunneled or
        remote-coordinator deployment that RTT (~100 ms measured) dwarfs the
        ~0.3 ms device step and becomes the serving bottleneck. This runs the
        whole generation as a ``lax.scan``: per step, one ragged forward (same
        program as :meth:`forward`, either attention path), argmax next token,
        advance the on-device metadata. KV blocks for all ``n_steps`` tokens
        must be pre-allocated (engine_v2.decode_loop does this).

        Returns generated tokens ``[n_steps, S_bucket]`` (host numpy); column i
        is sequence-slot i, rows are steps. The cache is updated in place with
        the n_steps inserted tokens (the last generated token is not yet
        inserted, matching the host-loop semantics).
        """
        import jax
        batch = ragged_batch.device_batch if hasattr(ragged_batch, "device_batch") else ragged_batch
        bucket = (batch["tok_meta"].shape[1], batch["seq_meta"].shape[0],
                  batch["seq_meta"].shape[1] - 4)
        temperature = float(temperature)
        key = (bucket, int(n_steps), temperature > 0)
        if key not in self._compiled:
            fn = jax.jit(
                partial(self._decode_loop_impl, n_steps=int(n_steps),
                        sampled=temperature > 0),
                donate_argnums=(1, ))
            self._lowerable[key] = fn
            cw = compile_watch.get()
            if cw is not None:
                fn = cw.wrap("inference_decode_loop", key, fn)
            self._compiled[key] = fn
        cache = self._state_manager.kv_cache.cache
        if temperature > 0 and rng is None:
            raise ValueError("decode_loop(temperature>0) requires an rng key — a fixed "
                             "default would return identical 'samples' every call")
        rng = rng if rng is not None else jax.random.PRNGKey(0)
        tokens, new_cache = self._compiled[key](
            self._params, cache, {"tok_meta": batch["tok_meta"], "seq_meta": batch["seq_meta"]},
            jax.numpy.float32(temperature), rng)
        self._state_manager.kv_cache.set_cache(new_cache)
        return np.asarray(tokens)

    def _decode_loop_impl(self, params, cache, batch, temperature, rng, *, n_steps,
                          sampled=False):
        import jax
        import jax.numpy as jnp

        tok_meta = jnp.asarray(batch["tok_meta"])
        seq_meta = jnp.asarray(batch["seq_meta"])

        def step(carry, _):
            cache, tok_meta, seq_meta, r = carry
            logits, cache = self._forward_impl(params, cache,
                                               {"tok_meta": tok_meta, "seq_meta": seq_meta})
            if sampled:
                r, sub = jax.random.split(r)
                next_ids = jax.random.categorical(
                    sub, logits / jnp.maximum(temperature, 1e-6), axis=-1).astype(jnp.int32)
            else:  # greedy: the key is carried untouched (no dead per-step split)
                next_ids = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # [S]
            tv = tok_meta[3] > 0
            # decode batches carry one token per sequence: slot i ↔ sequence i
            new_ids = jnp.where(tv, next_ids[tok_meta[1]], tok_meta[0])
            tok_meta = tok_meta.at[0].set(new_ids).at[2].add(tv.astype(tok_meta.dtype))
            sv = (seq_meta[:, 3] > 0).astype(seq_meta.dtype)
            seq_meta = seq_meta.at[:, 0].add(sv)
            return (cache, tok_meta, seq_meta, r), next_ids

        # static per-compile sampling flag rides on the jit-cache key
        (cache, _, _, _), tokens = jax.lax.scan(
            step, (cache, tok_meta, seq_meta, rng), None, length=n_steps)
        return tokens, cache

    @staticmethod
    def _unpack_batch(batch):
        """Packed [4,T]/[S,4+MB] metadata → the named per-field views (built
        inside jit: free slices, no extra transfers)."""
        tok, seq = batch["tok_meta"], batch["seq_meta"]
        return dict(input_ids=tok[0], token_seq=tok[1], token_pos=tok[2],
                    token_valid=tok[3].astype(bool), seq_seen=seq[:, 0],
                    seq_ntok=seq[:, 1], last_tok=seq[:, 2],
                    seq_valid=seq[:, 3].astype(bool), block_table=seq[:, 4:])

    def _forward_impl(self, params, cache, batch):
        import jax.numpy as jnp
        from deepspeed_tpu.inference.v2.quantization import dequantize_tree

        params = dequantize_tree(params)  # no-op without quantized leaves
        batch = self._unpack_batch(batch)
        x = self.embed(params, batch["input_ids"])
        attn = partial(self._paged_attention, batch=batch)
        for li in range(self.num_layers):
            x, cache = self.layer_forward(params, li, x, cache, attn, batch)
        # unembed ONLY each sequence's last token (reference logits_gather)
        x_last = x[batch["last_tok"]]
        logits = self.unembed(params, x_last)
        return logits.astype(jnp.float32), cache

    # ----------------------------------------------------- speculative verify --
    def forward_verify(self, ragged_batch, greedy: bool = False):
        """The speculative-decoding verify forward: identical layer compute to
        :meth:`forward`, but EVERY token position is unembedded — returns
        logits ``[T_bucket, vocab]`` (row t scores the token AFTER batch
        position t), so one ragged pass prices a next-input token plus its k
        draft tokens per sequence. The KV cache is updated in place for every
        fed position, including drafts that turn out wrong — the caller rolls
        those back by truncating ``seen_tokens`` (the KV is overwritten when
        the correct tokens are fed at the same positions).

        ``greedy=True`` runs the device-argmax variant instead: the ``[T,
        vocab]`` float32 logits stay on device and only ``[T]`` int32 token
        ids cross to the host — the greedy verify path's host transfer drops
        from ``T * vocab * 4`` bytes to ``T * 4`` (memoed in the
        ``spec_verify_step`` perf budget)."""
        import jax
        batch = ragged_batch.device_batch if hasattr(ragged_batch, "device_batch") else ragged_batch
        bucket = (batch["tok_meta"].shape[1], batch["seq_meta"].shape[0],
                  batch["seq_meta"].shape[1] - 4)
        key = ("verify_greedy" if greedy else "verify", bucket)
        if key not in self._compiled:
            fn = jax.jit(self._verify_greedy_impl if greedy else self._verify_impl,
                         donate_argnums=(1, ))
            self._lowerable[key] = fn
            cw = compile_watch.get()
            if cw is not None:
                fn = cw.wrap("inference_verify", key, fn)
            self._compiled[key] = fn
        cache = self._state_manager.kv_cache.cache
        dev = {"tok_meta": batch["tok_meta"], "seq_meta": batch["seq_meta"]}
        out, new_cache = self._compiled[key](self._params, cache, dev)
        self._state_manager.kv_cache.set_cache(new_cache)
        return out

    def _verify_impl(self, params, cache, batch):
        """Same program body as :meth:`_forward_impl` minus the last-token
        gather: the verify step needs logits at all 1+k fed positions."""
        import jax.numpy as jnp
        from deepspeed_tpu.inference.v2.quantization import dequantize_tree

        params = dequantize_tree(params)
        batch = self._unpack_batch(batch)
        x = self.embed(params, batch["input_ids"])
        attn = partial(self._paged_attention, batch=batch)
        for li in range(self.num_layers):
            x, cache = self.layer_forward(params, li, x, cache, attn, batch)
        logits = self.unembed(params, x)  # ALL positions, token-major
        return logits.astype(jnp.float32), cache

    def _verify_greedy_impl(self, params, cache, batch):
        """Greedy verify: argmax on device, so only ``[T]`` int32 ids transfer
        to the host instead of the full ``[T, vocab]`` float32 logits."""
        import jax.numpy as jnp
        logits, cache = self._verify_impl(params, cache, batch)
        return jnp.argmax(logits, axis=-1).astype(jnp.int32), cache

    # ------------------------------------------------------ tree verification --
    def forward_verify_tree(self, ragged_batch, greedy: bool = False):
        """Token-tree verify (spec/tree.py): one ragged forward scores every
        node of each sequence's draft TREE under a tree-attention mask — a
        node attends to the committed prefix plus its own ancestor path only,
        so sibling branches cannot see each other even though they share the
        batch. Requires the batch to carry ``tree_meta`` (the ragged wrapper
        packs it when a tree is inserted).

        Returns ``(rows_or_ids, hidden)``: per-node float32 logits ``[T,
        vocab]`` (or, with ``greedy=True``, device-argmax int32 ids ``[T]``)
        plus the final residual hidden state ``[T, hidden]`` float32 — the
        learned draft head's input for the NEXT draft step. KV is written at
        slot positions ``seen + node_index``; the caller re-packs the accepted
        path with ``engine_v2.compact_accepted``."""
        import jax
        batch = ragged_batch.device_batch if hasattr(ragged_batch, "device_batch") else ragged_batch
        if "tree_meta" not in batch:
            raise ValueError("forward_verify_tree needs a batch with tree_meta "
                             "(insert sequences with tree=(parents, depths))")
        bucket = (batch["tok_meta"].shape[1], batch["seq_meta"].shape[0],
                  batch["seq_meta"].shape[1] - 4)
        key = ("tree_verify_greedy" if greedy else "tree_verify", bucket)
        if key not in self._compiled:
            fn = jax.jit(partial(self._tree_verify_impl, greedy=greedy),
                         donate_argnums=(1, ))
            self._lowerable[key] = fn
            cw = compile_watch.get()
            if cw is not None:
                fn = cw.wrap("inference_tree_verify", key, fn)
            self._compiled[key] = fn
        cache = self._state_manager.kv_cache.cache
        dev = {"tok_meta": batch["tok_meta"], "seq_meta": batch["seq_meta"],
               "tree_meta": batch["tree_meta"]}
        out, hidden, new_cache = self._compiled[key](self._params, cache, dev)
        self._state_manager.kv_cache.set_cache(new_cache)
        return out, hidden

    def _tree_verify_impl(self, params, cache, batch, *, greedy=False):
        """Verify-program body for token trees. ``token_pos`` as packed by the
        wrapper is the KV SLOT position (``seen + node_index``); the model
        sees the LOGICAL position ``seen + depth`` (rotary embeddings must
        encode tree depth, not slot), while the attention closure keeps the
        slot positions for the cache scatter."""
        import jax.numpy as jnp
        from deepspeed_tpu.inference.v2.quantization import dequantize_tree

        params = dequantize_tree(params)
        tree_meta = jnp.asarray(batch["tree_meta"])
        parents, depths = tree_meta[0], tree_meta[1]
        batch = self._unpack_batch(batch)
        slot_pos = batch["token_pos"]
        batch = dict(batch,
                     token_pos=batch["seq_seen"][batch["token_seq"]] + depths)
        x = self.embed(params, batch["input_ids"])
        attn = partial(self._tree_paged_attention, batch=batch,
                       slot_pos=slot_pos, parents=parents, depths=depths)
        for li in range(self.num_layers):
            x, cache = self.layer_forward(params, li, x, cache, attn, batch)
        hidden = x.astype(jnp.float32)  # pre-final-norm residual, token-major
        logits = self.unembed(params, x).astype(jnp.float32)
        if greedy:
            return jnp.argmax(logits, axis=-1).astype(jnp.int32), hidden, cache
        return logits, hidden, cache

    def _traced_forward(self, batch, cache, n):
        """Phase-timed execution for the tracer: embed / per-layer phases /
        unembed run as separate device computations so host timers see real
        boundaries (slower than the fused program — tracing mode trades speed
        for observability; the reference pays CUDA-event overhead instead)."""
        import jax
        import jax.numpy as jnp
        from deepspeed_tpu.inference.v2.quantization import dequantize_tree

        # one cached jit; with quantization on, tracing mode holds a full-
        # precision weight copy for the duration of the phase-split forward
        # (observability mode trades memory+speed for timers, as documented)
        if not hasattr(self, "_dequant_fn"):
            self._dequant_fn = jax.jit(dequantize_tree)
        params = self._dequant_fn(self._params)
        batch_j = self._unpack_batch({k: jnp.asarray(v) for k, v in batch.items()})
        with record("embed"):
            x = jax.jit(self.embed)(params, batch_j["input_ids"])
            x.block_until_ready()
        attn = partial(self._paged_attention, batch=batch_j)
        for li in range(self.num_layers):
            x, cache = self.layer_forward_traced(params, li, x, cache, attn, batch_j)
        with record("unembed"):
            logits = jax.jit(self.unembed)(params, x[batch_j["last_tok"]])
            logits = logits.astype(jnp.float32)
            logits.block_until_ready()
        self._state_manager.kv_cache.set_cache(cache)
        return logits[:n], cache

    def layer_forward_traced(self, params, li, x, cache, attn_fn, batch):
        raise NotImplementedError("tracing requires a model with phase-split layers")

    # -------------------------------------------------------- paged attention --
    @property
    def attention_window(self) -> int:
        """Sliding attention window in tokens; 0 = full causal (mistral sets
        it via its model config)."""
        return 0

    def _use_paged_kernel(self, T: int) -> bool:
        """Attention-implementation choice; delegates to the heuristics layer
        (reference modules/heuristics.py:36-165)."""
        from deepspeed_tpu.inference.v2.modules.heuristics import attention_implementation
        return attention_implementation(self, self._engine_config, T) == "pallas_paged"

    def _paged_attention(self, q, k_new, v_new, cache, li, *, batch):
        """Scatter new K/V into the paged cache, then attend each query token to
        its sequence's full history (gather per-sequence K/V from the block
        table — the XLA lowering of the reference's blocked flash kernel; a
        Pallas kernel consuming the same layout can swap in here).

        q: [T, H, D]; k_new/v_new: [T, KVH, D];
        cache: [L, 2, num_blocks, KVH, bs, D]."""
        import jax
        import jax.numpy as jnp

        T = q.shape[0]
        S, MB = batch["block_table"].shape
        bs = cache.shape[4]
        H, D = self.num_heads, self.head_dim
        KVH = self.num_kv_heads

        token_seq = batch["token_seq"]
        token_pos = batch["token_pos"]
        token_valid = batch["token_valid"]

        if self._use_paged_kernel(T):
            # fused KV-insert + blocked attention; the cache is aliased through
            # the kernel (an XLA-side scatter would copy it at the boundary)
            from deepspeed_tpu.ops.pallas.paged_attention import paged_attention_update
            return paged_attention_update(q, k_new, v_new, cache, li, batch["block_table"],
                                          token_seq, token_pos, token_valid)

        # --- scatter new kv ---------------------------------------------------
        NB = cache.shape[2]
        blk_idx = token_pos // bs
        blk_ids = batch["block_table"][token_seq, jnp.minimum(blk_idx, MB - 1)]
        # padding tokens and unallocated (-1) table slots route to NB — a
        # POSITIVE out-of-bounds index: scatter mode="drop" discards those
        # writes, whereas -1 would WRAP to block NB-1 and corrupt it
        blk_ids = jnp.where(token_valid & (blk_ids >= 0), blk_ids, NB)
        offs = token_pos % bs
        cache = cache.at[li, 0, blk_ids, :, offs].set(k_new.astype(cache.dtype), mode="drop")
        cache = cache.at[li, 1, blk_ids, :, offs].set(v_new.astype(cache.dtype), mode="drop")

        # --- gather per-sequence history (XLA fallback) ----------------------
        table = jnp.maximum(batch["block_table"], 0)  # [S, MB]
        k_hist = cache[li, 0][table]  # [S, MB, KVH, bs, D]
        v_hist = cache[li, 1][table]
        KV = MB * bs
        k_hist = k_hist.transpose(0, 2, 1, 3, 4).reshape(S, KVH, KV, D) \
            .transpose(0, 2, 1, 3).astype(q.dtype)
        v_hist = v_hist.transpose(0, 2, 1, 3, 4).reshape(S, KVH, KV, D) \
            .transpose(0, 2, 1, 3).astype(q.dtype)
        if KVH != H:  # GQA
            rep = H // KVH
            k_hist = jnp.repeat(k_hist, rep, axis=2)
            v_hist = jnp.repeat(v_hist, rep, axis=2)

        # --- densify queries per sequence ------------------------------------
        local_q = token_pos - batch["seq_seen"][token_seq]
        Qm = int(np.max([1, q.shape[0]]))  # dense q rows per seq, bounded by T
        q_dense = jnp.zeros((S, Qm, H, D), q.dtype)
        seq_ids = jnp.where(token_valid, token_seq, S)  # OOB drop for padding
        q_dense = q_dense.at[seq_ids, jnp.minimum(local_q, Qm - 1)].set(q, mode="drop")

        scale = 1.0 / (D**0.5)
        logits = jnp.einsum("sqhd,skhd->shqk", q_dense, k_hist).astype(jnp.float32) * scale
        kv_pos = jnp.arange(KV)[None, None, None, :]              # [1,1,1,KV]
        q_pos = (batch["seq_seen"][:, None] + jnp.arange(Qm)[None, :])[:, None, :, None]
        valid_kv = kv_pos <= q_pos                                # causal incl. self
        seq_len = (batch["seq_seen"] + batch["seq_ntok"])[:, None, None, None]
        valid_kv &= kv_pos < seq_len
        if self.attention_window > 0:  # mistral sliding window
            valid_kv &= kv_pos > q_pos - self.attention_window
        logits = jnp.where(valid_kv, logits, -1e30)
        probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
        out_dense = jnp.einsum("shqk,skhd->sqhd", probs, v_hist)

        # --- back to token-major ---------------------------------------------
        out = out_dense[token_seq, jnp.minimum(local_q, Qm - 1)]  # [T, H, D]
        out = jnp.where(token_valid[:, None, None], out, 0.0)
        return out, cache

    def _tree_paged_attention(self, q, k_new, v_new, cache, li, *, batch,
                              slot_pos, parents, depths):
        """Tree-attention over the paged cache: each query node sees the
        committed prefix plus its ANCESTOR-OR-SELF nodes only — sibling draft
        branches sharing the feed are mutually invisible. New K/V scatter at
        SLOT positions (``seen + node_index``, distinct per node) while
        ``batch["token_pos"]`` already carries the LOGICAL (depth-based)
        positions the rotary embedding consumed.

        Bitwise-identity construction: every query node attends a PER-QUERY
        virtual KV view in which its depth-d ancestor occupies kv index
        ``seen + d`` — exactly the slot a linear feed of that root path would
        write. The masked logits, softmax reduction and value contraction
        then see identical operands at identical indices as the linear verify
        of the same path, so any accepted branch scores bit-identically to
        spec-off decode (floating-point reduction order is layout-sensitive;
        a mask alone cannot give token-identical speculation). The view is a
        gather of the shared history — ``Qm`` is a handful of draft nodes, so
        the duplication is bounded by the tree budget.

        Always the XLA fallback path: the Pallas paged kernel assumes a
        contiguous causal feed and cannot express the ancestor view."""
        import jax
        import jax.numpy as jnp

        T = q.shape[0]
        S, MB = batch["block_table"].shape
        bs = cache.shape[4]
        H, D = self.num_heads, self.head_dim
        KVH = self.num_kv_heads

        token_seq = batch["token_seq"]
        token_valid = batch["token_valid"]

        # --- scatter new kv at slot positions --------------------------------
        NB = cache.shape[2]
        blk_idx = slot_pos // bs
        blk_ids = batch["block_table"][token_seq, jnp.minimum(blk_idx, MB - 1)]
        blk_ids = jnp.where(token_valid & (blk_ids >= 0), blk_ids, NB)
        offs = slot_pos % bs
        cache = cache.at[li, 0, blk_ids, :, offs].set(k_new.astype(cache.dtype), mode="drop")
        cache = cache.at[li, 1, blk_ids, :, offs].set(v_new.astype(cache.dtype), mode="drop")

        # --- gather per-sequence history -------------------------------------
        table = jnp.maximum(batch["block_table"], 0)  # [S, MB]
        k_hist = cache[li, 0][table]
        v_hist = cache[li, 1][table]
        KV = MB * bs
        k_hist = k_hist.transpose(0, 2, 1, 3, 4).reshape(S, KVH, KV, D) \
            .transpose(0, 2, 1, 3).astype(q.dtype)
        v_hist = v_hist.transpose(0, 2, 1, 3, 4).reshape(S, KVH, KV, D) \
            .transpose(0, 2, 1, 3).astype(q.dtype)
        if KVH != H:  # GQA
            rep = H // KVH
            k_hist = jnp.repeat(k_hist, rep, axis=2)
            v_hist = jnp.repeat(v_hist, rep, axis=2)

        # --- densify queries + tree metadata per sequence --------------------
        local_q = slot_pos - batch["seq_seen"][token_seq]  # node index in feed
        Qm = int(np.max([1, T]))
        seq_ids = jnp.where(token_valid, token_seq, S)  # OOB drop for padding
        row = jnp.minimum(local_q, Qm - 1)
        q_dense = jnp.zeros((S, Qm, H, D), q.dtype).at[seq_ids, row].set(q, mode="drop")
        parent_dense = jnp.full((S, Qm), -1, jnp.int32) \
            .at[seq_ids, row].set(parents.astype(jnp.int32), mode="drop")
        depth_dense = jnp.zeros((S, Qm), jnp.int32) \
            .at[seq_ids, row].set(depths.astype(jnp.int32), mode="drop")

        # --- ancestors by depth: abd[s, i, d] = node on i's root path at
        # depth d, or -1. Parent pointers are topological (parent < child), so
        # Qm hops of pointer-chasing reach every ancestor.
        s_ix = jnp.arange(S)[:, None]
        i_ix = jnp.arange(Qm)[None, :]

        def _hop(_, carry):
            abd, cur = carry
            d = jnp.take_along_axis(depth_dense, jnp.clip(cur, 0, Qm - 1), axis=1)
            abd = abd.at[s_ix, i_ix, jnp.where(cur >= 0, d, Qm)].set(
                jnp.maximum(cur, -1), mode="drop")
            nxt = jnp.take_along_axis(parent_dense, jnp.clip(cur, 0, Qm - 1), axis=1)
            return abd, jnp.where(cur >= 0, nxt, -1)

        abd, _ = jax.lax.fori_loop(
            0, Qm, _hop,
            (jnp.full((S, Qm, Qm), -1, jnp.int32),
             jnp.tile(jnp.arange(Qm, dtype=jnp.int32)[None, :], (S, 1))))

        # --- per-query virtual KV: committed slots pass through; feed slot
        # seen+d resolves to the query's depth-d ancestor's slot -------------
        kvr = jnp.arange(KV)
        seen_v = batch["seq_seen"]
        d_of_kv = kvr[None, :] - seen_v[:, None]                     # [S, KV]
        in_feed = (d_of_kv >= 0) & (d_of_kv < Qm)
        node = abd[jnp.arange(S)[:, None, None],
                   jnp.arange(Qm)[None, :, None],
                   jnp.clip(d_of_kv, 0, Qm - 1)[:, None, :]]         # [S, Qm, KV]
        src = jnp.where(in_feed[:, None, :],
                        jnp.where(node >= 0, seen_v[:, None, None] + node, KV),
                        kvr[None, None, :])                          # [S, Qm, KV]
        src_c = jnp.clip(src, 0, KV - 1)
        k_q = k_hist[jnp.arange(S)[:, None, None], src_c]            # [S, Qm, KV, H, D]
        v_q = v_hist[jnp.arange(S)[:, None, None], src_c]

        scale = 1.0 / (D**0.5)
        logits = jnp.einsum("sihd,sikhd->shik", q_dense, k_q).astype(jnp.float32) * scale
        # visibility: committed prefix, or an existing ancestor-or-self at the
        # depth slot; the logical kv position of feed slot seen+d IS seen+d,
        # so the sliding window applies to the raw kv index either way
        valid_kv = (kvr[None, None, :] < seen_v[:, None, None]) | \
            (in_feed[:, None, :] & (node >= 0))                      # [S, Qm, KV]
        if self.attention_window > 0:
            q_log = seen_v[:, None] + depth_dense                    # [S, Qm]
            valid_kv &= kvr[None, None, :] > q_log[:, :, None] - self.attention_window
        logits = jnp.where(valid_kv[:, None, :, :], logits, -1e30)
        probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
        out_dense = jnp.einsum("shik,sikhd->sihd", probs, v_q)

        # --- back to token-major ---------------------------------------------
        out = out_dense[token_seq, jnp.minimum(local_q, Qm - 1)]  # [T, H, D]
        out = jnp.where(token_valid[:, None, None], out, 0.0)
        return out, cache

    # ---------------------------------------------------------- kv compaction --
    def compact_kv(self, seq_desc: DSSequenceDescriptor, src_slots, dst_slots) -> None:
        """Copy KV at ``src_slots`` to ``dst_slots`` (absolute token slots of
        ``seq_desc``) across every layer and both K/V in ONE jitted
        gather-then-scatter — the tree-verify accepted-path re-pack: accepted
        nodes live at scattered slots ``seen0 + node_index`` and must land at
        contiguous ``seen0 + 1..m`` before the rejected tail is truncated.
        The gather reads the pre-copy cache, so overlapping src/dst pairs are
        safe. Jitted per pow2-padded copy count; padded pairs scatter to an
        out-of-range block and drop."""
        import jax
        from deepspeed_tpu.inference.v2.ragged.ragged_wrapper import _pow2_pad

        src = np.asarray(src_slots, np.int64).reshape(-1)
        dst = np.asarray(dst_slots, np.int64).reshape(-1)
        if src.size != dst.size:
            raise ValueError("compact_kv needs matching src/dst slot lists")
        if src.size == 0:
            return
        bs = self._state_manager.kv_block_size
        blocks = seq_desc.kv_blocks
        NB = self._state_manager.kv_cache.cache.shape[2]
        P = _pow2_pad(src.size, 2)
        src_blk = np.zeros(P, np.int32)
        src_off = np.zeros(P, np.int32)
        dst_blk = np.full(P, NB, np.int32)  # pad -> positive OOB -> drop
        dst_off = np.zeros(P, np.int32)
        src_blk[:src.size] = blocks[src // bs]
        src_off[:src.size] = src % bs
        dst_blk[:dst.size] = blocks[dst // bs]
        dst_off[:dst.size] = dst % bs

        key = ("compact", P)
        if key not in self._compiled:
            fn = jax.jit(self._compact_impl, donate_argnums=(0, ))
            self._lowerable[key] = fn
            cw = compile_watch.get()
            if cw is not None:
                fn = cw.wrap("inference_kv_compact", key, fn)
            self._compiled[key] = fn
        new_cache = self._compiled[key](self._state_manager.kv_cache.cache,
                                        src_blk, src_off, dst_blk, dst_off)
        self._state_manager.kv_cache.set_cache(new_cache)

    @staticmethod
    def _compact_impl(cache, src_blk, src_off, dst_blk, dst_off):
        # advanced indexing at axes 2 (block) and 4 (offset) puts the pair
        # axis first: vals[p, l, kv, h, d]
        vals = cache[:, :, src_blk, :, src_off]
        return cache.at[:, :, dst_blk, :, dst_off].set(vals, mode="drop")

    # ------------------------------------------------------------- serialize --
    def flattened_params(self):
        import jax
        return jax.tree.leaves(self._params)

    # Subclass hooks -----------------------------------------------------------
    def embed(self, params, ids):
        raise NotImplementedError

    def layer_forward(self, params, li, x, cache, attn_fn, batch):
        raise NotImplementedError

    def unembed(self, params, x):
        raise NotImplementedError
