"""HF-checkpoint injection policies (the v1 "containers" tier).

Reference: ``deepspeed/module_inject/containers/`` (~20 per-architecture
policies: gpt2.py, gptneox.py, bloom.py, opt.py, bert.py, ...) consumed by
``replace_module.py:182`` — each policy knows where a foreign (HuggingFace)
module keeps its weights and maps them into DeepSpeed's inference modules.

TPU formulation: a policy maps a foreign *checkpoint* (HF ``config.json`` +
``model.safetensors``/``pytorch_model.bin``) into a native flax model's
parameter tree:

- name mapping per architecture (HF module paths → flax tree paths);
- storage-convention transforms: ``torch.nn.Linear`` keeps ``[out, in]``
  (transpose into flax's ``[in, out]`` kernels), GPT-2's ``Conv1D`` already
  keeps ``[in, out]`` (no transpose);
- fused-QKV semantics: gpt-neox and bloom interleave Q/K/V *per head*
  (``[H, 3, D, in]``), so un-fusing must reshape per head — plain thirds
  would scramble heads (the same class of bug state_dict_factory guards for
  Megatron checkpoints);
- tied embeddings materialize into the flax lm_head.

TP sharding then comes structurally from ``auto_tp_specs`` over the converted
tree — the policy layer's second job in the reference (row/col classification)
is derived rather than hand-written, but the tests pin it per policy.
"""

import json
import os
from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np

from deepspeed_tpu.utils.logging import logger

_POLICIES: Dict[str, "HFPolicy"] = {}


def register_policy(model_type):
    def deco(cls):
        _POLICIES[model_type] = cls()
        return cls
    return deco


def supported_model_types():
    return sorted(_POLICIES)


# --------------------------------------------------------------- primitives --
def _t(w):
    """torch Linear [out, in] → flax Dense kernel [in, out]."""
    return np.ascontiguousarray(np.asarray(w).T)


def _ln(sd, pfx):
    return {"scale": np.asarray(sd[f"{pfx}.weight"]), "bias": np.asarray(sd[f"{pfx}.bias"])}


def _dense(sd, pfx, transpose=True):
    out = {"kernel": _t(sd[f"{pfx}.weight"]) if transpose else np.asarray(sd[f"{pfx}.weight"])}
    if f"{pfx}.bias" in sd:
        out["bias"] = np.asarray(sd[f"{pfx}.bias"])
    return out


def _unfuse_headwise_qkv(w, b, num_heads):
    """HF gpt-neox/bloom fused QKV stores ``[H, 3, D, in]`` (per-head
    interleaved). Returns ({q,k,v} kernels [in, H*D], biases [H*D])."""
    w = np.asarray(w)
    three_h, hidden = w.shape
    D = three_h // (3 * num_heads)
    wr = w.reshape(num_heads, 3, D, hidden)
    outs = {}
    for j, name in enumerate("qkv"):
        wj = wr[:, j].reshape(num_heads * D, hidden)  # [H*D, in]
        outs[f"{name}_proj"] = {"kernel": _t(wj)}
        if b is not None:
            br = np.asarray(b).reshape(num_heads, 3, D)
            outs[f"{name}_proj"]["bias"] = br[:, j].reshape(num_heads * D)
    return outs


# ------------------------------------------------------------------ policies --
class HFPolicy:
    """One foreign architecture: build the native module from the HF config
    and convert the HF state dict into its parameter tree."""

    model_type: str = ""

    def build(self, hf_cfg: dict):
        """→ (flax module, our config object)."""
        raise NotImplementedError

    def convert(self, sd: Dict[str, np.ndarray], hf_cfg: dict) -> dict:
        """HF checkpoint state dict → flax params tree."""
        raise NotImplementedError

    def key_filter(self, hf_cfg: dict):
        """Optional predicate restricting which checkpoint tensors load
        (policies serving one tower of a multi-tower checkpoint)."""
        return None


@register_policy("gpt2")
class GPT2Policy(HFPolicy):
    """HF ``transformer.*`` → models/gpt2.GPT2Model. Conv1D stores [in, out]:
    kernels map without transpose (reference containers/gpt2.py
    HFGPT2LayerPolicy notes the same transposition quirk)."""

    model_type = "gpt2"

    def build(self, hf_cfg):
        from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2Model
        cfg = GPT2Config(vocab_size=hf_cfg["vocab_size"], n_positions=hf_cfg["n_positions"],
                         n_embd=hf_cfg["n_embd"], n_layer=hf_cfg["n_layer"],
                         n_head=hf_cfg["n_head"],
                         layer_norm_epsilon=hf_cfg.get("layer_norm_epsilon", 1e-5),
                         dtype=np.float32)
        return GPT2Model(cfg), cfg

    def convert(self, sd, hf_cfg):
        p = {"wte": {"embedding": np.asarray(sd["transformer.wte.weight"])},
             "wpe": {"embedding": np.asarray(sd["transformer.wpe.weight"])},
             "ln_f": _ln(sd, "transformer.ln_f")}
        for i in range(hf_cfg["n_layer"]):
            h = f"transformer.h.{i}"
            p[f"h_{i}"] = {
                "ln_1": _ln(sd, f"{h}.ln_1"),
                "c_attn": _dense(sd, f"{h}.attn.c_attn", transpose=False),
                "c_proj": _dense(sd, f"{h}.attn.c_proj", transpose=False),
                "ln_2": _ln(sd, f"{h}.ln_2"),
                "c_fc": _dense(sd, f"{h}.mlp.c_fc", transpose=False),
                "mlp_c_proj": _dense(sd, f"{h}.mlp.c_proj", transpose=False),
            }
        return p


class _DecoderPolicy(HFPolicy):
    """Shared convert for architectures mapped onto models/decoder.py."""

    def _layer_prefix(self, i):
        raise NotImplementedError

    def _convert_layer(self, sd, pfx, hf_cfg):
        raise NotImplementedError


@register_policy("opt")
class OPTPolicy(_DecoderPolicy):
    model_type = "opt"

    def build(self, hf_cfg):
        from deepspeed_tpu.models.decoder import DecoderConfig, DecoderModel
        # Reject variants whose tensor names/shapes match but whose math does
        # not (silent-wrong-logits hazard): post-layernorm OPT (opt-350m style
        # do_layer_norm_before=False) and projected embeddings
        # (word_embed_proj_dim != hidden_size, e.g. opt-350m's 512→1024).
        if not hf_cfg.get("do_layer_norm_before", True):
            raise NotImplementedError(
                "OPT with do_layer_norm_before=False (post-layernorm, opt-350m "
                "style) is not supported: DecoderConfig.opt builds a "
                "pre-layernorm block, so conversion would succeed and serve "
                "silently wrong logits.")
        if hf_cfg.get("word_embed_proj_dim", hf_cfg["hidden_size"]) != hf_cfg["hidden_size"]:
            raise NotImplementedError(
                "OPT with word_embed_proj_dim != hidden_size (projected "
                "embeddings, opt-350m style) is not supported by this "
                "container.")
        cfg = DecoderConfig.opt(
            vocab_size=hf_cfg["vocab_size"], hidden_size=hf_cfg["hidden_size"],
            intermediate_size=hf_cfg["ffn_dim"], num_hidden_layers=hf_cfg["num_hidden_layers"],
            num_attention_heads=hf_cfg["num_attention_heads"],
            num_key_value_heads=hf_cfg["num_attention_heads"],
            max_position_embeddings=hf_cfg["max_position_embeddings"], dtype=np.float32)
        return DecoderModel(cfg), cfg

    def convert(self, sd, hf_cfg):
        d = "model.decoder"
        wte = np.asarray(sd[f"{d}.embed_tokens.weight"])
        p = {"embed_tokens": {"embedding": wte},
             # HF stores the +2 offset rows IN the table; our config adds the
             # offset to the lookup index, so the table maps verbatim
             "embed_positions": {"embedding": np.asarray(sd[f"{d}.embed_positions.weight"])},
             "final_layer_norm": _ln(sd, f"{d}.final_layer_norm"),
             "lm_head": {"kernel": _t(wte)}}  # tied
        for i in range(hf_cfg["num_hidden_layers"]):
            l = f"{d}.layers.{i}"
            p[f"layers_{i}"] = {
                "input_layernorm": _ln(sd, f"{l}.self_attn_layer_norm"),
                "self_attn": {k: _dense(sd, f"{l}.self_attn.{k}")
                              for k in ("q_proj", "k_proj", "v_proj")} |
                             {"out_proj": _dense(sd, f"{l}.self_attn.out_proj")},
                "post_attention_layernorm": _ln(sd, f"{l}.final_layer_norm"),
                "mlp": {"fc1": _dense(sd, f"{l}.fc1"), "fc2": _dense(sd, f"{l}.fc2")},
            }
        return p


@register_policy("gpt_neox")
class GPTNeoXPolicy(_DecoderPolicy):
    model_type = "gpt_neox"

    def build(self, hf_cfg):
        from deepspeed_tpu.models.decoder import DecoderConfig, DecoderModel
        cfg = DecoderConfig.gpt_neox(
            vocab_size=hf_cfg["vocab_size"], hidden_size=hf_cfg["hidden_size"],
            intermediate_size=hf_cfg["intermediate_size"],
            num_hidden_layers=hf_cfg["num_hidden_layers"],
            num_attention_heads=hf_cfg["num_attention_heads"],
            num_key_value_heads=hf_cfg["num_attention_heads"],
            max_position_embeddings=hf_cfg["max_position_embeddings"],
            rotary_pct=hf_cfg.get("rotary_pct", 0.25),
            rope_theta=hf_cfg.get("rotary_emb_base", 10000),
            layer_norm_eps=hf_cfg.get("layer_norm_eps", 1e-5),
            parallel_residual=hf_cfg.get("use_parallel_residual", True), dtype=np.float32)
        return DecoderModel(cfg), cfg

    def convert(self, sd, hf_cfg):
        H = hf_cfg["num_attention_heads"]
        p = {"embed_tokens": {"embedding": np.asarray(sd["gpt_neox.embed_in.weight"])},
             "final_layer_norm": _ln(sd, "gpt_neox.final_layer_norm"),
             "lm_head": {"kernel": _t(sd["embed_out.weight"])}}  # NOT tied in neox
        for i in range(hf_cfg["num_hidden_layers"]):
            l = f"gpt_neox.layers.{i}"
            attn = _unfuse_headwise_qkv(sd[f"{l}.attention.query_key_value.weight"],
                                        sd.get(f"{l}.attention.query_key_value.bias"), H)
            attn["out_proj"] = _dense(sd, f"{l}.attention.dense")
            p[f"layers_{i}"] = {
                "input_layernorm": _ln(sd, f"{l}.input_layernorm"),
                "post_attention_layernorm": _ln(sd, f"{l}.post_attention_layernorm"),
                "self_attn": attn,
                "mlp": {"fc1": _dense(sd, f"{l}.mlp.dense_h_to_4h"),
                        "fc2": _dense(sd, f"{l}.mlp.dense_4h_to_h")},
            }
        return p


@register_policy("gptj")
class GPTJPolicy(_DecoderPolicy):
    model_type = "gptj"

    def build(self, hf_cfg):
        from deepspeed_tpu.models.decoder import DecoderConfig, DecoderModel
        n_embd = hf_cfg["n_embd"]
        head_dim = n_embd // hf_cfg["n_head"]
        act = {"gelu_new": "gelu", "gelu": "gelu_exact", "relu": "relu"}.get(
            hf_cfg.get("activation_function", "gelu_new"))
        if act is None:
            raise NotImplementedError(
                f"gptj activation_function={hf_cfg.get('activation_function')!r} has no "
                "mapped implementation — refusing to serve wrong logits")
        cfg = DecoderConfig.gptj(
            activation=act,
            vocab_size=hf_cfg["vocab_size"], hidden_size=n_embd,
            intermediate_size=hf_cfg.get("n_inner") or 4 * n_embd,
            num_hidden_layers=hf_cfg["n_layer"], num_attention_heads=hf_cfg["n_head"],
            num_key_value_heads=hf_cfg["n_head"],
            max_position_embeddings=hf_cfg["n_positions"],
            # HF default rotary_dim is 64; an explicit null means full-head
            rotary_pct=1.0 if hf_cfg.get("rotary_dim", 64) is None
            else hf_cfg.get("rotary_dim", 64) / head_dim,
            layer_norm_eps=hf_cfg.get("layer_norm_epsilon", 1e-5), dtype=np.float32)
        return DecoderModel(cfg), cfg

    def convert(self, sd, hf_cfg):
        p = {"embed_tokens": {"embedding": np.asarray(sd["transformer.wte.weight"])},
             "final_layer_norm": _ln(sd, "transformer.ln_f"),
             "lm_head": _dense(sd, "lm_head")}  # separate, biased
        for i in range(hf_cfg["n_layer"]):
            l = f"transformer.h.{i}"
            p[f"layers_{i}"] = {
                "input_layernorm": _ln(sd, f"{l}.ln_1"),
                "self_attn": {f"{nm}_proj": _dense(sd, f"{l}.attn.{nm}_proj")
                              for nm in ("q", "k", "v", "out")},
                "mlp": {"fc1": _dense(sd, f"{l}.mlp.fc_in"),
                        "fc2": _dense(sd, f"{l}.mlp.fc_out")},
            }
        return p


@register_policy("bloom")
class BloomPolicy(_DecoderPolicy):
    model_type = "bloom"

    def build(self, hf_cfg):
        from deepspeed_tpu.models.decoder import DecoderConfig, DecoderModel
        hidden = hf_cfg.get("hidden_size", hf_cfg.get("n_embed"))
        heads = hf_cfg.get("n_head", hf_cfg.get("num_attention_heads"))
        cfg = DecoderConfig.bloom(
            vocab_size=hf_cfg["vocab_size"], hidden_size=hidden,
            intermediate_size=4 * hidden,
            num_hidden_layers=hf_cfg.get("n_layer", hf_cfg.get("num_hidden_layers")),
            num_attention_heads=heads, num_key_value_heads=heads,
            max_position_embeddings=2048,
            layer_norm_eps=hf_cfg.get("layer_norm_epsilon", 1e-5), dtype=np.float32)
        return DecoderModel(cfg), cfg

    def convert(self, sd, hf_cfg):
        heads = hf_cfg.get("n_head", hf_cfg.get("num_attention_heads"))
        n_layer = hf_cfg.get("n_layer", hf_cfg.get("num_hidden_layers"))
        wte = np.asarray(sd["transformer.word_embeddings.weight"])
        p = {"embed_tokens": {"embedding": wte},
             "embed_layernorm": _ln(sd, "transformer.word_embeddings_layernorm"),
             "final_layer_norm": _ln(sd, "transformer.ln_f"),
             "lm_head": {"kernel": _t(wte)}}  # tied
        for i in range(n_layer):
            l = f"transformer.h.{i}"
            attn = _unfuse_headwise_qkv(sd[f"{l}.self_attention.query_key_value.weight"],
                                        sd.get(f"{l}.self_attention.query_key_value.bias"),
                                        heads)
            attn["out_proj"] = _dense(sd, f"{l}.self_attention.dense")
            p[f"layers_{i}"] = {
                "input_layernorm": _ln(sd, f"{l}.input_layernorm"),
                "post_attention_layernorm": _ln(sd, f"{l}.post_attention_layernorm"),
                "self_attn": attn,
                "mlp": {"fc1": _dense(sd, f"{l}.mlp.dense_h_to_4h"),
                        "fc2": _dense(sd, f"{l}.mlp.dense_4h_to_h")},
            }
        return p


@register_policy("bert")
class BertPolicy(HFPolicy):
    model_type = "bert"

    def build(self, hf_cfg):
        from deepspeed_tpu.models.bert import BertConfig, BertModel
        cfg = BertConfig(vocab_size=hf_cfg["vocab_size"], hidden_size=hf_cfg["hidden_size"],
                         num_hidden_layers=hf_cfg["num_hidden_layers"],
                         num_attention_heads=hf_cfg["num_attention_heads"],
                         intermediate_size=hf_cfg["intermediate_size"],
                         max_position_embeddings=hf_cfg["max_position_embeddings"],
                         type_vocab_size=hf_cfg.get("type_vocab_size", 2),
                         layer_norm_eps=hf_cfg.get("layer_norm_eps", 1e-12),
                         dtype=np.float32)
        return BertModel(cfg), cfg

    def convert(self, sd, hf_cfg):
        # checkpoints from BertModel have no prefix; BertFor* use "bert."
        pfx = "" if "embeddings.word_embeddings.weight" in sd else "bert."

        def k(name):
            return pfx + name

        e = "embeddings"
        p = {"word_embeddings": {"embedding": np.asarray(sd[k(f"{e}.word_embeddings.weight")])},
             "position_embeddings": {"embedding": np.asarray(sd[k(f"{e}.position_embeddings.weight")])},
             "token_type_embeddings": {"embedding": np.asarray(sd[k(f"{e}.token_type_embeddings.weight")])},
             "embeddings_layernorm": _ln(sd, k(f"{e}.LayerNorm")),
             "pooler": _dense(sd, k("pooler.dense"))}
        for i in range(hf_cfg["num_hidden_layers"]):
            l = k(f"encoder.layer.{i}")
            p[f"layer_{i}"] = {
                "attention": {nm: _dense(sd, f"{l}.attention.self.{nm}")
                              for nm in ("query", "key", "value")},
                "attention_output": _dense(sd, f"{l}.attention.output.dense"),
                "attention_layernorm": _ln(sd, f"{l}.attention.output.LayerNorm"),
                "intermediate": _dense(sd, f"{l}.intermediate.dense"),
                "output": _dense(sd, f"{l}.output.dense"),
                "output_layernorm": _ln(sd, f"{l}.output.LayerNorm"),
            }
        return p


@register_policy("llama")
class LlamaPolicy(HFPolicy):
    model_type = "llama"

    def build(self, hf_cfg):
        from deepspeed_tpu.models.llama import LlamaConfig, LlamaModel
        import jax.numpy as jnp
        cfg = LlamaConfig(vocab_size=hf_cfg["vocab_size"], hidden_size=hf_cfg["hidden_size"],
                          intermediate_size=hf_cfg["intermediate_size"],
                          num_hidden_layers=hf_cfg["num_hidden_layers"],
                          num_attention_heads=hf_cfg["num_attention_heads"],
                          num_key_value_heads=hf_cfg.get("num_key_value_heads",
                                                         hf_cfg["num_attention_heads"]),
                          max_position_embeddings=hf_cfg["max_position_embeddings"],
                          rope_theta=hf_cfg.get("rope_theta", 1e4),
                          rms_norm_eps=hf_cfg.get("rms_norm_eps", 1e-6), dtype=jnp.float32)
        return LlamaModel(cfg), cfg

    def convert(self, sd, hf_cfg):
        from deepspeed_tpu.models.llama import LlamaModel  # layout docs live there
        n = hf_cfg["num_hidden_layers"]
        p = {"embed_tokens": {"embedding": np.asarray(sd["model.embed_tokens.weight"])},
             "norm": {"weight": np.asarray(sd["model.norm.weight"])},
             "lm_head": {"kernel": _t(sd.get("lm_head.weight",
                                             sd["model.embed_tokens.weight"]))}}
        for i in range(n):
            l = f"model.layers.{i}"
            p[f"layers_{i}"] = {
                "input_layernorm": {"weight": np.asarray(sd[f"{l}.input_layernorm.weight"])},
                "post_attention_layernorm": {"weight": np.asarray(sd[f"{l}.post_attention_layernorm.weight"])},
                "self_attn": {nm: _dense(sd, f"{l}.self_attn.{nm}")
                              for nm in ("q_proj", "k_proj", "v_proj", "o_proj")},
                "mlp": {nm: _dense(sd, f"{l}.mlp.{nm}")
                        for nm in ("gate_proj", "up_proj", "down_proj")},
            }
        return p


@register_policy("gpt_neo")
class GPTNeoPolicy(_DecoderPolicy):
    """Reference containers/gptneo.py (HFGPTNEOLayerPolicy). Quirks mapped:
    UNSCALED attention scores, unbiased q/k/v with biased out_proj, and the
    alternating global/local (sliding-window) attention layer pattern."""

    model_type = "gpt_neo"

    @staticmethod
    def _expand_attention_types(hf_cfg):
        out = []
        for kinds, repeat in hf_cfg.get("attention_types", [[["global"], hf_cfg["num_layers"]]]):
            for _ in range(repeat):
                out.extend(kinds)
        if len(out) != hf_cfg["num_layers"]:
            raise ValueError(f"attention_types expands to {len(out)} entries "
                             f"for {hf_cfg['num_layers']} layers")
        return tuple(out[:hf_cfg["num_layers"]])

    def build(self, hf_cfg):
        from deepspeed_tpu.models.decoder import DecoderConfig, DecoderModel
        act = {"gelu_new": "gelu", "gelu": "gelu_exact", "relu": "relu"}.get(
            hf_cfg.get("activation_function", "gelu_new"))
        if act is None:
            raise NotImplementedError(
                f"gpt_neo activation_function={hf_cfg.get('activation_function')!r} has "
                "no mapped implementation — refusing to serve wrong logits")
        hidden = hf_cfg["hidden_size"]
        cfg = DecoderConfig.gpt_neo(
            activation=act,
            vocab_size=hf_cfg["vocab_size"], hidden_size=hidden,
            intermediate_size=hf_cfg.get("intermediate_size") or 4 * hidden,
            num_hidden_layers=hf_cfg["num_layers"],
            num_attention_heads=hf_cfg["num_heads"],
            num_key_value_heads=hf_cfg["num_heads"],
            max_position_embeddings=hf_cfg["max_position_embeddings"],
            layer_norm_eps=hf_cfg.get("layer_norm_epsilon", 1e-5),
            attention_layers=self._expand_attention_types(hf_cfg),
            window_size=hf_cfg.get("window_size", 256), dtype=np.float32)
        return DecoderModel(cfg), cfg

    def convert(self, sd, hf_cfg):
        wte = np.asarray(sd["transformer.wte.weight"])
        p = {"embed_tokens": {"embedding": wte},
             "embed_positions": {"embedding": np.asarray(sd["transformer.wpe.weight"])},
             "final_layer_norm": _ln(sd, "transformer.ln_f"),
             "lm_head": {"kernel": _t(wte)}}  # tied
        for i in range(hf_cfg["num_layers"]):
            l = f"transformer.h.{i}"
            p[f"layers_{i}"] = {
                "input_layernorm": _ln(sd, f"{l}.ln_1"),
                "self_attn": {k: _dense(sd, f"{l}.attn.attention.{k}")
                              for k in ("q_proj", "k_proj", "v_proj", "out_proj")},
                "post_attention_layernorm": _ln(sd, f"{l}.ln_2"),
                "mlp": {"fc1": _dense(sd, f"{l}.mlp.c_fc"),  # Linear: transpose
                        "fc2": _dense(sd, f"{l}.mlp.c_proj")},
            }
        return p


@register_policy("internlm")
class InternLMPolicy(HFPolicy):
    """Reference containers/internlm.py. InternLM-1 is the llama architecture
    with biases on all four attention projections (``bias: true``); the MLP
    stays unbiased gated-SiLU."""

    model_type = "internlm"

    def build(self, hf_cfg):
        from deepspeed_tpu.models.llama import LlamaConfig, LlamaModel
        import jax.numpy as jnp
        bias = bool(hf_cfg.get("bias", True))
        cfg = LlamaConfig(vocab_size=hf_cfg["vocab_size"], hidden_size=hf_cfg["hidden_size"],
                          intermediate_size=hf_cfg["intermediate_size"],
                          num_hidden_layers=hf_cfg["num_hidden_layers"],
                          num_attention_heads=hf_cfg["num_attention_heads"],
                          num_key_value_heads=hf_cfg.get("num_key_value_heads",
                                                         hf_cfg["num_attention_heads"]),
                          max_position_embeddings=hf_cfg["max_position_embeddings"],
                          rope_theta=hf_cfg.get("rope_theta", 1e4),
                          rms_norm_eps=hf_cfg.get("rms_norm_eps", 1e-6),
                          attention_bias=bias, attention_out_bias=bias,
                          dtype=jnp.float32)
        return LlamaModel(cfg), cfg

    def convert(self, sd, hf_cfg):
        # same tensor names as llama; _dense picks up the biases when present
        return _POLICIES["llama"].convert(sd, hf_cfg)


@register_policy("megatron_gpt")
@register_policy("megatron-gpt")
class MegatronGPTPolicy(HFPolicy):
    """Reference containers/megatron_gpt.py (MEGATRONLayerPolicy). Converts a
    Megatron-LM GPT checkpoint (``language_model.*`` naming, fused QKV whose
    layout depends on ``checkpoint_version`` — see
    runtime/state_dict_factory.py:16) onto the native GPT-2 module: the
    megatron-gpt2 architecture IS gpt2 (learned positions, tanh-gelu, scaled
    attention), only the storage differs (Linear [out,in] vs Conv1D
    [in,out]; sectioned vs per-head-interleaved QKV)."""

    model_type = "megatron_gpt"

    def build(self, hf_cfg):
        from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2Model
        cfg = GPT2Config(vocab_size=hf_cfg["padded_vocab_size"],
                         n_positions=hf_cfg["max_position_embeddings"],
                         n_embd=hf_cfg["hidden_size"], n_layer=hf_cfg["num_layers"],
                         n_head=hf_cfg["num_attention_heads"],
                         layer_norm_epsilon=hf_cfg.get("layernorm_epsilon", 1e-5),
                         dtype=np.float32)
        return GPT2Model(cfg), cfg

    @staticmethod
    def _qkv_to_sections(w, b, num_heads, ckpt_ver):
        """Fused QKV → gpt2 c_attn layout ([in, 3h], q|k|v sections).

        ver 0:   [(3*np*hn), h] — sections are already contiguous.
        ver 1.0: [(np*hn*3), h] — per head, q/k/v vary FASTEST ([np, hn, 3]).
        ver 2.0: [(np*3*hn), h] — per-head q|k|v blocks ([np, 3, hn]).
        (state_dict_factory.py:137 documents the same three layouts; silently
        applying the wrong one scrambles heads, so unknown versions raise.)"""
        w = np.asarray(w)
        three_h, hidden = w.shape
        D = three_h // (3 * num_heads)
        ver = float(ckpt_ver)
        if ver == 0:
            kernel = _t(w)
            bias = None if b is None else np.asarray(b)
        elif ver in (1.0, 2.0):
            if ver == 1.0:
                wr = np.moveaxis(w.reshape(num_heads, D, 3, hidden), 2, 1)
                br = None if b is None else np.moveaxis(
                    np.asarray(b).reshape(num_heads, D, 3), 2, 1)
            else:
                wr = w.reshape(num_heads, 3, D, hidden)
                br = None if b is None else np.asarray(b).reshape(num_heads, 3, D)
            kernel = _t(np.concatenate([wr[:, j].reshape(num_heads * D, hidden)
                                        for j in range(3)], axis=0))
            bias = None if br is None else np.concatenate(
                [br[:, j].reshape(num_heads * D) for j in range(3)])
        else:
            raise NotImplementedError(
                f"megatron checkpoint_version {ckpt_ver} fused-QKV layout unknown "
                "(supported: 0, 1.0, 2.0) — refusing to scramble heads")
        out = {"kernel": kernel}
        if bias is not None:
            out["bias"] = bias
        return out

    def convert(self, sd, hf_cfg):
        H = hf_cfg["num_attention_heads"]
        ver = hf_cfg.get("checkpoint_version", 0)
        lm = "language_model"
        # newer megatron nests layers under .encoder, older under .transformer
        enc = f"{lm}.encoder" if any(k.startswith(f"{lm}.encoder.") for k in sd) \
            else f"{lm}.transformer"
        p = {"wte": {"embedding": np.asarray(sd[f"{lm}.embedding.word_embeddings.weight"])},
             "wpe": {"embedding": np.asarray(sd[f"{lm}.embedding.position_embeddings.weight"])},
             "ln_f": _ln(sd, f"{enc}.final_layernorm")}
        for i in range(hf_cfg["num_layers"]):
            l = f"{enc}.layers.{i}"
            p[f"h_{i}"] = {
                "ln_1": _ln(sd, f"{l}.input_layernorm"),
                "c_attn": self._qkv_to_sections(
                    sd[f"{l}.attention.query_key_value.weight"],
                    sd.get(f"{l}.attention.query_key_value.bias"), H, ver),
                "c_proj": _dense(sd, f"{l}.attention.dense"),  # Linear: transpose
                "ln_2": _ln(sd, f"{l}.post_attention_layernorm"),
                "c_fc": _dense(sd, f"{l}.mlp.dense_h_to_4h"),
                "mlp_c_proj": _dense(sd, f"{l}.mlp.dense_4h_to_h"),
            }
        return p


@register_policy("distilbert")
class DistilBertPolicy(HFPolicy):
    """Reference containers/distil_bert.py (HFDistilBertLayerPolicy).
    DistilBERT = BERT minus token-type embeddings and pooler, with its own
    tensor naming (q_lin/k_lin/v_lin/out_lin, sa_layer_norm)."""

    model_type = "distilbert"

    def build(self, hf_cfg):
        from deepspeed_tpu.models.bert import BertConfig, BertModel
        cfg = BertConfig(vocab_size=hf_cfg["vocab_size"], hidden_size=hf_cfg["dim"],
                         num_hidden_layers=hf_cfg["n_layers"],
                         num_attention_heads=hf_cfg["n_heads"],
                         intermediate_size=hf_cfg["hidden_dim"],
                         max_position_embeddings=hf_cfg["max_position_embeddings"],
                         layer_norm_eps=1e-12,
                         use_token_type=False, use_pooler=False, dtype=np.float32)
        return BertModel(cfg), cfg

    def convert(self, sd, hf_cfg):
        pfx = "" if "embeddings.word_embeddings.weight" in sd else "distilbert."

        def k(name):
            return pfx + name

        p = {"word_embeddings": {"embedding": np.asarray(sd[k("embeddings.word_embeddings.weight")])},
             "position_embeddings": {"embedding": np.asarray(sd[k("embeddings.position_embeddings.weight")])},
             "embeddings_layernorm": _ln(sd, k("embeddings.LayerNorm"))}
        for i in range(hf_cfg["n_layers"]):
            l = k(f"transformer.layer.{i}")
            p[f"layer_{i}"] = {
                "attention": {"query": _dense(sd, f"{l}.attention.q_lin"),
                              "key": _dense(sd, f"{l}.attention.k_lin"),
                              "value": _dense(sd, f"{l}.attention.v_lin")},
                "attention_output": _dense(sd, f"{l}.attention.out_lin"),
                "attention_layernorm": _ln(sd, f"{l}.sa_layer_norm"),
                "intermediate": _dense(sd, f"{l}.ffn.lin1"),
                "output": _dense(sd, f"{l}.ffn.lin2"),
                "output_layernorm": _ln(sd, f"{l}.output_layer_norm"),
            }
        return p


@register_policy("clip_text_model")
@register_policy("clip")
class CLIPTextPolicy(HFPolicy):
    """Reference containers/clip.py (HFCLIPLayerPolicy). The piece a
    Stable-Diffusion pipeline injects is its text encoder — a
    ``CLIPTextModel`` checkpoint. A full dual-tower ``clip`` checkpoint
    loads its text tower (with a logged notice); the vision tower is not
    served by this policy."""

    model_type = "clip_text_model"

    @staticmethod
    def _text_cfg(hf_cfg):
        # full "clip" checkpoints nest the text tower under text_config
        return hf_cfg.get("text_config", hf_cfg)

    def build(self, hf_cfg):
        from deepspeed_tpu.models.clip import CLIPTextConfig, CLIPTextModel
        t = self._text_cfg(hf_cfg)
        if hf_cfg.get("model_type") == "clip":
            logger.warning("clip checkpoint: serving the TEXT tower only "
                           "(the diffusion-serving role of this container)")
        cfg = CLIPTextConfig(vocab_size=t["vocab_size"], hidden_size=t["hidden_size"],
                             intermediate_size=t["intermediate_size"],
                             num_hidden_layers=t["num_hidden_layers"],
                             num_attention_heads=t["num_attention_heads"],
                             max_position_embeddings=t["max_position_embeddings"],
                             layer_norm_eps=t.get("layer_norm_eps", 1e-5),
                             hidden_act=t.get("hidden_act", "quick_gelu"),
                             eos_token_id=t.get("eos_token_id", 49407),
                             dtype=np.float32)
        return CLIPTextModel(cfg), cfg

    def key_filter(self, hf_cfg):
        # skip the vision tower's I/O entirely on full dual-tower checkpoints
        return lambda k: k.startswith("text_model.")

    def convert(self, sd, hf_cfg):
        t = self._text_cfg(hf_cfg)
        tm = "text_model"
        p = {"token_embedding": {"embedding":
                                 np.asarray(sd[f"{tm}.embeddings.token_embedding.weight"])},
             "position_embedding": {"embedding":
                                    np.asarray(sd[f"{tm}.embeddings.position_embedding.weight"])},
             "final_layer_norm": _ln(sd, f"{tm}.final_layer_norm")}
        for i in range(t["num_hidden_layers"]):
            l = f"{tm}.encoder.layers.{i}"
            p[f"layers_{i}"] = {
                "layer_norm1": _ln(sd, f"{l}.layer_norm1"),
                "self_attn": {k: _dense(sd, f"{l}.self_attn.{k}")
                              for k in ("q_proj", "k_proj", "v_proj", "out_proj")},
                "layer_norm2": _ln(sd, f"{l}.layer_norm2"),
                "fc1": _dense(sd, f"{l}.mlp.fc1"),
                "fc2": _dense(sd, f"{l}.mlp.fc2"),
            }
        return p


# diffusers spatial models the reference serves with csrc/spatial CUDA
# kernels + diffusers containers (unet.py, vae.py). Rejected HERE, loudly:
# on TPU the convs/attention of a UNet lower straight onto the MXU through
# XLA — there is no custom-kernel gap to fill — but a faithful UNet/VAE
# module library is image-pipeline surface this LLM-serving-focused build
# does not provide. The text-encoder half of a diffusion pipeline IS
# served (CLIPTextPolicy above).
def _reject_diffusion_checkpoint(path: str, hf_cfg: Optional[dict]) -> None:
    if os.path.exists(os.path.join(path, "model_index.json")):
        raise NotImplementedError(
            f"{path} is a diffusers PIPELINE checkpoint (model_index.json). "
            "The diffusion/spatial tier (reference csrc/spatial + "
            "module_inject/containers/{unet,vae}.py) is not implemented: on "
            "TPU the UNet/VAE convs need no custom kernels (XLA lowers them "
            "onto the MXU), and this build serves the LLM tier. The "
            "pipeline's text_encoder/ subdirectory (CLIPTextModel) IS "
            "supported — point init_inference at it directly.")
    # diffusers model configs carry _class_name and no model_type;
    # transformers configs always carry model_type — keying on the generic
    # marker covers UNet2DConditionModel, AutoencoderKL, Transformer2DModel,
    # ControlNet, and every future diffusers class alike
    if hf_cfg is not None and "model_type" not in hf_cfg and "_class_name" in hf_cfg:
        raise NotImplementedError(
            f"{path} is a diffusers {hf_cfg['_class_name']} checkpoint. The "
            "diffusion/spatial tier is not implemented (see the "
            "model_index.json rejection for the rationale); only the CLIP "
            "text encoder of a diffusion pipeline is served.")


# ------------------------------------------------------------------ loading --
def _load_hf_state_dict(path: str, key_filter=None) -> Dict[str, np.ndarray]:
    """Read a HF checkpoint dir's tensors as numpy (safetensors or torch bin).

    ``key_filter(name) -> bool`` loads only matching tensors — policies that
    serve one tower of a multi-tower checkpoint (CLIP text) skip the other
    tower's I/O and host memory; unmatched shards are never opened."""
    keep = key_filter or (lambda k: True)
    st = os.path.join(path, "model.safetensors")
    if os.path.exists(st):
        from safetensors import safe_open
        with safe_open(st, framework="numpy") as f:
            return {k: f.get_tensor(k) for k in f.keys() if keep(k)}
    idx = os.path.join(path, "model.safetensors.index.json")
    if os.path.exists(idx):  # sharded safetensors (HF default over ~5 GB)
        from safetensors import safe_open
        with open(idx) as f:
            weight_map = json.load(f)["weight_map"]
        by_shard: Dict[str, list] = {}
        for name, shard in weight_map.items():
            if keep(name):
                by_shard.setdefault(shard, []).append(name)
        sd = {}
        for shard, names in sorted(by_shard.items()):
            with safe_open(os.path.join(path, shard), framework="numpy") as f:
                for name in names:
                    sd[name] = f.get_tensor(name)
        return sd
    bins = [f for f in os.listdir(path) if f.startswith("pytorch_model") and f.endswith(".bin")]
    if not bins:
        raise FileNotFoundError(
            f"no model.safetensors, model.safetensors.index.json (sharded "
            f"safetensors), or pytorch_model*.bin under {path}")
    import torch
    sd = {}
    for b in sorted(bins):
        for name, t in torch.load(os.path.join(path, b), map_location="cpu",
                                  weights_only=True).items():
            if keep(name):
                sd[name] = t.float().numpy() if t.dtype.is_floating_point else t.numpy()
    return sd


def load_hf_checkpoint(path: str) -> Tuple[Any, Any, dict]:
    """HF checkpoint dir (config.json + weights) → (flax module, params, cfg).

    The end-to-end entry the reference reaches through ``replace_module``:
    detect the architecture from config.json, build the native module, convert
    the weights. ``deepspeed_tpu.init_inference(checkpoint=...)`` calls this.
    """
    cfg_file = os.path.join(path, "config.json")
    hf_cfg = None
    if os.path.exists(cfg_file):
        with open(cfg_file) as f:
            hf_cfg = json.load(f)
    _reject_diffusion_checkpoint(path, hf_cfg)
    if hf_cfg is None:
        raise FileNotFoundError(f"no config.json under {path}")
    model_type = hf_cfg.get("model_type")
    policy = _POLICIES.get(model_type)
    if policy is None:
        raise NotImplementedError(
            f"no injection policy for model_type={model_type!r}; "
            f"supported: {supported_model_types()}")
    sd = _load_hf_state_dict(path, key_filter=policy.key_filter(hf_cfg))
    module, cfg = policy.build(hf_cfg)
    params = policy.convert(sd, hf_cfg)
    logger.info(f"loaded {model_type} checkpoint from {path}: "
                f"{len(sd)} HF tensors -> {len(params)} top-level tree entries")
    return module, params, cfg
