"""AutoTP: structural TP-spec derivation (reference module_inject/auto_tp.py:188
+ tests/unit/model_parallelism)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import jax.tree_util as jtu
from jax.sharding import PartitionSpec as P

import deepspeed_tpu
from deepspeed_tpu.models.gpt2 import GPT2Config, init_params as gpt2_init
from deepspeed_tpu.models.llama import LlamaConfig, init_params as llama_init
from deepspeed_tpu.models.mixtral import MixtralConfig, init_params as mixtral_init
from deepspeed_tpu.module_inject.auto_tp import auto_tp_specs
from deepspeed_tpu.utils import groups


def _by_path(specs):
    return {jtu.keystr(k): v for k, v in jtu.tree_flatten_with_path(specs)[0]}


def test_llama_matches_hand_written():
    """VERDICT r3 'done' criterion: auto specs == the (former) hand-written
    llama mapping, leaf for leaf."""
    _, params = llama_init(LlamaConfig.tiny(dtype=jnp.float32))
    got = _by_path(auto_tp_specs(params))

    COL = {"q_proj", "k_proj", "v_proj", "gate_proj", "up_proj", "lm_head"}
    ROW = {"o_proj", "down_proj"}
    for path, spec in got.items():
        if "embedding" in path:
            assert spec == P(None, "model"), path
        elif any(f"'{n}'" in path for n in COL) and "kernel" in path:
            assert spec == P(None, "model"), path
        elif any(f"'{n}'" in path for n in ROW) and "kernel" in path:
            assert spec == P("model", None), path
        else:
            assert spec == P(), path


def test_mixtral_expert_banks_and_attention():
    _, params = mixtral_init(MixtralConfig.tiny(dtype=jnp.float32))
    got = _by_path(auto_tp_specs(params))
    assert got["['layers_0']['block_sparse_moe']['ExpertFFN_0']['wi']"] == P("expert", None, None)
    assert got["['layers_0']['block_sparse_moe']['ExpertFFN_0']['wo']"] == P("expert", None, None)
    # router gate must NOT be TP-sharded (its output dim is num_experts)
    assert got["['layers_0']['block_sparse_moe']['gate']"] == P()
    assert got["['layers_0']['self_attn']['o_proj']['kernel']"] == P("model", None)
    assert got["['layers_0']['self_attn']['q_proj']['kernel']"] == P(None, "model")


def test_gpt2_flat_blocks():
    """GPT-2 keeps attention and MLP pairs in ONE flat dict per layer; the
    segment scan must find both all-reduce linears."""
    _, params = gpt2_init(GPT2Config.tiny(dtype=jnp.float32))
    got = _by_path(auto_tp_specs(params))
    assert got["['h_0']['c_attn']['kernel']"] == P(None, "model")
    assert got["['h_0']['c_proj']['kernel']"] == P("model", None)
    assert got["['h_0']['c_fc']['kernel']"] == P(None, "model")
    assert got["['h_0']['mlp_c_proj']['kernel']"] == P("model", None)
    assert got["['wte']['embedding']"] == P(None, "model")


@pytest.mark.parametrize("model_name", ["llama", "gpt2"])
def test_tp_training_parity(model_name):
    """Training with auto-derived TP specs on a model=2 mesh must match the
    unsharded run (the reference's configurable-parallelism resize tests)."""
    if model_name == "llama":
        cfg = LlamaConfig.tiny(dtype=jnp.float32)
        from deepspeed_tpu.models.llama import LlamaForCausalLM as Model
    else:
        cfg = GPT2Config.tiny(dtype=jnp.float32)
        from deepspeed_tpu.models.gpt2 import GPT2LMHeadModel as Model
    model = Model(cfg)
    rng = np.random.default_rng(0)
    ids = rng.integers(0, cfg.vocab_size, size=(8, 16)).astype(np.int32)
    batch = (ids, ids.copy())

    # gpt2's replicated c_attn bias gets near-zero grads whose Adam updates are
    # sign-sensitive to reduction order; SGD keeps that leg's comparison tight
    # while llama covers the adaptive-optimizer path (fwd loss is bit-equal in
    # both — verified when this test was introduced).
    opt = {"type": "AdamW", "params": {"lr": 1e-3}} if model_name == "llama" \
        else {"type": "sgd", "params": {"lr": 1e-2}}
    ds_cfg = {"train_micro_batch_size_per_gpu": 2,
              "optimizer": opt,
              "zero_optimization": {"stage": 0}}

    groups.initialize_mesh(force=True)
    _, params0 = (llama_init(cfg) if model_name == "llama" else gpt2_init(cfg))
    ref, _, _, _ = deepspeed_tpu.initialize(model=model, model_parameters=params0, config=ds_cfg)
    for _ in range(2):
        ref.train_batch(batch=batch)

    groups.initialize_mesh(model_parallel_size=2, force=True)
    eng, _, _, _ = deepspeed_tpu.initialize(model=model, model_parameters=params0, config=ds_cfg,
                                            param_specs=auto_tp_specs(params0))
    sharded = [l for l in jax.tree.leaves(eng.params) if not l.sharding.is_fully_replicated]
    assert sharded, "TP specs must actually shard parameters"
    for _ in range(2):
        eng.train_batch(batch=batch)

    for a, b in zip(jax.tree.leaves(jax.device_get(eng.params)),
                    jax.tree.leaves(jax.device_get(ref.params))):
        np.testing.assert_allclose(a, b, rtol=2e-4, atol=2e-5)
