"""Per-chip peak specs for the roofline model.

Public-datasheet numbers (per chip, bf16 dense peak; HBM and ICI are
aggregate per-chip bandwidths). These feed :mod:`deepspeed_tpu.perf.roofline`
to turn HLO-level facts into predicted step times — the specs are the only
chip-dependent piece of the perf-gate subsystem, so a new chip generation is
one table row, not a new gate.
"""

from dataclasses import dataclass
from typing import Dict


@dataclass(frozen=True)
class ChipSpec:
    name: str
    peak_bf16_flops: float      # dense bf16 FLOP/s per chip
    hbm_bytes_per_s: float      # HBM bandwidth per chip
    hbm_bytes: float            # HBM capacity per chip
    ici_bytes_per_s: float      # aggregate inter-chip interconnect bandwidth
    notes: str = ""


CHIP_SPECS: Dict[str, ChipSpec] = {
    # the deployment target (BASELINE.json: v5e-1 ZeRO-3 Llama SFT)
    "v5e": ChipSpec("v5e", peak_bf16_flops=197e12, hbm_bytes_per_s=819e9,
                    hbm_bytes=16 * 2**30, ici_bytes_per_s=2 * 200e9 / 2,
                    notes="v5litepod; 1600 Gbps ICI aggregate (200 GB/s, counted one-way)"),
    "v5p": ChipSpec("v5p", peak_bf16_flops=459e12, hbm_bytes_per_s=2765e9,
                    hbm_bytes=95 * 2**30, ici_bytes_per_s=600e9),
    "v4": ChipSpec("v4", peak_bf16_flops=275e12, hbm_bytes_per_s=1228e9,
                   hbm_bytes=32 * 2**30, ici_bytes_per_s=300e9),
    "v6e": ChipSpec("v6e", peak_bf16_flops=918e12, hbm_bytes_per_s=1640e9,
                    hbm_bytes=32 * 2**30, ici_bytes_per_s=448e9,
                    notes="trillium"),
    # CPU smoke entry so roofline math is exercisable in tests without
    # pretending the numbers mean anything about a TPU
    "cpu-host": ChipSpec("cpu-host", peak_bf16_flops=1e12, hbm_bytes_per_s=100e9,
                         hbm_bytes=64 * 2**30, ici_bytes_per_s=10e9,
                         notes="placeholder host spec for tests"),
}

DEFAULT_CHIP = "v5e"


def get_chip_spec(name: str = DEFAULT_CHIP) -> ChipSpec:
    try:
        return CHIP_SPECS[name]
    except KeyError:
        raise KeyError(f"unknown chip {name!r}; known: {sorted(CHIP_SPECS)}") from None
