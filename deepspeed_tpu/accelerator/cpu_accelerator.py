"""CPU (host XLA) accelerator — used by the test harness via a virtual N-device mesh.

Reference shape: ``accelerator/cpu_accelerator.py:18``. All JAX semantics are shared
with the TPU implementation; only identity and dtype preferences differ.
"""

from deepspeed_tpu.accelerator.tpu_accelerator import TPU_Accelerator


class CPU_Accelerator(TPU_Accelerator):

    def __init__(self):
        super().__init__()
        self._name = "cpu"
        self._communication_backend_name = "xla"

    def device_name(self, device_index=None):
        if device_index is None:
            return "cpu"
        return f"cpu:{device_index}"

    def current_device_name(self):
        return "cpu:0"

    def is_bf16_supported(self):
        return True

    def is_fp16_supported(self):
        return True

    def total_memory(self, device_index=None):
        try:
            with open("/proc/meminfo") as f:
                for line in f:
                    if line.startswith("MemTotal"):
                        return int(line.split()[1]) * 1024
        except Exception:
            pass
        return 0

    def available_memory(self, device_index=None):
        try:
            with open("/proc/meminfo") as f:
                for line in f:
                    if line.startswith("MemAvailable"):
                        return int(line.split()[1]) * 1024
        except Exception:
            pass
        return 0

    def op_builder_dir(self):
        # The Pallas/XLA op tier runs on host XLA too (interpret mode for Pallas).
        return "deepspeed_tpu.op_builder.tpu"
