"""Test model zoo.

Reference: ``tests/unit/simple_model.py`` (SimpleModel:19, SimpleMoEModel:79,
random_dataloader:272). Models are tiny flax modules whose apply returns the loss.
"""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np


class SimpleModel(nn.Module):
    """Reference SimpleModel: linear stack + cross-entropy-ish loss; here an MLP
    regression so the loss is smooth and deterministic."""
    hidden_dim: int = 16
    nlayers: int = 2

    @nn.compact
    def __call__(self, batch):
        x, y = batch
        for _ in range(self.nlayers):
            x = nn.Dense(self.hidden_dim)(x)
            x = nn.relu(x)
        x = nn.Dense(1)(x)
        return jnp.mean((x.squeeze(-1) - y)**2)


def make_simple_model(hidden_dim=16, nlayers=2, seed=0, batch_size=8):
    model = SimpleModel(hidden_dim=hidden_dim, nlayers=nlayers)
    x = jnp.ones((batch_size, hidden_dim))
    y = jnp.ones((batch_size, ))
    params = model.init(jax.random.PRNGKey(seed), (x, y))["params"]
    return model, params


def random_dataset(total_samples, hidden_dim, seed=123):
    rng = np.random.default_rng(seed)
    xs = rng.normal(size=(total_samples, hidden_dim)).astype(np.float32)
    w = rng.normal(size=(hidden_dim, )).astype(np.float32)
    ys = (xs @ w).astype(np.float32)
    return [(xs[i], ys[i]) for i in range(total_samples)]


def random_batches(n_batches, batch_size, hidden_dim, seed=123):
    rng = np.random.default_rng(seed)
    out = []
    w = rng.normal(size=(hidden_dim, )).astype(np.float32)
    for _ in range(n_batches):
        x = rng.normal(size=(batch_size, hidden_dim)).astype(np.float32)
        out.append((x, (x @ w).astype(np.float32)))
    return out
