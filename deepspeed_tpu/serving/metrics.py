"""Serving telemetry on the unified registry (``deepspeed_tpu/telemetry``).

Zero-cost-when-disabled contract: ``ServingMetrics.maybe_create()`` returns
None unless a telemetry session is active, and every scheduler call site is
guarded by that None check — the disabled hot path performs no registry work
(the same unit-enforceable guarantee the engine and comm layers give).
"""

from typing import Optional

# TTFT/e2e live in the default latency decades; inter-token latency needs the
# sub-millisecond end emphasized (a fast decode step is ~100us-10ms)
_ITL_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                0.5, 1.0, 2.5)


class ServingMetrics:
    """The serving-layer metric family; one instance per scheduler."""

    def __init__(self, registry):
        self.queue_depth = registry.gauge(
            "serving_queue_depth", "Requests waiting for admission")
        self.in_flight = registry.gauge(
            "serving_in_flight_requests", "Requests in PREFILL or DECODE")
        self.ttft = registry.histogram(
            "serving_ttft_seconds", "Submission to first generated token")
        self.itl = registry.histogram(
            "serving_inter_token_seconds", "Gap between consecutive streamed tokens",
            buckets=_ITL_BUCKETS)
        self.e2e = registry.histogram(
            "serving_e2e_latency_seconds", "Submission to terminal state")
        self.admissions = registry.counter(
            "serving_admissions_total", "Requests accepted into the queue")
        self.rejections = registry.counter(
            "serving_rejections_total", "Requests rejected by backpressure")
        self.completions = registry.counter(
            "serving_completions_total", "Requests finished DONE")
        self.timeouts = registry.counter(
            "serving_timeouts_total", "Requests that hit their deadline")
        self.cancellations = registry.counter(
            "serving_cancellations_total", "Requests cancelled mid-flight")
        self.failures = registry.counter(
            "serving_failures_total", "Requests that FAILED")
        self.evictions = registry.counter(
            "serving_kv_evictions_total", "Idle sequences offloaded under KV pressure")
        # automatic prefix cache (inference/v2/ragged/prefix_cache.py)
        self.prefix_lookups = registry.counter(
            "serving_prefix_lookups_total", "Admitted prompts looked up in the prefix trie")
        self.prefix_hits = registry.counter(
            "serving_prefix_hits_total", "Admitted prompts served a cached prefix")
        self.prefix_lookup_depth = registry.histogram(
            "serving_prefix_lookup_depth_blocks",
            "Cached-prefix depth (KV blocks) applied per lookup",
            buckets=(0, 1, 2, 4, 8, 16, 32, 64, 128, 256))
        self.prefix_tokens_saved = registry.counter(
            "serving_prefix_tokens_saved_total",
            "Prompt tokens served from cached KV instead of prefilled")
        self.prefix_trie_blocks = registry.gauge(
            "serving_prefix_trie_blocks", "Device KV blocks pinned by the prefix trie")
        self.prefix_evictions = registry.counter(
            "serving_prefix_evictions_total",
            "Prefix-trie leaves evicted (LRU) under KV pressure or the trie cap")
        # speculative decoding (inference/v2/spec/ + the scheduler's verify
        # execute path)
        self.spec_drafted = registry.counter(
            "serving_spec_draft_tokens_total",
            "Draft tokens proposed into speculative verify feeds")
        self.spec_accepted = registry.counter(
            "serving_spec_accepted_tokens_total",
            "Draft tokens the target model's verify step accepted")
        self.spec_verify_steps = registry.counter(
            "serving_spec_verify_steps_total",
            "Decode dispatches that carried at least one draft token")
        self.spec_rollback = registry.counter(
            "serving_spec_rollback_tokens_total",
            "Rejected draft positions truncated from committed KV (write-then-truncate)")
        self.spec_accept_rate = registry.gauge(
            "serving_spec_accept_rate",
            "EWMA of the speculative acceptance rate across verify steps")
        self.spec_tokens_per_step = registry.histogram(
            "serving_spec_tokens_per_step",
            "Tokens emitted per speculative verify step (1 = nothing accepted)",
            buckets=(1, 2, 3, 4, 6, 8, 12, 16))
        # token-tree verification + drafter arbitration (learned/auto modes)
        self.spec_tree_nodes = registry.counter(
            "serving_spec_tree_nodes_total",
            "Token-tree nodes fed through verify_tree dispatches (root included)")
        self.spec_tree_accept_depth = registry.histogram(
            "serving_spec_tree_accept_depth",
            "Accepted path depth per tree-verify step (0 = root only survived)",
            buckets=(0, 1, 2, 3, 4, 6, 8))
        self.spec_tree_compactions = registry.counter(
            "serving_spec_tree_compactions_total",
            "Tree-verify steps whose accepted path needed a KV gather-compact "
            "(non-chain acceptance)")
        self.spec_drafter_switches = registry.counter(
            "serving_spec_drafter_switches_total",
            "Per-request drafter changes decided by the auto arbitration")
        self.spec_drafter_learned_ewma = registry.gauge(
            "serving_spec_drafter_learned_ewma",
            "EWMA of the learned drafter's accepted-depth rate across requests")
        self.spec_drafter_lookup_ewma = registry.gauge(
            "serving_spec_drafter_lookup_ewma",
            "EWMA of the prompt-lookup drafter's accepted-depth rate across requests")
        # overload control (serving/overload.py + scheduler admission/shed)
        self.shed_admission = registry.counter(
            "serving_shed_admission_total",
            "Requests rejected at admission: deadline provably unmeetable")
        self.shed_queue = registry.counter(
            "serving_shed_queue_total",
            "Queued requests shed under sustained overload pressure")
        self.brownout_stage = registry.gauge(
            "serving_brownout_stage",
            "Current brownout degradation stage (0 = normal service)")
        self.brownout_transitions = registry.counter(
            "serving_brownout_transitions_total",
            "Brownout stage changes (hysteresis-smoothed)")
        self.brownout_clamped = registry.counter(
            "serving_brownout_clamped_total",
            "Batch-class requests whose max_new_tokens was brownout-clamped")
        self.brownout_rejections = registry.counter(
            "serving_brownout_rejections_total",
            "Batch-class requests rejected outright at brownout stage 3")
        self.fair_share_sheds = registry.counter(
            "serving_fair_share_sheds_total",
            "Requests shed/429'd by the fair-share stage (tenant over measured "
            "share under pressure)")
        # tiered KV memory (inference/v2/ragged/tiering.py + serving/kv_tiers.py)
        self.kv_tier_demotions = registry.counter(
            "serving_kv_tier_demotions_total",
            "KV blocks demoted device->host under pressure (trie + eviction path)")
        self.kv_tier_disk_demotions = registry.counter(
            "serving_kv_tier_disk_demotions_total",
            "Offloaded sessions demoted host->disk (coldest first)")
        self.kv_tier_promotions = registry.counter(
            "serving_kv_tier_promotions_total",
            "Demoted trie nodes promoted back to device on a prefix hit")
        self.kv_tier_device_blocks = registry.gauge(
            "serving_kv_tier_device_blocks", "KV blocks resident on device")
        self.kv_tier_host_blocks = registry.gauge(
            "serving_kv_tier_host_blocks", "KV blocks resident in the host tier")
        self.kv_tier_disk_blocks = registry.gauge(
            "serving_kv_tier_disk_blocks", "KV blocks resident in spill files on disk")

    @classmethod
    def maybe_create(cls) -> Optional["ServingMetrics"]:
        from deepspeed_tpu import telemetry
        if not telemetry.is_active():
            return None
        return cls(telemetry.get_registry())
