"""Elastic agent: supervise a launched job, shrink and restart on failure.

Reference: ``deepspeed/elasticity/elastic_agent.py`` (DSElasticAgent:28 — a
torch-elastic LocalElasticAgent subclass that restarts worker groups on
membership change, re-rendezvousing through the store).

TPU formulation: JAX's coordination service fixes world membership at
``jax.distributed.initialize``, so recovery is restart-shaped by construction —
exactly what this agent does. It spawns the per-process group, watches exits,
and on failure kills the stragglers, recomputes a *valid* world size from the
elasticity config (v0.1 batch math — the set of chip counts that keep the
global batch constant), and relaunches with ``DSTPU_NUM_PROCESSES`` shrunk to
the nearest valid size ≤ the surviving capacity.
"""

import os
import signal
import subprocess
import time
from typing import Callable, Dict, List, Optional

from deepspeed_tpu.elasticity.elasticity import compute_elastic_config
from deepspeed_tpu.utils.logging import logger


class ElasticAgentError(RuntimeError):
    pass


class DSElasticAgent:

    def __init__(self, cmd: List[str], num_processes: int, ds_config: Optional[dict] = None,
                 env: Optional[Dict[str, str]] = None, max_restarts: int = 3,
                 monitor_interval: float = 0.5,
                 capacity_fn: Optional[Callable[[], int]] = None,
                 restart_backoff_base_s: float = 0.0,
                 restart_backoff_cap_s: float = 30.0,
                 restart_jitter_frac: float = 0.1, seed: int = 0):
        """``cmd`` is launched once per process with DSTPU_NUM_PROCESSES /
        DSTPU_PROCESS_ID exported (the contract ``comm.init_distributed``
        reads). ``capacity_fn`` reports how many processes can be spawned for
        the next attempt (defaults to the last world size — a failed process is
        assumed recoverable; pass a probe for real node-loss handling).
        ``restart_backoff_base_s`` > 0 spaces restarts with the fleet's shared
        bounded-jitter ``backoff_delay`` policy (0 = immediate, the legacy
        behavior)."""
        self.cmd = list(cmd)
        self.num_processes = int(num_processes)
        self.ds_config = ds_config or {}
        self.env = dict(env if env is not None else os.environ)
        self.max_restarts = int(max_restarts)
        self.monitor_interval = monitor_interval
        self.capacity_fn = capacity_fn
        self.restart_count = 0
        self.restart_backoff_base_s = float(restart_backoff_base_s)
        self.restart_backoff_cap_s = float(restart_backoff_cap_s)
        self.restart_jitter_frac = float(restart_jitter_frac)
        import random as _random
        self._backoff_rng = _random.Random(f"{seed}:elastic_agent")

    # -- world-size policy -------------------------------------------------------
    def next_world_size(self, capacity: int) -> int:
        """Largest elasticity-valid world size ≤ capacity (or capacity itself
        when elasticity is off)."""
        if not self.ds_config.get("elasticity", {}).get("enabled", False):
            if capacity < 1:
                raise ElasticAgentError("no capacity left to restart into")
            return capacity
        _, valid = compute_elastic_config(self.ds_config)
        fitting = [n for n in valid if n <= capacity]
        if not fitting:
            raise ElasticAgentError(
                f"no elasticity-valid world size fits the surviving capacity {capacity} "
                f"(valid: {sorted(valid)[:10]}...)")
        return max(fitting)

    # -- process control ---------------------------------------------------------
    def _spawn(self, world_size: int) -> List[subprocess.Popen]:
        procs = []
        for rank in range(world_size):
            env = dict(self.env)
            env["DSTPU_NUM_PROCESSES"] = str(world_size)
            env["DSTPU_PROCESS_ID"] = str(rank)
            env["DSTPU_ELASTIC_RESTART"] = str(self.restart_count)
            # the training chaos injector keys its one-shot kill/sigterm
            # points on this (runtime/faults.first_life) — without it a
            # deterministic kill replays on every relaunch and crash-loops
            env["DSTPU_RESTART_COUNT"] = str(self.restart_count)
            procs.append(subprocess.Popen(self.cmd, env=env))
        return procs

    @staticmethod
    def _kill(procs: List[subprocess.Popen]):
        for p in procs:
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        deadline = time.time() + 5
        for p in procs:
            try:
                p.wait(timeout=max(0.1, deadline - time.time()))
            except subprocess.TimeoutExpired:
                p.kill()

    def _monitor(self, procs: List[subprocess.Popen]) -> bool:
        """True = clean exit of every process; False = a failure occurred."""
        while True:
            codes = [p.poll() for p in procs]
            if any(c not in (None, 0) for c in codes):
                self._kill(procs)
                return False
            if all(c == 0 for c in codes):
                return True
            time.sleep(self.monitor_interval)

    # -- main loop ---------------------------------------------------------------
    def run(self) -> int:
        world = self.num_processes
        while True:
            logger.info(f"elastic agent: launching world_size={world} "
                        f"(attempt {self.restart_count + 1})")
            procs = self._spawn(world)
            if self._monitor(procs):
                logger.info("elastic agent: job finished cleanly")
                return 0
            self.restart_count += 1
            if self.restart_count > self.max_restarts:
                raise ElasticAgentError(f"job failed after {self.max_restarts} restarts")
            capacity = self.capacity_fn() if self.capacity_fn is not None else world
            world = self.next_world_size(capacity)
            delay = 0.0
            if self.restart_backoff_base_s > 0.0:
                # the fleet's one backoff formula (fleet/breaker.backoff_delay):
                # exponential, capped, bounded jitter, deterministic in seed
                from deepspeed_tpu.fleet.breaker import backoff_delay
                delay = backoff_delay(self.restart_count - 1,
                                      self.restart_backoff_base_s,
                                      self.restart_backoff_cap_s,
                                      self.restart_jitter_frac,
                                      self._backoff_rng.random())
            logger.warning(f"elastic agent: worker failed; restarting with "
                           f"world_size={world} (capacity {capacity}"
                           f"{f', backoff {delay:.2f}s' if delay else ''})")
            if delay:
                time.sleep(delay)
