"""Pipeline schedules as instruction streams.

Reference: ``deepspeed/runtime/pipe/schedule.py`` (PipeSchedule, InferenceSchedule:135,
TrainSchedule:189 — 1F1B, DataParallelSchedule:301; PipeInstruction command objects).

On TPU the *execution* of a schedule is a jitted scan with ppermute (XLA overlaps
compute and stage transfers itself; see pipe/engine.py), but the instruction-stream
generators are kept with reference semantics: they document and test the 1F1B
ordering, and drive the host-level fallback executor.
"""

from abc import ABC, abstractmethod


class PipeSchedule(ABC):
    """Reference schedule.py PipeSchedule: yields lists of PipeInstruction per step."""

    def __init__(self, micro_batches, stages, stage_id):
        self.micro_batches = micro_batches
        self.stages = stages
        self.stage_id = stage_id
        self.prev_stage = self.stage_id - 1
        self.next_stage = self.stage_id + 1

    @abstractmethod
    def steps(self):
        ...

    def num_pipe_buffers(self):
        return self.micro_batches

    def _valid_micro_batch(self, micro_batch_id):
        return 0 <= micro_batch_id < self.micro_batches

    def _valid_stage(self, stage_id):
        return 0 <= stage_id < self.stages

    @property
    def stage(self):
        return self.stage_id

    @property
    def num_stages(self):
        return self.stages

    @property
    def num_micro_batches(self):
        return self.micro_batches

    @property
    def is_first_stage(self):
        return self.stage_id == 0

    @property
    def is_last_stage(self):
        return self.stage_id == self.stages - 1

    def _buffer_idx(self, micro_batch_id):
        assert self._valid_micro_batch(micro_batch_id)
        return micro_batch_id % self.num_pipe_buffers()

    def __iter__(self):
        self.it = None
        return self

    def __next__(self):
        if self.it is None:
            self.it = self.steps()
        return next(self.it)


class InferenceSchedule(PipeSchedule):
    """Reference schedule.py:135 — forward-only pipelining."""

    def steps(self):
        total_steps = self.micro_batches + self.stages - 1
        for step_id in range(total_steps):
            cmds = []
            micro_batch_id = step_id - self.stage_id

            if self._valid_micro_batch(micro_batch_id):
                if self.is_first_stage or self.is_last_stage:
                    cmds.append(LoadMicroBatch(self._buffer_idx(micro_batch_id)))
                if self._valid_stage(self.prev_stage):
                    cmds.append(RecvActivation(self._buffer_idx(micro_batch_id)))

            if self._valid_micro_batch(micro_batch_id):
                cmds.append(ForwardPass(self._buffer_idx(micro_batch_id)))
                if self._valid_stage(self.next_stage):
                    cmds.append(SendActivation(self._buffer_idx(micro_batch_id)))
            yield cmds

    def num_pipe_buffers(self):
        return 2


class TrainSchedule(PipeSchedule):
    """Reference schedule.py:189 — 1F1B: each stage alternates forward/backward
    once warm, bounding in-flight activations to the pipeline depth."""

    def steps(self):
        prev_micro_batch_id = -1
        total_steps = 2 * (self.micro_batches + self.stages - 1)
        for step_id in range(total_steps):
            micro_batch_id, is_forward = self._step_to_micro_batch(step_id)

            cmds = []
            # exchange activations/gradients
            if self._valid_micro_batch(prev_micro_batch_id) and self._valid_stage(self.prev_stage):
                if not is_forward:
                    cmds.append(SendGrad(self._buffer_idx(prev_micro_batch_id)))
            if self._valid_micro_batch(prev_micro_batch_id) and self._valid_stage(self.next_stage):
                if is_forward:
                    cmds.append(SendActivation(self._buffer_idx(prev_micro_batch_id)))
            if self._valid_micro_batch(micro_batch_id) and self._valid_stage(self.prev_stage):
                if is_forward:
                    cmds.append(RecvActivation(self._buffer_idx(micro_batch_id)))
            if self._valid_micro_batch(micro_batch_id) and self._valid_stage(self.next_stage):
                if not is_forward:
                    cmds.append(RecvGrad(self._buffer_idx(micro_batch_id)))

            # computation
            if self._valid_micro_batch(micro_batch_id):
                if is_forward:
                    if self.is_first_stage or self.is_last_stage:
                        cmds.append(LoadMicroBatch(self._buffer_idx(micro_batch_id)))
                    cmds.append(ForwardPass(self._buffer_idx(micro_batch_id)))
                else:
                    cmds.append(BackwardPass(self._buffer_idx(micro_batch_id)))

            # model step at the end
            if step_id == total_steps - 1:
                cmds.append(ReduceTiedGrads())
                cmds.append(ReduceGrads())
                cmds.append(OptimizerStep())

            prev_micro_batch_id = micro_batch_id
            yield cmds

    def num_pipe_buffers(self):
        """Reference: bounded by in-flight microbatches = stages - stage_id."""
        buffers = min(self.stages - self.stage_id, self.micro_batches)
        return max(2, buffers)

    def _step_to_micro_batch(self, step_id):
        if _is_even(step_id) and _is_even(self.stage_id):
            micro_batch_id = self._even_step_forward_id(step_id)
            is_forward = True
        elif _is_odd(step_id) and _is_odd(self.stage_id):
            micro_batch_id = self._odd_step_forward_id(step_id)
            is_forward = True
        elif _is_even(step_id) and _is_odd(self.stage_id):
            micro_batch_id = self._even_step_backward_id(step_id)
            is_forward = False
        elif _is_odd(step_id) and _is_even(self.stage_id):
            micro_batch_id = self._odd_step_backward_id(step_id)
            is_forward = False
        else:
            raise AssertionError()
        return micro_batch_id, is_forward

    def _even_step_forward_id(self, step_id):
        base = step_id // 2
        return int(base - self.stage_id // 2)

    def _odd_step_forward_id(self, step_id):
        base = (step_id - 1) // 2
        return int(base - self.stage_id // 2)

    def _even_step_backward_id(self, step_id):
        base = step_id // 2
        return int(base - self.stages + (self.stage_id + 1) // 2)

    def _odd_step_backward_id(self, step_id):
        base = ((step_id - 1) // 2) - self.stages + 1
        return int(base + self.stage_id // 2)


class DataParallelSchedule(PipeSchedule):
    """Reference schedule.py:301 — degenerate single-stage schedule."""

    def steps(self):
        for step_id in range(self.micro_batches):
            cmds = [
                LoadMicroBatch(buffer_id=0),
                ForwardPass(buffer_id=0),
                BackwardPass(buffer_id=0),
            ]
            if step_id == self.micro_batches - 1:
                cmds.extend([ReduceGrads(), OptimizerStep()])
            yield cmds

    def num_pipe_buffers(self):
        return 1


class PipeInstruction:

    def __init__(self, **kwargs):
        self.name = self.__class__.__name__
        self.kwargs = kwargs
        for key, val in kwargs.items():
            setattr(self, key, val)

    def __repr__(self):
        from deepspeed_tpu.runtime.utils import call_to_str
        return call_to_str(self.name, **self.kwargs)

    def __eq__(self, other):
        return type(self) is type(other) and self.kwargs == other.kwargs


class OptimizerStep(PipeInstruction):
    ...


class ReduceGrads(PipeInstruction):
    ...


class ReduceTiedGrads(PipeInstruction):
    ...


class BufferOpInstruction(PipeInstruction):

    def __init__(self, buffer_id, **kwargs):
        super().__init__(buffer_id=buffer_id, **kwargs)


class LoadMicroBatch(BufferOpInstruction):
    ...


class ForwardPass(BufferOpInstruction):
    ...


class BackwardPass(BufferOpInstruction):
    ...


class SendActivation(BufferOpInstruction):
    ...


class RecvActivation(BufferOpInstruction):
    ...


class SendGrad(BufferOpInstruction):
    ...


class RecvGrad(BufferOpInstruction):
    ...


def _is_even(x):
    return x % 2 == 0


def _is_odd(x):
    return x % 2 != 0
