"""Parallel-group registry as named axes of one global ``jax.sharding.Mesh``.

TPU-native analog of the reference's ``deepspeed/utils/groups.py`` (lazy registry of
torch process groups, groups.py:51-560). On TPU the natural SPMD formulation is ONE
device mesh whose named axes play the role of process groups:

    ('pipe', 'data', 'expert', 'seq', 'model')

- ``model``  — tensor parallelism (innermost: highest-bandwidth ICI neighbors).
- ``seq``    — Ulysses sequence parallelism (reference: deepspeed/sequence/layer.py).
- ``expert`` — expert parallelism; carved out of the data-parallel ranks exactly like
  the reference's ``_create_expert_and_data_parallel`` (groups.py:113-295): the dense
  data-parallel world is ``data × expert``; expert parameters are data-parallel over
  ``data`` only (the "expert-data-parallel group") and expert-parallel over ``expert``.
- ``data``   — the remaining data parallelism.
- ``pipe``   — pipeline stages (outermost; can span DCN).

ZeRO partitioning happens over the "sequence-data-parallel" axes
(('data', 'expert', 'seq')) matching the reference engine's use of
``seq_data_parallel_group`` as the ZeRO group (engine.py:1138-1145).

Collectives over these groups are expressed with ``jax.lax.{psum, all_gather,
psum_scatter, all_to_all, ppermute}`` inside ``jax.shard_map``/``pjit`` — XLA lowers
them to ICI/DCN collectives; there are no NCCL communicators to manage.
"""

import os
from dataclasses import dataclass, field
from typing import Optional, Sequence, Tuple

import numpy as np

from deepspeed_tpu.utils.logging import logger

# Canonical mesh axis names, outermost (DCN-friendly) to innermost (ICI-critical).
# ``hpz`` is the ZeRO++ hpZ / MiCS *secondary partition* axis: a split of the
# data-parallel dimension whose inner part stays on one node's ICI (reference
# zero/config.py zero_hpz_partition_size, mics.py MiCS_Optimizer shard groups).
# Size 1 (the default) makes it vanish from every PartitionSpec.
PIPE_AXIS = "pipe"
DATA_AXIS = "data"
HPZ_AXIS = "hpz"
EXPERT_AXIS = "expert"
SEQ_AXIS = "seq"
MODEL_AXIS = "model"
MESH_AXES = (PIPE_AXIS, DATA_AXIS, HPZ_AXIS, EXPERT_AXIS, SEQ_AXIS, MODEL_AXIS)

# Axis groups used as "process groups".
DATA_PARALLEL_AXES = (DATA_AXIS, HPZ_AXIS, EXPERT_AXIS)  # dense-param DP group
EXPERT_DATA_PARALLEL_AXES = (DATA_AXIS, HPZ_AXIS)  # expert-param DP group
SEQ_DATA_PARALLEL_AXES = (DATA_AXIS, HPZ_AXIS, EXPERT_AXIS, SEQ_AXIS)  # ZeRO partition group
SECONDARY_PARTITION_AXES = (HPZ_AXIS, EXPERT_AXIS, SEQ_AXIS)  # hpZ/MiCS shard group

_MESH = None  # the process-global Mesh (analog of the reference's module globals)


class TopologyError(ValueError):
    pass


@dataclass
class MeshTopology:
    """Degrees of each parallel dimension; multiplies to the device count."""

    pipe: int = 1
    data: int = 1
    hpz: int = 1
    expert: int = 1
    seq: int = 1
    model: int = 1

    @property
    def shape(self) -> Tuple[int, ...]:
        return (self.pipe, self.data, self.hpz, self.expert, self.seq, self.model)

    def world_size(self) -> int:
        return int(np.prod(self.shape))


def initialize_mesh(
    *,
    data_parallel_size: Optional[int] = None,
    model_parallel_size: int = 1,
    pipe_parallel_size: int = 1,
    expert_parallel_size: int = 1,
    sequence_parallel_size: int = 1,
    secondary_partition_size: int = 1,
    devices=None,
    force: bool = False,
):
    """Build (or rebuild) the global mesh. ``data_parallel_size=None`` infers it from
    the device count, mirroring the reference where dp = world // (mp*pp).

    ``secondary_partition_size`` splits the data dimension into
    (data // k, hpz=k) for ZeRO++ hpZ / MiCS: the inner ``hpz`` axis is the
    intra-node shard group (devices adjacent in the mesh order → ICI
    neighbors), the outer ``data`` axis crosses nodes."""
    global _MESH
    import jax
    from jax.sharding import Mesh

    if _MESH is not None and not force:
        return _MESH

    devices = devices if devices is not None else jax.devices()
    n = len(devices)
    fixed = model_parallel_size * pipe_parallel_size * expert_parallel_size * sequence_parallel_size
    if n % fixed != 0:
        raise TopologyError(f"device count {n} not divisible by mp*pp*ep*sp = {fixed}")
    if data_parallel_size is None:
        data_parallel_size = n // fixed
    k = max(1, secondary_partition_size)
    if data_parallel_size % k != 0:
        raise TopologyError(f"data degree {data_parallel_size} not divisible by "
                            f"secondary partition size {k}")
    topo = MeshTopology(pipe=pipe_parallel_size,
                        data=data_parallel_size // k,
                        hpz=k,
                        expert=expert_parallel_size,
                        seq=sequence_parallel_size,
                        model=model_parallel_size)
    if topo.world_size() != n:
        raise TopologyError(f"mesh shape {topo.shape} (= {topo.world_size()}) != device count {n}")

    dev_array = np.asarray(devices).reshape(topo.shape)
    _MESH = Mesh(dev_array, MESH_AXES)
    logger.info(f"initialized mesh pipe={topo.pipe} data={topo.data} hpz={topo.hpz} "
                f"expert={topo.expert} seq={topo.seq} model={topo.model} over {n} devices")
    return _MESH


def mesh_is_initialized() -> bool:
    return _MESH is not None


def get_mesh():
    if _MESH is None:
        initialize_mesh()
    return _MESH


def set_mesh(mesh):
    """Install an externally built mesh (must use MESH_AXES names)."""
    global _MESH
    for ax in mesh.axis_names:
        if ax not in MESH_AXES:
            raise TopologyError(f"external mesh axis {ax!r} not in {MESH_AXES}")
    _MESH = mesh
    return _MESH


def destroy_mesh():
    """Reset global state (tests)."""
    global _MESH
    _MESH = None


def _axis_size(axes) -> int:
    mesh = get_mesh()
    if isinstance(axes, str):
        axes = (axes, )
    size = 1
    for ax in axes:
        size *= mesh.shape.get(ax, 1)
    return size


# ---- world-size accessors (reference: groups.py getters) -------------------------

def get_model_parallel_world_size() -> int:
    return _axis_size(MODEL_AXIS)


def get_sequence_parallel_world_size() -> int:
    return _axis_size(SEQ_AXIS)


def get_pipe_parallel_world_size() -> int:
    return _axis_size(PIPE_AXIS)


def get_expert_parallel_world_size() -> int:
    return _axis_size(EXPERT_AXIS)


def get_data_parallel_world_size() -> int:
    """Dense-parameter DP degree (reference dp = world // (mp*pp))."""
    return _axis_size(DATA_PARALLEL_AXES)


def get_expert_data_parallel_world_size() -> int:
    return _axis_size(EXPERT_DATA_PARALLEL_AXES)


def get_sequence_data_parallel_world_size() -> int:
    """The ZeRO partition degree (sp * dp), reference groups.py:452-499."""
    return _axis_size(SEQ_DATA_PARALLEL_AXES)


def get_world_size() -> int:
    return get_mesh().size


# ---- axis-name accessors: pass these to jax.lax collectives ----------------------

def get_data_parallel_axes() -> Tuple[str, ...]:
    return DATA_PARALLEL_AXES


def get_expert_parallel_axis() -> str:
    return EXPERT_AXIS


def get_sequence_parallel_axis() -> str:
    return SEQ_AXIS


def get_model_parallel_axis() -> str:
    return MODEL_AXIS


def get_pipe_parallel_axis() -> str:
    return PIPE_AXIS


def get_zero_partition_axes() -> Tuple[str, ...]:
    return SEQ_DATA_PARALLEL_AXES


def get_secondary_partition_axes() -> Tuple[str, ...]:
    """hpZ/MiCS shard-group axes (the intra-node slice of the ZeRO group)."""
    return SECONDARY_PARTITION_AXES
