"""Host-level executor for pipeline instruction streams.

Reference: ``deepspeed/runtime/pipe/engine.py:1357`` (``_exec_schedule`` — the
dispatch loop walking a PipeSchedule's instructions through
``_INSTRUCTION_MAP``) with the P2P sends/recvs of ``pipe/p2p.py``.

TPU role: the HOT path executes pipelines as one jitted scan with ppermute
(``pipe/engine.py``); this executor is the general fallback the schedules
drive directly — it handles what the fused scan cannot: heterogeneous stages
(different layer types/shapes per stage) and ``TiedLayerSpec`` parameter
sharing. It simulates the P stage workers in lock step: per clock tick, all
sends deposit into per-link mailboxes, then recvs collect them (asserting the
same-tick pairing invariant the streams encode), then compute runs. Backward
uses per-buffer ``jax.vjp`` residuals exactly where the reference stashes
activation grads."""

from typing import Callable, List, Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp

from deepspeed_tpu.runtime.pipe import schedule as sched
from deepspeed_tpu.utils.logging import logger


class ScheduleExecutor:

    def __init__(self, stage_fns: Sequence[Callable], stage_params: Sequence,
                 loss_fn: Callable, micro_batches: int,
                 tied_groups: Optional[List[List[int]]] = None):
        """``stage_fns[s](params_s, x) -> y``; the last stage's output feeds
        ``loss_fn(y, label)``. ``tied_groups``: stage-index groups whose param
        trees are shared (TiedLayerSpec) — their grads are summed and mirrored
        (the reference's tied-weight allreduce, module.py:423)."""
        self.stage_fns = list(stage_fns)
        self.stage_params = list(stage_params)
        self.loss_fn = loss_fn
        self.P = len(stage_fns)
        self.M = micro_batches
        self.tied_groups = tied_groups or []

    # ------------------------------------------------------------------ exec --
    def train_batch(self, inputs: Sequence, labels: Sequence):
        """Run one TrainSchedule pass; returns (mean loss, per-stage grads)."""
        P, M = self.P, self.M
        assert len(inputs) == M and len(labels) == M
        schedules = [sched.TrainSchedule(M, P, s) for s in range(P)]
        streams = [list(s.steps()) for s in schedules]
        nbuf = [schedules[s].num_pipe_buffers() for s in range(P)]

        # per-stage state
        act_in = [[None] * nbuf[s] for s in range(P)]     # recv'd/loaded inputs
        vjps = self._vjps = [[None] * nbuf[s] for s in range(P)]  # backward closures
        act_out = [[None] * nbuf[s] for s in range(P)]    # forward outputs
        grad_in = [[None] * nbuf[s] for s in range(P)]    # recv'd output-grads
        grads = [jax.tree.map(jnp.zeros_like, p) for p in self.stage_params]
        labels_buf = [[None] * nbuf[P - 1]]
        losses = []

        # same-tick mailboxes, one per directed link
        act_mail = {}   # (src, dst) -> activation
        grad_mail = {}

        ticks = len(streams[0])
        for t in range(ticks):
            cmds_per_stage = [streams[s][t] for s in range(P)]

            # phase 1: sends + loads deposit
            for s, cmds in enumerate(cmds_per_stage):
                for cmd in cmds:
                    if isinstance(cmd, sched.SendActivation):
                        assert (s, s + 1) not in act_mail, f"act link {s}->{s+1} busy @t{t}"
                        act_mail[(s, s + 1)] = act_out[s][cmd.buffer_id]
                    elif isinstance(cmd, sched.SendGrad):
                        assert (s, s - 1) not in grad_mail, f"grad link {s}->{s-1} busy @t{t}"
                        grad_mail[(s, s - 1)] = self._input_grad(s, cmd.buffer_id)
                    elif isinstance(cmd, sched.LoadMicroBatch):
                        _, mb = schedules[s].work_at(t)
                        if s == 0:
                            act_in[0][cmd.buffer_id] = inputs[mb]
                        if s == P - 1:
                            labels_buf[0][cmd.buffer_id] = labels[mb]

            # phase 2: recvs collect (send must have happened THIS tick)
            for s, cmds in enumerate(cmds_per_stage):
                for cmd in cmds:
                    if isinstance(cmd, sched.RecvActivation):
                        key = (s - 1, s)
                        assert key in act_mail, f"unpaired RecvActivation on {key} @t{t}"
                        act_in[s][cmd.buffer_id] = act_mail.pop(key)
                    elif isinstance(cmd, sched.RecvGrad):
                        key = (s + 1, s)
                        assert key in grad_mail, f"unpaired RecvGrad on {key} @t{t}"
                        grad_in[s][cmd.buffer_id] = grad_mail.pop(key)

            # phase 3: compute
            for s, cmds in enumerate(cmds_per_stage):
                for cmd in cmds:
                    if isinstance(cmd, sched.ForwardPass):
                        b = cmd.buffer_id
                        x = act_in[s][b]
                        assert x is not None, \
                            f"ForwardPass on stage {s} buffer {b} @t{t} with no activation " \
                            f"(missing LoadMicroBatch/RecvActivation)"
                        if s == P - 1:
                            def full(p, x, y):
                                return self.loss_fn(self.stage_fns[s](p, x), y)
                            loss, vjp = jax.vjp(full, self.stage_params[s], x,
                                                labels_buf[0][b])
                            losses.append(loss)
                            vjps[s][b] = vjp
                        else:
                            y, vjp = jax.vjp(self.stage_fns[s], self.stage_params[s], x)
                            act_out[s][b] = y
                            vjps[s][b] = vjp
                    elif isinstance(cmd, sched.BackwardPass):
                        b = cmd.buffer_id
                        if s == P - 1:
                            dp, dx, _ = vjps[s][b](jnp.ones(()))
                        else:
                            dp, dx = vjps[s][b](grad_in[s][b])
                        grads[s] = jax.tree.map(jnp.add, grads[s], dp)
                        vjps[s][b] = ("done", dx)  # stash input-grad for SendGrad
                    elif isinstance(cmd, sched.ReduceTiedGrads):
                        if s == 0:
                            self._reduce_tied(grads)
                    elif isinstance(cmd, (sched.ReduceGrads, sched.OptimizerStep)):
                        pass  # DP reduction/step belong to the caller's engine

        assert not act_mail and not grad_mail, "unconsumed mailbox entries"
        assert len(losses) == M
        return jnp.mean(jnp.stack(losses)), grads

    def _input_grad(self, s, buffer_id):
        slot = self.vjp_slot(s, buffer_id)
        assert isinstance(slot, tuple) and slot[0] == "done", \
            f"SendGrad before BackwardPass on stage {s} buffer {buffer_id}"
        return slot[1]

    def vjp_slot(self, s, buffer_id):
        return self._vjps[s][buffer_id]

    def _reduce_tied(self, grads):
        """Sum tied groups' grads and mirror the total (reference
        _exec_reduce_tied_grads / module.py:423)."""
        for group in self.tied_groups:
            total = None
            for s in group:
                total = grads[s] if total is None else jax.tree.map(jnp.add, total, grads[s])
            for s in group:
                grads[s] = total
