"""Cross-replica tracing (ISSUE satellite): one routed request — prefill on
one replica, decode on another — renders as a SINGLE parented trace:
route → dispatch:prefill → replica request, dispatch:decode → replica request.
Plus trace continuity for the ``steal-victim``/``steal`` and peer-prefix-fetch
legs: every leg a request takes carries the ORIGINAL trace id end-to-end."""

import json
import urllib.request

import numpy as np

from deepspeed_tpu import telemetry
from deepspeed_tpu.fleet import FleetConfig, FleetRouter
from deepspeed_tpu.fleet.config import CacheRouteConfig, StealConfig
from deepspeed_tpu.fleet.router import _rendezvous_score
from deepspeed_tpu.serving import PrefixCacheConfig, ServingConfig
from deepspeed_tpu.serving.server import TRACE_HEADER


def _events(trace_id):
    evs = telemetry.state.spans.chrome_trace()["traceEvents"]
    return [e for e in evs if e.get("args", {}).get("trace_id") == trace_id
            and e.get("ph") == "X"]


def test_disaggregated_request_is_one_parented_trace(make_fleet):
    telemetry.configure(telemetry.TelemetryConfig(enabled=True))
    fleet = make_fleet(roles=("prefill", "decode"))
    router = FleetRouter(fleet).start()
    try:
        prompt = (np.arange(15) % 64).tolist()
        body = json.dumps({"prompt": prompt, "max_new_tokens": 5}).encode()
        req = urllib.request.Request(router.url + "/v1/generate", data=body,
                                     headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=120) as resp:
            doc = json.loads(resp.read())
            trace_id = resp.headers[TRACE_HEADER]
    finally:
        router.stop(drain=False)

    assert doc["state"] == "DONE" and doc["trace_id"] == trace_id
    evs = _events(trace_id)
    by_name = {}
    for e in evs:
        by_name.setdefault(e["name"], []).append(e)

    # the router's root covers the whole request
    (route, ) = by_name["route"]
    assert route["args"]["disaggregated"] is True
    assert len(route["args"]["legs"]) == 2

    # one dispatch hop per leg, parented under the route span
    (hop_prefill, ) = by_name["dispatch:prefill"]
    (hop_decode, ) = by_name["dispatch:decode"]
    for hop in (hop_prefill, hop_decode):
        assert hop["args"]["parent_id"] == route["args"]["span_id"]
    assert hop_prefill["args"]["role"] == "prefill"
    assert hop_decode["args"]["role"] == "decode"

    # each replica's request root parents under ITS dispatch hop — the
    # Perfetto track reads router -> prefill replica -> decode replica
    requests = by_name["request"]
    assert len(requests) == 2
    parents = {r["args"]["parent_id"] for r in requests}
    assert parents == {hop_prefill["args"]["span_id"],
                       hop_decode["args"]["span_id"]}
    resumed = {r["args"]["resumed"] for r in requests}
    assert resumed == {True, False}

    # every lifecycle span of both replica legs shares the one trace id
    names = {e["name"] for e in evs}
    assert {"queued", "prefill", "decode"} <= names


def _pin_key(target_id, other_id):
    """A session key whose rendezvous winner is ``target_id``."""
    for i in range(1000):
        k = f"pin{i}"
        if _rendezvous_score(k, target_id) > _rendezvous_score(k, other_id):
            return k
    raise AssertionError("rendezvous never favored the target")


def _by_name(evs):
    by = {}
    for e in evs:
        by.setdefault(e["name"], []).append(e)
    return by


def test_steal_legs_carry_the_original_trace_id(make_fleet):
    """Trace continuity through a steal (ISSUE satellite): the victim leg AND
    the stolen leg — two replicas, two schedulers — both parent under the one
    router trace, so the Perfetto view shows the regrant, not two orphans."""
    telemetry.configure(telemetry.TelemetryConfig(enabled=True))
    manager = make_fleet(roles=(),
                         config=FleetConfig(probe_ttl_s=0.0,
                                            drain_timeout_s=10.0,
                                            steal=StealConfig(
                                                enabled=True,
                                                wait_budget_s=0.1,
                                                load_ratio=1.5)),
                         max_tracked_sequences=1)
    manager.add_local(role="mixed", replica_id="r0")
    manager.add_local(role="mixed", replica_id="r1")
    r0, _ = manager.replicas()
    blocker = r0.scheduler.submit((np.arange(7) % 64).tolist(),
                                  max_new_tokens=300)
    router = FleetRouter(manager)
    routed = router.route({"prompt": (np.arange(9) % 64).tolist(),
                           "max_new_tokens": 4, "seed": 0},
                          session_key=_pin_key("r0", "r1"))
    final = dict(routed.result())
    blocker.result(timeout=300)

    assert final["state"] == "DONE"
    assert [leg["kind"] for leg in final["legs"]] == ["steal-victim", "steal"]
    trace_id = final["trace_id"]
    assert trace_id == routed.trace_id is not None

    by_name = _by_name(_events(trace_id))
    (route, ) = by_name["route"]
    (hop_serve, ) = by_name["dispatch:generate"]
    (hop_steal, ) = by_name["dispatch:steal"]
    for hop in (hop_serve, hop_steal):
        assert hop["args"]["parent_id"] == route["args"]["span_id"]

    # BOTH request roots — the cancelled victim and the stolen serve — carry
    # the original trace id and parent under their own dispatch hop
    requests = by_name["request"]
    assert len(requests) == 2
    states = {r["args"]["state"] for r in requests}
    assert states == {"CANCELLED", "DONE"}
    parents = {r["args"]["parent_id"] for r in requests}
    assert parents == {hop_serve["args"]["span_id"],
                       hop_steal["args"]["span_id"]}
    # the stolen leg's lifecycle spans ride the same trace
    assert {"queued", "prefill", "decode"} <= set(by_name)


def test_peer_prefix_fetch_leg_carries_the_trace_id(make_fleet, llama_setup):
    """Trace continuity through a peer prefix fetch (ISSUE satellite): the
    cross-replica KV import records a ``peer_prefix_fetch`` span under the
    request root, on the request's ORIGINAL trace id — cache-warm latency is
    attributable in the merged trace, not invisible."""
    telemetry.configure(telemetry.TelemetryConfig(enabled=True))
    cfg = llama_setup[0]
    manager = make_fleet(
        roles=("mixed", "mixed"),
        serving_config=ServingConfig(
            prefix_cache=PrefixCacheConfig(enabled=True)),
        config=FleetConfig(probe_ttl_s=0.0, drain_timeout_s=10.0,
                           cache_route=CacheRouteConfig(peer_fetch=True)))
    router = FleetRouter(manager)
    rng = np.random.default_rng(33)
    prefix = rng.integers(0, cfg.vocab_size, 3 * 16).tolist()

    warm = router.route({"prompt": prefix
                         + rng.integers(0, cfg.vocab_size, 6).tolist(),
                         "max_new_tokens": 1})
    warm.result()
    holder_id = warm._legs_meta[0]["replica"]
    (cold_id, ) = [r.id for r in manager.replicas() if r.id != holder_id]

    routed = router.route({"prompt": prefix
                           + rng.integers(0, cfg.vocab_size, 6).tolist(),
                           "max_new_tokens": 2, "routing": "hash"},
                          session_key=_pin_key(cold_id, holder_id))
    final = dict(routed.result())
    assert final["cached_tokens"] == 3 * 16  # the import actually happened
    trace_id = final["trace_id"]
    assert trace_id != warm.trace_id  # distinct traces, shared recorder

    by_name = _by_name(_events(trace_id))
    (route, ) = by_name["route"]
    (hop, ) = by_name["dispatch:generate"]
    (request, ) = by_name["request"]
    (fetch, ) = by_name["peer_prefix_fetch"]
    assert hop["args"]["parent_id"] == route["args"]["span_id"]
    assert request["args"]["parent_id"] == hop["args"]["span_id"]
    assert fetch["args"]["parent_id"] == request["args"]["span_id"]
    assert fetch["args"]["imported"] is True
    # the warm (donor-priming) request never leaked into this trace
    assert all(e["args"]["trace_id"] == trace_id
               for evs in by_name.values() for e in evs)
