"""ZeRO-Inference weight quantization (reference README.md:17 news item;
deepspeed/inference/quantization role)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deepspeed_tpu.inference.v2.quantization import (dequantize_tree, is_quantized_leaf,
                                                     quantize_tree, tree_nbytes)
from deepspeed_tpu.utils import groups


def test_quantize_roundtrip_error_bound():
    rng = np.random.default_rng(0)
    w = jnp.asarray(rng.normal(size=(128, 64)), jnp.float32)
    tree = {"layer": {"kernel": w, "bias": jnp.ones((64, ))}}
    q = quantize_tree(tree, min_size=1024)
    assert is_quantized_leaf(q["layer"]["kernel"])
    assert q["layer"]["kernel"]["__wq_int8__"].dtype == jnp.int8
    assert not is_quantized_leaf(q["layer"]["bias"])  # small leaves stay fp

    back = dequantize_tree(q)
    assert back["layer"]["kernel"].dtype == jnp.float32
    # symmetric per-channel int8: max error <= scale/2 = max|col|/254
    err = np.abs(np.asarray(back["layer"]["kernel"]) - np.asarray(w))
    bound = np.abs(np.asarray(w)).max(axis=0) / 254.0 + 1e-7
    assert (err <= bound[None, :] + 1e-6).all()


def test_quantize_memory_halves():
    rng = np.random.default_rng(1)
    tree = {"k": jnp.asarray(rng.normal(size=(256, 256)), jnp.bfloat16)}
    q = quantize_tree(tree, min_size=0)
    # bf16 (2B) -> int8 (1B) + small scale row
    assert tree_nbytes(q) < 0.6 * tree_nbytes(tree)
    back = dequantize_tree(q)
    assert back["k"].dtype == jnp.bfloat16


def test_bits_guard():
    with pytest.raises(NotImplementedError):
        quantize_tree({"k": jnp.ones((64, 64))}, bits=2)


def test_int4_roundtrip_error_bound():
    """Packed-int4 quantization (VERDICT r5 ask #5; reference
    csrc/quantization/quantize_intX.cu role): symmetric [-7,7] per output
    channel, 8 nibbles/int32 word along the contraction axis."""
    rng = np.random.default_rng(2)
    w = jnp.asarray(rng.normal(size=(128, 64)), jnp.float32)
    tree = {"layer": {"kernel": w, "bias": jnp.ones((64, ))}}
    q = quantize_tree(tree, min_size=1024, bits=4)
    packed = q["layer"]["kernel"]["__wq_int4x8__"]
    assert packed.dtype == jnp.int32
    assert packed.shape == (128 // 8, 64)
    assert is_quantized_leaf(q["layer"]["kernel"])

    back = dequantize_tree(q)
    assert back["layer"]["kernel"].dtype == jnp.float32
    # symmetric per-channel int4: max error <= scale/2 = max|col|/14
    err = np.abs(np.asarray(back["layer"]["kernel"]) - np.asarray(w))
    bound = np.abs(np.asarray(w)).max(axis=0) / 14.0 + 1e-7
    assert (err <= bound[None, :] + 1e-6).all()


def test_int4_negative_values_sign_extend():
    """The nibble sign-extension must reproduce the exact int4 levels,
    negatives included."""
    col = np.arange(-7, 8, dtype=np.float32)          # all 15 levels
    W = np.tile(col[:, None], (1, 4)) * 0.5
    W = jnp.asarray(np.concatenate([W, W[:1]], axis=0))  # K=16 (mult of 8)
    q = quantize_tree({"k": W}, min_size=0, bits=4)
    back = np.asarray(dequantize_tree(q)["k"])
    np.testing.assert_allclose(back, np.asarray(W), atol=1e-6)


def test_int4_memory_quarter():
    rng = np.random.default_rng(3)
    tree = {"k": jnp.asarray(rng.normal(size=(256, 256)), jnp.bfloat16)}
    q = quantize_tree(tree, min_size=0, bits=4)
    # bf16 (2B) -> packed int4 (0.5B) + small scale row
    assert tree_nbytes(q) < 0.35 * tree_nbytes(tree)
    back = dequantize_tree(q)
    assert back["k"].dtype == jnp.bfloat16


def test_int4_odd_contraction_axis_falls_back_to_int8():
    rng = np.random.default_rng(4)
    w = jnp.asarray(rng.normal(size=(100, 64)), jnp.float32)  # 100 % 8 != 0
    q = quantize_tree({"k": w}, min_size=0, bits=4)
    assert "__wq_int8__" in q["k"]


@pytest.mark.parametrize("bits", [8, 4])
def test_engine_quantized_logits_close(bits):
    """A quantized llama v2 engine must store int8 (or packed-int4) weights
    at rest and produce logits close to the full-precision engine."""
    from deepspeed_tpu.inference.v2.config_v2 import RaggedInferenceEngineConfig
    from deepspeed_tpu.inference.v2.engine_factory import build_engine
    from deepspeed_tpu.inference.v2.quantization import Q4KEY
    from deepspeed_tpu.inference.v2.ragged.manager_configs import (AllocationMode,
                                                                   DSStateManagerConfig,
                                                                   MemoryConfig)
    from deepspeed_tpu.models.llama import LlamaConfig, init_params

    groups.initialize_mesh(force=True)
    cfg = LlamaConfig.tiny(vocab_size=128, hidden_size=64, intermediate_size=128,
                           num_hidden_layers=2, num_attention_heads=4,
                           num_key_value_heads=4, max_position_embeddings=64)
    _, params = init_params(cfg, seq_len=8)

    def mgr():
        return DSStateManagerConfig(memory_config=MemoryConfig(mode=AllocationMode.ALLOCATE,
                                                               size=64),
                                    max_context=64, max_ragged_batch_size=64,
                                    max_ragged_sequence_count=4)

    prompt = np.arange(10) % 128
    fp = build_engine(params, cfg, RaggedInferenceEngineConfig(state_manager=mgr()))
    ref_logits = np.asarray(fp.put([0], [prompt]))

    q = build_engine(params, cfg,
                     RaggedInferenceEngineConfig(state_manager=mgr(),
                                                 weight_quantization={"enabled": True,
                                                                      "min_size": 1024,
                                                                      "bits": bits}))
    if bits == 8:
        import jax as _jax
        at_rest = [l for l in _jax.tree.leaves(q._model._params) if l.dtype == jnp.int8]
        assert at_rest, "engine must hold int8 weights at rest"
    else:
        packed = []

        def walk(node):
            if isinstance(node, dict):
                if Q4KEY in node:
                    packed.append(node[Q4KEY])
                else:
                    for v in node.values():
                        walk(v)

        walk(q._model._params)
        assert packed, "engine must hold packed-int4 weights at rest"
        assert all(p.dtype == jnp.int32 for p in packed)
    q_logits = np.asarray(q.put([0], [prompt]))

    assert q_logits.shape == ref_logits.shape
    # per-channel quantization: logits agree to first-order (int4 carries
    # ~16x coarser levels than int8, hence the looser bound)
    tol = 0.05 if bits == 8 else 0.35
    assert np.mean(np.abs(q_logits - ref_logits)) < tol * np.mean(np.abs(ref_logits)) + tol
    if bits == 8:
        # randomly initialized weights give near-uniform logits, so exact
        # argmax can flip on ties — the robust claim is top-k containment
        top5 = np.argsort(ref_logits[-1])[-5:]
        assert np.argmax(q_logits[-1]) in top5


def test_quantization_rejects_tp():
    from deepspeed_tpu.inference.v2.config_v2 import RaggedInferenceEngineConfig
    from deepspeed_tpu.inference.v2.engine_factory import build_engine
    from deepspeed_tpu.models.llama import LlamaConfig, init_params

    groups.initialize_mesh(model_parallel_size=2, force=True)
    cfg = LlamaConfig.tiny(vocab_size=128, hidden_size=64, intermediate_size=128,
                           num_hidden_layers=1, num_attention_heads=4,
                           num_key_value_heads=4)
    _, params = init_params(cfg, seq_len=8)
    with pytest.raises(NotImplementedError, match="AutoTP"):
        build_engine(params, cfg,
                     RaggedInferenceEngineConfig(tp={"tp_size": 2},
                                                 weight_quantization={"enabled": True}))
