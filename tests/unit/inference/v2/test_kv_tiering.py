"""TieredKVStore (inference/v2/ragged/tiering.py): the host→disk half of the
tiered KV ladder — budgeted LRU demotion on the async writer, non-destructive
reads from either tier, the read-vs-demote race reclaiming to host, and the
stats/counter surface the serving controller renders."""

import threading
import time

import numpy as np
import pytest

from deepspeed_tpu.inference.v2.ragged.tiering import TIERS, TieredKVStore


def _payload(n=2, seed=0):
    rng = np.random.default_rng(seed)
    return rng.normal(size=(2, 2, n, 2, 16, 8)).astype(np.float32)


def test_tier_names_are_the_public_ladder():
    assert TIERS == ("device", "host", "disk")


def test_put_read_drop_host_tier():
    store = TieredKVStore()
    data = _payload()
    h = store.put(data)
    assert h in store and store.tier_of(h) == "host"
    assert store.n_blocks(h) == 2
    got, tier = store.read(h)
    assert tier == "host"
    np.testing.assert_array_equal(got, data)
    # read is non-destructive: a second read sees the same payload
    got2, _ = store.read(h)
    np.testing.assert_array_equal(got2, data)
    store.drop(h)
    assert h not in store
    with pytest.raises(KeyError):
        store.read(h)
    store.close()


def test_explicit_demote_spills_and_reads_back(tmp_path):
    store = TieredKVStore(spill_dir=str(tmp_path))
    data = _payload(seed=1)
    h = store.put(data)
    assert store.demote(h, wait=True)
    assert store.tier_of(h) == "disk"
    assert list(tmp_path.glob("kv_offload_*.bin"))
    got, tier = store.read(h)
    assert tier == "disk"
    np.testing.assert_array_equal(got, data)
    s = store.stats()
    assert s["demotions"] == 1 and s["reads_disk"] == 1
    store.drop(h)
    assert not list(tmp_path.glob("kv_offload_*.bin"))
    store.close()


def test_demote_without_spill_dir_is_refused():
    store = TieredKVStore()
    h = store.put(_payload())
    assert not store.demote(h, wait=True)
    assert store.tier_of(h) == "host"
    store.close()


def test_host_budget_demotes_lru_first(tmp_path):
    one = _payload(n=1).nbytes
    store = TieredKVStore(spill_dir=str(tmp_path), host_bytes=2 * one)
    a = store.put(_payload(n=1, seed=1))
    b = store.put(_payload(n=1, seed=2))
    store.read(b)  # touch: a is now the LRU entry
    c = store.put(_payload(n=1, seed=3))  # over budget: the coldest demotes
    for _ in range(500):  # async writer: poll the commit
        if store.tier_of(a) == "disk":
            break
        time.sleep(0.01)
    assert store.tier_of(a) == "disk"
    assert store.tier_of(b) == "host" and store.tier_of(c) == "host"
    store.close()


def test_pinned_entries_never_demote(tmp_path):
    one = _payload(n=1).nbytes
    store = TieredKVStore(spill_dir=str(tmp_path), host_bytes=one)
    a = store.put(_payload(n=1, seed=1), pin_host=True)
    store.put(_payload(n=1, seed=2))
    assert not store.demote(a, wait=True)
    assert store.tier_of(a) == "host"
    store.pin(a, False)
    assert store.demote(a, wait=True)
    assert store.tier_of(a) == "disk"
    store.close()


def test_read_races_demote_and_reclaims_to_host(tmp_path):
    """The demote_race: a read arriving while the writer is mid-spill wins —
    the entry reclaims to host, the writer's commit re-check discards its
    orphan file, and the race is counted (what the ``demote_race`` fleet
    fault point makes deterministic)."""
    store = TieredKVStore(spill_dir=str(tmp_path))
    data = _payload(seed=4)
    h = store.put(data)
    raced = threading.Event()

    def hook(handle):
        # between the spill write and the commit: read NOW
        got, tier = store.read(handle)
        assert tier == "host"  # reclaimed, not served from the half-spill
        np.testing.assert_array_equal(got, data)
        raced.set()

    store.race_hook = hook
    store.demote(h, wait=True)
    assert raced.wait(5)
    assert store.tier_of(h) == "host"  # the reader won
    assert store.stats()["demote_races"] == 1
    # the writer unlinked its orphan: no spill file leaks for a host entry
    assert not list(tmp_path.glob("kv_offload_*.bin"))
    store.close()


def test_configure_retrofits_policy():
    """``configure`` is the serving controller's retrofit hook: the engine's
    store is built before the serving config exists."""
    store = TieredKVStore()
    h = store.put(_payload())
    assert not store.demote(h, wait=True)  # no spill dir yet
    import tempfile
    with tempfile.TemporaryDirectory() as d:
        store.configure(spill_dir=d, host_bytes=None)
        assert store.demote(h, wait=True)
        assert store.tier_of(h) == "disk"
        store.drop(h)
        store.close()


def test_stats_shape():
    store = TieredKVStore()
    h = store.put(_payload(n=3))
    s = store.stats()
    assert s["host_entries"] == 1 and s["host_blocks"] == 3
    assert s["disk_entries"] == 0 and s["disk_blocks"] == 0
    assert s["host_bytes"] == _payload(n=3).nbytes
    for k in ("demotions", "demote_races", "reads_host", "reads_disk",
              "writeback_joins"):
        assert k in s
    store.drop(h)
    store.close()
