"""Config model base.

Reference: ``deepspeed/runtime/config_utils.py:16`` — ``DeepSpeedConfigModel``, a
pydantic base supporting "auto" values and deprecated-field aliasing
(``json_schema_extra={"deprecated": True, "new_param": ...}``).
"""

from functools import reduce
from typing import Dict

from pydantic import BaseModel, ConfigDict, model_validator

from deepspeed_tpu.utils.logging import logger


class DeepSpeedConfigModel(BaseModel):
    """Base for all config blocks; extra fields allowed (forward compat), validation
    on assignment, and reference-style deprecated-field migration."""

    model_config = ConfigDict(
        validate_default=True,
        validate_assignment=True,
        use_enum_values=True,
        populate_by_name=True,
        extra="allow",
        protected_namespaces=(),
        arbitrary_types_allowed=True,
    )

    def __init__(self, strict=False, **data):
        if not strict:  # drop "auto" values so defaults apply (reference behavior)
            data = {k: v for k, v in data.items() if not (v == "auto" and k != "auto")}
        super().__init__(**data)

    def _process_deprecated_field(self, dep_field):
        fields_set = self.model_fields_set
        kwargs = type(self).model_fields[dep_field].json_schema_extra or {}
        new_param_fn = kwargs.get("new_param_fn", lambda x: x)
        param_value = new_param_fn(getattr(self, dep_field))
        new_param = kwargs.get("new_param", "")
        dep_msg = kwargs.get("deprecated_msg", "")
        if dep_field in fields_set:
            logger.warning(f"Config parameter {dep_field} is deprecated" +
                           (f" use {new_param} instead" if new_param else "") +
                           (f". {dep_msg}" if dep_msg else ""))
            if new_param and kwargs.get("set_new_param", True):
                new_param_nested = new_param.split(".")
                if len(new_param_nested) > 1:
                    new_param_name = new_param_nested[-1]
                    first_level_name = new_param_nested[0]
                    new_param_obj = reduce(getattr, new_param_nested[:-1], self)
                else:
                    new_param_name = new_param
                    new_param_obj = self
                try:
                    setattr(new_param_obj, new_param_name, param_value)
                except Exception as e:
                    logger.error(f"Tried setting value for '{new_param}' with value from deprecated '{dep_field}'")
                    raise e

    @model_validator(mode="after")
    def _deprecated_fields_check(self):
        fields = type(self).model_fields
        for field_name, field_info in fields.items():
            kwargs = field_info.json_schema_extra
            if isinstance(kwargs, dict) and kwargs.get("deprecated", False):
                self._process_deprecated_field(field_name)
        return self


def get_scalar_param(param_dict: Dict, param_name: str, param_default_value):
    return param_dict.get(param_name, param_default_value)


def get_list_param(param_dict: Dict, param_name: str, param_default_value):
    return param_dict.get(param_name, param_default_value)


def get_dict_param(param_dict: Dict, param_name: str, param_default_value):
    return param_dict.get(param_name, param_default_value)


def dict_raise_error_on_duplicate_keys(ordered_pairs):
    """Reject duplicate keys in the JSON config (reference config_utils.py)."""
    d = dict((k, v) for k, v in ordered_pairs)
    if len(d) != len(ordered_pairs):
        counter = {}
        for k, _ in ordered_pairs:
            counter[k] = counter.get(k, 0) + 1
        keys = [k for k, v in counter.items() if v > 1]
        raise ValueError(f"Duplicate keys {keys} in DeepSpeed config")
    return d
