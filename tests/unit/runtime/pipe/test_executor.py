"""Schedule-executor tests: the instruction streams must DRIVE real execution
(VERDICT r2 weak #5) — heterogeneous stages and tied weights, the cases the
fused scan engine cannot express."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deepspeed_tpu.runtime.pipe.executor import ScheduleExecutor


def _mse(y, label):
    return jnp.mean((y - label) ** 2)


def test_heterogeneous_pipeline_matches_sequential():
    """3 stages with DIFFERENT widths (8→32→16→1): executor loss/grads must
    equal plain end-to-end autodiff."""
    rng = np.random.default_rng(0)
    p0 = {"w": jnp.asarray(rng.normal(size=(8, 32)) * 0.3, jnp.float32)}
    p1 = {"w": jnp.asarray(rng.normal(size=(32, 16)) * 0.3, jnp.float32)}
    p2 = {"w": jnp.asarray(rng.normal(size=(16, 1)) * 0.3, jnp.float32)}

    def s0(p, x):
        return jnp.tanh(x @ p["w"])

    def s1(p, x):
        return jnp.tanh(x @ p["w"])

    def s2(p, x):
        return x @ p["w"]

    M = 4
    xs = [jnp.asarray(rng.normal(size=(4, 8)), jnp.float32) for _ in range(M)]
    ys = [jnp.asarray(rng.normal(size=(4, 1)), jnp.float32) for _ in range(M)]

    ex = ScheduleExecutor([s0, s1, s2], [p0, p1, p2], _mse, micro_batches=M)
    loss, grads = ex.train_batch(xs, ys)

    def seq_loss(p0, p1, p2):
        tot = 0.0
        for x, y in zip(xs, ys):
            tot = tot + _mse(s2(p2, s1(p1, s0(p0, x))), y)
        return tot / M

    want_loss = seq_loss(p0, p1, p2)
    want_grads = jax.grad(seq_loss, argnums=(0, 1, 2))(p0, p1, p2)
    np.testing.assert_allclose(float(loss), float(want_loss), rtol=1e-6)
    for got, want in zip(grads, want_grads):
        for a, b in zip(jax.tree.leaves(got), jax.tree.leaves(want)):
            # executor accumulates per-microbatch grads (sum); sequential ref
            # averages — scale by M
            np.testing.assert_allclose(np.asarray(a) / 4, np.asarray(b), rtol=2e-5,
                                       atol=1e-6)


def test_tied_weights_reduce():
    """Embedding tied to unembedding across first/last stage: ReduceTiedGrads
    must sum both stages' contributions (reference pipe/module.py:423)."""
    rng = np.random.default_rng(1)
    w = jnp.asarray(rng.normal(size=(8, 8)) * 0.3, jnp.float32)
    mid = {"w": jnp.asarray(rng.normal(size=(8, 8)) * 0.3, jnp.float32)}

    def embed(p, x):
        return x @ p["w"]

    def middle(p, x):
        return jnp.tanh(x @ p["w"])

    def unembed(p, x):
        return x @ p["w"].T

    M = 2
    xs = [jnp.asarray(rng.normal(size=(4, 8)), jnp.float32) for _ in range(M)]
    ys = [jnp.asarray(rng.normal(size=(4, 8)), jnp.float32) for _ in range(M)]

    ex = ScheduleExecutor([embed, middle, unembed], [{"w": w}, mid, {"w": w}], _mse,
                          micro_batches=M, tied_groups=[[0, 2]])
    loss, grads = ex.train_batch(xs, ys)

    def seq_loss(w, pm):
        tot = 0.0
        for x, y in zip(xs, ys):
            tot = tot + _mse(unembed({"w": w}, middle(pm, embed({"w": w}, x))), y)
        return tot / M

    want_w = jax.grad(seq_loss)(w, mid)
    # tied grad = sum of both stages' contributions == d/dw of the shared use
    got_w = np.asarray(grads[0]["w"]) / M
    np.testing.assert_allclose(got_w, np.asarray(want_w), rtol=2e-5, atol=1e-6)
    np.testing.assert_array_equal(np.asarray(grads[0]["w"]), np.asarray(grads[2]["w"]))


def test_unpaired_send_asserts():
    """The executor enforces the same-tick pairing invariant: a corrupted
    stream (send without matching recv) must fail loudly, not deadlock."""
    from deepspeed_tpu.runtime.pipe import schedule as sched

    ex = ScheduleExecutor([lambda p, x: x, lambda p, x: x], [{}, {}], _mse, micro_batches=2)
    orig_steps = sched.TrainSchedule.steps

    def broken_steps(self):
        for cmds in orig_steps(self):
            yield [c for c in cmds if not isinstance(c, sched.RecvActivation)]

    sched.TrainSchedule.steps = broken_steps
    try:
        with pytest.raises(AssertionError):
            ex.train_batch([jnp.zeros((2, 2))] * 2, [jnp.zeros((2, 2))] * 2)
    finally:
        sched.TrainSchedule.steps = orig_steps
