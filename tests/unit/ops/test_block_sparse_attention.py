"""Block-sparse flash kernel vs the masked dense reference (reference:
deepspeed/ops/sparse_attention/matmul.py sdd/dsd tier tests)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deepspeed_tpu.ops.pallas.block_sparse_attention import (block_sparse_attention,
                                                             build_block_lists)
from deepspeed_tpu.ops.sparse_attention.sparse_self_attention import sparse_self_attention
from deepspeed_tpu.ops.sparse_attention.sparsity_config import (BigBirdSparsityConfig,
                                                                FixedSparsityConfig,
                                                                LocalSlidingWindowSparsityConfig)

B, H, D = 2, 4, 64
LB = 16


def _qkv(S, seed=0):
    rng = np.random.default_rng(seed)
    mk = lambda: jnp.asarray(rng.normal(size=(B, H, S, D)), jnp.float32)
    return mk(), mk(), mk()


def _layouts(S):
    return {
        "bigbird": BigBirdSparsityConfig(num_heads=H, block=LB, num_random_blocks=1,
                                         num_sliding_window_blocks=3,
                                         num_global_blocks=1).make_layout(S),
        "fixed": FixedSparsityConfig(num_heads=H, block=LB).make_layout(S),
        "window": LocalSlidingWindowSparsityConfig(num_heads=H, block=LB,
                                                   num_sliding_window_blocks=2).make_layout(S),
    }


@pytest.mark.parametrize("name", ["bigbird", "fixed", "window"])
def test_kernel_matches_masked_reference(name):
    S = 128
    q, k, v = _qkv(S)
    layout = _layouts(S)[name]
    want = sparse_self_attention(q, k, v, layout, LB, impl="masked")
    got = block_sparse_attention(q, k, v, layout, LB)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5)


def test_kernel_gradients_match_masked_reference():
    S = 64
    q, k, v = _qkv(S, seed=3)
    layout = _layouts(S)["bigbird"]

    def loss_kernel(q, k, v):
        return (block_sparse_attention(q, k, v, layout, LB) ** 2).sum()

    def loss_masked(q, k, v):
        return (sparse_self_attention(q, k, v, layout, LB, impl="masked") ** 2).sum()

    gk = jax.grad(loss_kernel, argnums=(0, 1, 2))(q, k, v)
    gm = jax.grad(loss_masked, argnums=(0, 1, 2))(q, k, v)
    for a, b, nm in zip(gk, gm, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-4,
                                   err_msg=f"d{nm}")


def test_empty_rows_output_zero():
    """A head whose layout row is entirely off must emit zeros (masked-ref
    parity), not garbage from skipped online-softmax state."""
    S = 64
    q, k, v = _qkv(S, seed=4)
    nb = S // LB
    layout = np.zeros((H, nb, nb), bool)
    layout[0] = np.eye(nb, dtype=bool)  # head 0: diagonal only
    # head 1 row 2 attends nothing; other rows attend block 0
    layout[1, :, 0] = True
    layout[1, 2, :] = False
    got = np.asarray(block_sparse_attention(q, k, v, jnp.asarray(layout), LB))
    want = np.asarray(sparse_self_attention(q, k, v, jnp.asarray(layout), LB, impl="masked"))
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)
    assert not np.any(got[:, 1, 2 * LB:3 * LB])  # the empty row really is zero


def test_work_scales_with_density():
    """Structural density check: the kernel's walk length is the densest row's
    attended-block count — a denser layout means proportionally more steps."""
    S = 1024
    sparse_l = LocalSlidingWindowSparsityConfig(num_heads=H, block=LB,
                                                num_sliding_window_blocks=2).make_layout(S)
    dense_l = np.ones_like(sparse_l)
    bq, bk = 64, 128  # (bq/LB)*(bk/LB) = 32 — the scalar-prefetch bitfield cap
    idx_s, counts_s, _ = build_block_lists(sparse_l, S, LB, bq, bk)
    idx_d, counts_d, _ = build_block_lists(dense_l, S, LB, bq, bk)
    assert idx_d.shape[2] == S // bk              # dense walks every block
    assert idx_s.shape[2] <= 2                    # window touches <=2 kernel blocks
    assert counts_s.max() <= 2 and counts_d.min() == S // bk


def test_auto_impl_routes_and_masked_masks_compose():
    S = 64
    q, k, v = _qkv(S, seed=5)
    layout = _layouts(S)["fixed"]
    # auto with a padding mask must fall back to masked (and not raise)
    kpm = np.ones((B, S), bool)
    kpm[:, -8:] = False
    out = sparse_self_attention(q, k, v, layout, LB, key_padding_mask=kpm)
    assert out.shape == (B, H, S, D)
    with pytest.raises(ValueError, match="layout only"):
        sparse_self_attention(q, k, v, layout, LB, key_padding_mask=kpm, impl="kernel")
