"""The examples/ quickstarts must actually run (user-facing surface; each
executes in its own process on the virtual CPU mesh and prints OK).

train_zero3.py additionally runs in telemetry mode (DSTPU_TELEMETRY_DIR): the
run must leave a tail-able JSONL metrics stream and a loadable Chrome trace
with fwd/bwd/step and collective spans — the ISSUE-2 acceptance path."""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _run_example(script, extra_env=None):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = env.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
    env.update(extra_env or {})
    r = subprocess.run([sys.executable, os.path.join(REPO, "examples", script)],
                       capture_output=True, text=True, timeout=900, env=env,
                       cwd=REPO)
    assert r.returncode == 0, r.stderr[-800:]
    assert "OK" in r.stdout
    return r


@pytest.mark.parametrize("script", ["serve_v2.py", "autotune.py"])
def test_example_runs(script):
    _run_example(script)


def test_serve_v2_server_mode():
    """serve_v2.py DSTPU_SERVE_MODE=server: a real ServingServer on an
    ephemeral port, two SSE requests in flight concurrently, tokens printed
    as they arrive, graceful drain."""
    r = _run_example("serve_v2.py", extra_env={"DSTPU_SERVE_MODE": "server"})
    assert "[A] token 0:" in r.stdout and "[B] token 0:" in r.stdout
    assert "[A] done: state=DONE" in r.stdout and "[B] done: state=DONE" in r.stdout


def test_serve_v2_fleet_mode():
    """serve_v2.py DSTPU_SERVE_MODE=fleet: 2 prefill + 2 decode in-process
    replicas behind the FleetRouter; both SSE requests cross the
    prefill→decode KV handoff and report per-leg replica attribution."""
    r = _run_example("serve_v2.py", extra_env={"DSTPU_SERVE_MODE": "fleet"})
    assert "[A] done: state=DONE" in r.stdout and "[B] done: state=DONE" in r.stdout
    assert "legs=[('prefill', " in r.stdout  # the handoff actually happened
    assert "per-replica dispatches:" in r.stdout


def test_serve_v2_supervised_mode():
    """serve_v2.py DSTPU_SERVE_MODE=supervised: a ReplicaSupervisor-owned
    fleet survives a replica kill — requests succeed before, during (failover
    to the survivor) and after the automatic restart, and the supervisor
    table in /v1/fleet/stats records the restart."""
    r = _run_example("serve_v2.py", extra_env={"DSTPU_SERVE_MODE": "supervised"})
    assert "[before-kill] done: state=DONE" in r.stdout
    assert "[during-outage] done: state=DONE" in r.stdout
    assert "[after-restart] done: state=DONE" in r.stdout
    assert "restarted sup-mixed-0 automatically (restarts=1)" in r.stdout


def test_train_zero3_kill_resume_chaos_equivalence(tmp_path):
    """THE training chaos-equivalence gate (ISSUE 11): a run SIGKILLed at a
    seeded step and auto-resumed by bin/dstpu_train reaches a step-exact,
    bitwise-identical final loss AND params versus an uninterrupted run."""
    import numpy as np

    steps = "6"
    base = _run_example("train_zero3.py", extra_env={
        "DSTPU_CKPT_DIR": str(tmp_path / "base_ck"),
        "DSTPU_TOTAL_STEPS": steps,
        "DSTPU_FINAL_PARAMS": str(tmp_path / "base.npz")})

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = env.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
    env.update({"DSTPU_CKPT_DIR": str(tmp_path / "kill_ck"),
                "DSTPU_TOTAL_STEPS": steps,
                "DSTPU_KILL_AT_STEP": "3",
                "DSTPU_FINAL_PARAMS": str(tmp_path / "kill.npz")})
    r = subprocess.run([sys.executable, os.path.join(REPO, "bin", "dstpu_train"),
                        "--backoff-base", "0.05", "--",
                        sys.executable, os.path.join(REPO, "examples", "train_zero3.py")],
                       capture_output=True, text=True, timeout=900, env=env, cwd=REPO)
    assert r.returncode == 0, r.stderr[-800:]
    assert "OK" in r.stdout
    assert "dstpu_train: exit rc=0 restarts=1" in r.stdout  # it really died once
    assert "resumed from" in r.stdout  # ...and resumed from a checkpoint

    final = [ln for ln in base.stdout.splitlines() if ln.startswith("final step")]
    final_kill = [ln for ln in r.stdout.splitlines() if ln.startswith("final step")]
    assert final and final_kill
    assert final[-1] == final_kill[-1], \
        f"killed+resumed final loss diverged: {final[-1]!r} vs {final_kill[-1]!r}"
    with np.load(tmp_path / "base.npz") as a, np.load(tmp_path / "kill.npz") as b:
        assert set(a.files) == set(b.files)
        for key in a.files:
            assert np.array_equal(a[key], b[key]), \
                f"param {key} not bitwise-identical after kill+resume"


def test_train_zero3_with_telemetry(tmp_path):
    _run_example("train_zero3.py", extra_env={"DSTPU_TELEMETRY_DIR": str(tmp_path)})

    # JSONL metrics stream: per-step events carrying loss / lr / samples-per-sec
    events = [json.loads(line)
              for line in (tmp_path / "telemetry.jsonl").read_text().splitlines()]
    steps = [e for e in events if e["event"] == "train_step"]
    assert len(steps) >= 20
    assert all("loss" in e and "lr" in e for e in steps)
    assert any("samples_per_sec" in e for e in steps)

    # Chrome trace: valid JSON, monotonic ts, complete (X) events, and both
    # the engine phases and a collective present
    with open(tmp_path / "telemetry.trace.json") as f:
        trace = json.load(f)
    evs = trace["traceEvents"]
    names = {e["name"] for e in evs}
    assert {"fwd_microstep", "bwd_microstep", "step_microstep",
            "train_batch", "all_reduce"} <= names
    assert [e["ts"] for e in evs] == sorted(e["ts"] for e in evs)
    assert all(e["ph"] == "X" for e in evs)
