"""Model-free speculative drafters.

Role model: prompt-lookup decoding (Saxena) / n-gram speculative drafting as
shipped in vLLM and transformers — draft tokens come from cheap host-side
pattern matching instead of a second model, so drafting costs microseconds
and is exact-cost-free when rejected (the verify forward prices ``1+k``
positions for the cost of one ragged dispatch).

Two mining sources, tried in order:

1. **prefix-cache trie** (when the scheduler runs one): the radix trie holds
   the token histories of every published sequence — if the request's own
   history is an indexed path, the children spell out exactly what a previous
   request generated after the same tokens (the repeated-request /
   multi-turn / templated-traffic shape, 100% acceptance under greedy);
2. **self prompt-lookup**: the longest n-gram suffix of the request's own
   history that occurred earlier in that history; the tokens that followed
   the earlier occurrence become the draft (the code/chat repetition shape).

The drafter is stateless; per-request adaptation (the acceptance EWMA that
shrinks ``k`` to 0 on adversarial text) lives with the request in the
serving scheduler.
"""

from typing import Optional

import numpy as np


class PromptLookupDrafter:
    """N-gram prompt-lookup over a token history, plus optional continuation
    mining from a :class:`~deepspeed_tpu.inference.v2.ragged.prefix_cache.
    PrefixCache` trie. ``draft`` never proposes more than ``k`` tokens and
    returns empty when no source matches — the caller falls back to the plain
    single-token decode step (k=0)."""

    def __init__(self, min_ngram: int = 1, max_ngram: int = 3,
                 prefix_cache=None):
        if min_ngram < 1 or max_ngram < min_ngram:
            raise ValueError(f"need 1 <= min_ngram <= max_ngram "
                             f"(got {min_ngram}, {max_ngram})")
        self._min_ngram = int(min_ngram)
        self._max_ngram = int(max_ngram)
        self._prefix_cache = prefix_cache

    def draft(self, history, k: int, digests=None) -> np.ndarray:
        """Up to ``k`` proposed continuation tokens of ``history`` (the
        request's prompt + generated tokens, most recent last). ``digests``
        is the history's precomputed full-block digest chain when the caller
        has one (the scheduler hashes each prompt once at admission)."""
        if k <= 0:
            return np.empty(0, np.int32)
        history = np.asarray(history, np.int32).reshape(-1)
        if self._prefix_cache is not None:
            toks = self._prefix_cache.lookup_continuation(history, k,
                                                          digests=digests)
            if toks.size:
                return toks
        return self._self_lookup(history, k)

    def _self_lookup(self, history: np.ndarray, k: int) -> np.ndarray:
        """Longest-n-gram suffix match within the history itself: the MOST
        RECENT earlier occurrence wins (recency tracks the local pattern —
        the convention prompt-lookup implementations share)."""
        H = history.size
        for n in range(min(self._max_ngram, H - 1), self._min_ngram - 1, -1):
            pattern = history[H - n:]
            # candidate start positions of earlier occurrences (the suffix
            # itself starts at H - n and is excluded)
            windows = np.lib.stride_tricks.sliding_window_view(history, n)
            hits = np.nonzero((windows[:H - n] == pattern).all(axis=1))[0]
            if hits.size == 0:
                continue
            start = int(hits[-1]) + n  # continuation of the freshest match
            if start >= H:
                continue
            return np.array(history[start:start + k], np.int32, copy=True)
        return np.empty(0, np.int32)
