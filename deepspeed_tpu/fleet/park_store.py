"""Router-side parked-session store (the fleet half of tiered KV memory).

A session that finishes a turn but will plausibly return — chat, agent loops —
does not have to recompute its history next turn: the replica exports the
sequence as a *park frame* (``ragged/handoff.py`` ``PARK_VERSION``, carrying a
versioned ``extra["tier"]`` record) and the router banks it here, keyed by the
client's session key (the ``X-DSTPU-Session`` header / JSON ``session``
field). When the session's next turn arrives — a ``/v1/generate`` whose prompt
*strictly extends* the parked token history — the router dispatches a
*rehydrate* leg instead (``/v1/resume`` with both the payload and the new
prompt) on whichever replica wins placement: the parked turns' KV imports, only
the new suffix prefills, and the continuation is bitwise-identical to a cold
run at the same seed. Because the frame is self-describing and CRC-covered,
the session rehydrates on ANY replica with matching KV geometry, not just the
one that parked it.

The store is a bounded LRU: a session-count cap, a byte budget, and a TTL.
Eviction drops the coldest session — a dropped park costs the next turn a cold
prefill, never correctness. Every ``put`` re-validates the frame (framing,
header schema, CRC), so a corrupt payload is refused at park time; a frame
that a *replica* refuses at rehydrate time (``park_store_corrupt`` in transit,
or rot at rest) is dropped via :meth:`reject` and the turn falls back cold.
"""

import threading
import time
from collections import OrderedDict
from typing import List, Optional

from deepspeed_tpu.inference.v2.ragged.handoff import PARK_VERSION, unpack
from deepspeed_tpu.utils.logging import logger


class ParkedSession:
    """One banked session: the pristine frame plus its parsed-once header
    facts (the match predicate never re-parses the payload)."""

    __slots__ = ("payload", "tokens", "seen_tokens", "tier_source",
                 "replica_id", "parked_at_s", "last_touch_s")

    def __init__(self, payload: bytes, tokens: List[int], seen_tokens: int,
                 tier_source: Optional[str], replica_id: Optional[str]):
        self.payload = payload
        self.tokens = tokens
        self.seen_tokens = seen_tokens
        self.tier_source = tier_source
        self.replica_id = replica_id
        self.parked_at_s = time.monotonic()
        self.last_touch_s = self.parked_at_s

    @property
    def nbytes(self) -> int:
        return len(self.payload)


class ParkStore:
    """LRU/TTL/byte-budgeted map: session key → :class:`ParkedSession`.

    Thread-safe (router handler threads park and rehydrate concurrently).
    Counter semantics: ``parks`` = frames banked; ``rehydrate_hits`` = matches
    handed to a rehydrate dispatch; ``rehydrate_misses`` = a *known* session
    key that could not be used (expired, or the new prompt diverged from the
    parked history — the entry is dropped, histories never un-diverge);
    ``corrupt_rejects`` = entries dropped because a frame was refused (at park
    validation or by the rehydrating replica); ``evictions`` = budget/TTL
    drops. A session key the store never saw counts nothing — a first turn is
    not a miss.
    """

    def __init__(self, config=None, metrics=None):
        from deepspeed_tpu.fleet.config import ParkConfig
        self._config = config or ParkConfig()
        self._metrics = metrics
        self._lock = threading.Lock()
        self._sessions: "OrderedDict[str, ParkedSession]" = OrderedDict()
        self._bytes = 0
        self._counters = {"parks": 0, "rehydrate_hits": 0,
                          "rehydrate_misses": 0, "corrupt_rejects": 0,
                          "evictions": 0}

    # ------------------------------------------------------------------ park --
    def put(self, session_key: str, payload: bytes,
            replica_id: Optional[str] = None) -> bool:
        """Bank one park frame under ``session_key`` (replacing any previous
        turn's frame — the newest turn's history subsumes the old). The frame
        is fully validated here (framing, schema, KV CRC); an invalid one is
        counted as a corrupt reject and refused. Returns True when banked."""
        try:
            header, _ = unpack(payload)
            if header["version"] < PARK_VERSION:
                raise ValueError(
                    f"park frame must be version >= {PARK_VERSION}, "
                    f"got {header['version']}")
        except (ValueError, TypeError, KeyError) as e:
            with self._lock:
                self._counters["corrupt_rejects"] += 1
            if self._metrics is not None:
                self._metrics.park_corrupt_rejects.inc()
            logger.warning(f"fleet: park frame for session {session_key!r} "
                           f"refused at validation: {e}")
            return False
        tier = (header.get("extra") or {}).get("tier") or {}
        entry = ParkedSession(bytes(payload), list(header["tokens"]),
                              int(header["seen_tokens"]),
                              tier.get("source"), replica_id)
        with self._lock:
            old = self._sessions.pop(session_key, None)
            if old is not None:
                self._bytes -= old.nbytes
            self._sessions[session_key] = entry
            self._bytes += entry.nbytes
            self._counters["parks"] += 1
            self._evict_locked()
        if self._metrics is not None:
            self._metrics.parks.inc()
        self._update_gauges()
        return True

    def _evict_locked(self) -> None:
        """Enforce TTL, the session cap and the byte budget (caller holds the
        lock). Oldest-touch first — the OrderedDict IS the LRU order."""
        now = time.monotonic()
        ttl = self._config.ttl_s
        evicted = 0
        if ttl > 0:
            for key in [k for k, e in self._sessions.items()
                        if now - e.last_touch_s > ttl]:
                self._bytes -= self._sessions.pop(key).nbytes
                evicted += 1
        while self._sessions and (
                len(self._sessions) > self._config.max_sessions
                or self._bytes > self._config.max_bytes):
            _, entry = self._sessions.popitem(last=False)
            self._bytes -= entry.nbytes
            evicted += 1
        if evicted:
            self._counters["evictions"] += evicted
            if self._metrics is not None:
                self._metrics.park_evictions.inc(evicted)

    # ------------------------------------------------------------- rehydrate --
    def match(self, session_key: str, prompt) -> Optional[ParkedSession]:
        """The parked session for ``session_key`` iff the new turn's
        ``prompt`` strictly extends its token history (same predicate the
        rehydrating scheduler enforces — a non-matching dispatch would only
        bounce). A diverged prompt drops the entry: histories never
        re-converge, so keeping it would miss every future turn too."""
        prompt = [int(t) for t in prompt]
        with self._lock:
            entry = self._sessions.get(session_key)
            if entry is None:
                return None
            now = time.monotonic()
            ttl = self._config.ttl_s
            if ttl > 0 and now - entry.last_touch_s > ttl:
                self._bytes -= self._sessions.pop(session_key).nbytes
                self._counters["evictions"] += 1
                self._counters["rehydrate_misses"] += 1
                miss_reason = "expired"
            elif not (len(prompt) > len(entry.tokens)
                      and prompt[:len(entry.tokens)] == entry.tokens):
                # diverged (or not longer): unusable now and forever
                self._bytes -= self._sessions.pop(session_key).nbytes
                self._counters["rehydrate_misses"] += 1
                miss_reason = "diverged"
            else:
                entry.last_touch_s = now
                self._sessions.move_to_end(session_key)
                self._counters["rehydrate_hits"] += 1
                miss_reason = None
        if miss_reason is not None:
            if self._metrics is not None:
                self._metrics.park_rehydrate_misses.inc()
            logger.info(f"fleet: parked session {session_key!r} miss "
                        f"({miss_reason})")
            self._update_gauges()
            return None
        if self._metrics is not None:
            self._metrics.park_rehydrates.inc()
        return entry

    def reject(self, session_key: str) -> None:
        """A rehydrating replica refused this session's frame (CRC/framing —
        corruption in transit or at rest): drop it and count the reject; the
        caller falls back to a cold full-prompt run."""
        with self._lock:
            entry = self._sessions.pop(session_key, None)
            if entry is not None:
                self._bytes -= entry.nbytes
            self._counters["corrupt_rejects"] += 1
        if self._metrics is not None:
            self._metrics.park_corrupt_rejects.inc()
        self._update_gauges()

    def drop(self, session_key: str) -> None:
        with self._lock:
            entry = self._sessions.pop(session_key, None)
            if entry is not None:
                self._bytes -= entry.nbytes
        self._update_gauges()

    # ----------------------------------------------------------------- stats --
    def __len__(self) -> int:
        with self._lock:
            return len(self._sessions)

    def _update_gauges(self) -> None:
        if self._metrics is None:
            return
        with self._lock:
            n, b = len(self._sessions), self._bytes
        self._metrics.park_sessions.set(n)
        self._metrics.park_bytes.set(b)

    def stats(self) -> dict:
        """``/v1/fleet/stats`` park block: occupancy plus the counter set and
        a bounded per-session inventory (``dstpu_report --kv`` renders it)."""
        with self._lock:
            sessions = [{"session": key, "tokens": len(e.tokens),
                         "bytes": e.nbytes, "tier_source": e.tier_source,
                         "parked_by": e.replica_id,
                         "age_s": round(time.monotonic() - e.parked_at_s, 3)}
                        for key, e in list(self._sessions.items())[-32:]]
            return {"sessions": len(self._sessions), "bytes": self._bytes,
                    "max_sessions": self._config.max_sessions,
                    "max_bytes": self._config.max_bytes,
                    "ttl_s": self._config.ttl_s,
                    **dict(self._counters),
                    "inventory": sessions}
