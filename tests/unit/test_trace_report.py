"""``dstpu_report --trace``: per-request timeline report from a Chrome trace
or a flight-recorder dump (ISSUE satellite)."""

import json

from deepspeed_tpu.env_report import main as report_main
from deepspeed_tpu.env_report import trace_report


def _chrome_trace(tmp_path):
    trace, root = "aabbccdd00112233", 1
    events = [
        {"name": "request", "cat": "serving", "ph": "X", "ts": 0, "dur": 10000,
         "pid": 1, "tid": 1,
         "args": {"uid": 4, "state": "DONE", "finish_reason": "length",
                  "prompt_tokens": 24, "generated": 3,
                  "trace_id": trace, "span_id": root, "parent_id": None}},
        {"name": "queued", "cat": "serving", "ph": "X", "ts": 0, "dur": 1000,
         "pid": 1, "tid": 1,
         "args": {"uid": 4, "trace_id": trace, "span_id": 2, "parent_id": root}},
        {"name": "prefill", "cat": "serving", "ph": "X", "ts": 1000, "dur": 4000,
         "pid": 1, "tid": 1,
         "args": {"uid": 4, "tokens": 24, "trace_id": trace, "span_id": 3,
                  "parent_id": root}},
        {"name": "decode", "cat": "serving", "ph": "X", "ts": 5000, "dur": 2000,
         "pid": 1, "tid": 1,
         "args": {"uid": 4, "tokens": 1, "trace_id": trace, "span_id": 4,
                  "parent_id": root}},
        {"name": "decode", "cat": "serving", "ph": "X", "ts": 7000, "dur": 2000,
         "pid": 1, "tid": 1,
         "args": {"uid": 4, "tokens": 1, "trace_id": trace, "span_id": 5,
                  "parent_id": root}},
        {"name": "xla_compile", "cat": "compile", "ph": "X", "ts": 5500,
         "dur": 500, "pid": 1, "tid": 0, "args": {"site": "inference_forward"}},
        {"name": "xla_compile", "cat": "compile", "ph": "X", "ts": 90000,
         "dur": 500, "pid": 1, "tid": 0, "args": {"site": "train"}},
    ]
    path = tmp_path / "trace.json"
    path.write_text(json.dumps({"traceEvents": events}))
    return str(path), trace


def test_trace_report_prints_request_timeline(tmp_path, capsys):
    path, trace = _chrome_trace(tmp_path)
    assert report_main(["--trace", path]) == 0
    out = capsys.readouterr().out
    assert f"request uid=4 trace={trace} [DONE, length]" in out
    assert "24t / 3t" in out
    assert "1.000 ms" in out            # queued
    assert "(1 chunks)" in out          # prefill
    assert "(2 iterations, 2 tokens)" in out
    # only the overlapping compile counts, not the one outside the window
    assert "recompiles overlapped  1" in out


def test_trace_report_reads_flight_recorder_dumps(tmp_path, capsys):
    spans = [{"name": "request", "cat": "serving", "ts_us": 0, "dur_us": 5000,
              "trace_id": "ff00ff00ff00ff00", "span_id": 1, "parent_id": None,
              "args": {"uid": 9, "state": "CANCELLED", "prompt_tokens": 4,
                       "generated": 1}},
             {"name": "queued", "cat": "serving", "ts_us": 0, "dur_us": 500,
              "trace_id": "ff00ff00ff00ff00", "span_id": 2, "parent_id": 1,
              "args": {"uid": 9}}]
    path = tmp_path / "flight_1_0001_api.json"
    path.write_text(json.dumps({"meta": {}, "spans": spans}))
    assert trace_report(str(path)) == 0
    out = capsys.readouterr().out
    assert "request uid=9 trace=ff00ff00ff00ff00 [CANCELLED]" in out
    assert "0.500 ms" in out


def test_trace_report_handles_traceless_and_bad_files(tmp_path, capsys):
    empty = tmp_path / "empty.json"
    empty.write_text(json.dumps({"traceEvents": []}))
    assert trace_report(str(empty)) == 0
    assert "no request traces" in capsys.readouterr().out

    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps({"something": "else"}))
    assert trace_report(str(bad)) == 1
    assert trace_report(str(tmp_path / "missing.json")) == 1

    assert report_main(["--trace"]) == 2  # missing operand → usage
    capsys.readouterr()
