"""Pipeline schedules as instruction streams.

Capability parity with ``deepspeed/runtime/pipe/schedule.py`` (PipeSchedule,
InferenceSchedule:135, TrainSchedule:189, DataParallelSchedule:301) but derived
in-house from a single closed-form tick equation rather than the reference's four
parity-cased index helpers.

Derivation (non-interleaved 1F1B over P stages, M microbatches):

The pipeline runs on a global clock of ``T = 2*(M + P - 1)`` ticks. Forward work
for microbatch ``f`` enters stage 0 at tick ``2f`` and moves down one stage per
tick, so stage ``s`` runs forward(f) at tick ``t = s + 2f``. Backward work for
microbatch ``b`` leaves the last stage and climbs one stage per tick such that
stage ``s`` runs backward(b) at tick ``t = 2*(b + P) - s - 1``. Solving both for
the work at (t, s):

    (t - s) even  ->  FORWARD  on  f = (t - s) // 2
    (t - s) odd   ->  BACKWARD on  b = (t + s + 1) // 2 - P

Each is executed only when the microbatch index lies in [0, M). Every stage
alternates forward/backward ticks (1F1B steady state), warmup/drain fall out of
the validity window automatically.

Communication pairing (the invariant a step-synchronized executor needs): at any
tick t, a send on stage s must pair with the neighbor's recv at the *same* t.
  - Stage s forwards f at tick t; stage s+1 forwards f at t+1. The activation
    computed at tick t therefore has to be shipped during tick t+1 — which is a
    backward tick for s (and a forward tick for s+1). Hence on a BACKWARD tick,
    stage s emits SendActivation(f_prev) where f_prev is its forward work of the
    previous tick, while s+1 (on its forward tick) emits RecvActivation(f_prev).
  - Symmetrically, stage s backwards b at tick t; stage s-1 needs that gradient
    at t+1 (its backward tick), so on a FORWARD tick stage s emits
    SendGrad(b_prev) and s-1 emits RecvGrad(b_prev) at the same tick.

On TPU the hot-path *execution* of a schedule is a jitted scan with ppermute
(XLA overlaps compute and stage transfers; see pipe/engine.py); these streams
drive the host-level fallback executor (pipe/executor.py ScheduleExecutor —
heterogeneous stages, TiedLayerSpec weight sharing) and pin the ordering.
"""

from abc import ABC, abstractmethod

FORWARD = "forward"
BACKWARD = "backward"


class PipeSchedule(ABC):
    """Yields, per clock tick, the list of PipeInstructions for one stage."""

    def __init__(self, micro_batches, stages, stage_id):
        self.micro_batches = micro_batches
        self.stages = stages
        self.stage_id = stage_id
        self.prev_stage = stage_id - 1
        self.next_stage = stage_id + 1

    @abstractmethod
    def steps(self):
        ...

    def num_pipe_buffers(self):
        return self.micro_batches

    def _valid_micro_batch(self, micro_batch_id):
        return 0 <= micro_batch_id < self.micro_batches

    def _valid_stage(self, stage_id):
        return 0 <= stage_id < self.stages

    @property
    def stage(self):
        return self.stage_id

    @property
    def num_stages(self):
        return self.stages

    @property
    def num_micro_batches(self):
        return self.micro_batches

    @property
    def is_first_stage(self):
        return self.stage_id == 0

    @property
    def is_last_stage(self):
        return self.stage_id == self.stages - 1

    def _buffer_idx(self, micro_batch_id):
        assert self._valid_micro_batch(micro_batch_id)
        return micro_batch_id % self.num_pipe_buffers()

    def __iter__(self):
        self.it = None
        return self

    def __next__(self):
        if self.it is None:
            self.it = self.steps()
        return next(self.it)


class TrainSchedule(PipeSchedule):
    """1F1B instruction stream from the closed-form tick equation above."""

    def work_at(self, tick):
        """(direction, micro_batch_id) of this stage's compute slot at ``tick``.

        The microbatch may be outside [0, M) — warmup/drain ticks — in which
        case the slot is idle but the tick still has a well-defined direction.
        """
        if (tick - self.stage_id) % 2 == 0:
            return FORWARD, (tick - self.stage_id) // 2
        return BACKWARD, (tick + self.stage_id + 1) // 2 - self.stages

    def steps(self):
        total_ticks = 2 * (self.micro_batches + self.stages - 1)
        for tick in range(total_ticks):
            direction, mb = self.work_at(tick)
            cmds = []

            if direction == FORWARD:
                # Ship the gradient produced on the previous (backward) tick
                # upstream; the upstream stage recvs it on this same tick.
                if tick > 0 and self._valid_stage(self.prev_stage):
                    _, prev_b = self.work_at(tick - 1)
                    if self._valid_micro_batch(prev_b):
                        cmds.append(SendGrad(self._buffer_idx(prev_b)))
                if self._valid_micro_batch(mb):
                    if self._valid_stage(self.prev_stage):
                        cmds.append(RecvActivation(self._buffer_idx(mb)))
                    if self.is_first_stage or self.is_last_stage:
                        cmds.append(LoadMicroBatch(self._buffer_idx(mb)))
                    cmds.append(ForwardPass(self._buffer_idx(mb)))
            else:
                if self._valid_micro_batch(mb) and self._valid_stage(self.next_stage):
                    cmds.append(RecvGrad(self._buffer_idx(mb)))
                # Ship the activation produced on the previous (forward) tick
                # downstream; the downstream stage recvs it on this same tick.
                if tick > 0 and self._valid_stage(self.next_stage):
                    _, prev_f = self.work_at(tick - 1)
                    if self._valid_micro_batch(prev_f):
                        cmds.append(SendActivation(self._buffer_idx(prev_f)))
                if self._valid_micro_batch(mb):
                    cmds.append(BackwardPass(self._buffer_idx(mb)))

            if tick == total_ticks - 1:
                cmds.append(ReduceTiedGrads())
                cmds.append(ReduceGrads())
                cmds.append(OptimizerStep())

            yield cmds

    def num_pipe_buffers(self):
        """In-flight activations at stage s peak at the number of forwards that
        run before the first backward = min(P - s, M); floor of 2 so the
        send/compute double-buffering never aliases."""
        return max(2, min(self.stages - self.stage_id, self.micro_batches))


class InferenceSchedule(PipeSchedule):
    """Forward-only pipelining: microbatch f hits stage s at tick s + f.

    The activation computed at tick t is sent at tick t+1, pairing with the
    downstream stage's RecvActivation at that same tick (downstream forwards mb
    at tick s+1+mb) — the same same-tick send/recv invariant as TrainSchedule.
    """

    def steps(self):
        for tick in range(self.micro_batches + self.stages - 1):
            mb = tick - self.stage_id
            cmds = []
            prev_mb = mb - 1
            if self._valid_micro_batch(prev_mb) and self._valid_stage(self.next_stage):
                cmds.append(SendActivation(self._buffer_idx(prev_mb)))
            if self._valid_micro_batch(mb):
                if self.is_first_stage or self.is_last_stage:
                    cmds.append(LoadMicroBatch(self._buffer_idx(mb)))
                if self._valid_stage(self.prev_stage):
                    cmds.append(RecvActivation(self._buffer_idx(mb)))
                cmds.append(ForwardPass(self._buffer_idx(mb)))
            yield cmds

    def num_pipe_buffers(self):
        return 2


class DataParallelSchedule(PipeSchedule):
    """Degenerate single-stage schedule (pure gradient accumulation)."""

    def steps(self):
        for step_id in range(self.micro_batches):
            cmds = [
                LoadMicroBatch(buffer_id=0),
                ForwardPass(buffer_id=0),
                BackwardPass(buffer_id=0),
            ]
            if step_id == self.micro_batches - 1:
                cmds.extend([ReduceGrads(), OptimizerStep()])
            yield cmds

    def num_pipe_buffers(self):
        return 1


class PipeInstruction:

    def __init__(self, **kwargs):
        self.name = self.__class__.__name__
        self.kwargs = kwargs
        for key, val in kwargs.items():
            setattr(self, key, val)

    def __repr__(self):
        from deepspeed_tpu.runtime.utils import call_to_str
        return call_to_str(self.name, **self.kwargs)

    def __eq__(self, other):
        return type(self) is type(other) and self.kwargs == other.kwargs


class OptimizerStep(PipeInstruction):
    ...


class ReduceGrads(PipeInstruction):
    ...


class ReduceTiedGrads(PipeInstruction):
    ...


class BufferOpInstruction(PipeInstruction):

    def __init__(self, buffer_id, **kwargs):
        super().__init__(buffer_id=buffer_id, **kwargs)


class LoadMicroBatch(BufferOpInstruction):
    ...


class ForwardPass(BufferOpInstruction):
    ...


class BackwardPass(BufferOpInstruction):
    ...


class SendActivation(BufferOpInstruction):
    ...


class RecvActivation(BufferOpInstruction):
    ...


class SendGrad(BufferOpInstruction):
    ...


class RecvGrad(BufferOpInstruction):
    ...
