"""``ds_report`` analog: environment / compatibility report.

Reference: ``deepspeed/env_report.py:182`` — prints the op-compat matrix,
torch/cuda versions and install paths. The TPU report covers what matters
here: JAX backend + devices, default mesh axes, library versions, and which
native/pallas subsystems are usable on this backend.
"""

import importlib
import sys


def _version(mod):
    try:
        return importlib.import_module(mod).__version__
    except Exception:
        return "not installed"


GREEN_OK = "\033[92m[OKAY]\033[0m"
RED_NO = "\033[93m[NO]\033[0m"


def metrics_report(url):
    """``dstpu_report --metrics-url <url>``: scrape a running engine's
    telemetry endpoint and pretty-print it (plus the /healthz verdict)."""
    import json
    import urllib.request

    from deepspeed_tpu.telemetry import scrape_metrics

    base = url if url.startswith(("http://", "https://")) else "http://" + url
    base = base.rstrip("/")
    for suffix in ("/metrics", "/healthz", "/trace"):
        if base.endswith(suffix):
            base = base[:-len(suffix)]
            break
    try:
        with urllib.request.urlopen(base + "/healthz", timeout=5) as resp:
            health = json.loads(resp.read().decode()).get("status", "?")
            health_line = f"{GREEN_OK} ({health}, HTTP {resp.status})"
    except Exception as e:
        health_line = f"{RED_NO} ({e})"
    print("-" * 60)
    print(f"telemetry endpoint ..... {base}")
    print(f"healthz ................ {health_line}")
    print("-" * 60)
    try:
        families = scrape_metrics(base)
    except Exception as e:
        print(f"scrape failed: {e}")
        return 1
    for name in sorted(families):
        fam = families[name]
        header = f"{name} [{fam['type']}]"
        if fam["help"]:
            header += f" — {fam['help']}"
        print(header)
        for sample_name, labels, value in fam["samples"]:
            if sample_name.endswith("_bucket"):
                continue  # count/sum summarize; buckets are for the scraper
            print(f"  {sample_name + _fmt_labels(labels):<44} {value:g}")
        print()
    return 0


def _fmt_labels(labels):
    return "{" + ",".join(f"{k}={v}" for k, v in sorted(labels.items())) + "}" if labels else ""


def main(argv=None):
    argv = list(sys.argv[1:]) if argv is None else list(argv)
    if "--metrics-url" in argv:
        idx = argv.index("--metrics-url")
        if idx + 1 >= len(argv):
            print("usage: dstpu_report --metrics-url <host:port | http://...>")
            return 2
        return metrics_report(argv[idx + 1])
    import deepspeed_tpu
    print("-" * 60)
    print("DeepSpeed-TPU C++/JAX environment report")
    print("-" * 60)
    print(f"deepspeed_tpu version ... {deepspeed_tpu.__version__}")
    print(f"python ................. {sys.version.split()[0]}")
    for mod in ("jax", "jaxlib", "flax", "optax", "orbax.checkpoint", "numpy"):
        print(f"{mod:<22} ... {_version(mod)}")
    print("-" * 60)
    # a dead TPU tunnel HANGS backend init rather than raising — the device
    # facts come from ONE timed subprocess (shared probe; the parent never
    # touches the backend, so the report can't freeze and doesn't pay
    # backend init twice)
    from deepspeed_tpu.utils.jax_platform import probe_backend
    info, why = probe_backend()
    if info is None:
        print(f"backend ................ UNREACHABLE ({why})")
    else:
        mems = info["memory_kinds"]
        print(f"backend ................ {info['backend']}")
        print(f"devices ................ {info['device_count']}: {info['device_kind']}")
        print(f"process count .......... {info['process_count']}")
        print(f"memory kinds ........... {mems}")
        print(f"host offload ........... "
              f"{GREEN_OK if 'pinned_host' in mems else RED_NO}")
    print("-" * 60)
    # native-op compat matrix (reference env_report.py op_report / ds_report)
    from deepspeed_tpu.ops.op_builder import ALL_OPS
    for name, cls in ALL_OPS.items():
        b = cls()
        ok = b.is_compatible()
        print(f"native op {name:<12} ... {GREEN_OK if ok else RED_NO}"
              f"{'' if ok else '  (' + str(b.error_log) + ')'}")
    print("-" * 60)
    from deepspeed_tpu.utils import groups
    print(f"mesh axes .............. {groups.MESH_AXES}")
    if groups.mesh_is_initialized():
        print(f"mesh ................... {dict(groups.get_mesh().shape)}")
    else:
        print("mesh ................... not initialized (created at engine init)")
    print("-" * 60)
    return 0


if __name__ == "__main__":
    sys.exit(main())
