"""Version info for deepspeed_tpu.

Mirrors the role of the reference's ``version.txt`` / ``deepspeed/git_version_info.py``.
"""

__version__ = "0.1.0"
version = __version__
git_hash = "unknown"
git_branch = "main"

# Populated by the op registry at import time (analog of the reference's
# op_builder/all_ops.py + git_version_info installed-ops record).
installed_ops = {}
compatible_ops = {}
