"""Hybrid engine: the RLHF train ↔ generate flip.

Reference: ``deepspeed/runtime/hybrid_engine.py:32`` (DeepSpeedHybridEngine) —
DS-Chat's engine that trains under ZeRO-3 and flips to injected inference
kernels for the generation phase, sweating LoRA fuse/unfuse (:138-152),
inference-container weight sharing (:161) and per-layer param gathers
(``_zero3_forward:363``).

TPU-native: the flip is nearly free. Training params and the inference-v2
model read the *same pytree layout*, so ``generate()`` builds (once) an
:class:`InferenceEngineV2` whose params are a jit-cast view of the live
training masters — re-cast only when the step counter moved. No module
surgery, no gather loops: XLA reshards fp32 ZeRO shards → replicated/TP
compute-dtype arrays in one program. KV-cache blocks are allocated by the
engine at build and recycled by ``flush`` after every generation
(reference's ``release_inference_cache`` semantics).
"""

from typing import Optional, Sequence

from pydantic import Field

from deepspeed_tpu.runtime.config_utils import DeepSpeedConfigModel
from deepspeed_tpu.runtime.engine import DeepSpeedEngine
from deepspeed_tpu.utils.logging import logger


class DeepSpeedHybridEngineConfig(DeepSpeedConfigModel):
    """Reference: ``deepspeed/runtime/config.py`` hybrid_engine block."""
    enabled: bool = False
    max_out_tokens: int = Field(512, ge=1)
    inference_tp_size: int = Field(1, ge=1)
    release_inference_cache: bool = False
    pin_parameters: bool = True
    tp_gather_partition_size: int = Field(8, ge=1)


class DeepSpeedHybridEngine(DeepSpeedEngine):
    """Training engine + in-place generation over the live parameters."""

    def __init__(self, *args, model_config=None, **kwargs):
        super().__init__(*args, **kwargs)
        self._he_config = self._config.hybrid_engine_config
        self._model_config = model_config if model_config is not None \
            else getattr(self.module, "cfg", None)
        self._inference_engine = None
        self._inference_params_step = -1
        self._cast_fn = None

    # ------------------------------------------------------------ param share --
    def _inference_params(self):
        """Live training masters → inference dtype, same tree (the copy the
        reference's inference containers exist to avoid is one XLA cast here)."""
        import jax
        if self._cast_fn is None:
            dtype = getattr(self._model_config, "dtype", self.compute_dtype)
            self._cast_fn = jax.jit(lambda p: jax.tree.map(lambda x: x.astype(dtype), p))
        return self._cast_fn(self.params)

    def _get_inference_engine(self):
        from deepspeed_tpu.inference.v2.config_v2 import RaggedInferenceEngineConfig
        from deepspeed_tpu.inference.v2.engine_factory import build_engine
        from deepspeed_tpu.inference.v2.ragged.manager_configs import (AllocationMode,
                                                                       DSStateManagerConfig,
                                                                       MemoryConfig)

        if self._model_config is None:
            raise ValueError("hybrid engine needs the model config (pass model_config= or "
                             "use a module exposing .cfg, e.g. LlamaForCausalLM)")
        if self._inference_engine is None:
            he = self._he_config
            blocks = max(8, (2 * he.max_out_tokens) // 16)
            mgr = DSStateManagerConfig(memory_config=MemoryConfig(mode=AllocationMode.ALLOCATE,
                                                                  size=blocks),
                                       max_context=he.max_out_tokens)
            ecfg = RaggedInferenceEngineConfig(state_manager=mgr, kv_block_size=16)
            self._inference_engine = build_engine(self._inference_params(), self._model_config,
                                                  ecfg)
            self._inference_params_step = self.global_steps
            logger.info(f"hybrid engine: built inference engine "
                        f"(max_out_tokens={he.max_out_tokens}, kv blocks={blocks})")
        elif self._inference_params_step != self.global_steps:
            # weights moved: re-cast the live masters into the existing engine
            self._inference_engine._model._params = self._inference_params()
            self._inference_params_step = self.global_steps
        return self._inference_engine

    # --------------------------------------------------------------- generate --
    def generate(self, prompts: Sequence[Sequence[int]], max_new_tokens: int = 16,
                 temperature: float = 0.0, eos_token_id: Optional[int] = None, seed: int = 0):
        """Reference hybrid_engine.py:174 — generation over the live weights.
        Returns a list of generated token lists; KV blocks are recycled after."""
        from deepspeed_tpu.inference.v2 import engine_factory

        was_training = self.training
        self.eval()
        engine = self._get_inference_engine()
        try:
            return engine_factory.generate(engine, prompts, max_new_tokens=max_new_tokens,
                                           temperature=temperature, eos_token_id=eos_token_id,
                                           seed=seed)
        finally:
            engine.flush_all()
            if self._he_config.release_inference_cache:
                self._inference_engine = None
            self.train(was_training)

    # ---------------------------------------------------- draft-head distill --
    def distill_draft_head(self, num_heads: int = 3, steps: int = 150,
                           max_new_tokens: int = 48, seed: int = 0, **kw):
        """Self-distill speculative draft heads against the LIVE training
        weights (inference/v2/spec/distill.py): the corpus is generated
        in-process through this engine's generate path — the RLHF shape,
        where the policy drifts every step and the drafter must track it
        without an external dataset. Returns ``(MedusaDraftHead, losses)``;
        KV blocks recycle after, like :meth:`generate`."""
        from deepspeed_tpu.inference.v2.spec.distill import self_distill

        was_training = self.training
        self.eval()
        engine = self._get_inference_engine()
        try:
            return self_distill(engine, num_heads=num_heads, steps=steps,
                                max_new_tokens=max_new_tokens, seed=seed, **kw)
        finally:
            engine.flush_all()
            self.train(was_training)

    @property
    def inference_engine(self):
        return self._get_inference_engine()
