from deepspeed_tpu.module_inject.auto_tp import auto_tp_specs
from deepspeed_tpu.module_inject.layers import (EmbeddingLayer, LinearAllreduce, LinearLayer,
                                                Normalize)
