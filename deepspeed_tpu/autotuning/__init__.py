from deepspeed_tpu.autotuning.autotuner import Autotuner
