"""Learned draft heads: Medusa-style drafting over the target's hidden state.

Role model: Medusa (Cai et al.) / EAGLE-class drafters — instead of a second
autoregressive model, ``num_heads`` tiny MLP heads read the TARGET model's
last hidden state (the pre-unembed residual the verify forward already
computed) and each predicts one future offset: head ``h`` guesses the token
``h + 2`` positions past the hidden state's own token. Drafting is a few
numpy GEMVs on the host — no extra device dispatch, no second KV cache —
and unlike prompt-lookup it proposes on text that never repeats, because the
heads are trained (spec/distill.py) on the target model's OWN outputs.

Offset bookkeeping (the classic Medusa off-by-one): when the scheduler holds
hidden state for position ``t`` it has ALREADY emitted token ``t + 1`` (the
same forward's logits produced it). That emitted token becomes the tree
root; head ``h``'s candidates populate tree depth ``h + 1``.

The heads are per-offset independent (no path conditioning), so a token
tree built from them shares one candidate set per depth; the joint path
score is the product of per-head probabilities and the tree grows
best-first under the node budget (spec/tree.py carries it to the
tree-verify forward).
"""

import heapq
import io
from typing import List, Optional, Tuple

import numpy as np

from deepspeed_tpu.inference.v2.spec.tree import TokenTree

_EPS = 1e-6


def _relu(x: np.ndarray) -> np.ndarray:
    return np.maximum(x, 0.0)


def _log_softmax(logits: np.ndarray) -> np.ndarray:
    m = logits.max(axis=-1, keepdims=True)
    z = logits - m
    return z - np.log(np.exp(z).sum(axis=-1, keepdims=True))


class MedusaDraftHead:
    """``num_heads`` independent 2-layer MLP heads over an L2-normalized
    hidden state. Pure numpy and deterministic end to end — the same weights
    draft the same tree on every replica, which is what lets a handoff
    (serving/scheduler.py) resume speculation mid-request: the receiving
    replica checks ``head_id`` and keeps drafting where the sender stopped.
    """

    def __init__(self, params: List[dict], head_id: str) -> None:
        if not params:
            raise ValueError("need at least one draft head")
        self.params = params
        self.head_id = str(head_id)
        self.hidden_dim = int(params[0]["W1"].shape[0])
        self.vocab_size = int(params[0]["W2"].shape[1])

    # --- construction -----------------------------------------------------
    @classmethod
    def fresh(cls, hidden_dim: int, vocab_size: int, num_heads: int = 3,
              mlp_dim: Optional[int] = None, seed: int = 0) -> "MedusaDraftHead":
        if num_heads < 1:
            raise ValueError("need num_heads >= 1")
        mlp_dim = int(mlp_dim if mlp_dim is not None else 2 * hidden_dim)
        rng = np.random.default_rng(seed)
        params = []
        for _ in range(num_heads):
            params.append(dict(
                W1=(rng.standard_normal((hidden_dim, mlp_dim))
                    / np.sqrt(hidden_dim)).astype(np.float32),
                b1=np.zeros(mlp_dim, np.float32),
                W2=(rng.standard_normal((mlp_dim, vocab_size)) * 0.1
                    / np.sqrt(mlp_dim)).astype(np.float32),
                b2=np.zeros(vocab_size, np.float32),
            ))
        head_id = f"medusa-s{seed}-{num_heads}x{hidden_dim}v{vocab_size}"
        return cls(params, head_id)

    @property
    def num_heads(self) -> int:
        return len(self.params)

    # --- forward / training math -----------------------------------------
    @staticmethod
    def normalize(hidden: np.ndarray) -> np.ndarray:
        """L2-normalize rows: the target's residual magnitude drifts with
        depth and layer norm scale; the heads should read direction only."""
        hidden = np.asarray(hidden, np.float32)
        n = np.linalg.norm(hidden, axis=-1, keepdims=True)
        return hidden / np.maximum(n, _EPS)

    def head_logits(self, hidden: np.ndarray) -> np.ndarray:
        """[num_heads, ..., vocab] logits from (already raw) hidden state."""
        x = self.normalize(hidden)
        outs = []
        for p in self.params:
            a = _relu(x @ p["W1"] + p["b1"])
            outs.append(a @ p["W2"] + p["b2"])
        return np.stack(outs)

    def head_log_probs(self, hidden: np.ndarray) -> np.ndarray:
        return _log_softmax(self.head_logits(hidden))

    def loss_and_grads(self, hidden: np.ndarray,
                       targets: np.ndarray) -> Tuple[float, List[dict]]:
        """Mean cross-entropy over heads and examples, plus per-head grads.

        ``hidden`` is [N, hidden_dim] raw hidden states; ``targets`` is
        [num_heads, N] token ids (head ``h``'s row holds the token at offset
        ``h + 2``). Hand-written backward — the trainer must run where only
        numpy is guaranteed (no autograd dependency on the serving host)."""
        x = self.normalize(np.atleast_2d(hidden))
        targets = np.asarray(targets, np.int64)
        if targets.shape != (self.num_heads, x.shape[0]):
            raise ValueError("targets must be [num_heads, N] aligned with hidden")
        N = x.shape[0]
        total = 0.0
        grads = []
        for h, p in enumerate(self.params):
            z1 = x @ p["W1"] + p["b1"]
            a = _relu(z1)
            logits = a @ p["W2"] + p["b2"]
            logp = _log_softmax(logits)
            y = targets[h]
            total += -float(logp[np.arange(N), y].mean())
            dlogits = np.exp(logp)
            dlogits[np.arange(N), y] -= 1.0
            dlogits /= N
            da = dlogits @ p["W2"].T
            dz1 = da * (z1 > 0)
            grads.append(dict(
                W1=(x.T @ dz1).astype(np.float32),
                b1=dz1.sum(axis=0).astype(np.float32),
                W2=(a.T @ dlogits).astype(np.float32),
                b2=dlogits.sum(axis=0).astype(np.float32),
            ))
        return total / self.num_heads, grads

    # --- persistence ------------------------------------------------------
    def save(self, path) -> None:
        flat = {"head_id": np.array(self.head_id)}
        for h, p in enumerate(self.params):
            for k, v in p.items():
                flat[f"h{h}_{k}"] = v
        with open(path, "wb") as f:
            np.savez(f, **flat)

    @classmethod
    def load(cls, path) -> "MedusaDraftHead":
        if isinstance(path, (bytes, bytearray)):
            path = io.BytesIO(path)
        with np.load(path) as z:
            head_id = str(z["head_id"])
            params = []
            h = 0
            while f"h{h}_W1" in z:
                params.append({k: z[f"h{h}_{k}"] for k in ("W1", "b1", "W2", "b2")})
                h += 1
        return cls(params, head_id)


class LearnedDrafter:
    """Token-tree drafting from a :class:`MedusaDraftHead`.

    ``draft_tree`` grows the tree best-first by joint log-probability: pop
    the highest-scoring frontier node, commit it, push its children scored
    ``parent_score + logp(head[depth], token)``. Ties break on (depth,
    token id, insertion order) so the tree is bit-reproducible across hosts.
    """

    def __init__(self, head: MedusaDraftHead, width: int = 2,
                 node_budget: int = 8) -> None:
        if width < 1:
            raise ValueError("need width >= 1")
        if node_budget < 2:
            raise ValueError("need node_budget >= 2 (root + one draft node)")
        self.head = head
        self.width = int(width)
        self.node_budget = int(node_budget)

    def draft_tree(self, hidden: np.ndarray, root_token: int, k: int,
                   width: Optional[int] = None,
                   node_budget: Optional[int] = None) -> Optional[TokenTree]:
        """Build a draft tree rooted at the already-emitted ``root_token``.

        ``hidden`` is the target's hidden state for the position BEFORE the
        root token; head ``h`` supplies depth ``h + 1`` candidates. ``k``
        caps tree depth (matching the linear drafter's per-request adaptive
        k), the node budget caps total fed tokens under the ragged token
        budget. Returns None when no draft fits (k <= 0) — the caller falls
        back to the plain decode step."""
        width = self.width if width is None else int(width)
        node_budget = self.node_budget if node_budget is None else int(node_budget)
        depth_cap = min(int(k), self.head.num_heads)
        if depth_cap < 1 or node_budget < 2:
            return None
        logp = self.head_log_probs_cached(hidden)
        # per-depth candidate sets, deterministic order: score desc, token asc
        cand: List[List[Tuple[float, int]]] = []
        for h in range(depth_cap):
            idx = np.lexsort((np.arange(logp.shape[1]), -logp[h]))[:width]
            cand.append([(float(logp[h][t]), int(t)) for t in idx])

        tokens = [int(root_token)]
        parents = [-1]
        depths = [0]
        counter = 0
        heap: list = []
        for lp, t in cand[0]:
            heapq.heappush(heap, (-lp, 1, t, counter, 0))
            counter += 1
        while heap and len(tokens) < node_budget:
            neg, depth, tok, _, parent = heapq.heappop(heap)
            node = len(tokens)
            tokens.append(tok)
            parents.append(parent)
            depths.append(depth)
            if depth < depth_cap:
                for lp, t in cand[depth]:
                    heapq.heappush(heap, (neg - lp, depth + 1, t, counter, node))
                    counter += 1
        if len(tokens) < 2:
            return None
        return TokenTree(np.array(tokens, np.int32), np.array(parents, np.int32),
                         np.array(depths, np.int32))

    def head_log_probs_cached(self, hidden: np.ndarray) -> np.ndarray:
        return self.head.head_log_probs(np.asarray(hidden, np.float32).reshape(-1))
