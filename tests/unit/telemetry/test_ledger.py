"""Cost-attribution ledger units: PriceBook pricing, RequestCost accrual,
TenantRollup bounding, CostLedger conservation, and the predicted-vs-observed
PerfObservedLedger (compile amnesty, baseline freeze, drift detection)."""

import pytest

from deepspeed_tpu.perf.observed import PerfObservedLedger, _bucket
from deepspeed_tpu.telemetry import MetricsRegistry
from deepspeed_tpu.telemetry.ledger import (OTHER_TENANT, PHASES, CostLedger,
                                            PriceBook, RequestCost,
                                            TenantRollup)


class _Req:
    """The slice of Request the ledger touches."""

    def __init__(self, tenant=None):
        self.tenant = tenant
        self.cost = None


# ---------------------------------------------------------------- PriceBook --
def test_pricebook_fallback_and_analytic():
    fallback = PriceBook()
    assert fallback.source == "fallback"
    assert fallback.flops(10) == 10 * fallback.flops_per_token

    class Cfg:
        hidden_size = 64
        num_layers = 2
        vocab_size = 256
        intermediate_size = 128

    book = PriceBook.from_model_config(Cfg())
    assert book.source == "analytic"
    params = 2 * (4 * 64 * 64 + 3 * 64 * 128) + 256 * 64
    assert book.flops_per_token == 2.0 * params
    assert book.bytes_per_token == 2.0 * params  # bf16


def test_pricebook_bad_config_falls_back():
    assert PriceBook.from_model_config(None).source == "fallback"
    assert PriceBook.from_model_config(object()).source == "fallback"


# -------------------------------------------------------------- RequestCost --
def test_request_cost_docs_and_compact_row():
    cost = RequestCost(PriceBook())
    cost.tokens["prefill"] = 100
    cost.tokens["decode"] = 20
    cost.device_seconds = 0.25
    cost.kv_block_seconds["device"] = 3.0
    cost.wire_bytes["handoff"] = 512
    doc = cost.to_dict()
    assert doc["tokens"]["billed"] == 120
    assert doc["flops"] == PriceBook().flops(120)
    row = cost.compact_row()
    assert row == {"billed_tokens": 120, "device_ms": 250.0,
                   "kv_block_s": 3.0, "wire_bytes": 512}


# ------------------------------------------------------------- TenantRollup --
def test_tenant_rollup_bounds_and_conserves():
    rollup = TenantRollup(max_tenants=2)
    for tenant in ("a", "b", "c", "d"):
        cost = RequestCost(PriceBook())
        cost.tokens["decode"] = 10
        bucket = rollup.fold(tenant, cost)
        assert bucket == (tenant if tenant in ("a", "b") else OTHER_TENANT)
    doc = rollup.doc()
    assert set(doc) == {"a", "b", OTHER_TENANT}
    # overflow folds, never drops: the sum over rows is all 4 requests
    assert sum(row["tokens"]["billed"] for row in doc.values()) == 40
    assert sum(row["requests"] for row in doc.values()) == 4


# --------------------------------------------------------------- CostLedger --
def test_charge_dispatch_amortizes_by_token_share():
    reg = MetricsRegistry()
    ledger = CostLedger(reg, PriceBook())
    a, b = _Req("a"), _Req("b")
    ledger.begin(a)
    ledger.begin(b)
    # one dispatch, 30 + 10 fed tokens: wall time splits 3:1
    ledger.charge_dispatch([(a.cost, "prefill", 30), (b.cost, "prefill", 10)],
                           seconds=0.4, amnesty_s=0.04)
    assert a.cost.device_seconds == pytest.approx(0.3)
    assert b.cost.device_seconds == pytest.approx(0.1)
    assert a.cost.amnesty_seconds == pytest.approx(0.03)
    # the aggregate got the SAME dispatch exactly once
    assert ledger.totals.device_seconds == pytest.approx(0.4)
    assert ledger.totals.dispatches == 1
    assert ledger.totals.tokens["prefill"] == 40


def test_kv_touch_accrues_piecewise_constant():
    reg = MetricsRegistry()
    ledger = CostLedger(reg, PriceBook())
    req = _Req()
    ledger.begin(req)
    ledger.touch_kv(req.cost, blocks=4, tier="device", now_s=10.0)
    # 2s at 4 device blocks, then the occupancy moves to 2 host blocks
    ledger.touch_kv(req.cost, blocks=2, tier="host", now_s=12.0)
    ledger.finalize(req, now_s=15.0)  # closes the 3s host segment
    assert req.cost.kv_block_seconds["device"] == pytest.approx(8.0)
    assert req.cost.kv_block_seconds["host"] == pytest.approx(6.0)
    assert ledger.totals.kv_block_seconds == req.cost.kv_block_seconds


def test_conservation_per_tenant_sums_match_aggregate():
    """The conservation gate's core: after every request finalizes, the sum
    over tenant rows equals the aggregate exactly on the integer fields."""
    reg = MetricsRegistry()
    ledger = CostLedger(reg, PriceBook(), max_tenants=2)
    reqs = [_Req(t) for t in ("a", "b", "c", "a", None)]
    for i, req in enumerate(reqs):
        ledger.begin(req)
        ledger.charge_dispatch([(req.cost, "prefill", 7 + i)], seconds=0.01)
        ledger.charge_dispatch([(req.cost, "decode", 3)], seconds=0.002)
        ledger.charge_wire(req.cost, "handoff", 100 + i)
        ledger.charge_spec(req.cost, drafted=4, accepted=2)
        ledger.finalize(req, now_s=float(i))
    rows = ledger.usage_doc()["tenants"].values()
    totals = ledger.usage_doc()["totals"]
    for field in ("billed",):
        assert sum(r["tokens"][field] for r in rows) == totals["tokens"][field]
    for phase in PHASES:
        assert sum(r["tokens"][phase] for r in rows) == totals["tokens"][phase]
    assert sum(r["requests"] for r in rows) == totals["requests"] == 5
    assert sum(r["wire_bytes"].get("handoff", 0) for r in rows) \
        == totals["wire_bytes"]["handoff"]
    assert sum(r["speculative"]["accepted"] for r in rows) \
        == totals["speculative"]["accepted"] == 10
    # a and b claimed the 2 tenant slots; c and the unlabeled request (its
    # default-tenant identity arrived after the cap) folded into <other>
    assert set(ledger.usage_doc()["tenants"]) == {"a", "b", OTHER_TENANT}


def test_tenant_metric_top_k_overflow():
    reg = MetricsRegistry()
    ledger = CostLedger(reg, PriceBook(), max_tenants=16,
                        tenant_metric_top_k=2)
    for tenant in ("a", "b", "c", "d"):
        req = _Req(tenant)
        ledger.begin(req)
        ledger.charge_dispatch([(req.cost, "decode", 5)], seconds=0.001)
        ledger.finalize(req, now_s=0.0)
    # the rollup keeps all 4 rows, the metric families only top-K + <other>
    assert set(ledger.usage_doc()["tenants"]) == {"a", "b", "c", "d"}
    labeled = {t for t in ledger._tenant_m}
    assert labeled == {"a", "b", OTHER_TENANT}


# ------------------------------------------------------- PerfObservedLedger --
def test_bucket_is_next_power_of_two():
    assert [_bucket(n) for n in (1, 2, 3, 8, 9, 100)] == [1, 2, 4, 8, 16, 128]


def test_program_mapping():
    pf = PerfObservedLedger.program_for
    assert pf("decode_loop", 4, 4) == "paged_decode_step"
    assert pf("verify", 2, 10) == "spec_verify_step"
    assert pf("verify_tree", 1, 16) == "spec_tree_verify"
    assert pf("put", 2, 50) == "prefix_suffix_prefill"
    assert pf("put", 4, 4) == "paged_decode_step"  # all-single-token feeds


def test_compile_amnesty_then_ratio_gauge():
    reg = MetricsRegistry()
    perf = PerfObservedLedger(reg, PriceBook(), baseline_dispatches=2)
    # first sight of (program, bucket): the whole wall time is amnesty
    assert perf.observe("decode_loop", 4, 4, 0.5) == 0.5
    assert perf.observe("decode_loop", 4, 4, 0.01) == 0.0
    doc = perf.doc()
    (row,) = doc["programs"]
    assert row["program"] == "paged_decode_step" and row["bucket"] == 4
    assert row["dispatches"] == 1  # the amnestied dispatch is excluded
    assert row["ratio"] == pytest.approx(0.01 / row["predicted_s"])


def test_drift_event_after_consecutive_over_baseline():
    reg = MetricsRegistry()
    perf = PerfObservedLedger(reg, PriceBook(), drift_factor=4.0,
                              drift_consecutive=3, baseline_dispatches=2)
    perf.observe("decode_loop", 4, 4, 1.0)  # amnesty
    for _ in range(2):                      # freeze baseline at 0.01s
        perf.observe("decode_loop", 4, 4, 0.01)
    # two slow dispatches: under drift_consecutive, no event yet
    for _ in range(2):
        perf.observe("decode_loop", 4, 4, 0.01 * 10)
    assert perf.doc()["programs"][0]["drift_events"] == 0
    perf.observe("decode_loop", 4, 4, 0.01 * 10)  # third consecutive
    assert perf.doc()["programs"][0]["drift_events"] == 1
    counter = reg.counter("perf_drift_events_total",
                          labels={"program": "paged_decode_step"})
    assert counter.value == 1
    # a fast dispatch resets the run: no spurious second event
    perf.observe("decode_loop", 4, 4, 0.01)
    perf.observe("decode_loop", 4, 4, 0.01 * 10)
    assert counter.value == 1


def test_explicit_predictions_override_roofline():
    reg = MetricsRegistry()
    perf = PerfObservedLedger(reg, PriceBook(), baseline_dispatches=1)
    perf.load_predictions({"paged_decode_step": 0.02})
    perf.observe("decode_loop", 4, 4, 1.0)  # amnesty
    perf.observe("decode_loop", 4, 4, 0.04)
    (row,) = perf.doc()["programs"]
    assert row["predicted_s"] == 0.02
    assert row["ratio"] == pytest.approx(2.0)
