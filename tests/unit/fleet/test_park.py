"""Fleet-parked sessions (ISSUE 18): the router-side rung of the tiered KV
ladder. A finished-but-continuable session banks its park frame in the
router's ParkStore; when the next turn arrives, the router dispatches a
rehydrate leg on whichever replica wins placement — including one that never
saw the session — and the continuation is bitwise-identical to a cold run at
the same seed. Chaos arms: park_store_corrupt (loud reject + cold fallback)
and demote_race (read injected into the tier writer's spill window)."""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from deepspeed_tpu.fleet import (FaultConfig, FleetConfig, FleetRouter,
                                 ParkConfig, ParkStore)
from deepspeed_tpu.inference.v2.ragged import handoff
from deepspeed_tpu.serving import ServingConfig, ServingScheduler


def _prompt(n=9, vocab=64, base=0):
    return [(base + i) % vocab for i in range(n)]


def _fleet_config(**kw):
    kw.setdefault("probe_ttl_s", 0.0)
    kw.setdefault("drain_timeout_s", 10.0)
    kw.setdefault("park", ParkConfig(enabled=True))
    return FleetConfig(**kw)


@pytest.fixture
def park_frame(make_engine):
    """One real v2 park frame plus its full token history — the ParkStore
    unit tests validate against the same frames the fleet banks."""
    sched = ServingScheduler(make_engine(), ServingConfig(), start=False)
    p1 = _prompt(9)
    req = sched.submit(p1, max_new_tokens=4, park=True)
    for _ in range(400):
        if req.finished:
            break
        sched.step()
    assert req.park_payload is not None
    tokens = p1 + [int(t) for t in req.tokens]
    sched.stop(drain=False)
    return req.park_payload, tokens


# ---------------------------------------------------------------------------
# ParkStore unit surface
# ---------------------------------------------------------------------------
def test_store_put_match_and_lru_touch(park_frame):
    payload, tokens = park_frame
    store = ParkStore(ParkConfig(enabled=True))
    assert store.put("sess-a", payload, replica_id="r0")
    assert len(store) == 1
    # a key the store never saw counts nothing — a first turn is not a miss
    assert store.match("sess-unknown", tokens + [1]) is None
    entry = store.match("sess-a", tokens + _prompt(3, base=40))
    assert entry is not None
    assert entry.payload == bytes(payload)
    assert entry.tokens == tokens
    assert entry.seen_tokens == len(tokens) - 1
    assert entry.tier_source == "device"
    assert entry.replica_id == "r0"
    s = store.stats()
    assert s["parks"] == 1 and s["rehydrate_hits"] == 1
    assert s["rehydrate_misses"] == 0 and s["corrupt_rejects"] == 0
    assert s["bytes"] == len(payload)
    assert s["inventory"][0]["session"] == "sess-a"


def test_store_rejects_garbage_and_v1_frames(park_frame):
    payload, _ = park_frame
    store = ParkStore(ParkConfig(enabled=True))
    assert not store.put("sess-junk", b"not a frame at all")
    # a v1 (live-handoff) frame must be refused: parking it would lose the
    # versioned tier record the rehydrate response reports
    v1 = payload.replace(b'"version": 2', b'"version": 1').replace(
        b'"version":2', b'"version":1')
    assert v1 != payload, "frame header serialization changed — fix the probe"
    assert not store.put("sess-v1", v1)
    assert len(store) == 0
    assert store.stats()["corrupt_rejects"] == 2


def test_store_session_and_byte_budgets_evict_lru(park_frame):
    payload, _ = park_frame
    store = ParkStore(ParkConfig(enabled=True, max_sessions=2))
    for key in ("a", "b", "c"):
        assert store.put(key, payload)
    assert len(store) == 2
    s = store.stats()
    assert s["evictions"] == 1
    assert [row["session"] for row in s["inventory"]] == ["b", "c"]

    tight = ParkStore(ParkConfig(enabled=True, max_bytes=len(payload)))
    assert tight.put("a", payload)
    assert tight.put("b", payload)  # over the byte budget: a evicts
    assert len(tight) == 1
    assert tight.stats()["inventory"][0]["session"] == "b"


def test_store_ttl_expires_parked_sessions(park_frame):
    payload, tokens = park_frame
    store = ParkStore(ParkConfig(enabled=True, ttl_s=0.01))
    assert store.put("sess-old", payload)
    time.sleep(0.05)
    assert store.match("sess-old", tokens + [1, 2]) is None
    s = store.stats()
    assert s["sessions"] == 0
    assert s["evictions"] == 1 and s["rehydrate_misses"] == 1


def test_store_diverged_prompt_drops_entry_once(park_frame):
    payload, tokens = park_frame
    store = ParkStore(ParkConfig(enabled=True))
    assert store.put("sess-d", payload)
    # same length, shorter, and a diverged prefix are all unusable — and the
    # entry drops on the first miss (histories never re-converge)
    assert store.match("sess-d", tokens) is None
    assert len(store) == 0
    assert store.stats()["rehydrate_misses"] == 1
    # the key is now unknown: further probes count nothing
    assert store.match("sess-d", tokens + [1]) is None
    assert store.stats()["rehydrate_misses"] == 1


def test_store_reject_drops_and_counts(park_frame):
    payload, _ = park_frame
    store = ParkStore(ParkConfig(enabled=True))
    assert store.put("sess-r", payload)
    store.reject("sess-r")
    assert len(store) == 0
    assert store.stats()["corrupt_rejects"] == 1


def test_store_newer_turn_replaces_parked_frame(park_frame):
    payload, tokens = park_frame
    store = ParkStore(ParkConfig(enabled=True))
    assert store.put("sess", payload)
    assert store.put("sess", payload)  # the next turn's frame subsumes it
    assert len(store) == 1
    s = store.stats()
    assert s["parks"] == 2 and s["evictions"] == 0
    assert s["bytes"] == len(payload)


# ---------------------------------------------------------------------------
# router integration: park at finish, rehydrate on ANY replica
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("temperature", [0.0, 0.8], ids=["greedy", "sampled"])
def test_fleet_park_rehydrates_on_surviving_replica_bitwise(make_fleet,
                                                            temperature):
    """The fleet half of the flagship gate: turn 1 parks in the router store,
    the parking replica LEAVES the fleet, and turn 2 rehydrates on the
    survivor — served as a rehydrate leg with the parked turns cached, and
    bitwise-identical to a cold full-prompt run at the same seed."""
    manager = make_fleet(roles=("mixed", "mixed"), config=_fleet_config())
    router = FleetRouter(manager)
    p1 = _prompt(11)
    r1 = router.route({"prompt": p1, "max_new_tokens": 5, "seed": 3,
                       "temperature": temperature}, session_key="chat-7")
    f1 = r1.result()
    assert f1["state"] == "DONE"
    assert f1.get("parked") is True
    assert "park" not in f1  # the frame stays router-side
    parker = r1._legs_meta[0]["replica"]
    parked = p1 + [int(t) for t in f1["tokens"]]
    park = router.fleet_stats()["router"]["park"]
    assert park["sessions"] == 1 and park["parks"] == 1
    assert park["inventory"][0]["session"] == "chat-7"
    assert park["inventory"][0]["parked_by"] == parker
    assert park["inventory"][0]["tokens"] == len(parked)

    # the parker drains away: the session must rehydrate on a replica that
    # never saw it (the frame is self-describing — any geometry match works)
    manager.drain(parker)
    p2 = parked + _prompt(4, base=40)
    r2 = router.route({"prompt": p2, "max_new_tokens": 5, "seed": 9,
                       "temperature": temperature}, session_key="chat-7")
    f2 = r2.result()
    assert f2["state"] == "DONE"
    assert f2.get("rehydrated") is True
    assert f2["park_tier"] == "device"
    assert r2._legs_meta[0]["kind"] == "rehydrate"
    assert r2._legs_meta[0]["replica"] != parker
    # the parked turns came from the frame, not a re-prefill
    assert f2["cached_tokens"] == len(parked) - 1
    assert f2.get("parked") is True  # the returning turn re-parks

    # bitwise control: the uninterrupted cold run at the same seed
    fc = router.route({"prompt": p2, "max_new_tokens": 5, "seed": 9,
                       "temperature": temperature}).result()
    assert [int(t) for t in f2["tokens"]] == [int(t) for t in fc["tokens"]]
    park = router.fleet_stats()["router"]["park"]
    assert park["rehydrate_hits"] == 1 and park["corrupt_rejects"] == 0


def test_client_park_flag_returns_frame_without_store(make_fleet):
    """A client asking ``park: true`` manages its own copy: the final doc
    carries the raw v2 frame even with the router store disabled."""
    manager = make_fleet(roles=("mixed",))
    router = FleetRouter(manager)
    assert router._park_store is None  # off by default
    p1 = _prompt(10)
    f1 = router.route({"prompt": p1, "max_new_tokens": 4,
                       "park": True}).result()
    assert f1["state"] == "DONE"
    assert "parked" not in f1  # nothing banked router-side
    header, _ = handoff.unpack(f1["park"])
    assert header["version"] == handoff.PARK_VERSION
    assert header["tokens"] == p1 + [int(t) for t in f1["tokens"]]


def test_park_without_session_key_banks_nothing(make_fleet):
    manager = make_fleet(roles=("mixed",), config=_fleet_config())
    router = FleetRouter(manager)
    f = router.route({"prompt": _prompt(10), "max_new_tokens": 3}).result()
    assert f["state"] == "DONE"
    assert "parked" not in f and "park" not in f
    assert router.fleet_stats()["router"]["park"]["sessions"] == 0


# ---------------------------------------------------------------------------
# chaos arms
# ---------------------------------------------------------------------------
def test_park_store_corrupt_falls_back_cold_and_stays_correct(make_fleet):
    """The ``park_store_corrupt`` point corrupts the frame sent to the
    rehydrating replica: the replica rejects loudly (CRC/framing), the store
    drops the entry, and the turn runs cold — same tokens, one bounced
    dispatch, never a continuation from half-corrupt KV."""
    manager = make_fleet(
        roles=("mixed",),
        config=_fleet_config(faults=FaultConfig(enabled=True, seed=7,
                                                park_store_corrupt_p=1.0)))
    router = FleetRouter(manager)
    p1 = _prompt(11)
    f1 = router.route({"prompt": p1, "max_new_tokens": 4, "seed": 3},
                      session_key="sess-x").result()
    assert f1.get("parked") is True
    parked = p1 + [int(t) for t in f1["tokens"]]

    p2 = parked + _prompt(3, base=40)
    r2 = router.route({"prompt": p2, "max_new_tokens": 4, "seed": 5},
                      session_key="sess-x")
    f2 = r2.result()
    assert f2["state"] == "DONE"
    assert "rehydrated" not in f2  # the corrupt frame never served
    assert r2._legs_meta[0]["kind"] == "serve"
    park = router.fleet_stats()["router"]["park"]
    assert park["rehydrate_hits"] == 1  # the match happened...
    assert park["corrupt_rejects"] >= 1  # ...the frame bounced, loudly
    assert f2.get("parked") is True  # the cold run re-parked the session

    # correctness is untouched: the cold fallback matches a sessionless run
    fc = router.route({"prompt": p2, "max_new_tokens": 4,
                       "seed": 5}).result()
    assert [int(t) for t in f2["tokens"]] == [int(t) for t in fc["tokens"]]
    assert any(k.startswith("park_store_corrupt")
               for k in router._faults.report()["fired"])


def test_demote_race_point_reclaims_to_host(make_fleet, tmp_path):
    """The ``demote_race`` point injects a read into the tier writer's
    spill-to-commit window on a live replica's store: the entry must reclaim
    to host, the orphan spill file must unlink, and the race is counted."""
    manager = make_fleet(
        roles=("mixed",),
        config=_fleet_config(faults=FaultConfig(enabled=True, seed=1,
                                                demote_race_p=1.0)))
    router = FleetRouter(manager)  # arming the router arms manager.faults
    replica = manager.replicas()[0]
    kv_cache = replica.engine._state_manager.kv_cache
    kv_cache.configure_tiering(spill_dir=str(tmp_path))
    store = kv_cache.tiered_store
    rng = np.random.default_rng(0)
    data = rng.normal(size=(2, 2, 2, 2, 16, 8)).astype(np.float32)
    h = store.put(data)
    store.demote(h, wait=True)
    assert store.tier_of(h) == "host"  # the injected reader won
    assert store.stats()["demote_races"] == 1
    assert not list(tmp_path.glob("kv_offload_*.bin"))
    got, tier = store.read(h)
    assert tier == "host"
    np.testing.assert_array_equal(got, data)
    assert any(k.startswith("demote_race")
               for k in router._faults.report()["fired"])
    # disarmed, the hook is a no-op: demotion commits normally
    router.set_faults(None)
    assert store.demote(h, wait=True)
    assert store.tier_of(h) == "disk"


# ---------------------------------------------------------------------------
# CLI satellites: dstpu_loadgen --multi-turn and dstpu_report --kv
# ---------------------------------------------------------------------------
_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))


def _loadgen_module():
    """Load bin/dstpu_loadgen as a module (top-level imports are stdlib-only;
    main() is __main__-guarded) so its multi-turn helpers are unit-testable."""
    import importlib.util
    from importlib.machinery import SourceFileLoader
    loader = SourceFileLoader("_dstpu_loadgen_park_test",
                              os.path.join(_REPO, "bin", "dstpu_loadgen"))
    spec = importlib.util.spec_from_loader(loader.name, loader)
    mod = importlib.util.module_from_spec(spec)
    loader.exec_module(mod)
    return mod


def test_loadgen_multi_turn_parse_and_report_math(capsys):
    """``--multi-turn TURNS[:SESSIONS]`` parsing and the park-effectiveness
    report: hit rate over RETURNING turns only, recompute-tokens-saved, and
    TTFT split by the tier the parked KV was resident on."""
    lg = _loadgen_module()
    for bad in (["--multi-turn", "0"], ["--multi-turn", "2:0"],
                ["--multi-turn", "2:3:4"], ["--multi-turn", "x"],
                ["--multi-turn", "2", "--think-time", "-1"]):
        with pytest.raises(SystemExit):
            lg.main(["--url", "http://x"] + bad)
    capsys.readouterr()  # drop the argparse usage noise

    mk = lg.Result
    ok = [
        mk(True, 200, ttft_s=0.05, prompt_tokens=10, turn=0, parked=True),
        mk(True, 200, ttft_s=0.01, prompt_tokens=20, cached_tokens=15,
           turn=1, rehydrated=True, park_tier="device", parked=True),
        mk(True, 200, ttft_s=0.02, prompt_tokens=30, cached_tokens=25,
           turn=1, rehydrated=True, park_tier="disk", parked=True),
        mk(True, 200, ttft_s=0.08, prompt_tokens=40, turn=2),  # cold miss
    ]
    lg._multi_turn_report(ok)
    out = capsys.readouterr().out
    assert "rehydrated=2/3 returning turns" in out
    assert "hit_rate=0.67" in out
    assert "recompute_tokens_saved=40/90" in out
    assert "parked_finishes=3" in out
    assert "ttft (device)" in out
    assert "ttft (  disk)" in out
    assert "ttft (  cold)" in out

    lg._multi_turn_report([mk(True, 200, turn=0, parked=True)])
    assert "no returning turns (parked_finishes=1)" in capsys.readouterr().out


def test_loadgen_multi_turn_end_to_end(make_fleet):
    """The CLI satellite end-to-end: concurrent sessions over HTTP against a
    park-enabled router; every returning turn must rehydrate from the store
    and the report must show the hit rate and the device-tier TTFT split."""
    manager = make_fleet(roles=("mixed", "mixed"), config=_fleet_config())
    router = FleetRouter(manager).start()
    try:
        r = subprocess.run(
            [sys.executable, os.path.join(_REPO, "bin", "dstpu_loadgen"),
             "--target", router.url, "--multi-turn", "3:2",
             "--prompt-len", "8", "--max-new-tokens", "3",
             "--vocab-size", "64", "--seed", "0"],
            capture_output=True, text=True, timeout=300)
        assert r.returncode == 0, r.stderr[-800:]
        assert "requests=6 ok=6 err=0" in r.stdout
        assert "rehydrated=4/4 returning turns (hit_rate=1.00)" in r.stdout
        assert "parked_finishes=6" in r.stdout
        assert "ttft (device)" in r.stdout
        # the store-side view agrees with the client-side report
        park = router.fleet_stats()["router"]["park"]
        assert park["parks"] == 6 and park["rehydrate_hits"] == 4
        assert park["corrupt_rejects"] == 0
    finally:
        router.stop(drain=False)


def test_report_kv_renders_tiers_and_park(tmp_path, capsys):
    """``dstpu_report --kv`` over saved stats docs: the serving form renders
    the tier-occupancy ladder, the fleet form renders the parked-session
    inventory, disabled blocks say so, and garbage is a loud rc 2."""
    from deepspeed_tpu.env_report import kv_report, main

    serving = tmp_path / "stats.json"
    serving.write_text(json.dumps({"kv_tiers": {
        "enabled": True, "device_blocks_used": 5, "device_blocks_total": 64,
        "host_entries": 2, "host_blocks": 6, "host_bytes": 4096,
        "host_bytes_budget": 1 << 20, "disk_entries": 1, "disk_blocks": 3,
        "disk_bytes": 2048, "pressure_demotions": 4, "demotions": 3,
        "demote_races": 1, "writeback_pending": 0, "writeback_joins": 2,
        "reads_host": 7, "reads_disk": 1, "trie_offloaded_nodes": 2,
        "trie_demotions": 2, "trie_promotions": 1}}))
    assert kv_report(str(serving)) == 0
    out = capsys.readouterr().out
    assert "device ............... 5/64 blocks" in out
    assert "host ................. 2 entries, 6 blocks, 4096 bytes" in out
    assert "pressure demotions ... 4" in out
    assert "demote races ......... 1" in out
    assert "prefix trie .......... 2 offloaded nodes" in out

    fleet = tmp_path / "fleet.json"
    fleet.write_text(json.dumps({"router": {"park": {
        "sessions": 1, "bytes": 9000, "max_sessions": 256,
        "max_bytes": 1 << 30, "ttl_s": 600.0, "parks": 3,
        "rehydrate_hits": 2, "rehydrate_misses": 1, "corrupt_rejects": 0,
        "evictions": 0, "inventory": [
            {"session": "chat-7", "tokens": 21, "bytes": 9000,
             "tier_source": "device", "parked_by": "replica-0",
             "age_s": 4.2}]}}}))
    assert kv_report(str(fleet)) == 0
    out = capsys.readouterr().out
    assert "park store ............. 1 sessions, 9000 bytes" in out
    assert "rehydrate hits ....... 2" in out
    assert "chat-7" in out and "replica-0" in out

    # disabled blocks render as such (rc 0 — the doc IS a stats doc)
    serving.write_text(json.dumps({"kv_tiers": None}))
    assert kv_report(str(serving)) == 0
    assert "KVTierConfig.enabled=false" in capsys.readouterr().out
    fleet.write_text(json.dumps({"router": {"requests": 3}}))
    assert kv_report(str(fleet)) == 0
    assert "ParkConfig.enabled=false" in capsys.readouterr().out

    # garbage: loud rc 2, not a traceback — and main() dispatches the flag
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps({"foo": 1}))
    assert kv_report(str(bad)) == 2
    assert kv_report(str(tmp_path / "missing.json")) == 2
    capsys.readouterr()
    assert main(["--kv", str(bad)]) == 2
    assert main(["--kv"]) == 2
    assert "usage: dstpu_report --kv" in capsys.readouterr().out
