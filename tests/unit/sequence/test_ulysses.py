"""Ulysses sequence-parallel tests (reference: deepspeed/sequence/layer.py has no
dedicated unit test in-tree; this is the equivalence gate: distributed attention ==
local attention)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deepspeed_tpu.sequence.layer import DistributedAttention
from deepspeed_tpu.utils import groups


def _attn(q, k, v, scale=1.0):
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    probs = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def test_distributed_attention_matches_local():
    groups.initialize_mesh(sequence_parallel_size=4, force=True)
    mesh = groups.get_mesh()
    B, S, H, D = 2, 16, 8, 4
    rng = jax.random.PRNGKey(0)
    q, k, v = (jax.random.normal(r, (B, S, H, D)) for r in jax.random.split(rng, 3))

    dist_attn = DistributedAttention(_attn)

    from jax.sharding import NamedSharding, PartitionSpec as P
    seq_sharded = NamedSharding(mesh, P(None, "seq", None, None))

    @jax.jit
    def f(q, k, v):
        q = jax.lax.with_sharding_constraint(q, seq_sharded)
        k = jax.lax.with_sharding_constraint(k, seq_sharded)
        v = jax.lax.with_sharding_constraint(v, seq_sharded)
        return dist_attn(q, k, v)

    out = f(q, k, v)
    ref = _attn(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-6)


def test_distributed_attention_inserts_all_to_all():
    groups.initialize_mesh(sequence_parallel_size=4, force=True)
    mesh = groups.get_mesh()
    B, S, H, D = 1, 8, 8, 4
    q = jnp.ones((B, S, H, D))

    from jax.sharding import NamedSharding, PartitionSpec as P
    seq_sharded = NamedSharding(mesh, P(None, "seq", None, None))
    dist_attn = DistributedAttention(_attn)

    def f(q, k, v):
        q = jax.lax.with_sharding_constraint(q, seq_sharded)
        k = jax.lax.with_sharding_constraint(k, seq_sharded)
        v = jax.lax.with_sharding_constraint(v, seq_sharded)
        return dist_attn(q, k, v)

    compiled = jax.jit(f).lower(q, q, q).compile()
    hlo = compiled.as_text()
    assert "all-to-all" in hlo, "Ulysses sharding flip should lower to all-to-all"
