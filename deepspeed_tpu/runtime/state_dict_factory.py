"""Checkpoint state-dict loading with TP-degree conversion.

Reference: ``deepspeed/runtime/state_dict_factory.py`` (SDLoaderFactory:21,
SDLoaderBase:48, MegatronSDLoader:190): given a list of per-TP-rank checkpoint
files, ``load(mp_world_size, mp_rank)`` returns that rank's state dict —
loading directly when the degrees match, **merging** neighbor shards when the
new TP degree is smaller, **splitting** a shard when it is larger. Fused
query-key-value tensors need version-aware treatment (see below).

TPU formulation: checkpoint files are flat ``name -> numpy array`` dicts
(``.npz`` — what ``save_16bit_model`` writes) instead of torch pickles; the
merge/split axis per tensor follows the same Megatron naming rules the
reference hard-codes. All host-side numpy; the result feeds ``jax.device_put``
against whatever shardings the new topology assigns.

Fused-QKV layouts (reference :220): ckpt_ver 0 stores [(3*np*hn), h] — the
q/k/v *sections* are contiguous within a shard, so TP conversion must operate
per-section; ckpt_ver 1.0/2.0 store [(np*hn*3), h] / [(np*3*hn), h] — each
head's qkv travels with it, so conversion is plain concat/chunk on dim 0.
"""

import json
import os
from abc import ABC, abstractmethod
from typing import Dict, List, Optional

import numpy as np

from deepspeed_tpu.utils.logging import logger

AUTO_MODULE_KEY = "auto"


class SDLoaderFactory:

    @staticmethod
    def get_sd_loader_json(json_file, checkpoint_engine=None):
        """Reference :24 — a checkpoint-description JSON ({"type", "version",
        "checkpoints"}) or its already-parsed dict."""
        if isinstance(json_file, str):
            with open(json_file) as f:
                data = json.load(f)
        else:
            data = json_file
        sd_type = data["type"]
        ckpt_list = data["checkpoints"]
        version = data.get("version")
        if isinstance(ckpt_list, dict):  # BLOOM-style {"tp_size": n, "files": [...]}
            ckpt_list = ckpt_list["files"]
        if sd_type.lower() in ("bloom", "ds_model"):
            return data  # reference returns the raw dict for these types
        return SDLoaderFactory.get_sd_loader(ckpt_list, checkpoint_engine, sd_type, version)

    @staticmethod
    def get_sd_loader(ckpt_list, checkpoint_engine=None, sd_type="Megatron", version=None):
        if sd_type.lower() == "megatron":
            return MegatronSDLoader(ckpt_list, version, checkpoint_engine)
        raise NotImplementedError(f"SD loader for type {sd_type!r}")


def _load_file(path) -> Dict[str, np.ndarray]:
    if str(path).endswith(".npz"):
        with np.load(path, allow_pickle=True) as z:
            return {k: z[k] for k in z.files}
    raise ValueError(f"unsupported checkpoint file {path!r} (expected .npz)")


class SDLoaderBase(ABC):

    def __init__(self, ckpt_list: List[str], version, checkpoint_engine=None):
        self.module_key = AUTO_MODULE_KEY
        self.ckpt_list = list(ckpt_list)
        self.version = version
        self.check_ckpt_list()

    # ------------------------------------------------------------------- load --
    def load(self, mp_world_size: int, mp_rank: int, module_key=AUTO_MODULE_KEY,
             is_pipe_parallel=False, quantize=False, quantize_bits=8,
             quantize_groups=64, mlp_extra_grouping=True):
        """Reference :57. Returns (load_path, state_dict)."""
        self.module_key = module_key
        num_ckpt = len(self.ckpt_list)

        if num_ckpt == mp_world_size:
            path = self.ckpt_list[mp_rank]
            return path, _load_file(path)
        if num_ckpt > mp_world_size:
            if num_ckpt % mp_world_size != 0:
                raise ValueError(f"cannot merge {num_ckpt} shards into {mp_world_size}")
            return None, self.merge_state_dict(mp_world_size, mp_rank)
        if mp_world_size % num_ckpt != 0:
            raise ValueError(f"cannot split {num_ckpt} shards into {mp_world_size}")
        return None, self.split_state_dict(mp_world_size, mp_rank)

    def get_merge_state_dicts(self, mp_world_size: int, mp_rank: int):
        """The ckpt-file group this rank merges (reference :115)."""
        num_to_merge = len(self.ckpt_list) // mp_world_size
        files = self.ckpt_list[num_to_merge * mp_rank:num_to_merge * (mp_rank + 1)]
        logger.info(f"mp_rank {mp_rank}: merging {files}")
        return [_load_file(f) for f in files]

    def get_split_state_dict(self, mp_world_size: int, mp_rank: int):
        """The (ckpt file, intra-file offset) this rank splits from (:126)."""
        num_to_split = mp_world_size // len(self.ckpt_list)
        ckpt_index = mp_rank // num_to_split
        offset = mp_rank % num_to_split
        logger.info(f"mp_rank {mp_rank}: splitting {self.ckpt_list[ckpt_index]} "
                    f"({offset}/{num_to_split})")
        return _load_file(self.ckpt_list[ckpt_index]), num_to_split, offset

    def check_ckpt_list(self):
        assert len(self.ckpt_list) > 0, "empty checkpoint list"
        for p in self.ckpt_list:
            if not os.path.exists(p):
                raise FileNotFoundError(f"checkpoint shard {p} missing")

    @abstractmethod
    def merge_state_dict(self, mp_world_size, mp_rank):
        ...

    @abstractmethod
    def split_state_dict(self, mp_world_size, mp_rank):
        ...


class MegatronSDLoader(SDLoaderBase):
    """Megatron-naming merge/split rules (reference :190).

    - cat dim 0 (column-parallel fan-out): ``word_embeddings``,
      ``mlp.dense_h_to_4h`` (weight AND bias), fused QKV (version-aware).
    - cat dim 1 (row-parallel fan-in): ``attention.dense.weight``,
      ``mlp.dense_4h_to_h.weight``; their biases are replicated.
    - everything else (norms, row-parallel biases): identical across ranks.
    """

    # ------------------------------------------------------------ qkv helpers --
    def merge_query_key_value(self, param_list: List[np.ndarray], ckpt_ver):
        """Reference :220. ckpt_ver 0: each shard is [(3*np*hn), h] — the q/k/v
        sections are contiguous *within each shard*, so merging concatenates
        per-section (split each shard in 3, concat q-sections, k-sections,
        v-sections, restack [q|k|v]). ckpt_ver 1.0/2.0: [(np*hn*3), h] or
        [(np*3*hn), h] — heads carry their own qkv, so merge is plain concat."""
        if ckpt_ver == 0:
            qs, ks, vs = [], [], []
            for p in param_list:
                q, k, v = np.split(p, 3, axis=0)
                qs.append(q)
                ks.append(k)
                vs.append(v)
            return np.concatenate([np.concatenate(qs, axis=0),
                                   np.concatenate(ks, axis=0),
                                   np.concatenate(vs, axis=0)], axis=0)
        if ckpt_ver in (1, 2):
            return np.concatenate(param_list, axis=0)
        raise ValueError(f"checkpoint version: {ckpt_ver} is not supported")

    def split_query_key_value(self, param: np.ndarray, num_to_split: int, offset: int,
                              ckpt_ver):
        """Reference :258 — the inverse of :meth:`merge_query_key_value`."""
        if ckpt_ver == 0:
            q, k, v = np.split(param, 3, axis=0)
            return np.concatenate([np.split(q, num_to_split, axis=0)[offset],
                                   np.split(k, num_to_split, axis=0)[offset],
                                   np.split(v, num_to_split, axis=0)[offset]], axis=0)
        if ckpt_ver in (1, 2):
            return np.split(param, num_to_split, axis=0)[offset]
        raise ValueError(f"checkpoint version: {ckpt_ver} is not supported")

    # ---------------------------------------------------------- classification --
    @staticmethod
    def _is_qkv(key: str) -> bool:
        return "attention.query_key_value" in key or "attn.qkv" in key

    @staticmethod
    def _cat_dim(key: str) -> Optional[int]:
        """None = replicated."""
        if "word_embeddings" in key or "position_embeddings" in key:
            return 0 if "word" in key else None
        if "mlp.dense_h_to_4h" in key:  # column-parallel: weight + bias split
            return 0
        if ("attention.dense.weight" in key or "mlp.dense_4h_to_h.weight" in key
                or "attn.out_proj.weight" in key):
            return 1
        return None

    # --------------------------------------------------------------- merge/split --
    def merge_state_dict(self, mp_world_size, mp_rank, quantize=False, quantize_bits=8,
                         groups=64, mlp_extra_grouping=True):
        sds = self.get_merge_state_dicts(mp_world_size, mp_rank)
        ver = self.get_checkpoint_version(sds[0])
        out = {}
        for key in sds[0]:
            vals = [sd[key] for sd in sds]
            if self._is_qkv(key):
                out[key] = self.merge_query_key_value(vals, ver)
            else:
                dim = self._cat_dim(key)
                if dim is None or vals[0].ndim <= dim:
                    out[key] = vals[0]
                else:
                    out[key] = np.concatenate(vals, axis=dim)
        return out

    def split_state_dict(self, mp_world_size, mp_rank, quantize=False, quantize_bits=8,
                         groups=64, mlp_extra_grouping=True):
        sd, num_to_split, offset = self.get_split_state_dict(mp_world_size, mp_rank)
        ver = self.get_checkpoint_version(sd)
        out = {}
        for key, val in sd.items():
            if self._is_qkv(key):
                out[key] = self.split_query_key_value(val, num_to_split, offset, ver)
            else:
                dim = self._cat_dim(key)
                if dim is None or val.ndim <= dim:
                    out[key] = val
                else:
                    out[key] = np.split(val, num_to_split, axis=dim)[offset]
        return out

    def get_checkpoint_version(self, state_dict) -> int:
        """Reference :425 — an explicit ``version`` wins over the in-file tag."""
        if self.version is not None:
            return int(self.version)
        tag = state_dict.get("checkpoint_version")
        return int(np.asarray(tag)) if tag is not None else 0

    def sanity_check(self, ckpt_file_name):
        """Reference :403 — the Megatron keys the rules above rely on."""
        sd = _load_file(ckpt_file_name)
        required = ["attention.dense.weight", "mlp.dense_4h_to_h.weight"]
        for part in required:
            if not any(part in k for k in sd):
                logger.warning(f"{ckpt_file_name}: no key matching {part!r} — "
                               f"merge/split rules may not apply")
