"""FastGen ragged inference engine.

Reference: ``deepspeed/inference/v2/engine_v2.py`` (InferenceEngineV2:32 —
``put()``:135 inserts ragged sequences and runs one forward; ``query``/
``can_schedule`` token/KV-block occupancy logic; ``flush``; ``serialize``; the
fork's ``empty_run``:308 participating in EP collectives with zero tokens).

TPU execution model: the engine composes a :class:`RaggedBatchWrapper` on the
host, the model runs ONE jitted program per padded batch *bucket* (static
shapes), and the paged KV cache flows through the program functionally
(donated). TP/EP sharding is carried by the global device mesh
(``deepspeed_tpu.utils.groups``) — param/activation sharding constraints inside
the model program replace the reference's explicit process-group collectives.
"""

import json
import os
from typing import Iterable, List, Optional, Tuple

import numpy as np

from deepspeed_tpu.inference.v2.config_v2 import RaggedInferenceEngineConfig
from deepspeed_tpu.inference.v2.ragged.ragged_manager import DSStateManager
from deepspeed_tpu.inference.v2.ragged.ragged_wrapper import RaggedBatchWrapper
from deepspeed_tpu.inference.v2.ragged.sequence_descriptor import PlaceholderSequenceDescriptor
from deepspeed_tpu.inference.v2.scheduling_utils import SchedulingError, SchedulingResult
from deepspeed_tpu.inference.v2.tracer import Tracer, get_tracer, set_tracer
from deepspeed_tpu.telemetry import get_span_recorder as _tel_get_spans
from deepspeed_tpu.telemetry import is_active as _tel_is_active
from deepspeed_tpu.telemetry import now_us as _tel_now_us
from deepspeed_tpu.utils import groups
from deepspeed_tpu.utils.logging import logger


class InferenceEngineV2:

    def __init__(self, model, engine_config: RaggedInferenceEngineConfig) -> None:
        """``model`` is a built :class:`DSTransformerModelBase` subclass (the
        engine_factory constructs it from a policy; the reference builds it from
        ``policy.build_model`` — here the model consumes training pytrees
        directly so no container-mapping step exists)."""
        self._config = engine_config

        if engine_config.simulated_gating:
            from deepspeed_tpu.inference.v2.modules.moe import enable_simulated_gating
            enable_simulated_gating(engine_config.simulated_gating_temperature)

        self._model = model
        self._initialize_comm_groups()
        self._apply_tensor_parallel()

        self._batch = RaggedBatchWrapper(engine_config.state_manager,
                                         block_size=engine_config.kv_block_size)
        self._state_manager = DSStateManager(engine_config.state_manager,
                                             model.kv_cache_config())
        self._model.set_state_manager(self._state_manager)

        # unified telemetry (telemetry/): batch/token/KV gauges + spans +
        # optional /metrics //healthz endpoint, startable purely from config
        self._telemetry = None
        self._tel_metrics = None
        if engine_config.telemetry.enabled:
            from deepspeed_tpu import telemetry
            self._telemetry = telemetry.configure(engine_config.telemetry)
            self._tel_metrics = self._build_tel_metrics(self._telemetry.registry)

        # a ServingScheduler attaches here (serving/scheduler.py); close()
        # stops it so the engine can always be torn down safely
        self._serving_scheduler = None

        # cost-attribution hook (telemetry/ledger.py + perf/observed.py): a
        # scheduler with an active telemetry session installs a callable
        # ``(kind, n_seqs, n_tokens, wall_seconds)`` invoked around every
        # jitted dispatch (put / decode_loop / verify / verify_tree). None —
        # the default, and always the case with telemetry off — costs one
        # attribute load per dispatch.
        self.dispatch_observer = None

        if engine_config.trace_enabled:
            self._tracer = Tracer(max_batches=engine_config.max_trace_batches,
                                  span_recorder=self._telemetry.spans
                                  if self._telemetry is not None else None)
            set_tracer(self._tracer)
        else:
            self._tracer = None

    # ------------------------------------------------------------------ groups --
    def _initialize_comm_groups(self) -> None:
        """Reference engine_v2.py:108 creates TP (and fork: EP-replica) process
        groups; here both are axes of the one global mesh."""
        tp = self._config.tensor_parallel.tp_size
        ep = self._config.expert_parallel.replica_num if self._config.expert_parallel.enabled else 1
        if groups.mesh_is_initialized():
            mesh = groups.get_mesh()
            if tp > 1:
                assert mesh.shape[groups.MODEL_AXIS] == tp, \
                    f"mesh model axis {mesh.shape[groups.MODEL_AXIS]} != tp_size {tp}"
            if ep > 1:
                assert mesh.shape[groups.EXPERT_AXIS] == ep, \
                    f"mesh expert axis {mesh.shape[groups.EXPERT_AXIS]} != replica_num {ep}"
        elif tp > 1 or ep > 1:
            groups.initialize_mesh(model_parallel_size=tp, expert_parallel_size=ep)

    def _apply_tensor_parallel(self) -> None:
        """TP>1 (incl. TP+EP, which the reference rejects at engine_v2.py:85):
        place the param tree with AutoTP-derived shardings; the SPMD partitioner
        inserts the per-layer all-reduce the reference's ``LinearAllreduce``
        modules perform (module_inject/layers.py:16). Expert banks stay sharded
        only on the expert axis — the EP shard_map path owns their layout."""
        tp = self._config.tensor_parallel.tp_size
        if tp <= 1:
            return
        import jax
        from jax.sharding import NamedSharding
        from deepspeed_tpu.module_inject.auto_tp import auto_tp_specs

        mesh = groups.get_mesh()
        specs = auto_tp_specs(self._model._params)
        self._model._params = jax.device_put(
            self._model._params, jax.tree.map(lambda s: NamedSharding(mesh, s), specs))
        logger.info(f"inference-v2: AutoTP placed params over model axis (tp={tp})")

    # ------------------------------------------------------------ properties --
    @property
    def free_blocks(self) -> int:
        return self._state_manager.free_blocks

    @property
    def n_kv_cache_groups(self) -> int:
        return 1

    @property
    def model(self):
        return self._model

    @property
    def tracer(self) -> Optional[Tracer]:
        return self._tracer

    @property
    def telemetry_session(self):
        return self._telemetry

    @property
    def serving_scheduler(self):
        """The attached :class:`ServingScheduler` (None when not serving)."""
        return self._serving_scheduler

    @property
    def metrics_url(self) -> Optional[str]:
        """The served ``/metrics`` URL (None unless ``telemetry.http.enabled``)."""
        return self._telemetry.metrics_url if self._telemetry is not None else None

    def close(self) -> None:
        """Tear the engine down (idempotent): stop an attached serving
        scheduler, deregister this engine's tracer from the module-global slot
        (so tracer state cannot leak into the next engine in this process),
        and stop the telemetry endpoint / flush sinks."""
        if self._serving_scheduler is not None:
            self._serving_scheduler.stop(drain=False)
            self._serving_scheduler = None
        if self._tracer is not None and get_tracer() is self._tracer:
            set_tracer(None)
        if self._telemetry is not None:
            self._telemetry.close()
            self._telemetry = None

    # ----------------------------------------------------------------- put() --
    def put(self, batch_uids: Iterable[int], batch_tokens: Iterable, do_checks: bool = True):
        """Run one ragged forward over ``batch_uids``/``batch_tokens``; returns
        logits ``[len(batch_uids), vocab]`` — each sequence's final token only."""
        batch_uids = list(batch_uids)
        batch_tokens = [np.atleast_1d(np.asarray(t)) for t in batch_tokens]

        if do_checks:
            # BEFORE restoring: can_schedule counts offloaded sequences'
            # restore cost, so admission failure is a SchedulingError here,
            # never a raw allocator error mid-restore
            schedule_check = self.can_schedule(batch_uids, [t.size for t in batch_tokens])
            if schedule_check != SchedulingResult.Success:
                raise SchedulingError(schedule_check)
        self._restore_offloaded(batch_uids)

        self._batch.clear()
        if self._tracer:
            self._tracer.init_batch(is_empty_run=False, num_layers=self._model.num_layers)
        for uid, tokens in zip(batch_uids, batch_tokens):
            seq_desc = self._state_manager.get_or_create_sequence(uid)
            self._model.maybe_allocate_kv(seq_desc, tokens.size)
            seq_desc.pre_forward(tokens.size)
            self._batch.insert_sequence(seq_desc, tokens, do_checks=do_checks)
            if self._tracer:
                self._tracer.add_sequence(seq_desc)

        self._batch.finalize()
        self._model.prepare_batch(self._batch)
        spans = self._resolve_spans()
        observer = self.dispatch_observer
        if spans is not None or observer is not None:
            _t0 = _tel_now_us()
        logits = self._model.forward(self._batch)
        if observer is not None:
            observer("put", len(batch_uids),
                     int(sum(t.size for t in batch_tokens)),
                     (_tel_now_us() - _t0) / 1e6)
        assert logits.shape[0] == self._batch.current_sequences

        for uid in batch_uids:
            seq_desc = self._state_manager.get_sequence(uid)
            seq_desc.post_forward()
            self._model.maybe_free_kv(seq_desc)
        metrics = self._resolve_tel_metrics()
        if spans is not None or metrics is not None:
            n_tokens = int(sum(t.size for t in batch_tokens))
        if spans is not None:
            # uids link this batch span to the per-request serving traces
            # (each uid's request track carries the same uid in its args)
            spans.record("put", cat="inference", ts_us=_t0,
                         dur_us=_tel_now_us() - _t0,
                         args={"sequences": len(batch_uids),
                               "tokens": n_tokens,
                               "uids": [int(u) for u in batch_uids]})
        if metrics is not None:
            self._write_telemetry(metrics, batch_tokens=n_tokens)
        return logits

    @staticmethod
    def _build_tel_metrics(reg) -> dict:
        return {
            "batches": reg.counter("inference_batches_total", "Ragged batches executed"),
            "tokens": reg.counter("inference_tokens_total", "Tokens scheduled into batches"),
            "in_flight": reg.gauge("inference_in_flight_tokens",
                                   "Tokens in the last ragged batch"),
            "free_blocks": reg.gauge("inference_kv_free_blocks", "Free KV-cache blocks"),
            "tracked": reg.gauge("inference_tracked_sequences", "Sequences tracked"),
            "empty_runs": reg.counter("inference_empty_runs_total",
                                      "EP lock-step forwards with zero tokens"),
        }

    def _resolve_tel_metrics(self) -> Optional[dict]:
        """The inference_* families — always on the process-wide registry
        (an engine session's registry IS ``telemetry.get_registry()``, the
        singleton). With an engine-owned session the dict is built at init
        and lives until ``close()``; otherwise it is built lazily and
        returned only while a globally-configured session is active (the
        serving quickstart configures telemetry process-wide, not per
        engine), so a ``telemetry.shutdown()`` mid-process stops metric
        writes along with spans. Disabled telemetry costs one boolean check
        here."""
        if self._telemetry is not None:
            return self._tel_metrics
        if not _tel_is_active():
            return None
        if self._tel_metrics is None:
            from deepspeed_tpu import telemetry
            self._tel_metrics = self._build_tel_metrics(telemetry.get_registry())
        return self._tel_metrics

    def _resolve_spans(self):
        """The engine session's recorder — or a globally-configured
        session's (same fallback policy as :meth:`_resolve_tel_metrics`)."""
        return self._telemetry.spans if self._telemetry is not None else _tel_get_spans()

    def _write_telemetry(self, metrics: dict, batch_tokens: int) -> None:
        metrics["batches"].inc()
        metrics["tokens"].inc(batch_tokens)
        metrics["in_flight"].set(batch_tokens)
        metrics["free_blocks"].set(self._state_manager.free_blocks)
        metrics["tracked"].set(self._state_manager.n_tracked_sequences)

    # ------------------------------------------------------------ decode_loop --
    def decode_loop(self, batch_uids: Iterable[int], batch_tokens: Iterable,
                    n_steps: int, do_checks: bool = True, temperature: float = 0.0,
                    rng=None) -> np.ndarray:
        """Generate ``n_steps`` tokens per sequence in ONE device program (no
        host round-trip per token — see DSTransformerModelBase.decode_loop).
        ``batch_tokens`` holds each sequence's next-input token(s); returns
        generated tokens ``[n_seqs, n_steps]``. ``temperature`` 0 = greedy;
        > 0 samples categorically with the (per-step folded) ``rng``.

        **Multi-token verify feed** (speculative decoding): an entry may carry
        its next-input token followed by k draft tokens. Any entry wider than
        one token switches the call into verify mode — ``n_steps`` must be 1,
        greedy only — where ONE ragged forward scores every fed position and
        the return value is a list of per-sequence int32 arrays: element i
        holds, for each of sequence i's ``1+k_i`` positions, the target
        model's greedy next token after consuming the feed up to and including
        that position (``out[i][j] == argmax`` after ``feed_i[:j+1]``). The
        caller accepts the longest prefix where ``out[i][j] == feed_i[j+1]``
        and rolls back the rejected tail via :meth:`rollback`. All-single-token
        feeds keep the old on-device scan path unchanged — the k=0 fast case.
        Sampled verification consumes :meth:`verify` logits host-side instead
        (per-request seeded streams cannot share a device PRNG).

        EOS is not monitored on device: the loop always runs ``n_steps``; the
        caller trims at the first EOS (the fixed-shape scan is what makes the
        loop a single compiled program).
        """
        batch_uids = list(batch_uids)
        batch_tokens = [np.atleast_1d(np.asarray(t)) for t in batch_tokens]
        if any(t.size < 1 for t in batch_tokens):
            raise ValueError("decode_loop needs at least one next-input token per sequence")
        if n_steps < 1:
            raise ValueError("n_steps must be >= 1")
        if any(t.size != 1 for t in batch_tokens):
            if n_steps != 1:
                raise ValueError("a multi-token verify feed runs exactly one step "
                                 "(n_steps=1); the on-device scan takes single-token "
                                 "entries only")
            if temperature > 0:
                raise ValueError("the multi-token verify feed is greedy; sampled "
                                 "verification consumes engine.verify() logits "
                                 "host-side")
            # device-side argmax: only [1+k] int32 ids per sequence cross the
            # host boundary, not [1+k, vocab] float32 logits
            return self.verify(batch_uids, batch_tokens, do_checks=do_checks,
                               greedy=True)
        if do_checks:
            # each SCAN STEP's ragged batch holds one token per sequence, so
            # the token budget is checked against n_seqs — but the KV-block
            # budget must cover all n_steps appended tokens per sequence
            if len(batch_uids) > self._config.state_manager.max_ragged_sequence_count:
                raise SchedulingError(SchedulingResult.BatchSequenceLimitExceeded)
            if len(batch_uids) > self._config.state_manager.max_ragged_batch_size:
                raise SchedulingError(SchedulingResult.BatchTokenLimitExceeded)
            free_blocks = self._state_manager.free_blocks
            for uid in batch_uids:
                seq_desc = self._state_manager.get_sequence(uid)
                if seq_desc is None:
                    seq_desc = PlaceholderSequenceDescriptor()
                restore = self._restore_cost(uid, seq_desc)
                sched_len, sched_blocks = self._model.get_kv_requirements(
                    seq_desc, n_steps, free_blocks - restore)
                if sched_len != n_steps:
                    raise SchedulingError(SchedulingResult.KVCacheLimitExceeded)
                free_blocks -= sched_blocks + restore
        self._restore_offloaded(batch_uids)

        self._batch.clear()
        for uid, tokens in zip(batch_uids, batch_tokens):
            seq_desc = self._state_manager.get_or_create_sequence(uid)
            # pre-allocate KV blocks for the WHOLE generation: the device loop
            # cannot allocate mid-scan, and the block table is static inside it
            self._model.maybe_allocate_kv(seq_desc, n_steps)
            seq_desc.pre_forward(tokens.size)
            self._batch.insert_sequence(seq_desc, tokens, do_checks=do_checks)

        self._batch.finalize()
        spans = self._resolve_spans()
        observer = self.dispatch_observer
        if spans is not None or observer is not None:
            _t0 = _tel_now_us()
        tokens = self._model.decode_loop(self._batch, n_steps, temperature=temperature,
                                         rng=rng)  # [n_steps, S_bucket]
        if observer is not None:
            observer("decode_loop", len(batch_uids),
                     len(batch_uids) * n_steps, (_tel_now_us() - _t0) / 1e6)
        if spans is not None:
            spans.record("decode_loop", cat="inference", ts_us=_t0,
                         dur_us=_tel_now_us() - _t0,
                         args={"sequences": len(batch_uids),
                               "steps": n_steps,
                               "uids": [int(u) for u in batch_uids]})
        metrics = self._resolve_tel_metrics()
        if metrics is not None:
            self._write_telemetry(metrics, batch_tokens=len(batch_uids) * n_steps)
        for uid in batch_uids:
            seq_desc = self._state_manager.get_sequence(uid)
            seq_desc.post_forward()           # the token passed in
            if n_steps > 1:                   # the n_steps-1 tokens the loop inserted
                seq_desc.pre_forward(n_steps - 1)
                seq_desc.post_forward()
            self._model.maybe_free_kv(seq_desc)
        return tokens[:, :len(batch_uids)].T

    # ------------------------------------------------------ speculative verify --
    def verify(self, batch_uids: Iterable[int], batch_tokens: Iterable,
               do_checks: bool = True, greedy: bool = False) -> List[np.ndarray]:
        """Speculative-decoding verify step: feed each sequence its next-input
        token plus draft tokens (``batch_tokens[i]`` holds ``1+k_i`` ids)
        through ONE ragged forward — the chunked-prefill multi-token feed path
        — and return per-position logits: a list of float32 arrays, element i
        shaped ``[1+k_i, vocab]`` where row j scores the token AFTER
        ``batch_tokens[i][:j+1]``.

        ``greedy=True`` returns per-position ARGMAX ids instead (int32 arrays
        shaped ``[1+k_i]``): the argmax runs on device, so the host transfer
        is ``T`` ids rather than a ``[T, vocab]`` float32 materialization —
        the greedy verify path (decode_loop's multi-token branch) never pays
        the full-logit transfer.

        Every fed position's KV is written and committed (``seen_tokens``
        advances by ``1+k_i``); the caller decides the accepted prefix and
        truncates the rejected tail with :meth:`rollback` — the same
        write-then-truncate mechanism chunk-decode over-run relies on."""
        batch_uids = list(batch_uids)
        batch_tokens = [np.atleast_1d(np.asarray(t)) for t in batch_tokens]
        if do_checks:
            schedule_check = self.can_schedule(batch_uids, [t.size for t in batch_tokens])
            if schedule_check != SchedulingResult.Success:
                raise SchedulingError(schedule_check)
        self._restore_offloaded(batch_uids)

        self._batch.clear()
        if self._tracer:
            self._tracer.init_batch(is_empty_run=False, num_layers=self._model.num_layers)
        for uid, tokens in zip(batch_uids, batch_tokens):
            seq_desc = self._state_manager.get_or_create_sequence(uid)
            self._model.maybe_allocate_kv(seq_desc, tokens.size)
            seq_desc.pre_forward(tokens.size)
            self._batch.insert_sequence(seq_desc, tokens, do_checks=do_checks)
            if self._tracer:
                self._tracer.add_sequence(seq_desc)

        self._batch.finalize()
        self._model.prepare_batch(self._batch)
        spans = self._resolve_spans()
        observer = self.dispatch_observer
        if spans is not None or observer is not None:
            _t0 = _tel_now_us()
        # [T, vocab] logits, or [T] argmax ids when greedy
        rows = np.asarray(self._model.forward_verify(self._batch, greedy=greedy))
        if observer is not None:
            observer("verify", len(batch_uids),
                     int(sum(t.size for t in batch_tokens)),
                     (_tel_now_us() - _t0) / 1e6)

        for uid in batch_uids:
            seq_desc = self._state_manager.get_sequence(uid)
            seq_desc.post_forward()
            self._model.maybe_free_kv(seq_desc)
        n_tokens = int(sum(t.size for t in batch_tokens))
        if spans is not None:
            spans.record("verify", cat="inference", ts_us=_t0,
                         dur_us=_tel_now_us() - _t0,
                         args={"sequences": len(batch_uids),
                               "tokens": n_tokens,
                               "uids": [int(u) for u in batch_uids]})
        metrics = self._resolve_tel_metrics()
        if metrics is not None:
            self._write_telemetry(metrics, batch_tokens=n_tokens)
        # insertion order is batch order: each sequence's positions are one
        # contiguous token-major run
        out, offset = [], 0
        for tokens in batch_tokens:
            out.append(rows[offset:offset + tokens.size])
            offset += tokens.size
        return out

    def verify_tree(self, batch_uids: Iterable[int], trees: Iterable,
                    greedy: bool = False, do_checks: bool = True) -> List[dict]:
        """Token-tree verify: feed each sequence a draft TREE
        (:class:`~deepspeed_tpu.inference.v2.spec.tree.TokenTree`, root =
        next-input token) through ONE ragged forward under the tree-attention
        mask — multiple candidate branches priced for the cost of one
        dispatch. Returns one dict per sequence:

        - ``rows``:   float32 ``[n_nodes, vocab]`` logits (None when greedy) —
          row j scores the token AFTER node j's root path;
        - ``ids``:    int32 ``[n_nodes]`` device-argmax ids (greedy only);
        - ``hidden``: float32 ``[n_nodes, hidden]`` final residual states —
          the learned draft head's input for the next draft step.

        Every node's KV is written at slot ``seen + node_index`` and committed
        (``seen_tokens`` advances by ``n_nodes``); the caller walks the tree
        with the spec-off sampling rule and re-packs/truncates via
        :meth:`compact_accepted`."""
        batch_uids = list(batch_uids)
        trees = list(trees)
        if do_checks:
            schedule_check = self.can_schedule(batch_uids, [t.size for t in trees])
            if schedule_check != SchedulingResult.Success:
                raise SchedulingError(schedule_check)
        self._restore_offloaded(batch_uids)

        self._batch.clear()
        if self._tracer:
            self._tracer.init_batch(is_empty_run=False, num_layers=self._model.num_layers)
        for uid, tree in zip(batch_uids, trees):
            seq_desc = self._state_manager.get_or_create_sequence(uid)
            self._model.maybe_allocate_kv(seq_desc, tree.size)
            seq_desc.pre_forward(tree.size)
            self._batch.insert_sequence(seq_desc, tree.tokens, do_checks=do_checks,
                                        tree=(tree.parents, tree.depths))
            if self._tracer:
                self._tracer.add_sequence(seq_desc)

        self._batch.finalize()
        self._model.prepare_batch(self._batch)
        spans = self._resolve_spans()
        observer = self.dispatch_observer
        if spans is not None or observer is not None:
            _t0 = _tel_now_us()
        rows, hidden = self._model.forward_verify_tree(self._batch, greedy=greedy)
        rows, hidden = np.asarray(rows), np.asarray(hidden)
        if observer is not None:
            observer("verify_tree", len(batch_uids),
                     int(sum(t.size for t in trees)),
                     (_tel_now_us() - _t0) / 1e6)

        for uid in batch_uids:
            seq_desc = self._state_manager.get_sequence(uid)
            seq_desc.post_forward()
            self._model.maybe_free_kv(seq_desc)
        n_tokens = int(sum(t.size for t in trees))
        if spans is not None:
            spans.record("verify_tree", cat="inference", ts_us=_t0,
                         dur_us=_tel_now_us() - _t0,
                         args={"sequences": len(batch_uids),
                               "tokens": n_tokens,
                               "uids": [int(u) for u in batch_uids]})
        metrics = self._resolve_tel_metrics()
        if metrics is not None:
            self._write_telemetry(metrics, batch_tokens=n_tokens)
        out, offset = [], 0
        for tree in trees:
            n = tree.size
            out.append({"rows": None if greedy else rows[offset:offset + n],
                        "ids": rows[offset:offset + n] if greedy else None,
                        "hidden": hidden[offset:offset + n]})
            offset += n
        return out

    def compact_accepted(self, uid: int, n_fed: int, path_indices) -> int:
        """Tree-aware KV compaction after a :meth:`verify_tree` step over an
        ``n_fed``-node tree: keep the root plus the accepted path
        ``path_indices`` (ascending LOCAL node indices, root excluded),
        re-pack their KV to contiguous slots ``seen0 + 1..m`` in one jitted
        gather-then-scatter, and truncate the rejected remainder with the
        write-then-truncate rollback. Chain-shaped acceptances (``path[j] ==
        j+1``, the prompt-lookup case) skip the device copy entirely. Returns
        the number of rejected positions truncated."""
        seq_desc = self._state_manager.get_sequence(uid)
        if seq_desc is None:
            raise ValueError(f"compact_accepted: unknown uid {uid}")
        path = [int(i) for i in path_indices]
        if any(not (0 < i < n_fed) for i in path) or \
                any(b <= a for a, b in zip(path, path[1:])):
            raise ValueError(f"accepted path must be ascending non-root node "
                             f"indices inside the {n_fed}-node tree: {path}")
        copies = [(i, j + 1) for j, i in enumerate(path) if i != j + 1]
        if copies:
            seen0 = seq_desc.seen_tokens - n_fed  # committed count pre-feed
            self._model.compact_kv(seq_desc,
                                   [seen0 + s for s, _ in copies],
                                   [seen0 + d for _, d in copies])
        rejected = n_fed - 1 - len(path)
        if rejected > 0:
            seq_desc.rollback(rejected)
        return rejected

    def rollback(self, uid: int, n_tokens: int) -> None:
        """Truncate ``uid``'s last ``n_tokens`` committed tokens after a
        verify step rejected them: the stale KV stays in its blocks and is
        overwritten when the correct tokens are fed at those positions
        (write-then-truncate — the mechanism chunk-decode over-run already
        relies on). The blocks stay allocated for the sequence."""
        if n_tokens <= 0:
            return
        seq_desc = self._state_manager.get_sequence(uid)
        if seq_desc is None:
            raise ValueError(f"rollback: unknown uid {uid}")
        seq_desc.rollback(n_tokens)

    # ------------------------------------------------------------- scheduling --
    def query(self, uid: int, max_request_tokens: int, max_request_blocks: int) -> Tuple[int, int]:
        """(tokens schedulable, blocks required) for a hypothetical request."""
        seq_desc = self._state_manager.get_sequence(uid)
        if seq_desc is None:
            if self._state_manager.n_tracked_sequences >= self._config.state_manager.max_tracked_sequences:
                return (0, 0)
            seq_desc = PlaceholderSequenceDescriptor()
        restore = self._restore_cost(uid, seq_desc)
        toks, blocks = self._model.get_kv_requirements(
            seq_desc, max_request_tokens, max_request_blocks - restore)
        return toks, blocks + restore

    def _restore_cost(self, uid, seq_desc) -> int:
        """Device blocks a touch of ``uid`` must re-allocate first: an
        offloaded sequence's stale descriptor still reports its (freed)
        blocks as resident."""
        return seq_desc.cur_allocated_blocks if self._state_manager.is_offloaded(uid) else 0

    def can_schedule(self, uids: Iterable[int], lengths: Iterable[int]) -> SchedulingResult:
        uids, lengths = list(uids), list(lengths)
        cur_seqs = self._state_manager.n_tracked_sequences
        free_blocks = self._state_manager.free_blocks
        batch_len = 0

        if len(uids) > self._config.state_manager.max_ragged_sequence_count:
            return SchedulingResult.BatchSequenceLimitExceeded

        for uid, length in zip(uids, lengths):
            seq_desc = self._state_manager.get_sequence(uid)
            if seq_desc is None:
                cur_seqs += 1
                seq_desc = PlaceholderSequenceDescriptor()
            restore = self._restore_cost(uid, seq_desc)
            sched_len, sched_blocks = self._model.get_kv_requirements(
                seq_desc, length, free_blocks - restore)
            if sched_len != length:
                return SchedulingResult.KVCacheLimitExceeded
            batch_len += length
            free_blocks -= sched_blocks + restore

        if cur_seqs > self._config.state_manager.max_tracked_sequences:
            return SchedulingResult.EngineSequenceLimitExceeded
        if batch_len > self._config.state_manager.max_ragged_batch_size:
            return SchedulingResult.BatchTokenLimitExceeded
        return SchedulingResult.Success

    def get_remaining_block_capacity(self, uid: int) -> int:
        seq_desc = self._state_manager.get_sequence(uid)
        if seq_desc is None:
            return 0
        return self._model.get_remaining_block_capacity(seq_desc)

    def flush(self, uid: int) -> None:
        self._state_manager.flush_sequence(uid)

    # ------------------------------------------------------------- kv offload --
    def _restore_offloaded(self, batch_uids) -> None:
        """Touching an offloaded sequence restores it first (ZeRO-Inference
        KV-offload choreography; see ragged_manager.offload_sequence)."""
        for uid in batch_uids:
            if self._state_manager.is_offloaded(uid):
                self._state_manager.restore_sequence(uid)

    def offload_sequence(self, uid: int) -> None:
        """Evict a cold sequence's KV blocks to the host (or NVMe, when
        ``state_manager.offload_path`` is set), freeing device blocks for
        other sequences. The next put/decode_loop touching ``uid`` restores
        it transparently. Reference role: ``kv_cache.py:166`` offload +
        the ZeRO-Inference KV-offload leg (BASELINE.md)."""
        self._state_manager.offload_sequence(uid)

    def is_offloaded(self, uid: int) -> bool:
        return self._state_manager.is_offloaded(uid)

    # ------------------------------------------------------------- kv handoff --
    def export_sequence(self, uid: int, tokens=(), extra: Optional[dict] = None,
                        seen_tokens: Optional[int] = None,
                        version: Optional[int] = None) -> bytes:
        """Snapshot ``uid`` as a portable bytes payload — token history, KV-block
        contents and caller ``extra`` state — for :meth:`import_sequence` on
        ANOTHER engine: the fleet prefill→decode KV-block handoff transport,
        built on the same gather/scatter machinery as
        :meth:`offload_sequence`/``restore_sequence`` but serializable across a
        process or network boundary. ``seen_tokens`` caps the committed count
        the recipient adopts (chunked decode feeds ahead of the kept history;
        the recipient deterministically recomputes the trimmed tail). The
        sequence stays tracked here; ``flush(uid)`` once the recipient has
        taken over. ``version`` selects the frame version (None = the live
        handoff default; ``handoff.PARK_VERSION`` for parked-session frames,
        which carry a versioned tier record)."""
        from deepspeed_tpu.inference.v2.ragged.handoff import VERSION, pack_sequence
        return pack_sequence(self._state_manager, uid, tokens, extra=extra,
                             seen_tokens=seen_tokens,
                             version=VERSION if version is None else version)

    def import_sequence(self, payload: bytes, uid: Optional[int] = None) -> Tuple[int, dict]:
        """Recreate an exported sequence from a :meth:`export_sequence` payload
        under ``uid`` (default: the donor's uid); the next put/decode_loop
        continues it exactly where the donor stopped. Returns ``(uid, header)``
        — the header carries the token history and the exporter's ``extra``
        generation state."""
        from deepspeed_tpu.inference.v2.ragged.handoff import import_payload
        return import_payload(self._state_manager, payload, uid=uid)

    def flush_all(self) -> None:
        """Recycle every tracked sequence's KV blocks (hybrid-engine post-
        generation cleanup; reference release_inference_cache role)."""
        for uid in list(self._state_manager.tracked_sequences):
            self._state_manager.flush_sequence(uid)

    # ---------------------------------------------------------- lowering hooks --
    def lowerable_callables(self) -> dict:
        """The engine's jitted device programs as raw ``jax.jit`` callables
        (``.lower()``-able): ``forward`` keyed by ``(T, S, MB)`` pad bucket,
        ``decode_loop`` keyed by ``(bucket, n_steps, sampled)`` and ``verify``
        keyed by ``("verify", bucket)``. This is the official hook for
        HLO-level analysis (the deepspeed_tpu/perf/ gates); the jit-cache
        entries themselves may be compile-watch wrappers shared with
        telemetry and cannot lower."""
        return self._model.lowerable_callables()

    def lower_forward(self, bucket=None):
        """``jax.stages.Lowered`` of the ragged forward at ``bucket``
        (default: the smallest bucket). Never executes."""
        return self._model.lower_forward(bucket)

    def lower_decode_loop(self, n_steps: int, bucket=None, temperature: float = 0.0):
        """``jax.stages.Lowered`` of the on-device ``n_steps`` decode scan."""
        return self._model.lower_decode_loop(n_steps, bucket=bucket,
                                             temperature=temperature)

    def lower_verify_step(self, bucket=None):
        """``jax.stages.Lowered`` of the speculative verify program (one
        ragged forward unembedding every fed position). Never executes."""
        return self._model.lower_verify_step(bucket)

    def lower_tree_verify(self, bucket=None, greedy: bool = False):
        """``jax.stages.Lowered`` of the token-tree verify program (one
        ragged forward under the tree-attention mask, unembedding every node
        and returning the draft head's hidden states). Never executes."""
        return self._model.lower_tree_verify(bucket, greedy=greedy)

    # -------------------------------------------------------------- empty_run --
    def empty_run(self) -> None:
        """Participate in EP collectives with zero live tokens (fork
        engine_v2.py:308) — keeps idle replicas in lock-step with busy ones."""
        if self._tracer:
            self._tracer.init_batch(is_empty_run=True, num_layers=self._model.num_layers)
        metrics = self._resolve_tel_metrics()
        if metrics is not None:
            metrics["empty_runs"].inc()
        self._model.empty_run()

    # -------------------------------------------------------------- serialize --
    def serialize(self, save_path: str) -> None:
        """Write flattened params + metadata (reference engine_v2.py:289).
        ``engine_factory.build_engine_from_ds_checkpoint`` is the loader.

        Format notes: sub-byte/non-native dtypes (bf16) are stored as
        same-width uint views with the logical dtype in the metadata (npz
        can't carry ml_dtypes); trees must be pure string-keyed dicts with
        '/'-free keys (anything else cannot round-trip through the path
        encoding and is REJECTED here, not corrupted on load); the model
        config is JSON (no pickle — a checkpoint directory must never be an
        arbitrary-code-execution vector)."""
        import dataclasses

        import jax

        os.makedirs(save_path, exist_ok=True)
        leaves_with_paths = jax.tree_util.tree_flatten_with_path(self._model._params)[0]
        arrays, meta = {}, []
        for i, (path, leaf) in enumerate(leaves_with_paths):
            if not path:
                raise ValueError(
                    "serialize needs a dict param tree (a bare-leaf root has "
                    "no key path to encode and would not round-trip)")
            keys = []
            for k in path:
                key = getattr(k, "key", None)
                if not isinstance(key, str) or "/" in key:
                    raise ValueError(
                        f"serialize supports string-keyed dict trees with "
                        f"'/'-free keys only; cannot round-trip node {k!r} "
                        f"in path {jax.tree_util.keystr(path)}")
                keys.append(key)
            arr = np.asarray(jax.device_get(leaf))
            logical = str(arr.dtype)
            if arr.dtype.kind not in "fiub" or logical == "bfloat16":
                arr = arr.view(np.dtype(f"u{arr.dtype.itemsize}"))
            arrays[f"p{i}"] = arr
            meta.append({"path": "/".join(keys), "shape": list(leaf.shape),
                         "dtype": logical})
        np.savez(os.path.join(save_path, "params_rank0.npz"), **arrays)
        with open(os.path.join(save_path, "metadata_rank0.json"), "w") as f:
            json.dump(meta, f)

        cfg = self._model.config
        fields = {}
        for f_ in dataclasses.fields(cfg):
            v = getattr(cfg, f_.name)
            try:
                json.dumps(v)
            except TypeError:
                v = {"__dtype__": np.dtype(v).name}
            fields[f_.name] = v
        with open(os.path.join(save_path, "ds_model_config.json"), "w") as f:
            json.dump({"config_class": f"{type(cfg).__module__}.{type(cfg).__qualname__}",
                       "fields": fields}, f, indent=2)
        logger.info(f"serialized {len(arrays)} param tensors to {save_path}")
