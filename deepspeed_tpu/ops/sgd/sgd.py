"""SGD with momentum (torch.optim.SGD-compatible semantics)."""

from typing import NamedTuple

import jax
import jax.numpy as jnp

from deepspeed_tpu.ops.optimizer import TpuOptimizer, _tree_zeros_like


class SGDState(NamedTuple):
    step: jnp.ndarray
    momentum_buf: any


class SGD(TpuOptimizer):

    name = "sgd"

    def __init__(self, lr=1e-2, momentum=0.0, weight_decay=0.0, nesterov=False):
        super().__init__(lr=lr, weight_decay=weight_decay)
        self.momentum = momentum
        self.nesterov = nesterov

    def init(self, params):
        return SGDState(step=jnp.zeros([], jnp.int32),
                        momentum_buf=_tree_zeros_like(params) if self.momentum else None)

    def update(self, grads, state, params, lr):
        wd = self.weight_decay
        mom = self.momentum

        def upd(p, g, b):
            g = g.astype(p.dtype)
            if wd != 0.0:
                g = g + wd * p
            if mom != 0.0:
                b = mom * b + g
                g = (g + mom * b) if self.nesterov else b
            return p - lr * g, b

        p_flat, treedef = jax.tree.flatten(params)
        g_flat = treedef.flatten_up_to(grads)
        b_flat = treedef.flatten_up_to(state.momentum_buf) if mom else [None] * len(p_flat)
        if mom:
            out = [upd(p, g, b) for p, g, b in zip(p_flat, g_flat, b_flat)]
            return (jax.tree.unflatten(treedef, [o[0] for o in out]),
                    SGDState(step=state.step + 1,
                             momentum_buf=jax.tree.unflatten(treedef, [o[1] for o in out])))
        new_p = [p - lr * (g.astype(p.dtype) + (wd * p if wd else 0.0)) for p, g in zip(p_flat, g_flat)]
        return jax.tree.unflatten(treedef, new_p), SGDState(step=state.step + 1, momentum_buf=None)
