"""Inference-v2 model breadth (VERDICT r2 missing #5): mistral / qwen2 / opt /
falcon / phi ragged engines, logit-parity-tested against their training
forwards — the same gate the llama/mixtral implementations pass."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deepspeed_tpu.inference.v2.config_v2 import RaggedInferenceEngineConfig
from deepspeed_tpu.inference.v2.engine_factory import build_engine
from deepspeed_tpu.inference.v2.ragged.manager_configs import (AllocationMode,
                                                               DSStateManagerConfig,
                                                               MemoryConfig)
from deepspeed_tpu.utils import groups


def _ecfg():
    mgr = DSStateManagerConfig(memory_config=MemoryConfig(mode=AllocationMode.ALLOCATE, size=64),
                               max_context=128)
    return RaggedInferenceEngineConfig(state_manager=mgr, kv_block_size=16)


def _training_logits(model_cls, cfg, params, ids):
    logits = model_cls(cfg).apply({"params": params["model"] if "model" in params else params},
                                  ids[None])
    return np.asarray(logits[0], np.float32)


def _roundtrip(cfg, init_params_fn, inner_model_cls, decode_steps=2):
    groups.initialize_mesh(force=True)
    _, params = init_params_fn(cfg)
    rng = np.random.default_rng(0)
    prompt = rng.integers(0, cfg.vocab_size, 13)

    eng = build_engine(params, cfg, _ecfg())
    got = np.asarray(eng.put([0], [prompt]))[0]

    want = _training_logits(inner_model_cls, cfg, params, jnp.asarray(prompt, jnp.int32))[-1]
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)

    # paged decode continues consistently
    ctx = list(prompt)
    out = got
    for _ in range(decode_steps):
        nxt = int(np.argmax(out))
        ctx.append(nxt)
        out = np.asarray(eng.put([0], [np.asarray([nxt])]))[0]
    full = _training_logits(inner_model_cls, cfg, params,
                            jnp.asarray(np.asarray(ctx), jnp.int32))[-1]
    np.testing.assert_allclose(out, full, rtol=2e-4, atol=2e-4)


def test_mistral_sliding_window():
    from deepspeed_tpu.models.llama import LlamaConfig, LlamaModel, init_params
    from deepspeed_tpu.inference.v2.model_implementations.llama_v2 import MistralV2Model

    cfg = LlamaConfig.tiny(dtype=jnp.float32, model_type="mistral", sliding_window=8)
    groups.initialize_mesh(force=True)
    _, params = init_params(cfg)
    eng = build_engine(params, cfg, _ecfg())
    assert isinstance(eng.model, MistralV2Model)
    assert eng.model.attention_window == 8

    rng = np.random.default_rng(1)
    prompt = rng.integers(0, cfg.vocab_size, 20)  # longer than the window
    got = np.asarray(eng.put([0], [prompt]))[0]
    want = _training_logits(LlamaModel, cfg, params, jnp.asarray(prompt, jnp.int32))[-1]
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)

    # the window must MATTER: a full-causal engine disagrees beyond the window
    full_cfg = LlamaConfig.tiny(dtype=jnp.float32)
    full = np.asarray(build_engine(params, full_cfg, _ecfg()).put([0], [prompt]))[0]
    assert not np.allclose(got, full, atol=1e-3)


def test_qwen2_biases():
    from deepspeed_tpu.models.llama import LlamaConfig, LlamaModel, init_params

    cfg = LlamaConfig.tiny(dtype=jnp.float32, model_type="qwen2", attention_bias=True)
    groups.initialize_mesh(force=True)
    _, params = init_params(cfg)
    assert "bias" in params["model"]["layers_0"]["self_attn"]["q_proj"]
    _roundtrip(cfg, lambda c: init_params(c), LlamaModel)


@pytest.mark.parametrize("variant", ["opt", "falcon", "phi", "gptj", "gpt_neox"])
def test_decoder_family(variant):
    from deepspeed_tpu.models.decoder import DecoderConfig, DecoderModel, init_params
    from deepspeed_tpu.inference.v2.model_implementations.decoder_v2 import DecoderV2Model

    cfg = DecoderConfig.tiny(variant)
    groups.initialize_mesh(force=True)
    _, params = init_params(cfg)
    eng = build_engine(params, cfg, _ecfg())
    assert isinstance(eng.model, DecoderV2Model)
    _roundtrip(cfg, lambda c: init_params(c), DecoderModel)


def test_registry_lists_reference_breadth():
    from deepspeed_tpu.inference.v2.model_implementations.registry import \
        supported_model_types

    # the reference factory's model_type table (engine_factory.py:66-120)
    for mt in ("llama", "mistral", "mixtral", "opt", "falcon", "phi", "qwen2",
               "gptj", "gpt_neox"):
        assert mt in supported_model_types()


def test_bloom_v2_rejected_with_pointer():
    """ALiBi is not implemented in the paged attention paths: serving a bloom
    config through v2 must fail loudly with a pointer at the v1 engine, not
    emit wrong logits through the isinstance fallback."""
    from deepspeed_tpu.models.decoder import DecoderConfig, init_params

    cfg = DecoderConfig.tiny("bloom")
    groups.initialize_mesh(force=True)
    _, params = init_params(cfg)
    with pytest.raises(NotImplementedError, match="v1 engine"):
        build_engine(params, cfg, _ecfg())
