"""SpanRecorder: ring bound, Chrome-trace export, timer wrapping."""

import json
import time

from deepspeed_tpu.telemetry import SpanRecorder, TracingTimers
from deepspeed_tpu.utils.timer import SynchronizedWallClockTimer


def test_ring_buffer_bound_and_drop_count():
    rec = SpanRecorder(max_spans=4)
    for i in range(10):
        rec.record(f"s{i}", ts_us=i, dur_us=1)
    assert len(rec) == 4
    assert rec.dropped == 6
    names = [e["name"] for e in rec.chrome_trace()["traceEvents"]]
    assert names == ["s6", "s7", "s8", "s9"]


def test_span_context_manager_measures():
    rec = SpanRecorder()
    with rec.span("work", cat="test", args={"k": 1}):
        time.sleep(0.01)
    (ev, ) = rec.chrome_trace()["traceEvents"]
    assert ev["name"] == "work" and ev["cat"] == "test"
    assert ev["ph"] == "X" and ev["dur"] >= 9000
    assert ev["args"] == {"k": 1}


def test_chrome_trace_export_is_loadable(tmp_path):
    rec = SpanRecorder()
    # recorded out of order on purpose: export must sort by ts
    rec.record("late", ts_us=500, dur_us=10)
    rec.record("early", ts_us=100, dur_us=10)
    rec.record("mid", ts_us=300, dur_us=10)
    path = rec.export_chrome_trace(str(tmp_path / "trace.json"))

    with open(path) as f:
        trace = json.load(f)  # must be valid JSON
    evs = trace["traceEvents"]
    assert [e["ts"] for e in evs] == sorted(e["ts"] for e in evs)
    assert all(e["ph"] == "X" for e in evs)  # complete events: no B/E pairing to break
    assert all(isinstance(e["dur"], int) and e["dur"] >= 0 for e in evs)


def test_tracing_timers_wrap_wall_clock_timers():
    rec = SpanRecorder()
    timers = TracingTimers(SynchronizedWallClockTimer(), rec)
    t = timers("fwd")
    t.start()
    time.sleep(0.005)
    t.stop()
    t.start()
    t.stop()
    evs = rec.chrome_trace()["traceEvents"]
    assert [e["name"] for e in evs] == ["fwd", "fwd"]
    assert evs[0]["cat"] == "engine" and evs[0]["dur"] >= 4000
    # the inner timer still accumulates (the engine's log() path keeps working)
    assert timers("fwd").elapsed(reset=False) > 0
    assert "fwd" in timers.get_timers()
