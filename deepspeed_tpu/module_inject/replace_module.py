"""In-place module replacement entry points (reference
``deepspeed/module_inject/replace_module.py``).

The reference swaps live ``torch.nn.Module`` layers for fused kernels inside
an already-constructed model. Flax modules are immutable descriptions, so
in-process surgery has no TPU analog — injection happens at the CHECKPOINT
boundary instead (``containers.load_hf_checkpoint`` /
``init_inference(checkpoint=...)``), which covers the same architectures
with torch-forward parity. These functions exist so reference call sites
fail with a pointer at the equivalent path rather than an AttributeError.
"""


def replace_transformer_layer(orig_layer_impl=None, model=None, checkpoint_dict=None,
                              config=None, model_config=None):
    raise NotImplementedError(
        "replace_transformer_layer: flax modules are immutable, so live-module "
        "surgery has no TPU analog. Use deepspeed_tpu.init_inference("
        "checkpoint=<hf_dir>) — the checkpoint-boundary injection path covering "
        "the same architectures (module_inject/containers.py) — or serve through "
        "the v2 ragged engine (inference.v2.engine_factory.build_engine).")


def revert_transformer_layer(orig_layer_impl=None, model=None, config=None,
                             preln=False):
    raise NotImplementedError(
        "revert_transformer_layer: nothing to revert — TPU injection happens at "
        "the checkpoint boundary (see replace_transformer_layer), leaving no "
        "live model to restore.")
