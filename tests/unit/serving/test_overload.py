"""Overload control, serving side (ISSUE tentpole a+b): priority admission,
deadline-aware shedding, staged brownout degradation, the Retry-After
contract on every 429/503, and the loadgen --overload / dstpu_report
--overload tooling.

Policy math (RateEstimator, BrownoutController) is tested engine-free;
scheduler behavior drives ``step()`` manually (``start=False``) like
test_scheduler.py.
"""

import json
import os
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from deepspeed_tpu.serving import (AdmissionRejected, BrownoutController,
                                   RateEstimator, RequestState, ServingConfig,
                                   ServingScheduler, ServingServer)
from deepspeed_tpu.serving.config import OverloadConfig
from deepspeed_tpu.serving.overload import validate_priority

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

MAX_STEPS = 400


def _run_until(sched, pred, max_steps=MAX_STEPS):
    for _ in range(max_steps):
        if pred():
            return
        sched.step()
    raise AssertionError(f"predicate not reached in {max_steps} steps")


def _warm(sched, tokens_per_s=100.0, batches=6):
    """Warm the scheduler's rate estimator to a known synthetic rate: one
    batch of ``tokens_per_s`` tokens per synthetic second."""
    for i in range(batches):
        sched._rate.observe(int(tokens_per_s), now=float(i))
    assert sched._rate.rate == pytest.approx(tokens_per_s)


def _prompt(n=9, vocab=64):
    return (np.arange(n) % vocab).tolist()


# ---------------------------------------------------------------------------
# policy primitives (engine-free)
# ---------------------------------------------------------------------------
def test_priority_validation_default_and_unknown():
    assert validate_priority(None) == "interactive"
    assert validate_priority("interactive") == "interactive"
    assert validate_priority("batch") == "batch"
    with pytest.raises(ValueError, match="unknown priority"):
        validate_priority("platinum")


def test_rate_estimator_cold_then_converges():
    est = RateEstimator(alpha=0.5, min_samples=3)
    assert est.rate is None and est.seconds_for(100) is None  # cold
    est.observe(50, now=0.0)          # first batch: no interval yet
    est.observe(50, now=1.0)
    est.observe(50, now=2.0)
    assert est.rate is None           # 2 samples < min_samples
    est.observe(50, now=3.0)
    assert est.warm and est.rate == pytest.approx(50.0)
    assert est.seconds_for(100) == pytest.approx(2.0)
    # zero token counts and non-advancing clocks are ignored, never corrupt
    est.observe(0, now=4.0)
    est.observe(10, now=2.5)  # behind the last observation: dt <= 0
    assert est.rate == pytest.approx(50.0)


def test_brownout_stages_escalate_and_hysteresis_holds():
    ctl = BrownoutController(thresholds=(0.4, 0.6, 0.8), hysteresis=0.15,
                             alpha=1.0)  # alpha=1: the raw signal IS the stage driver
    assert ctl.update(0.1) == 0
    assert ctl.update(0.45) == 1
    assert ctl.update(0.65) == 2
    assert ctl.update(0.85) == 3 == ctl.max_stage
    # hovering just below the stage-3 threshold holds the stage (hysteresis)
    assert ctl.update(0.7) == 3
    # falling past threshold - hysteresis de-escalates (0.8 - 0.15 = 0.65)
    assert ctl.update(0.6) == 2
    assert ctl.update(0.0) == 0
    assert ctl.transitions == 5  # 0->1->2->3 then 3->2 and 2->0


def test_brownout_thresholds_must_be_ascending():
    with pytest.raises(ValueError, match="ascending"):
        BrownoutController(thresholds=(0.8, 0.6, 0.9))
    with pytest.raises(ValueError, match="ascending"):
        OverloadConfig(brownout_stage_thresholds=(0.9, 0.5, 0.95))


# ---------------------------------------------------------------------------
# admission control + shedding (manual stepping)
# ---------------------------------------------------------------------------
def test_admission_rejects_provably_unmeetable_deadline(make_engine):
    engine = make_engine()
    sched = ServingScheduler(engine, ServingConfig(), start=False)
    try:
        _warm(sched, tokens_per_s=100.0)
        # 9 prompt + 200 generation tokens at 100 tok/s ~ 2.1s >> 0.05s
        with pytest.raises(AdmissionRejected) as exc:
            sched.submit(_prompt(), max_new_tokens=200, deadline_s=0.05)
        assert exc.value.retry_after_s >= sched._config.overload.retry_after_floor_s
        assert sched.stats()["counters"]["shed_admission"] == 1
        # nothing was admitted, nothing touched the engine
        assert sched.queue_depth == 0 and sched.n_active == 0
        # a feasible deadline at the same rate is admitted
        req = sched.submit(_prompt(), max_new_tokens=3, deadline_s=30.0)
        _run_until(sched, lambda: req.state is RequestState.DONE)
    finally:
        sched.stop(drain=False)


def test_cold_estimator_admits_everything(make_engine):
    """Admission control can only act on what it can prove: a cold rate
    estimator admits even an absurd deadline (it will time out later, but
    was never rejected on a guess)."""
    engine = make_engine()
    sched = ServingScheduler(engine, ServingConfig(), start=False)
    try:
        req = sched.submit(_prompt(), max_new_tokens=500, deadline_s=0.001)
        assert req is not None  # admitted, not AdmissionRejected
    finally:
        sched.stop(drain=False)


def test_priority_ordering_admits_interactive_before_batch(make_engine):
    engine = make_engine(max_tracked_sequences=1)  # serialize admission
    sched = ServingScheduler(engine, ServingConfig(), start=False)
    try:
        b1 = sched.submit(_prompt(), max_new_tokens=2, priority="batch")
        b2 = sched.submit(_prompt(5), max_new_tokens=2, priority="batch")
        i1 = sched.submit(_prompt(7), max_new_tokens=2, priority="interactive")
        _run_until(sched, lambda: i1.state is RequestState.DONE)
        # the interactive request finished while at least one earlier-queued
        # batch request was still waiting: (priority, deadline, arrival) order
        assert b2.state is not RequestState.DONE
        _run_until(sched, lambda: b1.state is RequestState.DONE
                   and b2.state is RequestState.DONE)
    finally:
        sched.stop(drain=False)


def test_overload_disabled_is_fifo_control(make_engine):
    """The control arm: overload.enabled=False restores strict FIFO admission
    and never rejects at submit()."""
    engine = make_engine(max_tracked_sequences=1)
    cfg = ServingConfig(overload=OverloadConfig(enabled=False))
    sched = ServingScheduler(engine, cfg, start=False)
    try:
        _warm(sched, tokens_per_s=100.0)
        b1 = sched.submit(_prompt(), max_new_tokens=2, priority="batch",
                          deadline_s=120.0)
        i1 = sched.submit(_prompt(7), max_new_tokens=2, priority="interactive",
                          deadline_s=120.0)
        _run_until(sched, lambda: b1.state is RequestState.DONE)
        assert i1.state is not RequestState.DONE  # FIFO: batch went first
        _run_until(sched, lambda: i1.state is RequestState.DONE)
        # no admission gate either: an unmeetable deadline is still admitted
        req = sched.submit(_prompt(), max_new_tokens=500, deadline_s=0.001)
        assert req.shed_reason is None
    finally:
        sched.stop(drain=False)


def test_queue_shed_under_pressure_lowest_priority_first(make_engine):
    """Sustained pressure (brownout stage >= 1) sheds queued requests whose
    deadline is provably unmeetable — batch before interactive, before any
    engine work."""
    engine = make_engine(max_tracked_sequences=1)
    # admission control off: the requests must actually QUEUE so the
    # stage->shed path (not the submit() gate) is what rejects them
    cfg = ServingConfig(queue_capacity=4,
                        overload=OverloadConfig(admission_control=False))
    sched = ServingScheduler(engine, cfg, start=False)
    try:
        _warm(sched, tokens_per_s=10.0)  # slow: 49 tokens ~ 4.9s
        # each request is ~4.9s of work; at 6s deadlines the first fits and
        # every later one is provably unmeetable behind it
        reqs = [sched.submit(_prompt(), max_new_tokens=40, deadline_s=6.0,
                             priority=p)
                for p in ("interactive", "batch", "batch")]
        # force pressure past stage 1 (the shed trigger), deterministically
        for _ in range(30):
            sched._brownout.update(1.0)
        assert sched._brownout.stage >= 1
        sched._shed_queued(now=reqs[0].arrival_s)
        shed = [r for r in reqs if r.shed_reason is not None]
        assert shed, "nothing shed under provable overload"
        for r in shed:
            assert r.state is RequestState.FAILED
            assert r.retry_after_s is not None and r.retry_after_s > 0
            assert r.tokens == [] and r._fed == 0  # zero engine work consumed
        # the interactive request survives while any batch was shed
        if len(shed) < len(reqs):
            assert all(r.priority == "batch" for r in shed)
        assert sched.stats()["counters"]["shed_queue"] == len(shed)
    finally:
        sched.stop(drain=False)


# ---------------------------------------------------------------------------
# brownout stages
# ---------------------------------------------------------------------------
def _force_stage(sched, stage, pin=False):
    """Drive the brownout controller to ``stage`` through its own update
    path. ``pin=True`` additionally freezes it there — tests that keep
    stepping would otherwise watch the stage decay as every tick feeds the
    real (idle) pressure signal."""
    thresholds = sched._brownout._thresholds
    target = 1.0 if stage >= len(thresholds) else (
        (thresholds[stage - 1] + thresholds[stage]) / 2 if stage else 0.0)
    for _ in range(60):
        sched._brownout.update(target)
    assert sched._brownout.stage == stage
    if pin:
        sched._brownout.update = lambda pressure: stage


def test_brownout_stage1_clamps_batch_only_flagged(make_engine):
    engine = make_engine()
    sched = ServingScheduler(engine, ServingConfig(), start=False)
    try:
        _force_stage(sched, 1)
        clamp = sched._config.overload.brownout_clamp_max_new_tokens
        batch = sched.submit(_prompt(), max_new_tokens=clamp + 50,
                             priority="batch")
        assert batch.max_new_tokens == clamp
        assert "max_new_tokens_clamped" in batch.degraded_mode  # never silent
        inter = sched.submit(_prompt(5), max_new_tokens=clamp + 50,
                             priority="interactive")
        assert inter.max_new_tokens == clamp + 50  # interactive untouched
        assert not inter.degraded_mode
        assert sched.stats()["counters"]["brownout_clamped"] == 1
    finally:
        sched.stop(drain=False)


def test_brownout_stage2_disables_speculative_decode_chunk(make_engine,
                                                           llama_setup):
    """Stage >= 2: chunked decode dispatch falls back to one token per step,
    flagged per request — and the tokens stay greedy-identical."""
    cfg, _, _ = llama_setup
    engine = make_engine()
    sched = ServingScheduler(engine, ServingConfig(decode_chunk=4), start=False)
    try:
        prompt = _prompt(vocab=cfg.vocab_size)
        base = sched.submit(prompt, max_new_tokens=5)
        _run_until(sched, lambda: base.state is RequestState.DONE)
        batches_before_stage2 = sched.stats()["counters"]["batches"]

        _force_stage(sched, 2, pin=True)
        req = sched.submit(prompt, max_new_tokens=5)
        assert "speculative_disabled" in req.degraded_mode
        _run_until(sched, lambda: req.state is RequestState.DONE)
        assert req.tokens == base.tokens  # degraded, not different
        # one token per step now: strictly more batches than the chunked run
        degraded_batches = (sched.stats()["counters"]["batches"]
                            - batches_before_stage2)
        assert degraded_batches > 2  # 1 prefill + 5 single-token decode steps
    finally:
        sched.stop(drain=False)


def test_brownout_stage3_rejects_batch_admits_interactive(make_engine):
    engine = make_engine()
    sched = ServingScheduler(engine, ServingConfig(), start=False)
    try:
        _force_stage(sched, 3)
        with pytest.raises(AdmissionRejected, match="stage 3"):
            sched.submit(_prompt(), max_new_tokens=2, priority="batch")
        assert sched.stats()["counters"]["brownout_rejected"] == 1
        req = sched.submit(_prompt(5), max_new_tokens=2, priority="interactive")
        _run_until(sched, lambda: req.state is RequestState.DONE)
    finally:
        sched.stop(drain=False)


def test_brownout_recovers_and_stats_expose_overload_block(make_engine):
    engine = make_engine()
    sched = ServingScheduler(engine, ServingConfig(), start=False)
    try:
        _force_stage(sched, 3)
        _force_stage(sched, 0)  # pressure collapsed: full service restored
        req = sched.submit(_prompt(), max_new_tokens=2, priority="batch")
        _run_until(sched, lambda: req.state is RequestState.DONE)
        doc = sched.stats()["overload"]
        assert doc["enabled"] and doc["brownout_stage"] == 0
        assert doc["retry_after_s"] >= 0
    finally:
        sched.stop(drain=False)


# ---------------------------------------------------------------------------
# the Retry-After contract over HTTP
# ---------------------------------------------------------------------------
def test_http_429_and_503_carry_retry_after(make_engine):
    engine = make_engine()
    sched = ServingScheduler(engine, ServingConfig(), start=False)
    srv = ServingServer(sched).start()
    try:
        _warm(sched, tokens_per_s=10.0)
        body = json.dumps({"prompt": _prompt(), "max_new_tokens": 400,
                           "deadline_s": 0.05}).encode()
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(urllib.request.Request(
                srv.url + "/v1/generate", data=body,
                headers={"Content-Type": "application/json"}), timeout=30)
        assert exc.value.code == 429
        retry_after = exc.value.headers.get("Retry-After")
        assert retry_after is not None and int(retry_after) >= 1
        assert json.loads(exc.value.read())["retry_after_s"] > 0

        # draining: 503 with the same contract
        srv._draining.set()
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(urllib.request.Request(
                srv.url + "/v1/generate", data=body,
                headers={"Content-Type": "application/json"}), timeout=30)
        assert exc.value.code == 503
        assert int(exc.value.headers.get("Retry-After")) >= 1
    finally:
        srv.stop(drain=False)


def test_http_priority_header_and_unknown_class_400(make_engine):
    engine = make_engine()
    sched = ServingScheduler(engine, ServingConfig(), start=False)
    srv = ServingServer(sched).start()
    try:
        _force_stage(sched, 3)  # batch is rejected: proves the header landed
        body = json.dumps({"prompt": _prompt(), "max_new_tokens": 2}).encode()
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(urllib.request.Request(
                srv.url + "/v1/generate", data=body,
                headers={"Content-Type": "application/json",
                         "X-DSTPU-Priority": "batch"}), timeout=30)
        assert exc.value.code == 429

        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(urllib.request.Request(
                srv.url + "/v1/generate",
                data=json.dumps({"prompt": _prompt(), "priority": "gold",
                                 "max_new_tokens": 2}).encode(),
                headers={"Content-Type": "application/json"}), timeout=30)
        assert exc.value.code == 400  # unknown class is a client error
    finally:
        srv.stop(drain=False)


def test_response_doc_carries_priority_and_degradations(make_engine):
    engine = make_engine()
    sched = ServingScheduler(engine, ServingConfig(), start=False)
    srv = ServingServer(sched).start()
    try:
        _force_stage(sched, 1, pin=True)
        clamp = sched._config.overload.brownout_clamp_max_new_tokens
        body = json.dumps({"prompt": _prompt(), "max_new_tokens": clamp + 10,
                           "priority": "batch"}).encode()
        resp_holder = {}

        def post():
            with urllib.request.urlopen(urllib.request.Request(
                    srv.url + "/v1/generate", data=body,
                    headers={"Content-Type": "application/json"}),
                    timeout=60) as resp:
                resp_holder["doc"] = json.loads(resp.read())
        t = threading.Thread(target=post, daemon=True)
        t.start()
        # wall-clock-bounded stepping: the handler thread needs real time to
        # connect and submit before steps have any work to do
        deadline = time.monotonic() + 60
        while "doc" not in resp_holder and time.monotonic() < deadline:
            sched.step()
            time.sleep(0.001)
        t.join(timeout=10)
        assert "doc" in resp_holder, "response never arrived"
        doc = resp_holder["doc"]
        assert doc["priority"] == "batch"
        assert doc["degraded_mode"] == ["max_new_tokens_clamped"]
        assert doc["n_tokens"] == clamp  # the clamp actually bounded decode
    finally:
        srv.stop(drain=False)


# ---------------------------------------------------------------------------
# loadgen --overload ramp + dstpu_report --overload (ISSUE satellites)
# ---------------------------------------------------------------------------
def _overload_doc(goodputs, capacity=10.0):
    return {"capacity_req_s": capacity, "deadline_s": 2.0,
            "interactive_frac": 0.5, "requests_per_step": 8,
            "steps": [{"offered_x": 0.5 * (i + 1),
                       "offered_req_s": 0.5 * (i + 1) * capacity,
                       "goodput_req_s": g, "requests": 8, "ok": 8,
                       "on_deadline": 8, "shed": i, "degraded": 0, "hedged": 0,
                       "queue_expired": 0, "wall_s": 1.0,
                       "ttft": {"interactive": {"p50_s": 0.01, "p99_s": 0.05,
                                                "n": 4},
                                "batch": {"p50_s": 0.02, "p99_s": 0.08,
                                          "n": 4}}}
                      for i, g in enumerate(goodputs)]}


def test_report_overload_flags_the_knee(tmp_path, capsys):
    from deepspeed_tpu.env_report import overload_report
    path = tmp_path / "ramp.json"
    # goodput holds at capacity then collapses: knee at the third step
    path.write_text(json.dumps(_overload_doc([9.8, 9.5, 6.0, 4.0])))
    assert overload_report(str(path)) == 0
    out = capsys.readouterr().out
    assert "<- knee" in out
    assert "knee at 1.5x" in out  # first step below 90% of 10 req/s

    # no knee: goodput held
    path.write_text(json.dumps(_overload_doc([9.8, 9.5, 9.2])))
    assert overload_report(str(path)) == 0
    assert "no knee" in capsys.readouterr().out

    # a sub-capacity step is bounded by OFFERED load, not collapse: a lone
    # 0.5x step serving everything it was offered (5 < 9 req/s) is no knee
    path.write_text(json.dumps(_overload_doc([4.9])))
    assert overload_report(str(path)) == 0
    assert "no knee" in capsys.readouterr().out

    # garbage input is a loud rc 2, not a traceback
    bad = tmp_path / "bad.json"
    bad.write_text("{}")
    assert overload_report(str(bad)) == 2
    assert overload_report(str(tmp_path / "missing.json")) == 2


def _loadgen_module():
    """Load bin/dstpu_loadgen as a module (top-level imports are stdlib-only;
    main() is __main__-guarded) so its SLO helpers are unit-testable."""
    import importlib.util
    from importlib.machinery import SourceFileLoader
    loader = SourceFileLoader("_dstpu_loadgen_under_test",
                              os.path.join(REPO, "bin", "dstpu_loadgen"))
    spec = importlib.util.spec_from_loader(loader.name, loader)
    mod = importlib.util.module_from_spec(spec)
    loader.exec_module(mod)
    return mod


class _R:
    """A loadgen _Result stand-in: just the fields _slo_step_eval reads."""

    def __init__(self, ok=True, ttft_s=None, itl_s=(), e2e_s=None):
        self.ok = ok
        self.ttft_s = ttft_s
        self.itl_s = list(itl_s)
        self.e2e_s = e2e_s


def test_loadgen_slo_spec_and_step_eval(tmp_path):
    """ISSUE satellite: ``--slo <spec.json>`` parsing (defaults, validation)
    and the per-step burn-rate scoring the recovery report prints."""
    lg = _loadgen_module()
    spec_path = tmp_path / "slo.json"
    spec_path.write_text(json.dumps({"metric": "ttft", "target_s": 0.05,
                                     "target_ratio": 0.9}))
    spec = lg._load_slo_spec(str(spec_path))
    assert spec == {"metric": "ttft", "target_s": 0.05, "target_ratio": 0.9,
                    "burn_threshold": 2.0}  # defaults fill the rest
    for bad in ({"metric": "latency"}, {"target_ratio": 1.5},
                {"target_s": -1.0}):
        spec_path.write_text(json.dumps(bad))
        with pytest.raises(ValueError):
            lg._load_slo_spec(str(spec_path))

    # ttft scores COMPLETED observations: 3 of 4 over target, the failed
    # request contributes nothing; burn = 0.75 / (1 - 0.9)
    step = lg._slo_step_eval([_R(ttft_s=0.01), _R(ttft_s=0.2),
                              _R(ttft_s=0.3), _R(ttft_s=0.4), _R(ok=False)],
                             spec)
    assert (step["bad"], step["total"]) == (3, 4)
    assert step["burn_rate"] == pytest.approx(7.5)
    assert step["breached"] is True

    # goodput scores EVERY request against the step deadline
    g = lg._slo_step_eval([_R(e2e_s=0.5), _R(e2e_s=3.0), _R(ok=False)],
                          {"metric": "goodput", "target_s": 1.0,
                           "target_ratio": 0.5, "burn_threshold": 2.0},
                          deadline_s=2.0)
    assert (g["bad"], g["total"]) == (2, 3)
    assert g["breached"] is False  # burn 4/3 < 2

    # itl flattens the per-request inter-token gap lists
    i = lg._slo_step_eval([_R(itl_s=[0.01, 0.2]), _R(itl_s=[0.02])],
                          {"metric": "itl", "target_s": 0.1,
                           "target_ratio": 0.9, "burn_threshold": 2.0})
    assert (i["bad"], i["total"]) == (1, 3)


def test_report_overload_slo_burn_column_and_first_breach(tmp_path, capsys):
    """ISSUE satellite: an --slo ramp doc renders a per-step burn column
    (breached steps flagged), the spec line, and the first-breach verdict —
    riding the existing knee detection unchanged."""
    from deepspeed_tpu.env_report import overload_report
    doc = _overload_doc([9.8, 9.5, 6.0, 4.0])
    for i, (step, burn) in enumerate(zip(doc["steps"],
                                         [0.5, 1.0, 4.0, 9.0])):
        step["slo"] = {"metric": "ttft", "bad": i, "total": 8,
                       "bad_fraction": burn / 10.0, "burn_rate": burn,
                       "breached": burn >= 2.0}
    doc["slo_spec"] = {"metric": "ttft", "target_s": 0.05,
                       "target_ratio": 0.9, "burn_threshold": 2.0}
    doc["slo_first_breach_step"] = 2
    path = tmp_path / "ramp.json"
    path.write_text(json.dumps(doc))
    assert overload_report(str(path)) == 0
    out = capsys.readouterr().out
    assert "burn" in out
    assert "4.00!" in out          # breached step carries the flag
    assert "0.50 " in out          # healthy step: burn, no flag
    assert "first breach at step 2" in out and "1.5x offered" in out
    assert "<- knee" in out        # knee detection unchanged alongside SLO


def test_loadgen_overload_ramp_end_to_end(make_engine, llama_setup):
    """bin/dstpu_loadgen --overload against a live server: capacity phase,
    two ramp steps, JSON artifact, and dstpu_report rendering it."""
    import tempfile
    cfg, _, _ = llama_setup
    engine = make_engine()
    sched = ServingScheduler(engine, ServingConfig())
    srv = ServingServer(sched).start()
    try:
        with tempfile.TemporaryDirectory() as td:
            out_json = os.path.join(td, "ramp.json")
            proc = subprocess.run(
                [sys.executable, os.path.join(REPO, "bin", "dstpu_loadgen"),
                 "--url", srv.url, "--requests", "6", "--concurrency", "2",
                 "--prompt-len", "8", "--max-new-tokens", "3",
                 "--vocab-size", str(cfg.vocab_size), "--deadline-s", "30",
                 "--overload", "--overload-steps", "0.5,2",
                 "--interactive-frac", "0.5", "--seed", "7",
                 "--json", out_json],
                capture_output=True, text=True, timeout=560)
            assert proc.returncode == 0, proc.stdout + proc.stderr
            assert "overload ramp" in proc.stdout
            with open(out_json) as f:
                doc = json.load(f)
            assert doc["capacity_req_s"] > 0
            assert [s["offered_x"] for s in doc["steps"]] == [0.5, 2.0]
            for step in doc["steps"]:
                assert step["on_deadline"] > 0
                assert step["ttft"]["interactive"]["n"] + \
                    step["ttft"]["batch"]["n"] > 0

            report = subprocess.run(
                [sys.executable, os.path.join(REPO, "bin", "dstpu_report"),
                 "--overload", out_json],
                capture_output=True, text=True, timeout=60)
            assert report.returncode == 0, report.stdout + report.stderr
            assert "overload ramp" in report.stdout
    finally:
        srv.stop(drain=False)
