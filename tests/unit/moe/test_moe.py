"""MoE tests (reference: tests/unit/moe/test_moe.py)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deepspeed_tpu.moe.layer import MoE, expert_param_specs
from deepspeed_tpu.moe.sharded_moe import _capacity, top1gating, top2gating
from deepspeed_tpu.utils import groups


def test_capacity_math():
    assert _capacity(16, 4, 1.0, 1) == 4
    assert _capacity(16, 4, 1.5, 1) == 6
    assert _capacity(4, 8, 1.0, 4) == 4  # min_capacity floor


def test_top1gating_basic():
    rng = jax.random.PRNGKey(0)
    logits = jax.random.normal(rng, (32, 4))
    l_aux, combine, dispatch, counts = top1gating(logits, capacity_factor=2.0, min_capacity=1, rng=rng)
    S, E, C = combine.shape
    assert (S, E) == (32, 4)
    assert float(l_aux) > 0
    # each token goes to at most one (expert, slot)
    assert np.all(np.asarray(dispatch.sum(axis=(1, 2))) <= 1.0 + 1e-6)
    # combine weights are the softmax gate probs of kept tokens
    kept = np.asarray(dispatch.sum(axis=(1, 2))) > 0
    probs = np.asarray(jax.nn.softmax(logits, axis=1).max(axis=1))
    got = np.asarray(combine.sum(axis=(1, 2)))
    np.testing.assert_allclose(got[kept], probs[kept], rtol=1e-5)


def test_top1gating_capacity_respected():
    rng = jax.random.PRNGKey(1)
    # all tokens prefer expert 0
    logits = jnp.stack([jnp.full((64, ), 5.0), jnp.zeros((64, )), jnp.zeros((64, )), jnp.zeros((64, ))], axis=1)
    _, _, dispatch, _ = top1gating(logits, capacity_factor=1.0, min_capacity=1, rng=rng)
    C = dispatch.shape[2]
    per_slot = np.asarray(dispatch[:, 0, :].sum(axis=0))
    assert np.all(per_slot <= 1.0 + 1e-6)  # one token per slot
    assert float(dispatch[:, 0].sum()) <= C + 1e-6  # at most capacity kept


def test_top1gating_no_drop():
    rng = jax.random.PRNGKey(2)
    logits = jnp.stack([jnp.full((16, ), 5.0)] + [jnp.zeros((16, ))] * 3, axis=1)
    _, _, dispatch, _ = top1gating(logits, capacity_factor=0.1, min_capacity=1, rng=rng, drop_tokens=False)
    # every token kept when drop_tokens=False
    np.testing.assert_allclose(np.asarray(dispatch.sum(axis=(1, 2))), 1.0)


def test_top2gating_normalized():
    rng = jax.random.PRNGKey(3)
    logits = jax.random.normal(rng, (32, 8))
    l_aux, combine, dispatch, counts = top2gating(logits, capacity_factor=4.0, min_capacity=1)
    tot = np.asarray(combine.sum(axis=(1, 2)))
    kept_both = np.asarray(dispatch.sum(axis=(1, 2))) == 2
    # where both experts kept, weights normalize to 1
    np.testing.assert_allclose(tot[kept_both], 1.0, rtol=1e-5)


def test_moe_module_forward_and_grad():
    groups.initialize_mesh(force=True)
    layer = MoE(hidden_size=16, num_experts=4, ffn_hidden_size=32, k=1, capacity_factor=2.0)
    x = jax.random.normal(jax.random.PRNGKey(0), (4, 8, 16))
    params = layer.init({"params": jax.random.PRNGKey(1), "gating": jax.random.PRNGKey(2)}, x)["params"]
    out, l_aux, counts = layer.apply({"params": params}, x, rngs={"gating": jax.random.PRNGKey(3)})
    assert out.shape == x.shape
    assert np.isfinite(float(l_aux))

    def loss(p):
        o, la, _ = layer.apply({"params": p}, x, rngs={"gating": jax.random.PRNGKey(3)})
        return jnp.mean(o**2) + 0.01 * la

    g = jax.grad(loss)(params)
    gnorm = sum(float(jnp.sum(jnp.abs(l))) for l in jax.tree.leaves(g))
    assert gnorm > 0  # gradients flow through dispatch/combine AND the gate


def test_moe_expert_parallel_sharding():
    """Expert banks sharded over the expert axis; forward runs under jit on the mesh."""
    groups.initialize_mesh(expert_parallel_size=4, force=True)
    mesh = groups.get_mesh()
    layer = MoE(hidden_size=16, num_experts=4, ffn_hidden_size=32, k=1, capacity_factor=2.0)
    x = jax.random.normal(jax.random.PRNGKey(0), (8, 4, 16))
    params = layer.init({"params": jax.random.PRNGKey(1), "gating": jax.random.PRNGKey(2)}, x)["params"]
    specs = expert_param_specs(params)
    from jax.sharding import NamedSharding
    shardings = jax.tree.map(lambda s: NamedSharding(mesh, s), specs)
    params_sharded = jax.device_put(params, shardings)
    wi = params_sharded["ExpertFFN_0"]["wi"]
    assert not wi.sharding.is_fully_replicated

    @jax.jit
    def f(p, x):
        o, la, _ = layer.apply({"params": p}, x, rngs={"gating": jax.random.PRNGKey(3)})
        return o, la

    out, l_aux = f(params_sharded, x)
    ref_out, ref_aux = f(params, x)  # replicated run
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref_out), rtol=2e-5, atol=2e-6)


def test_pr_moe_residual():
    groups.initialize_mesh(force=True)
    layer = MoE(hidden_size=8, num_experts=2, ffn_hidden_size=16, use_residual=True)
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 4, 8))
    params = layer.init({"params": jax.random.PRNGKey(1), "gating": jax.random.PRNGKey(2)}, x)["params"]
    out, _, _ = layer.apply({"params": params}, x, rngs={"gating": jax.random.PRNGKey(3)})
    assert out.shape == x.shape


def test_split_params_into_moe_groups():
    """Reference moe/utils.py:65 analog: expert membership is structural (the
    spec carries the expert axis); the splitter partitions a mixtral tree
    into dense + moe groups with structures preserved."""
    import jax
    from deepspeed_tpu.moe import (is_moe_param_spec,
                                   split_params_into_different_moe_groups_for_optimizer)
    from deepspeed_tpu.models.mixtral import MixtralConfig, init_params, mixtral_param_specs

    cfg = MixtralConfig.tiny()
    _, params = init_params(cfg)
    specs = mixtral_param_specs(params)

    groups_out = split_params_into_different_moe_groups_for_optimizer(
        {"params": params, "lr": 1e-4, "name": "all"}, specs)
    assert len(groups_out) == 2
    dense, moe = groups_out
    assert moe["moe"] is True and not dense.get("moe")
    assert dense["lr"] == moe["lr"] == 1e-4

    def count(tree):
        return sum(1 for l in jax.tree.leaves(tree) if l is not None)

    n_dense, n_moe, n_all = count(dense["params"]), count(moe["params"]), \
        len(jax.tree.leaves(params))
    assert n_moe > 0, "mixtral must have expert-axis params"
    assert n_dense + n_moe == n_all  # a partition, not a copy or a drop
    # the classification matches the spec tree leaf-for-leaf
    flat_specs = jax.tree.leaves(specs)
    assert sum(1 for s in flat_specs if is_moe_param_spec(s)) == n_moe
    # missing specs refuse loudly
    import pytest as _pytest
    with _pytest.raises(ValueError, match="param_specs"):
        split_params_into_different_moe_groups_for_optimizer({"params": params})
